"""Device ops: HLC lane packing and the batched lattice-join merge.

Everything in this package operates on the columnar HLC representation
(SURVEY.md §7 "core representation"): an HLC is carried as

- ``lt``   int64  — ``(millis << 16) | counter``, the reference's own
  logicalTime packing (hlc.dart:16); millis < 2^47 keeps it positive.
- ``node`` int32  — ordinal of the node id in a per-store
  :class:`~crdt_tpu.ops.packing.NodeTable`, order-preserving so that
  lexicographic ``(lt, node)`` compare == ``Hlc.compareTo``
  (hlc.dart:158-161).

int64 lanes require jax x64 mode; it is enabled here, before any
tracing happens.
"""

import jax

# int64 logicalTime lanes need x64 mode. This is a process-global JAX
# setting; crdt_tpu documents it (README "Embedding") and fails loudly
# rather than silently computing wrong clocks if the host app pinned
# x64 off.
jax.config.update("jax_enable_x64", True)
if not jax.config.jax_enable_x64:  # pragma: no cover
    raise ImportError(
        "crdt_tpu requires jax x64 mode for int64 HLC lanes, but "
        "jax_enable_x64 could not be enabled in this process.")

from .packing import NodeTable, pack_logical_time, unpack_logical_time
from .merge import (Store, Changeset, MergeResult, merge_step,
                    empty_store, grow_store, max_logical_time,
                    delta_mask)
from .dense import (DenseStore, DenseChangeset, FaninResult,
                    empty_dense_store, fanin_step, fanin_stream,
                    dense_delta_mask, dense_range_delta_mask,
                    dense_max_logical_time, store_to_changeset)
from .digest import (DigestTree, DEFAULT_LEAF_WIDTH, digest_tree_device,
                     build_digest_tree, walk_divergent_leaves,
                     coalesce_leaf_ranges)
from .pallas_merge import (SplitStore, SplitChangeset, PallasFaninResult,
                           pallas_fanin_batch, pallas_fanin_step,
                           pallas_fanin_stream, split_store,
                           split_changeset, join_store, tile_changeset,
                           model_fanin_split, pad_split_rows,
                           split_to_wide, TILE)

__all__ = [
    "NodeTable", "pack_logical_time", "unpack_logical_time",
    "Store", "Changeset", "MergeResult", "merge_step", "empty_store",
    "grow_store", "max_logical_time", "delta_mask",
    "DenseStore", "DenseChangeset", "FaninResult", "empty_dense_store",
    "fanin_step", "fanin_stream", "dense_delta_mask",
    "dense_range_delta_mask", "dense_max_logical_time",
    "store_to_changeset",
    "DigestTree", "DEFAULT_LEAF_WIDTH", "digest_tree_device",
    "build_digest_tree", "walk_divergent_leaves",
    "coalesce_leaf_ranges",
    "SplitStore", "SplitChangeset", "PallasFaninResult",
    "pallas_fanin_batch", "pallas_fanin_step", "pallas_fanin_stream",
    "split_store", "split_changeset", "join_store", "tile_changeset",
    "model_fanin_split", "pad_split_rows", "split_to_wide", "TILE",
]

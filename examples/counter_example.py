"""PN-counter metrics workload: per-replica in-flight request gauges.

Three web replicas each track their own "requests in flight" gauge —
increment on arrival, decrement on completion — and gossip typed
packed deltas so every replica can report the cluster-wide total.

The load-bearing pattern is ONE WRITER PER SLOT: the pncounter join
is a per-half max (each replica's lane converges to the largest
pos/neg counts ever shipped for that slot), not a sum, so two
replicas incrementing the SAME slot would collapse to the max instead
of adding. Giving each replica its own slot and summing across slots
at read time is the dense-store form of the classic per-actor-entry
PN-counter. The full contract is in docs/TYPES.md.
"""

from crdt_tpu.models.dense_crdt import DenseCrdt

N_REPLICAS = 3
GAUGE_SLOTS = list(range(N_REPLICAS))   # slot i: replica i's gauge


def make_replica(i: int) -> DenseCrdt:
    c = DenseCrdt(f"web-{i}", n_slots=8)
    # Replica-local configuration: every replica types the same slots
    # the same way BEFORE syncing them (docs/TYPES.md, rollout rules).
    c.set_semantics(GAUGE_SLOTS, "pncounter")
    return c


def exchange(a: DenseCrdt, b: DenseCrdt) -> None:
    """One bidirectional typed sync round over the packed wire form.

    `sem_mode="include"` is what a negotiated `semantics` hello
    session ships; both ends here are typed, so nothing is withheld.
    """
    pa, ids_a = a.pack_since(None, sem_mode="include")
    pb, ids_b = b.pack_since(None, sem_mode="include")
    b.merge_packed(pa, ids_a)
    a.merge_packed(pb, ids_b)


def main() -> None:
    replicas = [make_replica(i) for i in range(N_REPLICAS)]

    # Each replica records only its own traffic (one writer per slot):
    # (requests started, requests finished).
    traffic = [(40, 37), (25, 25), (60, 52)]
    for i, (started, finished) in enumerate(traffic):
        replicas[i].counter_add(i, started)
        replicas[i].counter_add(i, -finished)
        print(f"web-{i}: started={started} finished={finished} "
              f"local gauge={replicas[i].counter_value(i)}")

    # Gossip around the ring until everyone has seen everything.
    exchange(replicas[0], replicas[1])
    exchange(replicas[1], replicas[2])
    exchange(replicas[0], replicas[1])

    expected = sum(s - f for s, f in traffic)
    for r in replicas:
        total = sum(r.counter_value(s) for s in GAUGE_SLOTS)
        print(f"{r.node_id}: cluster in-flight = {total}")
        assert total == expected, (r.node_id, total, expected)

    # Redelivery is free: the join is idempotent, so a duplicated
    # gossip round cannot double-count.
    exchange(replicas[0], replicas[2])
    assert sum(replicas[0].counter_value(s)
               for s in GAUGE_SLOTS) == expected
    print(f"converged at {expected} in-flight across the cluster")


if __name__ == "__main__":
    main()

"""Cross-backend network soak (VERDICT r3 item 8): every backend
family in one randomized gossip mesh, with a real TCP boundary in the
loop, driven to global convergence.

Extends the in-process merge soaks (tests/test_properties.py,
benchmarks/validate_on_chip.py) to the transport layer: replicas
gossip through `SyncServer`/`sync_over_tcp` frames — nothing but wire
JSON crosses — interleaved with random local writes, deletes, clears,
and direct record-map merges. At the end, one full all-pairs round
settles the mesh and every replica must hold the same records with
byte-identical wire exports (same insertion history ⇒ same bytes is
NOT required across replicas; record equality is the contract, and
export equality is checked key-sorted)."""

import json
import random

import pytest

from conformance import FakeClock
from crdt_tpu import (DenseCrdt, KeyedDenseCrdt, MapCrdt, SqliteCrdt,
                      SyncServer, TpuMapCrdt, sync_over_tcp)

KEYS = [f"k{i}" for i in range(40)]


def _mk_replicas(clk):
    return [
        MapCrdt("oracle", wall_clock=clk),
        TpuMapCrdt("tpu", wall_clock=clk),
        SqliteCrdt("lite", wall_clock=clk, check_same_thread=False),
        KeyedDenseCrdt(DenseCrdt("dense", 64, wall_clock=clk)),
    ]


def _sorted_state(crdt):
    # key-sorted wire view: replicas with different insertion
    # histories legitimately order keys differently
    return dict(sorted(json.loads(crdt.to_json()).items()))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_four_backend_tcp_gossip_converges(seed):
    rng = random.Random(seed)
    clk = FakeClock(step=3)
    replicas = _mk_replicas(clk)
    servers = [SyncServer(c) for c in replicas]
    for s in servers:
        s.start()
    marks = {}
    try:
        for step in range(120):
            r = rng.randrange(len(replicas))
            c = replicas[r]
            op = rng.random()
            with servers[r].lock:
                if op < 0.40:
                    c.put(rng.choice(KEYS), rng.randrange(1000))
                elif op < 0.55:
                    c.delete(rng.choice(KEYS))
                elif op < 0.62:
                    c.put_all({rng.choice(KEYS): rng.randrange(1000)
                               for _ in range(rng.randrange(1, 6))})
                elif op < 0.66:
                    c.clear()
            if op >= 0.66 or step % 7 == 0:
                # gossip: one anti-entropy round against a random peer
                # over real TCP, with the self-served replica's lock
                o = rng.randrange(len(replicas))
                if o != r:
                    marks[(r, o)] = sync_over_tcp(
                        c, servers[o].host, servers[o].port,
                        since=marks.get((r, o)), lock=servers[r].lock)
        # settle: two deterministic all-pairs rounds (full pulls)
        for _ in range(2):
            for i, c in enumerate(replicas):
                for j, s in enumerate(servers):
                    if i != j:
                        sync_over_tcp(c, s.host, s.port,
                                      lock=servers[i].lock)
    finally:
        for s in servers:
            s.stop()

    states = [_sorted_state(c) for c in replicas]
    for i, st in enumerate(states[1:], 1):
        assert st == states[0], (
            f"replica {i} diverged at seed {seed}: "
            f"{set(st) ^ set(states[0])}")
    # live views agree too (tombstones hidden consistently)
    maps = [c.map for c in replicas]
    assert all(m == maps[0] for m in maps[1:])
    replicas[2].close()

"""Rendering: registry snapshots → Prometheus text / human summary,
trace JSONL → per-phase latency table.

Everything here is pure (dicts in, strings out) so the CLI, tests and
any embedding service render identically. The snapshot shape is the
one :meth:`crdt_tpu.obs.registry.MetricsRegistry.snapshot` produces,
optionally extended by the `SyncServer` ``metrics`` op with ``node``
(identity) and ``lag`` (per-peer staleness) sections.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List

from .sketch import sketch_from_sample

# Sketch series render as Prometheus summary quantiles at these
# points — the tails SLO gates read, plus the median.
_SKETCH_QUANTILES = (0.5, 0.9, 0.99)

# stats-collector kinds → Prometheus metric family prefixes. The
# legacy dataclasses expose as_dict() fields; each numeric field
# becomes one family: e.g. MergeStats.merges (kind "merge") renders as
# crdt_tpu_merge_merges_total{backend=...,node=...}.
_STATS_PREFIX = {
    "merge": ("crdt_tpu_merge_", "_total"),
    "peer_sync": ("crdt_tpu_peer_", "_total"),
    "wire": ("crdt_tpu_wire_", "_bytes_total"),
}


def _esc(value: Any) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt(value: float) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int) or (isinstance(value, float)
                                  and value.is_integer()):
        return str(int(value))
    return repr(float(value))


def render_prometheus(snapshot: Dict[str, Any]) -> str:
    """Prometheus text exposition (v0.0.4) of a metrics snapshot."""
    lines: List[str] = []

    for name, samples in sorted(snapshot.get("counters", {}).items()):
        lines.append(f"# TYPE {name} counter")
        for s in samples:
            lines.append(f"{name}{_labels(s['labels'])} "
                         f"{_fmt(s['value'])}")
    for name, samples in sorted(snapshot.get("gauges", {}).items()):
        lines.append(f"# TYPE {name} gauge")
        for s in samples:
            lines.append(f"{name}{_labels(s['labels'])} "
                         f"{_fmt(s['value'])}")
    for name, samples in sorted(
            snapshot.get("histograms", {}).items()):
        lines.append(f"# TYPE {name} histogram")
        for s in samples:
            cum = 0
            for bound, count in s["buckets"]:
                cum += count
                labels = dict(s["labels"], le=f"{bound:.9g}")
                lines.append(f"{name}_bucket{_labels(labels)} {cum}")
            cum += s.get("overflow", 0)
            labels = dict(s["labels"], le="+Inf")
            lines.append(f"{name}_bucket{_labels(labels)} {cum}")
            lines.append(f"{name}_count{_labels(s['labels'])} "
                         f"{s['count']}")
            lines.append(f"{name}_sum{_labels(s['labels'])} "
                         f"{_fmt(s['sum'])}")
    # Quantile sketches expose as summaries: unlike the log2
    # histogram families above (whose quantiles are bucket ceilings),
    # these carry the sketch's relative-error bound — the series a
    # dashboard should alert on (docs/OBSERVABILITY.md).
    for name, samples in sorted(snapshot.get("sketches", {}).items()):
        lines.append(f"# TYPE {name} summary")
        for s in samples:
            sk = sketch_from_sample(s)
            if sk is not None and sk.count > 0:
                for q in _SKETCH_QUANTILES:
                    labels = dict(s["labels"], quantile=f"{q:g}")
                    lines.append(f"{name}{_labels(labels)} "
                                 f"{_fmt(sk.quantile(q))}")
            lines.append(f"{name}_count{_labels(s['labels'])} "
                         f"{s['count']}")
            lines.append(f"{name}_sum{_labels(s['labels'])} "
                         f"{_fmt(s['sum'])}")

    for kind, entries in sorted(snapshot.get("stats", {}).items()):
        prefix, suffix = _STATS_PREFIX.get(
            kind, (f"crdt_tpu_{kind}_", ""))
        for entry in entries:
            for field, value in entry["values"].items():
                if not isinstance(value, (int, float)) \
                        or isinstance(value, bool):
                    continue
                lines.append(f"{prefix}{field}{suffix}"
                             f"{_labels(entry['labels'])} "
                             f"{_fmt(value)}")

    node = snapshot.get("node")
    lag = snapshot.get("lag")
    node_label = ({} if not isinstance(node, dict)
                  else {"node": node.get("node_id", "")})
    if isinstance(lag, dict):
        for peer, entry in sorted(lag.items()):
            labels = dict(node_label, peer=peer)
            lines.append(f"crdt_tpu_peer_synced{_labels(labels)} "
                         f"{1 if entry.get('synced') else 0}")
            if entry.get("lag_ms") is not None:
                lines.append(
                    f"crdt_tpu_peer_lag_millis{_labels(labels)} "
                    f"{_fmt(entry['lag_ms'])}")
            if entry.get("seconds_behind") is not None:
                lines.append(
                    f"crdt_tpu_peer_seconds_behind"
                    f"{_labels(labels)} "
                    f"{_fmt(entry['seconds_behind'])}")
            if entry.get("pending_records") is not None:
                lines.append(
                    f"crdt_tpu_peer_pending_records"
                    f"{_labels(labels)} "
                    f"{_fmt(entry['pending_records'])}")
    return "\n".join(lines) + ("\n" if lines else "")


def _table(headers: List[str], rows: List[List[str]]) -> List[str]:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows
              else len(h) for i, h in enumerate(headers)]
    out = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for row in rows:
        out.append("  ".join(c.ljust(w)
                             for c, w in zip(row, widths)))
    return out


def render_summary(snapshot: Dict[str, Any]) -> str:
    """Compact human-readable summary of a metrics snapshot — the
    default ``python -m crdt_tpu.obs`` output."""
    lines: List[str] = []
    node = snapshot.get("node")
    if isinstance(node, dict):
        lines.append(f"node {node.get('node_id')}  "
                     f"head={node.get('hlc_head')}")

    lag = snapshot.get("lag")
    if isinstance(lag, dict) and lag:
        rows = []
        for peer, e in sorted(lag.items()):
            rows.append([
                peer,
                "-" if e.get("lag_ms") is None else str(e["lag_ms"]),
                "-" if e.get("pending_records") is None
                else str(e["pending_records"]),
                str(e.get("breaker") or "-"),
                "dense" if e.get("dense") else "json",
                "yes" if e.get("synced") else "NEVER",
            ])
        lines.append("")
        lines.extend(_table(
            ["peer", "lag_ms", "pending", "breaker", "wire",
             "synced"], rows))

    stats = snapshot.get("stats", {})
    merge = stats.get("merge", [])
    if merge:
        rows = []
        for entry in merge:
            lbl = entry["labels"]
            v = entry["values"]
            rows.append([
                str(lbl.get("backend", "?")),
                str(lbl.get("node", "?")),
                str(v.get("merges", 0)),
                str(v.get("records_seen", 0)),
                str(v.get("records_adopted", 0)),
                str(v.get("puts", 0)),
                str(v.get("records_put", 0)),
            ])
        lines.append("")
        lines.extend(_table(
            ["backend", "node", "merges", "seen", "adopted", "puts",
             "recs_put"], rows))

    peers = stats.get("peer_sync", [])
    if peers:
        rows = []
        for entry in peers:
            lbl = entry["labels"]
            v = entry["values"]
            rows.append([
                str(lbl.get("peer", "?")),
                str(v.get("rounds_ok", 0)),
                str(v.get("rounds_failed", 0)),
                str(v.get("retries", 0)),
                str(v.get("bytes_sent", 0)),
                str(v.get("bytes_received", 0)),
            ])
        lines.append("")
        lines.extend(_table(
            ["peer", "ok", "failed", "retries", "tx_bytes",
             "rx_bytes"], rows))

    wire = stats.get("wire", [])
    if wire:
        lines.append("")
        for entry in wire:
            lbl = entry["labels"]
            v = entry["values"]
            lines.append(f"wire[{lbl.get('role', '?')}] "
                         f"sent={v.get('sent', 0)}B "
                         f"received={v.get('received', 0)}B")
    return "\n".join(lines) + ("\n" if lines else "")


def _percentile(sorted_values: List[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1,
              max(0, math.ceil(q * len(sorted_values)) - 1))
    return sorted_values[idx]


def summarize_trace(events: Iterable[Dict[str, Any]]
                    ) -> Dict[str, Dict[str, float]]:
    """Aggregate span-shaped trace events (those carrying ``dur_s``)
    into per-phase latency stats. The phase key is the event's
    ``span`` name when present, else its ``kind``."""
    groups: Dict[str, List[float]] = {}
    for event in events:
        dur = event.get("dur_s")
        if dur is None:
            continue
        phase = event.get("span") or event.get("kind", "?")
        groups.setdefault(str(phase), []).append(float(dur))
    out: Dict[str, Dict[str, float]] = {}
    for phase, durs in groups.items():
        durs.sort()
        out[phase] = {
            "count": len(durs),
            "total_s": sum(durs),
            "mean_s": sum(durs) / len(durs),
            "p50_s": _percentile(durs, 0.50),
            "p95_s": _percentile(durs, 0.95),
            "max_s": durs[-1],
        }
    return out


def format_phase_table(summary: Dict[str, Dict[str, float]]) -> str:
    """Fixed-width per-phase latency table from `summarize_trace`."""
    if not summary:
        return "no span events\n"
    rows = []
    for phase in sorted(summary,
                        key=lambda p: -summary[p]["total_s"]):
        s = summary[phase]
        rows.append([phase, str(int(s["count"])),
                     f"{s['total_s']:.6f}", f"{s['mean_s']:.6f}",
                     f"{s['p50_s']:.6f}", f"{s['p95_s']:.6f}",
                     f"{s['max_s']:.6f}"])
    return "\n".join(_table(
        ["phase", "count", "total_s", "mean_s", "p50_s", "p95_s",
         "max_s"], rows)) + "\n"

"""HLC-stamped structured trace events + profiler-annotated spans.

A :class:`TraceRing` is a bounded in-memory event ring (newest N
events) with an optional JSONL sink. Events are plain dicts:

    {"seq": 17, "kind": "gossip_round", "mono_s": 123.456,
     "hlc": "2026-08-05T..+0000-0000-n0", "peer": "b",
     "outcome": "ok", "dur_s": 0.0123}

- ``kind`` names the event class: ``merge`` (a merge dispatch span),
  ``gossip_round``, ``wire_frame``, ``checkpoint``, ``breaker``,
  ``bench_phase``, ``ingest`` (a write-combiner flush span,
  models/ingest.py — carries ``rows`` and ``trigger``).
- ``hlc`` is the emitting replica's canonical HLC at emission — the
  cluster-orderable stamp. ``mono_s`` (``time.monotonic()``) orders
  events within one process; wall-clock reads stay where they belong
  (``hlc.wall_clock_millis`` is the one sanctioned boundary).
- ``dur_s`` is present on span-shaped events.

**Cost model**: tracing is off by default and every emit site checks
``tracer().enabled`` (one attribute read) first. :func:`span` always
wraps its body in ``jax.profiler.TraceAnnotation`` — so TPU profiles
show named merge/pack/wire phases whether or not the ring is on — and
only times + emits when the ring is enabled. The stream-bench
per-phase row (bench.py) pins the enabled-overhead at ~0 on the hot
path.

HLC arguments may be zero-arg callables; they are invoked only when an
event is actually recorded, so disabled tracing never pays for a
``str(Hlc)``.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

import jax.profiler


class TraceRing:
    """Bounded in-memory trace event ring + optional JSONL sink."""

    # crdtlint lock-discipline contract: ring storage and sink are
    # touched only under self._lock. ``enabled`` is a bare bool read
    # on hot paths by design (stale reads only delay on/off by one
    # event).
    _CRDTLINT_GUARDED = {"_lock": ("_events", "_sink", "_seq")}

    def __init__(self, capacity: int = 4096):
        self.enabled = False
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._sink = None
        self._seq = 0

    @property
    def capacity(self) -> int:
        with self._lock:
            return self._events.maxlen

    def enable(self, capacity: Optional[int] = None,
               jsonl_path: Optional[str] = None) -> "TraceRing":
        """Turn event recording on; optionally resize the ring and/or
        append every event to a JSONL file."""
        with self._lock:
            if capacity is not None:
                self._events = deque(self._events, maxlen=capacity)
            if jsonl_path is not None:
                if self._sink is not None:
                    self._sink.close()
                self._sink = open(jsonl_path, "a", encoding="utf-8")
        self.enabled = True
        return self

    def disable(self) -> None:
        """Stop recording and close any JSONL sink."""
        self.enabled = False
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def emit(self, kind: str, hlc: Any = None, **fields: Any) -> None:
        """Record one event (no-op while disabled). ``hlc`` may be an
        `Hlc`, a string, or a zero-arg callable evaluated lazily."""
        if not self.enabled:
            return
        event: Dict[str, Any] = {"kind": kind,
                                 "mono_s": time.monotonic()}
        if hlc is not None:
            if callable(hlc):
                hlc = hlc()
            if hlc is not None:
                event["hlc"] = str(hlc)
        event.update(fields)
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
            if self._sink is not None:
                self._sink.write(json.dumps(event, default=str) + "\n")
                self._sink.flush()

    def events(self, kind: Optional[str] = None) -> List[dict]:
        """Snapshot the ring (oldest first), optionally one kind."""
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e.get("kind") == kind]
        return out


_DEFAULT = TraceRing()

# Span durations double into a fixed log2 histogram so the metrics op
# exposes per-phase latency distributions, not just the event tail the
# ring happens to hold. Created lazily to keep import order trivial.
_SPAN_HIST = None
_SPAN_HIST_LOCK = threading.Lock()


def tracer() -> TraceRing:
    """The process-wide trace ring every in-tree emit site uses."""
    return _DEFAULT


def _span_histogram():
    global _SPAN_HIST
    with _SPAN_HIST_LOCK:
        if _SPAN_HIST is None:
            from .registry import default_registry
            _SPAN_HIST = default_registry().histogram(
                "crdt_tpu_span_seconds",
                "traced span durations by span name (log2 buckets)",
                low_exp=-20, high_exp=5)
        return _SPAN_HIST


@contextmanager
def span(name: str, kind: str = "span", hlc: Any = None,
         **fields: Any):
    """Profiler-annotated span: the body always runs inside
    ``jax.profiler.TraceAnnotation(name)`` (named kernels in TPU
    profiles); when the process tracer is enabled the span is also
    timed, emitted as an HLC-stamped ring event, and observed into the
    ``crdt_tpu_span_seconds`` histogram."""
    ring = _DEFAULT
    if not ring.enabled:
        with jax.profiler.TraceAnnotation(name):
            yield
        return
    start = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        dur = time.perf_counter() - start
        ring.emit(kind, hlc=hlc, span=name, dur_s=dur, **fields)
        _span_histogram().observe(dur, span=name)

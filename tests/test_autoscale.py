"""Autoscaler suite: the pure decide() state machine (hysteresis,
cooldown, floor/ceiling, the all-cold merge rule, degraded freezes),
the _apply fences (one change in flight, epoch staleness), and a
closed-loop split-then-merge against a real federation.

The decide tests craft observation dicts by hand — the controller's
contract is that `decide` is pure given an observation plus its own
streak state, so every discipline is testable without a socket.
"""

import time

import pytest

from crdt_tpu import Autoscaler, FederatedClient, FederatedTier

pytestmark = pytest.mark.serve

N_SLOTS = 256


def _obs(rates, *, partitions=None, epoch=0, primaryless=(),
         ack_ok=True, t=0.0):
    n = len(rates) if rates is not None else (partitions or 0)
    return {
        "epoch": epoch,
        "partitions": partitions if partitions is not None else n,
        "rows": [0] * n,
        "rates": rates,
        "queue_depth": 0,
        "shed": 0,
        "primaryless": list(primaryless),
        "slo": {"checks": {"ack_p99_s": {"ok": ack_ok}}},
        "t": t,
    }


def _scaler(**kw):
    """A controller with no federation behind it — decide() never
    touches ``fed``."""
    kw.setdefault("hysteresis_ticks", 3)
    kw.setdefault("cooldown_s", 2.0)
    kw.setdefault("split_rows_per_s", 100.0)
    kw.setdefault("merge_rows_per_s", 10.0)
    kw.setdefault("max_partitions", 8)
    return Autoscaler(fed=None, **kw)


# --- decide(): hysteresis, thresholds, bounds ---

def test_split_requires_consecutive_hot_ticks():
    a = _scaler()
    hot = _obs([500.0, 1.0])
    assert a.decide(dict(hot))["reason"] == "hysteresis"
    assert a.decide(dict(hot))["reason"] == "hysteresis"
    dec = a.decide(dict(hot))
    assert dec["action"] == "split"
    assert dec["reason"] == "hot-rate"
    assert dec["src"] == 0                    # the hottest partition
    assert dec["epoch"] == 0                  # fenced to the evidence


def test_one_cool_tick_resets_the_split_streak():
    a = _scaler()
    hot, cool = _obs([500.0, 1.0]), _obs([50.0, 1.0])
    a.decide(dict(hot))
    a.decide(dict(hot))
    assert a.decide(dict(cool))["action"] == "hold"   # streak broken
    assert a.decide(dict(hot))["reason"] == "hysteresis"


def test_slo_breach_is_split_pressure_even_below_rate_threshold():
    a = _scaler(hysteresis_ticks=1)
    dec = a.decide(_obs([5.0, 1.0], ack_ok=False))
    assert dec["action"] == "split" and dec["reason"] == "slo-breach"


def test_merge_requires_every_partition_cold():
    a = _scaler(hysteresis_ticks=1)
    # One busy partition keeps the whole fleet's headroom.
    assert a.decide(_obs([1.0, 50.0]))["action"] == "hold"
    dec = a.decide(_obs([1.0, 4.0]))
    assert dec["action"] == "merge"
    assert dec["reason"] == "all-cold"
    assert dec["src"] == 0                    # the coldest partition


def test_floor_and_ceiling_hold():
    a = _scaler(hysteresis_ticks=1, min_partitions=2,
                max_partitions=2)
    assert a.decide(_obs([1.0, 2.0]))["reason"] == "floor"
    assert a.decide(_obs([500.0, 1.0]))["reason"] == "ceiling"


def test_cooldown_outranks_pressure():
    a = _scaler(hysteresis_ticks=1)
    a._last_change_t = 100.0
    dec = a.decide(_obs([500.0, 1.0], t=100.5))
    assert dec["action"] == "hold" and dec["reason"] == "cooldown"
    # ...and expires.
    dec = a.decide(_obs([500.0, 1.0], t=103.0))
    assert dec["action"] == "split"


def test_bad_bounds_rejected():
    with pytest.raises(ValueError):
        Autoscaler(fed=None, min_partitions=0)
    with pytest.raises(ValueError):
        Autoscaler(fed=None, min_partitions=4, max_partitions=2)


# --- degraded mode: unmeasured ≠ safe to shrink ---

def test_degraded_reasons_freeze_all_scaling():
    a = _scaler(hysteresis_ticks=1)
    cases = [
        (_obs([1.0, 1.0], epoch=None), "degraded:no-table"),
        (_obs([1.0, 1.0], primaryless=[1]),
         "degraded:primaryless-group"),
        (_obs(None, partitions=2), "degraded:unmeasured-rate"),
        (_obs([1.0, 1.0], ack_ok=None), "degraded:unmeasured-slo"),
    ]
    for obs, want in cases:
        dec = a.decide(obs)
        assert dec["action"] == "hold", want
        assert dec["reason"] == want


def test_degraded_tick_zeroes_streaks():
    a = _scaler()
    a.decide(_obs([1.0, 1.0]))
    a.decide(_obs([1.0, 1.0]))
    assert a._streak["merge"] == 2
    a.decide(_obs([1.0, 1.0], primaryless=[0]))
    assert a._streak["merge"] == 0 and a._streak["split"] == 0


# --- _apply fences ---

class _FakeTable:
    def __init__(self, epoch):
        self.epoch = epoch


class _FakeFed:
    def __init__(self, epoch=0):
        self.table = _FakeTable(epoch)
        self.calls = []

    def split_hot(self, src=None):
        self.calls.append(("split", src))
        self.table = _FakeTable(self.table.epoch + 1)
        return {}

    def merge_cold(self, src=None):
        self.calls.append(("merge", src))
        self.table = _FakeTable(self.table.epoch + 1)
        return {}


def _dec(action, epoch, src=0):
    return {"action": action, "reason": "test", "src": src,
            "epoch": epoch}


def test_apply_refuses_while_a_change_is_in_flight():
    fed = _FakeFed()
    a = Autoscaler(fed=fed)
    a._inflight = "split"
    assert a._apply(_dec("merge", 0)) is False
    assert fed.calls == []
    assert a.decisions[-1]["reason"] == "fence:inflight"


def test_apply_refuses_a_stale_epoch():
    fed = _FakeFed(epoch=5)
    a = Autoscaler(fed=fed)
    # Evidence read under epoch 4; topology moved since.
    assert a._apply(_dec("merge", 4)) is False
    assert fed.calls == []
    assert a.decisions[-1]["reason"] == "fence:stale-epoch"


def test_apply_executes_and_resets_controller_state():
    fed = _FakeFed(epoch=3)
    a = Autoscaler(fed=fed)
    a._streak["split"] = 5
    a._prev_rows = [1, 2]
    assert a._apply(_dec("split", 3, src=1)) is True
    assert fed.calls == [("split", 1)]
    assert a._streak["split"] == 0
    assert a._prev_rows is None               # rate baseline reset
    assert a._last_change_t is not None       # cooldown armed
    assert a.last_action["action"] == "split"
    assert a._inflight is None                # fence released


def test_apply_failure_is_noted_and_releases_the_fence():
    class _Boom(_FakeFed):
        def merge_cold(self, src=None):
            raise ValueError("no mergeable partition")

    a = Autoscaler(fed=_Boom())
    assert a._apply(_dec("merge", 0)) is False
    assert a.decisions[-1]["reason"] == "failed"
    assert a._inflight is None


# --- closed loop against a real federation ---

def _measured_slo():
    return {"checks": {"ack_p99_s": {"ok": True, "value": 0.001,
                                     "budget": 0.00425}}}


def test_closed_loop_split_then_merge():
    with FederatedTier(N_SLOTS, partitions=1,
                       flush_interval=0.002) as fed:
        a = Autoscaler(fed, min_partitions=1, max_partitions=2,
                       split_rows_per_s=5.0, merge_rows_per_s=1.0,
                       hysteresis_ticks=1, cooldown_s=0.0,
                       slo_probe=_measured_slo)
        # Tick 1: no rate baseline yet — degraded, never scales.
        dec = a.tick()
        assert dec["reason"] == "degraded:unmeasured-rate"

        cli = FederatedClient(fed.addrs())
        try:
            # Hot phase: a burst of committed rows between two ticks.
            for slot in range(0, N_SLOTS, 2):
                cli.put(slot, slot)
            time.sleep(0.05)
            dec = a.tick()
            assert dec["action"] == "split" and dec["applied"]
            assert len(fed.tiers) == 2
            e_split = fed.table.epoch

            # Cold phase: no writes. First post-change tick re-seeds
            # the baseline (degraded), the next one measures ~0 and
            # merges back down to the floor.
            dec = a.tick()
            assert dec["reason"] == "degraded:unmeasured-rate"
            time.sleep(0.05)
            dec = a.tick()
            assert dec["action"] == "merge" and dec["applied"]
            assert len(fed.tiers) == 1
            assert fed.table.epoch == e_split + 1

        finally:
            cli.close()

        # Every pre-scale write survives the round trip. The merge
        # may have retired the original seed tier, so read back
        # through the survivors.
        cli = FederatedClient(fed.addrs())
        try:
            for slot in range(0, N_SLOTS, 2):
                assert cli.get(slot) == slot
        finally:
            cli.close()


def test_daemon_never_merges_a_primaryless_fleet():
    """Kill the sole primary of a replicated partition and let the
    daemon tick over the wreckage: every decision during the outage
    must be a degraded hold — shrinking a fleet it cannot measure is
    the exact failure mode the freeze exists for."""
    from crdt_tpu.testing_faults import abrupt_kill

    with FederatedTier(N_SLOTS, partitions=2, replicas=2,
                       ack_replicas=1, flush_interval=0.002,
                       heartbeat_interval=0.02,
                       heartbeat_timeout=10.0,   # no auto-failover
                       lease_misses=400) as fed:
        a = Autoscaler(fed, min_partitions=1, max_partitions=2,
                       split_rows_per_s=1e9, merge_rows_per_s=1e9,
                       hysteresis_ticks=1, cooldown_s=0.0,
                       interval=0.01, slo_probe=_measured_slo)
        abrupt_kill(fed.groups[0].primary.tier)
        with a:
            time.sleep(0.2)
        assert len(fed.tiers) == 2            # nothing merged
        held = [d for d in a.decisions if d["action"] == "hold"]
        assert held, "daemon never ticked"
        assert any(d["reason"] == "degraded:primaryless-group"
                   for d in held)
        assert all(d["action"] == "hold" for d in a.decisions)

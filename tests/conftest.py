"""Test configuration: force an 8-virtual-device CPU JAX platform.

Tests must run without TPU hardware; multi-chip sharding is validated on
a virtual CPU mesh (the driver separately dry-runs the multichip path).
The env vars must be set before jax initializes its backends.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The environment may pin JAX_PLATFORMS to a hardware plugin before this
# file runs (site customization), so the env-var route is not enough —
# override the config directly, before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

"""crdt_tpu.semantics — the per-lane CRDT type zoo.

A first-class registry of lane semantics: every entry bundles a
donated/jit-cacheable merge kernel branch, a wire tag, a value codec
and a law spec, and registering it is what puts it under CI (the
analysis gate consumes :func:`law_targets` / :func:`audit_targets`
and fails on a spec missing either). Five semantics ship: ``lww``
(tag 0, the seed behavior), ``gcounter``, ``pncounter``, ``orset``
and ``mvreg`` — encodings and laws in `kernels` and `types`, usage in
docs/TYPES.md.

Models consume this through `DenseCrdt.set_semantics` (per-slot tag
column) plus the typed op helpers (``counter_add``, ``orset_add``,
``mvreg_put``, ...); the wire carries tags only to peers that
negotiated the ``semantics`` hello capability (docs/WIRE.md).
"""

from __future__ import annotations

from typing import List

from .types import (LWW, GCOUNTER, PNCOUNTER, ORSET, MVREG,
                    SemanticsSpec, all_semantics, by_tag,
                    get_semantics, names, register)
from .kernels import (MVREG_K, MVREG_MAX, ORSET_MAX_LEN,
                      ORSET_UNIVERSE, SEM_GCOUNTER, SEM_LWW,
                      SEM_MVREG, SEM_ORSET, SEM_PNCOUNTER,
                      typed_fanin_step, typed_join_lanes,
                      typed_sparse_join_step, typed_wire_join_step)

__all__ = [
    "SemanticsSpec", "register", "get_semantics", "by_tag",
    "all_semantics", "names",
    "LWW", "GCOUNTER", "PNCOUNTER", "ORSET", "MVREG",
    "SEM_LWW", "SEM_GCOUNTER", "SEM_PNCOUNTER", "SEM_ORSET",
    "SEM_MVREG", "ORSET_UNIVERSE", "ORSET_MAX_LEN", "MVREG_K",
    "MVREG_MAX",
    "typed_join_lanes", "typed_wire_join_step",
    "typed_sparse_join_step", "typed_fanin_step",
    "law_targets", "audit_targets",
]


def law_targets() -> List:
    """Seeded-law targets for every registered semantics that declares
    one — what `analysis.lattice_laws.builtin_targets` appends, so a
    new type gets law coverage by registering, with zero hand-listed
    targets."""
    return [spec.law_target() for spec in all_semantics()
            if spec.law_target is not None]


def audit_targets() -> List:
    """Jaxpr-audit targets for every registered semantics that
    declares one — appended by `analysis.jaxpr_audit.builtin_targets`
    beside the shared typed sparse/fanin kernel targets."""
    return [spec.audit_target() for spec in all_semantics()
            if spec.audit_target is not None]

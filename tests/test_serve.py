"""Serving-tier suite (docs/SERVING.md): session multiplexing onto
the combiner tick, admission watermark, cold-lane bounds, and wire
compatibility with every client generation — negotiated
`PeerConnection` sessions (packed + merkle) and pre-hello legacy
peers — in both directions."""

import socket
import time

import pytest

from crdt_tpu import (DenseCrdt, PeerConnection, ServeTier,
                      SyncTransportError, default_registry,
                      fetch_metrics, sync_merkle_over_conn,
                      sync_over_tcp, sync_packed_over_conn)
from crdt_tpu.net import recv_frame, send_frame

pytestmark = pytest.mark.serve


def _connect(tier):
    sock = socket.create_connection((tier.host, tier.port),
                                    timeout=10.0)
    sock.settimeout(10.0)
    return sock


def _req(sock, obj, codec=None):
    send_frame(sock, obj, None, codec)
    return recv_frame(sock, deadline=time.monotonic() + 10.0,
                      codec=codec)


# --- serve-only ops: put / get / delete over the framed wire ---

def test_put_get_delete_roundtrip():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        with _connect(tier) as sock:
            assert _req(sock, {"op": "put", "slot": 3,
                               "value": 42}) == {"ok": True}
            # read-your-writes: the ack resolved AFTER the commit, so
            # the overlay/store answers immediately.
            assert _req(sock, {"op": "get", "slot": 3}) \
                == {"ok": True, "value": 42}
            assert _req(sock, {"op": "delete", "slot": 3}) \
                == {"ok": True}
            assert _req(sock, {"op": "get", "slot": 3})["value"] is None
            send_frame(sock, {"op": "bye"})
    # tier stopped -> ingest window closed; direct reads are safe.
    assert crdt.get(3) is None


def test_malformed_write_rejected_session_survives():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with _connect(tier) as sock:
            for bad in ({"op": "put", "slot": 999, "value": 1},
                        {"op": "put", "slot": -1, "value": 1},
                        {"op": "put", "slot": 1, "value": "x"},
                        {"op": "get", "slot": "nope"}):
                reply = _req(sock, bad)
                assert reply["ok"] is False
                assert reply["code"] == "write_rejected"
            # ...and the session is still alive afterwards.
            assert _req(sock, {"op": "put", "slot": 5,
                               "value": 7}) == {"ok": True}
            send_frame(sock, {"op": "bye"})
    assert crdt.get(5) == 7


def test_out_of_range_and_bool_writes_rejected_flusher_survives():
    """An int outside int64 passes `isinstance(value, int)` but would
    blow up the flush tick's np.int64 conversion — it must be rejected
    per-write at the session, and the flusher must survive even if
    something slips through (a dead flusher hangs EVERY later ack).
    JSON true/false are ints to isinstance and must be rejected too."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with _connect(tier) as sock:
            for bad in ({"op": "put", "slot": 1, "value": 2 ** 63},
                        {"op": "put", "slot": 1, "value": -(2 ** 63) - 1},
                        {"op": "put", "slot": 1, "value": 2 ** 200},
                        {"op": "put", "slot": True, "value": 1},
                        {"op": "put", "slot": 1, "value": False},
                        {"op": "delete", "slot": False},
                        {"op": "get", "slot": True}):
                reply = _req(sock, bad)
                assert reply["ok"] is False
                assert reply["code"] == "write_rejected"
            # int64 boundaries themselves are legal...
            assert _req(sock, {"op": "put", "slot": 2,
                               "value": 2 ** 63 - 1}) == {"ok": True}
            # ...and the flusher is still ticking afterwards.
            assert _req(sock, {"op": "put", "slot": 5,
                               "value": 7}) == {"ok": True}
            assert _req(sock, {"op": "get", "slot": 5}) \
                == {"ok": True, "value": 7}
            send_frame(sock, {"op": "bye"})
    assert crdt.get(5) == 7


def test_malformed_digest_more_replies_merkle_rejected():
    """A 'more' entry that is not a [level, idx] pair must get the
    merkle_rejected reply (like SyncServer), not an unhandled
    TypeError that kills the session without a reply."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        for more in ([5], ["xy"], [[0]], [[0, [0], 9]]):
            with _connect(tier) as sock:
                reply = _req(sock, {"op": "digest", "level": 0,
                                    "idx": [0], "more": more})
                assert reply["code"] == "merkle_rejected"


def test_idle_timeout_is_clean_close_not_a_drop():
    """Routine idle expiry must not inflate dropped_sessions — the
    bench's zero-dropped acceptance criterion reads that counter."""
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt, idle_timeout=0.2) as tier:
        with _connect(tier) as sock:
            assert _req(sock, {"op": "put", "slot": 1,
                               "value": 1}) == {"ok": True}
            # park past idle_timeout: the server closes cleanly (EOF)
            assert recv_frame(sock,
                              deadline=time.monotonic() + 10.0) is None
        assert tier.idle_closed_sessions == 1
        assert tier.dropped_sessions == 0


def test_unknown_op_hangs_up():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with _connect(tier) as sock:
            reply = _req(sock, {"op": "frobnicate"})
            assert reply["code"] == "unknown_op"
            assert recv_frame(sock,
                              deadline=time.monotonic() + 10.0) is None


# --- the tentpole property: N writers, ONE combiner tick ---

def test_many_sessions_share_one_combiner_tick():
    crdt = DenseCrdt("a", n_slots=256)
    flushes = default_registry().counter(
        "crdt_tpu_ingest_flush_total",
        "write-combiner flushes by trigger")
    before = flushes.value(trigger="tick", node="a")
    with ServeTier(crdt, flush_interval=0.05) as tier:
        socks = [_connect(tier) for _ in range(8)]
        try:
            # All eight sessions write BEFORE any reads its ack: the
            # writes land in the same queue window and commit as one
            # put_batch + one combiner flush.
            for i, s in enumerate(socks):
                send_frame(s, {"op": "put", "slot": i, "value": i * 10})
            for s in socks:
                assert recv_frame(
                    s, deadline=time.monotonic() + 10.0) == {"ok": True}
            ticks = flushes.value(trigger="tick", node="a") - before
            # 8 writers, at most 2 ticks (2 only if a tick boundary
            # happened to split the sends) — never one flush per write.
            assert 1 <= ticks <= 2
        finally:
            for s in socks:
                s.close()
    for i in range(8):
        assert crdt.get(i) == i * 10
    assert tier.dropped_sessions == 0


# --- admission watermark ---

def test_admission_watermark_sheds_with_busy():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt, max_sessions=2) as tier:
        c1 = PeerConnection(tier.host, tier.port, timeout=5.0)
        c2 = PeerConnection(tier.host, tier.port, timeout=5.0)
        c3 = PeerConnection(tier.host, tier.port, timeout=5.0)
        try:
            c1.ensure()
            c2.ensure()
            with pytest.raises(SyncTransportError, match="busy"):
                c3.ensure()
            # Retryable refusal, NOT the legacy-downgrade signal.
            assert c3.legacy is False
            assert tier.shed_count >= 1
            shed = default_registry().counter(
                "crdt_tpu_serve_shed_total",
                "requests shed for backpressure (admission watermark "
                "or cold-join lane bound)")
            assert shed.value(lane="admission", node="a") >= 1
            # Freeing a slot readmits the shed client (bye is
            # processed asynchronously server-side, so poll).
            c1.close()
            for _ in range(500):
                try:
                    c3.ensure()
                    break
                except SyncTransportError:
                    time.sleep(0.01)
            else:
                raise AssertionError("slot never freed after close")
            assert "packed" in c3.caps
        finally:
            for c in (c1, c2, c3):
                c.close()


def test_hello_negotiates_full_caps():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with PeerConnection(tier.host, tier.port, timeout=5.0) as conn:
            conn.ensure()
            assert {"zlib", "packed", "semantics",
                    "merkle", "trace"} <= conn.caps
            assert conn.codec is not None


# --- cold-join slow lane ---

def test_cold_lane_bound_sheds_digest_with_busy():
    crdt = DenseCrdt("a", n_slots=64)
    crdt.put_batch([1], [1])
    joiner = DenseCrdt("b", n_slots=64)
    with ServeTier(crdt, cold_lane_depth=0) as tier:
        with PeerConnection(tier.host, tier.port, timeout=5.0) as conn:
            with pytest.raises(SyncTransportError, match="busy"):
                sync_merkle_over_conn(joiner, conn)
        assert tier.shed_count >= 1
        shed = default_registry().counter(
            "crdt_tpu_serve_shed_total",
            "requests shed for backpressure (admission watermark "
            "or cold-join lane bound)")
        assert shed.value(lane="cold", node="a") >= 1


def test_merkle_cold_join_through_tier():
    crdt = DenseCrdt("a", n_slots=64)
    slots = list(range(0, 64, 7))
    crdt.put_batch(slots, [s * 3 + 1 for s in slots])
    joiner = DenseCrdt("b", n_slots=64)
    with ServeTier(crdt) as tier:
        with PeerConnection(tier.host, tier.port, timeout=5.0) as conn:
            stats = {}
            sync_merkle_over_conn(joiner, conn, _stats=stats)
            assert stats["rounds"] >= 1
    for s in slots:
        assert joiner.get(s) == s * 3 + 1


# --- wire compat: negotiated packed sessions, both directions ---

def test_packed_round_through_tier_converges_both_ways():
    served = DenseCrdt("a", n_slots=64)
    client = DenseCrdt("b", n_slots=64)
    served.put_batch([1, 2], [10, 20])
    client.put_batch([5], [50])
    with ServeTier(served) as tier:
        with PeerConnection(tier.host, tier.port, timeout=5.0) as conn:
            mark = sync_packed_over_conn(client, conn, since=None)
            assert client.get(1) == 10 and client.get(2) == 20
            for _ in range(6):
                with tier.lock:
                    before = (str(served.canonical_time),
                              str(client.canonical_time))
                mark = sync_packed_over_conn(client, conn, since=mark)
                with tier.lock:
                    after = (str(served.canonical_time),
                             str(client.canonical_time))
                if after == before:
                    break
            else:
                raise AssertionError(
                    "clocks never settled through the tier")
    assert served.get(5) == 50
    assert client.get(5) == 50
    assert served.get(1) == 10 and served.get(2) == 20


def test_writes_landed_mid_session_reach_packed_pulls():
    served = DenseCrdt("a", n_slots=64)
    client = DenseCrdt("b", n_slots=64)
    with ServeTier(served) as tier:
        # A serve-session write...
        with _connect(tier) as wsock:
            assert _req(wsock, {"op": "put", "slot": 9,
                                "value": 99}) == {"ok": True}
            send_frame(wsock, {"op": "bye"})
        # ...is visible to a packed replication pull on the same tier
        # (the pack path drains the combiner as its barrier).
        with PeerConnection(tier.host, tier.port, timeout=5.0) as conn:
            sync_packed_over_conn(client, conn, since=None)
    assert client.get(9) == 99


# --- wire compat: pre-hello legacy JSON peers ---

def test_legacy_pre_hello_json_round():
    served = DenseCrdt("a", n_slots=64)
    legacy = DenseCrdt("b", n_slots=64)
    served.put_batch([2], [22])
    legacy.put_batch([4], [44])
    with ServeTier(served) as tier:
        # sync_over_tcp never sends hello: byte-identical legacy wire.
        sync_over_tcp(legacy, tier.host, tier.port)
        assert legacy.get(2) == 22
        with tier.lock:
            assert served.get(4) == 44
    assert served.get(4) == 44


# --- observability surface ---

def test_metrics_op_reports_serve_instruments():
    crdt = DenseCrdt("a", n_slots=64)
    with ServeTier(crdt) as tier:
        with _connect(tier) as sock:
            assert _req(sock, {"op": "put", "slot": 1,
                               "value": 2}) == {"ok": True}
            send_frame(sock, {"op": "bye"})
        snap = fetch_metrics(tier.host, tier.port)
    assert "crdt_tpu_serve_sessions" in snap["gauges"]
    assert "crdt_tpu_serve_ops_total" in snap["counters"]
    assert "crdt_tpu_serve_ack_seconds" in snap["histograms"]
    assert "crdt_tpu_serve_flush_seconds" in snap["histograms"]


# --- ack attribution (PR 11): queue_wait / stamp / scatter / ack_write ---

def test_ack_phase_attribution_sums_to_ack():
    """Every acked write decomposes into queue_wait + stamp + scatter
    + ack_write; the phase-histogram sums must reconstruct the ack
    histogram's sum (per-write observation, shared tick legs)."""
    crdt = DenseCrdt("phase-a", n_slots=64)
    node = str(crdt.node_id)
    reg = default_registry()
    ack = reg.histogram("crdt_tpu_serve_ack_seconds")
    phase = reg.histogram("crdt_tpu_serve_ack_phase_seconds")

    def _sum(h, **labels):
        return sum(s["sum"] for s in h.samples()
                   if all(s["labels"].get(k) == v
                          for k, v in labels.items()))

    ack0 = _sum(ack, node=node)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        with _connect(tier) as sock:
            for i in range(20):
                assert _req(sock, {"op": "put", "slot": i,
                                   "value": i})["ok"] is True
            send_frame(sock, {"op": "bye"})
    ack_sum = _sum(ack, node=node) - ack0
    phases = {p: _sum(phase, node=node, phase=p)
              for p in ("queue_wait", "stamp", "scatter", "ack_write")}
    counts = {p: sum(s["count"] for s in phase.samples()
                     if s["labels"] == {"node": node, "phase": p})
              for p in ("queue_wait", "stamp", "scatter", "ack_write")}
    # one observation per phase per acked write
    assert counts["queue_wait"] == 20
    assert counts == {p: 20 for p in counts}
    assert phases["stamp"] > 0 and phases["scatter"] > 0
    total = sum(phases.values())
    assert total == pytest.approx(ack_sum, rel=0.10), \
        (phases, ack_sum)


def test_rejected_tick_observes_ack_but_not_phases():
    """A failed tick still acks (with the rejection) but attributes
    nothing — phase sums must only ever cover committed writes."""
    crdt = DenseCrdt("phase-r", n_slots=64)
    node = str(crdt.node_id)
    reg = default_registry()
    phase = reg.histogram("crdt_tpu_serve_ack_phase_seconds")

    def _count(**labels):
        return sum(s["count"] for s in phase.samples()
                   if all(s["labels"].get(k) == v
                          for k, v in labels.items()))

    before = _count(node=node)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        # an in-range slot whose value overflows int64 passes the
        # session-side guard shape but np.fromiter(int64) rejects the
        # WHOLE tick
        import crdt_tpu.serve as serve_mod
        orig = serve_mod._value_ok
        serve_mod._value_ok = lambda v: True
        try:
            with _connect(tier) as sock:
                reply = _req(sock, {"op": "put", "slot": 1,
                                    "value": 1 << 80})
                assert reply["ok"] is False
                assert reply["code"] == "write_rejected"
                send_frame(sock, {"op": "bye"})
        finally:
            serve_mod._value_ok = orig
    assert _count(node=node) == before

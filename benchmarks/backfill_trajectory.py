"""One-shot backfill: fold the historical result files into the bench
trajectory (``benchmarks/history/trajectory.jsonl``).

The repo's first eleven PRs left results in four incompatible shapes:

- ``BENCH_r01..r06.json`` — driver wrapper dicts
  ``{n, cmd, rc, tail, parsed:{metric, value, ...}}``;
- ``BENCH_r07/r08.json`` — LISTS of those wrappers (multi-mode runs);
- ``BENCH_r09.json``, ``SERVE_r01.json``, ``MULTICHIP_SCALE_*.json``
  — raw result dicts straight off the bench's JSON line;
- ``MULTICHIP_r0*.json`` — validate-on-chip wrappers whose payload
  (when the run survived) is JSON lines inside ``tail``;
- ``SUITE_r0*.json`` — multi-line JSONL, one metric dict per line.

Each becomes one normalized trajectory record
(:func:`crdt_tpu.obs.trajectory.normalize_record`): ``run_id`` from
the source filename (stable and idempotent — re-running skips ids
already in the output), ``git_sha`` "unknown" (the files predate the
schema and carry no sha), ``host_class`` from the recorded platform
(coarse historical classes like ``tpu`` / ``cpu`` / ``multichip8`` —
deliberately never equal to a live `host_class()` string, so history
informs trends but can never serve as a floor for a fresh run on
different hardware).

Usage::

    python benchmarks/backfill_trajectory.py            # repo root
    python benchmarks/backfill_trajectory.py --out PATH --src DIR
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from crdt_tpu.obs.trajectory import (TRAJECTORY_PATH, append_record,
                                     load_trajectory, normalize_record)

#: metric-name → bench.py mode. Metrics with no entry keep their own
#: name as the mode — still one comparable group per metric family.
_MODE_BY_METRIC = {
    "e2e_sync": "sync",
    "ingest_fast_lane": "ingest",
    "typed_merges_per_sec_1024_slots": "types",
    "merkle_antientropy_soak": "antientropy",
    "serve_open_loop": "serve",
}


def _mode_for(metric: str) -> str:
    if metric in _MODE_BY_METRIC:
        return _MODE_BY_METRIC[metric]
    if metric.startswith("record_merges_per_sec"):
        return "stream"
    if metric.startswith("oracle_"):
        return "oracle"
    if metric.startswith("tpu_backend_"):
        return "tpu-backend"
    return metric


def _json_lines(text: str):
    """Every parseable JSON object in a blob of output lines —
    the validate-on-chip wrappers bury their payload in ``tail``."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            out.append(obj)
    return out


def _rec(run_id, metric, result, platform, source):
    return normalize_record(
        _mode_for(metric or "unknown"), result, run_id=run_id,
        sha="unknown", host=str(platform or "unknown"), smoke=False,
        source=source)


def records_from(path: str):
    """Normalized records for ONE historical file (see module
    docstring for the shapes)."""
    stem = os.path.splitext(os.path.basename(path))[0]
    with open(path) as f:
        text = f.read()
    if stem.startswith("SUITE_"):
        data = None  # multi-line JSONL, parsed per line below
    else:
        data = json.loads(text)
    out = []

    def from_wrapper(w, run_id):
        parsed = w.get("parsed")
        if not isinstance(parsed, dict):
            parsed = {"rc": w.get("rc")}
        result = dict(parsed)
        if w.get("rc") is not None:
            result.setdefault("rc", w["rc"])
        out.append(_rec(run_id, parsed.get("metric"), result,
                        parsed.get("platform"), stem))

    if stem.startswith("BENCH_"):
        if isinstance(data, list):
            for i, w in enumerate(data):
                if isinstance(w, dict) and "parsed" in w:
                    from_wrapper(w, f"{stem.lower()}-{i:02d}")
                elif isinstance(w, dict):
                    out.append(_rec(f"{stem.lower()}-{i:02d}",
                                    w.get("metric"), w,
                                    w.get("platform"), stem))
        elif isinstance(data, dict) and "parsed" in data:
            from_wrapper(data, stem.lower())
        elif isinstance(data, dict):
            out.append(_rec(stem.lower(), data.get("metric"), data,
                            data.get("platform"), stem))
    elif stem.startswith("SERVE_"):
        out.append(_rec(stem.lower(), data.get("metric"), data,
                        data.get("platform"), stem))
    elif stem.startswith("MULTICHIP_SCALE_"):
        host = f"multichip{data.get('n_devices', 0)}"
        out.append(_rec(stem.lower(), "multichip_scale", data, host,
                        stem))
    elif stem.startswith("MULTICHIP_"):
        host = f"multichip{data.get('n_devices', 0)}"
        payload = {"rc": data.get("rc"),
                   "n_devices": data.get("n_devices")}
        for obj in _json_lines(data.get("tail", "")):
            payload.update(obj)
        out.append(_rec(stem.lower(), "multichip_validate", payload,
                        host, stem))
    elif stem.startswith("SUITE_"):
        for i, obj in enumerate(_json_lines(text)):
            out.append(_rec(f"{stem.lower()}-{i:02d}",
                            obj.get("metric"), obj,
                            obj.get("platform"), stem))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fold historical BENCH/SERVE/MULTICHIP/SUITE "
                    "result files into the bench trajectory")
    ap.add_argument("--src", default=".",
                    help="directory holding the historical files")
    ap.add_argument("--out", default=TRAJECTORY_PATH)
    args = ap.parse_args(argv)

    have = {r.get("run_id") for r in load_trajectory(args.out)}
    paths = []
    for pat in ("BENCH_r*.json", "SERVE_r*.json", "MULTICHIP_r*.json",
                "MULTICHIP_SCALE_r*.json", "SUITE_r*.json"):
        paths.extend(glob.glob(os.path.join(args.src, pat)))
    added = skipped = 0
    for path in sorted(set(paths)):
        for rec in records_from(path):
            if rec["run_id"] in have:
                skipped += 1
                continue
            append_record(rec, args.out)
            have.add(rec["run_id"])
            added += 1
    print(f"backfill: {added} record(s) added, {skipped} skipped "
          f"(already present) -> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Host-layer AST linter: project-specific race/discipline rules.

Every rule encodes a bug class this codebase has actually shipped or
is structurally exposed to (see docs/ANALYSIS.md for the catalog with
reproduced bugs):

- ``socket-no-timeout`` — a socket created without a timeout bound
  turns one silent peer into an unbounded stall of the (single-
  connection) replication endpoint.
- ``lock-discipline`` — reads/writes of attributes a class declares
  lock-guarded (``_CRDTLINT_GUARDED``) outside a ``with self.<lock>:``
  block.
- ``wall-clock-read`` — wall-clock reads outside the one sanctioned
  boundary (``hlc.wall_clock_millis``); HLC math against an ad-hoc
  clock source breaks drift accounting and injected-clock tests.
- ``hlc-wall-compare`` — comparing HLC state against a wall-clock
  read; HLCs order by ``(logical_time, node)``, not wall time.
- ``record-mutation`` — in-place mutation of a ``Record``'s
  ``hlc``/``modified``/``value``; records are handed to merge/watch
  machinery by reference and must be treated as immutable cells.
- ``add-batch-unique-keys`` — passing a keyed ``get`` callback to
  ``ChangeHub.add_batch`` without a visible uniqueness gate; ``get``
  answers a key AT MOST ONCE per batch, so repeat-capable batches
  must pass ``get=None`` (the round-5 ADVICE bug).
- ``donated-buffer-reuse`` — reusing a store buffer after passing it
  to a scatter wrapper with ``donate=True``; the donated buffer is
  aliased and its contents are undefined after the call.
- ``scatter-combiner-bypass`` — calling a store scatter wrapper
  (``put_scatter``/``record_scatter``/``delete_scatter``/
  ``ingest_scatter``/``ingest_scatter_tiles``) in a function with no
  visible ingest gate (no ``drain_ingest`` call and no ``_ingest``
  check before the call); a staged ``ingest()`` window would commit
  its backlog AFTER such a write, stamping over it out of HLC order.
  The combiner's own flush is the one sanctioned direct writer
  (reasoned suppression).
- ``pack-path-extra-copy`` — a materializing call (``bytes(...)``,
  ``.tobytes()``, ``np.asarray``/``np.ascontiguousarray``/
  ``np.array``) inside a pack→frame function; the zero-copy fast path
  frames memoryviews over the pack arena directly, and every stray
  copy silently re-inflates bytes-to-wire latency. Legitimate copies
  (a device_get, normalizing a foreign lane) carry reasoned
  suppressions and are counted in
  ``crdt_tpu_pack_copy_bytes_total`` (docs/FASTPATH.md).
- ``merkle-digest-host-hash`` — a host-side hash call (``hashlib.*``,
  builtin ``hash(...)``, ``zlib.crc32``/``zlib.adler32``) inside a
  digest/merkle-path function; the anti-entropy digest is the
  device's job (one jit-cached reduction in ``ops/digest.py``), and a
  host re-hash both drags store lanes off device and — for builtin
  ``hash`` — is salted per process, so equal stores digest unequal
  (docs/ANTIENTROPY.md).
- ``async-blocking-call`` — a blocking call (``time.sleep``, a
  ``socket.*`` constructor, a blocking socket method, or one of the
  sync frame helpers ``send_frame``/``recv_frame``/
  ``send_bytes_frame``/``recv_bytes_frame``) lexically inside an
  ``async def``; one blocked coroutine stalls the serving tier's
  entire event loop and every multiplexed session on it
  (docs/SERVING.md). Route device/file work through
  ``loop.run_in_executor`` and sleep with ``asyncio.sleep``. Passing
  a sync helper BY REFERENCE to an executor is fine — only the
  direct call blocks. Also flags a synchronous ``with self.<lock>:``
  inside an ``async def`` when ``<lock>`` is named by the class's
  ``_CRDTLINT_LOCK_ORDER`` contract: a contended thread-lock
  acquisition parks the event loop exactly like a blocking socket
  (``async with`` on an asyncio lock is the sanctioned form).
- ``thread-unnamed`` — a ``threading.Thread(...)`` constructed
  without a stable ``name=``; lock-order witness paths, the runtime
  sanitizer's violation events, and fleet traces all identify the
  holder by thread name, and ``Thread-12`` identifies nothing.
- ``metric-name-unprefixed`` — a counter/gauge/histogram registered
  outside the ``crdt_tpu_`` namespace, or a metric label whose value
  is drawn from a user key/slot. The fleet poller (obs/fleet.py)
  federates series by name, so an unprefixed name collides with
  foreign exporters; a per-key label value mints one time series per
  key — unbounded cardinality that melts the registry
  (docs/OBSERVABILITY.md).
- ``router-epoch-bypass`` — in a class carrying a partition router
  (``self.router`` assigned in ``__init__``), a keyspace-op enqueue
  (``self._q.append``/``.push``) with no router consultation
  lexically before it; such a write skips the ``moved``/stale-epoch
  admission gate and can land on a partition that no longer owns the
  slot mid-split (docs/FEDERATION.md).
- ``combiner-enqueue-unsafe`` — in a combiner-owning class
  (``self._wc`` assigned in ``__init__``, the serving-tier shape), a
  plain-list ``.append`` on the pending write queue (any
  ``self._q...`` target); multi-loop serving drains that queue from
  the committer while EVERY accept loop produces into it, so the one
  sanctioned enqueue is the MPSC gate's ``push`` — a bare list append
  races the committer's swap and can drop or double-resolve an acked
  write (docs/SERVING.md).
- ``collective-socket-fallback-silent`` — in a class carrying a
  pod-local replica group (``self._group`` assigned in ``__init__``),
  a ``try`` that attempts the collective join with an except-handler
  that neither counts the downgrade
  (``crdt_tpu_collective_fallback_total`` / ``stats.fallbacks``) nor
  re-raises; a co-located round silently landing on the socket path
  is an invisible topology regression (docs/COLLECTIVE.md).
- ``scale-decision-unfenced`` — in a class owning a federation handle
  (``self.fed`` assigned in ``__init__``, the autoscaler shape), a
  ``split_hot``/``merge_cold`` invocation without BOTH fences
  lexically before it: the table-epoch consult (any ``epoch``
  attribute/name read — the stale-observation fence) and the
  in-flight guard (any name containing ``inflight``/``in_flight``).
  A scale decision acted on a stale epoch can retire an arc a
  concurrent change just made hot, and overlapping changes race each
  other's ``_control`` hold (docs/FEDERATION.md).
- ``purge-watermark-unfenced`` — a ``.gc_purge(...)`` invocation
  without a stability-watermark consult (any name or attribute
  containing ``stability``) lexically at or before it in the same
  function. Epoch GC is only sound against a fleet stability
  watermark (`GossipNode.stability_hlc` / `ServeTier.stability_hlc`
  — min over every peer's durable delivery mark, pinned on any
  unmeasured input); purging against a local clock, a guess, or a
  single peer's ack physically deletes tombstones other replicas
  still need, and the resulting resurrection is silent data
  corruption (docs/STORAGE.md).

The linter is purely lexical/AST — no imports of the linted code — so
it runs on broken or unimportable files (the self-test fixtures).
Lock discipline is declaration-driven: a class opts in with

    _CRDTLINT_GUARDED = {"_lock": ("attr_a", "attr_b")}

and the linter enforces that every ``self.attr_a`` access in a method
sits lexically inside ``with self._lock:``. ``__init__`` is exempt
(construction happens-before publication).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, apply_suppressions, parse_suppressions

RULES = (
    "socket-no-timeout",
    "lock-discipline",
    "wall-clock-read",
    "hlc-wall-compare",
    "record-mutation",
    "add-batch-unique-keys",
    "donated-buffer-reuse",
    "scatter-combiner-bypass",
    "pack-path-extra-copy",
    "merkle-digest-host-hash",
    "async-blocking-call",
    "metric-name-unprefixed",
    "router-epoch-bypass",
    "combiner-enqueue-unsafe",
    "collective-socket-fallback-silent",
    "ack-before-replicate",
    "scale-decision-unfenced",
    "purge-watermark-unfenced",
    "thread-unnamed",
    "histogram-ceiling-gate",
    "suppression-without-reason",
)

_SOCKET_CTORS = {"create_connection", "create_server"}
_WALL_CALLS = {
    "time.time", "time.time_ns", "_time.time", "_time.time_ns",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}
_HLC_ATTRS = {"hlc", "canonical_time", "_canonical_time", "logical_time"}
_DONATING_WRAPPERS = {"put_scatter", "record_scatter", "delete_scatter",
                      "ingest_scatter", "ingest_scatter_tiles"}
_COMBINER_SCATTERS = _DONATING_WRAPPERS
# Lexical evidence that a function respects the write-combiner barrier:
# it drains the window, or it branches on the staging handle.
_COMBINER_GATES = {"drain_ingest", "_ingest"}
# pack-path-extra-copy fires only inside functions on the pack→frame
# path: names containing "pack" (but not the unpack/decode direction
# or the merge ingest surface, whose np.asarray lane normalization is
# the WIRE-IN side), plus the framing entry points by exact name.
_PACK_PATH_EXACT = {"encode", "send_bytes_frame"}
_PACK_COPY_CALLS = {"np.asarray", "np.ascontiguousarray",
                    "numpy.asarray", "numpy.ascontiguousarray"}
# merkle-digest-host-hash: host hash calls that must never appear on
# the digest path — the digest is the device's job, and builtin hash()
# is salted per process (PYTHONHASHSEED), so equal stores would digest
# unequal across replicas.
_HOST_HASH_CALLS = {"zlib.crc32", "zlib.adler32",
                    "_zlib.crc32", "_zlib.adler32"}
# async-blocking-call: calls that park the whole event loop when made
# directly from a coroutine. The sync frame helpers (net.py) block on
# sendall/recv under the hood; coroutines must use the async codec
# path in serve.py instead.
_ASYNC_BLOCKING_SLEEPS = {"time.sleep", "_time.sleep"}
_ASYNC_FRAME_HELPERS = {"send_frame", "recv_frame",
                        "send_bytes_frame", "recv_bytes_frame"}
_ASYNC_BLOCKING_SOCK_METHODS = {"sendall", "recv", "accept", "connect",
                                "makefile"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _is_wall_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    return d in _WALL_CALLS


def _contains_wall_call(node: ast.AST) -> bool:
    return any(_is_wall_call(n) for n in ast.walk(node))


def _contains_hlc_attr(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in _HLC_ATTRS:
            return True
        if isinstance(n, ast.Name) and n.id in _HLC_ATTRS:
            return True
    return False


# --- rule: socket-no-timeout ---

def _check_sockets(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for fn in _functions(tree):
        # assignment target (dotted) per socket-ctor call id
        targets: Dict[int, Optional[str]] = {}
        settimeout_on: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call):
                targets[id(node.value)] = _dotted(node.targets[0])
            if isinstance(node, ast.withitem) \
                    and isinstance(node.context_expr, ast.Call) \
                    and node.optional_vars is not None:
                targets[id(node.context_expr)] = _dotted(node.optional_vars)
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d is not None and d.endswith(".settimeout"):
                    settimeout_on.add(d.rsplit(".", 1)[0])
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            name = d.rsplit(".", 1)[-1]
            if name not in _SOCKET_CTORS and d != "socket.socket":
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            target = targets.get(id(node))
            if target is not None and target in settimeout_on:
                continue
            out.append(Finding(
                rule="socket-no-timeout", path=path, line=node.lineno,
                message=f"{d}(...) without a timeout bound (no timeout= "
                        "and no settimeout on the result); a silent peer "
                        "stalls this path forever"))
    # Pooled sessions: PeerConnection(..., idle_timeout=None) disables
    # the stale-session bound, so a parked connection can outlive the
    # server's io_timeout and the next round races a half-closed
    # socket. The default (20 s) is deliberately below the server's
    # 30 s — only an explicit None is flagged.
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None or d.rsplit(".", 1)[-1] != "PeerConnection":
            continue
        for kw in node.keywords:
            if kw.arg == "idle_timeout" \
                    and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is None:
                out.append(Finding(
                    rule="socket-no-timeout", path=path,
                    line=node.lineno,
                    message="PeerConnection(..., idle_timeout=None) "
                            "disables the stale-session bound; a "
                            "parked session can outlive the server's "
                            "io_timeout and the next round races a "
                            "half-closed socket"))
    return out


# --- rule: lock-discipline ---

def _guard_decl(cls: ast.ClassDef) -> Dict[str, Tuple[str, ...]]:
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == "_CRDTLINT_GUARDED":
            try:
                raw = ast.literal_eval(stmt.value)
            except (ValueError, SyntaxError):
                return {}
            if isinstance(raw, dict):
                return {str(k): tuple(str(a) for a in v)
                        for k, v in raw.items()}
    return {}


def _check_lock_discipline(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guards = _guard_decl(cls)
        if not guards:
            continue
        attr_to_lock = {attr: lock
                        for lock, attrs in guards.items()
                        for attr in attrs}

        def visit(node: ast.AST, held: frozenset) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = set()
                for item in node.items:
                    d = _dotted(item.context_expr)
                    if d is not None and d.startswith("self."):
                        lock = d[len("self."):]
                        if lock in guards:
                            acquired.add(lock)
                    # the lock expression itself runs unguarded
                    visit(item.context_expr, held)
                for child in node.body:
                    visit(child, held | frozenset(acquired))
                return
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and node.attr in attr_to_lock \
                    and attr_to_lock[node.attr] not in held:
                out.append(Finding(
                    rule="lock-discipline", path=path, line=node.lineno,
                    message=f"self.{node.attr} accessed outside "
                            f"'with self.{attr_to_lock[node.attr]}:' "
                            f"(declared guarded by "
                            f"{cls.name}._CRDTLINT_GUARDED)"))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name in ("__init__", "__new__", "__del__"):
                    continue
                visit(stmt, frozenset())
    return out


# --- rules: wall-clock-read / hlc-wall-compare ---

def _check_wall_clock(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    # calls inside the sanctioned boundary function are exempt
    exempt: Set[int] = set()
    for fn in _functions(tree):
        if fn.name == "wall_clock_millis":
            exempt.update(id(n) for n in ast.walk(fn))
    for node in ast.walk(tree):
        if _is_wall_call(node) and id(node) not in exempt:
            out.append(Finding(
                rule="wall-clock-read", path=path, line=node.lineno,
                message=f"{_dotted(node.func)}() outside "
                        "hlc.wall_clock_millis; clock-path code must "
                        "read wall time through the injectable boundary"))
        if isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if any(_contains_wall_call(s) for s in sides) \
                    and any(_contains_hlc_attr(s) for s in sides
                            if not _contains_wall_call(s)):
                out.append(Finding(
                    rule="hlc-wall-compare", path=path, line=node.lineno,
                    message="HLC state compared against a wall-clock "
                            "read; HLCs order by (logical_time, node), "
                            "not wall time"))
    return out


# --- rule: record-mutation ---

def _check_record_mutation(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            tgts = node.targets
        elif isinstance(node, ast.AugAssign):
            tgts = [node.target]
        else:
            continue
        for tgt in tgts:
            if not isinstance(tgt, ast.Attribute):
                continue
            base = _dotted(tgt.value)
            if base == "self":
                continue  # a class assigning its own slots
            hit = (tgt.attr in ("hlc", "modified")
                   or (tgt.attr == "value" and base is not None
                       and "record" in base.lower()))
            if hit:
                out.append(Finding(
                    rule="record-mutation", path=path, line=tgt.lineno,
                    message=f"in-place mutation of {base}.{tgt.attr}; "
                            "Records are shared by reference with "
                            "merge/watch machinery — build a new "
                            "Record instead"))
    return out


# --- rule: add-batch-unique-keys ---

def _get_arg(call: ast.Call) -> Optional[ast.AST]:
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "get":
            return kw.value
    return None


def _check_add_batch(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_batch"):
            continue
        get = _get_arg(node)
        if get is None:
            continue
        if isinstance(get, ast.Constant) and get.value is None:
            continue
        if isinstance(get, ast.IfExp) and any(
                isinstance(b, ast.Constant) and b.value is None
                for b in (get.body, get.orelse)):
            continue  # '... if unique else None' uniqueness gate
        out.append(Finding(
            rule="add-batch-unique-keys", path=path, line=node.lineno,
            message="add_batch(..., get=...) without a visible "
                    "uniqueness gate ('get if unique else None'); "
                    "get answers a key AT MOST ONCE per batch — a "
                    "repeat-capable batch must pass get=None "
                    "(suppress with the uniqueness argument if slots "
                    "are unique by construction)"))
    return out


# --- rule: donated-buffer-reuse ---

def _check_donated_reuse(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for fn in _functions(tree):
        # result-assignment targets per call id: 'store = put_scatter(
        # store, ..., donate=True)' rebinds the name, so later reads
        # see the fresh buffer and are fine.
        assigned: Dict[int, Set[str]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                names = {d for d in (_dotted(t) for t in node.targets)
                         if d is not None}
                assigned[id(node.value)] = names
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None or d.rsplit(".", 1)[-1] not in _DONATING_WRAPPERS:
                continue
            donated = any(
                kw.arg == "donate"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords)
            if not donated or not node.args:
                continue
            buf = _dotted(node.args[0])
            if buf is None or buf in assigned.get(id(node), set()):
                continue
            for later in ast.walk(fn):
                if not isinstance(later, (ast.Name, ast.Attribute)):
                    continue
                if getattr(later, "lineno", 0) <= node.lineno:
                    continue
                if _dotted(later) == buf \
                        and isinstance(getattr(later, "ctx", None),
                                       ast.Load):
                    out.append(Finding(
                        rule="donated-buffer-reuse", path=path,
                        line=later.lineno,
                        message=f"{buf} read after being donated to "
                                f"{d}(donate=True) at line "
                                f"{node.lineno}; a donated buffer is "
                                "aliased and undefined afterwards"))
                    break
    return out


# --- rule: scatter-combiner-bypass ---

def _check_combiner_bypass(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for fn in _functions(tree):
        if fn.name in _COMBINER_SCATTERS:
            # The public wrappers are definitionally below the barrier.
            # The combiner's own flush is NOT exempted by name — it
            # carries a reasoned suppression at its call site instead.
            continue
        gates: List[int] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) \
                    and node.attr in _COMBINER_GATES:
                gates.append(node.lineno)
            elif isinstance(node, ast.Name) and node.id in _COMBINER_GATES:
                gates.append(node.lineno)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None or d.rsplit(".", 1)[-1] not in _COMBINER_SCATTERS:
                continue
            if any(g <= node.lineno for g in gates):
                continue
            out.append(Finding(
                rule="scatter-combiner-bypass", path=path,
                line=node.lineno,
                message=f"{d}(...) writes the store with no visible "
                        "ingest gate (no drain_ingest call or _ingest "
                        "check earlier in this function); a staged "
                        "ingest() window would commit its backlog AFTER "
                        "this write and stamp over it out of HLC order "
                        "— drain first (suppress only for the "
                        "combiner's own flush)"))
    return out


# --- rule: pack-path-extra-copy ---

def _on_pack_path(name: str) -> bool:
    """Pack→frame functions only: the OUTBOUND direction. ``unpack``
    (wire-in decode) and ``merge`` (ingest surface — its np.asarray
    calls normalize PEER lanes, not the local pack) are the two name
    families that legitimately materialize."""
    low = name.lower()
    if low in _PACK_PATH_EXACT:
        return True
    return "pack" in low and "unpack" not in low and "merge" not in low


def _check_pack_path_copies(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for fn in _functions(tree):
        if not _on_pack_path(fn.name):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            what = None
            if d == "bytes":
                what = "bytes(...)"
            elif d in _PACK_COPY_CALLS:
                what = f"{d}(...)"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "tobytes":
                what = ".tobytes()"
            if what is None:
                continue
            out.append(Finding(
                rule="pack-path-extra-copy", path=path,
                line=node.lineno,
                message=f"{what} in pack-path function {fn.name}() "
                        "materializes a copy between pack and frame; "
                        "the fast path frames memoryviews over the "
                        "pack arena directly — if this copy is "
                        "required (device_get, foreign-lane "
                        "normalization), suppress with a reason and "
                        "count it in crdt_tpu_pack_copy_bytes_total"))
    return out


# --- rule: merkle-digest-host-hash ---

def _on_digest_path(name: str) -> bool:
    """Digest/merkle-path functions by name — the same lexical scoping
    the pack-path rule uses."""
    low = name.lower()
    return "digest" in low or "merkle" in low


def _check_digest_host_hash(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for fn in _functions(tree):
        if not _on_digest_path(fn.name):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            what = None
            if d == "hash":
                what = "builtin hash(...)"
            elif d in _HOST_HASH_CALLS:
                what = f"{d}(...)"
            elif d is not None and (d.startswith("hashlib.")
                                    or d.startswith("_hashlib.")):
                what = f"{d}(...)"
            if what is None:
                continue
            out.append(Finding(
                rule="merkle-digest-host-hash", path=path,
                line=node.lineno,
                message=f"{what} in digest-path function {fn.name}() "
                        "re-hashes on host; the anti-entropy digest "
                        "is computed on device (ops/digest.py) and "
                        "builtin hash() is salted per process, so a "
                        "host hash diverges across replicas — use the "
                        "device digest tree (docs/ANTIENTROPY.md)"))
    return out


# --- rule: async-blocking-call ---

def _own_nodes(fn: ast.AsyncFunctionDef):
    """The coroutine's OWN statements: nested defs are excluded — a
    nested sync helper is executor bait (called off-loop by design)
    and a nested async def gets its own visit from the outer walk."""
    def rec(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield child
            yield from rec(child)
    yield from rec(fn)


def _check_async_blocking(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        # a call that is directly awaited is an async API, whatever
        # its name — only the un-awaited form blocks the loop
        awaited = {id(n.value) for n in _own_nodes(fn)
                   if isinstance(n, ast.Await)}
        for node in _own_nodes(fn):
            if not isinstance(node, ast.Call) or id(node) in awaited:
                continue
            d = _dotted(node.func)
            what = None
            if d in _ASYNC_BLOCKING_SLEEPS:
                what = (f"{d}(...) parks the event loop; "
                        "await asyncio.sleep(...) instead")
            elif d is not None and (d == "socket.socket"
                                    or (d.startswith("socket.")
                                        and d.rsplit(".", 1)[-1]
                                        in _SOCKET_CTORS)):
                what = (f"{d}(...) creates a blocking socket; use "
                        "asyncio streams (asyncio.start_server / "
                        "open_connection)")
            elif d is not None and d.rsplit(".", 1)[-1] \
                    in _ASYNC_FRAME_HELPERS:
                what = (f"{d}(...) is the SYNC frame helper "
                        "(blocking sendall/recv under the hood); "
                        "coroutines must use the async frame path or "
                        "run_in_executor")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _ASYNC_BLOCKING_SOCK_METHODS:
                what = (f".{node.func.attr}(...) is a blocking socket "
                        "call; use asyncio transports or "
                        "run_in_executor")
            if what is None:
                continue
            out.append(Finding(
                rule="async-blocking-call", path=path,
                line=node.lineno,
                message=f"{what} — inside coroutine {fn.name}() this "
                        "stalls every session multiplexed on the "
                        "serving tier's loop (docs/SERVING.md)"))
    # Synchronous acquisition of a declared-contract thread lock
    # inside a coroutine: a contended `with self.<lock>:` parks the
    # event loop exactly like a blocking socket. The contract tuple
    # (`_CRDTLINT_LOCK_ORDER`) tells us which attributes are real
    # cross-thread locks; `async with` (an asyncio lock) is the
    # sanctioned form and is a different AST node entirely.
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        attrs = _lock_order_attrs(cls)
        if not attrs:
            continue
        for fn in ast.walk(cls):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _own_nodes(fn):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    d = _dotted(item.context_expr)
                    if d is not None and d.startswith("self.") \
                            and d[len("self."):] in attrs:
                        out.append(Finding(
                            rule="async-blocking-call", path=path,
                            line=item.context_expr.lineno,
                            message=f"sync `with {d}:` inside "
                                    f"coroutine {fn.name}() — "
                                    f"{d[len('self.'):]} is a "
                                    "declared contract lock "
                                    "(_CRDTLINT_LOCK_ORDER), and a "
                                    "contended thread-lock "
                                    "acquisition parks the event "
                                    "loop; hold it via "
                                    "run_in_executor or switch to "
                                    "an asyncio.Lock"))
    return out


def _lock_order_attrs(cls: ast.ClassDef) -> Set[str]:
    """Bare ``self.<attr>`` lock names a class's
    ``_CRDTLINT_LOCK_ORDER`` contract declares (pattern entries name
    foreign locks and don't bind a self attribute)."""
    for stmt in cls.body:
        value = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == "_CRDTLINT_LOCK_ORDER":
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name) \
                and stmt.target.id == "_CRDTLINT_LOCK_ORDER" \
                and stmt.value is not None:
            value = stmt.value
        if value is None:
            continue
        try:
            raw = ast.literal_eval(value)
        except (ValueError, SyntaxError):
            return set()
        if isinstance(raw, (tuple, list)):
            return {e for e in raw if isinstance(e, str)}
        return set()
    return set()


# --- rule: thread-unnamed ---

def _check_thread_unnamed(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None or not (d == "Thread"
                             or d.endswith("threading.Thread")
                             or d == "_threading.Thread"):
            continue
        if any(kw.arg == "name" for kw in node.keywords):
            continue
        out.append(Finding(
            rule="thread-unnamed", path=path, line=node.lineno,
            message="threading.Thread(...) without a stable name= — "
                    "lock-order witness paths, sanitizer violation "
                    "events, and fleet traces identify the holder by "
                    "thread name, and the default Thread-N "
                    "identifies nothing"))
    return out


# --- rule: metric-name-unprefixed ---

# Metric registration surfaces (MetricsRegistry methods) and the
# observation methods that accept **label kwargs. jax's `.at[..].set()`
# takes labels-free positional/mode args, so restricting the
# cardinality scan to KEYWORD values keeps it off the device paths.
_METRIC_CTORS = {"counter", "gauge", "histogram"}
_METRIC_LABEL_SINKS = _METRIC_CTORS | {"inc", "observe", "set"}
# Identifier shapes that mean "this came from user data": a key or a
# store slot. Bounded enums (op/trigger/phase/lane/node/peer) are the
# sanctioned label vocabulary.
_USER_KEY_NAMES = {"key", "keys", "user_key", "raw_key",
                   "slot", "slots"}
_METRIC_PREFIX = "crdt_tpu_"


def _mentions_user_key(node: ast.AST) -> Optional[str]:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in _USER_KEY_NAMES:
            return n.id
        if isinstance(n, ast.Attribute) and n.attr in _USER_KEY_NAMES:
            return n.attr
    return None


def _check_metric_names(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        attr = node.func.attr
        if attr in _METRIC_CTORS and node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str) \
                    and not first.value.startswith(_METRIC_PREFIX):
                out.append(Finding(
                    rule="metric-name-unprefixed", path=path,
                    line=node.lineno,
                    message=f".{attr}({first.value!r}) registers a "
                            "metric outside the 'crdt_tpu_' "
                            "namespace; the fleet poller federates "
                            "series by name, and an unprefixed name "
                            "collides with foreign exporters "
                            "(docs/OBSERVABILITY.md)"))
        if attr in _METRIC_LABEL_SINKS:
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                bad = _mentions_user_key(kw.value)
                if bad is not None:
                    out.append(Finding(
                        rule="metric-name-unprefixed", path=path,
                        line=node.lineno,
                        message=f"label {kw.arg}= draws its value "
                                f"from {bad!r} (a user key/slot); "
                                "per-key label values mint one time "
                                "series per key — unbounded "
                                "cardinality. Aggregate, bucket, or "
                                "drop the label "
                                "(docs/OBSERVABILITY.md)"))
    return out


# --- rule: router-epoch-bypass ---

# Lexical evidence that a method admits keyspace ops through the
# partition router before enqueueing: it touches self.router, or it
# calls the tier's route-verdict helper (the batched binop admission
# path goes through check_batch).
_ROUTER_GATE_CALLS = {"_route_verdict", "check", "check_batch"}

# Enqueue spellings the write-queue rules recognize: list-era append
# and the MPSC gate's push.
_ENQUEUE_CALLS = {"append", "push"}


def _check_router_bypass(tree: ast.AST, path: str) -> List[Finding]:
    """In a class that carries a partition router (``self.router``
    assigned in ``__init__``), every method that enqueues a keyspace
    op (``self._q.append``/``.push``) must consult the router FIRST —
    an enqueue lexically before any router reference is a write the
    `moved`/stale-epoch protocol never saw, which silently violates
    partition ownership during a live split (docs/FEDERATION.md)."""
    out: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        routed = False
        for fn in cls.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn.name == "__init__":
                for n in ast.walk(fn):
                    if isinstance(n, ast.Attribute) \
                            and n.attr == "router" \
                            and isinstance(n.value, ast.Name) \
                            and n.value.id == "self" \
                            and isinstance(n.ctx, ast.Store):
                        routed = True
        if not routed:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    or fn.name == "__init__":
                continue
            gate_line = None
            appends: List[ast.Call] = []
            for n in ast.walk(fn):
                if isinstance(n, ast.Attribute) \
                        and n.attr == "router" \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id == "self":
                    if gate_line is None or n.lineno < gate_line:
                        gate_line = n.lineno
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _ROUTER_GATE_CALLS:
                    if gate_line is None or n.lineno < gate_line:
                        gate_line = n.lineno
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _ENQUEUE_CALLS:
                    tgt = _dotted(n.func.value)
                    if tgt == "self._q":
                        appends.append(n)
            for call in appends:
                if gate_line is None or call.lineno < gate_line:
                    out.append(Finding(
                        rule="router-epoch-bypass", path=path,
                        line=call.lineno,
                        message=f"{fn.name}() enqueues a keyspace op "
                                "(self._q enqueue) without first "
                                "consulting self.router — the op "
                                "bypasses the moved/stale-epoch "
                                "admission gate and can land on a "
                                "partition that no longer owns the "
                                "slot mid-split "
                                "(docs/FEDERATION.md)"))
    return out


# --- rule: combiner-enqueue-unsafe ---


def _dotted_through_subscripts(node: ast.AST) -> Optional[str]:
    """Like ``_dotted`` but a subscript link in the chain is elided
    rather than fatal: ``self._q._stripes[0].items`` reads as
    ``self._q._stripes.items`` — reaching INTO the queue's stripes is
    exactly the bypass this rule exists to catch."""
    parts: List[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        else:
            break
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _check_combiner_enqueue(tree: ast.AST, path: str) -> List[Finding]:
    """In a combiner-owning class (``self._wc`` assigned in
    ``__init__`` — the serving-tier shape), every enqueue into the
    pending write queue must go through the MPSC gate (``.push``): a
    plain-list ``.append`` on any ``self._q...`` target is a producer
    that bypasses the stripe locks, racing the committer's drain swap
    from whatever thread it runs on. ``__init__`` is exempt
    (construction happens-before publication)."""
    out: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        owns_wc = False
        for fn in cls.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn.name == "__init__":
                for n in ast.walk(fn):
                    if isinstance(n, ast.Attribute) \
                            and n.attr == "_wc" \
                            and isinstance(n.value, ast.Name) \
                            and n.value.id == "self" \
                            and isinstance(n.ctx, ast.Store):
                        owns_wc = True
        if not owns_wc:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    or fn.name == "__init__":
                continue
            for n in ast.walk(fn):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "append"):
                    continue
                tgt = _dotted_through_subscripts(n.func.value)
                if tgt is not None and tgt.startswith("self._q"):
                    out.append(Finding(
                        rule="combiner-enqueue-unsafe", path=path,
                        line=n.lineno,
                        message=f"{fn.name}() appends to {tgt} "
                                "directly — the pending write queue "
                                "of a combiner-owning class is "
                                "multi-producer, and only the MPSC "
                                "gate (.push) is safe against the "
                                "committer's drain swap; a bare list "
                                "append can drop or double-resolve "
                                "an acked write (docs/SERVING.md)"))
    return out


# --- rule: collective-socket-fallback-silent ---

# Lexical evidence that an except-handler counts the downgrade before
# the round lands on sockets: the fallback counter's name, or a
# fallbacks-stat bump.
_COLLECTIVE_FALLBACK_EVIDENCE = ("collective_fallback", "fallbacks")


def _check_collective_fallback(tree: ast.AST,
                               path: str) -> List[Finding]:
    """In a class that carries a pod-local replica group
    (``self._group`` assigned in ``__init__``), a ``try`` that
    attempts the collective lane (a ``.join()`` call on the group)
    must count the downgrade in every handler that swallows the
    failure — a co-located round silently landing on the socket path
    is a topology regression no dashboard would ever show
    (docs/COLLECTIVE.md). Handlers that re-raise are exempt: loud is
    fine, silent is the finding."""
    out: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        grouped = False
        for fn in cls.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn.name == "__init__":
                for n in ast.walk(fn):
                    if isinstance(n, ast.Attribute) \
                            and n.attr == "_group" \
                            and isinstance(n.value, ast.Name) \
                            and n.value.id == "self" \
                            and isinstance(n.ctx, ast.Store):
                        grouped = True
        if not grouped:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            for tr in ast.walk(fn):
                if not isinstance(tr, ast.Try):
                    continue
                joins = [n for stmt in tr.body for n in ast.walk(stmt)
                         if isinstance(n, ast.Call)
                         and isinstance(n.func, ast.Attribute)
                         and n.func.attr == "join"
                         and "group" in (_dotted(n.func.value) or "")]
                if not joins:
                    continue
                for handler in tr.handlers:
                    body_src = ast.unparse(ast.Module(
                        body=handler.body, type_ignores=[]))
                    if any(isinstance(n, ast.Raise)
                           for stmt in handler.body
                           for n in ast.walk(stmt)):
                        continue
                    if any(ev in body_src
                           for ev in _COLLECTIVE_FALLBACK_EVIDENCE):
                        continue
                    out.append(Finding(
                        rule="collective-socket-fallback-silent",
                        path=path, line=handler.lineno,
                        message=f"{fn.name}() catches a failed "
                                "collective join without counting the "
                                "downgrade — the round lands on the "
                                "socket path invisibly; increment "
                                "crdt_tpu_collective_fallback_total "
                                "(or peer.stats.fallbacks) in the "
                                "handler, or re-raise "
                                "(docs/COLLECTIVE.md)"))
    return out


# --- rule: ack-before-replicate ---


def _check_ack_before_replicate(tree: ast.AST,
                                path: str) -> List[Finding]:
    """In a class that carries a write-concern replicator
    (``self.replicator`` assigned in ``__init__`` — the primary
    serving surface), any method that resolves a client ack future
    (``set_result``) must consult the replicator FIRST: an ack
    resolved lexically before any ``self.replicator`` read (or a
    ``.barrier()`` call) can reach the client before the tick's delta
    is confirmed on any follower, and a primary crash then loses an
    ACKED write — the exact failure write concern exists to exclude
    (docs/REPLICATION.md). Matching the bare ``set_result`` attribute
    (not just calls) also catches the callback-passing form
    (``call_soon_threadsafe(fut.set_result, ...)``)."""
    out: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        replicated = False
        for fn in cls.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn.name == "__init__":
                for n in ast.walk(fn):
                    if isinstance(n, ast.Attribute) \
                            and n.attr == "replicator" \
                            and isinstance(n.value, ast.Name) \
                            and n.value.id == "self" \
                            and isinstance(n.ctx, ast.Store):
                        replicated = True
        if not replicated:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    or fn.name == "__init__":
                continue
            gate_line = None
            acks: List[ast.Attribute] = []
            for n in ast.walk(fn):
                if isinstance(n, ast.Attribute) \
                        and n.attr == "replicator" \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id == "self" \
                        and isinstance(n.ctx, ast.Load):
                    if gate_line is None or n.lineno < gate_line:
                        gate_line = n.lineno
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "barrier":
                    if gate_line is None or n.lineno < gate_line:
                        gate_line = n.lineno
                if isinstance(n, ast.Attribute) \
                        and n.attr == "set_result" \
                        and isinstance(n.ctx, ast.Load):
                    acks.append(n)
            for ack in acks:
                if gate_line is None or ack.lineno < gate_line:
                    out.append(Finding(
                        rule="ack-before-replicate", path=path,
                        line=ack.lineno,
                        message=f"{fn.name}() resolves a client ack "
                                "(set_result) without first "
                                "consulting self.replicator — the "
                                "ack can land before the "
                                "write-concern barrier confirmed the "
                                "tick on any follower, so a primary "
                                "crash loses an ACKED write "
                                "(docs/REPLICATION.md)"))
    return out


_SCALE_CALLS = {"split_hot", "merge_cold"}


def _ident_contains(name: str, needles: Tuple[str, ...]) -> bool:
    low = name.lower()
    return any(n in low for n in needles)


def _check_scale_fence(tree: ast.AST, path: str) -> List[Finding]:
    """In a class owning a federation handle (``self.fed`` assigned
    in ``__init__`` — the autoscaler shape), any method that fires a
    topology change (a ``split_hot``/``merge_cold`` call) must
    consult BOTH fences lexically first: the table epoch (an
    attribute or name containing ``epoch`` — the stale-observation
    fence) and the in-flight guard (a name containing ``inflight`` /
    ``in_flight``). A decision acted on a stale epoch can retire an
    arc a concurrent change just made hot; a second change fired
    while one is in flight races its ``_control`` hold
    (docs/FEDERATION.md)."""
    out: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        owns_fed = False
        for fn in cls.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn.name == "__init__":
                for n in ast.walk(fn):
                    if isinstance(n, ast.Attribute) \
                            and n.attr == "fed" \
                            and isinstance(n.value, ast.Name) \
                            and n.value.id == "self" \
                            and isinstance(n.ctx, ast.Store):
                        owns_fed = True
        if not owns_fed:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    or fn.name == "__init__":
                continue
            epoch_line = None
            inflight_line = None
            calls: List[ast.Call] = []
            for n in ast.walk(fn):
                if isinstance(n, ast.Attribute) \
                        and isinstance(n.ctx, ast.Load):
                    if _ident_contains(n.attr, ("epoch",)):
                        if epoch_line is None \
                                or n.lineno < epoch_line:
                            epoch_line = n.lineno
                    if _ident_contains(n.attr,
                                       ("inflight", "in_flight")):
                        if inflight_line is None \
                                or n.lineno < inflight_line:
                            inflight_line = n.lineno
                if isinstance(n, ast.Name) \
                        and isinstance(n.ctx, ast.Load):
                    if _ident_contains(n.id, ("epoch",)):
                        if epoch_line is None \
                                or n.lineno < epoch_line:
                            epoch_line = n.lineno
                    if _ident_contains(n.id,
                                       ("inflight", "in_flight")):
                        if inflight_line is None \
                                or n.lineno < inflight_line:
                            inflight_line = n.lineno
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _SCALE_CALLS:
                    calls.append(n)
            for call in calls:
                missing = []
                if epoch_line is None or call.lineno < epoch_line:
                    missing.append("the table-epoch fence")
                if inflight_line is None \
                        or call.lineno < inflight_line:
                    missing.append("the in-flight guard")
                if missing:
                    out.append(Finding(
                        rule="scale-decision-unfenced", path=path,
                        line=call.lineno,
                        message=f"{fn.name}() invokes "
                                f"{call.func.attr}() without "
                                f"consulting {' or '.join(missing)} "
                                "first — a stale observation can "
                                "retire a fresh arc, and overlapping "
                                "topology changes race each other "
                                "(docs/FEDERATION.md)"))
    return out


def _check_purge_watermark(tree: ast.AST, path: str) -> List[Finding]:
    """Any function invoking ``.gc_purge(...)`` must consult a
    stability watermark lexically at or before the call: a Load of a
    name or attribute containing ``stability`` (the
    `GossipNode.stability_hlc` / `ServeTier.stability_hlc` surfaces,
    or a local bound from them — including the call's own argument).
    Epoch GC against anything weaker than the fleet stability
    watermark physically deletes tombstones some replica still
    needs, and the eventual resurrection is silent data corruption
    (docs/STORAGE.md)."""
    out: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        evidence: Optional[int] = None
        calls: List[ast.Call] = []
        for n in ast.walk(fn):
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.ctx, ast.Load) \
                    and _ident_contains(n.attr, ("stability",)):
                if evidence is None or n.lineno < evidence:
                    evidence = n.lineno
            if isinstance(n, ast.Name) \
                    and isinstance(n.ctx, ast.Load) \
                    and _ident_contains(n.id, ("stability",)):
                if evidence is None or n.lineno < evidence:
                    evidence = n.lineno
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "gc_purge":
                calls.append(n)
        for call in calls:
            if evidence is None or call.lineno < evidence:
                out.append(Finding(
                    rule="purge-watermark-unfenced", path=path,
                    line=call.lineno,
                    message=f"{fn.name}() invokes gc_purge() without "
                            "consulting a stability watermark first "
                            "— purging against anything weaker than "
                            "the fleet stability floor deletes "
                            "tombstones other replicas still need "
                            "(docs/STORAGE.md)"))
    return out


_BUDGET_NEEDLES = ("budget",)


def _hq_tainted(node: ast.AST, tainted: Set[str]) -> bool:
    """Does ``node`` carry ``histogram_quantile`` output — a direct
    call, or a Load of a name the caller already marked tainted?"""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = _dotted(n.func)
            if name and name.rsplit(".", 1)[-1] \
                    == "histogram_quantile":
                return True
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id in tainted:
            return True
    return False


def _mentions_budget(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) \
                and _ident_contains(n.id, _BUDGET_NEEDLES):
            return True
        if isinstance(n, ast.Attribute) \
                and _ident_contains(n.attr, _BUDGET_NEEDLES):
            return True
    return False


def _check_histogram_ceiling_gate(tree: ast.AST,
                                  path: str) -> List[Finding]:
    """Comparing ``histogram_quantile(...)`` output against an SLO
    budget is a verdict built on quantization, not latency: the log2
    histogram answers the bucket CEILING, so a true p99 of 16 ms
    reads as 31.25 ms and any off-power-of-two budget (the 14.6 ms
    serve envelope) either flaps or can never pass. Controller and
    verdict code must gate on the quantile sketch
    (``registry.sketch()`` / ``obs.fleet.fleet_sketch``), which
    answers true quantiles within ~1% relative error; ceilings are
    for display. Taint is tracked per function through assignments
    (including min/max folds), so ``v = histogram_quantile(s, .99);
    ceil = max(ceil, v); if ceil > budget:`` still fires."""
    out: List[Finding] = []
    for fn in _functions(tree):
        tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign):
                    value, targets = n.value, n.targets
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                    value, targets = n.value, [n.target]
                elif isinstance(n, ast.NamedExpr):
                    value, targets = n.value, [n.target]
                else:
                    continue
                if value is None \
                        or not _hq_tainted(value, tainted):
                    continue
                for t in targets:
                    if isinstance(t, ast.Name) \
                            and t.id not in tainted:
                        tainted.add(t.id)
                        changed = True
        for n in ast.walk(fn):
            if not isinstance(n, ast.Compare):
                continue
            sides = [n.left] + list(n.comparators)
            if any(_hq_tainted(s, tainted) for s in sides) \
                    and any(_mentions_budget(s) for s in sides):
                out.append(Finding(
                    rule="histogram-ceiling-gate", path=path,
                    line=n.lineno,
                    message=f"{fn.name}() gates an SLO budget on "
                            "histogram_quantile output — a log2 "
                            "bucket CEILING, not the latency; an "
                            "off-power-of-two budget flaps or never "
                            "passes. Gate on the quantile sketch "
                            "(obs/sketch.py, ~1% relative error); "
                            "ceilings are display-only"))
    return out


_ALL_CHECKS = (
    _check_sockets,
    _check_lock_discipline,
    _check_wall_clock,
    _check_record_mutation,
    _check_add_batch,
    _check_donated_reuse,
    _check_combiner_bypass,
    _check_pack_path_copies,
    _check_digest_host_hash,
    _check_async_blocking,
    _check_metric_names,
    _check_router_bypass,
    _check_combiner_enqueue,
    _check_collective_fallback,
    _check_ack_before_replicate,
    _check_scale_fence,
    _check_purge_watermark,
    _check_thread_unnamed,
    _check_histogram_ceiling_gate,
)


def lint_source(text: str, path: str) -> List[Finding]:
    """Lint one source text. ``path`` labels findings and is matched
    against suppression comments in ``text``."""
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [Finding(rule="parse-error", path=path,
                        line=e.lineno or 0,
                        message=f"file does not parse: {e.msg}")]
    findings: List[Finding] = []
    for check in _ALL_CHECKS:
        findings.extend(check(tree, path))
    findings = apply_suppressions(findings, parse_suppressions(text),
                                  path)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def lint_package(root: str) -> List[Finding]:
    """Lint every .py file under ``root`` (the crdt_tpu package)."""
    findings: List[Finding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__",))
        for name in sorted(filenames):
            if name.endswith(".py"):
                findings.extend(lint_file(os.path.join(dirpath, name)))
    return findings

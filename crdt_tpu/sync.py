"""Anti-entropy sync rounds (C10) — the reference's replication protocol
as a library utility.

The reference keeps the sync round in its tests
(`test/map_crdt_test.dart:273-279`): capture the local canonical time,
full-push to the remote, then delta-pull everything the remote modified
at-or-after that time (inclusive bound, map_crdt.dart:44-45). Three-node
convergence through an intermediary relies on merged records being
re-stamped with the relay's ``modified`` time (crdt.dart:87) — the
relay's deltas then include records it learned from others.

Three transports:

- :func:`sync` — in-process record maps (replicas share a process, the
  reference's own test topology).
- :func:`sync_json` — the JSON wire format (crdt_json.dart), what
  crosses a real replica boundary; transport remains the application's
  job (example/crdt_example.dart:21-25).
- :func:`sync_packed` — the O(k) packed columnar form
  (`DenseCrdt.pack_since` / `merge_packed`), the in-process twin of
  `net.sync_packed_over_conn` — same one-watermark round shape, no
  sockets. Both replicas must speak the packed form.

Plus one group form: :func:`sync_collective` joins a whole
mesh-co-located `CollectiveGroup` in ONE device dispatch — no wire
form at all (docs/COLLECTIVE.md).
"""

from __future__ import annotations

from typing import Optional

from .crdt import Crdt
from .hlc import Hlc
from .record import (KeyDecoder, KeyEncoder, ValueDecoder, ValueEncoder)

# Default for ``since``: pull from the SAME round's pre-push canonical
# time — the reference's one-shot `_sync` shape. Distinct from None,
# which (matching `sync_over_tcp`) requests a cold-start FULL pull.
_SAME_ROUND = object()


def sync(local: Crdt, remote: Crdt, since=_SAME_ROUND) -> Hlc:
    """One push/pull anti-entropy round between two in-process replicas.

    After a round in each direction (or one round plus a later reverse
    round) the two replicas converge; N replicas converge through any
    connected gossip topology.

    ``since`` aligns this with :func:`crdt_tpu.net.sync_over_tcp`'s
    watermark contract: omit it for the reference's one-shot round
    (pull bounded by this round's pre-push canonical time), pass
    ``None`` for a cold-start full pull, or pass the watermark a
    previous round returned to resume delta sync."""
    watermark = local.canonical_time
    remote.merge(local.record_map())
    local.merge(remote.record_map(
        modified_since=watermark if since is _SAME_ROUND else since))
    return watermark


def sync_json(local: Crdt, remote: Crdt,
              key_encoder: Optional[KeyEncoder] = None,
              value_encoder: Optional[ValueEncoder] = None,
              key_decoder: Optional[KeyDecoder] = None,
              value_decoder: Optional[ValueDecoder] = None,
              since=_SAME_ROUND) -> Hlc:
    """The same round over the JSON wire format — full-state push, then
    delta pull keyed on the pre-push canonical time (crdt.dart:124-135).
    ``since`` follows :func:`sync`'s watermark contract."""
    watermark = local.canonical_time
    remote.merge_json(local.to_json(key_encoder=key_encoder,
                                    value_encoder=value_encoder),
                      key_decoder=key_decoder,
                      value_decoder=value_decoder)
    local.merge_json(remote.to_json(
        modified_since=watermark if since is _SAME_ROUND else since,
        key_encoder=key_encoder,
        value_encoder=value_encoder),
        key_decoder=key_decoder,
        value_decoder=value_decoder)
    return watermark


def sync_packed(local, remote, since=_SAME_ROUND) -> Hlc:
    """The same round on the packed columnar wire form: push only the
    rows the local replica modified since ``since``, pull only the
    rows the remote modified since the same watermark. ``since``
    follows :func:`sync`'s contract (omit: one-shot round bounded by
    this round's pre-push canonical time; ``None``: cold-start full
    exchange; a prior round's return: resume delta sync — the single
    watermark soundly bounds BOTH halves, exactly as
    `net.sync_packed_over_conn`). Empty halves (k == 0) skip the
    merge, keeping both clocks — and so both pack caches — still on
    a no-change round."""
    # Commit any ingest-window backlog before the watermark read:
    # pack_since drains internally, but that flush advances the
    # canonical AFTER a watermark captured here, and the stale bound
    # would re-send every flushed row on the next round.
    drain = getattr(local, "drain_ingest", None)
    if drain is not None:
        drain()
    watermark = local.canonical_time
    # One-shot shape: FULL push (the reference pushes its whole record
    # map), pull bounded by the pre-push canonical time. With an
    # explicit watermark, the same bound governs both halves.
    push_bound = None if since is _SAME_ROUND else since
    pull_bound = watermark if since is _SAME_ROUND else since
    # In-process twin of the hello negotiation: the sem tag lane rides
    # only when BOTH replicas expose the typed surface (docs/TYPES.md);
    # otherwise typed rows are withheld, never stripped of their tags.
    from .net import _pack_for_peer
    from .obs.trace import round_id, span, tracer
    sem_ok = (hasattr(local, "set_semantics")
              and hasattr(remote, "set_semantics"))
    node = str(getattr(local, "node_id", ""))
    # Same round id a socket round would carry in its trace context —
    # in-process rounds correlate in the one JSONL sink identically.
    rid = {"rid": round_id(node)} if tracer().enabled else {}
    with span("sync_packed", kind="sync", node=node,
              hlc=lambda: local.canonical_time,
              peer=str(getattr(remote, "node_id", "")), **rid):
        packed, ids = _pack_for_peer(local, push_bound, sem_ok)
        if packed.k:
            remote.merge_packed(packed, ids)
        pulled, pulled_ids = _pack_for_peer(remote, pull_bound, sem_ok)
        if pulled.k:
            if hasattr(local, "merge_and_repack"):
                # Fused merge+repack: the pull's join also computes
                # (and caches) the NEXT round's push pack under this
                # round's watermark — the exact `since` a resumed
                # delta round presents (docs/FASTPATH.md).
                local.merge_and_repack(
                    pulled, pulled_ids, since=watermark,
                    sem_mode="include" if sem_ok else "auto")
            else:
                local.merge_packed(pulled, pulled_ids)
    return watermark


def sync_collective(group):
    """One anti-entropy round over a whole mesh-co-located replica
    group as a SINGLE device dispatch — the in-process twin of the
    gossip fast lane's collective round, for benches and tests that
    want the group shape without a `GossipNode`.

    Where :func:`sync_packed` converges one replica *pair* per call
    (N replicas need O(N²) rounds through a connected topology), one
    ``sync_collective(group)`` call lands every member of the
    `crdt_tpu.collective.CollectiveGroup` on the joined state at once:
    zero bytes to any wire, zero pack-path copies, pack and digest
    caches pre-seeded (docs/COLLECTIVE.md). Returns the group's
    `CollectiveJoinReport`."""
    # `CollectiveGroup.join` carries its own "collective_join" span
    # (kind="sync", round id, member count) — the trace shape this
    # module's pairwise rounds set, one level up.
    return group.join()


class MerkleSyncReport:
    """What one in-process anti-entropy round cost
    (:func:`sync_merkle`) — the accounting the socket path keeps in
    metrics/WireTally, exposed as a plain object so topology benches
    (bench.py --mode antientropy) can sum traffic without sockets.
    ``digest_bytes`` models the walk's wire cost (8 bytes per digest
    value, both directions); ``payload_bytes`` is the packed arenas'
    exact size. An empty ``ranges`` means the trees matched and no
    payload moved."""

    __slots__ = ("watermark", "rounds", "digests", "ranges",
                 "pushed_rows", "pulled_rows", "payload_bytes")

    def __init__(self, watermark, rounds, digests, ranges,
                 pushed_rows, pulled_rows, payload_bytes):
        self.watermark = watermark
        self.rounds = rounds
        self.digests = digests
        self.ranges = ranges
        self.pushed_rows = pushed_rows
        self.pulled_rows = pulled_rows
        self.payload_bytes = payload_bytes

    @property
    def digest_bytes(self) -> int:
        return 16 * self.digests   # 8B value out + 8B value back

    @property
    def total_bytes(self) -> int:
        return self.digest_bytes + self.payload_bytes


def _packed_nbytes(packed) -> int:
    total = 0
    for lane in packed:
        nbytes = getattr(lane, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def sync_merkle(local, remote) -> MerkleSyncReport:
    """In-process twin of `net.sync_merkle_over_conn`
    (docs/ANTIENTROPY.md): compare digest trees, walk only differing
    subtrees (one `walk_divergent_leaves` level per simulated round
    trip), then exchange JUST the divergent leaf ranges through
    ``pack_since(ranges=...)`` both ways. Matching roots cost one
    probe and zero payload. Raises ValueError on tree geometry
    mismatch — the socket path's ``merkle_rejected``, where a full
    packed round is the right fallback."""
    from .ops.digest import coalesce_leaf_ranges, walk_divergent_leaves
    from .obs.trace import round_id, span, tracer
    drain = getattr(local, "drain_ingest", None)
    if drain is not None:
        drain()
    watermark = local.canonical_time
    node = str(getattr(local, "node_id", ""))
    rid = {"rid": round_id(node)} if tracer().enabled else {}
    with span("sync_merkle", kind="sync", node=node,
              hlc=lambda: local.canonical_time,
              peer=str(getattr(remote, "node_id", "")), **rid):
        tree = local.digest_tree()
        remote_tree = remote.digest_tree()
        if not tree.same_geometry(remote_tree.n_slots,
                                  remote_tree.leaf_width,
                                  remote_tree.depth):
            raise ValueError(
                f"merkle geometry mismatch: local ({tree.n_slots}, "
                f"{tree.leaf_width}) vs remote ({remote_tree.n_slots}, "
                f"{remote_tree.leaf_width})")
        leaves, rounds, fetched = walk_divergent_leaves(
            tree, remote_tree.values)
        if not leaves:
            return MerkleSyncReport(watermark, rounds, fetched, (),
                                    0, 0, 0)
        ranges = coalesce_leaf_ranges(leaves, tree.leaf_width,
                                      tree.n_slots)
        from .net import _pack_for_peer
        sem_ok = (hasattr(local, "set_semantics")
                  and hasattr(remote, "set_semantics"))
        packed, ids = _pack_for_peer(local, None, sem_ok,
                                     ranges=ranges)
        payload = _packed_nbytes(packed) if packed.k else 0
        if packed.k:
            remote.merge_packed(packed, ids)
        pulled, pulled_ids = _pack_for_peer(remote, None, sem_ok,
                                            ranges=ranges)
        payload += _packed_nbytes(pulled) if pulled.k else 0
        if pulled.k:
            if hasattr(local, "merge_and_repack"):
                local.merge_and_repack(
                    pulled, pulled_ids, since=watermark,
                    sem_mode="include" if sem_ok else "auto")
            else:
                local.merge_packed(pulled, pulled_ids)
    return MerkleSyncReport(watermark, rounds, fetched, ranges,
                            int(packed.k), int(pulled.k), payload)

"""Pallas TPU kernel for the ingest commit scatter — touched tiles only.

The write combiner's flush commits a deduped slot batch with ONE
stamp owner (the local node): no lattice compare, no guards — every
valid row overwrites its slot (`ops.dense.ingest_scatter` semantics).
XLA lowers that k-index scatter as a serialized per-index loop on TPU;
here the batch is regrouped host-side onto the TILE blocks it touches
and each touched block is rewritten in one VMEM pass — a masked
select over ``(_SB, _LANE)`` vregs, not k sequential row updates. The
grid walks ONLY touched tiles (scalar-prefetched block ids), so a
64-row flush against a 16M-slot store moves a handful of tiles, not
the store.

Lanes ride split (hi int32, lo uint32) exactly like the merge kernel
(`pallas_merge.SplitStore`): no int64 emulation, occupancy encoded as
``hi != NEG_HI`` — writing a real logicalTime marks the slot occupied
with no separate lane.

The tile-id pad (to a power of two of DISTINCT grid sizes) uses
UNTOUCHED tile ids, never duplicates: the pipelined grid may prefetch
a revisited tile's input block before the first visit's write-back
lands, so a duplicated id could commit stale lanes. Padded tiles carry
an all-zero valid mask and write themselves back unchanged.

The lax fallback (`ops.dense.ingest_scatter`) stays the CPU/GPU path;
`models.dense_crdt.DenseCrdt._commit_scatter` picks per platform.
Buffer ownership and donation rules: docs/FASTPATH.md.
"""

from __future__ import annotations

import functools as _ft
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .dense import DenseStore
from .pallas_merge import (_LANE, _SB, TILE, SplitStore, _join64, _split64,
                           join_store, split_store)
from ..obs import device as _obs_device

_obs_device.register("pallas.ingest_scatter_tiles")


def prepare_tile_updates(slots: np.ndarray, lt: np.ndarray,
                         val: np.ndarray, tomb: np.ndarray,
                         n_slots: int) -> Tuple[np.ndarray, np.ndarray,
                                                np.ndarray, np.ndarray,
                                                np.ndarray]:
    """Host prep: regroup a deduped slot batch onto the TILE blocks it
    touches. Returns ``(tile_ids, valid, lt, val, tomb)`` — dense
    ``(n_tiles_padded * _SB, _LANE)`` lanes holding each row at its
    within-tile position, plus the int32 block ids the grid visits.
    Duplicate slots are the CALLER's bug (last-wins dedup happens in
    the combiner); a duplicate here would leave one write undefined."""
    n_tiles = n_slots // TILE
    tile_of = slots // TILE
    touched = np.unique(tile_of)
    t = len(touched)
    padded_t = min(1 << max(t - 1, 1).bit_length(), n_tiles)
    if padded_t > t:
        # Pad with DISTINCT untouched tiles (all-invalid → written back
        # unchanged); see the module docstring for why a duplicated id
        # is unsafe under the pipelined grid.
        spare = np.setdiff1d(np.arange(n_tiles, dtype=np.int64),
                             touched)[:padded_t - t]
        tile_ids = np.concatenate([touched, spare]).astype(np.int32)
    else:
        tile_ids = touched.astype(np.int32)
    pos = np.searchsorted(touched, tile_of)
    within = slots - tile_of * TILE
    r = within // _LANE
    c = within % _LANE
    valid = np.zeros((padded_t, _SB, _LANE), np.int32)
    lt_d = np.zeros((padded_t, _SB, _LANE), np.int64)
    val_d = np.zeros((padded_t, _SB, _LANE), np.int64)
    tomb_d = np.zeros((padded_t, _SB, _LANE), np.int32)
    valid[pos, r, c] = 1
    lt_d[pos, r, c] = lt
    val_d[pos, r, c] = val
    tomb_d[pos, r, c] = tomb
    flat = lambda a: a.reshape(padded_t * _SB, _LANE)
    return tile_ids, flat(valid), flat(lt_d), flat(val_d), flat(tomb_d)


def _ingest_kernel(ids_ref, me_ref, *refs):
    """One touched tile: masked overwrite of all nine store lanes.
    ``ids_ref``/``me_ref`` are the scalar-prefetch operands (block ids
    drive the index maps; ``me`` stamps node/mod_node)."""
    (s_hi, s_lo, s_node, s_vhi, s_vlo, s_tomb, s_mhi, s_mlo, s_mnode,
     v_ref, lhi_ref, llo_ref, vhi_ref, vlo_ref, tb_ref,
     o_hi, o_lo, o_node, o_vhi, o_vlo, o_tomb,
     o_mhi, o_mlo, o_mnode) = refs
    v = v_ref[...] != 0
    me = me_ref[0]
    lhi = lhi_ref[...]
    llo = llo_ref[...]
    o_hi[...] = jnp.where(v, lhi, s_hi[...])
    o_lo[...] = jnp.where(v, llo, s_lo[...])
    o_node[...] = jnp.where(v, me, s_node[...])
    o_vhi[...] = jnp.where(v, vhi_ref[...], s_vhi[...])
    o_vlo[...] = jnp.where(v, vlo_ref[...], s_vlo[...])
    o_tomb[...] = jnp.where(v, tb_ref[...], s_tomb[...])
    o_mhi[...] = jnp.where(v, lhi, s_mhi[...])
    o_mlo[...] = jnp.where(v, llo, s_mlo[...])
    o_mnode[...] = jnp.where(v, me, s_mnode[...])


def _scatter_step(store: DenseStore, tile_ids, valid, lt_d, val_d,
                  tomb_d, me, *, interpret: bool):
    n = store.lt.shape[0]
    rows = n // _LANE
    s = split_store.__wrapped__(store)
    st = [ln.reshape(rows, _LANE) for ln in s]
    lhi, llo = _split64(lt_d)
    vhi, vlo = _split64(val_d)
    padded_t = valid.shape[0] // _SB
    # Index maps see (grid index, *scalar prefetch operands): store
    # blocks follow the prefetched tile ids, update blocks walk 0..t.
    st_spec = pl.BlockSpec((_SB, _LANE), lambda i, ids, me: (ids[i], 0),
                           memory_space=pltpu.VMEM)
    up_spec = pl.BlockSpec((_SB, _LANE), lambda i, ids, me: (i, 0),
                           memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2, grid=(padded_t,),
        in_specs=[st_spec] * 9 + [up_spec] * 6,
        out_specs=[st_spec] * 9)
    # Alias numbering counts the scalar-prefetch operands: store lane
    # j is pallas_call input 2 + j.
    outs = pl.pallas_call(
        _ingest_kernel,
        out_shape=[jax.ShapeDtypeStruct((rows, _LANE), ln.dtype)
                   for ln in st],
        grid_spec=grid_spec,
        input_output_aliases={2 + j: j for j in range(9)},
        interpret=interpret,
    )(tile_ids, me, *st, valid.astype(jnp.int32), lhi, llo, vhi, vlo,
      tomb_d.astype(jnp.int32))
    return join_store.__wrapped__(
        SplitStore(*(o.reshape(n) for o in outs)))


@_ft.lru_cache(maxsize=None)
def _scatter_jit(donate: bool, interpret: bool):
    step = _ft.partial(_scatter_step, interpret=interpret)
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def ingest_scatter_tiles(store: DenseStore, slots: np.ndarray,
                         lt: np.ndarray, val: np.ndarray,
                         tomb: np.ndarray, me: int, *,
                         donate: bool = False,
                         interpret: bool = False) -> DenseStore:
    """Commit a deduped ingest batch through the touched-tile kernel.
    Bit-identical to `ops.dense.ingest_scatter` over in-range slots
    (host prep drops nothing — callers bound slots beforehand)."""
    tile_ids, valid, lt_d, val_d, tomb_d = prepare_tile_updates(
        np.asarray(slots, np.int64), np.asarray(lt, np.int64),
        np.asarray(val, np.int64), np.asarray(tomb), store.lt.shape[0])
    with _obs_device.record("pallas.ingest_scatter_tiles",
                            dim=int(tile_ids.shape[0]),
                            donated=store.lt if donate else None):
        return _scatter_jit(donate, interpret)(
            store, jnp.asarray(tile_ids), jnp.asarray(valid),
            jnp.asarray(lt_d), jnp.asarray(val_d), jnp.asarray(tomb_d),
            jnp.full((1,), me, jnp.int32))

"""Planted blocking-under-lock fixture for the concurrency analyzer.

Expected findings, exactly two ``blocking-under-lock``:

- ``push()`` — a socket ``sendall`` directly inside the contract
  lock's critical section: one slow peer wedges every thread that
  wants the lock.
- ``push_with_retry()`` — a ``time.sleep`` reached through the
  ``_backoff`` helper while the lock is held (the interprocedural
  case: the sleep is lexically nowhere near a ``with`` block).

The socket carries a timeout so the fixture stays clean under
host_lint's ``socket-no-timeout`` — the planted bugs are exclusively
the concurrency analyzer's to catch.
"""

import socket
import threading
import time


class Shipper:
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    def __init__(self, addr):
        self._lock = threading.Lock()
        self.sock = socket.create_connection(addr, timeout=1.0)
        self.backlog = []

    def push(self, payload):
        # PLANTED: socket I/O inside the critical section
        with self._lock:
            self.backlog.append(payload)
            self.sock.sendall(payload)

    def _backoff(self, attempt):
        time.sleep(0.05 * (attempt + 1))

    def _try_stage(self, payload):
        self.backlog.append(payload)
        return len(self.backlog) < 64

    def push_with_retry(self, payload, attempts=3):
        # PLANTED: the sleep is reached through a helper while the
        # lock is held
        with self._lock:
            for attempt in range(attempts):
                if self._try_stage(payload):
                    return True
                self._backoff(attempt)
        return False

"""MapCrdt + merge integration tests — port of `test/map_crdt_test.dart`.

Covers: the conformance suite instantiation, seeded construction, the
10 merge scenarios, golden-string serialization both directions, delta
subset boundary semantics, and 3-replica delta-sync convergence (with an
injected deterministic clock instead of real sleeps).
"""

from datetime import datetime

from crdt_tpu import Crdt, CrdtJson, Hlc, MapCrdt, Record

from conformance import CrdtConformance, FakeClock

MILLIS = 1000000000000
ISO_TIME = "2001-09-09T01:46:40.000Z"


class TestMapCrdtConformance(CrdtConformance):
    def make_crdt(self):
        return MapCrdt("abc", wall_clock=FakeClock())


def _now():
    return Hlc.now("abc")


class TestSeed:
    def test_seed_item(self):
        hlc_now = _now()
        crdt = MapCrdt("abc", {"x": Record(hlc_now, 1, hlc_now)})
        assert crdt.get("x") == 1

    def test_seed_and_put(self):
        hlc_now = _now()
        crdt = MapCrdt("abc", {"x": Record(hlc_now, 1, hlc_now)})
        crdt.put("x", 2)
        assert crdt.get("x") == 2


class TestMerge:
    def setup_method(self):
        self.clock = FakeClock()
        self.crdt = MapCrdt("abc", wall_clock=self.clock)
        self.hlc_now = Hlc.now("abc", millis=self.clock.millis)

    def test_merge_older(self):
        self.crdt.put("x", 2)
        self.crdt.merge(
            {"x": Record(Hlc(MILLIS - 1, 0, "xyz"), 1, self.hlc_now)})
        assert self.crdt.get("x") == 2

    def test_merge_very_old(self):
        self.crdt.put("x", 2)
        self.crdt.merge({"x": Record(Hlc(0, 0, "xyz"), 1, self.hlc_now)})
        assert self.crdt.get("x") == 2

    def test_merge_newer(self):
        self.crdt.put("x", 1)
        self.clock.advance(10)
        newer = Hlc.now("xyz", millis=self.clock.millis + 1)
        self.crdt.merge({"x": Record(newer, 2, self.hlc_now)})
        assert self.crdt.get("x") == 2

    def test_disambiguate_using_node_id(self):
        self.crdt.merge(
            {"x": Record(Hlc(MILLIS, 0, "nodeA"), 1, self.hlc_now)})
        self.crdt.merge(
            {"x": Record(Hlc(MILLIS, 0, "nodeB"), 2, self.hlc_now)})
        assert self.crdt.get("x") == 2

    def test_merge_same(self):
        self.crdt.put("x", 2)
        remote_ts = self.crdt.get_record("x").hlc
        self.crdt.merge({"x": Record(remote_ts, 1, self.hlc_now)})
        assert self.crdt.get("x") == 2

    def test_merge_older_newer_counter(self):
        self.crdt.put("x", 2)
        self.crdt.merge(
            {"x": Record(Hlc(MILLIS - 1, 2, "xyz"), 1, self.hlc_now)})
        assert self.crdt.get("x") == 2

    def test_merge_same_millis_newer_counter(self):
        self.crdt.put("x", 1)
        remote_ts = Hlc(self.crdt.get_record("x").hlc.millis, 2, "xyz")
        self.crdt.merge({"x": Record(remote_ts, 2, self.hlc_now)})
        assert self.crdt.get("x") == 2

    def test_merge_new_item(self):
        records = {"x": Record(Hlc.now("xyz", millis=self.clock.millis),
                               2, self.hlc_now)}
        self.crdt.merge(dict(records))
        assert self.crdt.record_map() == records

    def test_merge_deleted_item(self):
        self.crdt.put("x", 1)
        self.clock.advance(10)
        newer = Hlc.now("xyz", millis=self.clock.millis + 1)
        self.crdt.merge({"x": Record(newer, None, self.hlc_now)})
        assert self.crdt.is_deleted("x") is True

    def test_update_hlc_on_merge(self):
        self.crdt.put("x", 1)
        self.crdt.merge(
            {"y": Record(Hlc(MILLIS - 1, 0, "xyz"), 2, self.hlc_now)})
        assert self.crdt.values == [1, 2]

    def test_canonical_absorbs_remote_clock(self):
        # Clock absorption runs for winners AND losers (crdt.dart:82);
        # the canonical time ends >= every remote hlc seen.
        remote_hlc = Hlc(self.clock.millis + 50_000, 7, "xyz")
        self.crdt.merge({"x": Record(remote_hlc, 1, self.hlc_now)})
        assert self.crdt.canonical_time.logical_time > \
            remote_hlc.logical_time  # final send bump (crdt.dart:93)
        assert self.crdt.canonical_time.node_id == "abc"


class TestSerialization:
    hlc_now = Hlc.now("abc")

    def test_to_map(self):
        crdt = MapCrdt("abc",
                       {"x": Record(Hlc(MILLIS, 0, "abc"), 1, self.hlc_now)})
        assert crdt.record_map() == {
            "x": Record(Hlc(MILLIS, 0, "abc"), 1, self.hlc_now)}

    def test_json_encode_string_key(self):
        crdt = MapCrdt("abc",
                       {"x": Record(Hlc(MILLIS, 0, "abc"), 1, self.hlc_now)})
        assert crdt.to_json() == \
            '{"x":{"hlc":"%s-0000-abc","value":1}}' % ISO_TIME

    def test_json_encode_int_key(self):
        crdt = MapCrdt("abc",
                       {1: Record(Hlc(MILLIS, 0, "abc"), 1, self.hlc_now)})
        assert crdt.to_json() == \
            '{"1":{"hlc":"%s-0000-abc","value":1}}' % ISO_TIME

    def test_json_encode_datetime_key(self):
        crdt = MapCrdt("abc", {
            datetime(2000, 1, 1, 1, 20):
                Record(Hlc(MILLIS, 0, "abc"), 1, self.hlc_now)})
        assert crdt.to_json() == (
            '{"2000-01-01 01:20:00.000":'
            '{"hlc":"%s-0000-abc","value":1}}' % ISO_TIME)

    def test_json_encode_custom_class_value(self):
        crdt = MapCrdt("abc", {
            "x": Record(Hlc(MILLIS, 0, "abc"), TestClass("test"),
                        self.hlc_now)})
        assert crdt.to_json() == (
            '{"x":{"hlc":"%s-0000-abc","value":{"test":"test"}}}' % ISO_TIME)

    def test_json_encode_custom_node_id(self):
        crdt = MapCrdt("abc",
                       {"x": Record(Hlc(MILLIS, 0, 1), 0, self.hlc_now)})
        assert crdt.to_json() == \
            '{"x":{"hlc":"%s-0000-1","value":0}}' % ISO_TIME

    def test_json_decode_string_key(self):
        crdt = MapCrdt("abc")
        records = CrdtJson.decode(
            '{"x":{"hlc":"%s-0000-abc","value":1}}' % ISO_TIME, self.hlc_now)
        crdt.put_records(records)
        assert crdt.record_map() == {
            "x": Record(Hlc(MILLIS, 0, "abc"), 1, self.hlc_now)}

    def test_json_decode_int_key(self):
        crdt = MapCrdt("abc")
        records = CrdtJson.decode(
            '{"1":{"hlc":"%s-0000-abc","value":1}}' % ISO_TIME, self.hlc_now,
            key_decoder=int)
        crdt.put_records(records)
        assert crdt.record_map() == {
            1: Record(Hlc(MILLIS, 0, "abc"), 1, self.hlc_now)}

    def test_json_decode_datetime_key(self):
        crdt = MapCrdt("abc")
        records = CrdtJson.decode(
            '{"2000-01-01 01:20:00.000":{"hlc":"%s-0000-abc","value":1}}'
            % ISO_TIME, self.hlc_now,
            key_decoder=lambda k: datetime.fromisoformat(k.replace(" ", "T")))
        crdt.put_records(records)
        assert crdt.record_map() == {
            datetime(2000, 1, 1, 1, 20):
                Record(Hlc(MILLIS, 0, "abc"), 1, self.hlc_now)}

    def test_json_decode_custom_class_value(self):
        crdt = MapCrdt("abc")
        records = CrdtJson.decode(
            '{"x":{"hlc":"%s-0000-abc","value":{"test":"test"}}}' % ISO_TIME,
            self.hlc_now,
            value_decoder=lambda key, value: TestClass.from_json(value))
        crdt.put_records(records)
        assert crdt.record_map() == {
            "x": Record(Hlc(MILLIS, 0, "abc"), TestClass("test"),
                        self.hlc_now)}

    def test_json_decode_custom_node_id(self):
        crdt = MapCrdt("abc")
        records = CrdtJson.decode(
            '{"x":{"hlc":"%s-0000-1","value":0}}' % ISO_TIME, self.hlc_now,
            node_id_decoder=int)
        crdt.put_records(records)
        assert crdt.record_map() == {
            "x": Record(Hlc(MILLIS, 0, 1), 0, self.hlc_now)}


class TestDeltaSubsets:
    hlc1 = Hlc(MILLIS, 0, "abc")
    hlc2 = Hlc(MILLIS + 1, 0, "abc")
    hlc3 = Hlc(MILLIS + 2, 0, "abc")

    def make(self):
        return MapCrdt("abc", {
            "x": Record(self.hlc1, 1, self.hlc1),
            "y": Record(self.hlc2, 2, self.hlc2),
        })

    def test_null_modified_since(self):
        assert len(self.make().record_map()) == 2

    def test_modified_since_hlc1(self):
        assert len(self.make().record_map(modified_since=self.hlc1)) == 2

    def test_modified_since_hlc2(self):
        assert len(self.make().record_map(modified_since=self.hlc2)) == 1

    def test_modified_since_hlc3(self):
        assert len(self.make().record_map(modified_since=self.hlc3)) == 0


def _sync(local: Crdt, remote: Crdt):
    """The reference's anti-entropy round (map_crdt_test.dart:273-279):
    full-state push then delta pull keyed on pre-push canonical time."""
    time = local.canonical_time
    remote.merge(local.record_map())
    local.merge(remote.record_map(modified_since=time))


class TestDeltaSync:
    def setup_method(self):
        clock = FakeClock()
        self.crdt_a = MapCrdt("a", wall_clock=clock)
        self.crdt_b = MapCrdt("b", wall_clock=clock)
        self.crdt_c = MapCrdt("c", wall_clock=clock)

        self.crdt_a.put("x", 1)
        clock.advance(100)
        self.crdt_b.put("x", 2)

    def test_merge_in_order(self):
        _sync(self.crdt_a, self.crdt_c)
        _sync(self.crdt_b, self.crdt_c)

        assert self.crdt_a.get("x") == 1  # A still has the old value
        assert self.crdt_b.get("x") == 2
        assert self.crdt_c.get("x") == 2

    def test_merge_in_reverse_order(self):
        _sync(self.crdt_b, self.crdt_c)
        _sync(self.crdt_a, self.crdt_c)
        _sync(self.crdt_b, self.crdt_c)

        assert self.crdt_a.get("x") == 2
        assert self.crdt_b.get("x") == 2
        assert self.crdt_c.get("x") == 2


class TestClass:
    __test__ = False  # custom value class, not a pytest suite

    def __init__(self, test: str):
        self.test = test

    @staticmethod
    def from_json(obj):
        return TestClass(obj["test"])

    def to_json(self):
        return {"test": self.test}

    def __eq__(self, other):
        return isinstance(other, TestClass) and self.test == other.test

    def __hash__(self):
        return hash(self.test)

    def __repr__(self):
        return self.test

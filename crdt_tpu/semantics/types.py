"""The per-lane semantics registry and its five shipped entries.

Each :class:`SemanticsSpec` bundles everything one lane type needs to
exist end to end — the contract `docs/TYPES.md` documents:

- ``tag``: the wire tag (the packed frame's optional ``sem`` lane and
  the store's per-slot semantics column hold these). LWW is tag 0 so
  an untyped store is all-zeros and the wire can omit the lane.
- a value **codec** (``encode``/``decode``) between user values and
  the int64 lane form `crdt_tpu.semantics.kernels` joins.
- a **law spec**: ``law_target()`` builds a seeded-search
  `analysis.lattice_laws.LawTarget` over the typed wire join with
  this tag, including a type-canonical value generator (event
  uniqueness: the value is a deterministic function of ``(lt, node)``)
  and a delta-combine for the associativity law.
- an **audit spec**: ``audit_target()`` builds an
  `analysis.jaxpr_audit.AuditTarget` tracing the typed join at this
  tag for scatter-order/float-reduce/RNG hazards.

`analysis` consumes the registry wholesale (`law_targets()` /
`audit_targets()` in the package ``__init__``), so registering a type
IS what puts it under CI: a spec whose ``law_target`` or
``audit_target`` is None fails ``python -m crdt_tpu.analysis``
(rule ``semantics-missing-law-target`` / ``-audit-target``) rather
than silently shipping an unverified kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .kernels import (MVREG_K, MVREG_MAX, ORSET_MAX_LEN,
                      ORSET_UNIVERSE, SEM_GCOUNTER, SEM_LWW,
                      SEM_MVREG, SEM_ORSET, SEM_PNCOUNTER, _PN_HALF)

_LAW_N = 64   # store width for registry-generated law targets


@dataclass(frozen=True)
class SemanticsSpec:
    """One registered lane semantics. ``law_val(lt, node)`` maps HLC
    stamps to type-canonical lane values (vectorized numpy) for the
    seeded law search; Optional law/audit factories exist so the CI
    completeness gate has something concrete to flag."""

    name: str
    tag: int
    doc: str
    encode: Callable[[object], int]
    decode: Callable[[int], object]
    law_val: Callable[[object, object], object]
    law_target: Optional[Callable[[], object]] = None
    audit_target: Optional[Callable[[], object]] = None


_REGISTRY: Dict[str, SemanticsSpec] = {}
_BY_TAG: Dict[int, SemanticsSpec] = {}


def register(spec: SemanticsSpec) -> SemanticsSpec:
    """Add a semantics to the registry. Names and tags are unique;
    re-registering either is a programming error, not a merge."""
    if spec.name in _REGISTRY:
        raise ValueError(f"semantics {spec.name!r} already registered")
    if spec.tag in _BY_TAG:
        raise ValueError(
            f"semantics tag {spec.tag} already registered "
            f"({_BY_TAG[spec.tag].name!r})")
    if not 0 <= spec.tag <= 127:
        raise ValueError(f"semantics tag must fit int8/uint8 wire "
                         f"lanes; got {spec.tag}")
    _REGISTRY[spec.name] = spec
    _BY_TAG[spec.tag] = spec
    return spec


def get_semantics(name: str) -> SemanticsSpec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown semantics {name!r}; registered: "
                       f"{sorted(_REGISTRY)}") from None


def by_tag(tag: int) -> SemanticsSpec:
    try:
        return _BY_TAG[tag]
    except KeyError:
        raise KeyError(f"unknown semantics tag {tag}; registered: "
                       f"{sorted(_BY_TAG)}") from None


def all_semantics() -> List[SemanticsSpec]:
    return sorted(_REGISTRY.values(), key=lambda s: s.tag)


def names() -> List[str]:
    return [s.name for s in all_semantics()]


# --- registry-driven law / audit target generation ---


def _typed_law_target(spec: SemanticsSpec):
    """Seeded-law target over `typed_wire_join_step` with a constant
    ``sem`` lane at this spec's tag. The generator derives lane values
    through ``spec.law_val`` — type-canonical AND deterministic from
    (lt, node), the event-uniqueness invariant the law harness
    requires. ``combine`` is the typed join applied delta-vs-delta
    (`kernels.combine_wire_deltas`), so non-associativity cannot hide
    behind a hand-written merge."""
    import numpy as np
    from ..analysis.lattice_laws import (LawTarget, _LOCAL_NODE,
                                         _WALL)
    from ..ops.dense import empty_dense_store
    from . import kernels

    sem = np.full(_LAW_N, spec.tag, np.int8)

    def gen(rng):
        millis = rng.integers(1, 1 << 20, size=_LAW_N)
        counter = rng.integers(0, 4, size=_LAW_N)
        lt = ((millis << 16) | counter).astype(np.int64)
        node = rng.integers(1, 5, size=_LAW_N).astype(np.int32)
        val = np.asarray(spec.law_val(lt, node), np.int64)
        tomb = ((lt ^ node) & 1).astype(bool)
        valid = rng.integers(0, 2, size=_LAW_N).astype(bool)
        return {"lt": np.where(valid, lt, 0),
                "node": np.where(valid, node, 0).astype(np.int32),
                "val": np.where(valid, val, 0),
                "tomb": valid & tomb, "valid": valid}

    def apply(store, batch):
        new_store, _win = kernels.typed_wire_join_step(
            store, sem, batch["lt"], batch["node"], batch["val"],
            batch["tomb"], batch["valid"],
            np.int64(_WALL << 16), np.int32(_LOCAL_NODE))
        return new_store

    def extract(store):
        return {k: np.asarray(getattr(store, k))
                for k in ("lt", "node", "val", "occupied", "tomb")}

    return LawTarget(
        name=f"semantics.{spec.name}.typed_wire_join",
        fresh=lambda: empty_dense_store(_LAW_N),
        gen=gen, apply=apply, extract=extract,
        combine=lambda a, b: kernels.combine_wire_deltas(sem, a, b),
        notes=f"registry-generated for tag {spec.tag}; all three laws")


def _typed_audit_target(spec: SemanticsSpec):
    """Jaxpr audit target over the typed wire join at this tag —
    elementwise, so a scatter or float reduce appearing here is a
    regression by definition."""
    import jax
    import numpy as np
    from ..analysis.jaxpr_audit import AuditTarget
    from ..ops.dense import DenseStore
    from . import kernels

    def build():
        n = _LAW_N
        store = DenseStore(
            lt=np.zeros(n, np.int64), node=np.zeros(n, np.int32),
            val=np.zeros(n, np.int64), mod_lt=np.zeros(n, np.int64),
            mod_node=np.zeros(n, np.int32),
            occupied=np.zeros(n, bool), tomb=np.zeros(n, bool))
        return jax.make_jaxpr(kernels.typed_wire_join_step)(
            store, np.full(n, spec.tag, np.int8),
            np.zeros(n, np.int64), np.zeros(n, np.int32),
            np.zeros(n, np.int64), np.zeros(n, bool),
            np.zeros(n, bool), np.int64(0), np.int32(0))

    return AuditTarget(
        name=f"semantics.{spec.name}.typed_wire_join",
        notes=f"registry-generated; elementwise typed join at "
              f"tag {spec.tag}",
        build=build)


def _spec(name: str, tag: int, doc: str, encode, decode,
          law_val) -> SemanticsSpec:
    # The factories close over the spec being built (late binding):
    # they only run when analysis asks for targets, well after
    # registration completes.
    spec = SemanticsSpec(
        name=name, tag=tag, doc=doc, encode=encode, decode=decode,
        law_val=law_val,
        law_target=lambda: _typed_law_target(spec),
        audit_target=lambda: _typed_audit_target(spec))
    return register(spec)


# --- codecs ---


def _lww_encode(v) -> int:
    return int(v)


def _gc_encode(v) -> int:
    v = int(v)
    if v < 0:
        raise ValueError(f"gcounter values are non-negative; got {v}")
    return v


def _pn_encode(v) -> int:
    """Absolute user value -> lane form: positive counts into the pos
    half, negative into the neg half."""
    v = int(v)
    mag = abs(v)
    if mag > _PN_HALF:
        raise ValueError(f"pncounter magnitude exceeds 31 bits: {v}")
    return (mag << 32) if v >= 0 else mag


def _pn_decode(lane: int) -> int:
    return ((int(lane) >> 32) & _PN_HALF) - (int(lane) & _PN_HALF)


def _orset_encode(elements) -> int:
    """A set of element indices -> lane with causal length 1 (present)
    for each member."""
    lane = 0
    for e in elements:
        e = int(e)
        if not 0 <= e < ORSET_UNIVERSE:
            raise ValueError(
                f"orset element out of universe "
                f"[0, {ORSET_UNIVERSE}): {e}")
        lane |= 1 << (4 * e)
    return lane


def _orset_decode(lane: int) -> frozenset:
    lane = int(lane)
    return frozenset(e for e in range(ORSET_UNIVERSE)
                     if ((lane >> (4 * e)) & 0xF) % 2 == 1)


def _mvreg_encode(v) -> int:
    v = int(v)
    if not 1 <= v <= MVREG_MAX:
        raise ValueError(
            f"mvreg values are 16-bit nonzero (1..{MVREG_MAX}); "
            f"got {v}")
    return v << 48


def _mvreg_decode(lane: int) -> Tuple[int, ...]:
    lane = int(lane)
    vals = [(lane >> s) & MVREG_MAX for s in (48, 32, 16, 0)]
    return tuple(v for v in vals if v)


# --- the five shipped semantics ---

LWW = _spec(
    "lww", SEM_LWW,
    "last-writer-wins register: strict (lt, node) lex compare, the "
    "clock winner takes every lane (the seed semantics; tag 0 so an "
    "untyped store is all-zeros)",
    _lww_encode, _lww_encode,
    law_val=lambda lt, node: (lt * 31 + node * 7) & 0x7FFF)

GCOUNTER = _spec(
    "gcounter", SEM_GCOUNTER,
    "grow-only counter: non-negative int64, join = max; one lane per "
    "(counter, replica) realizes the classic dense G-counter",
    _gc_encode, _lww_encode,
    law_val=lambda lt, node: (lt * 13 + node * 5) & 0xFFFF)

PNCOUNTER = _spec(
    "pncounter", SEM_PNCOUNTER,
    "PN counter: pos half bits 32..62, neg half bits 0..30, join = "
    "per-half max, user value = pos - neg",
    _pn_encode, _pn_decode,
    law_val=lambda lt, node: (((lt * 11 + node * 3) & 0x3FFF) << 32)
                             | ((lt * 17 + node * 7) & 0x3FFF))

ORSET = _spec(
    "orset", SEM_ORSET,
    "observed-remove set via causal lengths: 16 elements x 4-bit "
    "length, join = per-nibble max, present iff length is odd; "
    "lengths saturate at 15",
    _orset_encode, _orset_decode,
    law_val=lambda lt, node: (lt * 2654435761 + node * 97)
                             & 0x7FFFFFFFFFFFFFFF)

MVREG = _spec(
    "mvreg", SEM_MVREG,
    "multi-value register: top-4 concurrent (equal-lt) 16-bit values "
    "packed descending; strictly newer lt replaces, equal lt unions",
    _mvreg_encode, _mvreg_decode,
    law_val=lambda lt, node: (((lt * 7 + node) & 0xFFFF) | 1) << 48)

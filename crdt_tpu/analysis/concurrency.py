"""Whole-tree lock-order analyzer + runtime deadlock sanitizer.

The control plane runs at least six thread families (serve loop,
flusher, gossip rounds, ReplicaGroup monitors, the autoscaler daemon,
the fleet poller) whose deadlock-freedom used to rest on prose
("promote path group-lock -> control-lock, publish path control-lock
-> install_table lock-free — the cycle that doesn't happen"). This
module turns those invariants into machine-checked contracts.

**Static half** (purely lexical/AST, like host_lint): a class opts in
with a declared acquisition order::

    _CRDTLINT_LOCK_ORDER = ("_control", ("donor.lock", "ServeTier.lock"))

Each entry is either a bare attribute name — ``self.<attr>`` is a lock
this class owns, canonically named ``ClassName.attr`` — or a
``(pattern, key)`` pair: an acquisition site whose dotted expression
suffix-matches ``pattern`` on a dot boundary (``with donor.lock:``)
resolves to the canonical ``key``. Tuple position IS the permitted
acquisition order: an earlier entry may be held while acquiring a
later one, never the reverse.

The analyzer extracts every ``with <lock>:`` / ``<lock>.acquire()``
site, follows self-method and same-module function calls
interprocedurally (so a nested acquisition through a helper —
``split_hot`` -> ``_split_locked`` -> ``_ship_ranges`` -> ``with
donor.lock:`` — becomes a graph edge), and checks the observed
acquisition graph against the union of every declared contract:

- ``lock-order-cycle`` — the combined declared+observed graph contains
  a cycle; the finding carries the full witness path.
- ``lock-order-undeclared`` — an observed edge between two contract
  locks with no declared path from holder to acquiree.
- ``blocking-under-lock`` — a blocking call (``time.sleep``, socket
  I/O, a thread join, a subprocess wait) reachable while ANY declared
  lock is held, or a device dispatch (``pack_since``, the PR 12
  ledger's jit entry points) reachable while an OUTER lock is held.
  A lock is *outer* when some contract orders it before another lock;
  a *leaf* lock (last in every contract that names it — the store
  lock) legitimately guards device work, which is why the gossip fast
  lane and the serve commit path need no suppressions while a sleep
  under the federation ``_control`` hold is flagged (the exact wedge
  class PR 16 fixed by hand in ``_dial_upstream``).

Findings accept the standard ``# crdtlint: disable=rule -- reason``
suppressions at the witness line.

**Runtime half**: :func:`make_lock` is the creation seam. With
``CRDT_TPU_SANITIZE`` unset it returns a plain ``threading.Lock`` /
``RLock`` — zero overhead, byte-identical behavior. With the env var
set at creation time it returns an :class:`OrderedLock` that keeps a
per-thread held-set and asserts every acquisition against the declared
rank order: a violation increments
``crdt_tpu_lock_order_violations_total{held,acquiring}`` and emits a
``lock_order_violation`` trace event naming both locks and the holder
thread — then proceeds normally, so the sanitizer can never introduce
a hang the unsanitized build doesn't have.
"""

from __future__ import annotations

import ast
import os
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding, parse_suppressions

RULES = (
    "lock-order-cycle",
    "lock-order-undeclared",
    "blocking-under-lock",
)

# How deep the interprocedural walk follows self-method / same-module
# call chains. The shipped tree needs 3 (split_hot -> _split_locked ->
# _ship_ranges); 6 leaves headroom without risking blowup on cycles
# (visited-set guarded anyway).
_MAX_CALL_DEPTH = 6

# --- blocking-call families ---

_SLEEP_CALLS = {"time.sleep", "_time.sleep"}
_SOCKET_CTORS = {"create_connection", "create_server"}
_SOCKET_METHODS = {"sendall", "recv", "accept", "connect", "makefile"}
_FRAME_HELPERS = {"send_frame", "recv_frame",
                  "send_bytes_frame", "recv_bytes_frame"}
_SUBPROCESS_CALLS = {"subprocess.run", "subprocess.call",
                     "subprocess.check_call", "subprocess.check_output",
                     "subprocess.Popen"}
# ``<thread-ish>.join()`` only: a receiver whose name mentions a
# thread/monitor/worker. ``", ".join`` (str) and ``group.join`` (the
# collective device dispatch) must not match.
_THREADISH_NAMES = ("thread", "monitor", "worker", "flusher", "poller")

# Device dispatches: pack/merge/digest entry points plus the PR 12
# dispatch ledger's jit kernel list (cli._LEDGER_REQUIRED last
# components, inlined so the analyzer stays import-light). Flagged
# only under an OUTER lock — a leaf (store) lock guards device work by
# design.
_DEVICE_DISPATCH = {
    "pack_since", "merge_packed", "merge_and_repack", "drain_ingest",
    "digest_tree", "put_batch", "_pack_for_peer",
    # ledger kernel entry-point last names (obs/device.py census)
    "fanin_step", "fanin_stream", "sparse_fanin_step",
    "wire_join_step", "merge_repack_step", "delta_mask",
    "range_delta_mask", "max_logical_time", "put_scatter",
    "record_scatter", "delete_scatter", "ingest_scatter",
    "digest_tree_device", "ingest_scatter_tiles", "model_fanin_batch",
    "model_fanin_split", "pipelined_model_step",
    "pipelined_model_step_split", "typed_wire_join_step",
    "typed_sparse_join_step", "typed_fanin_step", "sharded_fanin",
    "sharded_pallas_fanin", "sharded_ingest", "sharded_digest",
    "sharded_delta_mask", "sharded_max_logical_time",
    "collective_join",
}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --- contract declarations ---

class _Contract:
    """One class's ``_CRDTLINT_LOCK_ORDER`` declaration."""

    __slots__ = ("cls_name", "path", "line", "order", "self_attrs",
                 "patterns")

    def __init__(self, cls_name: str, path: str, line: int):
        self.cls_name = cls_name
        self.path = path
        self.line = line
        self.order: List[str] = []          # canonical keys, in order
        self.self_attrs: Dict[str, str] = {}  # attr -> canonical key
        self.patterns: List[Tuple[str, str]] = []  # (pattern, key)


def _order_decl(cls: ast.ClassDef, path: str) -> Optional[_Contract]:
    for stmt in cls.body:
        if not (isinstance(stmt, (ast.Assign, ast.AnnAssign))):
            continue
        if isinstance(stmt, ast.Assign):
            if len(stmt.targets) != 1 \
                    or not isinstance(stmt.targets[0], ast.Name) \
                    or stmt.targets[0].id != "_CRDTLINT_LOCK_ORDER":
                continue
            value = stmt.value
        else:
            if not isinstance(stmt.target, ast.Name) \
                    or stmt.target.id != "_CRDTLINT_LOCK_ORDER" \
                    or stmt.value is None:
                continue
            value = stmt.value
        try:
            raw = ast.literal_eval(value)
        except (ValueError, SyntaxError):
            return None
        if not isinstance(raw, (tuple, list)):
            return None
        contract = _Contract(cls.name, path, stmt.lineno)
        for entry in raw:
            if isinstance(entry, str):
                key = f"{cls.name}.{entry}"
                contract.self_attrs[entry] = key
                contract.order.append(key)
            elif isinstance(entry, (tuple, list)) and len(entry) == 2 \
                    and all(isinstance(e, str) for e in entry):
                pattern, key = entry
                contract.patterns.append((pattern, key))
                contract.order.append(key)
        return contract
    return None


class _Model:
    """Whole-tree view: contracts + per-class/module function tables
    needed for the interprocedural walk."""

    def __init__(self):
        self.contracts: List[_Contract] = []
        #: bare attr name -> set of canonical keys that declare it
        self.attr_keys: Dict[str, Set[str]] = {}
        #: (path, cls_name) -> {method name -> FunctionDef}
        self.methods: Dict[Tuple[str, str], Dict[str, ast.AST]] = {}
        #: path -> {module-level function name -> FunctionDef}
        self.functions: Dict[str, Dict[str, ast.AST]] = {}
        #: path -> parsed tree
        self.trees: Dict[str, ast.AST] = {}
        #: path -> Suppressions
        self.suppressions: Dict[str, object] = {}
        #: per-class contract lookup
        self.by_class: Dict[Tuple[str, str], _Contract] = {}

    # -- declared-order graph --

    def declared_edges(self) -> Set[Tuple[str, str]]:
        edges: Set[Tuple[str, str]] = set()
        for c in self.contracts:
            for i, a in enumerate(c.order):
                for b in c.order[i + 1:]:
                    if a != b:
                        edges.add((a, b))
        return edges

    def outer_keys(self) -> Set[str]:
        """Keys some contract orders BEFORE another lock — holding one
        of these across a device dispatch wedges the control plane,
        unlike a leaf (store) lock that guards device work by
        design."""
        return {a for a, _ in self.declared_edges()}

    def resolve(self, dotted: str,
                contract: Optional[_Contract]) -> Optional[str]:
        """Canonical lock key for an acquisition-site expression, or
        None when the expression names no contract lock."""
        if dotted.startswith("self."):
            rest = dotted[len("self."):]
            if contract is not None and rest in contract.self_attrs:
                return contract.self_attrs[rest]
            expr = rest
        else:
            expr = dotted
        if contract is not None:
            for pattern, key in contract.patterns:
                if expr == pattern or expr.endswith("." + pattern):
                    return key
        # Unambiguous foreign reference: exactly one class in the tree
        # declares a bare lock with this attribute name.
        attr = expr.rsplit(".", 1)[-1]
        keys = self.attr_keys.get(attr)
        if keys is not None and len(keys) == 1 and "." in expr:
            return next(iter(keys))
        return None


def _build_model(sources: Sequence[Tuple[str, str]]) -> _Model:
    model = _Model()
    for path, text in sources:
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue  # host_lint reports parse errors
        model.trees[path] = tree
        model.suppressions[path] = parse_suppressions(text)
        model.functions[path] = {
            fn.name: fn for fn in tree.body
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            model.methods[(path, cls.name)] = {
                fn.name: fn for fn in cls.body
                if isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))}
            contract = _order_decl(cls, path)
            if contract is not None:
                model.contracts.append(contract)
                model.by_class[(path, cls.name)] = contract
                for attr, key in contract.self_attrs.items():
                    model.attr_keys.setdefault(attr, set()).add(key)
    return model


# --- the interprocedural walk ---

class _Edge:
    __slots__ = ("src", "dst", "path", "line", "witness")

    def __init__(self, src: str, dst: str, path: str, line: int,
                 witness: List[str]):
        self.src = src
        self.dst = dst
        self.path = path
        self.line = line
        self.witness = list(witness)


class _Blocked:
    __slots__ = ("what", "path", "line", "held", "witness")

    def __init__(self, what: str, path: str, line: int,
                 held: Tuple[str, ...], witness: List[str]):
        self.what = what
        self.path = path
        self.line = line
        self.held = held
        self.witness = list(witness)


def _blocking_what(node: ast.Call, outer_held: bool) -> Optional[str]:
    d = _dotted(node.func)
    last = d.rsplit(".", 1)[-1] if d else None
    if d in _SLEEP_CALLS:
        return f"{d}(...)"
    if d in _SUBPROCESS_CALLS:
        return f"{d}(...)"
    if d == "socket.socket" or (last in _SOCKET_CTORS and d
                                and "." in d):
        return f"{d}(...) [socket]"
    if last in _FRAME_HELPERS:
        return f"{last}(...) [socket frame]"
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        recv = _dotted(node.func.value) or ""
        low = recv.rsplit(".", 1)[-1].lower()
        if attr in _SOCKET_METHODS and recv and not recv.startswith(
                ("np.", "numpy.", "jnp.", "jax.")):
            return f"{recv}.{attr}(...) [socket]"
        if attr == "join" and any(t in low for t in _THREADISH_NAMES):
            return f"{recv}.join() [thread join]"
        if attr in ("wait", "communicate") and "proc" in low:
            return f"{recv}.{attr}() [subprocess wait]"
        if outer_held and attr in _DEVICE_DISPATCH:
            return f"{recv + '.' if recv else ''}{attr}(...) " \
                   "[device dispatch]"
    elif isinstance(node.func, ast.Name):
        if outer_held and node.func.id in _DEVICE_DISPATCH:
            return f"{node.func.id}(...) [device dispatch]"
    return None


class _Walker:
    """Walks one function body with a held-lock set, following
    self-method and same-module calls, recording acquisition edges and
    blocking sites."""

    def __init__(self, model: _Model, outer: Set[str]):
        self.model = model
        self.outer = outer
        self.edges: List[_Edge] = []
        self.blocked: List[_Blocked] = []
        self._seen_edges: Set[Tuple[str, str, str, int]] = set()
        self._seen_blocked: Set[Tuple[str, int, str]] = set()

    def walk_method(self, path: str, cls_name: Optional[str],
                    fn: ast.AST) -> None:
        contract = self.model.by_class.get((path, cls_name)) \
            if cls_name else None
        self._visit_body(list(ast.iter_child_nodes(fn)), path,
                         cls_name, contract, frozenset(), [], set(), 0)

    # -- internals --

    def _record_edge(self, held: frozenset, key: str, path: str,
                     line: int, witness: List[str]) -> None:
        for src in held:
            if src == key:
                continue  # reentrant same-lock hold (RLock contract)
            sig = (src, key, path, line)
            if sig not in self._seen_edges:
                self._seen_edges.add(sig)
                self.edges.append(_Edge(src, key, path, line, witness))

    def _record_block(self, what: str, path: str, line: int,
                      held: frozenset, witness: List[str]) -> None:
        sig = (path, line, what)
        if sig not in self._seen_blocked:
            self._seen_blocked.add(sig)
            self.blocked.append(_Blocked(
                what, path, line, tuple(sorted(held)), witness))

    def _visit_body(self, nodes: List[ast.AST], path: str,
                    cls_name: Optional[str],
                    contract: Optional[_Contract], held: frozenset,
                    witness: List[str], visiting: Set[Tuple[str, str]],
                    depth: int) -> None:
        for node in nodes:
            self._visit(node, path, cls_name, contract, held, witness,
                        visiting, depth)

    def _visit(self, node: ast.AST, path: str,
               cls_name: Optional[str], contract: Optional[_Contract],
               held: frozenset, witness: List[str],
               visiting: Set[Tuple[str, str]], depth: int) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs get their own top-level walk
        if isinstance(node, ast.With):
            acquired: List[str] = []
            for item in node.items:
                d = _dotted(item.context_expr)
                if d is not None:
                    key = self.model.resolve(d, contract)
                    if key is not None:
                        site = f"{path}:{item.context_expr.lineno} " \
                               f"with {d}:"
                        self._record_edge(
                            held, key, path, item.context_expr.lineno,
                            witness + [site])
                        acquired.append(key)
                self._visit(item.context_expr, path, cls_name,
                            contract, held, witness, visiting, depth)
            inner = held | frozenset(acquired)
            w = witness + [f"{path}:{node.lineno} with "
                           + ", ".join(acquired)] if acquired \
                else witness
            self._visit_body(node.body, path, cls_name, contract,
                             inner, w, visiting, depth)
            return
        if isinstance(node, ast.AsyncWith):
            # asyncio locks order the EVENT LOOP, not threads — they
            # are outside the thread-lock contract (the serve loop's
            # _OwnerProxy._lock).
            self._visit_body(node.body, path, cls_name, contract,
                             held, witness, visiting, depth)
            return
        if isinstance(node, ast.Call):
            self._handle_call(node, path, cls_name, contract, held,
                              witness, visiting, depth)
        for child in ast.iter_child_nodes(node):
            self._visit(child, path, cls_name, contract, held,
                        witness, visiting, depth)

    def _handle_call(self, node: ast.Call, path: str,
                     cls_name: Optional[str],
                     contract: Optional[_Contract], held: frozenset,
                     witness: List[str],
                     visiting: Set[Tuple[str, str]],
                     depth: int) -> None:
        d = _dotted(node.func)
        # <lock>.acquire(): held for the remainder of the enclosing
        # scope (conservative — matches the try/finally idiom).
        if d is not None and d.endswith(".acquire"):
            key = self.model.resolve(d[:-len(".acquire")], contract)
            if key is not None:
                site = f"{path}:{node.lineno} {d}()"
                self._record_edge(held, key, path, node.lineno,
                                  witness + [site])
                # NOTE: scope-held tracking for .acquire() is handled
                # by the caller via _acquired_keys (statement lists).
        if held:
            what = _blocking_what(
                node, outer_held=any(k in self.outer for k in held))
            if what is not None:
                self._record_block(what, path, node.lineno, held,
                                   witness)
        # interprocedural: self-method and same-module calls
        if depth >= _MAX_CALL_DEPTH or not held:
            # Follow calls only while a lock is held: edges and
            # blocking sites need a non-empty held-set to matter,
            # and an unconditional walk would be quadratic.
            return
        callee: Optional[ast.AST] = None
        callee_cls = cls_name
        if d is not None and d.startswith("self.") \
                and "." not in d[len("self."):] and cls_name:
            callee = self.model.methods.get(
                (path, cls_name), {}).get(d[len("self."):])
        elif isinstance(node.func, ast.Name):
            callee = self.model.functions.get(path, {}).get(
                node.func.id)
            callee_cls = None
        if callee is None:
            return
        sig = (path, getattr(callee, "name", ""))
        if sig in visiting:
            return
        callee_contract = self.model.by_class.get((path, callee_cls)) \
            if callee_cls else None
        self._visit_body(
            list(ast.iter_child_nodes(callee)), path, callee_cls,
            callee_contract, held,
            witness + [f"{path}:{node.lineno} via {d or '?'}()"],
            visiting | {sig}, depth + 1)


# --- .acquire() scope tracking (statement-ordered pre-pass) ---

def _acquire_rewrite(model: _Model, path: str, tree: ast.AST) -> None:
    """Fold ``<lock>.acquire()`` statements into synthetic With
    blocks: every statement AFTER the acquire in the same body runs
    with the lock held (conservative; a matching ``release()`` ends
    the hold)."""
    class _Rewriter(ast.NodeTransformer):
        def _fold(self, body: List[ast.stmt]) -> List[ast.stmt]:
            for i, stmt in enumerate(body):
                if isinstance(stmt, ast.Expr) \
                        and isinstance(stmt.value, ast.Call):
                    d = _dotted(stmt.value.func)
                    if d is not None and d.endswith(".acquire"):
                        rest = body[i + 1:]
                        # stop the hold at an explicit release()
                        for j, later in enumerate(rest):
                            if isinstance(later, ast.Expr) \
                                    and isinstance(later.value,
                                                   ast.Call):
                                dl = _dotted(later.value.func)
                                if dl is not None and dl.endswith(
                                        ".release") \
                                        and dl[:-len(".release")] == \
                                        d[:-len(".acquire")]:
                                    rest = rest[:j]
                                    break
                        if not rest:
                            continue
                        lock_expr = ast.parse(
                            d[:-len(".acquire")], mode="eval").body
                        ast.copy_location(lock_expr, stmt)
                        for n in ast.walk(lock_expr):
                            ast.copy_location(n, stmt)
                        wrapped = ast.With(
                            items=[ast.withitem(
                                context_expr=lock_expr,
                                optional_vars=None)],
                            body=self._fold(rest), type_comment=None)
                        ast.copy_location(wrapped, stmt)
                        return body[:i + 1] + [wrapped]
            return body

        def visit(self, node):
            node = self.generic_visit(node)
            for field in ("body", "orelse", "finalbody"):
                old = getattr(node, field, None)
                if isinstance(old, list) and old \
                        and all(isinstance(s, ast.stmt) for s in old):
                    setattr(node, field, self._fold(old))
            return node

    _Rewriter().visit(tree)


# --- graph checks ---

def _tarjan_sccs(nodes: Set[str],
                 edges: Set[Tuple[str, str]]) -> List[List[str]]:
    adj: Dict[str, List[str]] = {n: [] for n in nodes}
    for a, b in edges:
        if a in adj and b in nodes:
            adj[a].append(b)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (deep graphs must not hit the recursion
        # limit inside a linter)
        work = [(v, iter(adj[v]))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    for n in sorted(nodes):
        if n not in index:
            strongconnect(n)
    return sccs


def _reachable(edges: Set[Tuple[str, str]], src: str,
               dst: str) -> bool:
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    seen = {src}
    frontier = [src]
    while frontier:
        n = frontier.pop()
        if n == dst:
            return True
        for m in adj.get(n, ()):
            if m not in seen:
                seen.add(m)
                frontier.append(m)
    return dst in seen


def _graph_findings(model: _Model,
                    edges: List[_Edge]) -> List[Finding]:
    declared = model.declared_edges()
    observed = {(e.src, e.dst) for e in edges}
    nodes = {k for pair in declared | observed for k in pair}
    combined = declared | observed
    sccs = [scc for scc in _tarjan_sccs(nodes, combined)
            if len(scc) > 1]
    cyclic: Set[str] = {n for scc in sccs for n in scc}
    out: List[Finding] = []
    reported_sccs: Set[frozenset] = set()
    # One cycle finding per SCC, pinned at the OFFENDING witness: an
    # observed edge that runs against the declared order if one
    # exists, else the first witness by position. The conforming half
    # of an AB/BA inversion is not the bug.
    by_scc: Dict[frozenset, List[_Edge]] = {}
    for e in edges:
        if e.src in cyclic and e.dst in cyclic:
            for scc in sccs:
                if e.src in scc and e.dst in scc:
                    by_scc.setdefault(frozenset(scc), []).append(e)
                    break
    for scc_key, scc_edges in sorted(
            by_scc.items(), key=lambda kv: sorted(kv[0])):
        pick = min(scc_edges,
                   key=lambda e: ((e.src, e.dst) in declared,
                                  e.path, e.line))
        cycle = " -> ".join(sorted(scc_key))
        out.append(Finding(
            rule="lock-order-cycle", path=pick.path, line=pick.line,
            message=f"acquiring {pick.dst} while holding {pick.src} "
                    f"completes a lock-order cycle "
                    f"({cycle} -> ...)",
            detail="witness path:\n  " + "\n  ".join(pick.witness)
                   + "\nbreak the cycle or re-declare the "
                     "_CRDTLINT_LOCK_ORDER contracts so one "
                     "global order covers every path"))
        reported_sccs.add(scc_key)
    for e in edges:
        if e.src in cyclic and e.dst in cyclic and any(
                e.src in scc and e.dst in scc for scc in sccs):
            continue  # reported above, once per SCC
        if not _reachable(declared, e.src, e.dst):
            out.append(Finding(
                rule="lock-order-undeclared", path=e.path,
                line=e.line,
                message=f"acquiring {e.dst} while holding {e.src} — "
                        "no _CRDTLINT_LOCK_ORDER contract declares "
                        f"{e.src} before {e.dst}",
                detail="witness path:\n  " + "\n  ".join(e.witness)
                       + "\ndeclare the order (extend a contract "
                         "tuple) or restructure so the inner "
                         "acquisition happens after release"))
    # contract-only cycles (inconsistent declarations, no runtime
    # witness): pin at the first declaring contract
    for scc in sccs:
        key = frozenset(scc)
        if key in reported_sccs:
            continue
        decl = next((c for c in model.contracts
                     if any(k in scc for k in c.order)), None)
        if decl is not None:
            out.append(Finding(
                rule="lock-order-cycle", path=decl.path,
                line=decl.line,
                message="declared _CRDTLINT_LOCK_ORDER contracts are "
                        "mutually inconsistent: "
                        + " -> ".join(sorted(scc)) + " -> ...",
                detail="no acquisition site witnesses the cycle, but "
                       "the declarations themselves admit it — "
                       "re-order the contract tuples"))
    return out


def _blocking_findings(model: _Model,
                       blocked: List[_Blocked]) -> List[Finding]:
    out = []
    for b in blocked:
        held = ", ".join(b.held)
        out.append(Finding(
            rule="blocking-under-lock", path=b.path, line=b.line,
            message=f"{b.what} reachable while holding {held}",
            detail="witness path:\n  " + "\n  ".join(b.witness)
                   + "\nmove the blocking call outside the hold, or "
                     "suppress with the reason the hold is bounded "
                     "(docs/ANALYSIS.md, Concurrency)"))
    return out


# --- public API ---

def analyze_sources(sources: Sequence[Tuple[str, str]]
                    ) -> List[Finding]:
    """Run the whole-tree concurrency pass over ``(path, text)``
    pairs: one global lock graph, findings pinned at witness sites,
    per-file suppressions honored. ``suppression-without-reason`` is
    host_lint's to report — unexplained comments are simply inert
    here."""
    model = _build_model(sources)
    if not model.contracts:
        return []
    for path, tree in model.trees.items():
        _acquire_rewrite(model, path, tree)
    walker = _Walker(model, model.outer_keys())
    for (path, cls_name), methods in model.methods.items():
        for name, fn in methods.items():
            if name in ("__init__", "__new__"):
                continue  # construction happens-before publication
            walker.walk_method(path, cls_name, fn)
    for path, functions in model.functions.items():
        for fn in functions.values():
            walker.walk_method(path, None, fn)
    findings = _graph_findings(model, walker.edges)
    findings.extend(_blocking_findings(model, walker.blocked))
    kept = []
    for f in findings:
        supp = model.suppressions.get(f.path)
        if supp is not None and supp.covers(f.rule, f.line):
            continue
        kept.append(f)
    return sorted(set(kept), key=lambda f: (f.path, f.line, f.rule,
                                            f.message))


def analyze_source(text: str, path: str) -> List[Finding]:
    """Single-source convenience wrapper (fixtures, unit tests)."""
    return analyze_sources([(path, text)])


def analyze_paths(paths: Iterable[str]) -> List[Finding]:
    """Analyze files and/or directories as ONE tree (one global
    graph)."""
    sources: List[Tuple[str, str]] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in ("__pycache__",))
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        full = os.path.join(dirpath, name)
                        with open(full, "r", encoding="utf-8") as fh:
                            sources.append((full, fh.read()))
        else:
            with open(p, "r", encoding="utf-8") as fh:
                sources.append((p, fh.read()))
    return analyze_sources(sources)


def analyze_package(root: str) -> List[Finding]:
    """Analyze every .py file under ``root`` as one tree — the CI
    gate surface (`python -m crdt_tpu.analysis`)."""
    return analyze_paths([root])


# --- runtime twin: the deadlock sanitizer ---

_VIOLATIONS_METRIC = "crdt_tpu_lock_order_violations_total"

# Per-thread held-lock stack: list of [rank, name, lock_obj, count].
_held = threading.local()


def _held_stack() -> list:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


class OrderedLock:
    """Sanitizing lock proxy (``CRDT_TPU_SANITIZE=1`` at creation).

    Wraps a real ``threading.Lock``/``RLock``; every acquisition is
    checked against the per-thread held-set: acquiring a rank at or
    below an already-held rank (another lock — same-lock RLock
    re-entry is the RLock contract) is a declared-order violation.
    Violations are COUNTED and TRACED, never raised or blocked on —
    the sanitized build can only ever report a deadlock hazard, not
    introduce one.
    """

    __slots__ = ("name", "rank", "_inner")

    def __init__(self, name: str, rank: int, rlock: bool = False):
        self.name = name
        self.rank = rank
        self._inner = threading.RLock() if rlock else threading.Lock()

    def _check(self) -> None:
        stack = _held_stack()
        if getattr(_held, "reporting", False):
            return  # the violation report path takes obs locks itself
        for rank, name, lock, _count in stack:
            if lock is self:
                return  # re-entry; RLock semantics judge it
            if rank >= self.rank:
                self._report(name)
                return

    def _report(self, held_name: str) -> None:
        _held.reporting = True
        try:
            thread = threading.current_thread().name
            try:
                from ..obs.registry import default_registry
                default_registry().counter(
                    _VIOLATIONS_METRIC,
                    "runtime lock acquisitions violating the declared "
                    "_CRDTLINT_LOCK_ORDER rank order",
                ).inc(held=held_name, acquiring=self.name)
            except Exception:
                pass
            try:
                from ..obs.trace import tracer
                ring = tracer()
                if ring.enabled:
                    ring.emit("lock_order_violation", held=held_name,
                              acquiring=self.name, thread=thread)
            except Exception:
                pass
            try:
                # Flight recorder (obs/recorder.py): a lock-order
                # violation is an incident edge worth a debug bundle.
                # Same safety profile as the obs calls above — the
                # reporting guard stops recursive reports, and the
                # recorder throttles same-kind storms itself.
                from ..obs.recorder import default_recorder
                default_recorder().trigger(
                    "lock_order_violation",
                    {"held": held_name, "acquiring": self.name,
                     "thread": thread})
            except Exception:
                pass
        finally:
            _held.reporting = False

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        self._check()
        got = self._inner.acquire(blocking, timeout)
        if got:
            stack = _held_stack()
            for entry in stack:
                if entry[2] is self:
                    entry[3] += 1
                    break
            else:
                stack.append([self.rank, self.name, self, 1])
        return got

    def release(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][2] is self:
                stack[i][3] -= 1
                if stack[i][3] == 0:
                    del stack[i]
                break
        self._inner.release()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else False


def make_lock(name: str, rank: int, rlock: bool = False):
    """The lock-creation seam every contract lock goes through.

    ``name`` is the canonical contract key (``"ClassName.attr"``);
    ``rank`` is its position in the global declared order (larger =
    acquired later / more leaf-ward). The sanitize env var is read at
    CREATION time: with it unset this returns a plain
    ``threading.Lock``/``RLock`` — zero per-acquisition overhead and
    byte-identical semantics for the production build."""
    from . import sanitizer
    if not sanitizer.enabled():
        return threading.RLock() if rlock else threading.Lock()
    return OrderedLock(name, rank, rlock=rlock)

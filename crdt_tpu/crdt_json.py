"""JSON wire codec (L3) — the replica-boundary format.

Matches the reference `lib/src/crdt_json.dart:1-38` byte-for-byte on the
golden strings in `test/map_crdt_test.dart:114-150`:

- ``encode``: ``{key: {"hlc": "<iso>-<hex4>-<node>", "value": v}}``,
  compact separators, insertion order preserved.
- ``decode``: stamps every incoming record's ``modified`` with
  ``max(canonical_time, Hlc.now(node_id))`` (crdt_json.dart:23-24).
- Keys stringified by default (crdt_json.dart:13) via :func:`dart_str`,
  which mirrors Dart's ``toString`` for the key types exercised by the
  reference tests (str, int, datetime).
"""

from __future__ import annotations

import json
from datetime import datetime
from typing import Any, Dict, Optional

from . import native
from .hlc import Hlc
from .record import (KeyDecoder, KeyEncoder, NodeIdDecoder, Record,
                     ValueDecoder, ValueEncoder)


def dart_str(key: Any) -> str:
    """Default key stringification, matching Dart ``toString()`` for the
    reference's golden key types (map_crdt_test.dart:119-150)."""
    if isinstance(key, datetime):
        # Dart DateTime.toString(): 'YYYY-MM-DD HH:MM:SS.mmm' (+micros if set)
        base = (f"{key.year:04d}-{key.month:02d}-{key.day:02d} "
                f"{key.hour:02d}:{key.minute:02d}:{key.second:02d}")
        micros = key.microsecond
        if micros % 1000 == 0:
            return f"{base}.{micros // 1000:03d}"
        return f"{base}.{micros:06d}"
    if isinstance(key, bool):
        return "true" if key else "false"
    return str(key)


def _default(obj: Any) -> Any:
    to_json = getattr(obj, "to_json", None) or getattr(obj, "toJson", None)
    if callable(to_json):
        return to_json()
    raise TypeError(f"Object of type {type(obj).__name__} "
                    f"is not JSON serializable")


# The wire format's one dumps configuration — compact separators,
# raw UTF-8, to_json-hook default — shared by every export path
# (including as the container/custom-object fallback handed to the C
# `format_wire` assembler).
import functools  # noqa: E402

compact_dumps = functools.partial(json.dumps, separators=(",", ":"),
                                  ensure_ascii=False, default=_default)


def encode(record_map: Dict[Any, Record],
           key_encoder: Optional[KeyEncoder] = None,
           value_encoder: Optional[ValueEncoder] = None) -> str:
    """Map of records -> wire JSON string (crdt_json.dart:8-17)."""
    codec = native.load()
    if codec is not None and record_map:
        # Batch-format the HLC strings natively. None entries defer to
        # the Python formatter per record: out-of-window years (which
        # raise there) and non-UTF-8 node ids (which serialize fine).
        recs = list(record_map.values())
        hlcs = codec.format_hlc_batch(
            [r.hlc.millis for r in recs], [r.hlc.counter for r in recs],
            [str(r.hlc.node_id) for r in recs])
        # Keys/values are computed ONCE and shared with the dict
        # fallback below — user encoders must not be double-called
        # when format_wire defers (surrogates, key collisions).
        keys = ([dart_str(k) for k in record_map]
                if key_encoder is None
                else [key_encoder(k) for k in record_map])
        values = ([r.value for r in recs] if value_encoder is None
                  else [value_encoder(k, r.value)
                        for k, r in zip(record_map, recs)])
        if None not in hlcs and len(set(keys)) == len(keys):
            # One-pass C assembly, byte-identical to the json.dumps of
            # the dict below (scalar values serialize in C; containers
            # and custom objects go through `compact_dumps`). Colliding
            # stringified keys must collapse dict-style, so those use
            # the dict build instead.
            out = codec.format_wire(keys, hlcs, values, compact_dumps)
            if out is not None:
                return out
        obj = {}
        for k, record, hlc_str, v in zip(keys, recs, hlcs, values):
            obj[k] = {
                "hlc": record.hlc.to_json() if hlc_str is None else hlc_str,
                "value": v,
            }
    else:
        obj = {
            (dart_str(key) if key_encoder is None else key_encoder(key)):
                record.to_json(key, value_encoder=value_encoder)
            for key, record in record_map.items()
        }
    return json.dumps(obj, separators=(",", ":"), ensure_ascii=False,
                      default=_default)


def decode(json_str: str, canonical_time: Hlc,
           key_decoder: Optional[KeyDecoder] = None,
           value_decoder: Optional[ValueDecoder] = None,
           node_id_decoder: Optional[NodeIdDecoder] = None,
           now_millis: Optional[int] = None) -> Dict[Any, Record]:
    """Wire JSON -> map of records, re-stamping ``modified`` with
    ``max(canonical, now)`` (crdt_json.dart:19-37).

    ``now_millis`` makes the wall-clock read injectable for tests.
    """
    now = Hlc.now(canonical_time.node_id, millis=now_millis)
    modified = canonical_time if canonical_time >= now else now
    codec = native.load()
    if codec is not None and node_id_decoder is None:
        scanned = codec.parse_wire(json_str)
        if scanned is not None:
            import numpy as np
            keys, lt_buf, nodes, values, bad = scanned
            lt = np.frombuffer(lt_buf, np.int64)
            raw_hlc = Hlc._raw
            from .hlc import MAX_COUNTER, SHIFT
            out = {}
            bad_set = set(bad)
            for i, key in enumerate(keys):
                if i in bad_set:
                    h = Hlc.parse(nodes[i])
                else:
                    ltv = int(lt[i])
                    h = raw_hlc(ltv >> SHIFT, ltv & MAX_COUNTER, nodes[i])
                v = values[i]
                if value_decoder is not None and v is not None:
                    v = value_decoder(key, v)
                out[key if key_decoder is None else key_decoder(key)] = \
                    Record(h, v, modified)
            return out
    raw = json.loads(json_str)
    if codec is not None and node_id_decoder is None and raw:
        # Batch-parse the canonical-shape HLC strings natively; None
        # entries (non-canonical shapes) fall back to the full Python
        # parser per item.
        items = list(raw.items())
        millis_l, counter_l, node_l = codec.parse_hlc_batch(
            [v["hlc"] for _, v in items])
        out = {}
        for (key, value), ms, counter, node in zip(items, millis_l,
                                                   counter_l, node_l):
            if ms is None:
                record = Record.from_json(key, value, modified,
                                          value_decoder=value_decoder)
            else:
                raw_v = value.get("value")
                decoded = (raw_v if value_decoder is None or raw_v is None
                           else value_decoder(key, raw_v))
                record = Record(Hlc(ms, counter, node), decoded, modified)
            out[key if key_decoder is None else key_decoder(key)] = record
        return out
    return {
        (key if key_decoder is None else key_decoder(key)):
            Record.from_json(key, value, modified,
                             value_decoder=value_decoder,
                             node_id_decoder=node_id_decoder)
        for key, value in raw.items()
    }


def _check_lane_millis(millis: int) -> None:
    """Refuse millis the int64 lane packing can't hold, with the same
    curated message on every columnar path (batch or deferred item) —
    numpy's generic OverflowError on assignment says nothing about the
    remedy."""
    if not -0x8000_0000_0000 <= millis <= 0x7FFF_FFFF_FFFF:
        raise OverflowError(
            "HLC millis outside the int64 lane range (|millis| "
            ">= 2^47); use the scalar MapCrdt for such timestamps")


def decode_columns(json_str: str,
                   key_decoder: Optional[KeyDecoder] = None,
                   value_decoder: Optional[ValueDecoder] = None,
                   node_id_decoder: Optional[NodeIdDecoder] = None,
                   with_hlc_strs: bool = False):
    """Wire JSON -> columnar ``(keys, lt, node_ids, values)`` without
    materializing `Record`/`Hlc` objects — the ingest shape the
    vectorized backends consume (``lt`` is an int64 ndarray of packed
    logical times; ``node_ids`` a list aligned with it).

    Semantics match :func:`decode` minus the ``modified`` stamp, which
    is the MERGING store's concern (winners are re-stamped with the
    post-absorption canonical anyway, crdt.dart:86-87; ``modified`` is
    never itself on the wire, record.dart:28-31).

    ``with_hlc_strs`` appends a fifth column: each record's CANONICAL
    wire hlc string (byte-equal to what ``str(hlc)`` would re-derive),
    or None where only a normalizing parse was possible — backends
    that store hlc strings (SqliteCrdt) skip the re-format round trip
    for everything non-None.
    """
    import numpy as np

    from .hlc import SHIFT
    codec = native.load()
    if codec is not None:
        scanned = codec.parse_wire(json_str, with_hlc_strs)
        if scanned is not None:
            if with_hlc_strs:
                keys, lt_buf, nodes, values, bad, hlc_strs = scanned
            else:
                keys, lt_buf, nodes, values, bad = scanned
                hlc_strs = None
            # bytearray buffer -> writable int64 view, zero copies
            lt = np.frombuffer(lt_buf, np.int64)
            for i in bad:
                h = Hlc.parse(nodes[i])
                _check_lane_millis(h.millis)
                lt[i] = (h.millis << SHIFT) + h.counter
                nodes[i] = h.node_id
            if node_id_decoder is not None:
                nodes = [node_id_decoder(n) for n in nodes]
            if value_decoder is not None:
                # decoder sees the RAW wire key, like the generic path
                values = [None if v is None else value_decoder(k, v)
                          for k, v in zip(keys, values)]
            if key_decoder is not None:
                keys = [key_decoder(k) for k in keys]
            if with_hlc_strs:
                return keys, lt, nodes, values, hlc_strs
            return keys, lt, nodes, values
    raw = json.loads(json_str)
    items = list(raw.items())
    m = len(items)
    hlc_strs = [v["hlc"] for _, v in items]
    millis_l = counter_l = node_l = None
    if codec is not None and m:
        millis_l, counter_l, node_l = codec.parse_hlc_batch(hlc_strs)
    if millis_l is not None and None not in millis_l:
        ms_arr = np.array(millis_l, np.int64)
        if ms_arr.size:
            # (millis << 16) would wrap int64 — outside the lane
            # packing's range (years beyond ~6429). The scalar oracle
            # handles these; the columnar path refuses loudly.
            _check_lane_millis(int(ms_arr.max()))
            _check_lane_millis(int(ms_arr.min()))
        lt = (ms_arr << SHIFT) + np.array(counter_l, np.int64)
        nodes = node_l
    else:
        # Per-item fallback for non-canonical shapes (or no C codec).
        lt = np.empty(m, np.int64)
        nodes = [None] * m
        for i, s in enumerate(hlc_strs):
            if millis_l is not None and millis_l[i] is not None:
                ms, c, n = millis_l[i], counter_l[i], node_l[i]
            else:
                h = Hlc.parse(s)
                ms, c, n = h.millis, h.counter, h.node_id
            _check_lane_millis(ms)
            lt[i] = (ms << SHIFT) + c
            nodes[i] = n
    if node_id_decoder is not None:
        nodes = [node_id_decoder(n) for n in nodes]
    keys = ([k for k, _ in items] if key_decoder is None
            else [key_decoder(k) for k, _ in items])
    if value_decoder is None:
        values = [v.get("value") for _, v in items]
    else:
        values = [None if (raw_v := v.get("value")) is None
                  else value_decoder(k, raw_v) for k, v in items]
    if with_hlc_strs:
        # Raw strings only where the batch parser certified the
        # canonical shape AND the counter hex is uppercase (raw ==
        # what str(hlc)'s %04X re-derives); everything else reports
        # None for the caller to re-format.
        out_strs = [s if millis_l is not None and millis_l[i] is not None
                    and s[25:29] == s[25:29].upper()
                    else None
                    for i, s in enumerate(hlc_strs)]
        return keys, lt, nodes, values, out_strs
    return keys, lt, nodes, values


class CrdtJson:
    """Namespace mirroring the reference's static class (crdt_json.dart:5)."""

    encode = staticmethod(encode)
    decode = staticmethod(decode)

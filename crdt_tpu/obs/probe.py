"""Canary convergence probes: timestamped beats in a reserved slot range.

Convergence lag is a *fleet* property — no single replica can measure
it from local state (``lag_ms`` is this replica's view of how stale a
peer *might* be, an upper bound from watermarks). The canary protocol
measures it end to end, through the real write path:

- The fleet reserves ``n_origins`` slots at the top of every store
  (``base_slot .. base_slot + n_origins``); slot ``base_slot + i``
  belongs to origin ``i``.
- Each replica's probe periodically :meth:`~CanaryProbe.beat`\\ s its
  own slot with the current ``hlc.wall_clock_millis()`` as the int64
  value. The beat is an ordinary LWW write — it is stamped, flushed,
  packed, shipped, and merged exactly like user traffic.
- Every replica exposes :meth:`~CanaryProbe.observed` — the last-seen
  canary millis per origin — through the ``metrics`` wire op (the
  ``canary`` section `GossipNode` contributes).
- The fleet poller (`crdt_tpu.obs.fleet`) scrapes those sections into
  a per-(origin, observer) lag matrix:
  ``lag(o, w) = newest_beat(o) − observed(w)[o]``.

Values are wall-clock millis (read through the one sanctioned boundary,
``hlc.wall_clock_millis``), so the matrix is only as honest as fleet
clock sync — same caveat as HLC itself, and fine for the "seconds
behind" granularity an SLO budget cares about.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..hlc import wall_clock_millis


class CanaryProbe:
    """One replica's canary writer/reader over a reserved slot range.

    ``origin`` is this replica's index in the fleet's canary range
    (``0 <= origin < n_origins``); ``base_slot`` defaults to the top
    ``n_origins`` slots of the store. An optional ``lock`` guards the
    underlying replica (pass the owning server's lock when the replica
    is shared).
    """

    def __init__(self, crdt: Any, origin: int, n_origins: int,
                 base_slot: Optional[int] = None, lock: Any = None):
        if not 0 <= origin < n_origins:
            raise ValueError(
                f"origin {origin} out of range [0, {n_origins})")
        if base_slot is None:
            base_slot = int(getattr(crdt, "n_slots")) - n_origins
        if base_slot < 0:
            raise ValueError(
                f"store too small for {n_origins} canary slots")
        self.crdt = crdt
        self.origin = int(origin)
        self.n_origins = int(n_origins)
        self.base_slot = int(base_slot)
        self._lock = lock

    @property
    def slot(self) -> int:
        """This origin's canary slot."""
        return self.base_slot + self.origin

    def beat(self, millis: Optional[int] = None) -> int:
        """Write one canary beat (current wall millis unless given)
        into this origin's slot, through the ordinary write path."""
        if millis is None:
            millis = wall_clock_millis()
        millis = int(millis)
        if self._lock is not None:
            with self._lock:
                self._put(millis)
        else:
            self._put(millis)
        return millis

    def _put(self, millis: int) -> None:
        self.crdt.put_batch(np.asarray([self.slot], dtype=np.int32),
                            np.asarray([millis], dtype=np.int64))

    def observed(self) -> Dict[str, Optional[int]]:
        """Last-seen canary millis per origin index (string keys so
        the dict is JSON-clean on the metrics wire); ``None`` until a
        beat from that origin has replicated here."""
        if self._lock is not None:
            with self._lock:
                return self._observed()
        return self._observed()

    def _observed(self) -> Dict[str, Optional[int]]:
        return canary_observed(self.crdt, self.base_slot,
                               self.n_origins)

    def snapshot(self) -> Dict[str, Any]:
        """The ``canary`` section of the ``metrics`` wire op reply."""
        return {
            "origin": self.origin,
            "n_origins": self.n_origins,
            "base_slot": self.base_slot,
            "observed": self.observed(),
        }


def canary_observed(crdt: Any, base_slot: int, n_origins: int
                    ) -> Dict[str, Optional[int]]:
    """Read the reserved canary range of ``crdt``: origin index (as a
    string) → last-seen beat millis, ``None`` where nothing has
    replicated yet."""
    out: Dict[str, Optional[int]] = {}
    for i in range(n_origins):
        v = crdt.get(base_slot + i)
        out[str(i)] = None if v is None else int(v)
    return out

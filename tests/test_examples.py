"""Every example must run to completion — they are the quickstart
contract (example/crdt_example.dart parity plus this framework's
deployment stories), so a broken example is a broken doc."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# discovered, not hardcoded: a future example joins CI automatically
EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(ROOT, "examples"))
    if f.endswith(".py"))
assert EXAMPLES, "examples/ directory went missing"


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name):
    env = dict(os.environ)
    # examples run on the CPU path in CI, like the rest of the tests
    env.setdefault("JAX_PLATFORMS", "cpu")
    # the examples import the in-tree package; don't require an
    # editable install for the subprocess to find it
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (ROOT, env.get("PYTHONPATH")) if p)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", name)],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=ROOT)
    assert proc.returncode == 0, (
        f"{name} failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")

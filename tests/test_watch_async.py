"""Async change streams: the Dart `await for` shape over watch().

The reference's watch() returns a Dart broadcast Stream consumed with
`await for` (crdt_test.dart:95-131 uses emitsInAnyOrder). The sync
callback hub stays the primitive; `ChangeStream.aiter()` bridges it to
asyncio consumers.
"""

import asyncio

from conformance import FakeClock

from crdt_tpu import MapCrdt, SqliteCrdt, TpuMapCrdt


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_aiter_receives_pre_and_mid_iteration_events():
    crdt = MapCrdt("n", wall_clock=FakeClock())
    it = crdt.watch().aiter()
    crdt.put("before", 1)  # emitted before the first await: buffered

    async def consume():
        got = []
        async def producer():
            await asyncio.sleep(0)
            crdt.put("during", 2)
        task = asyncio.ensure_future(producer())
        async for event in it:
            got.append((event.key, event.value))
            if len(got) == 2:
                it.close()
        await task
        return got

    assert run(consume()) == [("before", 1), ("during", 2)]


def test_aiter_key_filter():
    crdt = MapCrdt("n", wall_clock=FakeClock())
    it = crdt.watch(key="y").aiter()
    crdt.put("x", 1)
    crdt.put("y", 2)
    crdt.put("y", 3)

    async def consume():
        got = []
        async with it:
            async for event in it:
                got.append((event.key, event.value))
                if len(got) == 2:
                    break
        return got

    assert run(consume()) == [("y", 2), ("y", 3)]


def test_close_drains_then_stops():
    crdt = MapCrdt("n", wall_clock=FakeClock())
    it = crdt.watch().aiter()
    crdt.put("a", 1)
    crdt.put("b", 2)
    it.close()
    crdt.put("after-close", 3)  # must NOT be delivered

    async def consume():
        return [(e.key, e.value) async for e in it]

    assert run(consume()) == [("a", 1), ("b", 2)]


def test_cross_thread_emission_no_loss():
    # Events emitted from a worker thread racing the first __anext__
    # must all arrive (the pending->queue handoff is lock-serialized).
    import threading
    crdt = MapCrdt("n", wall_clock=FakeClock())
    it = crdt.watch().aiter()
    n = 200

    def producer():
        for i in range(n):
            crdt.put(f"k{i % 7}", i)
        it.close()

    async def consume():
        t = threading.Thread(target=producer)
        t.start()
        got = [e.value async for e in it]
        t.join()
        return got

    got = run(consume())
    assert got == list(range(n))


def test_break_without_close_detaches_on_gc():
    import gc
    crdt = MapCrdt("n", wall_clock=FakeClock())
    hub = crdt._hub

    async def consume():
        it = crdt.watch().aiter()
        crdt.put("a", 1)
        async for _ in it:
            break  # no close(), no async-with

    run(consume())
    gc.collect()
    # The dropped iterator's subscription must not keep the hub hot.
    assert not hub.active


def test_aiter_works_on_all_backends():
    for crdt in (MapCrdt("n", wall_clock=FakeClock()),
                 TpuMapCrdt("n", wall_clock=FakeClock()),
                 SqliteCrdt("n", wall_clock=FakeClock())):
        it = crdt.watch().aiter()
        crdt.put("k", 7)
        it.close()

        async def consume():
            return [(e.key, e.value) async for e in it]

        assert run(consume()) == [("k", 7)], type(crdt).__name__


def test_add_batch_skips_sinkless_streams():
    """An idle watch() handle (no record()/listen()) must not force
    batch materialization or keyed scans."""
    from crdt_tpu.watch import ChangeHub
    hub = ChangeHub()
    hub.stream()          # idle unfiltered handle
    hub.stream(key="k")   # idle keyed handle
    live = hub.stream(key="k").record()
    calls = {"pairs": 0, "get": 0}

    def pairs():
        calls["pairs"] += 1
        return ["k"], [1]

    def get(k):
        calls["get"] += 1
        return True, 1

    hub.add_batch(pairs, get)
    assert calls["pairs"] == 0      # idle streams forced nothing
    assert calls["get"] == 1        # only the live keyed stream asked
    assert [(e.key, e.value) for e in live.events] == [("k", 1)]


def test_dense_duplicate_slot_batch_events_agree():
    """put_batch with a repeated slot: keyed and whole-store
    subscribers see the SAME per-occurrence events."""
    from crdt_tpu import DenseCrdt
    from crdt_tpu.testing import FakeClock
    c = DenseCrdt("n", 64, wall_clock=FakeClock())
    keyed = c.watch(5).record()
    whole = c.watch().record()
    c.put_batch([5, 3, 5], [1, 9, 2])
    assert [(e.key, e.value) for e in keyed.events] == [(5, 1), (5, 2)]
    assert [(e.key, e.value) for e in whole.events] == \
        [(5, 1), (3, 9), (5, 2)]

"""Sync fast path: pooled sessions, hello negotiation, compressed and
packed frames — and, critically, that none of it breaks a pre-PR peer.

The legacy-interop tests speak the OLD wire by hand (raw sockets,
untagged frames, no hello) so the bytes they exchange are exactly what
a pre-fast-path build would send; the new endpoints must serve and
consume them unchanged.
"""

import json
import socket
import struct
import threading

import numpy as np
import pytest

from crdt_tpu import (DenseCrdt, FrameCodec, GossipNode, MapCrdt,
                      PeerConnection, SyncProtocolError, SyncServer,
                      SyncTransportError, WireTally, sync_over_conn,
                      sync_packed, sync_packed_over_conn)
from crdt_tpu.ops.packing import PackedDelta, pack_rows, unpack_rows
from crdt_tpu.testing import FakeClock
from crdt_tpu.testing_faults import FaultProxy, FaultSchedule

pytestmark = pytest.mark.net


# --- FrameCodec ---

def test_codec_raw_roundtrip_and_tag():
    c = FrameCodec(compress=False)
    pieces = c.encode([b"hello ", b"world"])
    assert pieces[0] == FrameCodec.TAG_RAW
    assert c.decode(b"".join(pieces)) == b"hello world"


def test_codec_compresses_large_compressible_bodies():
    c = FrameCodec(compress=True)
    body = b"abc" * 1000
    tally = WireTally()
    pieces = c.encode([body], tally)
    assert pieces[0] == FrameCodec.TAG_ZLIB
    wire = b"".join(pieces)
    assert len(wire) < len(body)
    assert tally.z_raw == len(body) and tally.z_wire == len(wire) - 1
    assert tally.z_ratio > 1.0
    assert c.decode(wire) == body


def test_codec_small_and_incompressible_ship_raw():
    c = FrameCodec(compress=True)
    # under the threshold: never compressed
    assert c.encode([b"tiny"])[0] == FrameCodec.TAG_RAW
    # over the threshold but incompressible: raw beats a larger stream
    noise = np.random.default_rng(3).bytes(4096)
    pieces = c.encode([noise])
    assert pieces[0] == FrameCodec.TAG_RAW
    assert c.decode(b"".join(pieces)) == noise


def test_codec_rejects_garbage():
    c = FrameCodec()
    with pytest.raises(ValueError):
        c.decode(b"")                       # empty tagged body
    with pytest.raises(ValueError):
        c.decode(b"\x07data")               # unknown tag
    with pytest.raises(ValueError):
        c.decode(FrameCodec.TAG_ZLIB + b"not zlib at all")
    import zlib
    ok = zlib.compress(b"x" * 100)
    with pytest.raises(ValueError):
        c.decode(FrameCodec.TAG_ZLIB + ok[:-3])   # truncated stream
    with pytest.raises(ValueError):
        c.decode(FrameCodec.TAG_ZLIB + ok + b"trailing")


# --- hello negotiation + pooling ---

def test_hello_negotiates_cap_intersection():
    with SyncServer(DenseCrdt("s", n_slots=32)) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            conn.ensure()
            assert conn.caps == frozenset({"zlib", "packed",
                                           "semantics", "merkle",
                                           "trace", "sketch"})
            assert not conn.legacy
        with PeerConnection(server.host, server.port, timeout=5.0,
                            want_caps=("zlib",)) as conn:
            conn.ensure()
            assert conn.caps == frozenset({"zlib"})


def test_map_server_does_not_advertise_packed():
    with SyncServer(MapCrdt("s", wall_clock=FakeClock())) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            conn.ensure()
            assert conn.caps == frozenset({"zlib", "trace",
                                           "sketch"})


def test_pooled_session_reuses_one_connect():
    clk = FakeClock()
    hub = MapCrdt("hub", wall_clock=clk)
    edge = MapCrdt("edge", wall_clock=clk)
    with SyncServer(hub) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            mark = None
            for i in range(5):
                edge.put(f"k{i}", i)
                mark = sync_over_conn(edge, conn, since=mark)
            assert conn.connects == 1
    assert hub.map == edge.map


def test_pooled_session_reconnects_after_server_drop():
    clk = FakeClock()
    hub = MapCrdt("hub", wall_clock=clk)
    edge = MapCrdt("edge", wall_clock=clk)
    edge.put("a", 1)
    server = SyncServer(hub).start()
    try:
        conn = PeerConnection(server.host, server.port, timeout=5.0)
        mark = sync_over_conn(edge, conn, since=None)
        # the server restarts out from under the parked session
        host, port = server.host, server.port
        server.stop()
        server = SyncServer(hub, host, port).start()
        edge.put("b", 2)
        try:
            sync_over_conn(edge, conn, since=mark)
        except SyncTransportError:
            # dead socket detected mid-round: session was reset,
            # the retry reconnects — exactly what gossip does
            sync_over_conn(edge, conn, since=mark)
        assert conn.connects == 2
        assert hub.get("b") == 2
        conn.close()
    finally:
        server.stop()


def test_no_zlib_cap_means_raw_frames():
    clk = FakeClock()
    hub = MapCrdt("hub", wall_clock=clk)
    edge = MapCrdt("edge", wall_clock=clk)
    for i in range(200):
        edge.put(f"key-number-{i}", f"value-{i}" * 4)
    with SyncServer(hub) as server:
        tally = WireTally()
        with PeerConnection(server.host, server.port, timeout=5.0,
                            want_caps=("packed",)) as conn:
            sync_over_conn(edge, conn, since=None, tally=tally)
        assert tally.z_wire == 0            # nothing compressed...
    assert hub.map == edge.map              # ...round still converges


def test_zlib_cap_compresses_big_payloads():
    clk = FakeClock()
    hub = MapCrdt("hub", wall_clock=clk)
    edge = MapCrdt("edge", wall_clock=clk)
    for i in range(200):
        edge.put(f"key-number-{i}", f"value-{i}" * 4)
    with SyncServer(hub) as server:
        tally = WireTally()
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            sync_over_conn(edge, conn, since=None, tally=tally)
        assert tally.z_ratio > 1.5
    assert hub.map == edge.map


# --- admission refusal: busy is retryable, never a downgrade ---

def test_busy_refusal_is_retryable_not_legacy():
    """A connection past max_conns used to be closed silently (the
    client saw a raw EOF mid-hello). The server now answers a 'busy'
    error frame pre-hello; the client must classify it as a RETRYABLE
    transport fault — no sticky legacy mark, no capability downgrade —
    and succeed on a later redial once a slot frees."""
    import time
    with SyncServer(DenseCrdt("s", n_slots=16), max_conns=1) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as first:
            first.ensure()                   # occupies the only slot
            second = PeerConnection(server.host, server.port,
                                    timeout=5.0)
            with pytest.raises(SyncTransportError, match="busy"):
                second.ensure()
            # the refusal was understood, not mistaken for a pre-hello
            # server or a dead link
            assert second.legacy is False
            assert second.caps == frozenset()
            assert not second.connected
        # first session closed -> its handler exits; the retry that
        # gossip's backoff would issue now lands in the freed slot
        for _ in range(100):
            try:
                second.ensure()
                break
            except SyncTransportError:
                time.sleep(0.02)
        else:
            raise AssertionError("slot never freed after close")
        assert second.legacy is False
        assert "packed" in second.caps       # full renegotiation
        second.close()


def test_busy_refusal_speaks_pre_hello_framing():
    """The refusal crosses BEFORE any hello, so it must ride the
    untagged legacy framing every client generation can read — a
    pre-fast-path client sees a structured error, not a reset."""
    with SyncServer(DenseCrdt("s", n_slots=16), max_conns=1) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as first:
            first.ensure()
            with socket.create_connection(
                    (server.host, server.port), timeout=5.0) as sock:
                reply = _legacy_recv(sock)
                assert reply["ok"] is False
                assert reply["code"] == "busy"


def test_busy_code_is_not_a_gossip_fallback_signal():
    """'busy' must never appear in the sticky-downgrade code sets:
    a capacity blip on a merkle-capable peer would otherwise demote
    the pair to packed/dense/json forever."""
    from crdt_tpu.gossip import (_DENSE_FALLBACK_CODES,
                                 _MERKLE_FALLBACK_CODES,
                                 _PACKED_FALLBACK_CODES)
    for codes in (_MERKLE_FALLBACK_CODES, _PACKED_FALLBACK_CODES,
                  _DENSE_FALLBACK_CODES):
        assert "busy" not in codes


# --- legacy interop: the pre-PR wire, both directions ---

def _legacy_send(sock, obj):
    body = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(body)) + body)


def _legacy_recv(sock):
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        assert chunk, "legacy peer saw EOF"
        head += chunk
    (n,) = struct.unpack(">I", head)
    body = b""
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        assert chunk, "legacy peer saw EOF mid-frame"
        body += chunk
    return json.loads(body)


def test_legacy_client_against_new_server():
    """A pre-PR client (no hello, untagged frames) must sync against
    the new multi-capability server byte-for-byte as before."""
    clk = FakeClock()
    hub = MapCrdt("hub", wall_clock=clk)
    hub.put("motd", "hi")
    edge = MapCrdt("edge", wall_clock=clk)
    edge.put("n", 7)
    with SyncServer(hub) as server:
        with socket.create_connection((server.host, server.port),
                                      timeout=5.0) as sock:
            _legacy_send(sock, {"op": "push",
                                "payload": edge.to_json()})
            assert _legacy_recv(sock).get("ok") is True
            _legacy_send(sock, {"op": "delta", "since": None})
            reply = _legacy_recv(sock)
            assert "payload" in reply
            edge.merge_json(reply["payload"])
            _legacy_send(sock, {"op": "bye"})
    assert edge.map == hub.map
    assert hub.get("n") == 7


class _LegacyServer:
    """A hand-rolled pre-hello server: answers ``unknown_op`` to
    anything but push/delta/bye — including hello — then hangs up,
    exactly like a pre-PR SyncServer. One connection at a time."""

    def __init__(self, crdt):
        self.crdt = crdt
        self.lock = threading.Lock()
        self._lsock = socket.create_server(("127.0.0.1", 0))
        self._lsock.settimeout(0.2)
        self.host, self.port = self._lsock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=10)
        self._lsock.close()

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with conn:
                conn.settimeout(5.0)
                try:
                    self._handle(conn)
                except (OSError, AssertionError, ValueError):
                    pass

    def _handle(self, conn):
        while True:
            req = _legacy_recv(conn)
            op = req.get("op")
            if op == "push":
                with self.lock:
                    self.crdt.merge_json(req["payload"])
                _legacy_send(conn, {"ok": True})
            elif op == "delta":
                with self.lock:
                    payload = self.crdt.to_json(
                        modified_since=None if req["since"] is None
                        else __import__("crdt_tpu").Hlc.parse(
                            req["since"]))
                _legacy_send(conn, {"payload": payload})
            elif op == "bye":
                return
            else:
                _legacy_send(conn, {"ok": False, "code": "unknown_op",
                                    "error": "unknown_op"})
                return


def test_new_client_against_legacy_server():
    """The pooled client must detect a pre-hello server (unknown_op +
    hangup), mark the session legacy (sticky), reconnect, and run
    plain JSON rounds on the untagged framing."""
    clk = FakeClock()
    hub = MapCrdt("hub", wall_clock=clk)
    hub.put("old", "state")
    edge = MapCrdt("edge", wall_clock=clk)
    edge.put("n", 7)
    with _LegacyServer(hub) as server:
        conn = PeerConnection(server.host, server.port, timeout=5.0)
        mark = sync_over_conn(edge, conn, since=None)
        assert conn.legacy is True
        assert conn.caps == frozenset()
        edge.put("m", 8)
        sync_over_conn(edge, conn, since=mark)
        conn.reset()     # legacy server closed after bye-less rounds
    assert edge.map == hub.map
    assert hub.get("n") == 7 and hub.get("m") == 8


def test_gossip_node_against_legacy_server():
    """End-to-end: a GossipNode aiming packed-first degrades through
    the caps gate (no fallback counted — capability selection) and
    converges with a legacy JSON-only peer."""
    a = GossipNode(MapCrdt("a", wall_clock=FakeClock()))
    hub = MapCrdt("hub", wall_clock=FakeClock())
    hub.put("old", 1)
    with a, _LegacyServer(hub) as server:
        a.add_peer("legacy", server.host, server.port)
        with a.lock:
            a.crdt.put("new", 2)
        assert a.sync_peer("legacy") == "ok"
        snap = a.stats_snapshot()["legacy"]
        assert snap["fallbacks"] == 0
        assert snap["rounds_ok"] == 1
    assert hub.get("new") == 2
    with a.lock:
        assert a.crdt.get("old") == 1


# --- packed wire over sockets ---

def test_packed_round_over_socket_and_empty_delta():
    a = DenseCrdt("a", n_slots=64)
    b = DenseCrdt("b", n_slots=64)
    a.put_batch([1, 2], [10, 20])
    with SyncServer(b) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            mark = sync_packed_over_conn(a, conn, since=None,
                                         lock=server.lock)
            assert b.get(1) == 10 and b.get(2) == 20
            # boundary rows (modified == watermark, inclusive bound)
            # re-ship for a round or two; then a no-change round is
            # k == 0 both ways and touches neither clock
            for _ in range(6):
                before = (a.canonical_time, b.canonical_time)
                mark = sync_packed_over_conn(a, conn, since=mark,
                                             lock=server.lock)
                if (a.canonical_time, b.canonical_time) == before:
                    break
            else:
                raise AssertionError("clocks never settled")
            assert mark == before[0]


def test_packed_rejected_before_any_bytes_on_capless_session():
    a = DenseCrdt("a", n_slots=64)
    a.put_batch([1], [10])
    hub = MapCrdt("hub", wall_clock=FakeClock())
    with SyncServer(hub) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            with pytest.raises(SyncProtocolError) as ei:
                sync_packed_over_conn(a, conn, since=None,
                                      lock=server.lock)
            assert ei.value.code == "packed_rejected"
            # the session was NOT reset: it is immediately reusable
            assert conn.connected and conn.connects == 1


def test_server_rejects_malformed_packed_meta():
    b = DenseCrdt("b", n_slots=64)
    with SyncServer(b) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            sock = conn.ensure()
            from crdt_tpu.net import recv_frame, send_bytes_frame, \
                send_frame
            send_frame(sock, {"op": "push_packed",
                              "meta": {"form": "packed",
                                       "lanes": [["bogus", "int32",
                                                  [1]]]},
                              "node_ids": ["a"]}, codec=conn.codec)
            send_bytes_frame(sock, [b"\x00" * 4], codec=conn.codec)
            reply = recv_frame(sock, codec=conn.codec)
            assert reply["ok"] is False
            assert reply["code"] == "packed_rejected"


def test_gossip_packed_pair_fault_proxy_midstream_recovery():
    """A fault proxy truncating/dropping mid-stream during pooled
    packed rounds: the session resets, the retry reconnects, and the
    pair still converges."""
    from crdt_tpu.testing_faults import ScriptedSchedule
    a = GossipNode(DenseCrdt("a", n_slots=128))
    b = GossipNode(DenseCrdt("b", n_slots=128))
    # connection 1 (the initial pooled session) is cut 20 bytes into
    # the stream — mid-hello — then every reconnect behaves
    schedule = ScriptedSchedule([{"kind": "truncate", "after": 20}])
    with a, b:
        with FaultProxy(b.host, b.port, schedule) as proxy:
            a.add_peer("b", proxy.host, proxy.port)
            with a.lock:
                a.crdt.put_batch([3, 4], [30, 40])
            with b.lock:
                b.crdt.put_batch([5], [50])
            for _ in range(4):
                a.run_round()
            snap = a.stats_snapshot()["b"]
            assert snap["rounds_ok"] > 0
            assert snap["retries"] > 0
            assert snap["connects"] >= 2     # reset + reconnect
            assert proxy.counters.get("truncate", 0) > 0
            with a.lock:
                got_a = {s: a.crdt.get(s) for s in (3, 4, 5)}
            with b.lock:
                got_b = {s: b.crdt.get(s) for s in (3, 4, 5)}
    assert got_a == {3: 30, 4: 40, 5: 50}
    assert got_b == got_a


# --- pack_since cache + merge_packed validation ---

def test_pack_since_cache_hits_and_invalidation():
    from crdt_tpu.obs.registry import default_registry
    counter = default_registry().counter("crdt_tpu_pack_cache_total",
                                         "")
    crdt = DenseCrdt("n", n_slots=64)
    crdt.put_batch([1, 2], [10, 20])
    mark = crdt.canonical_time

    def counts():
        return (counter.value(outcome="hit", node="n"),
                counter.value(outcome="miss", node="n"))

    h0, m0 = counts()
    p1, ids1 = crdt.pack_since(None)
    h1, m1 = counts()
    assert (h1, m1) == (h0, m0 + 1)
    p2, ids2 = crdt.pack_since(None)            # same key: cached
    h2, m2 = counts()
    assert (h2, m2) == (h1 + 1, m1)
    assert p2 is p1 and ids2 == ids1
    crdt.pack_since(mark)                       # new since: misses
    assert counts() == (h2, m2 + 1)
    crdt.put_batch([3], [30])                   # store replaced:
    crdt.pack_since(None)                       # cache invalidated
    assert counts() == (h2, m2 + 2)


def test_merge_packed_rejects_bad_lanes():
    crdt = DenseCrdt("n", n_slots=8)
    ragged = PackedDelta(
        slots=np.array([1, 2], np.int32),
        lt=np.array([5], np.int64),             # ragged
        node=np.zeros(2, np.int32),
        val=np.zeros(2, np.int64),
        tomb=np.zeros(2, np.uint8))
    with pytest.raises(ValueError):
        crdt.merge_packed(ragged, ["peer"])
    bad_ord = PackedDelta(
        slots=np.array([1], np.int32),
        lt=np.array([5 << 16], np.int64),
        node=np.array([7], np.int32),           # only 1 id shipped
        val=np.array([1], np.int64),
        tomb=np.zeros(1, np.uint8))
    with pytest.raises(ValueError):
        crdt.merge_packed(bad_ord, ["peer"])


def test_pack_roundtrip_and_unpack_validation():
    d = PackedDelta(
        slots=np.array([3, 9], np.int32),
        lt=np.array([1 << 20, 2 << 20], np.int64),
        node=np.array([0, 1], np.int32),
        val=np.array([30, 90], np.int64),
        tomb=np.array([0, 1], np.uint8))
    meta, bufs = pack_rows(d)
    blob = b"".join(bytes(b) for b in bufs)
    back = unpack_rows(meta, blob)
    for lane, orig in zip(back, d):
        assert np.array_equal(lane, orig)
    with pytest.raises(ValueError):
        unpack_rows(meta, blob + b"\x00")        # size mismatch
    with pytest.raises(ValueError):
        unpack_rows({"form": "nope"}, blob)


def test_sync_packed_in_process_matches_wire_semantics():
    a = DenseCrdt("a", n_slots=32)
    b = DenseCrdt("b", n_slots=32)
    a.put_batch([1], [10])
    b.put_batch([2], [20])
    mark = sync_packed(a, b)
    assert a.get(2) == 20 and b.get(1) == 10
    a.put_batch([3], [30])
    mark2 = sync_packed(a, b, since=mark)
    assert b.get(3) == 30
    # after boundary rows settle, a no-change resume keeps both
    # clocks still
    for _ in range(6):
        before = (a.canonical_time, b.canonical_time)
        mark2 = sync_packed(a, b, since=mark2)
        if (a.canonical_time, b.canonical_time) == before:
            break
    else:
        raise AssertionError("clocks never settled")


# --- semantics on the wire: cache keying + downgrade compatibility ---


def test_pack_cache_keyed_on_semantics_version_and_mode():
    # satellite regression: a semantics migration must invalidate
    # cached packs (the key carries the column version), and the two
    # negotiated modes get DISTINCT entries under one watermark
    crdt = DenseCrdt("n", n_slots=64)
    crdt.put_batch([1, 2], [10, 20])
    p1, _ = crdt.pack_since(None)
    p1b, _ = crdt.pack_since(None)
    assert p1b is p1 and p1.sem is None      # plain repeat: cached
    crdt.set_semantics([2], "gcounter")      # migration: invalidates
    p2, _ = crdt.pack_since(None, sem_mode="include")
    assert p2 is not p1 and p2.sem is not None
    assert set(p2.slots) == {1, 2}
    p3, _ = crdt.pack_since(None, sem_mode="withhold")
    assert p3 is not p2 and p3.sem is None
    assert list(p3.slots) == [1]             # typed row stays home
    p2b, _ = crdt.pack_since(None, sem_mode="include")
    p3b, _ = crdt.pack_since(None, sem_mode="withhold")
    assert p2b is p2 and p3b is p3           # modes cache side by side
    crdt.set_semantics([2], "lww")           # migrating BACK also
    p4, _ = crdt.pack_since(None)            # invalidates
    assert p4 is not p1 and p4 is not p2 and p4.sem is None
    assert set(p4.slots) == {1, 2}


def test_packed_round_negotiated_session_ships_typed_slots():
    a = DenseCrdt("a", n_slots=64)
    b = DenseCrdt("b", n_slots=64)
    for c in (a, b):
        c.set_semantics([0], "pncounter")
    a.counter_add(0, 7)
    a.put_batch([5], [50])
    with SyncServer(b) as server:
        with PeerConnection(server.host, server.port,
                            timeout=5.0) as conn:
            sync_packed_over_conn(a, conn, since=None,
                                  lock=server.lock)
            assert "semantics" in conn.caps
    assert b.counter_value(0) == 7 and b.get(5) == 50


def test_packed_round_without_semantics_cap_withholds_both_ways():
    # "LWW-only peer" compatibility, BOTH directions of one round: a
    # session that did not negotiate the semantics cap moves only LWW
    # rows — typed slots are withheld at each sender (never shipped
    # tagless, never corrupted at the receiver) and counted in the
    # downgrade metric
    from crdt_tpu.obs.registry import default_registry
    a = DenseCrdt("a", n_slots=64)
    b = DenseCrdt("b", n_slots=64)
    for c in (a, b):
        c.set_semantics([0], "gcounter")
    a.counter_add(0, 7)      # typed write at the client
    a.put_batch([5], [50])
    b.counter_add(0, 3)      # typed write at the server
    b.put_batch([6], [60])
    counter = default_registry().counter(
        "crdt_tpu_sync_semantics_downgrade_total")
    out_a = counter.value(direction="outbound", node="a")
    out_b = counter.value(direction="outbound", node="b")
    with SyncServer(b) as server:
        # the client deliberately does NOT want the semantics cap —
        # the stand-in for an older LWW-only build on either end
        with PeerConnection(server.host, server.port, timeout=5.0,
                            want_caps=("zlib", "packed")) as conn:
            sync_packed_over_conn(a, conn, since=None,
                                  lock=server.lock)
            assert "semantics" not in conn.caps
    # push half: a's typed row stayed home, b's lattice untouched
    assert b.counter_value(0) == 3 and b.get(5) == 50
    # pull half: b's typed row stayed home, a's lattice untouched
    assert a.counter_value(0) == 7 and a.get(6) == 60
    assert counter.value(direction="outbound", node="a") == out_a + 1
    assert counter.value(direction="outbound", node="b") == out_b + 1


def test_gossip_downgrade_is_sticky_and_converges_lww_rows():
    # a mesh mixing a typed replica with one that never negotiates
    # semantics keeps converging its LWW rows round after round
    a = DenseCrdt("a", n_slots=64)
    a.set_semantics([0], "orset")
    a.orset_add(0, 1)
    a.put_batch([8], [80])
    b = DenseCrdt("b", n_slots=64)
    b.put_batch([9], [90])
    with SyncServer(b) as server:
        with PeerConnection(server.host, server.port, timeout=5.0,
                            want_caps=("zlib", "packed")) as conn:
            mark = sync_packed_over_conn(a, conn, since=None,
                                         lock=server.lock)
            a.put_batch([10], [100])
            sync_packed_over_conn(a, conn, since=mark,
                                  lock=server.lock)
    assert b.get(8) == 80 and b.get(9) == 90 and b.get(10) == 100
    assert a.get(9) == 90
    assert b.get(0) is None                   # withheld, not mangled
    assert a.orset_members(0) == frozenset({1})


# --- mixed-version trace negotiation (PR 11) ---

def _packed_round_bytes(enable_trace, want_caps=None,
                        strip_server_trace=False):
    """One deterministic packed round; returns (sent, received,
    caps). FakeClock makes the payload byte-identical across calls,
    so two runs differing only in tracer state compare exactly."""
    from crdt_tpu.obs import tracer
    clk = FakeClock()
    a = DenseCrdt("mv-a", n_slots=64, wall_clock=clk)
    b = DenseCrdt("mv-b", n_slots=64, wall_clock=clk)
    a.put_batch([1, 2, 3], [10, 20, 30])
    ring = tracer()
    if enable_trace:
        ring.enable()
        ring.clear()
    try:
        with SyncServer(b) as server:
            if strip_server_trace:
                orig = server._caps
                server._caps = lambda: orig() - {"trace"}
            kw = {} if want_caps is None else {"want_caps": want_caps}
            tally = WireTally()
            with PeerConnection(server.host, server.port,
                                timeout=5.0, **kw) as conn:
                sync_packed_over_conn(a, conn, since=None,
                                      lock=server.lock, tally=tally)
                caps = conn.caps
        assert b.get(1) == 10 and b.get(3) == 30
        return tally.sent, tally.received, caps
    finally:
        if enable_trace:
            ring.disable()
            ring.clear()


def test_trace_client_against_pretrace_server_byte_identical():
    """A trace-capable client syncing with a pre-trace server must
    negotiate the cap off and keep the wire byte-identical to an
    untraced run — even with the process tracer ENABLED."""
    base = _packed_round_bytes(False, strip_server_trace=True)
    traced = _packed_round_bytes(True, strip_server_trace=True)
    assert base[2] == traced[2]
    assert "trace" not in traced[2]
    assert (base[0], base[1]) == (traced[0], traced[1])


def test_pretrace_client_against_trace_server_byte_identical():
    """The other direction: an old client that never asks for the cap
    gets identical bytes from a modern server whatever the server's
    tracer state."""
    want = ("zlib", "packed", "semantics", "merkle")
    base = _packed_round_bytes(False, want_caps=want)
    traced = _packed_round_bytes(True, want_caps=want)
    assert "trace" not in traced[2]
    assert (base[0], base[1]) == (traced[0], traced[1])


def test_trace_cap_rides_only_when_tracer_enabled():
    """Negotiating the cap costs nothing on the round itself: with
    the tracer OFF, a trace-negotiated session sends byte-identical
    requests, and only the hello REPLY differs (the server naming the
    extra cap). With the tracer ON the context does ride."""
    capless = _packed_round_bytes(False, strip_server_trace=True)
    negotiated = _packed_round_bytes(False)
    assert "trace" in negotiated[2]
    assert capless[0] == negotiated[0]
    # the received delta is the hello caps list alone — one short
    # token, nothing per-frame
    assert 0 < negotiated[1] - capless[1] <= 16
    traced = _packed_round_bytes(True)
    assert traced[0] > negotiated[0]


def test_trace_negotiation_survives_midhello_truncate():
    """FaultProxy cuts connection 1 twenty bytes in — mid-hello. The
    client sees a retryable transport fault, reconnects, and the
    fresh hello still negotiates trace; the round then correlates
    across the wire as usual."""
    from crdt_tpu.obs import tracer
    from crdt_tpu.testing_faults import ScriptedSchedule
    clk = FakeClock()
    a = DenseCrdt("mh-a", n_slots=64, wall_clock=clk)
    b = DenseCrdt("mh-b", n_slots=64, wall_clock=clk)
    a.put_batch([7], [70])
    ring = tracer()
    ring.enable()
    ring.clear()
    schedule = ScriptedSchedule([{"kind": "truncate", "after": 20}])
    try:
        with SyncServer(b) as server:
            with FaultProxy(server.host, server.port,
                            schedule) as proxy:
                conn = PeerConnection(proxy.host, proxy.port,
                                      timeout=5.0)
                with pytest.raises(SyncTransportError):
                    sync_packed_over_conn(a, conn, since=None,
                                          lock=server.lock)
                assert not conn.connected and not conn.legacy
                sync_packed_over_conn(a, conn, since=None,
                                      lock=server.lock)
                assert "trace" in conn.caps
                conn.close()
        assert b.get(7) == 70
        (sync_span,) = [e for e in ring.events("sync")
                        if e.get("span") == "sync_packed"
                        and e.get("rid")]
        assert any(e.get("rid") == sync_span["rid"]
                   for e in ring.events("sync_recv"))
    finally:
        ring.disable()
        ring.clear()

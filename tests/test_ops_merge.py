"""Direct coverage of the generic slot-indexed device join
(`ops.merge.merge_step` / `scatter_put`) — the public device-side op
for `Store`-layout workflows (e.g. a `TpuMapCrdt.store` mirror).
Previously exercised only transitively; the backend now decides
small merges host-side, so the op is pinned here directly."""

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu import Hlc, MapCrdt, Record
from crdt_tpu.ops.merge import (Changeset, empty_store, max_logical_time,
                                merge_step, scatter_put, delta_mask)
from crdt_tpu.testing import FakeClock

BASE = 1_700_000_000_000


def _changeset(slots, lts, nodes, tombs):
    m = len(slots)
    return Changeset(
        slot=jnp.asarray(np.array(slots, np.int32)),
        lt=jnp.asarray(np.array(lts, np.int64)),
        node=jnp.asarray(np.array(nodes, np.int32)),
        tomb=jnp.asarray(np.array(tombs, bool)),
        valid=jnp.ones(m, bool))


class TestMergeStep:
    def test_matches_oracle_merge(self):
        """merge_step's win set and stamps equal MapCrdt.merge for the
        same records (node ordinals: 0='aaa' local, 1='nbb', 2='ncc')."""
        oracle = MapCrdt("aaa", wall_clock=FakeClock(start=BASE + 50))
        h_old = Hlc(BASE + 1, 0, "nbb")
        h_new = Hlc(BASE + 9, 2, "ncc")
        oracle.put_record("k0", Record(h_old, 10, h_old))
        remote = {"k0": Record(h_new, 20, h_new),
                  "k1": Record(h_old, 30, h_old)}
        oracle.merge(dict(remote))

        store = empty_store(8)
        # seed slot 0 with the local record (ordinal 1 = 'nbb')
        store = scatter_put(
            store, _changeset([0], [h_old.logical_time], [1], [False]),
            jnp.asarray([h_old.logical_time]), jnp.asarray([1], jnp.int32))
        cs = _changeset([0, 1],
                        [h_new.logical_time, h_old.logical_time],
                        [2, 1], [False, False])
        new_store, res = merge_step(
            store, cs, jnp.int64(0), jnp.int32(0),
            jnp.int64(BASE + 50))
        assert not bool(res.any_bad)
        assert list(np.asarray(res.win)) == [True, True]
        assert int(res.new_canonical) == h_new.logical_time
        # Winner lanes carry the remote hlc; modified = final canonical
        # (crdt.dart:86-87) — same as the oracle's stored records.
        rec0 = oracle.get_record("k0")
        assert int(new_store.lt[0]) == rec0.hlc.logical_time
        assert int(new_store.mod_lt[0]) == res.new_canonical

    def test_local_wins_exact_tie(self):
        h = Hlc(BASE, 0, "nbb")
        store = scatter_put(
            empty_store(8),
            _changeset([3], [h.logical_time], [1], [False]),
            jnp.asarray([h.logical_time]), jnp.asarray([1], jnp.int32))
        cs = _changeset([3], [h.logical_time], [1], [True])
        _, res = merge_step(store, cs, jnp.int64(h.logical_time),
                            jnp.int32(0), jnp.int64(BASE))
        assert list(np.asarray(res.win)) == [False]

    def test_guards_flag_duplicate_and_drift(self):
        lt_ahead = (BASE + 100) << 16
        cs = _changeset([0], [lt_ahead], [0], [False])  # local ordinal
        _, res = merge_step(empty_store(8), cs, jnp.int64(0),
                            jnp.int32(0), jnp.int64(BASE))
        assert bool(res.any_bad) and bool(res.first_is_dup)

        lt_far = (BASE + 100_000) << 16
        cs = _changeset([0], [lt_far], [2], [False])
        _, res = merge_step(empty_store(8), cs, jnp.int64(0),
                            jnp.int32(0), jnp.int64(BASE))
        assert bool(res.any_bad) and not bool(res.first_is_dup)

    def test_reductions(self):
        h = Hlc(BASE + 5, 3, "nbb")
        store = scatter_put(
            empty_store(8),
            _changeset([2], [h.logical_time], [1], [False]),
            jnp.asarray([(BASE + 7) << 16]), jnp.asarray([1], jnp.int32))
        assert int(max_logical_time(store)) == h.logical_time
        mask = np.asarray(delta_mask(store, jnp.int64((BASE + 7) << 16)))
        assert mask[2] and mask.sum() == 1  # inclusive bound


class TestSendStep:
    """Device-side Hlc.send (`ops.merge.send_step`) — used by the
    pipelined window's final bump."""

    def test_counter_increments_and_millis_advances(self):
        import jax.numpy as jnp
        from crdt_tpu.hlc import SHIFT
        from crdt_tpu.ops.merge import send_step
        base = 1_700_000_000_000
        lt, ovf, drift = send_step(jnp.int64(base << SHIFT),
                                   jnp.int64(base))
        assert int(lt) == (base << SHIFT) + 1 and not bool(ovf)
        lt, ovf, drift = send_step(jnp.int64(base << SHIFT),
                                   jnp.int64(base + 5))
        assert int(lt) == (base + 5) << SHIFT and not bool(ovf)

    def test_overflow_clamps_instead_of_wrapping(self):
        # ADVICE r4: on counter overflow lt+1 would carry into the
        # millis field; the host path raises WITHOUT mutating, so the
        # device bump must leave the clock where the host would.
        import jax.numpy as jnp
        from crdt_tpu.hlc import MAX_COUNTER, SHIFT
        from crdt_tpu.ops.merge import send_step
        base = 1_700_000_000_000
        full = (base << SHIFT) | MAX_COUNTER
        lt, ovf, drift = send_step(jnp.int64(full), jnp.int64(base))
        assert bool(ovf)
        assert int(lt) == full          # clamped, not millis+1

"""Driver entry-point gate: entry() compiles, dryrun_multichip passes.

The round-1 gate failure (MULTICHIP_r01.json ok=false) was an in-process
platform switch racing an already-initialized backend; these tests pin
both the in-process path (env preconfigured, as under pytest) and the
subprocess re-exec fallback (env NOT preconfigured, as under the driver).
"""

import os
import subprocess
import sys
import time

import jax

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    store, res = out
    assert int(res.win_count) > 0
    assert not bool(res.any_bad)


def test_dryrun_multichip_in_process():
    # conftest already set the 8-device CPU platform, so this exercises
    # the in-process fast path.
    graft.dryrun_multichip(8)


def test_dryrun_multichip_subprocess_reexec():
    # Simulate the driver: a process whose backend is already live and
    # whose XLA_FLAGS lack the virtual-device count. dryrun_multichip
    # must re-exec itself in a correctly-configured child and succeed.
    #
    # JAX_PLATFORMS=cpu stays SET in the child: the re-exec trigger is
    # the missing xla_force_host_platform_device_count flag, which this
    # env still omits — but an unset JAX_PLATFORMS would send the
    # child's `jax.devices()` probing for real accelerators, and on a
    # TPU-capable host that probe blocks for minutes before falling
    # back (the tier-1 ~8-minute stall this test once caused).
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    code = ("import jax; jax.devices(); "
            "import __graft_entry__ as g; g.dryrun_multichip(4); "
            "print('SUBPROC_GATE_OK')")
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    start = time.monotonic()
    proc = subprocess.run([sys.executable, "-c", code], cwd=here, env=env,
                          capture_output=True, text=True, timeout=570)
    elapsed = time.monotonic() - start
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "SUBPROC_GATE_OK" in proc.stdout
    # Regression guard for the stall itself: with the platform pinned,
    # the whole child+grandchild round trip is pure CPU compile work.
    # Anything in the minutes range means a backend probe snuck back in
    # and the tier-1 suite is blocking on device enumeration again.
    assert elapsed < 120, (
        f"dryrun re-exec took {elapsed:.0f}s — backend probing is "
        "blocking the suite (JAX_PLATFORMS must stay pinned in every "
        "child env)")

"""TCP replication endpoints: real sockets, in-process server thread.

The transport layer must deliver exactly the reference's anti-entropy
semantics (full push + inclusive delta pull) with nothing but wire
JSON crossing the connection.
"""

import threading

import pytest

from crdt_tpu import (MapCrdt, SyncServer, TpuMapCrdt, sync_over_tcp)
from crdt_tpu.testing import FakeClock


def test_cold_start_then_incremental():
    clk = FakeClock()
    hub = TpuMapCrdt("hub", wall_clock=clk)
    hub.put_all({"motd": "hi", "n": 0})
    edge = MapCrdt("edge", wall_clock=clk)
    edge.put("n", 7)
    edge.put("local", "x")
    edge.delete("local")

    with SyncServer(hub) as server:
        # cold start: since=None -> full pull
        mark = sync_over_tcp(edge, server.host, server.port, since=None)
        assert edge.map == hub.map
        # incremental: only records stamped at/after the watermark
        edge.put("second", True)
        sync_over_tcp(edge, server.host, server.port, since=mark)
        assert edge.map == hub.map
        assert hub.get("second") is True and hub.get("motd") == "hi"


def test_three_replicas_converge_through_one_hub():
    clk = FakeClock()
    hub = MapCrdt("hub", wall_clock=clk)
    edges = [MapCrdt(f"e{i}", wall_clock=clk) for i in range(3)]
    for i, e in enumerate(edges):
        e.put_all({f"k{i}": i, "shared": i})

    with SyncServer(hub) as server:
        marks = [sync_over_tcp(e, server.host, server.port)
                 for e in edges]
        # second round picks up what OTHER edges pushed in round 1
        for e, m in zip(edges, marks):
            sync_over_tcp(e, server.host, server.port, since=m)
    maps = [hub.map] + [e.map for e in edges]
    assert all(m == maps[0] for m in maps)
    # LWW winner on the contended key is a single consistent value
    assert maps[0]["shared"] in (0, 1, 2)


def test_concurrent_local_writes_under_lock():
    clk = FakeClock()
    hub = MapCrdt("hub", wall_clock=clk)
    edge = MapCrdt("edge", wall_clock=clk)
    stop = threading.Event()

    with SyncServer(hub) as server:
        def writer():
            i = 0
            while not stop.is_set():
                with server.lock:   # the documented contract
                    hub.put(f"w{i % 50}", i)
                i += 1

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            for r in range(5):
                edge.put(f"edge{r}", r)
                sync_over_tcp(edge, server.host, server.port)
        finally:
            stop.set()
            t.join(timeout=10)
        # final full round after writers stop -> converged
        sync_over_tcp(edge, server.host, server.port)
    assert edge.map == hub.map


def test_unknown_op_rejected():
    import socket as socket_mod

    from crdt_tpu.net import recv_frame, send_frame
    hub = MapCrdt("hub", wall_clock=FakeClock())
    with SyncServer(hub) as server:
        with socket_mod.create_connection(
                (server.host, server.port), timeout=10) as sock:
            send_frame(sock, {"op": "nope"})
            assert "error" in recv_frame(sock)


def test_push_applies_guards():
    # A pushed payload from a duplicate node id trips the guard on the
    # SERVER side — reuse of the hub's own node id is the duplicate-
    # node condition (hlc.dart:87-90). The server survives, reports
    # the rejection, and the record is not adopted.
    clk = FakeClock()
    hub = MapCrdt("hub", wall_clock=clk)
    hub.put("x", 1)
    impostor = MapCrdt("hub", wall_clock=FakeClock(
        start=clk.millis + 1000))
    impostor.put("y", 2)
    with SyncServer(hub) as server:
        with pytest.raises(ConnectionError,
                          match="DuplicateNodeException"):
            sync_over_tcp(impostor, server.host, server.port)
        assert "y" not in hub.map
        # the server is still alive for well-behaved peers
        ok = MapCrdt("edge", wall_clock=FakeClock(
            start=clk.millis + 2000))
        ok.put("z", 3)
        sync_over_tcp(ok, server.host, server.port)
    assert hub.get("z") == 3


def test_serves_sqlite_backend():
    """The durable backend is servable when constructed with
    check_same_thread=False (the server thread is not the
    constructing thread; the server lock serializes access)."""
    from crdt_tpu import SqliteCrdt
    clk = FakeClock()
    hub = SqliteCrdt("hub", wall_clock=clk, check_same_thread=False)
    hub.put("persisted", 1)
    edge = MapCrdt("edge", wall_clock=clk)
    edge.put("volatile", 2)
    with SyncServer(hub) as server:
        sync_over_tcp(edge, server.host, server.port)
    assert edge.map == hub.map == {"persisted": 1, "volatile": 2}


def test_oversized_frame_rejected():
    import socket as socket_mod
    import struct as struct_mod
    hub = MapCrdt("hub", wall_clock=FakeClock())
    hub.put("x", 1)
    with SyncServer(hub) as server:
        with socket_mod.create_connection(
                (server.host, server.port), timeout=10) as sock:
            # announce a 4 GiB frame: the server must drop us, not
            # allocate
            sock.sendall(struct_mod.pack(">I", 0xFFFFFFFF))
            sock.sendall(b"garbage")
            # the server drops us without allocating: clean close
            # (None) or RST, depending on unread-buffer timing
            import crdt_tpu.net as net
            try:
                assert net.recv_frame(sock) is None
            except OSError:
                pass
        # and the server still serves well-behaved peers
        edge = MapCrdt("edge", wall_clock=FakeClock())
        sync_over_tcp(edge, server.host, server.port)
        assert edge.get("x") == 1


def test_malformed_frames_do_not_kill_server():
    import socket as socket_mod
    from crdt_tpu.net import send_frame
    hub = MapCrdt("hub", wall_clock=FakeClock())
    hub.put("x", 1)
    with SyncServer(hub) as server:
        for frame in (["not", "a", "dict"], {"no_op": 1},
                      {"op": "delta", "since": "garbage-hlc"},
                      {"op": "push", "payload": "{not json"}):
            with socket_mod.create_connection(
                    (server.host, server.port), timeout=10) as sock:
                send_frame(sock, frame)
                # server replies with an error or just closes; either
                # way it survives
                try:
                    import crdt_tpu.net as net
                    net.recv_frame(sock)
                except Exception:
                    pass
        edge = MapCrdt("edge", wall_clock=FakeClock())
        sync_over_tcp(edge, server.host, server.port)
        assert edge.get("x") == 1


def test_codec_passthrough_int_keys():
    """Custom-typed keys need the same coders over TCP that sync_json
    takes — int keys must come back as ints on both sides."""
    clk = FakeClock()
    hub = MapCrdt("hub", wall_clock=clk)
    hub.put(1, "one")
    edge = MapCrdt("edge", wall_clock=clk)
    edge.put(2, "two")
    kw = dict(key_decoder=int)
    with SyncServer(hub, **kw) as server:
        sync_over_tcp(edge, server.host, server.port, **kw)
    assert edge.map == hub.map == {1: "one", 2: "two"}
    assert all(isinstance(k, int) for k in hub.map)


def test_stop_is_quiescent_with_idle_client():
    import socket as socket_mod
    import time as time_mod
    hub = MapCrdt("hub", wall_clock=FakeClock())
    server = SyncServer(hub).start()
    # park an idle connection: the handler blocks in recv
    idle = socket_mod.create_connection((server.host, server.port),
                                        timeout=10)
    time_mod.sleep(0.3)
    t0 = time_mod.monotonic()
    server.stop()   # must shut the idle conn down, not wait 30s
    assert time_mod.monotonic() - t0 < 10
    idle.close()


def test_serves_dense_backend():
    """All four backends are servable: a DenseCrdt hub (int slot keys
    on the wire) replicates to a MapCrdt edge and back."""
    from crdt_tpu import DenseCrdt
    clk = FakeClock()
    hub = DenseCrdt("hub", 64, wall_clock=clk)
    hub.put_batch([0, 1, 2], [10, 11, 12])
    hub.delete_batch([1])
    edge = MapCrdt("edge", wall_clock=clk)
    edge.put(5, 55)
    with SyncServer(hub, key_decoder=int) as server:
        sync_over_tcp(edge, server.host, server.port, key_decoder=int)
    assert edge.map == {0: 10, 2: 12, 5: 55}
    assert hub.get(5) == 55 and hub.is_deleted(1)


def test_sync_over_tcp_lock_serializes_self_served_replica():
    # A replica that is ALSO served by its own SyncServer: passing that
    # server's lock to sync_over_tcp is the documented way to make the
    # bidirectional mesh safe. The round must hold the lock only around
    # local replica calls (never across network waits), so two
    # self-served replicas syncing into each other can't deadlock.
    clk = FakeClock()
    a = MapCrdt("a", wall_clock=clk)
    b = MapCrdt("b", wall_clock=clk)
    a.put("ka", 1)
    b.put("kb", 2)
    with SyncServer(a) as sa, SyncServer(b) as sb:
        done = []

        def round_a():
            sync_over_tcp(a, sb.host, sb.port, lock=sa.lock)
            done.append("a")

        def round_b():
            sync_over_tcp(b, sa.host, sa.port, lock=sb.lock)
            done.append("b")

        ta = threading.Thread(target=round_a)
        tb = threading.Thread(target=round_b)
        ta.start(); tb.start()
        ta.join(timeout=10); tb.join(timeout=10)
        assert sorted(done) == ["a", "b"], "rounds deadlocked or died"
    assert a.map == b.map == {"ka": 1, "kb": 2}


def test_connection_op_bound_drops_chatty_peer():
    # One peer may not monopolize the single-connection endpoint: after
    # max_ops framed requests the server closes the connection; a fresh
    # connection still works.
    import socket as _socket

    from crdt_tpu.net import recv_frame, send_frame

    hub = MapCrdt("hub", wall_clock=FakeClock())
    with SyncServer(hub, max_ops=3) as server:
        with _socket.create_connection((server.host, server.port),
                                       timeout=5) as sock:
            sock.settimeout(5)
            for _ in range(3):
                send_frame(sock, {"op": "delta", "since": None})
                assert recv_frame(sock) is not None
            # 4th op: connection dropped (EOF or reset mid-frame)
            try:
                send_frame(sock, {"op": "delta", "since": None})
                reply = recv_frame(sock)
            except OSError:
                reply = None
            assert reply is None
        # the endpoint itself survives for the next peer
        sync_over_tcp(MapCrdt("edge", wall_clock=FakeClock()),
                      server.host, server.port)


def test_connection_deadline_drops_held_connection():
    import socket as _socket

    from crdt_tpu.net import recv_frame, send_frame

    hub = MapCrdt("hub", wall_clock=FakeClock())
    with SyncServer(hub, conn_deadline=0.2) as server:
        with _socket.create_connection((server.host, server.port),
                                       timeout=5) as sock:
            sock.settimeout(5)
            send_frame(sock, {"op": "delta", "since": None})
            assert recv_frame(sock) is not None
            import time
            time.sleep(0.4)   # overstay the per-connection deadline
            try:
                send_frame(sock, {"op": "delta", "since": None})
                reply = recv_frame(sock)
            except OSError:
                reply = None
            assert reply is None


def test_connection_deadline_bounds_mid_frame_trickle():
    # The deadline must bound the WHOLE frame: a peer trickling bytes
    # (each chunk inside the per-recv socket timeout) cannot hold the
    # single-connection server past conn_deadline.
    import socket as _socket
    import struct
    import time

    hub = MapCrdt("hub", wall_clock=FakeClock())
    with SyncServer(hub, conn_deadline=0.3) as server:
        with _socket.create_connection((server.host, server.port),
                                       timeout=5) as sock:
            sock.sendall(struct.pack(">I", 100))  # announce 100 bytes
            t0 = time.monotonic()
            dropped_at = None
            for _ in range(40):                   # trickle 1 B / 50 ms
                try:
                    sock.sendall(b"x")
                except OSError:
                    dropped_at = time.monotonic() - t0
                    break
                time.sleep(0.05)
            if dropped_at is None:
                # sends may succeed into the OS buffer after the peer
                # closed; detect the close via EOF instead
                sock.settimeout(2)
                assert sock.recv(1) == b""
                dropped_at = time.monotonic() - t0
            assert dropped_at < 2.0, (
                f"server held a trickling connection {dropped_at:.1f}s "
                "past a 0.3s deadline")


class TestDenseBinarySync:
    """Binary split-lane sync (`push_dense`/`delta_dense` +
    `sync_dense_over_tcp`): dense peers exchange the kernel wire form
    as raw frames; the JSON ops stay the universal interop path."""

    BASE = 1_700_000_000_000

    def _dense(self, node, start_off=0, n=64):
        from crdt_tpu import DenseCrdt
        return DenseCrdt(node, n,
                         wall_clock=FakeClock(start=self.BASE + start_off))

    def test_round_converges_and_watermark(self):
        from crdt_tpu.net import SyncServer, sync_dense_over_tcp
        a = self._dense("na")
        b = self._dense("nb", 5)
        a.put_batch([1, 3], [10, 30])
        b.put_batch([2], [20])
        b.delete_batch([2])
        with SyncServer(b) as server:
            wm = sync_dense_over_tcp(a, server.host, server.port)
            # second round with the watermark: only newer records move
            b.put_batch([7], [70])
            sync_dense_over_tcp(a, server.host, server.port, since=wm)
        for c in (a, b):
            assert c.get(1) == 10 and c.get(3) == 30
            assert c.get(2) is None and c.is_deleted(2)
            assert c.get(7) == 70

    def test_matches_json_sync_lane_exact(self):
        from crdt_tpu.net import (SyncServer, sync_dense_over_tcp,
                                  sync_over_tcp)
        srv_bin = self._dense("srv")
        srv_json = self._dense("srv")
        cl_bin = self._dense("cl", 3)
        cl_json = self._dense("cl", 3)
        for cl in (cl_bin, cl_json):
            cl.put_batch([0, 9], [5, 95])
        for srv in (srv_bin, srv_json):
            srv.put_batch([4], [44])
        with SyncServer(srv_bin) as s1, SyncServer(srv_json) as s2:
            sync_dense_over_tcp(cl_bin, s1.host, s1.port)
            sync_over_tcp(cl_json, s2.host, s2.port, key_decoder=int)
        import numpy as np
        occ = np.asarray(cl_json.store.occupied)
        np.testing.assert_array_equal(
            np.asarray(cl_bin.store.occupied), occ)
        for lane in ("lt", "val", "tomb"):
            np.testing.assert_array_equal(
                np.asarray(getattr(cl_bin.store, lane))[occ],
                np.asarray(getattr(cl_json.store, lane))[occ],
                err_msg=lane)
        # Canonical clocks are NOT asserted equal: the JSON round's
        # merge_json spends its decode-stamp wall read (the reference
        # contract), while merge_split reads like merge() — one fewer
        # tick under an injected clock. Both must dominate every
        # record they absorbed.
        for cl in (cl_bin, cl_json):
            assert (cl.canonical_time.logical_time
                    >= int(np.asarray(cl.store.lt)[occ].max()))

    def test_non_dense_server_rejects_gracefully(self):
        from crdt_tpu import MapCrdt
        from crdt_tpu.net import SyncServer, sync_dense_over_tcp
        m = MapCrdt("mm", wall_clock=FakeClock(start=self.BASE))
        a = self._dense("na")
        a.put_batch([0], [1])
        with SyncServer(m) as server:
            with pytest.raises(ConnectionError, match="rejected"):
                sync_dense_over_tcp(a, server.host, server.port)
        assert m.map == {}        # replica untouched

    def test_malformed_meta_rejected(self):
        import socket as socket_mod
        from crdt_tpu.net import (SyncServer, recv_frame, send_frame,
                                  send_bytes_frame)
        b = self._dense("nb")
        with SyncServer(b) as server:
            with socket_mod.create_connection(
                    (server.host, server.port), timeout=10) as sock:
                sock.settimeout(10)
                # dtype smuggling: 'object' must be refused
                send_frame(sock, {"op": "push_dense", "node_ids": ["x"],
                                  "meta": {"form": "split", "lanes": [
                                      [f, "object", [1, 64]]
                                      for f in ("hi", "lo", "node",
                                                "val_hi", "val_lo",
                                                "tomb")]}})
                send_bytes_frame(sock, [b"\0" * 64])
                reply = recv_frame(sock)
                assert reply and reply.get("ok") is False
                assert reply["error"] == "ValueError"
        assert len(b) == 0

    def test_frame_size_mismatch_rejected(self):
        import socket as socket_mod
        from crdt_tpu.net import (SyncServer, recv_frame, send_frame,
                                  send_bytes_frame, _pack_split)
        a = self._dense("na")
        a.put_batch([0], [1])
        scs, ids = a.export_split_delta(tiled=False)
        meta, bufs = _pack_split(scs)
        b = self._dense("nb")
        with SyncServer(b) as server:
            with socket_mod.create_connection(
                    (server.host, server.port), timeout=10) as sock:
                sock.settimeout(10)
                send_frame(sock, {"op": "push_dense", "meta": meta,
                                  "node_ids": list(ids)})
                # truncated binary frame
                send_bytes_frame(sock, [bytes(bufs[0])[:-4]])
                reply = recv_frame(sock)
                assert reply and reply.get("ok") is False
        assert len(b) == 0

    def test_value_ref_narrow_lanes_roundtrip(self):
        from crdt_tpu import DenseCrdt
        from crdt_tpu.net import SyncServer, sync_dense_over_tcp
        a = DenseCrdt("na", 64, value_width=32,
                      wall_clock=FakeClock(start=self.BASE))
        b = DenseCrdt("nb", 64, value_width=32,
                      wall_clock=FakeClock(start=self.BASE + 5))
        a.put_batch([3], [-33])
        with SyncServer(b) as server:
            sync_dense_over_tcp(a, server.host, server.port)
        assert b.get(3) == -33

    def test_push_dense_meta_without_binary_frame_is_bounded(self):
        # A peer announces push_dense and goes silent before the
        # binary frame: io_timeout (not the 300 s conn_deadline) must
        # reclaim the single-connection endpoint.
        import socket as socket_mod
        import time
        from crdt_tpu.net import SyncServer, send_frame, sync_dense_over_tcp
        b = self._dense("nb")
        with SyncServer(b, io_timeout=0.3) as server:
            with socket_mod.create_connection(
                    (server.host, server.port), timeout=10) as sock:
                sock.settimeout(5)
                send_frame(sock, {"op": "push_dense", "node_ids": ["x"],
                                  "meta": {"form": "split", "lanes": []}})
                t0 = time.monotonic()
                assert sock.recv(1) == b""     # dropped, no reply
                assert time.monotonic() - t0 < 2.0
            # the endpoint serves the next (well-behaved) peer
            a = self._dense("na")
            a.put_batch([1], [10])
            sync_dense_over_tcp(a, server.host, server.port)
        assert b.get(1) == 10

"""On-device segment-tree digests over dense store lanes.

Anti-entropy half of the delta-state design (docs/ANTIENTROPY.md):
watermark deltas (`pack_since`) assume a peer you've talked to before,
so a fresh or long-partitioned replica forces a full-store scan. A
Merkle-style digest tree lets two replicas localize divergence in
O(log n) round trips instead — exchange the root, walk only the
subtrees whose digests differ, ship the divergent slot ranges through
the zero-copy range pack.

The whole reduction runs ON DEVICE in one jit-cached program
(`_digest_tree_jit`): a per-slot 64-bit mix over the replicated lanes
(`lt`, `val`, `tomb`, optional sem tag — NOT `node`/`mod_lt`, which are
replica-local ordinals/bookkeeping and differ between converged
stores), a wrapping-sum fold into fixed-width leaves, then pairwise
order-sensitive combines up to the root. Leaves are padded to a power
of two with the all-empty digest (0) so equal stores always produce
equal trees regardless of slot-count rounding. The model layer caches
the fetched levels keyed on ``(clock, sem_version)`` exactly like the
pack cache, so an unchanged store recomputes (and dispatches) nothing.

The mix is splitmix64's finalizer — fast, avalanche-complete, and
expressible as u64 shifts/xors/multiplies the TPU vector units handle
natively. It is NOT cryptographic; anti-entropy digests defend against
divergence, not adversaries (same trust model as the wire itself).
Host code must never re-hash store lanes (crdtlint rule
``merkle-digest-host-hash``) — the digest is the device's job.
"""

from __future__ import annotations

import functools as _ft
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .dense import DenseStore
from ..obs import device as _obs_device

_obs_device.register("digest.digest_tree_device")

#: Slots folded into one leaf digest. The width trades walk traffic
#: against re-ship amplification: a divergent slot re-ships its whole
#: leaf, and under UNIFORMLY scattered divergence at rate p the
#: expected fraction of leaves hit is ``1-(1-p)^W`` (~``W*p`` for
#: small p), so wide leaves ship most of the store at 1% scatter
#: (W=64 -> 47%) while narrow ones stay proportional (W=8 -> 7.7%).
#: 8 keeps the walk within depth log2(n)-2 rounds and the bottom-level
#: probes ride the binary frame at 8 bytes/digest, so the extra depth
#: costs little (measured in BENCH_r08).
DEFAULT_LEAF_WIDTH = 8

#: Tree levels probed per round trip when the fetch side supports
#: batched (multi-level) probes. Each round speculatively requests the
#: DESCENDANTS of the whole current frontier for the next
#: ``PREFETCH_LEVELS - 1`` levels — at most ``(2^P - 1)`` digests per
#: frontier node, 8 bytes each — so a walk costs
#: ``ceil(depth / PREFETCH_LEVELS)`` round trips instead of ``depth``.
#: 3 trades ~7x the (tiny) digest bytes for a 3x round-trip cut, the
#: right direction on the high-RTT links cold joins cross.
PREFETCH_LEVELS = 3

#: Speculative expansion stops growing a batch past this many indices
#: per level: a wide frontier (heavy divergence) already amortizes its
#: round trips, and an unbounded 2^P fan-out on a big tree could make
#: one probe frame rival the payload it is trying to localize.
PREFETCH_MAX_BATCH = 512

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_MIX_A = np.uint64(0xBF58476D1CE4E5B9)
_MIX_B = np.uint64(0x94D049BB133111EB)
_TOMB_SALT = np.uint64(0xD6E8FEB86659FD93)
_SEM_SALT = np.uint64(0xFF51AFD7ED558CCD)
_NODE_SALT = np.uint64(0xC2B2AE3D27D4EB4F)


def _mix64(x):
    """splitmix64 finalizer — u64 shifts/xors/multiplies only, so the
    same expression runs under jit and on host numpy scalars."""
    x = (x ^ (x >> np.uint64(30))) * _MIX_A
    x = (x ^ (x >> np.uint64(27))) * _MIX_B
    return x ^ (x >> np.uint64(31))


def slot_digests(lt, val, tomb, occupied, sem=None, idx_offset=None):
    """Per-slot 64-bit digests over the REPLICATED lanes, zero where
    unoccupied. ``idx_offset`` shifts the mixed-in slot index so a
    shard can digest its local window against global positions
    (parallel/fanin.py)."""
    n = lt.shape[0]
    idx = jnp.arange(n, dtype=jnp.uint64)
    if idx_offset is not None:
        idx = idx + idx_offset
    h = _mix64(lt.astype(jnp.uint64) + _GOLDEN * (idx + np.uint64(1)))
    h = h ^ _mix64(val.astype(jnp.uint64) ^ _MIX_B)
    h = h ^ jnp.where(tomb, _TOMB_SALT, np.uint64(0))
    if sem is not None:
        h = h ^ _mix64(sem.astype(jnp.uint64) + _SEM_SALT)
    return jnp.where(occupied, _mix64(h), np.uint64(0))


def _pow2_at_least(n: int) -> int:
    p2 = 1
    while p2 < max(1, n):
        p2 *= 2
    return p2


def fold_leaves(digests, leaf_width: int):
    """Wrapping-sum fold of per-slot digests into leaf digests (slot
    position is already mixed into each digest, so the commutative sum
    loses nothing), slot-padded with zeros so a ragged tail leaf and
    an all-empty leaf digest identically (0 contribution). Emits
    ``ceil(n / leaf_width)`` leaves — NO power-of-two padding here, so
    per-shard folds concatenate into the exact global leaf row
    (`parallel.make_sharded_digest`)."""
    n = digests.shape[0]
    n_leaves = max(1, -(-n // leaf_width))
    pad = n_leaves * leaf_width - n
    if pad:
        digests = jnp.concatenate(
            [digests, jnp.zeros((pad,), jnp.uint64)])
    return jnp.sum(digests.reshape(n_leaves, leaf_width), axis=1)


def combine_level(children):
    """One interior level: order-sensitive pairwise combine."""
    left, right = children[0::2], children[1::2]
    return _mix64(left + _GOLDEN * right + _MIX_A)


def tree_levels_from_leaves(leaves) -> Tuple[jax.Array, ...]:
    """Pad the leaf row to a power of two with the all-empty digest
    (so equal stores always produce equal trees regardless of
    slot-count rounding), then build every interior level. Returns
    levels ROOT-FIRST (``levels[0]`` shape (1,), ``levels[-1]`` the
    padded leaves)."""
    n_leaves = _pow2_at_least(int(leaves.shape[0]))
    pad = n_leaves - int(leaves.shape[0])
    if pad:
        leaves = jnp.concatenate(
            [leaves, jnp.zeros((pad,), jnp.uint64)])
    levels = [leaves]
    while levels[-1].shape[0] > 1:
        levels.append(combine_level(levels[-1]))
    return tuple(reversed(levels))


def digest_levels_from_lanes(lt, val, tomb, occupied, sem=None,
                             leaf_width: int = DEFAULT_LEAF_WIDTH,
                             idx_offset=None) -> Tuple[jax.Array, ...]:
    """The full traceable reduction — per-slot mix -> leaf fold ->
    every interior combine — straight from store lanes. This is the
    composition `_digest_tree_jit` runs standalone AND the piece
    `ops.dense.compact_remap` fuses after its slot remap, so a
    compacted store leaves the dispatch with its digest tree already
    rebuilt (one program, no second dispatch)."""
    h = slot_digests(lt, val, tomb, occupied, sem=sem,
                     idx_offset=idx_offset)
    return tree_levels_from_leaves(fold_leaves(h, leaf_width))


@_ft.lru_cache(maxsize=None)
def _digest_tree_jit(leaf_width: int, has_sem: bool):
    """jit-cached digest reduction: per-slot mix -> leaf fold -> all
    interior combines in ONE program. Inputs are live store lanes read
    in place (a digest must not consume the store, so nothing is
    donated); the cache key mirrors the other kernel factories."""

    def step(lt, val, tomb, occupied, *sem):
        return digest_levels_from_lanes(
            lt, val, tomb, occupied, sem=sem[0] if has_sem else None,
            leaf_width=leaf_width)

    return jax.jit(step)


def digest_tree_device(store: DenseStore, sem=None,
                       leaf_width: int = DEFAULT_LEAF_WIDTH
                       ) -> Tuple[jax.Array, ...]:
    """Digest-tree levels (root-first) for a dense store, computed on
    device. ``sem`` is the optional per-slot semantics tag column."""
    args = (store.lt, store.val, store.tomb, store.occupied)
    with _obs_device.record("digest.digest_tree_device",
                            dim=store.lt.shape[0]):
        if sem is not None:
            return _digest_tree_jit(leaf_width, True)(*args, sem)
        return _digest_tree_jit(leaf_width, False)(*args)


class DigestTree(NamedTuple):
    """Host-side view of the fetched levels + walk geometry. Two trees
    are comparable only when ``n_slots`` and ``leaf_width`` agree —
    the wire walk checks geometry before descending."""

    n_slots: int
    leaf_width: int
    levels: Tuple[np.ndarray, ...]  # root-first; levels[-1] = leaves

    @property
    def depth(self) -> int:
        return len(self.levels)

    @property
    def root(self) -> int:
        return int(self.levels[0][0])

    @property
    def n_leaves(self) -> int:
        return int(self.levels[-1].shape[0])

    def values(self, level: int, idxs: Sequence[int]) -> List[int]:
        if not 0 <= level < self.depth:
            raise ValueError(f"digest level {level} out of range "
                             f"[0, {self.depth})")
        row = self.levels[level]
        out = []
        for i in idxs:
            i = int(i)
            if not 0 <= i < row.shape[0]:
                raise ValueError(f"digest index {i} out of range for "
                                 f"level {level} (width {row.shape[0]})")
            out.append(int(row[i]))
        return out

    def values_levels(self, groups: Sequence[Tuple[int, Sequence[int]]]
                      ) -> List[List[int]]:
        """Batched :meth:`values`: one result row per ``(level, idxs)``
        group — the in-process mirror of the wire's multi-level
        ``digest`` probe (``more`` groups), so tests and local walks
        exercise the same prefetch shape the socket path ships."""
        return [self.values(level, idxs) for level, idxs in groups]

    def same_geometry(self, n_slots: int, leaf_width: int,
                      depth: int) -> bool:
        return (self.n_slots == n_slots
                and self.leaf_width == leaf_width
                and self.depth == depth)

    def leaf_range(self, leaf_idx: int) -> Tuple[int, int]:
        lo = leaf_idx * self.leaf_width
        return lo, min(lo + self.leaf_width, self.n_slots)


def build_digest_tree(n_slots: int, leaf_width: int,
                      levels: Sequence[jax.Array]) -> DigestTree:
    """One ``device_get`` over every level -> host DigestTree."""
    host = jax.device_get(tuple(levels))
    return DigestTree(n_slots=int(n_slots), leaf_width=int(leaf_width),
                      levels=tuple(np.asarray(a) for a in host))


def walk_divergent_leaves(
        tree: DigestTree,
        fetch: Optional[Callable[[int, List[int]], Sequence[int]]],
        fetch_levels: Optional[
            Callable[[List[Tuple[int, List[int]]]],
                     Sequence[Sequence[int]]]] = None,
        prefetch: int = PREFETCH_LEVELS,
) -> Tuple[List[int], int, int]:
    """Top-down walk against a remote tree reachable only through a
    fetch callback. Two fetch shapes:

    - ``fetch(level, idxs) -> values`` — one level per call (one wire
      round trip on the socket path), so the whole walk is <= depth =
      log2(n_leaves)+1 rounds. The original shape; any peer speaking
      the single-level ``digest`` op supports it.
    - ``fetch_levels(groups) -> [values, ...]`` with ``groups`` a list
      of ``(level, idxs)`` pairs — frontier PREFETCH: each call probes
      the current frontier plus the speculative descendants of the
      whole frontier for the next ``prefetch - 1`` levels (capped at
      `PREFETCH_MAX_BATCH` indices per level), cutting the walk to
      ``ceil(depth / prefetch)`` round trips. The walk then descends
      through the prefetched levels locally: every next frontier is by
      construction a subset of the speculative request, so no
      mid-batch fetch is ever needed.

    Returns ``(divergent_leaf_idxs, rounds, values_fetched)`` — an
    empty leaf list means the trees (and therefore the replicated
    lanes) agree. ``values_fetched`` counts every digest requested,
    speculative ones included (8 bytes each on the wire).
    """
    if fetch_levels is None:
        if fetch is None:
            raise ValueError("walk needs fetch or fetch_levels")
        frontier = [0]
        rounds = 0
        fetched = 0
        for level in range(tree.depth):
            remote = fetch(level, frontier)
            rounds += 1
            fetched += len(frontier)
            local = tree.levels[level]
            diff = [i for i, v in zip(frontier, remote)
                    if int(local[i]) != int(v)]
            if not diff:
                return [], rounds, fetched
            if level == tree.depth - 1:
                return diff, rounds, fetched
            frontier = [c for i in diff for c in (2 * i, 2 * i + 1)]
        return [], rounds, fetched  # pragma: no cover — loop returns

    if prefetch < 1:
        raise ValueError(f"prefetch must be >= 1; got {prefetch}")
    frontier = [0]
    level = 0
    rounds = 0
    fetched = 0
    while level < tree.depth:
        groups: List[Tuple[int, List[int]]] = []
        idxs = list(frontier)
        for lvl in range(level, min(level + prefetch, tree.depth)):
            if groups and len(idxs) > PREFETCH_MAX_BATCH:
                break
            groups.append((lvl, idxs))
            if lvl + 1 < tree.depth:
                idxs = [c for i in idxs for c in (2 * i, 2 * i + 1)]
        results = fetch_levels(groups)
        rounds += 1
        fetched += sum(len(ix) for _, ix in groups)
        if len(results) != len(groups):
            raise ValueError(
                f"fetch_levels returned {len(results)} groups for "
                f"{len(groups)} requested")
        for (lvl, g_idxs), vals in zip(groups, results):
            if len(vals) != len(g_idxs):
                raise ValueError(
                    f"fetch_levels group {lvl} returned {len(vals)} "
                    f"values for {len(g_idxs)} indices")
            remote = {i: int(v) for i, v in zip(g_idxs, vals)}
            local = tree.levels[lvl]
            diff = [i for i in frontier if int(local[i]) != remote[i]]
            if not diff:
                return [], rounds, fetched
            if lvl == tree.depth - 1:
                return diff, rounds, fetched
            frontier = [c for i in diff for c in (2 * i, 2 * i + 1)]
        level = groups[-1][0] + 1
    return [], rounds, fetched  # pragma: no cover — loop returns


def coalesce_leaf_ranges(leaf_idxs: Sequence[int], leaf_width: int,
                         n_slots: int) -> Tuple[Tuple[int, int], ...]:
    """Divergent leaves -> minimal sorted ``(lo, hi)`` slot spans for
    the range pack (adjacent leaves merge into one span; the tail span
    clips to ``n_slots`` so padding leaves never widen the pack)."""
    spans: List[Tuple[int, int]] = []
    for leaf in sorted(set(int(i) for i in leaf_idxs)):
        lo = leaf * leaf_width
        hi = min(lo + leaf_width, n_slots)
        if lo >= n_slots or hi <= lo:
            continue  # pure padding leaf
        if spans and spans[-1][1] == lo:
            spans[-1] = (spans[-1][0], hi)
        else:
            spans.append((lo, hi))
    return tuple(spans)

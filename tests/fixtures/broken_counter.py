"""Deliberately BROKEN counter kernel — crdtlint self-test fixture.

A typed-lane bug class the semantics registry exists to catch: the
counter "join" below applies the remote value as an INCREMENT
(``2*local + remote``) instead of taking the per-lane max the real
`semantics.kernels` gcounter join uses. Increment application is not
a semilattice join — re-delivering the same delta changes the value
again (no idempotence) and the two merge orders disagree (no
commutativity) — so the seeded law search must find a counterexample
and print the violating input:

    python -m crdt_tpu.analysis --law-fixture tests/fixtures/broken_counter.py

The clock lanes are kept CORRECT on purpose (strict (lt, node) lex,
winner adoption): the breakage is confined to the value lattice,
proving the law harness sees typed-value bugs even when every clock
guard passes — exactly the blind spot a hand-written counter merge
would ship with.
"""

import jax
import jax.numpy as jnp

from crdt_tpu.analysis.lattice_laws import make_wire_join_target
from crdt_tpu.ops.dense import DenseStore, _NEG


@jax.jit
def skewed_counter_join_step(store: DenseStore, lt, node, val, tomb,
                             valid, stamp_lt, local_node):
    """Counter wire join with the max→increment bug planted."""
    lt = jnp.where(valid, lt, _NEG)
    node = node.astype(jnp.int32)
    val = val.astype(jnp.int64)
    remote_newer = ((lt > store.lt) |
                    ((lt == store.lt) & (node > store.node)))
    take = valid & (~store.occupied | remote_newer)
    both = valid & store.occupied
    # BUG: increment application instead of a per-lane max join —
    # 2*local + remote is neither commutative nor idempotent.
    joined = jnp.where(both, 2 * store.val + val,
                       jnp.where(take, val, store.val))
    win = take | (valid & (joined != store.val))
    new_store = DenseStore(
        lt=jnp.where(take, lt, store.lt),
        node=jnp.where(take, node, store.node),
        val=joined,
        mod_lt=jnp.where(win, stamp_lt, store.mod_lt),
        mod_node=jnp.where(win, local_node, store.mod_node),
        occupied=store.occupied | valid,
        tomb=jnp.where(take, tomb, store.tomb),
    )
    return new_store, win


LAW_TARGETS = [
    make_wire_join_target(skewed_counter_join_step,
                          "broken-counter-join",
                          notes="max→increment planted bug"),
]

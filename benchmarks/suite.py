"""Full benchmark suite — the five BASELINE.json configs.

Prints one JSON line per config (the driver's single-line contract is
`bench.py` at the repo root; this suite is the detailed harness).

Configs (BASELINE.json / BASELINE.md):
1. 2-replica LWW merge, 1k keys, int values — the ported
   example/crdt_example.dart shape, measured on the scalar oracle
   (the stand-in for the reference's single-thread Dart merge loop,
   crdt.dart:77-94) AND on the device path.
2. N-replica fan-in, 1M keys × {8, 64, 1024} replicas.
3. Tombstone-heavy merge (50% deletes, record.dart:17).
4. HLC tie-break stress (colliding logicalTimes; node-ordinal
   disambiguation, hlc.dart:158-161).
5. String/JSON payloads: variable-length values live in a host-side
   table; the device reduction carries table indices (SURVEY.md §7
   hard part 4). Measures the full wire path: JSON decode → merge.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root (bench.py helpers)

from bench import (_MILLIS, bench, bench_distinct, bench_e2e_1024,
                   bench_e2e_generator_only, result_dict)
from crdt_tpu import Hlc, MapCrdt, Record, TpuMapCrdt
from crdt_tpu.testing import FakeClock


def scalar_records(n_keys, node, value=None):
    h = lambda i: Hlc(_MILLIS + i % 997, i % 3, node)
    return {f"k{i}": Record(h(i), value(i) if value else i, h(i))
            for i in range(n_keys)}


def bench_example_oracle(n_keys=1000, repeats=5):
    """Config 1 on the scalar oracle — the single-thread comparison
    point (the reference publishes no numbers; this is its moral
    equivalent in-process)."""
    remote = scalar_records(n_keys, "remote")
    best = float("inf")
    for _ in range(repeats):
        crdt = MapCrdt("local", wall_clock=FakeClock(start=_MILLIS + 10_000))
        t0 = time.perf_counter()
        crdt.merge(dict(remote))
        best = min(best, time.perf_counter() - t0)
    return result_dict(
        f"oracle_2replica_{n_keys}key_int_merges_per_sec", n_keys, best,
        path="oracle-scalar-host")


def bench_example_device(n_keys=1000, repeats=5):
    """Config 1 on the drop-in TpuMapCrdt (host-shadow vectorized merge;
    reads are fetch-free, the device mirror syncs lazily)."""
    remote = scalar_records(n_keys, "remote")
    best = float("inf")
    for _ in range(repeats):
        crdt = TpuMapCrdt("local",
                          wall_clock=FakeClock(start=_MILLIS + 10_000))
        t0 = time.perf_counter()
        crdt.merge(dict(remote))
        crdt.get_record("k0")
        best = min(best, time.perf_counter() - t0)
    return result_dict(
        f"tpu_backend_2replica_{n_keys}key_int_merges_per_sec", n_keys,
        best, path="tpu_map_crdt-host-shadow")


def _bench_wire(dst_factory, metric: str, path: str, n_keys: int,
                repeats: int, sync_key=None):
    """Config 5 body: varlen-payload wire JSON decode + merge into the
    backend ``dst_factory`` builds; ``sync_key`` forces a device sync
    after the merge (device backends only)."""
    src = MapCrdt("remote", wall_clock=FakeClock(start=_MILLIS))
    src.put_all({f"key-{i}": {"s": "x" * (8 + i % 57), "i": i}
                 for i in range(n_keys)})
    wire = src.to_json()
    best = float("inf")
    for _ in range(repeats):
        dst = dst_factory()
        t0 = time.perf_counter()
        dst.merge_json(wire)
        if sync_key is not None:
            dst.get_record(sync_key)
        best = min(best, time.perf_counter() - t0)
    return result_dict(metric, n_keys, best, path=path)


def bench_payload_wire(n_keys=10_000, repeats=3):
    """Config 5: wire ingest into TpuMapCrdt — columnar decode (C batch
    HLC parse) + vectorized shadow-lane join, no Record/Hlc objects."""
    return _bench_wire(
        lambda: TpuMapCrdt("local", wall_clock=FakeClock(start=_MILLIS + 10)),
        f"wire_json_{n_keys}key_varlen_payload_merges_per_sec",
        "wire-json-columnar", n_keys, repeats, sync_key="key-0")


def bench_payload_wire_sqlite(n_keys=10_000, repeats=3):
    """Config 5 on the durable backend — what persistence costs: the
    same decode feeds per-record SQL upserts (plugin-pattern backend,
    README.md:39)."""
    from crdt_tpu import SqliteCrdt
    return _bench_wire(
        lambda: SqliteCrdt("local", wall_clock=FakeClock(start=_MILLIS + 10)),
        f"wire_json_sqlite_{n_keys}key_varlen_payload_merges_per_sec",
        "wire-json-sqlite-durable", n_keys, repeats)


def bench_payload_wire_watched(n_keys=1 << 20, repeats=1):
    """The watch contract under bulk merge (VERDICT r3 item 4): one
    active subscriber must not de-vectorize the wire ingest. Reports
    the watched/unwatched slowdown for (a) a key-filtered subscriber
    (the realistic watch shape — answered O(1) from the batch) and
    (b) a whole-store recording subscriber (buffer extended in one
    C-level pass)."""
    src = MapCrdt("remote", wall_clock=FakeClock(start=_MILLIS))
    src.put_all({f"key-{i}": {"s": "x" * (8 + i % 57), "i": i}
                 for i in range(n_keys)})
    wire = src.to_json()

    def run(subscribe):
        best = float("inf")
        for _ in range(repeats + 1):
            dst = TpuMapCrdt("local",
                             wall_clock=FakeClock(start=_MILLIS + 10))
            stream = subscribe(dst) if subscribe else None
            t0 = time.perf_counter()
            dst.merge_json(wire)
            best = min(best, time.perf_counter() - t0)
            if stream is not None:
                assert stream.events, "subscriber saw no events"
        return best

    base = run(None)
    keyed = run(lambda d: d.watch(key="key-7").record())
    recording = run(lambda d: d.watch().record())
    out = result_dict(
        f"wire_json_{n_keys}key_watched_keyed_merges_per_sec",
        n_keys, keyed, path="wire-json-columnar-watched")
    out["slowdown_vs_unwatched"] = round(keyed / base, 3)
    out2 = result_dict(
        f"wire_json_{n_keys}key_watched_recording_merges_per_sec",
        n_keys, recording, path="wire-json-columnar-watched")
    out2["slowdown_vs_unwatched"] = round(recording / base, 3)
    return out, out2


def bench_sqlite_upsert_floor(n_keys=10_000, repeats=5):
    """VERDICT r4 item 5: bare ``executemany(_UPSERT)`` of PRE-ENCODED
    rows into a fresh store — the durable-ingest floor. The full wire
    row can't beat this by construction; if (wire row) ≈ (floor) +
    (oracle-measured codec work), the residue really is sqlite's
    upsert, with a number attached."""
    from crdt_tpu import SqliteCrdt
    src = MapCrdt("remote", wall_clock=FakeClock(start=_MILLIS))
    src.put_all({f"key-{i}": {"s": "x" * (8 + i % 57), "i": i}
                 for i in range(n_keys)})
    wire = src.to_json()
    # The exact rows one real ingest upserts, pre-encoded once.
    probe = SqliteCrdt("local", wall_clock=FakeClock(start=_MILLIS + 10))
    probe.merge_json(wire)
    rows = probe._conn.execute(
        "SELECT * FROM records ORDER BY rowid").fetchall()
    probe.close()
    best = float("inf")
    for _ in range(repeats):
        dst = SqliteCrdt("local2", wall_clock=FakeClock(start=_MILLIS + 10))
        t0 = time.perf_counter()
        with dst._conn:
            dst._conn.executemany(dst._UPSERT, rows)
        best = min(best, time.perf_counter() - t0)
        dst.close()
    return result_dict(
        f"sqlite_upsert_floor_{n_keys}key_rows_per_sec", n_keys, best,
        path="sqlite-bare-executemany")


def _int_wire(n_keys):
    """Int-value wire payload over int keys — the shape every backend
    (including the dense models, whose payload lane is int64) can
    ingest, so the dense/TpuMap rows compare apples to apples."""
    import numpy as np
    from crdt_tpu import DenseCrdt
    src = DenseCrdt("remote", n_keys, wall_clock=FakeClock(start=_MILLIS))
    src.put_batch(np.arange(n_keys), np.arange(n_keys, dtype=np.int64) * 3)
    src.delete_batch(np.arange(0, n_keys, 11))
    return src.to_json()


def bench_payload_wire_dense(n_keys=1 << 20, repeats=1):
    """VERDICT r4 item 3: wire ingest into the dense flagship model —
    decode_columns → shared recv fold → O(k) sparse scatter, no
    Record/Hlc objects (models/dense_crdt.py `_merge_columns`)."""
    from crdt_tpu import DenseCrdt
    wire = _int_wire(n_keys)
    best = float("inf")
    for _ in range(repeats + 1):
        dst = DenseCrdt("local", n_keys,
                        wall_clock=FakeClock(start=_MILLIS + 10))
        t0 = time.perf_counter()
        dst.merge_json(wire)
        dst.get(0)    # device sync
        best = min(best, time.perf_counter() - t0)
    return result_dict(
        f"wire_json_dense_{n_keys}key_int_merges_per_sec", n_keys,
        best, path="wire-json-columnar-dense")


def bench_payload_wire_int_tpu_map(n_keys=1 << 20, repeats=1):
    """The same int wire payload into TpuMapCrdt — the comparator for
    the dense row (same decode, shadow-lane join instead of the dense
    scatter)."""
    wire = _int_wire(n_keys)
    best = float("inf")
    for _ in range(repeats + 1):
        dst = TpuMapCrdt("local", wall_clock=FakeClock(start=_MILLIS + 10))
        t0 = time.perf_counter()
        dst.merge_json(wire, key_decoder=int)
        best = min(best, time.perf_counter() - t0)
    return result_dict(
        f"wire_json_tpu_map_{n_keys}key_int_merges_per_sec", n_keys,
        best, path="wire-json-columnar")


def bench_gossip_interchange(n_keys=1 << 20, loops=12):
    """Round-5 interchange claim: single-row gossip merges through the
    pre-split kernel wire form (`merge_split`) vs wide-lane `merge` —
    the split path skips the per-merge int64 split and tile relayout.
    Run in ONE process back-to-back so proxy variance hits both."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from bench import make_changeset
    from crdt_tpu import DenseCrdt
    ids = [f"n{i}" for i in range(9)]
    w = DenseCrdt("w", n_keys, node_ids=ids,
                  wall_clock=FakeClock(start=_MILLIS))
    w.merge(make_changeset(128, n_keys, seed=0), ids)
    scs, sids = w.export_split_delta()
    wcs, wids = w.export_delta()
    jax.block_until_ready((scs, wcs))
    merges = int(jnp.sum(wcs.valid))
    peers = sorted(set(ids + ["rcv", "w"]))

    def run_once(fn):
        rcv = DenseCrdt("rcv", n_keys, node_ids=peers)
        t0 = _time.perf_counter()
        with rcv.pipelined():
            for _ in range(loops):
                fn(rcv)
        return (_time.perf_counter() - t0) / loops

    wide_fn = lambda r: r.merge(wcs, wids)          # noqa: E731
    split_fn = lambda r: r.merge_split(scs, sids)   # noqa: E731
    for fn in (wide_fn, split_fn):                  # warm both paths
        rcv = DenseCrdt("rcv", n_keys, node_ids=peers)
        with rcv.pipelined():
            fn(rcv)
            fn(rcv)
    # INTERLEAVED best-of: these windows are host-enqueue-bound on the
    # proxied chip and single-shot timings swing 2-3x with RPC jitter —
    # alternating reps hit both paths with the same weather.
    wide_s = split_s = float("inf")
    for _ in range(3):
        wide_s = min(wide_s, run_once(wide_fn))
        split_s = min(split_s, run_once(split_fn))
    out = result_dict(
        f"gossip_split_interchange_{n_keys}key_merges_per_sec", merges,
        split_s, path="merge_split-pre-tiled")
    out["wide_merge_per_sec"] = round(merges / wide_s, 1)
    out["speedup_vs_wide"] = round(wide_s / split_s, 2)
    return out


def bench_dense_to_json(n_slots=1 << 20, repeats=3):
    """1M-slot full wire export on the dense model (the interop contract
    crdt.dart:124-135 at dense scale): lane-direct C-codec formatting."""
    import numpy as np
    from crdt_tpu import DenseCrdt
    c = DenseCrdt("na", n_slots, wall_clock=FakeClock(start=_MILLIS))
    c.put_batch(np.arange(n_slots), np.arange(n_slots, dtype=np.int64))
    c.delete_batch(np.arange(0, n_slots, 7))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = c.to_json()
        best = min(best, time.perf_counter() - t0)
    assert out.startswith('{"0":')
    return result_dict(
        f"dense_to_json_{n_slots // 1000}k_records_per_sec", n_slots,
        best, path="lane-direct-c-codec")


def bench_tpu_map_to_json(n_keys=1 << 20, repeats=3):
    """1M-key full wire export on the drop-in general-key backend:
    lane-direct shadow-lane formatting (crdt.dart:124-135 interop at
    the scale the round-2 review called effectively unusable)."""
    c = TpuMapCrdt("na", wall_clock=FakeClock(start=_MILLIS))
    c.put_all({f"k{i}": i for i in range(n_keys)})
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = c.to_json()
        best = min(best, time.perf_counter() - t0)
    assert out.startswith('{"k0":')
    return result_dict(
        f"tpu_map_to_json_{n_keys // 1000}k_records_per_sec", n_keys,
        best, path="lane-direct-c-codec")


def bench_payload_wire_oracle(n_keys=10_000, repeats=5):
    """Config 5 on the host-only oracle — isolates the wire codec
    (native batch HLC parse + merge loop) from device round-trip
    latency, which dominates and jitters the TpuMapCrdt row on a
    remote-proxied chip."""
    return _bench_wire(
        lambda: MapCrdt("local", wall_clock=FakeClock(start=_MILLIS + 10)),
        f"wire_json_oracle_{n_keys}key_varlen_payload_merges_per_sec",
        "wire-json-oracle-host", n_keys, repeats)


def main():
    # Each config prints as it completes (a late failure must not lose
    # earlier results); forced-executor rows tag the metric name so the
    # xla/pallas pair never collides for consumers keyed on metric.
    def emit(make_result, tag=None):
        """Run one config, print its row(s), return the first row (or
        None on failure) so the e2e decomposition can reuse it."""
        try:
            r = make_result()
        except Exception as e:
            print(f"suite config failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            return None
        rows = r if isinstance(r, tuple) else (r,)
        for row in rows:
            if tag:
                row["metric"] += f"_{tag}"
            print(json.dumps(row), flush=True)
        return rows[0]

    emit(bench_example_oracle)
    emit(bench_example_device)
    # Small fan-ins chain more repeats so the one-off dispatch round
    # trip doesn't dominate (see bench.py protocol note).
    emit(lambda: bench(1 << 20, 8, 8, repeats=256))
    emit(lambda: bench(1 << 20, 64, 8, repeats=64))
    # Write-stream headline config on BOTH executors, side by side.
    emit(lambda: bench(1 << 20, 1024, 8, path="xla", repeats=64), tag="xla")
    emit(lambda: bench(1 << 20, 1024, 8, path="pallas", repeats=64),
         tag="pallas")
    # GENUINELY DISTINCT replica rows resident in HBM (the
    # BASELINE.md:26 north-star workload; every counted merge pays its
    # full HBM read — see bench.bench_distinct).
    emit(lambda: bench_distinct(1 << 20, 128, loops=48))
    # value-ref mode: int32 payloads/table indices (15 B vs 19 B per
    # merge) — the recommended shape for variable-length values.
    emit(lambda: bench_distinct(1 << 20, 128, loops=48,
                                value_width=32))
    # THE north-star workload end to end: 1M × 1024 DISTINCT replica
    # rows as 8 freshly device-generated batches (generation cost
    # included, disclosed in the protocol fields) — once through the
    # model API (pipelined window), once through the raw kernel; the
    # pair isolates model-API overhead at the headline scale.
    # Three-row protocol (VERDICT r4 item 6): model e2e, raw-kernel
    # e2e, generator-only — the last isolates input manufacture so the
    # e2e rows decompose; a derived merge-only row reports the
    # subtraction.
    e2e_rows = {
        "model": emit(lambda: bench_e2e_1024(1 << 20, through_model=True)),
        "kernel": emit(lambda: bench_e2e_1024(1 << 20,
                                              through_model=False)),
        "gen": emit(lambda: bench_e2e_generator_only(1 << 20)),
    }
    if e2e_rows["gen"] is not None:
        for which in ("model", "kernel"):
            if e2e_rows[which] is None:
                continue
            v_e2e = e2e_rows[which]["value"]
            v_gen = e2e_rows["gen"]["value"]
            if v_gen <= v_e2e:
                continue   # generation slower than e2e: noise, skip
            derived = result_dict(
                f"record_merges_per_sec_1048k_keys_x1024_distinct_"
                f"replicas_e2e_{which}_minus_generation",
                1, 1 / v_e2e - 1 / v_gen,
                # The generator row spends extra time in its consumer
                # reduces (which the e2e rows don't run), so the
                # subtraction slightly UNDERSTATES framework time —
                # treat as an upper bound on merge-side throughput.
                path="derived: 1/(1/e2e - 1/generator_only), upper bound")
            print(json.dumps(derived), flush=True)
    emit(lambda: bench(1 << 20, 1024, 8, config="tombstone", repeats=64))
    emit(lambda: bench(1 << 20, 1024, 8, config="tiebreak", repeats=64))
    emit(bench_payload_wire)
    emit(bench_payload_wire_oracle)
    emit(bench_payload_wire_sqlite)
    emit(bench_sqlite_upsert_floor)
    # 1M-key wire ingest: the drop-in backend vs the oracle at the
    # scale DenseCrdt stores actually run at.
    emit(lambda: bench_payload_wire(n_keys=1 << 20, repeats=1))
    emit(lambda: bench_payload_wire_oracle(n_keys=1 << 20, repeats=1))
    emit(bench_payload_wire_watched)
    # 1M-key INT wire ingest: dense flagship vs the drop-in backend on
    # the identical payload (VERDICT r4 item 3's "≥ TpuMapCrdt" bar).
    emit(bench_payload_wire_dense)
    emit(bench_payload_wire_int_tpu_map)
    emit(bench_gossip_interchange)
    emit(bench_dense_to_json)
    emit(bench_tpu_map_to_json)


if __name__ == "__main__":
    main()

"""Multi-device parallelism: replica fan-in and key-space sharding over
a `jax.sharding.Mesh`, with XLA collectives riding ICI (DCN across
slices). See `crdt_tpu.parallel.fanin` for the design and
`crdt_tpu.parallel.collective` for the pod-local group join."""

from .collective import (MEMBER_AXIS, CollectiveJoinResult,
                         make_collective_join, make_collective_mesh)
from .fanin import (KEY_AXIS, REPLICA_AXIS, SLICE_AXIS,
                    ShardedFaninResult, changeset_sharding,
                    make_fanin_mesh, make_multislice_fanin_mesh,
                    make_sharded_fanin, make_sharded_ingest,
                    make_sharded_pallas_fanin,
                    replica_extent, shard_changeset,
                    make_sharded_compact, make_sharded_digest,
                    shard_store,
                    sharded_delta_mask, sharded_max_logical_time,
                    store_sharding)

__all__ = [
    "KEY_AXIS", "MEMBER_AXIS", "REPLICA_AXIS", "SLICE_AXIS",
    "CollectiveJoinResult", "ShardedFaninResult",
    "changeset_sharding", "make_collective_join",
    "make_collective_mesh", "make_fanin_mesh",
    "make_multislice_fanin_mesh", "make_sharded_fanin",
    "make_sharded_ingest", "make_sharded_pallas_fanin",
    "replica_extent", "shard_changeset", "shard_store",
    "make_sharded_compact", "make_sharded_digest", "sharded_delta_mask",
    "sharded_max_logical_time", "store_sharding",
]

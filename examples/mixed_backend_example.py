"""Mixed-backend replication + checkpoint/resume walkthrough.

Three replicas on three different backends — the scalar oracle
(`MapCrdt`), the device-columnar drop-in (`TpuMapCrdt`), and the dense
array model (`DenseCrdt`) — converge over the reference JSON wire
format (crdt_json.dart:8-37 semantics), then the dense replica
snapshots and resumes with its writer attribution intact.

Run: ``python examples/mixed_backend_example.py``
"""

import os
import tempfile

from crdt_tpu import DenseCrdt, MapCrdt, TpuMapCrdt

N_SLOTS = 64

dense = DenseCrdt("node-dense", N_SLOTS)
oracle = MapCrdt("node-map")
device = TpuMapCrdt("node-tpu")

# Independent writes on each replica (int keys: dense slots).
dense.put_batch([0, 1], [100, 101])
oracle.put(2, 200)
device.put(3, 300)
device.delete(3)                      # tombstone propagates

# One gossip round over the JSON wire.
oracle.merge_json(dense.to_json(), key_decoder=int)
device.merge_json(oracle.to_json(), key_decoder=int)
dense.merge_json(device.to_json())
oracle.merge_json(dense.to_json(), key_decoder=int)

assert oracle.map == device.map == {0: 100, 1: 101, 2: 200}
assert [dense.get(s) for s in (0, 1, 2, 3)] == [100, 101, 200, None]
print("converged:", oracle.map)

# Watch a slot on the dense replica.
events = []
dense.watch().listen(events.append)
dense.put_batch([9], [900])
print("watch event:", events[-1])

# Snapshot the dense replica (lanes + node table) and resume.
path = os.path.join(tempfile.mkdtemp(), "dense.npz")
dense.save(path)
resumed = DenseCrdt.load("node-dense", path)
assert resumed.to_json() == dense.to_json()
assert resumed.record_map()[2].hlc.node_id == "node-map"  # attribution
print("resumed replica matches; record 2 written by",
      resumed.record_map()[2].hlc.node_id)

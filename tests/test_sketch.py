"""Quantile-sketch suite (PR 18, docs/OBSERVABILITY.md):

- merge laws as executable obligations — commutativity, associativity,
  and the relative-error bound preserved under 64-way merge
  permutations (seeded deterministic sweeps; a hypothesis variant
  rides along when the library is installed);
- serialization round-trips (JSON wire dict + compact binary frame)
  and their truncation/corruption rejections;
- registry / Prometheus-summary / evaluate_slo integration, including
  THE decision regression the sketch exists for: a true p99 of 16 ms
  breaches a 14.6 ms envelope through the sketch while the old
  histogram-boundary probe reads healthy;
- mixed-version ``metrics`` wire negotiation in both directions, with
  the pre-sketch reply byte-identical, plus a FaultProxy mid-hello
  truncate;
- the SLO flight recorder: capture/throttle/capacity, the three
  trigger edges (SLO flip, lease fence, lock-order violation), the
  ``debug_dump`` wire op and the ``dump`` CLI.
"""

import io
import json
import math
import random
import socket
import threading
import time

import pytest

from crdt_tpu.obs.sketch import (QuantileSketch, merge_sketches,
                                 sketch_from_sample, sketch_quantile)

pytestmark = pytest.mark.sketch

ALPHA = 0.01
# The guarantee is alpha on the bucket midpoint; 1.5x leaves slack for
# the sample's own discreteness without ever excusing a wrong bucket.
TOL = ALPHA * 1.5


@pytest.fixture(autouse=True)
def _clean_recorder():
    """The flight recorder is process-global and throttles per kind;
    every test starts and ends with an empty, unthrottled one."""
    from crdt_tpu.obs.recorder import default_recorder
    rec = default_recorder()
    rec.clear()
    yield
    rec.clear()


def _lognormal_sample(seed, n=8000, scale=0.002):
    rng = random.Random(seed)
    return [scale * rng.lognormvariate(0.0, 0.8) for _ in range(n)]


def _true_quantile(sorted_sample, q):
    return sorted_sample[int(q * (len(sorted_sample) - 1))]


def _fill(values, **kw):
    sk = QuantileSketch(relative_accuracy=ALPHA, **kw)
    for v in values:
        sk.record(v)
    return sk


# --------------------------------------------------- core error bound

def test_relative_error_bound_on_known_distribution():
    sample = _lognormal_sample(11)
    sk = _fill(sample)
    ordered = sorted(sample)
    for q in (0.01, 0.25, 0.5, 0.9, 0.99, 0.999):
        true = _true_quantile(ordered, q)
        got = sk.quantile(q)
        assert abs(got - true) / true <= TOL, (q, true, got)


def test_empty_and_zero_semantics():
    sk = QuantileSketch()
    assert sk.quantile(0.5) is None          # unmeasured != zero
    sk.record(0.0)
    sk.record(-1.0)                           # backwards clock: zeros
    assert sk.zeros == 2 and sk.count == 2
    assert sk.quantile(0.5) == 0.0
    with pytest.raises(ValueError):
        sk.quantile(1.5)
    assert merge_sketches([]) is None


# ----------------------------------------------------------- laws

def _state(sk):
    """to_dict minus ``sum``: the merge-order-invariant state. ``sum``
    is a float accumulator — commutative but (like all float
    addition) associative only to the last ulp, so law tests pin it
    separately with an approx compare."""
    d = sk.to_dict()
    return {k: v for k, v in d.items() if k != "sum"}, d["sum"]


def test_merge_commutative_exact():
    a = _fill(_lognormal_sample(1, n=2000))
    b = _fill(_lognormal_sample(2, n=2000))
    ab = a.copy().merge(b)
    ba = b.copy().merge(a)
    assert ab.to_dict() == ba.to_dict()
    # and the inputs were not mutated by merge_sketches
    pooled = merge_sketches([a, b])
    assert pooled.to_dict() == ab.to_dict()
    assert a.count == 2000 and b.count == 2000


def test_merge_associative_exact():
    a = _fill(_lognormal_sample(3, n=1500))
    b = _fill(_lognormal_sample(4, n=1500))
    c = _fill(_lognormal_sample(5, n=1500))
    left_state, left_sum = _state(a.copy().merge(b).merge(c))
    right_state, right_sum = _state(a.copy().merge(b.copy().merge(c)))
    assert left_state == right_state
    assert left_sum == pytest.approx(right_sum, rel=1e-12)


def test_64_way_merge_permutations_error_preserving():
    """64 per-replica shards merged in shuffled orders: every order
    yields the identical sketch, and the merged quantiles still honor
    the relative-error bound against the pooled sample."""
    rng = random.Random(64)
    shards = []
    pooled = []
    for i in range(64):
        vals = _lognormal_sample(100 + i, n=250)
        pooled.extend(vals)
        shards.append(_fill(vals))
    ref_state, ref_sum = _state(merge_sketches(shards))
    for _ in range(10):
        order = list(range(64))
        rng.shuffle(order)
        state, total = _state(merge_sketches([shards[i] for i in order]))
        assert state == ref_state
        assert total == pytest.approx(ref_sum, rel=1e-12)
    ordered = sorted(pooled)
    merged = merge_sketches(shards)
    assert merged.count == len(pooled)
    for q in (0.5, 0.9, 0.99):
        true = _true_quantile(ordered, q)
        got = merged.quantile(q)
        assert abs(got - true) / true <= TOL, (q, true, got)


def test_collapse_preserves_upper_quantiles():
    """A tiny max_bins forces the collapsing tail: accuracy is
    sacrificed at the BOTTOM of the distribution only — the p90/p99
    the SLO gates read stay within the bound, and low quantiles are
    only ever overestimated (folded upward), never silently under."""
    sample = _lognormal_sample(7, n=6000, scale=0.002)
    sk = _fill(sample, max_bins=128)       # ~200 natural buckets
    assert len(sk.bins) <= 128
    ordered = sorted(sample)
    for q in (0.9, 0.99):
        true = _true_quantile(ordered, q)
        got = sk.quantile(q)
        assert abs(got - true) / true <= TOL, (q, true, got)
    low_true = _true_quantile(ordered, 0.01)
    assert sk.quantile(0.01) >= low_true * (1.0 - ALPHA)
    # merging two collapsed sketches keeps the bound too
    other = _fill(_lognormal_sample(8, n=6000), max_bins=128)
    both = merge_sketches([sk, other])
    pooled = sorted(sample + _lognormal_sample(8, n=6000))
    true99 = _true_quantile(pooled, 0.99)
    assert abs(both.quantile(0.99) - true99) / true99 <= TOL


def test_gamma_mismatch_merge_rejected():
    a = QuantileSketch(relative_accuracy=0.01)
    b = QuantileSketch(relative_accuracy=0.02)
    b.record(1.0)
    with pytest.raises(ValueError, match="relative"):
        a.merge(b)


def test_merge_laws_hypothesis_variant():
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=50, deadline=None)
    @given(st.lists(st.floats(min_value=1e-6, max_value=1e3,
                              allow_nan=False, allow_infinity=False),
                    min_size=1, max_size=64),
           st.lists(st.floats(min_value=1e-6, max_value=1e3,
                              allow_nan=False, allow_infinity=False),
                    min_size=1, max_size=64))
    def commutes(xs, ys):
        a, b = _fill(xs), _fill(ys)
        assert a.copy().merge(b).to_dict() \
            == b.copy().merge(a).to_dict()

    commutes()


# -------------------------------------------------- serialization

def test_dict_and_bytes_roundtrips_exact():
    sk = _fill(_lognormal_sample(9, n=3000))
    sk.record(0.0)
    via_dict = QuantileSketch.from_dict(sk.to_dict())
    assert via_dict.to_dict() == sk.to_dict()
    assert via_dict.quantile(0.99) == sk.quantile(0.99)
    via_bytes = QuantileSketch.from_bytes(sk.to_bytes())
    assert via_bytes.to_dict() == sk.to_dict()
    # the JSON wire shape survives an actual dumps/loads
    wire = json.loads(json.dumps(sk.to_dict()))
    assert QuantileSketch.from_dict(wire).to_dict() == sk.to_dict()


def test_bytes_rejects_truncation_and_bad_magic():
    blob = _fill(_lognormal_sample(10, n=500)).to_bytes()
    with pytest.raises(ValueError, match="truncated"):
        QuantileSketch.from_bytes(blob[:10])
    with pytest.raises(ValueError, match="truncated"):
        QuantileSketch.from_bytes(blob[:-3])
    with pytest.raises(ValueError, match="magic"):
        QuantileSketch.from_bytes(b"NOPE" + blob[4:])


def test_sample_helpers_degrade_to_none():
    assert sketch_from_sample("not a dict") is None
    assert sketch_from_sample({"labels": {}, "count": 3}) is None
    assert sketch_quantile([{"bogus": 1}], 0.99) is None
    sk = _fill([0.001, 0.002, 0.004])
    entry = {"labels": {"node": "a"}, "count": sk.count,
             "sum": sk.sum, "sketch": sk.to_dict()}
    assert sketch_quantile([entry], 0.5) == sk.quantile(0.5)


# ------------------------------------- registry / render / evaluate_slo

def test_registry_sketch_snapshot_order_and_prometheus_summary():
    from crdt_tpu.obs.registry import default_registry
    from crdt_tpu.obs.render import render_prometheus
    reg = default_registry()
    sk = reg.sketch("crdt_tpu_test_latency_seconds_sketch",
                    "test latencies")
    for v in (0.004, 0.008, 0.016):
        sk.observe(v, node="t")
    snap = reg.snapshot()
    # sketches sit BEFORE stats so a pre-sketch session's pop()
    # restores the legacy key order byte for byte
    assert list(snap.keys()) == ["counters", "gauges", "histograms",
                                 "sketches", "stats"]
    assert "crdt_tpu_test_latency_seconds_sketch" in snap["sketches"]
    prom = render_prometheus(snap)
    assert "# TYPE crdt_tpu_test_latency_seconds_sketch summary" \
        in prom
    assert 'quantile="0.99"' in prom
    assert "crdt_tpu_test_latency_seconds_sketch_count" in prom


def _slo_snapshots(latency_s, n=400, sketches=True):
    """One instance's snapshot with the serve ack histogram and (when
    asked) its sketch twin populated at a constant latency."""
    from crdt_tpu.obs.fleet import ACK_HIST_NAME, ACK_SKETCH_NAME
    from crdt_tpu.obs.registry import Histogram, Sketch
    h = Histogram(ACK_HIST_NAME)
    s = Sketch(ACK_SKETCH_NAME)
    for _ in range(n):
        h.observe(latency_s, node="srv")
        s.observe(latency_s, node="srv")
    snap = {"counters": {}, "gauges": {},
            "histograms": {ACK_HIST_NAME: h.samples()},
            "sketches": {ACK_SKETCH_NAME: s.samples()},
            "stats": {}}
    if not sketches:
        snap.pop("sketches")
    return {"srv": snap}


def test_slo_decision_regression_sketch_vs_histogram_boundary():
    """THE regression the sketch exists for (ISSUE 18 acceptance): a
    true p99 of 16 ms against the 14.6 ms envelope.

    The old controller could only gate the log2 histogram at the
    31.25 ms bucket boundary (a 14.6 ms histogram gate reads the
    15.625 ms ceiling as breached forever and flaps) — and at that
    boundary a 16 ms fleet reads HEALTHY. The sketch-sourced check
    flags the breach at the exact envelope; a 13 ms fleet stays green
    under both."""
    from crdt_tpu.obs.fleet import evaluate_slo
    # 16 ms: breach at 14.6 through the sketch...
    slo = evaluate_slo(_slo_snapshots(0.016), ack_p99_budget_s=0.0146)
    ack = slo["checks"]["ack_p99_s"]
    assert ack["source"] == "sketch"
    assert ack["ok"] is False
    assert abs(ack["value"] - 0.016) <= 0.016 * TOL
    # ...while the boundary probe a histogram fleet was stuck with
    # reads the same fleet as healthy (ceiling 31.25 ms gate):
    old = evaluate_slo(_slo_snapshots(0.016, sketches=False),
                       ack_p99_budget_s=0.0313)
    old_ack = old["checks"]["ack_p99_s"]
    assert old_ack["source"] == "histogram_ceiling"
    assert old_ack["ok"] is True            # the miss, demonstrated
    # 13 ms: green both ways
    assert evaluate_slo(_slo_snapshots(0.013),
                        ack_p99_budget_s=0.0146)[
        "checks"]["ack_p99_s"]["ok"] is True
    assert evaluate_slo(_slo_snapshots(0.013, sketches=False),
                        ack_p99_budget_s=0.0313)[
        "checks"]["ack_p99_s"]["ok"] is True


def test_histogram_fallback_is_three_valued():
    """Pre-sketch fleets degrade HONESTLY: ceiling within budget
    proves a pass, bucket floor above budget proves a breach, and the
    ambiguous middle is unmeasured (None) — never a silent pass."""
    from crdt_tpu.obs.fleet import evaluate_slo
    def ack(latency_s, budget):
        return evaluate_slo(_slo_snapshots(latency_s, sketches=False),
                            ack_p99_budget_s=budget)[
            "checks"]["ack_p99_s"]
    # ceiling 15.625 ms <= 20 ms budget: provable pass
    assert ack(0.013, 0.020)["ok"] is True
    # ceiling 31.25 ms, floor 15.625 ms > 10 ms budget: provable breach
    assert ack(0.016, 0.010)["ok"] is False
    # ceiling 15.625 ms vs 14.6 ms budget: floor 7.8 ms is under,
    # ceiling is over — unmeasured
    assert ack(0.013, 0.0146)["ok"] is None


def test_fleet_sketch_merges_replicas():
    from crdt_tpu.obs.fleet import fleet_sketch
    fast = _slo_snapshots(0.004)["srv"]
    slow = _slo_snapshots(0.016)["srv"]
    merged = fleet_sketch({"a": fast, "b": slow})
    # union of 400 fast + 400 slow samples: p99 sits in the slow mass
    assert abs(merged.quantile(0.99) - 0.016) <= 0.016 * TOL
    assert fleet_sketch({"a": {"histograms": {}}}) is None


# ------------------------------------------------- mixed-version wire

def _raw_metrics_reply(host, port):
    """One pre-sketch-generation poll: bare metrics frame, NO hello —
    returns the reply's raw body bytes plus the decoded snapshot."""
    from crdt_tpu.net import recv_bytes_frame, send_frame
    with socket.create_connection((host, port), timeout=10) as sock:
        sock.settimeout(10)
        send_frame(sock, {"op": "metrics"})
        body = recv_bytes_frame(sock,
                                deadline=time.monotonic() + 10)
        send_frame(sock, {"op": "bye"})
    return body, json.loads(body)["metrics"]


def test_metrics_op_mixed_version_both_directions():
    """Old poller vs new server: the reply carries no sketch section
    and keeps the exact pre-sketch registry key order (the stripped
    dict re-serializes to the bytes a pre-sketch server produced).
    New poller vs new server: the negotiated session ships the
    quantile payloads."""
    from crdt_tpu import DenseCrdt, ServeTier, fetch_metrics
    from crdt_tpu.net import recv_frame, send_frame
    crdt = DenseCrdt("sk-mix", n_slots=64)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        with socket.create_connection((tier.host, tier.port),
                                      timeout=10) as sock:
            sock.settimeout(10)
            send_frame(sock, {"op": "put", "slot": 1, "value": 7})
            assert recv_frame(
                sock, deadline=time.monotonic() + 10) == {"ok": True}
            send_frame(sock, {"op": "bye"})
        # old direction: bare frame, no hello
        body, snap_old = _raw_metrics_reply(tier.host, tier.port)
        assert b'"sketches"' not in body
        reg_keys = [k for k in snap_old
                    if k in ("counters", "gauges", "histograms",
                             "sketches", "stats")]
        assert reg_keys == ["counters", "gauges", "histograms",
                            "stats"]
        # new direction: negotiated sketch cap
        snap_new = fetch_metrics(tier.host, tier.port)
        assert "sketches" in snap_new
        sketches = snap_new["sketches"]
        assert "crdt_tpu_serve_ack_seconds_sketch" in sketches
        p99 = sketch_quantile(
            sketches["crdt_tpu_serve_ack_seconds_sketch"], 0.99)
        assert p99 is not None and p99 > 0.0
        # opting out reproduces the legacy payload through the
        # public helper too
        assert "sketches" not in fetch_metrics(
            tier.host, tier.port, sketches=False)


def test_fetch_metrics_against_pre_hello_server():
    """New poller vs OLD server: the legacy peer answers the hello
    with unknown_op and hangs up; the poll falls back to the bare
    legacy frame on a fresh socket and still returns the snapshot."""
    from crdt_tpu.net import recv_frame, send_frame, fetch_metrics
    snapshot = {"counters": {}, "gauges": {}, "histograms": {},
                "stats": {}, "node": {"node_id": "legacy"}}
    lsock = socket.create_server(("127.0.0.1", 0))
    lsock.settimeout(0.2)
    host, port = lsock.getsockname()[:2]
    stop = threading.Event()
    hellos = []

    def legacy():
        while not stop.is_set():
            try:
                conn, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with conn:
                conn.settimeout(5)
                try:
                    msg = recv_frame(
                        conn, deadline=time.monotonic() + 5)
                except (OSError, ValueError):
                    continue
                if not isinstance(msg, dict):
                    continue
                if msg.get("op") == "hello":
                    # the pre-hello generation: reject and hang up
                    hellos.append(msg)
                    send_frame(conn, {"code": "unknown_op",
                                      "error": "unknown op 'hello'"})
                    continue
                if msg.get("op") == "metrics":
                    send_frame(conn, {"metrics": snapshot})
                    try:  # drain the bye before closing
                        recv_frame(conn,
                                   deadline=time.monotonic() + 5)
                    except (OSError, ValueError):
                        pass

    t = threading.Thread(target=legacy, daemon=True,
                         name="legacy-metrics-server")
    t.start()
    try:
        snap = fetch_metrics(host, port, timeout=5)
    finally:
        stop.set()
        t.join(timeout=10)
        lsock.close()
    assert snap["node"]["node_id"] == "legacy"
    assert "sketches" not in snap
    # the new poller did try to negotiate first
    assert hellos and "sketch" in hellos[0].get("caps", [])


def test_fault_proxy_mid_hello_truncate_degrades_cleanly():
    """A hello truncated mid-frame is indistinguishable from a
    pre-hello hangup: the poll retries bare on a fresh socket and
    degrades to the sketchless legacy snapshot; the server session
    survives and a direct negotiated poll still ships sketches."""
    from crdt_tpu import DenseCrdt, ServeTier, fetch_metrics
    from crdt_tpu.testing_faults import FaultProxy, ScriptedSchedule
    crdt = DenseCrdt("sk-fault", n_slots=64)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        proxy = FaultProxy(
            tier.host, tier.port,
            ScriptedSchedule([{"kind": "truncate", "after": 9}]))
        proxy.start()
        try:
            snap = fetch_metrics(proxy.host, proxy.port, timeout=10)
        finally:
            proxy.stop()
        assert proxy.counters.get("truncate", 0) >= 1
        assert "sketches" not in snap          # degraded, not broken
        assert "counters" in snap
        # the tier is unharmed and still negotiates with the next poll
        assert "sketches" in fetch_metrics(tier.host, tier.port)


# ---------------------------------------------------- flight recorder

def test_recorder_capture_throttle_capacity_and_sources():
    from crdt_tpu.obs.recorder import FlightRecorder
    from crdt_tpu.obs.registry import default_registry
    reg = default_registry()
    reg.sketch("crdt_tpu_rec_test_sketch").observe(0.005, node="r")
    rec = FlightRecorder(capacity=2, throttle_s=60.0)
    source = lambda: {"lag": {"a": 0.1}}   # strong ref: weakly held
    rec.attach_source(source)
    b1 = rec.trigger("slo_failing", {"why": "test"})
    assert b1 is not None and b1["kind"] == "slo_failing"
    assert b1["context"] == {"why": "test"}
    assert "crdt_tpu_rec_test_sketch" in b1["sketches"]
    assert b1["sources"] == [{"lag": {"a": 0.1}}]
    # same-kind storm throttled; distinct kinds are not
    assert rec.trigger("slo_failing") is None
    assert rec.trigger("lease_fence") is not None
    assert rec.trigger("lock_order_violation") is not None
    kinds = [b["kind"] for b in rec.bundles()]
    assert kinds == ["lease_fence", "lock_order_violation"]  # cap 2
    seqs = [b["seq"] for b in rec.bundles()]
    assert seqs == sorted(seqs)
    rec.clear()
    assert rec.bundles() == []
    assert rec.trigger("slo_failing") is not None  # throttle reset


def test_recorder_dead_source_is_pruned_not_fatal():
    from crdt_tpu.obs.recorder import FlightRecorder

    class _Node:
        def extra(self):
            return {"routing_epoch": 4}

    rec = FlightRecorder(throttle_s=0.0)
    node = _Node()
    rec.attach_source(node.extra)
    assert rec.trigger("slo_failing")["sources"] \
        == [{"routing_epoch": 4}]
    del node
    import gc
    gc.collect()
    b = rec.trigger("slo_failing")
    assert b is not None and "sources" not in b


def test_autoscaler_slo_flip_edge_detects():
    """The autoscaler triggers the recorder on the ok->failing EDGE,
    not on every failing tick."""
    from crdt_tpu.autoscale import Autoscaler
    from crdt_tpu.obs.recorder import default_recorder

    class _FedStub:
        table = None
        tiers = ()
        groups = ()

    rec = default_recorder()
    rec.throttle_s, saved = 0.0, rec.throttle_s
    try:
        verdicts = [{"ok": False}, {"ok": False}, {"ok": True},
                    {"ok": False}]
        it = iter(verdicts)
        a = Autoscaler(fed=_FedStub(), slo_probe=lambda: next(it))
        for _ in verdicts:
            a.observe()
        kinds = [b["kind"] for b in rec.bundles()]
        assert kinds == ["slo_failing", "slo_failing"]  # two edges
        assert rec.bundles()[0]["context"]["slo"] == {"ok": False}
    finally:
        rec.throttle_s = saved


def test_lease_fence_triggers_recorder_and_busy():
    """A write landing after the primary's lease lapsed is fenced
    with the retryable busy code AND captured as an incident."""
    from crdt_tpu import DenseCrdt, ServeTier
    from crdt_tpu.net import recv_frame, send_frame
    from crdt_tpu.obs.recorder import default_recorder
    crdt = DenseCrdt("sk-fence", n_slots=64)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        assert tier._grant_lease({"holder": "mon", "ttl_ms": 0.0,
                                  "epoch": 1}) is None
        time.sleep(0.01)                      # let the lease lapse
        with socket.create_connection((tier.host, tier.port),
                                      timeout=10) as sock:
            sock.settimeout(10)
            send_frame(sock, {"op": "put", "slot": 2, "value": 9})
            reply = recv_frame(sock, deadline=time.monotonic() + 10)
            send_frame(sock, {"op": "bye"})
    assert isinstance(reply, dict) and reply.get("ok") is not True
    assert reply.get("code") == "busy"
    bundles = [b for b in default_recorder().bundles()
               if b["kind"] == "lease_fence"]
    assert bundles
    assert bundles[0]["context"]["node"] == "sk-fence"
    assert bundles[0]["context"]["writes_fenced"] >= 1


def test_lock_order_violation_triggers_recorder(monkeypatch):
    monkeypatch.setenv("CRDT_TPU_SANITIZE", "1")
    from crdt_tpu.analysis.concurrency import make_lock
    from crdt_tpu.obs.recorder import default_recorder

    a = make_lock("SkRec.a", 10)
    b = make_lock("SkRec.b", 20)

    def inverted():
        with b:
            with a:               # rank 10 while holding rank 20
                pass

    t = threading.Thread(target=inverted, name="sk-inv")
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    bundles = [x for x in default_recorder().bundles()
               if x["kind"] == "lock_order_violation"]
    assert bundles
    ctx = bundles[0]["context"]
    assert ctx["held"] == "SkRec.b"
    assert ctx["acquiring"] == "SkRec.a"
    assert ctx["thread"] == "sk-inv"


def test_debug_dump_op_and_cli():
    """Bundles fetch over the wire — sketch payloads only on
    negotiated sessions — and render through the dump CLI."""
    from crdt_tpu import DenseCrdt, ServeTier
    from crdt_tpu.net import fetch_debug_dump, recv_frame, send_frame
    from crdt_tpu.obs.cli import main as obs_main
    from crdt_tpu.obs.recorder import default_recorder
    from crdt_tpu.obs.registry import default_registry
    default_registry().sketch(
        "crdt_tpu_dump_test_sketch").observe(0.003, node="d")
    default_recorder().trigger("slo_failing", {"why": "dump-test"})
    crdt = DenseCrdt("sk-dump", n_slots=64)
    with ServeTier(crdt, flush_interval=0.002) as tier:
        bundles = fetch_debug_dump(tier.host, tier.port)
        assert bundles and bundles[0]["kind"] == "slo_failing"
        assert "sketches" in bundles[0]
        # a pre-sketch session gets the bundles stripped of sketch
        # payloads, never a new section it cannot parse
        with socket.create_connection((tier.host, tier.port),
                                      timeout=10) as sock:
            sock.settimeout(10)
            send_frame(sock, {"op": "debug_dump"})
            plain = recv_frame(sock, deadline=time.monotonic() + 10)
            send_frame(sock, {"op": "bye"})
        assert plain["ok"] is True
        assert all("sketches" not in b for b in plain["bundles"])
        out = io.StringIO()
        rc = obs_main(["dump", f"{tier.host}:{tier.port}"], out=out)
        assert rc == 0
        text = out.getvalue()
        assert "bundle #" in text and "slo_failing" in text
        out_json = io.StringIO()
        assert obs_main(["dump", "--json",
                         f"{tier.host}:{tier.port}"],
                        out=out_json) == 0
        assert json.loads(
            out_json.getvalue().splitlines()[0])["kind"] \
            == "slo_failing"
    # empty-recorder path
    default_recorder().clear()
    with ServeTier(DenseCrdt("sk-dump2", n_slots=64)) as tier2:
        out2 = io.StringIO()
        assert obs_main(["dump", f"{tier2.host}:{tier2.port}"],
                        out=out2) == 0
        assert "no bundles recorded" in out2.getvalue()

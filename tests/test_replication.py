"""Replica-group robustness (docs/REPLICATION.md): write-concern
acks, health-checked failover, zero-loss crash recovery.

The acceptance test kills the live primary three times in a row under
a sustained client write storm and proves the contract the whole
subsystem exists for: a write the client saw acked is NEVER lost, the
routing epoch advances on every failover, and a killed member rejoins
as a convergent follower. The soak (-m soak) replays the same chaos
with every wire the group uses routed through a `FaultProxy`.
"""

import threading
import time

import pytest

from crdt_tpu import DenseCrdt, FederatedClient
from crdt_tpu.net import SyncServer
from crdt_tpu.replication import ReplicaGroup, _HbClient
from crdt_tpu.testing_faults import FaultProxy, FaultSchedule, \
    abrupt_kill

# Tight but CI-safe chaos timings: detection in ~3 beats, promote in
# milliseconds, client retry budget (~2 s) comfortably above both.
FAST = dict(flush_interval=0.002, heartbeat_interval=0.02,
            heartbeat_timeout=0.15, lease_misses=3)


def _wait(pred, timeout=10.0, interval=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


class _Storm:
    """Background client write storm over disjoint slots: monotone
    values, one (slot -> last acked value) ledger. Write failures are
    retried forever — only an ACKED write enters the ledger, which is
    exactly the set failover must not lose."""

    def __init__(self, seeds, writers=3, slots_per_writer=4,
                 rate_hz=100.0):
        self.seeds = list(seeds)
        self.writers = writers
        self.slots_per_writer = slots_per_writer
        self.rate_hz = rate_hz
        self.lock = threading.Lock()
        self.last_acked = {}
        self.acked = 0
        self.errors = []
        self._stop = threading.Event()
        self._threads = []

    def _run(self, w):
        cli = FederatedClient(self.seeds, timeout=5.0)
        my = [w * self.slots_per_writer + j
              for j in range(self.slots_per_writer)]
        i = 0
        try:
            while not self._stop.is_set():
                slot = my[i % len(my)]
                val = i + 1
                try:
                    cli.put(slot, val)
                except (ConnectionError, ValueError):
                    time.sleep(0.02)
                    continue
                with self.lock:
                    self.last_acked[slot] = val
                    self.acked += 1
                i += 1
                time.sleep(1.0 / self.rate_hz)
        except Exception as exc:  # pragma: no cover - asserted empty
            self.errors.append(f"writer{w}: {exc!r}")
        finally:
            cli.close()

    def __enter__(self):
        self._threads = [
            threading.Thread(target=self._run, args=(w,), daemon=True)
            for w in range(self.writers)]
        for t in self._threads:
            t.start()
        _wait(lambda: self.acked >= self.writers,
              what="storm first acks")
        return self

    def __exit__(self, *exc):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10.0)

    def checkpoint(self):
        with self.lock:
            return dict(self.last_acked)


def _assert_no_acked_loss(seeds, checkpoint):
    cli = FederatedClient(seeds, timeout=5.0)
    try:
        lost = {slot: (val, cli.get(slot))
                for slot, val in checkpoint.items()
                if cli.get(slot) is None or int(cli.get(slot)) < val}
        assert not lost, f"acked writes lost: {lost}"
    finally:
        cli.close()


def _wait_converged(group, seeds, nudge_slot, timeout=10.0):
    """All live replicas agree on one digest root. Nudge writes
    re-arm the flush tick so the replicator ships every follower to
    head after the storm stops."""
    cli = FederatedClient(seeds, timeout=5.0)
    bump = 0
    try:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            bump += 1
            cli.put(nudge_slot, bump)
            time.sleep(0.05)
            roots = []
            for m in group.members:
                tier = m.tier
                if m.role == "down" or tier is None or tier.killed:
                    continue
                with tier.lock:
                    roots.append(int(tier.crdt.digest_tree().root))
            if len(roots) == group.replicas and len(set(roots)) == 1:
                return roots[0]
        raise AssertionError(
            f"replicas did not converge within {timeout}s")
    finally:
        cli.close()


def test_group_replicates_and_serves():
    with ReplicaGroup(256, replicas=3, ack_replicas=1,
                      **FAST) as group:
        seeds = group.member_addrs()
        cli = FederatedClient(seeds, timeout=5.0)
        try:
            for s in range(0, 64, 8):
                cli.put(s, 100 + s)
            for s in range(0, 64, 8):
                assert cli.get(s) == 100 + s
        finally:
            cli.close()
        # The write-concern barrier really shipped the ticks: at
        # least one follower's durable head is stamped.
        rep = group.primary.tier.replicator
        _wait(lambda: any(f["durable"] is not None
                          for f in rep.status().values()),
              what="follower durable head")


def test_three_consecutive_failovers_zero_acked_loss():
    """The acceptance gate: >=3 kill-the-primary cycles under a
    write storm; every cycle promotes (epoch +1), loses nothing the
    client saw acked, and measures a finite MTTR."""
    with ReplicaGroup(256, replicas=3, ack_replicas=1,
                      **FAST) as group:
        seeds = group.member_addrs()
        probe = FederatedClient(seeds, timeout=5.0)
        mttrs = []
        try:
            with _Storm(seeds) as storm:
                for cycle in range(3):
                    epoch_before = group.table.epoch
                    checkpoint = storm.checkpoint()
                    abrupt_kill(group)
                    t_kill = time.monotonic()
                    # Client-observed MTTR: the routed retry loop
                    # rides out detection + promotion on its own.
                    probe.put(200 + cycle, 9000 + cycle)
                    mttr = time.monotonic() - t_kill
                    probe.refresh()
                    assert probe.table.epoch > epoch_before, (
                        f"cycle {cycle}: epoch did not advance")
                    assert probe.get(200 + cycle) == 9000 + cycle
                    _assert_no_acked_loss(seeds, checkpoint)
                    mttrs.append(mttr)
                    # restart the corpse as a follower before the
                    # next cycle so the group is back to strength
                    downed = [m for m in group.members
                              if m.role == "down"]
                    assert len(downed) == 1
                    group.rejoin(downed[0].index)
                    _wait(lambda: all(m.role != "down"
                                      for m in group.members),
                          what="rejoin")
                assert not storm.errors
            assert group.failovers == 3
            assert group.table.epoch >= 3
            assert all(0 < m < 30 for m in mttrs)
            _assert_no_acked_loss(seeds, storm.checkpoint())
        finally:
            probe.close()
        _wait_converged(group, seeds, nudge_slot=255)


def test_rejoin_discards_crash_image_and_converges():
    with ReplicaGroup(256, replicas=3, ack_replicas=1,
                      **FAST) as group:
        seeds = group.member_addrs()
        cli = FederatedClient(seeds, timeout=5.0)
        try:
            for s in range(16):
                cli.put(s, s + 1)
            dead = group.kill_primary()
            gen_before = dead.generation
            cli.put(100, 42)          # rides out the failover
            member = group.rejoin(dead.index)
            assert member is dead
            assert member.generation == gen_before + 1
            assert member.role == "follower"
            # rebind contract: a restarted member comes back at its
            # previous address, so original seeds stay valid forever
            assert member.addr in seeds
            for s in range(16):
                assert cli.get(s) == s + 1
        finally:
            cli.close()
        _wait_converged(group, seeds, nudge_slot=255)


def test_write_concern_blocks_acks_without_followers():
    """ack_replicas=2 with both followers dead: the flush tick keeps
    answering retryable busy — the primary NEVER fabricates a group-
    backed ack alone. Restoring the followers restores acks."""
    with ReplicaGroup(128, replicas=3, ack_replicas=2,
                      **FAST) as group:
        seeds = group.member_addrs()
        cli = FederatedClient(seeds, timeout=2.0, max_redirects=5)
        try:
            cli.put(1, 11)            # healthy group acks
            followers = [m for m in group.members
                         if m.role == "follower"]
            for m in followers:
                group.kill(m.index)
            _wait(lambda: all(m.role == "down" for m in followers),
                  what="follower death detection")
            with pytest.raises(ConnectionError):
                cli.put(2, 22)
            assert cli.busy_retries > 0
            for m in followers:
                group.rejoin(m.index)
            cli2 = FederatedClient(seeds, timeout=5.0)
            try:
                cli2.put(3, 33)
                assert cli2.get(3) == 33
            finally:
                cli2.close()
        finally:
            cli.close()


def test_sync_server_answers_heartbeat():
    crdt = DenseCrdt("hb-node", n_slots=64)
    crdt.put_batch([3], [7])
    crdt.drain_ingest()
    with SyncServer(crdt) as server:
        hb = _HbClient(f"{server.host}:{server.port}", timeout=2.0)
        try:
            reply = hb.beat()
            assert reply["node"] == "hb-node"
            assert "hlc" in reply and "root" not in reply
            reply = hb.beat(want_root=True)
            assert int(reply["root"]) == int(crdt.digest_tree().root)
        finally:
            hb.close()


def test_abrupt_kill_dispatches_by_shape():
    with ReplicaGroup(64, replicas=2, ack_replicas=0,
                      **FAST) as group:
        primary_tier = group.primary.tier
        abrupt_kill(group)
        assert primary_tier.killed
    with pytest.raises(TypeError):
        abrupt_kill(object())


def test_fault_proxy_blackhole_is_silent_and_asymmetric():
    crdt = DenseCrdt("mute-node", n_slots=64)
    with SyncServer(crdt) as server:
        with FaultProxy(server.host, server.port,
                        schedule=FaultSchedule(rate=0.0)) as proxy:
            proxy.passthrough = True
            addr = f"{proxy.host}:{proxy.port}"
            hb = _HbClient(addr, timeout=0.3)
            try:
                assert hb.beat()["node"] == "mute-node"
                # s2c blackhole: the request lands (server is fine)
                # but the reply never comes back — "mute", the state
                # lease fencing distinguishes from "dead". No RST, no
                # FIN: the client just times out.
                proxy.blackhole = "s2c"
                with pytest.raises(ConnectionError):
                    hb.beat()
                assert proxy.counters.get("blackhole_s2c", 0) > 0
                proxy.blackhole = None
            finally:
                hb.close()
            hb2 = _HbClient(addr, timeout=2.0)
            try:
                assert hb2.beat()["node"] == "mute-node"
            finally:
                hb2.close()
    with pytest.raises(ValueError):
        proxy.blackhole = "sideways"


@pytest.mark.slow
@pytest.mark.soak
def test_chaos_soak_proxied_kill_restart_cycles():
    """>=3 kill-and-restart primary cycles with EVERY wire the group
    uses (client ops, heartbeats, replication ships, merkle rejoin
    walks) routed through a misbehaving `FaultProxy`: acked writes
    all survive and every killed replica rejoins convergent."""
    proxies = {}

    def via(real_addr):
        if real_addr not in proxies:
            host, port = real_addr.rsplit(":", 1)
            schedule = FaultSchedule(
                seed=len(proxies), rate=0.15,
                kinds={"drop": 1, "delay": 2, "duplicate": 1},
                max_delay=0.02)
            proxies[real_addr] = FaultProxy(
                host, int(port), schedule=schedule).start()
        p = proxies[real_addr]
        return f"{p.host}:{p.port}"

    group = ReplicaGroup(256, replicas=3, ack_replicas=1,
                         addr_via=via, **FAST)
    group.start()
    try:
        seeds = group.member_addrs()
        probe = FederatedClient(seeds, timeout=5.0)
        try:
            with _Storm(seeds, writers=3, rate_hz=150.0) as storm:
                for cycle in range(4):
                    epoch_before = group.table.epoch
                    checkpoint = storm.checkpoint()
                    group.kill_primary()
                    probe.put(200 + cycle, 5000 + cycle)
                    probe.refresh()
                    assert probe.table.epoch > epoch_before
                    _assert_no_acked_loss(seeds, checkpoint)
                    downed = [m for m in group.members
                              if m.role == "down"]
                    assert len(downed) == 1
                    group.rejoin(downed[0].index)
                    _wait(lambda: all(m.role != "down"
                                      for m in group.members),
                          what="proxied rejoin")
                assert not storm.errors
            assert group.failovers >= 4
            _assert_no_acked_loss(seeds, storm.checkpoint())
        finally:
            probe.close()
        _wait_converged(group, seeds, nudge_slot=255, timeout=20.0)
        assert sum(p.counters.get("connections", 0)
                   for p in proxies.values()) > 0
    finally:
        group.stop()
        for p in proxies.values():
            p.stop()

"""Anti-entropy sync rounds (C10) — the reference's replication protocol
as a library utility.

The reference keeps the sync round in its tests
(`test/map_crdt_test.dart:273-279`): capture the local canonical time,
full-push to the remote, then delta-pull everything the remote modified
at-or-after that time (inclusive bound, map_crdt.dart:44-45). Three-node
convergence through an intermediary relies on merged records being
re-stamped with the relay's ``modified`` time (crdt.dart:87) — the
relay's deltas then include records it learned from others.

Two transports:

- :func:`sync` — in-process record maps (replicas share a process, the
  reference's own test topology).
- :func:`sync_json` — the JSON wire format (crdt_json.dart), what
  crosses a real replica boundary; transport remains the application's
  job (example/crdt_example.dart:21-25).
"""

from __future__ import annotations

from typing import Optional

from .crdt import Crdt
from .hlc import Hlc
from .record import (KeyDecoder, KeyEncoder, ValueDecoder, ValueEncoder)

# Default for ``since``: pull from the SAME round's pre-push canonical
# time — the reference's one-shot `_sync` shape. Distinct from None,
# which (matching `sync_over_tcp`) requests a cold-start FULL pull.
_SAME_ROUND = object()


def sync(local: Crdt, remote: Crdt, since=_SAME_ROUND) -> Hlc:
    """One push/pull anti-entropy round between two in-process replicas.

    After a round in each direction (or one round plus a later reverse
    round) the two replicas converge; N replicas converge through any
    connected gossip topology.

    ``since`` aligns this with :func:`crdt_tpu.net.sync_over_tcp`'s
    watermark contract: omit it for the reference's one-shot round
    (pull bounded by this round's pre-push canonical time), pass
    ``None`` for a cold-start full pull, or pass the watermark a
    previous round returned to resume delta sync."""
    watermark = local.canonical_time
    remote.merge(local.record_map())
    local.merge(remote.record_map(
        modified_since=watermark if since is _SAME_ROUND else since))
    return watermark


def sync_json(local: Crdt, remote: Crdt,
              key_encoder: Optional[KeyEncoder] = None,
              value_encoder: Optional[ValueEncoder] = None,
              key_decoder: Optional[KeyDecoder] = None,
              value_decoder: Optional[ValueDecoder] = None,
              since=_SAME_ROUND) -> Hlc:
    """The same round over the JSON wire format — full-state push, then
    delta pull keyed on the pre-push canonical time (crdt.dart:124-135).
    ``since`` follows :func:`sync`'s watermark contract."""
    watermark = local.canonical_time
    remote.merge_json(local.to_json(key_encoder=key_encoder,
                                    value_encoder=value_encoder),
                      key_decoder=key_decoder,
                      value_decoder=value_decoder)
    local.merge_json(remote.to_json(
        modified_since=watermark if since is _SAME_ROUND else since,
        key_encoder=key_encoder,
        value_encoder=value_encoder),
        key_decoder=key_decoder,
        value_decoder=value_decoder)
    return watermark

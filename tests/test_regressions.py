"""Regression tests for review findings."""

import copy
import pickle

import pytest

from crdt_tpu import (DuplicateNodeException, Hlc, MapCrdt, Record,
                      TpuMapCrdt)

from conformance import FakeClock


def test_empty_merge_clock_parity():
    """Empty merges must consume the same number of wall-clock ticks on
    both backends (the reference bumps the canonical clock even for an
    empty changeset, crdt.dart:93)."""
    oracle = MapCrdt("abc", wall_clock=FakeClock())
    tpu = TpuMapCrdt("abc", wall_clock=FakeClock())
    for c in (oracle, tpu):
        c.put("x", 1)
        c.merge({})
        c.put("y", 2)
    assert oracle.to_json() == tpu.to_json()
    assert oracle.canonical_time == tpu.canonical_time


def test_failed_merge_rolls_back_host_state():
    """A merge raising from the recv guard must not leave phantom keys
    (the oracle's store is untouched when recv throws mid-loop)."""
    clock = FakeClock()
    tpu = TpuMapCrdt("abc", wall_clock=clock)
    oracle = MapCrdt("abc", wall_clock=FakeClock())
    bad = Hlc(clock.millis + 1000, 0, "abc")  # duplicate node, ahead
    for c in (tpu, oracle):
        with pytest.raises(DuplicateNodeException):
            c.merge({"phantom": Record(bad, 1, bad)})
    assert tpu.contains_key("phantom") == oracle.contains_key("phantom") \
        == False
    assert tpu.record_map() == oracle.record_map() == {}


def test_hlc_copy_and_pickle():
    h = Hlc(1000000000000, 0x42, "abc")
    assert copy.copy(h) is h
    assert copy.deepcopy(h) is h
    assert pickle.loads(pickle.dumps(h)) == h


def test_parse_with_utc_offset():
    # fractional seconds + explicit offset must not be silently mis-parsed
    h = Hlc.parse("2024-01-01T12:00:00.123+02:00-0001-n")
    assert h.millis == 1704103200123
    assert h.counter == 1


def test_unsubscribe_idempotent():
    crdt = MapCrdt("abc", wall_clock=FakeClock())
    stream = crdt.watch()
    seen = []
    unsub = stream.listen(seen.append)
    crdt.put("x", 1)
    unsub()
    unsub()  # second call must not raise
    crdt.put("y", 2)
    assert len(seen) == 1


# --- round-2 advisor findings ---

# 0001-01-01T00:00:00Z in epoch millis; one day earlier is year 0.
_YEAR1_MS = -62135596800000


def test_native_formatter_defers_year_zero():
    """The C formatter must not emit year-0 wire strings the pure-Python
    codec refuses: both paths raise for year < 1 (hlccodec.c guard)."""
    from crdt_tpu import crdt_json, native
    year0 = Hlc(_YEAR1_MS - 86_400_000, 0, "n")
    rec = Record(year0, 1, year0)
    with pytest.raises(ValueError):
        crdt_json.encode({"k": rec})
    codec = native.load()
    if codec is not None:  # direct check of the C guard boundary
        assert codec.format_hlc_batch(
            [year0.millis], [0], ["n"]) == [None]
        assert codec.format_hlc_batch(
            [_YEAR1_MS], [0], ["n"]) == ["0001-01-01T00:00:00.000Z-0000-n"]


def test_sqlite_record_map_includes_pre_epoch_modified():
    """record_map() with no bound must return ALL rows, including ones
    whose modified HLC has negative millis (reachable via put_record;
    a default `modified_lt >= 0` filter silently dropped them)."""
    from crdt_tpu import SqliteCrdt
    crdt = SqliteCrdt("abc", wall_clock=FakeClock())
    old = Hlc(-5000, 0, "abc")
    crdt.put_record("k", Record(old, 1, old))
    assert "k" in crdt.record_map()
    assert crdt.record_map()["k"].value == 1


def test_dense_pallas_executor_rejects_unaligned_capacity_eagerly():
    """A forced pallas executor must refuse a TILE-unaligned n_slots at
    construction (not via a strippable assert at first merge)."""
    from crdt_tpu import DenseCrdt
    from crdt_tpu.ops.pallas_merge import TILE
    with pytest.raises(ValueError, match="n_slots"):
        DenseCrdt("abc", TILE + 1, executor="pallas")
    with pytest.raises(ValueError, match="executor"):
        DenseCrdt("abc", TILE, executor="warp")


def test_empty_merge_json_clock_parity():
    """merge_json('{}') must consume the same number of wall-clock
    ticks on both backends (decode read + merge read + final send) so
    differential parity survives the no-changes sync case."""
    oracle = MapCrdt("abc", wall_clock=FakeClock())
    tpu = TpuMapCrdt("abc", wall_clock=FakeClock())
    for c in (oracle, tpu):
        c.put("x", 1)
        c.merge_json("{}")
        c.put("y", 2)
    assert oracle.canonical_time == tpu.canonical_time
    assert oracle.to_json() == tpu.to_json()


class TestDuplicateWireKeys:
    """ADVICE r5 findings 1-2: distinct wire keys that decode to ONE
    dense slot ("5" and "05" under the int key decoder) must collapse
    last-wins BEFORE the columnar merge dispatch — the legacy
    decode-dict semantics, and the uniqueness the scatter join and the
    watch `get` callback both require."""

    BASE = 1_700_000_000_000

    def _hlc(self, offset_ms):
        return str(Hlc(self.BASE + offset_ms, 0, "peer"))

    def test_last_occurrence_wins_matches_oracle(self):
        import json

        from crdt_tpu import DenseCrdt
        payload = json.dumps({
            "5": {"hlc": self._hlc(10_000), "value": 111},
            "05": {"hlc": self._hlc(5_000), "value": 222},
        })
        dense = DenseCrdt("dd", 64, wall_clock=FakeClock())
        dense.merge_json(payload)
        oracle = MapCrdt("dd", wall_clock=FakeClock())
        oracle.merge_json(payload, key_decoder=int)
        # decode-dict parity: the LAST occurrence survives dedup even
        # though the dropped one carries the higher hlc
        assert dense.get(5) == oracle.get(5) == 222
        assert dense.record_map()[5].hlc == oracle.record_map()[5].hlc
        # the dropped occurrence was never seen by the merge
        assert dense.stats.records_seen == 1

    def test_literal_duplicate_keys_match_oracle(self):
        # The same canonical key appearing twice in the raw wire text
        # (json.loads collapses it last-wins; the columnar scan must
        # agree) — exercises the C wire-scan dedup when available.
        from crdt_tpu import DenseCrdt
        payload = ('{"5": {"hlc": "%s", "value": 111}, '
                   '"5": {"hlc": "%s", "value": 222}}'
                   % (self._hlc(10_000), self._hlc(5_000)))
        dense = DenseCrdt("dd", 64, wall_clock=FakeClock())
        dense.merge_json(payload)
        oracle = MapCrdt("dd", wall_clock=FakeClock())
        oracle.merge_json(payload, key_decoder=int)
        assert dense.get(5) == oracle.get(5) == 222

    def test_dropped_duplicate_never_reaches_watch(self):
        # Finding 2 shape: the surviving (last) occurrence LOSES to the
        # local record while the dropped one would have won. Decode-dict
        # semantics: nothing is adopted, nothing emits — previously the
        # winning dropped occurrence merged and the keyed get callback
        # could answer with the losing occurrence's value.
        import json

        from crdt_tpu import DenseCrdt
        dense = DenseCrdt("dd", 64, wall_clock=FakeClock(start=self.BASE))
        dense.put_batch([5], [7])                    # local, ~BASE
        whole = dense.watch().record()
        keyed = dense.watch(5).record()
        payload = json.dumps({
            "5": {"hlc": self._hlc(30_000), "value": 111},   # would win
            "05": {"hlc": self._hlc(-30_000), "value": 222},  # loses
        })
        dense.merge_json(payload)
        oracle = MapCrdt("dd", wall_clock=FakeClock(start=self.BASE))
        oracle.put(5, 7)
        oracle.merge_json(payload, key_decoder=int)
        assert dense.get(5) == oracle.get(5) == 7    # local still wins
        assert whole.events == [] and keyed.events == []

    def test_surviving_duplicate_emits_winning_value(self):
        # Positive shape of finding 2: the surviving (last) occurrence
        # WINS over the local record — the keyed stream must report
        # the value the store adopts (post-dedup `get` callback), not
        # stay silent and not answer with the dropped occurrence.
        import json

        from crdt_tpu import DenseCrdt
        dense = DenseCrdt("dd", 64, wall_clock=FakeClock(start=self.BASE))
        dense.put_batch([5], [7])                    # local, ~BASE
        whole = dense.watch().record()
        keyed = dense.watch(5).record()
        payload = json.dumps({
            "5": {"hlc": self._hlc(5_000), "value": 111},    # dropped
            "05": {"hlc": self._hlc(30_000), "value": 222},  # wins
        })
        dense.merge_json(payload)
        assert dense.get(5) == 222
        assert keyed.events == [(5, 222)]
        assert whole.events == [(5, 222)]

"""Pallas TPU kernel for the fan-in lattice join — the hot op, fused.

Why a kernel when XLA already fuses the fold (`ops.dense.lex_fold`):

1. **No int64 emulation.** TPUs have no native 64-bit integers; XLA
   emulates every int64 compare/select as multi-op int32 sequences. Here
   the 64-bit logicalTime is carried as SPLIT (hi int32, lo uint32)
   lanes and the lexicographic LWW compare is
   ``(hi, lo, node)`` — three native int32/uint32 VPU compares.
2. **One VMEM pass.** Store lanes, changeset lanes, guard masks, and
   the win mask are produced in a single tiled sweep: each (R, BLK)
   changeset tile and its (1, BLK) store tile are resident in VMEM
   once; XLA's fold reads/writes store lanes across several fusions.
3. **Drift guard as a compare.** ``(lt >> 16) - wall > MAX_DRIFT`` is
   algebraically ``lt > ((wall + MAX_DRIFT) << 16) | 0xFFFF`` (the
   ``|0xFFFF`` makes the strict compare millis-level: counter bits at
   exactly wall+MAX_DRIFT millis must not trip); the threshold is split
   host-side so the in-kernel check is the same three-way lex compare —
   no 64-bit shifts on device.

Guard semantics match the sharded path (`crdt_tpu.parallel.fanin`):
recv's fast-path shielding (hlc.dart:85) is evaluated per key column —
the running clock cummaxes over the rows of this column only, seeded
with the pre-merge canonical time. Strictly more sensitive than the
r-major flat order of `ops.dense.fanin_step` (can only flag a
superset); store lanes and canonical time are bit-identical. On a
tripped guard, re-run the scalar oracle for first-offender diagnostics.

Empty/invalid encoding: a store slot is empty iff its ``hi`` lane holds
``NEG_HI`` (no occupied lane on device); an invalid changeset entry is
pre-masked to sentinels at split time (no valid lane on device).
Tombstones ride an int32 lane (record.dart:17 semantics).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..hlc import MAX_COUNTER, MAX_DRIFT, SHIFT
from ..obs import device as _obs_device
from .dense import DenseChangeset, DenseStore, _NEG

_obs_device.register(
    "pallas.model_fanin_batch", "pallas.model_fanin_split",
    "pallas.pipelined_model_step", "pallas.pipelined_model_step_split")

# Sentinel hi word of _NEG = -(2**62): anything real compares greater.
# Plain ints (not jnp scalars): module-level concrete arrays would
# initialize the jax backend at import time, foreclosing the platform
# selection the driver entry points must do first.
NEG_HI = _NEG >> 32
NEG_LO = _NEG & 0xFFFFFFFF


class SplitStore(NamedTuple):
    """DenseStore with 64-bit lanes split for native 32-bit compute.
    Slot empty ⇔ ``hi == NEG_HI``."""
    hi: jax.Array        # int32[N]  lt >> 32 (NEG_HI = empty)
    lo: jax.Array        # uint32[N] lt & 0xFFFFFFFF
    node: jax.Array      # int32[N]
    val_hi: jax.Array    # int32[N]
    val_lo: jax.Array    # uint32[N]
    tomb: jax.Array      # int32[N] 0/1
    mod_hi: jax.Array    # int32[N]
    mod_lo: jax.Array    # uint32[N]
    mod_node: jax.Array  # int32[N]


# Node ordinals ride an int16 changeset lane (ordinals count DISTINCT
# replica ids — far below 32k in any real deployment); the in-kernel
# compare widens to int32, so (lt, node) semantics are unchanged while
# the wire lane costs 2 bytes instead of 4. I16_NEG is the invalid
# sentinel (widens below any real ordinal, which are >= 0).
I16_NEG = -(2 ** 15)
MAX_NODE_ORDINAL = 2 ** 15 - 1


class SplitChangeset(NamedTuple):
    """[R, N] changeset lanes, invalid entries pre-masked to sentinels.
    Narrow wire lanes (int16 node, int8 tomb) cut HBM traffic per
    merge from 24 B to 19 B; compares run widened in-kernel."""
    hi: jax.Array      # int32[R, N] (NEG_HI = invalid)
    lo: jax.Array      # uint32[R, N]
    node: jax.Array    # int16[R, N] (I16_NEG when invalid)
    val_hi: jax.Array  # int32[R, N]
    val_lo: jax.Array  # uint32[R, N]
    tomb: jax.Array    # int8[R, N]


class NarrowSplitChangeset(NamedTuple):
    """[R, N] changeset lanes for the value-ref mode: ``val`` is a
    SINGLE int32 lane (sign-extended into the store's 64-bit payload
    in-kernel), for workloads whose values are int32-range scalars or
    indices into an application-side payload table (SURVEY.md §7 hard
    part 4 — the recommended shape for variable-length values). 15 B
    of HBM per merge instead of the wide form's 19 B."""
    hi: jax.Array    # int32[R, N] (NEG_HI = invalid)
    lo: jax.Array    # uint32[R, N]
    node: jax.Array  # int16[R, N] (I16_NEG when invalid)
    val: jax.Array   # int32[R, N]
    tomb: jax.Array  # int8[R, N]


@jax.jit
def split_changeset_narrow(cs: DenseChangeset
                           ) -> Tuple[NarrowSplitChangeset, jax.Array]:
    """`split_changeset` for the value-ref mode. Returns the narrow
    lanes AND a ``val_overflow`` device flag: True iff any valid value
    does not round-trip through int32 — the caller must check it (at
    its next batched fetch; merging out-of-range values in this mode
    would silently truncate payloads)."""
    v32 = cs.val.astype(jnp.int32)
    fits = v32.astype(jnp.int64) == cs.val
    overflow = jnp.any(cs.valid & ~fits)
    # Overflowing rows are masked INVALID, not truncated: a silently
    # narrowed payload under the peer's winning HLC could never be
    # repaired by any later merge (LWW ties keep the local record).
    ok = cs.valid & fits
    lt = jnp.where(ok, cs.lt, _NEG)
    hi, lo = _split64(lt)
    return NarrowSplitChangeset(
        hi=hi, lo=lo,
        node=jnp.where(ok, cs.node, I16_NEG).astype(jnp.int16),
        val=v32,
        tomb=cs.tomb.astype(jnp.int8)), overflow


def _split64(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    return ((x >> 32).astype(jnp.int32),
            (x & 0xFFFFFFFF).astype(jnp.uint32))


def _join64(hi: jax.Array, lo: jax.Array) -> jax.Array:
    return (hi.astype(jnp.int64) << 32) | lo.astype(jnp.int64)


@jax.jit
def split_store(store: DenseStore) -> SplitStore:
    lt = jnp.where(store.occupied, store.lt, _NEG)
    hi, lo = _split64(lt)
    val_hi, val_lo = _split64(store.val)
    mod_hi, mod_lo = _split64(store.mod_lt)
    return SplitStore(hi=hi, lo=lo, node=store.node, val_hi=val_hi,
                      val_lo=val_lo, tomb=store.tomb.astype(jnp.int32),
                      mod_hi=mod_hi, mod_lo=mod_lo,
                      mod_node=store.mod_node)


@jax.jit
def join_store(s: SplitStore) -> DenseStore:
    occupied = s.hi != NEG_HI
    return DenseStore(
        lt=jnp.where(occupied, _join64(s.hi, s.lo), 0),
        node=s.node, val=_join64(s.val_hi, s.val_lo),
        mod_lt=_join64(s.mod_hi, s.mod_lo), mod_node=s.mod_node,
        occupied=occupied, tomb=s.tomb.astype(bool))


@jax.jit
def split_changeset(cs: DenseChangeset) -> SplitChangeset:
    lt = jnp.where(cs.valid, cs.lt, _NEG)
    hi, lo = _split64(lt)
    val_hi, val_lo = _split64(cs.val)
    # Callers must keep node ordinals <= MAX_NODE_ORDINAL (the model
    # layer routes to the XLA fold beyond that); the cast would wrap
    # silently under jit, so the bound is enforced host-side.
    return SplitChangeset(
        hi=hi, lo=lo,
        node=jnp.where(cs.valid, cs.node, I16_NEG).astype(jnp.int16),
        val_hi=val_hi, val_lo=val_lo,
        tomb=cs.tomb.astype(jnp.int8))


def _lex_gt(a_hi, a_lo, a_node, b_hi, b_lo, b_node):
    """(hi, lo, node) strict lexicographic greater-than — native 32-bit."""
    return ((a_hi > b_hi) |
            ((a_hi == b_hi) & ((a_lo > b_lo) |
                               ((a_lo == b_lo) & (a_node > b_node)))))


class PallasFaninResult(NamedTuple):
    new_canonical: jax.Array  # int64 scalar (pre final-send-bump)
    win: jax.Array            # bool[N]
    any_dup: jax.Array        # bool
    any_drift: jax.Array      # bool


def _max64(a_hi, a_lo, b_hi, b_lo):
    """Scalar 64-bit max on split (i32 hi, u32 lo) pairs."""
    take_b = (b_hi > a_hi) | ((b_hi == a_hi) & (b_lo > a_lo))
    return jnp.where(take_b, b_hi, a_hi), jnp.where(take_b, b_lo, a_lo)


def _add_off64(hi, lo, off_u32):
    """(hi, lo) + off with carry propagation (off < 2**31)."""
    lo2 = lo + off_u32
    return hi + (lo2 < lo).astype(hi.dtype), lo2


def _fanin_stream_kernel(exact_guards, advance_clock, narrow_val,
                         scalars_ref, *refs):
    """Multi-chunk fan-in: grid (row_blocks, n_chunks); the store block
    stays VMEM-resident across the chunk dimension (block index constant
    in c), so HBM sees each store/changeset lane once per row block
    instead of once per chunk. Chunk ``c`` merges the changeset with
    every logicalTime advanced by ``c`` ms (the steady-state write
    stream `bench.build_stream_fn` models); results are bit-identical
    to ``n_chunks`` sequential `fanin_step` folds threading the
    canonical clock.

    ``exact_guards`` (static): True runs the column-local shielded
    recv guards in-kernel (~half the per-row compute is the running
    cummax chain); False skips ALL in-kernel guard work — the caller
    derives superset flags from closed-form scalar reductions instead
    (see `pallas_fanin_stream`).

    ``advance_clock`` (static): True replays ONE changeset block with
    chunk clocks advancing 1ms per chunk (`pallas_fanin_stream`);
    False walks DISTINCT changeset row groups with no offsets — the
    chunked form of a single merge, stamped with the union-final
    canonical (`pallas_fanin_batch`)."""
    assert advance_clock or not exact_guards, (
        "exact guards are only defined for the clock-advancing stream")
    if narrow_val:
        # value-ref mode: ONE int32 val lane, sign-extended per row
        (cs_hi, cs_lo, cs_node, cs_v32, cs_tomb,
         st_hi, st_lo, st_node, st_vhi, st_vlo, st_tomb,
         st_mhi, st_mlo, st_mnode,
         o_hi, o_lo, o_node, o_vhi, o_vlo, o_tomb,
         o_mhi, o_mlo, o_mnode,
         win_ref, dup_ref, drift_ref, *acc_refs) = refs
    else:
        (cs_hi, cs_lo, cs_node, cs_vhi, cs_vlo, cs_tomb,
         st_hi, st_lo, st_node, st_vhi, st_vlo, st_tomb,
         st_mhi, st_mlo, st_mnode,
         o_hi, o_lo, o_node, o_vhi, o_vlo, o_tomb,
         o_mhi, o_mlo, o_mnode,
         win_ref, dup_ref, drift_ref, *acc_refs) = refs
    if not advance_clock:
        # Batch-only vector accumulators (unused outputs are not free:
        # three extra resident blocks measurably slowed the stream).
        bm_hi_ref, bm_lo_ref, dupacc_ref = acc_refs
    rb = pl.program_id(0)
    c = pl.program_id(1)
    first = c == 0

    @pl.when((rb == 0) & first)
    def _init():
        # Before ANY accumulation below (grid steps run in order).
        dup_ref[0, 0] = jnp.int32(0)
        drift_ref[0, 0] = jnp.int32(0)

    canon_hi = scalars_ref[0]
    canon_lo = scalars_ref[1].astype(jnp.uint32)
    local_node = scalars_ref[2]
    thresh_hi = scalars_ref[3]
    thresh_lo = scalars_ref[4].astype(jnp.uint32)
    bmax_hi = scalars_ref[5]
    bmax_lo = scalars_ref[6].astype(jnp.uint32)

    if advance_clock:
        off = (c << SHIFT).astype(jnp.uint32)
        # Canonical clock after chunk c (threaded exactly as the
        # sequential fold does): newc_c = max(canon_0,
        # basemax + c<<SHIFT); the run seed for chunk c is newc_{c-1}
        # (= canon_0 at c == 0).
        nc_hi, nc_lo = _max64(canon_hi, canon_lo,
                              *_add_off64(bmax_hi, bmax_lo, off))
    # else (batch): the union-final canonical is computed FROM this
    # kernel's own basemax output; winners' modified lanes are stamped
    # by the wrapper's cheap post-pass (the kernel carries the store's
    # mod lanes through untouched).

    b_hi = jnp.where(first, st_hi[...], o_hi[...])
    b_lo = jnp.where(first, st_lo[...], o_lo[...])
    b_node = jnp.where(first, st_node[...], o_node[...])
    b_vhi = jnp.where(first, st_vhi[...], o_vhi[...])
    b_vlo = jnp.where(first, st_vlo[...], o_vlo[...])
    b_tomb = jnp.where(first, st_tomb[...], o_tomb[...])
    win_prev = jnp.where(first, jnp.int32(0), win_ref[...])
    win = jnp.zeros(b_hi.shape, jnp.bool_)

    if exact_guards:
        pv_hi, pv_lo = _max64(
            canon_hi, canon_lo,
            *_add_off64(bmax_hi, bmax_lo,
                        ((c - 1) << SHIFT).astype(jnp.uint32)))
        seed_hi = jnp.where(first, canon_hi, pv_hi)
        seed_lo = jnp.where(first, canon_lo, pv_lo)
        run_hi = jnp.full(b_hi.shape, seed_hi, jnp.int32)
        run_lo = jnp.full(b_hi.shape, seed_lo, jnp.uint32)
        acc_dup = jnp.zeros(b_hi.shape, jnp.int32)
        acc_drift = jnp.zeros(b_hi.shape, jnp.int32)

    if advance_clock:
        # Sequential row walk. Exact guards NEED the running cummax
        # chain; the fast-guard replay stream ALSO keeps the chain —
        # its cs block is VMEM-resident across chunks (compute-bound),
        # and there the chain's smaller live set beats the tournament's
        # ILP (measured 72 vs 57 B merges/s on the stream row).
        for r in range(cs_hi.shape[0]):
            hi0 = cs_hi[r]
            lo0 = cs_lo[r]
            # Narrow wire lanes widen on load: compares are int32
            # either way, so (lt, node) semantics are untouched.
            node = cs_node[r].astype(jnp.int32)
            if advance_clock:
                # Advance the chunk clock on real lanes only: the NEG
                # sentinel must stay the unique minimum (its lo is 0,
                # so a masked offset also never carries into hi).
                lo = lo0 + jnp.where(hi0 == NEG_HI, jnp.uint32(0), off)
                hi = hi0 + (lo < lo0).astype(jnp.int32)
            else:
                hi, lo = hi0, lo0

            if exact_guards:
                slow = _lex_gt(hi, lo, jnp.int32(0),
                               run_hi, run_lo, jnp.int32(0))
                dup = slow & (node == local_node)
                drift = (slow & ~dup &
                         _lex_gt(hi, lo, jnp.int32(0),
                                 thresh_hi, thresh_lo, jnp.int32(0)))
                acc_dup = acc_dup | dup.astype(jnp.int32)
                acc_drift = acc_drift | drift.astype(jnp.int32)
                run_hi = jnp.where(slow, hi, run_hi)
                run_lo = jnp.where(slow, lo, run_lo)

            gt = _lex_gt(hi, lo, node, b_hi, b_lo, b_node)
            b_hi = jnp.where(gt, hi, b_hi)
            b_lo = jnp.where(gt, lo, b_lo)
            b_node = jnp.where(gt, node, b_node)
            if narrow_val:
                v = cs_v32[r]
                # sign-extend into the store's 64-bit payload: hi word
                # is the sign fill; lo word the int32 bits (signed->
                # unsigned convert is modular, a bit-preserving wrap)
                b_vhi = jnp.where(gt, v >> 31, b_vhi)
                b_vlo = jnp.where(gt, v.astype(jnp.uint32), b_vlo)
            else:
                b_vhi = jnp.where(gt, cs_vhi[r], b_vhi)
                b_vlo = jnp.where(gt, cs_vlo[r], b_vlo)
            b_tomb = jnp.where(gt, cs_tomb[r].astype(jnp.int32), b_tomb)
            win = win | gt
    else:
        # No in-kernel guard work: reduce the rows as a TOURNAMENT
        # TREE instead of a sequential running-best chain. Same op
        # count, but pair merges at each level are independent, so
        # Mosaic can hide the whole VPU cost behind the DMA — measured
        # 7.4 -> ~20 B merges/s on the distinct batch row (the
        # same-layout pure-copy ceiling; docs/PERF.md round 5).
        # Tie-break parity: pairs are (lower row, higher row) and the
        # higher row wins only on STRICT (lt, node) greatership, so
        # the lowest replica row survives ties at every level —
        # exactly the sequential chain's stable order (associative,
        # so the bracket shape doesn't matter).
        items = []
        dup_any = None
        for r in range(cs_hi.shape[0]):
            hi = cs_hi[r]
            lo = cs_lo[r]
            node = cs_node[r].astype(jnp.int32)
            # Batch self-reduction (see below): dup candidates are
            # local-node records above the pre-merge canonical — the
            # closed-form bound, evaluated while the rows are already
            # VMEM-resident instead of as a separate XLA sweep over
            # the whole changeset. Accumulated as a VECTOR mask
            # (elementwise OR per row) — per-row scalar reduces
            # measurably stall the VPU.
            row_dup = ((node == local_node) &
                       _lex_gt(hi, lo, jnp.int32(0),
                               canon_hi, canon_lo, jnp.int32(0)))
            dup_any = (row_dup if dup_any is None
                       else dup_any | row_dup)
            if narrow_val:
                v = cs_v32[r]
                vhi, vlo = v >> 31, v.astype(jnp.uint32)
            else:
                vhi, vlo = cs_vhi[r], cs_vlo[r]
            items.append((hi, lo, node, vhi, vlo,
                          cs_tomb[r].astype(jnp.int32)))
        while len(items) > 1:
            nxt = []
            for i in range(0, len(items) - 1, 2):
                a, b = items[i], items[i + 1]
                gt = _lex_gt(b[0], b[1], b[2], a[0], a[1], a[2])
                nxt.append(tuple(jnp.where(gt, bb, aa)
                                 for aa, bb in zip(a, b)))
            if len(items) % 2:
                nxt.append(items[-1])
            items = nxt
        w = items[0]
        gt = _lex_gt(w[0], w[1], w[2], b_hi, b_lo, b_node)
        b_hi = jnp.where(gt, w[0], b_hi)
        b_lo = jnp.where(gt, w[1], b_lo)
        b_node = jnp.where(gt, w[2], b_node)
        b_vhi = jnp.where(gt, w[3], b_vhi)
        b_vlo = jnp.where(gt, w[4], b_vlo)
        b_tomb = jnp.where(gt, w[5], b_tomb)
        win = win | gt
        # The canonical/flag reductions fold INTO this kernel (the
        # rows are right here in VMEM; the XLA sweeps they replace
        # cost more than the whole join) — but as ELEMENTWISE vector
        # accumulators, never in-kernel cross-lane reduces (those
        # stall the VPU measurably; the wrapper reduces the one
        # accumulated block in XLA):
        # - dupacc: per-position OR of the dup-candidate masks;
        # - bm: per-position (hi, lo) max64 of the per-key winners
        #   across grid steps.
        init = (rb == 0) & first
        prev_hi = jnp.where(init, jnp.int32(NEG_HI), bm_hi_ref[...])
        prev_lo = jnp.where(init, jnp.uint32(0), bm_lo_ref[...])
        take = ((w[0] > prev_hi) |
                ((w[0] == prev_hi) & (w[1] > prev_lo)))
        bm_hi_ref[...] = jnp.where(take, w[0], prev_hi)
        bm_lo_ref[...] = jnp.where(take, w[1], prev_lo)
        prev_dup = jnp.where(init, jnp.int32(0), dupacc_ref[...])
        dupacc_ref[...] = prev_dup | dup_any.astype(jnp.int32)

    o_hi[...] = b_hi
    o_lo[...] = b_lo
    o_node[...] = b_node
    o_vhi[...] = b_vhi
    o_vlo[...] = b_vlo
    o_tomb[...] = b_tomb
    m_hi = jnp.where(first, st_mhi[...], o_mhi[...])
    m_lo = jnp.where(first, st_mlo[...], o_mlo[...])
    m_node = jnp.where(first, st_mnode[...], o_mnode[...])
    if advance_clock:
        o_mhi[...] = jnp.where(win, nc_hi, m_hi)
        o_mlo[...] = jnp.where(win, nc_lo, m_lo)
        o_mnode[...] = jnp.where(win, local_node, m_node)
    else:
        # Batch: stamped post-kernel (nc needs this kernel's basemax).
        o_mhi[...] = m_hi
        o_mlo[...] = m_lo
        o_mnode[...] = m_node
    win_ref[...] = win_prev | win.astype(jnp.int32)

    if exact_guards:
        dup_ref[0, 0] = dup_ref[0, 0] | jnp.max(acc_dup)
        drift_ref[0, 0] = drift_ref[0, 0] | jnp.max(acc_drift)


# Tile geometry: (sublane, lane) int32 tiles (Mosaic floor: sublane %
# 8 == 0, lane % 128 == 0). The two kernels want DIFFERENT tiles
# (measured on v5e with the narrow int16/int8 wire lanes, 48-loop
# production-kernel runs — not synthetic probes, which mislead on
# this platform; docs/PERF.md):
#
# - distinct batch (HBM-bound; every chunk reads fresh rows):
#   (8, 512) → 7.40B merges/s vs 6.6B at (8, 1024), 7.0B at (8, 2048).
# - stream replay (compute-bound; the cs block is VMEM-resident
#   across chunks): (8, 1024) → 69B vs 58B at (8, 512).
#
# TILE (the n_slots alignment floor, 4096) is the batch tile; the
# stream path upgrades its lane width to 1024 when n_slots allows.
_SB = 8
_LANE = 512
TILE = _SB * _LANE  # n_slots must be a multiple of this
_STREAM_LANE = 1024


def _stream_tile_lane(n: int) -> int:
    return _STREAM_LANE if n % (_SB * _STREAM_LANE) == 0 else _LANE


def tile_changeset(scs, lane: int = _LANE):
    """Pre-tile split wire lanes to the kernel's resident
    ``(r, n//lane, lane)`` layout. A TPU reshape across tile
    boundaries is a physical relayout copy (~2.4 GB for the 1M×128
    batch — comparable to the join's own HBM traffic, measured ~7 ms
    of the old 15 ms call); batches that LIVE in HBM between merges
    should be stored pre-tiled so each merge doesn't re-pay it. 2-D
    lanes remain accepted by every kernel wrapper (the reshape then
    happens in-jit, where it can fuse with a producing split)."""
    r, n = scs.hi.shape
    if n % (_SB * lane):
        raise ValueError(f"n={n} not tileable at lane={lane}")
    return type(scs)(*(l.reshape(r, n // lane, lane) for l in scs))


def _cs_shape(cs) -> Tuple[int, int]:
    """(r, n) for 2-D or pre-tiled 3-D changeset lanes."""
    if cs.hi.ndim == 3:
        r, rows, lane = cs.hi.shape
        return r, rows * lane
    return cs.hi.shape


def pad_split_rows(cs, multiple: int):
    """Pad a split (2-D or tiled 3-D) changeset's replica axis with
    INVALID rows (hi=NEG_HI, node=I16_NEG, zeros elsewhere) up to a
    multiple — the split-lane counterpart of `ops.dense.
    pad_replica_rows`, for callers feeding `pallas_fanin_batch`'s
    chunk_rows requirement with pre-split wire data."""
    r = cs.hi.shape[0]
    pad = (-r) % multiple
    if not pad:
        return cs
    out = {}
    for f in cs._fields:
        lane = getattr(cs, f)
        fill = NEG_HI if f == "hi" else (I16_NEG if f == "node" else 0)
        out[f] = jnp.concatenate([
            lane, jnp.full((pad,) + lane.shape[1:], fill, lane.dtype)])
    return type(cs)(**out)


@jax.jit
def split_guard_lanes(hi, lo, node, node_map):
    """Just the three lanes recv guards read — ``(lt, node, valid)``
    with LOCAL ordinals — from split wire lanes, without
    reconstructing the payload (exact-guard pipelined windows need
    these every merge; `split_to_wide` would rebuild all five)."""
    r = hi.shape[0]
    hi2 = hi.reshape(r, -1)
    valid = hi2 != NEG_HI
    lt = _join64(hi2, lo.reshape(r, -1))
    idx = jnp.clip(node.reshape(r, -1), 0,
                   node_map.shape[0] - 1).astype(jnp.int32)
    return lt, node_map.astype(jnp.int32)[idx], valid


@jax.jit
def split_to_wide(cs) -> DenseChangeset:
    """Reconstruct wide `DenseChangeset` lanes from split wire lanes
    (either width) — the exact inverse of `split_changeset`[`_narrow`]
    up to the masked content of invalid entries (which no consumer
    reads). Used by the model layer's non-kernel fallback and the
    failure-path exact guard recompute."""
    r, n = _cs_shape(cs)
    flat = type(cs)(*(l.reshape(r, n) if l.ndim == 3 else l
                      for l in cs))
    valid = flat.hi != NEG_HI
    lt = jnp.where(valid, _join64(flat.hi, flat.lo), 0)
    if isinstance(cs, NarrowSplitChangeset):
        val = flat.val.astype(jnp.int64)
    else:
        val = _join64(flat.val_hi, flat.val_lo)
    return DenseChangeset(
        lt=lt,
        node=jnp.where(valid, flat.node.astype(jnp.int32), 0),
        val=val,
        tomb=flat.tomb.astype(bool),
        valid=valid,
    )


@partial(jax.jit, static_argnames=("interpret",))
def pallas_fanin_step(store: SplitStore, cs: SplitChangeset,
                      canonical_lt: jax.Array, local_node: jax.Array,
                      wall_millis: jax.Array, *,
                      interpret: bool = False
                      ) -> Tuple[SplitStore, PallasFaninResult]:
    """Fused single-changeset fan-in on split lanes — the ``n_chunks=1``
    case of `pallas_fanin_stream` (one kernel, one semantics). Same
    store-lane/canonical results as `ops.dense.fanin_step`; guard flags
    per the module docstring. ``n_slots`` must be a multiple of
    ``TILE`` (= ``_SB * _LANE``)."""
    return pallas_fanin_stream(store, cs, canonical_lt, local_node,
                               wall_millis, n_chunks=1,
                               interpret=interpret)


@partial(jax.jit, static_argnames=("n_chunks", "guards", "interpret"))
def pallas_fanin_stream(store: SplitStore, cs: SplitChangeset,
                        canonical_lt: jax.Array, local_node: jax.Array,
                        wall_millis: jax.Array, *, n_chunks: int,
                        guards: str = "exact",
                        interpret: bool = False
                        ) -> Tuple[SplitStore, PallasFaninResult]:
    """``n_chunks`` sequential fan-in folds fused into ONE kernel launch.

    Chunk ``c`` applies ``cs`` with every logicalTime advanced by ``c``
    ms and the canonical clock threaded through (the steady-state write
    stream). Bit-identical store/canonical results to the equivalent
    loop of `fanin_step` / `pallas_fanin_step` calls, but the store
    block is VMEM-resident across the chunk grid dimension, so HBM
    traffic is ~``n_chunks``× lower than the sequential loop: the
    memory system sees each store and changeset lane once per row block.

    ``guards`` selects the recv-guard executor (store lanes, canonical
    and ``win`` are identical either way):

    - ``"exact"`` — the column-local shielded semantics in-kernel
      (flags bit-identical to `pallas_fanin_step` loops). The running
      cummax chain is ~half the per-row compute.
    - ``"fast"`` — optimistic guards: ZERO per-row guard work; flags
      come from closed-form scalar bounds (max local-node logicalTime
      vs the threaded canonical; changeset max vs the drift
      threshold). A strict SUPERSET of the exact flags — no anomaly is
      missed, but a shielded record may flag. The model layer's
      contract already handles this: on any trip it recomputes the
      guards exactly on host for first-offender diagnostics and clears
      false positives (`DenseCrdt._exact_guards`). Measured ~1.9×
      faster at the 1M×1024 headline.

    ``win`` is the OR across chunks (slots adopted at least once);
    ``new_canonical`` is the post-final-chunk canonical time.
    """
    r, n = _cs_shape(cs)
    if n % TILE:  # ValueError, not assert: must survive `python -O`
        raise ValueError(f"n_slots={n} not a multiple of TILE={TILE}")
    if not 0 < n_chunks < (1 << 15):  # c << 16 must fit int32
        raise ValueError(f"n_chunks={n_chunks} out of range [1, 2^15)")
    rows = n // _LANE

    if guards not in ("exact", "fast"):
        # ValueError, not assert: a stripped assert under `python -O`
        # would silently route an unknown mode to the fast branch.
        raise ValueError(f"unknown guards mode {guards!r}")
    # Base changeset max (chunk 0's clock ceiling): chunk c's ceiling is
    # basemax + c<<SHIFT, threaded against canonical in-kernel.
    m_hi = jnp.max(cs.hi)
    m_lo = jnp.max(jnp.where(cs.hi == m_hi, cs.lo, 0))
    # The replayed changeset block is VMEM-resident across the chunk
    # dim, so its lane width costs nothing in HBM — widen the narrow
    # wire lanes ONCE here and the in-kernel astype becomes identity
    # (the compute-bound replay loses no VPU cycles to widening).
    if isinstance(cs, NarrowSplitChangeset):
        v = cs.val
        cs = SplitChangeset(hi=cs.hi, lo=cs.lo,
                            node=cs.node.astype(jnp.int32),
                            val_hi=v >> 31,
                            val_lo=v.astype(jnp.uint32),
                            tomb=cs.tomb.astype(jnp.int32))
    else:
        cs = cs._replace(node=cs.node.astype(jnp.int32),
                         tomb=cs.tomb.astype(jnp.int32))
    outs = _launch_stream_grid(
        guards == "exact", True, store, cs, canonical_lt, local_node,
        wall_millis, m_hi, m_lo, cs_block_rows=r,
        cs_index_map=lambda i, c: (jnp.int32(0), jnp.int32(i),
                                   jnp.int32(0)),
        n_chunks=n_chunks, interpret=interpret,
        lane=_stream_tile_lane(n))

    final_off = ((n_chunks - 1) << SHIFT)
    basemax = _join64(m_hi, m_lo)
    new_canonical = jnp.maximum(canonical_lt, basemax + final_off)
    new_store = SplitStore(*(o.reshape(n) for o in outs[:9]))

    if guards == "exact":
        any_dup = outs[10][0, 0] > 0
        any_drift = outs[11][0, 0] > 0
    else:
        # Optimistic superset flags in closed form. A chunk-c dup
        # candidate is a local-node record above the threaded canonical
        # newc_{c-1} = max(canon_0, basemax + (c-1)<<SHIFT); with
        # M_loc = max local-node logicalTime, "exists c" collapses to
        # the c=0 test plus (for c>=1) a c-independent bound against
        # basemax and the last chunk's test against canon_0. Drift is
        # the changeset ceiling vs the wall threshold.
        m_loc = _max_local_lt(cs, local_node)
        any_dup = m_loc > canonical_lt
        if n_chunks > 1:
            any_dup = any_dup | ((m_loc > basemax - (1 << SHIFT))
                                 & (m_loc + final_off > canonical_lt))
        thresh = ((wall_millis + MAX_DRIFT) << SHIFT) | MAX_COUNTER
        any_drift = basemax + final_off > thresh

    return new_store, PallasFaninResult(
        new_canonical=new_canonical,
        win=outs[9].reshape(n).astype(bool),
        any_dup=any_dup,
        any_drift=any_drift,
    )


def _max_local_lt(cs: SplitChangeset, local_node: jax.Array) -> jax.Array:
    """Max logicalTime over the changeset's local-node records (the
    closed-form dup-candidate bound); NEG when there are none."""
    loc = cs.node.astype(jnp.int32) == local_node
    ml_hi = jnp.max(jnp.where(loc, cs.hi, NEG_HI))
    ml_lo = jnp.max(jnp.where(loc & (cs.hi == ml_hi), cs.lo, 0))
    return _join64(ml_hi, ml_lo)


def _launch_stream_grid(exact_guards, advance_clock, store, cs,
                        canonical_lt, local_node, wall_millis, m_hi, m_lo,
                        *, cs_block_rows, cs_index_map, n_chunks,
                        interpret, lane=_LANE):
    """Shared pallas_call plumbing for the (row_blocks, n_chunks) grid:
    scalar stack, block specs, reshapes, out shapes, store aliasing.
    The two wrappers differ only in the kernel's static flags, the
    changeset block geometry/index map, and the tile lane width."""
    r, n = _cs_shape(cs)
    rows = n // lane
    canon_hi, canon_lo = _split64(canonical_lt)
    thresh_hi, thresh_lo = _split64(
        ((wall_millis + MAX_DRIFT) << SHIFT) | MAX_COUNTER)
    scalars = jnp.stack([
        canon_hi, canon_lo.astype(jnp.int32), local_node,
        thresh_hi, thresh_lo.astype(jnp.int32),
        m_hi, m_lo.astype(jnp.int32)]).astype(jnp.int32)

    _i32 = jnp.int32
    cs_spec = pl.BlockSpec((cs_block_rows, _SB, lane), cs_index_map,
                           memory_space=pltpu.VMEM)
    st_spec = pl.BlockSpec((_SB, lane), lambda i, c: (_i32(i), _i32(0)),
                           memory_space=pltpu.VMEM)
    flag_spec = pl.BlockSpec((1, 1), lambda i, c: (_i32(0), _i32(0)),
                             memory_space=pltpu.SMEM)

    st2d = [ln.reshape(rows, lane) for ln in store]
    if cs.hi.ndim == 3 and cs.hi.shape[2] == lane:
        cs3d = list(cs)    # pre-tiled: no per-call relayout copy
    else:
        if cs.hi.ndim == 3:   # tiled at another lane width: flatten
            cs = type(cs)(*(ln.reshape(r, n) for ln in cs))
        cs3d = [ln.reshape(r, rows, lane) for ln in cs]

    out_shapes = (
        [jax.ShapeDtypeStruct((rows, lane), ln.dtype) for ln in st2d] +
        [jax.ShapeDtypeStruct((rows, lane), jnp.int32),   # win (OR)
         jax.ShapeDtypeStruct((1, 1), jnp.int32),         # any_dup
         jax.ShapeDtypeStruct((1, 1), jnp.int32)])        # any_drift
    out_specs = [st_spec] * 9 + [st_spec, flag_spec, flag_spec]
    if not advance_clock:
        # Batch-mode vector accumulators: ONE (_SB, lane) block shared
        # by every grid step (constant index map; TPU grids run
        # sequentially). Batch-only — unused resident outputs are not
        # free (three extra blocks measurably slowed the stream).
        acc_spec = pl.BlockSpec((_SB, lane),
                                lambda i, c: (_i32(0), _i32(0)),
                                memory_space=pltpu.VMEM)
        out_shapes += [jax.ShapeDtypeStruct((_SB, lane), jnp.int32),
                       jax.ShapeDtypeStruct((_SB, lane), jnp.uint32),
                       jax.ShapeDtypeStruct((_SB, lane), jnp.int32)]
        out_specs += [acc_spec] * 3

    n_cs = len(cs3d)   # 6 wide lanes, 5 in value-ref (narrow) mode
    return pl.pallas_call(
        partial(_fanin_stream_kernel, exact_guards, advance_clock,
                n_cs == 5),
        grid=(rows // _SB, n_chunks),
        in_specs=([pl.BlockSpec((7,), lambda i, c: (_i32(0),),
                                memory_space=pltpu.SMEM)] +
                  [cs_spec] * n_cs + [st_spec] * 9),
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shapes),
        input_output_aliases={1 + n_cs + j: j for j in range(9)},
        interpret=interpret,
    )(scalars, *cs3d, *st2d)


@partial(jax.jit,
         static_argnames=("chunk_rows", "interpret", "value_width"))
def _model_fanin_batch_jit(store, cs, canonical_lt, local_node,
                           wall_millis, *, chunk_rows: int = 16,
                           interpret: bool = False,
                           value_width: int = 64):
    """The model layer's ONE-dispatch merge: wide `DenseStore` +
    `DenseChangeset` in, wide store out — split/convert, the batch
    kernel, and the re-join all inside a single jit. On remote-proxied
    backends every separate dispatch costs a host round trip, and an
    unfused model merge was paying four of them per call.

    Returns ``(new_store, PallasFaninResult, seen, val_overflow)``
    where ``seen`` counts valid lanes (the stats counter) and
    ``val_overflow`` flags value_width=32 range violations (those
    records are masked out of the join, never truncated)."""
    if value_width == 32:
        fits = cs.val.astype(jnp.int32).astype(jnp.int64) == cs.val
        val_overflow = jnp.any(cs.valid & ~fits)
        cs = cs._replace(valid=cs.valid & fits)
        scs, _ = split_changeset_narrow.__wrapped__(cs)
    else:
        val_overflow = jnp.asarray(False)
        scs = split_changeset.__wrapped__(cs)
    seen = jnp.sum(cs.valid)
    sst = split_store.__wrapped__(store)
    out, res = pallas_fanin_batch.__wrapped__(
        sst, scs, canonical_lt, local_node, wall_millis,
        chunk_rows=chunk_rows, interpret=interpret)
    return join_store.__wrapped__(out), res, seen, val_overflow


def model_fanin_batch(store, cs, canonical_lt, local_node, wall_millis,
                      **kw):
    """Ledger-recording host entry for `_model_fanin_batch_jit` (the
    fused model merge; see its docstring for semantics)."""
    with _obs_device.record("pallas.model_fanin_batch",
                            dim=cs.valid.shape[0]):
        return _model_fanin_batch_jit(store, cs, canonical_lt,
                                      local_node, wall_millis, **kw)


# Trace-time composition (`pipelined_model_step`) fuses through the
# UN-jitted body, bypassing the ledger wrapper — in-jit calls are not
# dispatches.
model_fanin_batch.__wrapped__ = _model_fanin_batch_jit.__wrapped__


@partial(jax.jit,
         static_argnames=("chunk_rows", "interpret", "value_width"))
def _model_fanin_split_jit(store, cs, node_map, canonical_lt,
                           local_node, wall_millis, *,
                           chunk_rows: int = 16, interpret: bool = False,
                           value_width: int = 64):
    """`model_fanin_batch` for a PRE-SPLIT (optionally pre-tiled)
    changeset — the zero-conversion gossip path: peers exchange the
    kernel wire form (`DenseCrdt.export_split_delta`) and the merge
    skips the per-call split/tile entirely.

    ``node_map`` (int16[peer_table_len]) rewrites the changeset's
    node ordinals into the local table IN-JIT (each eager dispatch is
    a host round trip on proxied backends; pass the identity map when
    tables already match — the gather fuses away to a copy).

    Same return contract as `model_fanin_batch`:
    ``(new_store, PallasFaninResult, seen, val_overflow)``. A
    value_width=32 replica receiving WIDE split lanes masks records
    whose payload is not a sign-extension of its low word (invalid,
    never truncated) and flags ``val_overflow``; narrow lanes fit by
    construction."""
    idx = jnp.clip(cs.node, 0, node_map.shape[0] - 1).astype(jnp.int32)
    cs = cs._replace(node=jnp.where(
        cs.node == jnp.int16(I16_NEG), jnp.int16(I16_NEG),
        node_map[idx]))
    if value_width == 32 and not isinstance(cs, NarrowSplitChangeset):
        fits = cs.val_hi == (
            cs.val_lo.astype(jnp.int32) >> 31)
        val_overflow = jnp.any((cs.hi != NEG_HI) & ~fits)
        inval = ~fits
        # Full sentinel masking (hi AND lo AND node): a half-masked
        # entry with hi=NEG_HI but a nonzero lo would beat an empty
        # store slot's (NEG_HI, 0) in the strict lex compare.
        cs = cs._replace(
            hi=jnp.where(inval, jnp.int32(NEG_HI), cs.hi),
            lo=jnp.where(inval, jnp.uint32(0), cs.lo),
            node=jnp.where(inval, jnp.int16(I16_NEG), cs.node))
    else:
        val_overflow = jnp.asarray(False)
    seen = jnp.sum(cs.hi != NEG_HI)
    sst = split_store.__wrapped__(store)
    out, res = pallas_fanin_batch.__wrapped__(
        sst, cs, canonical_lt, local_node, wall_millis,
        chunk_rows=chunk_rows, interpret=interpret)
    return join_store.__wrapped__(out), res, seen, val_overflow


def model_fanin_split(store, cs, node_map, canonical_lt, local_node,
                      wall_millis, **kw):
    """Ledger-recording host entry for `_model_fanin_split_jit` (the
    pre-split fused model merge; see its docstring for semantics)."""
    with _obs_device.record("pallas.model_fanin_split",
                            dim=cs.hi.shape[0]):
        return _model_fanin_split_jit(store, cs, node_map, canonical_lt,
                                      local_node, wall_millis, **kw)


model_fanin_split.__wrapped__ = _model_fanin_split_jit.__wrapped__


@partial(jax.jit,
         static_argnames=("chunk_rows", "interpret", "value_width"))
def _pipelined_model_step_jit(store, cs, canonical, any_bad, overflow,
                              drift, val_ovf, first_idx, local_node,
                              wall_merge, wall_send, merge_idx, *,
                              chunk_rows: int = 16,
                              interpret: bool = False,
                              value_width: int = 64):
    """One COARSE pipelined merge as a single dispatch: the fused
    model merge (`model_fanin_batch`) plus the window bookkeeping the
    model layer otherwise runs as separate eager ops — flag
    OR-accumulation, first-flag attribution, and the final send bump
    (`ops.merge.send_step`). On remote-proxied backends each separate
    dispatch is a host round trip; at the north-star e2e shape the
    bookkeeping dispatches were costing more than the merge itself.

    ``wall_merge`` is the absorption-phase wall read, ``wall_send``
    the send bump's — two reads, like the unfused path, so injected
    clocks tick identically. Returns the full window-state update:
    ``(new_store, new_canonical, any_bad, overflow, drift,
    val_overflow, first_flag_idx, win_count, win, seen)``."""
    new_store, pres, seen, voverflow = model_fanin_batch.__wrapped__(
        store, cs, canonical, local_node, wall_merge,
        chunk_rows=chunk_rows, interpret=interpret,
        value_width=value_width)
    return _pipelined_tail(new_store, pres, seen, voverflow,
                           value_width, any_bad, overflow, drift,
                           val_ovf, first_idx, merge_idx, wall_send)


def pipelined_model_step(store, cs, *args, **kw):
    """Ledger-recording host entry for `_pipelined_model_step_jit`
    (the coarse pipelined merge; see its docstring for semantics)."""
    with _obs_device.record("pallas.pipelined_model_step",
                            dim=cs.valid.shape[0]):
        return _pipelined_model_step_jit(store, cs, *args, **kw)


pipelined_model_step.__wrapped__ = _pipelined_model_step_jit.__wrapped__


@partial(jax.jit,
         static_argnames=("chunk_rows", "interpret", "value_width"))
def _pipelined_model_step_split_jit(store, cs, node_map, canonical,
                                    any_bad, overflow, drift, val_ovf,
                                    first_idx, local_node, wall_merge,
                                    wall_send, merge_idx, *,
                                    chunk_rows: int = 16,
                                    interpret: bool = False,
                                    value_width: int = 64):
    """`pipelined_model_step` for PRE-SPLIT changesets (`merge_split`
    in a coarse window) — the interchange path gets the same
    one-dispatch treatment, else fusing only the wide path would make
    the zero-conversion gossip route the slower of the two."""
    new_store, pres, seen, voverflow = model_fanin_split.__wrapped__(
        store, cs, node_map, canonical, local_node, wall_merge,
        chunk_rows=chunk_rows, interpret=interpret,
        value_width=value_width)
    return _pipelined_tail(new_store, pres, seen, voverflow,
                           value_width, any_bad, overflow, drift,
                           val_ovf, first_idx, merge_idx, wall_send)


def pipelined_model_step_split(store, cs, *args, **kw):
    """Ledger-recording host entry for
    `_pipelined_model_step_split_jit` (the pre-split coarse pipelined
    merge; see its docstring for semantics)."""
    with _obs_device.record("pallas.pipelined_model_step_split",
                            dim=cs.hi.shape[0]):
        return _pipelined_model_step_split_jit(store, cs, *args, **kw)


pipelined_model_step_split.__wrapped__ = \
    _pipelined_model_step_split_jit.__wrapped__


def _pipelined_tail(new_store, pres, seen, voverflow, value_width,
                    any_bad, overflow, drift, val_ovf, first_idx,
                    merge_idx, wall_send):
    """Shared in-jit window bookkeeping: flag OR-accumulation,
    first-flag attribution, and the final send bump."""
    from .merge import send_step
    recv_flag = pres.any_dup | pres.any_drift
    new_flags = recv_flag | (voverflow if value_width == 32
                             else jnp.asarray(False))
    newly = (first_idx < 0) & new_flags
    first_idx = jnp.where(newly, merge_idx, first_idx)
    new_lt, s_ovf, s_drift = send_step.__wrapped__(pres.new_canonical,
                                                   wall_send)
    newly2 = (first_idx < 0) & (s_ovf | s_drift)
    first_idx = jnp.where(newly2, merge_idx, first_idx)
    return (new_store, new_lt, any_bad | recv_flag, overflow | s_ovf,
            drift | s_drift, val_ovf | voverflow, first_idx,
            jnp.sum(pres.win).astype(jnp.int32), pres.win, seen)


@partial(jax.jit, static_argnames=("chunk_rows", "interpret"))
def pallas_fanin_batch(store: SplitStore, cs: SplitChangeset,
                       canonical_lt: jax.Array, local_node: jax.Array,
                       wall_millis: jax.Array, *, chunk_rows: int = 8,
                       interpret: bool = False
                       ) -> Tuple[SplitStore, PallasFaninResult]:
    """ONE logical merge of an [R, N] changeset, walked in-kernel as
    ``R / chunk_rows`` DISTINCT row groups with the store block
    VMEM-resident across the chunk grid dimension — the kernel
    counterpart of `ops.dense.fanin_stream` (union-final canonical
    stamping, no per-chunk clock offsets). Store lanes, ``win``, and
    ``new_canonical`` match `pallas_fanin_step` on the full batch
    bit-for-bit; guard flags are the optimistic closed-form superset
    (`pallas_fanin_stream` guards="fast" contract): the model layer
    recomputes exactly on host when one trips.

    ``r`` must be a multiple of ``chunk_rows`` (pad with invalid rows)
    and ``n_slots`` a multiple of ``TILE``."""
    r, n = _cs_shape(cs)
    if n % TILE:  # ValueError, not assert: must survive `python -O`
        raise ValueError(f"n_slots={n} not a multiple of TILE={TILE}")
    if r % chunk_rows:
        raise ValueError(f"replica rows {r} not a multiple of "
                         f"chunk_rows={chunk_rows} (pad with invalid rows)")
    n_chunks = r // chunk_rows

    # No XLA pre-reductions: basemax and the dup bound come OUT of the
    # kernel (the rows are resident in VMEM there anyway; separate XLA
    # sweeps over the [R, N] lanes cost more than the whole join —
    # docs/PERF.md round 5). Chunk c reads row group c — the block
    # index map's only difference from the replay stream.
    outs = _launch_stream_grid(
        False, False, store, cs, canonical_lt, local_node, wall_millis,
        jnp.int32(0), jnp.uint32(0), cs_block_rows=chunk_rows,
        cs_index_map=lambda i, c: (c, jnp.int32(i), jnp.int32(0)),
        n_chunks=n_chunks, interpret=interpret)

    # Reduce the kernel's one accumulated (_SB, lane) block here in
    # XLA (4096 elements — negligible next to the lanes themselves).
    acc_hi, acc_lo, dupacc = outs[12], outs[13], outs[14]
    bm_hi = jnp.max(acc_hi)
    bm_lo = jnp.max(jnp.where(acc_hi == bm_hi, acc_lo, 0))
    basemax = _join64(bm_hi, bm_lo)
    thresh = ((wall_millis + MAX_DRIFT) << SHIFT) | MAX_COUNTER
    new_canonical = jnp.maximum(canonical_lt, basemax)
    win2d = outs[9]
    # Winners' modified stamp as a cheap elementwise post-pass over the
    # three mod lanes only (the kernel carried the store's through):
    # nc wasn't known until the kernel's own basemax came back.
    nc_hi, nc_lo = _split64(new_canonical)
    winb = win2d > 0
    mod_hi = jnp.where(winb, nc_hi, outs[6])
    mod_lo = jnp.where(winb, nc_lo, outs[7])
    mod_node = jnp.where(winb, local_node, outs[8])
    new_store = SplitStore(*(
        o.reshape(n) for o in
        (outs[0], outs[1], outs[2], outs[3], outs[4], outs[5],
         mod_hi, mod_lo, mod_node)))

    # Optimistic superset flags (no offsets, so the c=0 bound covers
    # every chunk): a local-node record above the pre-merge canonical
    # (OR-accumulated in-kernel), or any record past the drift
    # threshold.
    return new_store, PallasFaninResult(
        new_canonical=new_canonical,
        win=win2d.reshape(n).astype(bool),
        any_dup=jnp.max(dupacc) > 0,
        any_drift=basemax > thresh,
    )

"""SQLite-backed persistent CRDT — the out-of-tree plugin pattern,
in-tree.

The reference documents persistent backends as `Crdt` subclasses built
outside the package (README.md:39 points at hive_crdt; the abstract
storage slots at crdt.dart:140-169 are the plugin contract, and the
`modified` field exists precisely so such backends can answer delta
queries, CHANGELOG.md:14-15). This module is that pattern realized on
Python's stdlib `sqlite3`: a durable replica that speaks the same wire
format, runs the same conformance suite, and can sync with any other
backend (`MapCrdt`, `TpuMapCrdt`, `DenseCrdt`) or an external JSON
peer.

Storage model — one table, one row per record:

- ``hlc``/``modified`` persist through the reference string codec
  (hlc.dart:102-104), so a row is meaningful to any replica.
- ``lt``/``modified_lt`` are the packed 64-bit logicalTimes
  (hlc.dart:16) as INTEGER columns: ``refresh_canonical_time`` is
  ``MAX(lt)`` (the efficient override the reference invites,
  crdt.dart:113) and the inclusive delta bound (map_crdt.dart:44-45)
  is an indexed ``modified_lt >= ?`` scan.
- ``value`` is JSON text; SQL ``NULL`` is the tombstone
  (record.dart:17). Custom value types plug in via
  ``value_encoder``/``value_decoder`` (record.dart:3-9 typedefs).

Resume-from-disk is the constructor: opening an existing database file
seeds the canonical clock from the stored max (crdt.dart:31-33).
"""

from __future__ import annotations

import json
import sqlite3
from typing import Any, Callable, Dict, Generic, Optional, TypeVar

from ..crdt import Crdt
from ..hlc import Hlc
from ..record import Record
from ..watch import ChangeHub, ChangeStream

K = TypeVar("K")
V = TypeVar("V")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS records (
    key TEXT PRIMARY KEY,
    hlc TEXT NOT NULL,
    lt INTEGER NOT NULL,
    value TEXT,
    modified TEXT NOT NULL,
    modified_lt INTEGER NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_records_modified_lt
    ON records (modified_lt);
"""


class SqliteCrdt(Crdt[K, V], Generic[K, V]):
    """A durable LWW-map CRDT on a SQLite database.

    ``path=":memory:"`` (the default) is an ephemeral store useful for
    tests; a filesystem path makes the replica survive restarts —
    reconstructing is just ``SqliteCrdt(node_id, path)`` again.

    Like the reference's single-isolate model, a replica instance is
    single-threaded (sqlite3's default ``check_same_thread`` guard is
    left on); cross-thread consumption happens through the watch
    streams (`watch().aiter()` marshals onto the consumer's loop).
    """

    def __init__(self, node_id: Any, path: str = ":memory:", *,
                 wall_clock: Optional[Callable[[], int]] = None,
                 key_encoder: Optional[Callable[[K], str]] = None,
                 key_decoder: Optional[Callable[[str], K]] = None,
                 value_encoder: Optional[Callable[[V], Any]] = None,
                 value_decoder: Optional[Callable[[Any], V]] = None,
                 node_decoder: Optional[Callable[[str], Any]] = None,
                 check_same_thread: bool = True):
        self._node_id = node_id
        # check_same_thread=False is required to serve this replica
        # from another thread (e.g. `crdt_tpu.net.SyncServer`); the
        # single-threaded-replica contract still applies — ALL access
        # must be externally serialized (the server's lock does this).
        self._conn = sqlite3.connect(
            path, check_same_thread=check_same_thread)
        self._conn.executescript(_SCHEMA)
        self._key_enc = key_encoder or str
        self._key_dec = key_decoder or (lambda s: s)
        self._val_enc = value_encoder or (lambda v: v)
        self._val_dec = value_decoder or (lambda v: v)
        # HLC node ids persist as text; without a decoder a non-str
        # node_id would parse back as str and break tie-break compares
        # and duplicate-node detection against the typed canonical
        # clock. Default to the node_id's own type (int("7") etc.);
        # exotic types must pass node_decoder explicitly.
        if node_decoder is None and not isinstance(node_id, str):
            node_decoder = type(node_id)
        self._node_dec = node_decoder
        self._hub = ChangeHub()
        super().__init__(wall_clock=wall_clock)

    @property
    def node_id(self) -> Any:
        return self._node_id

    def close(self) -> None:
        self._conn.close()

    def __enter__(self) -> "SqliteCrdt[K, V]":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- codecs ---

    def _encode_row(self, key: K, record: Record[V]):
        value = (None if record.value is None
                 else json.dumps(self._val_enc(record.value)))
        return (self._key_enc(key), str(record.hlc),
                record.hlc.logical_time, value, str(record.modified),
                record.modified.logical_time)

    def _decode_row(self, row) -> Record[V]:
        _, hlc, _, value, modified, _ = row
        return Record(
            Hlc.parse(hlc, id_decoder=self._node_dec),
            None if value is None else self._val_dec(json.loads(value)),
            Hlc.parse(modified, id_decoder=self._node_dec))

    # --- efficient clock rebuild (crdt.dart:113: "should be overridden
    # if the implementation can do it more efficiently") ---

    def refresh_canonical_time(self) -> None:
        (max_lt,) = self._conn.execute(
            "SELECT COALESCE(MAX(lt), 0) FROM records").fetchone()
        self._canonical_time = Hlc.from_logical_time(max_lt, self._node_id)

    # --- storage primitives (crdt.dart:140-169) ---

    def contains_key(self, key: K) -> bool:
        return self._conn.execute(
            "SELECT 1 FROM records WHERE key = ?",
            (self._key_enc(key),)).fetchone() is not None

    def get_record(self, key: K) -> Optional[Record[V]]:
        row = self._conn.execute(
            "SELECT * FROM records WHERE key = ?",
            (self._key_enc(key),)).fetchone()
        return None if row is None else self._decode_row(row)

    # Upsert (NOT "INSERT OR REPLACE", which deletes + reinserts and so
    # reassigns the rowid): existing keys keep their rowid, making
    # `ORDER BY rowid` the dict-like first-insertion iteration order —
    # wire bytes match the in-memory backends op-for-op.
    _UPSERT = (
        "INSERT INTO records VALUES (?, ?, ?, ?, ?, ?) "
        "ON CONFLICT(key) DO UPDATE SET hlc=excluded.hlc, "
        "lt=excluded.lt, value=excluded.value, "
        "modified=excluded.modified, modified_lt=excluded.modified_lt")

    def put_record(self, key: K, record: Record[V]) -> None:
        with self._conn:
            self._conn.execute(self._UPSERT, self._encode_row(key, record))
        self._hub.add(key, record.value)

    def put_records(self, record_map: Dict[K, Record[V]]) -> None:
        with self._conn:
            self._conn.executemany(
                self._UPSERT,
                [self._encode_row(k, r) for k, r in record_map.items()])
        for key, record in record_map.items():
            self._hub.add(key, record.value)

    def _local_records_for(self, keys) -> Dict[K, Record[V]]:
        # Keyed lookup so delta merges are O(delta) rows, not a full
        # table scan+parse (the whole point of a beyond-memory store).
        encoded = [self._key_enc(k) for k in keys]
        out: Dict[K, Record[V]] = {}
        for i in range(0, len(encoded), 500):  # SQLite host-param cap
            batch = encoded[i:i + 500]
            rows = self._conn.execute(
                "SELECT * FROM records WHERE key IN "
                f"({','.join('?' * len(batch))})", batch)
            out.update({self._key_dec(row[0]): self._decode_row(row)
                        for row in rows})
        return out

    def record_map(self, modified_since: Optional[Hlc] = None
                   ) -> Dict[K, Record[V]]:
        if modified_since is None:
            # No WHERE clause: a `>= 0` default would silently drop rows
            # whose modified HLC has pre-epoch (negative) millis —
            # reachable via the public put_record primitive, where the
            # reference recordMap() returns all records.
            rows = self._conn.execute(
                "SELECT * FROM records ORDER BY rowid")
        else:
            rows = self._conn.execute(
                "SELECT * FROM records WHERE modified_lt >= ? "
                "ORDER BY rowid", (modified_since.logical_time,))
        return {self._key_dec(row[0]): self._decode_row(row)
                for row in rows}

    def watch(self, key: Optional[K] = None) -> ChangeStream:
        return self._hub.stream(key)

    def purge(self) -> None:
        with self._conn:
            self._conn.execute("DELETE FROM records")

"""Hardware validation: the Mosaic kernels vs the XLA fold ON THE CHIP.

The test suite proves kernel correctness in interpreter mode on CPU;
this script closes the remaining gap — Mosaic compilation could in
principle diverge from the interpreter — by running randomized
differentials on the real accelerator:

1. `pallas_fanin_stream` (exact and fast guards) vs the sequential
   XLA fold with threaded clocks — store lanes, win, canonical.
2. `pallas_fanin_batch` vs one-shot `fanin_step` on the same batch.
3. `DenseCrdt(executor="pallas")` vs `DenseCrdt(executor="xla")`
   through the model API, including a guard-trip exception.

Exits 0 and prints PASS per section; any mismatch raises.

Usage: python benchmarks/validate_on_chip.py [--keys 32768] [--seeds 3]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root

import numpy as np

import jax
import jax.numpy as jnp

from bench import build_stream_fn, make_changeset, _MILLIS
from crdt_tpu.hlc import SHIFT
from crdt_tpu.ops.dense import empty_dense_store, fanin_step
from crdt_tpu.ops.pallas_merge import (join_store, pallas_fanin_batch,
                                       pallas_fanin_stream,
                                       split_changeset, split_store)


from crdt_tpu.testing import assert_dense_stores_equal as \
    assert_lanes_equal  # noqa: E402  (one definition of store equality)


def validate_stream(n_keys, n_chunks, seed):
    cs = make_changeset(4, n_keys, seed=seed, fill=0.7)
    canonical = jnp.int64(_MILLIS << SHIFT)
    wall = jnp.int64(_MILLIS + 10_000)
    store = empty_dense_store(n_keys)

    # The reference semantics ARE bench.build_stream_fn — use it, so
    # the validator can't drift from the contract the bench measures.
    ref_store, ref_canon = build_stream_fn(n_chunks)(
        store, cs, canonical, jnp.int32(0), wall)
    ref_canon = int(ref_canon)

    for guards in ("exact", "fast"):
        sst, sres = pallas_fanin_stream(
            split_store(store), split_changeset(cs), canonical,
            jnp.int32(0), wall, n_chunks=n_chunks, guards=guards)
        assert_lanes_equal(ref_store, join_store(sst),
                           f"stream[{guards}] seed={seed}")
        assert int(sres.new_canonical) == ref_canon, guards
        assert not bool(sres.any_dup) and not bool(sres.any_drift)

    # Positive guard path ON HARDWARE: a local-node (ordinal 0) record
    # ahead of the canonical clock must set any_dup in BOTH guard
    # modes (the exact in-kernel cummax chain and the closed-form
    # bound) — an all-clear-only check would miss a compiled-kernel
    # flag bug.
    dup_cs = cs._replace(
        node=cs.node.at[0, 0].set(0),
        valid=cs.valid.at[0, 0].set(True))
    for guards in ("exact", "fast"):
        _, dres = pallas_fanin_stream(
            split_store(store), split_changeset(dup_cs),
            jnp.int64(0), jnp.int32(0), wall, n_chunks=n_chunks,
            guards=guards)
        assert bool(dres.any_dup), f"dup flag missed [{guards}]"
    print(f"PASS stream n_chunks={n_chunks} seed={seed} "
          f"(exact+fast bit-identical to XLA fold; dup flag fires)")


def validate_batch(n_keys, seed):
    cs = make_changeset(16, n_keys, seed=seed + 50, fill=0.6)
    canonical = jnp.int64(_MILLIS << SHIFT)
    wall = jnp.int64(_MILLIS + 10_000)
    store = empty_dense_store(n_keys)

    ref_store, ref_res = fanin_step(store, cs, canonical, jnp.int32(0),
                                    wall)
    sst, sres = pallas_fanin_batch(
        split_store(store), split_changeset(cs), canonical, jnp.int32(0),
        wall, chunk_rows=8)
    assert_lanes_equal(ref_store, join_store(sst), f"batch seed={seed}")
    assert int(sres.new_canonical) == int(ref_res.new_canonical)
    np.testing.assert_array_equal(np.asarray(sres.win),
                                  np.asarray(ref_res.win))
    # value-ref (int32 val lane) mode, incl. negative payloads: the
    # sign-extension must land bit-identical on hardware too.
    from crdt_tpu.ops.pallas_merge import split_changeset_narrow
    ncs_src = cs._replace(
        val=((cs.val & 0xFFFFFFFF).astype(jnp.int32)).astype(jnp.int64))
    nref_store, nref_res = fanin_step(store, ncs_src, canonical,
                                      jnp.int32(0), wall)
    ncs, overflow = split_changeset_narrow(ncs_src)
    assert not bool(overflow)
    nst, nres = pallas_fanin_batch(
        split_store(store), ncs, canonical, jnp.int32(0), wall,
        chunk_rows=8)
    assert_lanes_equal(nref_store, join_store(nst),
                       f"narrow batch seed={seed}")
    assert int(nres.new_canonical) == int(nref_res.new_canonical)
    print(f"PASS batch seed={seed} (16 rows, chunked 8, == fanin_step; "
          "narrow valref32 incl. negatives)")


def validate_model(n_keys):
    from crdt_tpu import DenseCrdt, DuplicateNodeException
    from crdt_tpu.testing import FakeClock
    BASE = _MILLIS
    pal = DenseCrdt("ns", n_keys, wall_clock=FakeClock(start=BASE),
                    executor="pallas")
    xla = DenseCrdt("ns", n_keys, wall_clock=FakeClock(start=BASE),
                    executor="xla")
    peers = []
    for i, name in enumerate(["p1", "p2", "p3"]):
        p = DenseCrdt(name, n_keys, wall_clock=FakeClock(start=BASE + i))
        p.put_batch(jnp.arange(i * 100, i * 100 + 500),
                    jnp.arange(500, dtype=jnp.int64) + 1000 * i)
        p.delete_batch(jnp.arange(i * 100, i * 100 + 7))
        peers.append(p.export_delta())
    pal.merge_many(peers)
    xla.merge_many(peers)
    assert_lanes_equal(pal.store, xla.store, "model")
    assert pal.canonical_time == xla.canonical_time

    bad = DenseCrdt("ns", n_keys, wall_clock=FakeClock(start=BASE + 900))
    bad.put_batch([1], [1])
    payloads = []
    for c in (pal, xla):
        try:
            c.merge_many([bad.export_delta()])
            raise AssertionError("guard did not trip")
        except DuplicateNodeException as e:
            payloads.append((str(e), c.canonical_time.logical_time))
    assert payloads[0] == payloads[1], payloads
    print("PASS model (pallas executor == xla executor on chip, "
          "guard payloads identical)")


def validate_model_soak(n_keys, rounds, seed=0):
    """Randomized op-sequence soak: a pallas-executor replica and an
    xla-executor replica apply IDENTICAL random local writes, deletes,
    multi-peer merges, and clears; lane equality is asserted after
    every round."""
    import random
    from crdt_tpu import DenseCrdt
    from crdt_tpu.testing import FakeClock
    rng = random.Random(seed)
    BASE = _MILLIS
    pal_clk = FakeClock(start=BASE)
    xla_clk = FakeClock(start=BASE)
    pal = DenseCrdt("ns", n_keys, wall_clock=pal_clk, executor="pallas")
    xla = DenseCrdt("ns", n_keys, wall_clock=xla_clk, executor="xla")
    peer_clk = BASE
    for rnd in range(rounds):
        # Keep the replicas' wall clocks tracking the peer clock:
        # unbounded divergence would eventually trip the (correct)
        # drift guard as a harness artifact, not a finding.
        for clk in (pal_clk, xla_clk):
            clk.advance(max(0, peer_clk - clk.millis))
        op = rng.random()
        if op < 0.35:
            k = rng.randrange(1, 200)
            slots = rng.sample(range(n_keys), k)
            vals = [rng.randrange(1 << 40) for _ in slots]
            pal.put_batch(slots, vals)
            xla.put_batch(slots, vals)
        elif op < 0.5:
            slots = rng.sample(range(n_keys), rng.randrange(1, 50))
            pal.delete_batch(slots)
            xla.delete_batch(slots)
        elif op < 0.9:
            deltas = []
            for p in range(rng.randrange(1, 5)):
                peer_clk += rng.randrange(1, 4)
                peer = DenseCrdt(f"p{rng.randrange(6)}", n_keys,
                                 wall_clock=FakeClock(start=peer_clk))
                slots = rng.sample(range(n_keys), rng.randrange(1, 300))
                peer.put_batch(slots, [rng.randrange(1 << 40)
                                       for _ in slots])
                if rng.random() < 0.4:
                    peer.delete_batch(rng.sample(slots,
                                                 max(1, len(slots) // 4)))
                deltas.append(peer.export_delta())
            pal.merge_many(deltas)
            xla.merge_many(deltas)
        else:
            # clear(): tombstone every live slot via one batch
            pal.clear()
            xla.clear()
        assert_lanes_equal(pal.store, xla.store, f"soak round {rnd}")
        assert pal.canonical_time.logical_time == \
            xla.canonical_time.logical_time, rnd
    print(f"PASS soak ({rounds} rounds, seed={seed}: pallas == xla "
          "after every round)")


def validate_merge_split(n_keys, seed=0):
    """`merge_split` (pre-split interchange, tiled and untiled, wide
    and value-ref lanes) vs the wide `merge` path ON THE CHIP — the
    interpret-mode differentials re-run against real Mosaic output."""
    import random
    from crdt_tpu import DenseCrdt
    from crdt_tpu.ops.pallas_merge import split_changeset
    from crdt_tpu.testing import FakeClock
    rng = random.Random(seed)
    BASE = _MILLIS
    for value_width, tiled in ((64, True), (64, False), (32, True)):
        writers = []
        for i, nid in enumerate(("wa", "wb", "wc")):
            w = DenseCrdt(nid, n_keys, value_width=value_width,
                          wall_clock=FakeClock(start=BASE + 1 + i))
            slots = rng.sample(range(n_keys), 200)
            w.put_batch(slots, [rng.randrange(1 << 30) for _ in slots])
            w.delete_batch(rng.sample(slots, 40))
            writers.append(w)
        via_split = DenseCrdt("hub", n_keys, value_width=value_width,
                              wall_clock=FakeClock(start=BASE + 50))
        via_wide = DenseCrdt("hub", n_keys, value_width=value_width,
                             wall_clock=FakeClock(start=BASE + 50))
        for w in writers:
            scs, ids = w.export_split_delta(tiled=tiled)
            via_split.merge_split(scs, ids)
            via_wide.merge(*w.export_delta())
        assert_lanes_equal(via_split.store, via_wide.store,
                           f"merge_split vw={value_width} tiled={tiled}")
        assert (via_split.canonical_time.logical_time
                == via_wide.canonical_time.logical_time)
    # multi-row (r > chunk) padded form through the real kernel
    rows = 12
    lt = ((_MILLIS + np.random.default_rng(seed).integers(
        0, 50, (rows, n_keys))) << SHIFT)
    from crdt_tpu.ops.dense import DenseChangeset
    rng2 = np.random.default_rng(seed + 1)
    cs = DenseChangeset(
        lt=jnp.asarray(lt, jnp.int64),
        node=jnp.asarray(rng2.integers(0, 3, (rows, n_keys)), jnp.int32),
        val=jnp.asarray(rng2.integers(0, 1 << 40, (rows, n_keys)),
                        jnp.int64),
        tomb=jnp.asarray(rng2.random((rows, n_keys)) < 0.3),
        valid=jnp.asarray(rng2.random((rows, n_keys)) < 0.7))
    ids = ["pa", "pb", "pc"]
    a = DenseCrdt("hub", n_keys, wall_clock=FakeClock(start=_MILLIS + 99))
    b = DenseCrdt("hub", n_keys, wall_clock=FakeClock(start=_MILLIS + 99))
    a.merge_split(split_changeset(cs), ids)
    b.merge(cs, ids)
    assert_lanes_equal(a.store, b.store, "merge_split multirow")
    # the one condition unique to this section: padding rows must not
    # leak their sentinels into the canonical either
    assert (a.canonical_time.logical_time
            == b.canonical_time.logical_time)
    print("PASS merge_split (tiled/untiled, wide/valref, multirow "
          "padded == wide merge on chip)")


def main():
    from crdt_tpu.ops.pallas_merge import TILE
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=4 * 8192)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--soak", type=int, default=0, metavar="ROUNDS",
                    help="additionally run a randomized op-sequence "
                         "soak of this many rounds")
    ap.add_argument("--soak-seed", type=int, default=0)
    args = ap.parse_args()
    if args.keys % TILE:
        ap.error(f"--keys must be a multiple of the Pallas tile "
                 f"({TILE}); got {args.keys}")
    print(f"platform: {jax.devices()[0].platform} ({jax.devices()[0]})")
    for seed in range(args.seeds):
        validate_stream(args.keys, n_chunks=4, seed=seed)
        validate_batch(args.keys, seed)
    validate_model(args.keys)
    validate_merge_split(args.keys)
    if args.soak:
        validate_model_soak(args.keys, args.soak, seed=args.soak_seed)
    print("ALL PASS")


if __name__ == "__main__":
    main()

"""Pod-local collective anti-entropy: the lattice join as ONE
donated, jit-cached ``shard_map`` program over a 1-D member mesh.

The socket path replicates co-located members the same way it
replicates cross-pod peers: pack → frame → loopback → unpack → merge,
once per peer pair. But the state-based merge discipline says the
lattice join IS the whole protocol — so for N replicas that share a
mesh, anti-entropy can be an all-reduce instead of N(N-1) wire
exchanges. This module composes that all-reduce from the exact join
rules the pairwise kernels apply:

- **clock lanes** — the lexicographic ``(lt, node)`` max, built from
  primitive collectives the way `fanin._fanin_block` does: ``pmax``
  lt → masked ``pmax`` node → stable ``pmin`` flat-rank tie →
  one-hot ``psum`` winner broadcast. Ties on identical HLCs pick the
  lowest member rank (identical events carry identical payloads by
  the uniqueness invariant, so the pick is payload-neutral).
- **value lane** — per-tag G-ary joins matching a pairwise fold of
  `semantics.kernels.typed_join_lanes`: LWW takes the clock winner's
  payload; gcounter is a plain ``pmax`` (0 is the join identity);
  pncounter ``pmax``es each 31-bit half; orset ``pmax``es all 16
  causal-length nibbles in one stacked collective; mvreg
  ``all_gather``s the packs of members holding the winning lt and
  folds `_mvreg_union` over them (the empty pack 0 is its identity).
- **tomb / occupied** — the clock winner's flag; presence is the
  member-axis OR.

The post-join digest-tree leaves are computed in the SAME program
(the joined lanes are replicated across members by construction, so
each member digests its own output block and the levels come out
``P()``), and per-member ``mod`` stamps + repack masks ride along —
one dispatch yields everything `CollectiveGroup.join` needs to
pre-seed the pack and digest caches exactly like `merge_and_repack`.

On a real pod the member axis rides ICI; on the 1-core virtual mesh
(tests, `bench.py --mode collective`) the same program runs across
virtual CPU devices — bit-identical results, honest-downscale timing.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..obs import device as _obs_device

_obs_device.register("parallel.collective_join")

from ..ops.dense import DenseStore, _NEG, _I32_NEG
from ..ops.digest import (fold_leaves, slot_digests,
                          tree_levels_from_leaves)
from ..semantics.kernels import (ORSET_UNIVERSE, SEM_GCOUNTER,
                                 SEM_MVREG, SEM_ORSET, SEM_PNCOUNTER,
                                 _PN_HALF, _mvreg_union)
from .fanin import Mesh, P, _BIG_RANK, _make_mesh, _shard_map

#: The 1-D replica-group axis. Distinct from fanin's REPLICA_AXIS on
#: purpose: a fan-in mesh shards *changeset rows*; a collective mesh
#: places one whole member replica per device.
MEMBER_AXIS = "member"


class CollectiveJoinResult(NamedTuple):
    new_canonical: jax.Array        # int64 scalar, replicated
    win: jax.Array                  # bool[G, N] per-member changed mask
    repack: jax.Array               # bool[G, N] mod_lt >= since mask
    levels: Tuple[jax.Array, ...]   # post-join digest levels, root-first


def make_collective_mesh(n_members: int, devices=None) -> Mesh:
    """A 1-D ``(member,)`` mesh over the first ``n_members`` devices
    (or the given device list)."""
    if devices is None:
        devices = jax.devices()[:n_members]
    return _make_mesh((n_members,), (MEMBER_AXIS,), devices)


def _typed_group_val(sem, occ, l_lt, l_val, m1, w_val):
    """G-ary value join by tag over the member axis. Equal to folding
    `typed_join_lanes`'s pairwise value rule over the group in any
    order — each branch is a commutative/associative join with 0 as
    the absent-member identity, which is exactly what lets it ride
    collectives instead of a fold."""
    ax = MEMBER_AXIS
    gmax = jax.lax.pmax(l_val, ax)
    pos = jax.lax.pmax((l_val >> 32) & _PN_HALF, ax)
    neg = jax.lax.pmax(l_val & _PN_HALF, ax)
    pn = (pos << 32) | neg
    # orset: all 16 causal-length nibbles in ONE stacked pmax.
    nibs = jnp.stack([(l_val >> (4 * i)) & 0xF
                      for i in range(ORSET_UNIVERSE)])
    g_nibs = jax.lax.pmax(nibs, ax)
    ors = jnp.zeros_like(l_val)
    for i in range(ORSET_UNIVERSE):
        ors = ors | (g_nibs[i] << (4 * i))
    # mvreg: strictly newer lt wins outright, equal lt unions — so
    # only members holding the winning lt contribute their pack, and
    # the union folds over a static G-row gather (0 = empty pack is
    # the `_mvreg_union` identity).
    cand = jnp.where(occ & (l_lt == m1), l_val, 0)
    packs = jax.lax.all_gather(cand, ax)
    mv = packs[0]
    for g in range(1, packs.shape[0]):
        mv = _mvreg_union(mv, packs[g])
    out = w_val
    out = jnp.where(sem == SEM_GCOUNTER, gmax, out)
    out = jnp.where(sem == SEM_PNCOUNTER, pn, out)
    out = jnp.where(sem == SEM_ORSET, ors, out)
    out = jnp.where(sem == SEM_MVREG, mv, out)
    return out


def _join_block(leaf_width: int, has_sem: bool, store: DenseStore,
                *rest):
    """Per-member body under shard_map: lanes arrive [1, N], scalars
    per member arrive [1]; ``sem`` (when present) and ``canonical_in``
    are replicated."""
    if has_sem:
        sem, since, me, canonical_in = rest
    else:
        since, me, canonical_in = rest
        sem = None
    lt, node, val = store.lt[0], store.node[0], store.val[0]
    occ, tomb = store.occupied[0], store.tomb[0]
    mod_lt, mod_node = store.mod_lt[0], store.mod_node[0]
    since_i, me_i = since[0], me[0]

    # Mask absent slots to the join identities so an unoccupied member
    # can never win a lane (mirrors reduce_replicas' valid masking).
    l_lt = jnp.where(occ, lt, _NEG)
    l_node = jnp.where(occ, node, _I32_NEG)
    l_val = jnp.where(occ, val, 0)
    l_tomb = occ & tomb

    # Lexicographic (lt, node) max over the group — the fanin block's
    # collective composition, verbatim.
    ax = MEMBER_AXIS
    m1 = jax.lax.pmax(l_lt, ax)
    node_cand = jnp.where(l_lt == m1, l_node, _I32_NEG)
    m2 = jax.lax.pmax(node_cand, ax)
    has = (l_lt == m1) & (l_node == m2)
    rank = jax.lax.axis_index(ax)
    winner_rank = jax.lax.pmin(jnp.where(has, rank, _BIG_RANK), ax)
    mine = has & (rank == winner_rank)
    w_val = jax.lax.psum(jnp.where(mine, l_val, 0), ax)
    w_tomb = jax.lax.psum(jnp.where(mine & l_tomb, 1, 0)
                          .astype(jnp.int32), ax) > 0
    g_occ = jax.lax.pmax(occ.astype(jnp.int32), ax) > 0

    if has_sem:
        v = _typed_group_val(sem, occ, l_lt, l_val, m1, w_val)
    else:
        v = w_val

    # Unoccupied-everywhere slots keep this member's own (zero) lanes
    # — never the _NEG/_I32_NEG sentinels.
    lt_out = jnp.where(g_occ, m1, lt)
    node_out = jnp.where(g_occ, m2, node)
    val_out = jnp.where(g_occ, v, val)
    tomb_out = jnp.where(g_occ, w_tomb, tomb)
    occ_out = occ | g_occ

    # Per-member adoption = changed-vs-own-input (the typed kernels'
    # `win` semantics; for LWW lanes it coincides with the strict
    # take mask, since adoption always moves lt or node or presence).
    win = ((lt_out != lt) | (node_out != node) | (val_out != val)
           | (tomb_out != tomb) | (occ_out & ~occ))

    new_canonical = jnp.maximum(
        canonical_in,
        jax.lax.pmax(jnp.max(jnp.where(occ, lt, _NEG)), ax))
    mod_lt_out = jnp.where(win, new_canonical, mod_lt)
    mod_node_out = jnp.where(win, me_i, mod_node)
    repack = occ_out & (mod_lt_out >= since_i)

    # Post-join digest leaves in the SAME program: the replicated
    # lanes are identical across members by construction, so every
    # member digests its own output block and the row is P().
    h = slot_digests(lt_out, val_out, tomb_out, occ_out,
                     sem=sem, idx_offset=None)
    leaves = fold_leaves(h, leaf_width)

    new_store = DenseStore(
        lt=lt_out[None], node=node_out[None], val=val_out[None],
        mod_lt=mod_lt_out[None], mod_node=mod_node_out[None],
        occupied=occ_out[None], tomb=tomb_out[None])
    return (new_store, win[None], repack[None], new_canonical, leaves)


@functools.lru_cache(maxsize=None)
def make_collective_join(mesh: Mesh, has_sem: bool, leaf_width: int,
                         donate: bool = False):
    """Build the jitted single-dispatch collective join for a member
    mesh.

    Returns ``step(stores, [sem,] since, me, canonical_in) ->
    (stacked_store, CollectiveJoinResult)`` where ``stores`` is a
    G-tuple of per-member `DenseStore`s (G = mesh extent), ``sem`` is
    the shared [N] int8 tag column (only when ``has_sem``), ``since``
    and ``me`` are [G] per-member watermark lts / node ordinals, and
    ``canonical_in`` is the max of the members' pre-join canonical
    lts. The returned store is stacked [G, N]; replicated lanes are
    identical across members, ``mod`` lanes are per-member.
    ``donate=True`` consumes the input store buffers (gate it off on
    CPU, where XLA ignores donation with a warning)."""
    g = mesh.shape[MEMBER_AXIS]
    store_spec = DenseStore(*([P(MEMBER_AXIS)]
                              * len(DenseStore._fields)))
    in_specs = ((store_spec,)
                + ((P(),) if has_sem else ())
                + (P(MEMBER_AXIS), P(MEMBER_AXIS), P()))
    join = _shard_map(
        functools.partial(_join_block, leaf_width, has_sem),
        mesh=mesh, in_specs=in_specs,
        out_specs=(store_spec, P(MEMBER_AXIS), P(MEMBER_AXIS),
                   P(), P()),
        check_vma=False)

    def _step(stores, *args):
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *stores)
        out_store, win, repack, canonical, leaves = join(stacked, *args)
        return out_store, CollectiveJoinResult(
            new_canonical=canonical, win=win, repack=repack,
            levels=tree_levels_from_leaves(leaves))

    jitted = jax.jit(_step, donate_argnums=(0,) if donate else ())

    def step(stores, *args):
        if len(stores) != g:
            raise ValueError(
                f"collective join over a {g}-member mesh got "
                f"{len(stores)} stores")
        with _obs_device.record("parallel.collective_join",
                                dim=stores[0].lt.shape[0],
                                donated=(stores[0].lt if donate
                                         else None)):
            return jitted(stores, *args)

    # The raw jitted program, for jaxpr tracing (analysis/jaxpr_audit)
    # without the ledger accounting a real dispatch carries.
    step.jitted = jitted
    return step

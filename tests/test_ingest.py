"""Write-path fast lane (crdt_tpu.models.ingest): batched HLC
stamping, the read-your-writes overlay, barrier draining, commit-time
watch events, sharded commits, and gossip rounds that drain mid-flight
staging — the acceptance suite for `DenseCrdt.ingest()`
(docs/INGEST.md)."""

import random

import numpy as np
import pytest

import jax

from crdt_tpu import DenseCrdt, GossipNode, RetryPolicy
from crdt_tpu.hlc import (MAX_COUNTER, MAX_DRIFT, ClockDriftException,
                          Hlc, OverflowException)
from crdt_tpu.models.dense_crdt import ShardedDenseCrdt
from crdt_tpu.models.keyed_dense import KeyedDenseCrdt
from crdt_tpu.parallel import make_fanin_mesh
from crdt_tpu.testing import FakeClock, FaultProxy, ScriptedSchedule

pytestmark = pytest.mark.ingest

BASE = 1_700_000_000_000
N = 64


def frozen():
    """A wall clock that never ticks: under it, staged and unbatched
    writes must produce BIT-IDENTICAL stamps (the combiner's one
    wall-read-per-flush is unobservable when the clock stands still)."""
    return lambda: BASE


# ---------------------------------------------------------- Hlc.send_batch


class TestSendBatch:

    def test_equals_sequential_sends_under_frozen_clock(self):
        canonical = Hlc(BASE, 3, "n")
        seq = canonical
        seq_lts = []
        for _ in range(5):
            seq = Hlc.send(seq, millis=BASE + 7)
            seq_lts.append(seq.logical_time)
        batched, lts = Hlc.send_batch(canonical, 5, millis=BASE + 7)
        assert lts == seq_lts
        assert batched == seq

    def test_stamps_strictly_monotonic_and_dominate_canonical(self):
        canonical = Hlc(BASE, 0, "n")
        new, lts = Hlc.send_batch(canonical, 100, millis=BASE)
        assert all(a < b for a, b in zip(lts, lts[1:]))
        assert lts[0] > canonical.logical_time
        assert new.logical_time == lts[-1]

    def test_overflow_raises_before_stamping(self):
        canonical = Hlc(BASE, MAX_COUNTER - 1, "n")
        with pytest.raises(OverflowException):
            Hlc.send_batch(canonical, 3, millis=BASE)

    def test_drift_raises(self):
        canonical = Hlc(BASE + MAX_DRIFT + 1, 0, "n")
        with pytest.raises(ClockDriftException):
            Hlc.send_batch(canonical, 1, millis=BASE)

    def test_rejects_empty_batch(self):
        with pytest.raises(ValueError):
            Hlc.send_batch(Hlc(BASE, 0, "n"), 0, millis=BASE)


# ------------------------------------------------- staged == unbatched


def _write_script(c: DenseCrdt) -> None:
    c.put_batch([1, 5, 9], [10, 50, 90])
    c.put_batch([2, 5], [20, 55], tombs=[False, True])   # mixed putAll
    c.delete_batch([9])
    c.put_batch([], [])                                  # empty: one tick
    c.put_batch([3], [33])


def test_frozen_clock_bit_identity_with_unbatched():
    unbatched = DenseCrdt("n", N, wall_clock=frozen())
    staged = DenseCrdt("n", N, wall_clock=frozen())
    _write_script(unbatched)
    with staged.ingest():
        _write_script(staged)
    assert staged.canonical_time == unbatched.canonical_time
    assert staged.stats.puts == unbatched.stats.puts
    a, b = staged.record_map(), unbatched.record_map()
    assert a.keys() == b.keys()
    for slot in a:
        assert a[slot].hlc == b[slot].hlc, slot
        assert a[slot].value == b[slot].value, slot


def test_lww_outcome_matches_unbatched_under_ticking_clock():
    # A ticking clock makes the raw stamps differ (one wall read per
    # flush — the documented opt-in trade); the VALUES and tombstone
    # outcomes must still match the unbatched run exactly.
    unbatched = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    staged = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    _write_script(unbatched)
    with staged.ingest():
        _write_script(staged)
    a, b = staged.record_map(), unbatched.record_map()
    assert a.keys() == b.keys()
    assert {s: r.value for s, r in a.items()} == \
        {s: r.value for s, r in b.items()}


def test_hlc_monotonic_across_staged_groups():
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    with c.ingest():
        c.put_batch([0], [1])
        c.put_batch([1], [2])
        c.put_batch([2], [3])
        c.put_batch([3, 4], [9, 9])     # one group, one shared stamp
    rm = c.record_map()
    assert rm[0].hlc < rm[1].hlc < rm[2].hlc < rm[3].hlc
    assert rm[3].hlc == rm[4].hlc       # putAll batch-shares-one-stamp
    # all five stamps come from ONE wall read (consecutive counters)
    assert len({r.hlc.millis for r in rm.values()}) == 1
    assert c.canonical_time.logical_time == rm[4].hlc.logical_time


def test_duplicate_staged_slots_collapse_last_wins():
    c = DenseCrdt("n", N, wall_clock=frozen())
    with c.ingest() as wc:
        c.put_batch([7, 7, 7], [1, 2, 3])
        c.put_batch([7], [4])
        c.delete_batch([8])
        c.put_batch([8], [80])          # resurrects the tombstone
    assert wc.rows_committed == 2       # post-dedup: slots {7, 8}
    assert c.get(7) == 4
    assert c.get(8) == 80


# ------------------------------------------------ read-your-writes overlay


def test_overlay_answers_point_reads_without_flushing():
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    c.put_batch([0], [5])
    with c.ingest() as wc:
        c.put_batch([1], [11])
        c.delete_batch([0])
        assert c.get(1) == 11           # staged put visible
        assert c.get(0) is None         # staged delete shadows commit
        assert c.contains_slot(1)
        assert c.is_deleted(0) is True
        assert c.is_deleted(1) is False
        assert wc.flushes == 0          # none of the above flushed
        assert wc.pending_rows == 2
    assert c.get(1) == 11               # same answers after commit
    assert c.get(0) is None


def test_count_modified_since_includes_staged_rows():
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    c.put_batch([0], [1])
    watermark = c.canonical_time
    with c.ingest() as wc:
        # the bound is INCLUSIVE (at-or-after, map_crdt.dart:44-45):
        # slot 0 sits exactly at the watermark and counts
        assert c.count_modified_since(watermark) == 1
        c.put_batch([5, 6], [7, 8])
        assert c.count_modified_since(watermark) == 3
        assert c.count_modified_since(None) == 3
        assert wc.flushes == 0
    assert c.count_modified_since(watermark) == 3


# ----------------------------------------------------------- barriers


@pytest.mark.parametrize("surface", [
    lambda c: c.record_map(),
    lambda c: c.to_json(),
    lambda c: c.pack_since(None),
    lambda c: c.export_delta(),
    lambda c: c.get_slot_record(1),
    lambda c: c.store,
    lambda c: c.merge_records({}),
])
def test_bulk_surfaces_drain_first(surface):
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    with c.ingest() as wc:
        c.put_batch([1], [11])
        surface(c)
        assert wc.flushes == 1 and wc.pending_rows == 0
        c.put_batch([2], [22])          # window stays usable after
    assert c.get(1) == 11 and c.get(2) == 22


def test_merge_barrier_keeps_lww_order():
    # A remote record merged MID-WINDOW must lose to a staged write
    # that was issued later in wall order — the drain commits the
    # staged rows (with their pre-merge stamps) before the merge runs.
    clk = FakeClock(start=BASE)
    c = DenseCrdt("n", N, wall_clock=clk)
    remote = DenseCrdt("r", N, wall_clock=FakeClock(start=BASE))
    remote.put_batch([1], [999])
    with c.ingest():
        c.put_batch([1], [1])
        clk.advance(60_000)             # local write is much newer
        c.put_batch([1], [2])
        c.merge_records(remote.record_map())
    assert c.get(1) == 2


def test_checkpoint_save_includes_staged_rows(tmp_path):
    path = str(tmp_path / "snap.crdt")
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    with c.ingest():
        c.put_batch([4, 5], [44, 55])
        c.save(path)                    # barrier: snapshot is complete
    loaded = DenseCrdt.load("n", path)
    assert loaded.get(4) == 44 and loaded.get(5) == 55
    assert loaded.canonical_time == c.canonical_time


def test_gossip_round_drains_mid_flight_staging():
    # The round's watermark read sits AFTER the drain: staged rows get
    # stamps at-or-before the watermark, so the next delta round must
    # not re-send them — and the peer sees every staged write even
    # when the first transport attempt is dropped by the fault proxy.
    clk = FakeClock(start=BASE)
    a = GossipNode(DenseCrdt("a", N, wall_clock=clk),
                   rng=random.Random(7), sleep=lambda _s: None,
                   retry=RetryPolicy(max_attempts=3, base_delay=0.001))
    b = GossipNode(DenseCrdt("b", N, wall_clock=clk),
                   rng=random.Random(8), sleep=lambda _s: None)
    with a, b:
        sched = ScriptedSchedule([{"kind": "drop"}, None])
        with FaultProxy(b.host, b.port, sched) as proxy:
            a.add_peer("b", proxy.host, proxy.port)
            with a.crdt.ingest() as wc:
                a.crdt.put_batch([1, 2], [10, 20])
                assert a.sync_peer("b") == "ok"
                assert wc.pending_rows == 0      # round drained it
                a.crdt.put_batch([3], [30])      # staging still works
            assert a.sync_peer("b") == "ok"
            assert proxy.counters.get("drop") == 1
    assert a.peers["b"].stats.retries == 1
    for slot, val in ((1, 10), (2, 20), (3, 30)):
        assert b.crdt.get(slot) == val


# ----------------------------------------------------- window lifecycle


def test_auto_flush_threshold():
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    with c.ingest(auto_flush_rows=4) as wc:
        c.put_batch([0, 1, 2], [1, 1, 1])
        assert wc.flushes == 0
        c.put_batch([3, 4], [1, 1])     # backlog hits 5 >= 4
        assert wc.flushes == 1 and wc.pending_rows == 0
    assert wc.flushes == 1              # exit flush had nothing to do


def test_windows_do_not_nest_and_refuse_pipelined():
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    with c.ingest():
        with pytest.raises(RuntimeError, match="nest"):
            with c.ingest():
                pass
    with c.pipelined():
        with pytest.raises(RuntimeError, match="pipelined"):
            with c.ingest():
                pass


def test_pipelined_entry_drains_open_window():
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    with c.ingest() as wc:
        c.put_batch([1], [11])
        with c.pipelined():
            pass
        assert wc.flushes == 1 and wc.pending_rows == 0
    assert c.get(1) == 11


def test_body_exception_propagates_and_backlog_still_commits():
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    with pytest.raises(ValueError, match="boom"):
        with c.ingest():
            c.put_batch([1], [11])
            raise ValueError("boom")
    assert c.get(1) == 11               # exit flush ran regardless


def test_invalid_rows_fail_at_the_call_site():
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    with c.ingest() as wc:
        with pytest.raises(IndexError):
            c.put_batch([N + 1], [1])   # out of range: eager, like
        assert wc.pending_rows == 0     # unbatched — nothing staged


# ------------------------------------------------------- watch at commit


def test_watch_fires_at_commit_with_winning_values():
    c = DenseCrdt("n", N, wall_clock=FakeClock(start=BASE))
    rec = c.watch().record()
    keyed = c.watch(1).record()
    with c.ingest():
        c.put_batch([1, 2], [10, 20])
        c.put_batch([1], [30])          # same window: last wins
        c.delete_batch([2])
        assert rec.events == []         # nothing until commit
    assert sorted(rec.events) == [(1, 30), (2, None)]
    assert keyed.events == [(1, 30)]


# ------------------------------------------------------------ obs wiring


def test_flush_metrics_and_trigger_labels():
    from crdt_tpu.obs.registry import default_registry
    reg = default_registry()
    flushes = reg.counter("crdt_tpu_ingest_flush_total", "")
    rows = reg.counter("crdt_tpu_ingest_flush_rows_total", "")
    f0 = flushes.value(trigger="explicit", node="m")
    b0 = flushes.value(trigger="barrier", node="m")
    r0 = rows.value(node="m")
    c = DenseCrdt("m", N, wall_clock=FakeClock(start=BASE))
    with c.ingest() as wc:
        c.put_batch([1], [1])
        wc.flush()
        c.put_batch([2, 3], [2, 3])
        c.record_map()                  # barrier-trigger flush
    assert flushes.value(trigger="explicit", node="m") == f0 + 1
    assert flushes.value(trigger="barrier", node="m") == b0 + 1
    assert rows.value(node="m") == r0 + 3


# -------------------------------------------------------- keyed adapter


def test_keyed_adapter_stages_and_reads_through_overlay():
    kc = KeyedDenseCrdt(DenseCrdt("k", 8, wall_clock=FakeClock()))
    with kc.ingest() as wc:
        kc.put("x", 1)
        kc.put_all({"y": 2, "z": None})
        kc.delete("x")
        assert kc.get("x") is None and kc.is_deleted("x") is True
        assert kc.get("y") == 2 and kc.contains_key("y")
        assert wc.flushes == 0
    assert kc.map == {"y": 2}


# ------------------------------------------------------------- sharded


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 (virtual) devices")
def test_sharded_staged_matches_single_device_unbatched():
    mesh = make_fanin_mesh(2, 4)
    sharded = ShardedDenseCrdt("n", N, mesh, wall_clock=frozen())
    plain = DenseCrdt("n", N, wall_clock=frozen())
    _write_script(plain)
    with sharded.ingest():
        _write_script(sharded)
    assert sharded.canonical_time == plain.canonical_time
    a, b = sharded.record_map(), plain.record_map()
    assert a.keys() == b.keys()
    for slot in a:
        assert (a[slot].hlc, a[slot].value) == \
            (b[slot].hlc, b[slot].value), slot
    # the fused commit must land already laid out — one consistent
    # NamedSharding across every lane, same as before the window
    shardings = {str(getattr(sharded.store, f).sharding)
                 for f in sharded.store._fields}
    assert len(shardings) == 1
    assert "key" in shardings.pop()

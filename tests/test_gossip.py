"""Resilient gossip runtime (crdt_tpu.gossip): bounded retry with
jittered backoff, the per-peer circuit breaker, durable watermark
resume, and dense→JSON wire degradation — driven over real sockets,
with the fault proxy (crdt_tpu.testing_faults) injecting the failures
the runtime claims to survive."""

import random
import socket
import threading

import pytest

from crdt_tpu import (BreakerPolicy, CircuitBreaker, DenseCrdt,
                      GossipNode, MapCrdt, RetryPolicy, SqliteCrdt,
                      load_gossip_state)
from crdt_tpu.checkpoint import save_gossip_state
from crdt_tpu.testing import (FakeClock, FaultProxy, FaultSchedule,
                              ScriptedSchedule)

NO_SLEEP = lambda _s: None   # collapse backoff waits in tests


class MonotonicStub:
    """Injectable seconds clock for breaker cool-down tests."""

    def __init__(self):
        self.now = 100.0

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


def _dead_port() -> int:
    """A port nothing is listening on (bind, read, close)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# --- RetryPolicy ---

def test_retry_delay_full_jitter_bounds():
    policy = RetryPolicy(max_attempts=6, base_delay=0.1, max_delay=1.0)
    rng = random.Random(42)
    for attempt in range(1, 8):
        cap = min(policy.max_delay,
                  policy.base_delay * (2 ** attempt))
        draws = [policy.delay(attempt, rng) for _ in range(200)]
        assert all(0.0 <= d <= cap for d in draws)
        # FULL jitter, not equal jitter: the low half of the range
        # must actually be drawn (spreads retrying replicas apart)
        assert min(draws) < cap / 2


# --- CircuitBreaker state machine ---

def test_breaker_opens_after_threshold_and_probes():
    clk = MonotonicStub()
    br = CircuitBreaker(BreakerPolicy(failure_threshold=3,
                                      reset_timeout=30.0), clock=clk)
    for _ in range(2):
        br.record_failure()
        assert br.state == CircuitBreaker.CLOSED and br.allow()
    br.record_failure()                      # third consecutive: open
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()
    clk.advance(29.0)
    assert not br.allow()                    # cool-down not elapsed
    clk.advance(2.0)
    assert br.allow()                        # one probe allowed
    assert br.state == CircuitBreaker.HALF_OPEN
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED
    assert br.failures == 0


def test_breaker_failed_probe_reopens():
    clk = MonotonicStub()
    br = CircuitBreaker(BreakerPolicy(failure_threshold=2,
                                      reset_timeout=10.0), clock=clk)
    br.record_failure()
    br.record_failure()
    clk.advance(11.0)
    assert br.allow() and br.state == CircuitBreaker.HALF_OPEN
    br.record_failure()                      # probe failed
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()                    # a fresh cool-down starts
    clk.advance(11.0)
    assert br.allow()


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(BreakerPolicy(failure_threshold=3,
                                      reset_timeout=1.0),
                        clock=MonotonicStub())
    for _ in range(5):                       # fail, fail, success, ...
        br.record_failure()
        br.record_failure()
        br.record_success()
    assert br.state == CircuitBreaker.CLOSED  # never opened


# --- GossipNode rounds ---

def _node(crdt, **kw):
    kw.setdefault("rng", random.Random(7))
    kw.setdefault("sleep", NO_SLEEP)
    return GossipNode(crdt, **kw)


def test_two_nodes_converge_and_count_pull_kinds():
    clk = FakeClock()
    a = _node(MapCrdt("a", wall_clock=clk))
    b = _node(MapCrdt("b", wall_clock=clk))
    with a, b:
        a.add_peer("b", b.host, b.port)
        b.add_peer("a", a.host, a.port)
        a.crdt.put("ka", 1)
        b.crdt.put("kb", 2)
        assert a.run_round() == {"b": "ok"}
        assert b.run_round() == {"a": "ok"}
        a.crdt.put("ka2", 3)
        assert a.sync_peer("b") == "ok"
        assert b.sync_peer("a") == "ok"
    assert a.crdt.map == b.crdt.map == {"ka": 1, "kb": 2, "ka2": 3}
    sa = a.stats_snapshot()["b"]
    # first round is the cold-start full pull; every later one a delta
    assert sa["full_pulls"] == 1 and sa["delta_pulls"] == 1
    assert sa["rounds_ok"] == 2 and sa["rounds_failed"] == 0
    assert sa["bytes_sent"] > 0 and sa["bytes_received"] > 0
    assert sa["breaker"] == "closed"
    assert sa["watermark"] is not None


def test_transport_fault_is_retried_within_budget():
    clk = FakeClock()
    b = _node(MapCrdt("b", wall_clock=clk))
    b.crdt.put("kb", 2)
    with b:
        sched = ScriptedSchedule([{"kind": "drop"}, None])
        with FaultProxy(b.host, b.port, sched) as proxy:
            a = _node(MapCrdt("a", wall_clock=clk),
                      retry=RetryPolicy(max_attempts=3,
                                        base_delay=0.001))
            with a:
                a.add_peer("b", proxy.host, proxy.port)
                assert a.sync_peer("b") == "ok"
            stats = a.peers["b"].stats
            assert stats.retries == 1 and stats.rounds_ok == 1
            assert proxy.counters.get("drop") == 1
    assert a.crdt.get("kb") == 2


def test_retry_budget_exhaustion_fails_and_trips_breaker():
    clk = MonotonicStub()
    a = _node(MapCrdt("a", wall_clock=FakeClock()),
              retry=RetryPolicy(max_attempts=2, base_delay=0.001),
              breaker=BreakerPolicy(failure_threshold=2,
                                    reset_timeout=30.0),
              clock=clk)
    peer = a.add_peer("ghost", "127.0.0.1", _dead_port())
    assert a.sync_peer("ghost") == "failed"
    assert a.sync_peer("ghost") == "failed"      # second round: opens
    assert peer.breaker.state == CircuitBreaker.OPEN
    assert a.sync_peer("ghost") == "skipped"     # no network attempt
    assert peer.stats.skipped == 1
    assert peer.stats.retries == 2               # one retry per round
    assert peer.stats.rounds_failed == 2
    assert peer.stats.breaker_opened == 1
    assert isinstance(peer.last_error, ConnectionError)
    # cool-down elapses; the probe round finds a revived peer
    clk.advance(31.0)
    live = _node(MapCrdt("b", wall_clock=FakeClock()))
    with live:
        peer.host, peer.port = live.host, live.port
        assert a.sync_peer("ghost") == "ok"
    assert peer.breaker.state == CircuitBreaker.CLOSED
    assert peer.stats.breaker_half_open == 1
    assert peer.stats.breaker_closed == 1


def test_background_loop_converges(tmp_path):
    clk = FakeClock()
    a = GossipNode(MapCrdt("a", wall_clock=clk))
    b = GossipNode(MapCrdt("b", wall_clock=clk))
    try:
        a.start(gossip_interval=0.02)
        b.start(gossip_interval=0.02)
        a.add_peer("b", b.host, b.port)
        b.add_peer("a", a.host, a.port)
        with a.lock:
            a.crdt.put("ka", 1)
        with b.lock:
            b.crdt.put("kb", 2)
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with a.lock:
                done = a.crdt.map == {"ka": 1, "kb": 2}
            if done:
                break
            time.sleep(0.02)
    finally:
        a.stop()
        b.stop()
    assert a.crdt.map == b.crdt.map == {"ka": 1, "kb": 2}


# --- watermark persistence / crash resume ---

def test_restart_resumes_delta_from_persisted_watermark(tmp_path):
    state = str(tmp_path / "a.gossip.json")
    db = str(tmp_path / "a.db")
    clk = FakeClock()
    b = _node(MapCrdt("b", wall_clock=clk))
    b.crdt.put("early", 1)
    with b:
        # first incarnation: durable replica + durable watermarks
        a = _node(SqliteCrdt("a", db, wall_clock=clk,
                             check_same_thread=False),
                  state_path=state)
        with a:
            a.add_peer("b", b.host, b.port)
            assert a.sync_peer("b") == "ok"
            assert a.peers["b"].stats.full_pulls == 1
        marks = load_gossip_state(state, "a")
        assert str(marks["b"]) == \
            a.stats_snapshot()["b"]["watermark"]

        # node "a" dies; the world moves on
        b.crdt.put("while_down", 2)

        # second incarnation: same replica file, same state file
        a2 = _node(SqliteCrdt("a", db, wall_clock=clk,
                              check_same_thread=False),
                   state_path=state)
        with a2:
            a2.add_peer("b", b.host, b.port)
            assert a2.peers["b"].watermark is not None  # resumed
            assert a2.sync_peer("b") == "ok"
            stats = a2.peers["b"].stats
            # the resumed round is a DELTA pull, not a full re-pull
            assert stats.full_pulls == 0
            assert stats.delta_pulls == 1
    assert a2.crdt.map == {"early": 1, "while_down": 2}


def test_foreign_state_file_rejected(tmp_path):
    state = str(tmp_path / "gossip.json")
    from crdt_tpu import Hlc
    save_gossip_state(state, "somebody_else",
                      {"b": Hlc(1_700_000_000_000, 0, "b")})
    with pytest.raises(ValueError, match="somebody_else"):
        GossipNode(MapCrdt("a", wall_clock=FakeClock()),
                   state_path=state)


# --- dense→JSON wire degradation ---

def test_dense_peer_pair_stays_dense():
    clk = FakeClock()
    a = _node(DenseCrdt("a", 64, wall_clock=clk))
    b = _node(DenseCrdt("b", 64, wall_clock=clk))
    assert a.prefer_dense and b.prefer_dense
    with a, b:
        a.add_peer("b", b.host, b.port)
        a.crdt.put_batch([1, 2], [10, 20])
        assert a.sync_peer("b") == "ok"
        assert a.peers["b"].dense is True
        assert a.peers["b"].stats.fallbacks == 0
    assert b.crdt.get(1) == 10 and b.crdt.get(2) == 20


def test_dense_rejection_downgrades_sticky_to_json():
    clk = FakeClock()
    a = _node(DenseCrdt("a", 64, wall_clock=clk))
    # a JSON-only peer (MapCrdt cannot merge_split)
    b = _node(MapCrdt("b", wall_clock=clk), key_decoder=int)
    with a, b:
        a.add_peer("b", b.host, b.port)
        a.crdt.put_batch([3], [30])
        b.crdt.put(8, 80)
        assert a.sync_peer("b") == "ok"      # fell back within the round
        peer = a.peers["b"]
        assert peer.dense is False           # sticky downgrade
        assert peer.stats.fallbacks == 1
        assert peer.stats.rounds_ok == 1 and peer.stats.retries == 0
        # subsequent rounds go straight to JSON — no second fallback
        a.crdt.put_batch([4], [40])
        assert a.sync_peer("b") == "ok"
        assert peer.stats.fallbacks == 1
    assert b.crdt.get(3) == 30 and b.crdt.get(4) == 40
    assert a.crdt.get(8) == 80


# --- faulty-link convergence (the tier-1 slice of the soak) ---

def test_convergence_through_seeded_fault_proxy():
    clk = FakeClock()
    a = _node(MapCrdt("a", wall_clock=clk),
              retry=RetryPolicy(max_attempts=6, base_delay=0.001,
                                max_delay=0.01),
              breaker=BreakerPolicy(failure_threshold=50))
    b = _node(MapCrdt("b", wall_clock=clk))
    with a, b:
        sched = FaultSchedule(seed=11, rate=0.7, max_delay=0.01)
        with FaultProxy(b.host, b.port, sched) as proxy:
            a.add_peer("b", proxy.host, proxy.port)
            for i in range(6):
                with a.lock:
                    a.crdt.put(f"ka{i}", i)
                with b.lock:
                    b.crdt.put(f"kb{i}", i)
                a.sync_peer("b")
            proxy.passthrough = True         # settle: faults off
            assert a.sync_peer("b") == "ok"
            assert a.sync_peer("b") == "ok"
            fired = {k: v for k, v in proxy.counters.items()
                     if k != "connections"}
            assert sum(fired.values()) > 0, \
                f"no faults fired: {proxy.counters}"
    want = {f"ka{i}": i for i in range(6)}
    want.update({f"kb{i}": i for i in range(6)})
    assert a.crdt.map == b.crdt.map == want
    stats = a.peers["b"].stats
    assert stats.retries > 0                 # the runtime earned it

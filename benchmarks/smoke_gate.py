"""CI smoke-bench regression gate.

Runs the static lint leg first (``python -m crdt_tpu.analysis
--skip-laws --skip-jaxpr``: host linter + whole-tree lock-order
analyzer — the cheap passes; laws and jaxpr audit have their own CI
leg), then a sketch-accuracy leg (the quantile sketch every SLO gate
now trusts must recover the quantiles of a known synthetic
distribution within its configured relative error — if that contract
drifts, every latency verdict downstream is wrong, so it fails CI
before any bench runs), then one fast bench (default ``bench.py
--mode sync --smoke``) — which appends a normalized record to the
trajectory — then verdicts that record against the fastest-of-N
floors of its ``(mode, host_class, smoke)`` group via the same code
path as ``python -m crdt_tpu.obs bench --compare``.

Exit code is the verdict's, unchanged:

- ``0`` — every measured metric within its noise budget;
- ``1`` — regression (some metric outside budget);
- ``2`` — nothing comparable: first run on this host class, or the
  series is empty. Deliberately NOT success (unmeasured != passed);
  CI that wants to bootstrap a fresh host seeds the baseline with one
  accepted run and keeps 2 as failure thereafter.

Usage::

    python benchmarks/smoke_gate.py                 # sync smoke gate
    python benchmarks/smoke_gate.py --mode ingest
    python benchmarks/smoke_gate.py --trajectory /tmp/t.jsonl --budget 0.4
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from crdt_tpu.obs.trajectory import TRAJECTORY_PATH, bench_main


def sketch_accuracy_leg() -> int:
    """Recover known quantiles of a synthetic distribution through
    the quantile sketch within its configured relative error. Pure
    host-side, deterministic, <100 ms — the cheapest possible proof
    that the instrument every 14.6 ms SLO verdict rests on still
    honors its error bound."""
    import random

    from crdt_tpu.obs.sketch import QuantileSketch

    rng = random.Random(181)
    # Latency-shaped lognormal sample, ~0.4..80 ms, known exactly by
    # sorting — the sketch's answer must sit within alpha of the true
    # order statistic (DDSketch guarantee, plus one half-bucket of
    # discretization slack).
    sample = [0.002 * rng.lognormvariate(0.0, 0.75)
              for _ in range(20000)]
    alpha = 0.01
    sk = QuantileSketch(relative_accuracy=alpha)
    for v in sample:
        sk.record(v)
    ordered = sorted(sample)
    failures = []
    for q in (0.5, 0.9, 0.99):
        true = ordered[int(q * (len(ordered) - 1))]
        got = sk.quantile(q)
        rel = abs(got - true) / true
        if rel > alpha * 1.5:
            failures.append(f"q{q}: true={true:.6f} sketch={got:.6f} "
                            f"rel_err={rel:.4f} > {alpha * 1.5}")
    if failures:
        print("smoke_gate: sketch accuracy leg failed:\n  "
              + "\n  ".join(failures), file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run a smoke bench and gate it against the "
                    "trajectory floors")
    ap.add_argument("--mode", default="sync",
                    help="bench.py mode to run (default sync)")
    ap.add_argument("--trajectory", default=TRAJECTORY_PATH,
                    help="trajectory jsonl to append to and gate "
                         "against")
    ap.add_argument("--budget", type=float, default=None,
                    help="per-metric noise budget fraction override")
    ap.add_argument("--pool", type=int, default=None,
                    help="fastest-of-N baseline pool override")
    ap.add_argument("--full", action="store_true",
                    help="run the full-size bench instead of --smoke")
    args = ap.parse_args(argv)

    lint_rc = subprocess.run(
        [sys.executable, "-m", "crdt_tpu.analysis",
         "--skip-laws", "--skip-jaxpr"], cwd=_REPO).returncode
    if lint_rc != 0:
        print(f"smoke_gate: lint leg failed (rc={lint_rc})",
              file=sys.stderr)
        return lint_rc

    sketch_rc = sketch_accuracy_leg()
    if sketch_rc != 0:
        return sketch_rc

    cmd = [sys.executable, os.path.join(_REPO, "bench.py"),
           "--mode", args.mode, "--trajectory", args.trajectory]
    if not args.full:
        cmd.append("--smoke")
    env = dict(os.environ)
    if args.mode == "collective":
        # The collective join shards over a member mesh; on a plain
        # CPU CI host that mesh only exists as virtual devices. The
        # resulting record self-identifies via its "-virtualmesh"
        # host_class, so it never gates against real-hardware floors.
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    rc = subprocess.run(cmd, cwd=_REPO, env=env).returncode
    if rc != 0:
        print(f"smoke_gate: bench run failed (rc={rc})",
              file=sys.stderr)
        return rc

    gate_args = ["--compare", args.trajectory]
    if args.budget is not None:
        gate_args += ["--budget", str(args.budget)]
    if args.pool is not None:
        gate_args += ["--pool", str(args.pool)]
    return bench_main(gate_args)


if __name__ == "__main__":
    raise SystemExit(main())

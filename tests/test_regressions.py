"""Regression tests for review findings."""

import copy
import pickle

import pytest

from crdt_tpu import (DuplicateNodeException, Hlc, MapCrdt, Record,
                      TpuMapCrdt)

from conformance import FakeClock


def test_empty_merge_clock_parity():
    """Empty merges must consume the same number of wall-clock ticks on
    both backends (the reference bumps the canonical clock even for an
    empty changeset, crdt.dart:93)."""
    oracle = MapCrdt("abc", wall_clock=FakeClock())
    tpu = TpuMapCrdt("abc", wall_clock=FakeClock())
    for c in (oracle, tpu):
        c.put("x", 1)
        c.merge({})
        c.put("y", 2)
    assert oracle.to_json() == tpu.to_json()
    assert oracle.canonical_time == tpu.canonical_time


def test_failed_merge_rolls_back_host_state():
    """A merge raising from the recv guard must not leave phantom keys
    (the oracle's store is untouched when recv throws mid-loop)."""
    clock = FakeClock()
    tpu = TpuMapCrdt("abc", wall_clock=clock)
    oracle = MapCrdt("abc", wall_clock=FakeClock())
    bad = Hlc(clock.millis + 1000, 0, "abc")  # duplicate node, ahead
    for c in (tpu, oracle):
        with pytest.raises(DuplicateNodeException):
            c.merge({"phantom": Record(bad, 1, bad)})
    assert tpu.contains_key("phantom") == oracle.contains_key("phantom") \
        == False
    assert tpu.record_map() == oracle.record_map() == {}


def test_hlc_copy_and_pickle():
    h = Hlc(1000000000000, 0x42, "abc")
    assert copy.copy(h) is h
    assert copy.deepcopy(h) is h
    assert pickle.loads(pickle.dumps(h)) == h


def test_parse_with_utc_offset():
    # fractional seconds + explicit offset must not be silently mis-parsed
    h = Hlc.parse("2024-01-01T12:00:00.123+02:00-0001-n")
    assert h.millis == 1704103200123
    assert h.counter == 1


def test_unsubscribe_idempotent():
    crdt = MapCrdt("abc", wall_clock=FakeClock())
    stream = crdt.watch()
    seen = []
    unsub = stream.listen(seen.append)
    crdt.put("x", 1)
    unsub()
    unsub()  # second call must not raise
    crdt.put("y", 2)
    assert len(seen) == 1

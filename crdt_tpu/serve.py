"""Async serving tier: multiplex thousands of client sessions onto
the ingest combiner and the packed read path (docs/SERVING.md).

`SyncServer` (net.py) is a REPLICATION endpoint: a handful of pooled
gossip peers, one handler thread per connection, every request a full
replica-lock round trip. A serving workload is the opposite shape —
10k mostly-idle client sessions each issuing small point writes and
reads — and a thread per session or a scatter per write would sink
it. :class:`ServeTier` multiplexes every session onto ONE asyncio
event loop and funnels all writes through the replica's shared
`DenseCrdt.ingest()` combiner window:

- **Writes** (the serve-only ``put``/``delete`` ops) never touch the
  replica from the event loop. Each session appends to a loop-local
  queue; a flusher task ticks every ``flush_interval`` seconds and
  commits the whole backlog as ONE ``put_batch`` + combiner flush on
  the replica executor — one batched HLC stamp and one donated
  scatter per tick, however many clients wrote. Acks resolve when
  the tick's commit returns, so p99 write-ack latency is bounded by
  (tick interval + one flush), not by client count.
- **Reads** ride the existing fast paths: ``delta_packed`` answers
  from the replica's clock-keyed pack cache (a quiet store serves
  every session's pull from one pack) and the arena's memoryviews are
  handed to the transport as a vectored ``writelines`` — zero copies
  in this module. Point ``get`` reads answer from the combiner's
  read-your-writes overlay.
- **Cold joins** (the ``digest`` Merkle-walk op) are routed to a
  bounded single-worker "slow lane" executor: a digest-tree build is
  the most expensive lock hold in the tier, so at most
  ``cold_lane_depth`` walks may be queued — the rest are shed with
  the retryable ``busy`` code and counted, and warm sessions never
  wait behind a herd of cold peers.

The tier speaks the exact `SyncServer` frame protocol — hello
negotiation, `FrameCodec` tagged framing, packed/dense/merkle ops,
error codes — so existing `PeerConnection` clients (and pre-hello
legacy peers, who simply never send hello) interoperate unchanged;
the wire-compat tests in tests/test_serve.py prove both directions
bit-compatible.

Backpressure is explicit and measured (`MetricsRegistry`):
``crdt_tpu_serve_sessions`` / ``_queue_depth`` gauges,
``_flush_seconds`` / ``_ack_seconds`` histograms,
``_shed_total{lane=admission|cold}``, and an admission watermark —
sessions past ``max_sessions`` are refused at accept with the same
pre-hello ``busy`` frame `SyncServer` uses, so clients back off
instead of downgrading.

Blocking discipline: nothing in a coroutine may block the loop — no
sync frame helpers, no ``time.sleep``, no raw sockets. The crdtlint
``async-blocking-call`` rule enforces this for the whole module.
"""

from __future__ import annotations

import asyncio
import json
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, List, Optional, Tuple

import numpy as np

from .analysis.concurrency import make_lock
from .hlc import Hlc
from .net import (BINOP_DELETE, BINOP_GET, BINOP_PUT, BINOP_ST_BUSY,
                  BINOP_ST_MOVED, BINOP_ST_OK, BINOP_ST_OK_NULL,
                  BINOP_ST_REJECTED, MAX_FRAME_BYTES, FrameCodec,
                  WireTally, _flat_views, _pack_for_peer, _pack_split,
                  _recv_span, _unpack_split, decode_binop_request,
                  encode_binop_reply)

# First body byte of a binary op frame (docs/WIRE.md): a negotiated
# session dispatches on it — JSON ops start with '{' (0x7b), so the
# two dialects share one read loop with no ambiguity.
_BINOP_REQ_TAG = b"\xb1"
_BINOP_OP_NAMES = {BINOP_PUT: "put", BINOP_DELETE: "delete",
                   BINOP_GET: "get"}


# --- async framing (the length-prefixed wire of net.py, loop-side) ---

async def read_bytes_frame_async(reader: asyncio.StreamReader,
                                 codec: Optional[FrameCodec] = None,
                                 tally: Optional[WireTally] = None
                                 ) -> Optional[bytes]:
    """One RAW frame from a stream reader; None on EOF/hangup —
    exactly `recv_bytes_frame`'s contract, minus the blocking."""
    try:
        head = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        return None
    (n,) = struct.unpack(">I", head)
    if n > MAX_FRAME_BYTES:
        raise ValueError(f"peer announced a {n}-byte frame (cap "
                         f"{MAX_FRAME_BYTES}); corrupt stream?")
    try:
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        return None
    if tally is not None:
        tally.received += 4 + n
    if codec is not None:
        body = codec.decode(body)
    return body


async def read_frame_async(reader: asyncio.StreamReader,
                           codec: Optional[FrameCodec] = None,
                           tally: Optional[WireTally] = None
                           ) -> Optional[Any]:
    body = await read_bytes_frame_async(reader, codec, tally)
    return None if body is None else json.loads(body)


def frame_pieces(bufs, codec: Optional[FrameCodec] = None,
                 tally: Optional[WireTally] = None) -> list:
    """Header + body pieces for one frame, ready for a vectored
    ``writer.writelines`` — the async twin of `send_bytes_frame`,
    sharing its codec/tally/size-cap semantics. Pieces pass through
    as memoryviews (a packed delta's arena views reach the transport
    with zero copies in our code)."""
    if codec is not None:
        bufs = codec.encode(bufs, tally)
    views = _flat_views(bufs)
    total = sum(v.nbytes for v in views)
    if total > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {total} bytes exceeds "
                         f"MAX_FRAME_BYTES ({MAX_FRAME_BYTES})")
    if tally is not None:
        tally.sent += 4 + total
    return [struct.pack(">I", total)] + views


async def write_frame_async(writer: asyncio.StreamWriter, bufs,
                            codec: Optional[FrameCodec] = None,
                            tally: Optional[WireTally] = None) -> None:
    writer.writelines(frame_pieces(bufs, codec, tally))
    await writer.drain()


async def write_json_async(writer: asyncio.StreamWriter, obj: Any,
                           codec: Optional[FrameCodec] = None,
                           tally: Optional[WireTally] = None) -> None:
    await write_frame_async(writer, [json.dumps(obj).encode()],
                            codec, tally)


# A watcher whose transport buffer exceeds this is shed at fan-out
# time: watch delivery is at-least-once over idempotent lattice rows,
# so a shed subscriber resubscribes and catches up via the watermark —
# unbounded buffering for a stalled reader is the one outcome the tier
# must never choose.
_WATCH_BUFFER_CAP = 1 << 22


class _OwnerProxy:
    """One pooled upstream connection to an owning tier, forwarding
    keyspace ops on behalf of pre-federation sessions (the `moved`
    fallback negotiated away by the missing hello cap). Speaks the
    pre-hello untagged framing — the upstream tier treats it as one
    more legacy session — and serializes in-flight requests under an
    asyncio lock, so one connection serves every proxied op this tier
    sends that owner. Loop-confined, like the sessions it serves."""

    def __init__(self, addr: str, timeout: float):
        host, _, port = addr.rpartition(":")
        self.host, self.port = host, int(port)
        self._timeout = timeout
        self._lock = asyncio.Lock()
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None

    async def request(self, msg: dict) -> Any:
        async with self._lock:
            last: Optional[BaseException] = None
            for attempt in range(2):
                try:
                    if self._writer is None:
                        self._reader, self._writer = \
                            await asyncio.wait_for(
                                asyncio.open_connection(
                                    self.host, self.port),
                                timeout=self._timeout)
                    await write_json_async(self._writer, msg)
                    reply = await asyncio.wait_for(
                        read_frame_async(self._reader),
                        timeout=self._timeout)
                    if reply is None:
                        raise ConnectionError(
                            "upstream closed mid-request")
                    return reply
                except (ConnectionError, OSError, ValueError,
                        asyncio.TimeoutError) as e:
                    # A dead pooled connection retries ONCE on a fresh
                    # one; forwarded writes are idempotent lattice
                    # writes, so the replay is safe.
                    last = e
                    await self.close()
            raise last if last is not None else ConnectionError(
                "proxy request failed")

    async def close(self) -> None:
        w, self._writer = self._writer, None
        self._reader = None
        if w is not None:
            try:
                w.close()
                await w.wait_closed()
            except (ConnectionError, OSError):
                pass


def _resolve_ack(fut: "asyncio.Future", outcome: Any) -> None:
    """Resolve one write-ack future ON ITS OWNING LOOP — the callback
    the committer hands to `call_soon_threadsafe` for writes enqueued
    by another accept loop. Resolved via set_result, never
    set_exception, so a session torn down mid-ack leaves no
    unretrieved exception behind."""
    if not fut.done():
        fut.set_result(outcome)


class _MpscStripe:
    """One mutex lane of the MPSC write queue. The stripe lock is a
    LEAF by construction: it wraps exactly a list append or a list
    swap, never a replica touch, a frame write, or another lock — so
    an accept loop's enqueue can never wait behind device work."""

    # Checked by analysis/concurrency.py: rank 46 sits above every
    # control-plane lock and is never held while acquiring anything.
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    __slots__ = ("_lock", "items")

    def __init__(self) -> None:
        self._lock = make_lock("MpscStripe._lock", 46)
        self.items: list = []

    def push(self, entry) -> None:
        with self._lock:
            self.items.append(entry)

    def swap(self) -> list:
        with self._lock:
            out, self.items = self.items, []
        return out


class MpscQueue:
    """Multi-producer single-consumer staging queue for the write
    path: every accept loop enqueues through `push` (the MPSC gate the
    crdtlint ``combiner-enqueue-unsafe`` rule holds combiner-owning
    classes to) and ONLY the committer loop drains. Enqueues stripe by
    producer thread id, so loops contend on disjoint mutexes; `drain`
    swaps each stripe's list under its own lock, one at a time — two
    stripe locks are never held together. Per-producer FIFO order is
    preserved (one thread always lands on one stripe); cross-producer
    order is whatever the tick observes, exactly as with concurrent
    appends to a single list."""

    __slots__ = ("_stripes", "_mask")

    def __init__(self, stripes: int = 8) -> None:
        n = 1
        while n < stripes:
            n *= 2
        self._mask = n - 1
        self._stripes = tuple(_MpscStripe() for _ in range(n))

    def push(self, entry) -> None:
        self._stripes[threading.get_ident() & self._mask].push(entry)

    def drain(self) -> list:
        out: list = []
        for stripe in self._stripes:
            if stripe.items:
                out.extend(stripe.swap())
        return out

    def __len__(self) -> int:
        # Torn-free under the GIL: a load signal (queue-depth gauge,
        # autoscaler pressure), not an invariant.
        return sum(len(s.items) for s in self._stripes)


class _LoopCtx:
    """Per-accept-loop state for one `ServeTier`. Everything here is
    confined to its OWN event loop thread — sessions, writers, watch
    index, proxy pool — so N loops share nothing hot; cross-loop
    traffic is exactly two seams: the MPSC write queue in, and
    `call_soon_threadsafe` ack/fan-out hops out. ``index`` 0 is the
    committer: it owns the ingest window, the flusher task and the
    tier's public port."""

    __slots__ = ("index", "loop", "stop_event", "started", "error",
                 "thread", "sessions", "writers", "watch",
                 "watch_codec", "watch_mark", "proxies", "fanout_busy")

    def __init__(self, index: int) -> None:
        from .watch import WatchIndex
        self.index = index
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.stop_event: Optional[asyncio.Event] = None
        self.started = threading.Event()
        self.error: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None
        self.sessions = 0
        self.writers: set = set()
        self.watch = WatchIndex()
        self.watch_codec: dict = {}
        self.watch_mark: Optional[Hlc] = None
        self.proxies: dict = {}
        self.fanout_busy = False


class ServeTier:
    """Serve one replica to thousands of concurrent client sessions.

    Runs its own asyncio event loop on a dedicated daemon thread
    (``start``/``stop``, or use as a context manager), so synchronous
    callers — tests, `PeerConnection` clients, the embedding app —
    need no loop of their own. The replica's `ingest()` window is
    held open for the tier's whole lifetime; ALL replica access from
    the tier goes through :attr:`lock` on executor threads, never on
    the event loop. An application that also touches the replica from
    other threads must share this lock (pass its own via ``lock=``).

    Serve-only ops, in the same framed JSON protocol::

        {"op": "put",    "slot": s, "value": v} -> {"ok": true}
        {"op": "delete", "slot": s}             -> {"ok": true}
        {"op": "get",    "slot": s}             -> {"ok": true, "value": v|null}

    Write acks resolve after the batch containing the write has
    committed — read-your-writes for the writer, one flush per tick
    for the tier. A malformed write is answered with code
    ``write_rejected`` and the session STAYS OPEN (long-lived client
    sessions should not die for one bad request; protocol-level
    violations still hang up, like `SyncServer`).
    """

    # crdtlint lock-discipline contract, same as SyncServer: every
    # replica access holds the replica lock.
    _CRDTLINT_GUARDED = {"lock": ("crdt",)}
    # Checked by analysis/concurrency.py: the store lock is a LEAF —
    # it guards device dispatches by design and no other lock is ever
    # taken inside it. Control-plane classes that take it while
    # holding their own lock declare that order on their side
    # (FederatedTier, Replicator, ReplicaGroup, GossipNode).
    _CRDTLINT_LOCK_ORDER = ("lock",)

    def __init__(self, crdt, host: str = "127.0.0.1", port: int = 0,
                 max_sessions: int = 12000,
                 flush_interval: float = 0.002,
                 auto_flush_rows: int = 1 << 15,
                 cold_lane_depth: int = 8,
                 idle_timeout: Optional[float] = 300.0,
                 io_timeout: float = 30.0,
                 key_encoder=None, value_encoder=None,
                 key_decoder=None, value_decoder=None,
                 lock: Optional[threading.RLock] = None,
                 router=None, loops: int = 1):
        self.crdt = crdt
        # Multi-loop serving (docs/SERVING.md): `loops` accept loops
        # share ONE listening port via SO_REUSEPORT, each with its own
        # event loop thread and loop-confined session state; writes
        # from every loop funnel through the MPSC queue into loop 0's
        # flusher, so the one-stamp/one-scatter-per-tick invariant is
        # unchanged however many loops accept. Platforms without
        # SO_REUSEPORT fall back to one loop, COUNTED on the
        # crdt_tpu_serve_loops gauge — never a silent downscale.
        self.loops = max(1, int(loops))
        self.loops_effective: Optional[int] = None
        self.lock = lock if lock is not None \
            else make_lock("ServeTier.lock", 40, rlock=True)
        # Federation: an attached `PartitionRouter` (routing.py) makes
        # this tier one partition of a federated keyspace — keyspace
        # ops are admitted through router.check() before they may
        # enqueue (the crdtlint router-epoch-bypass contract), foreign
        # slots answer `moved` (or proxy for pre-federation sessions),
        # and the `federation` hello cap is advertised.
        self.router = router
        # Elastic repartitioning breadcrumb: the last split/merge this
        # partition took part in, stamped by the federation's scale
        # engine and surfaced on the metrics wire for the fleet table
        # (obs/fleet.py `format_partitions`).
        self.last_scale: Optional[dict] = None
        # Replication (docs/REPLICATION.md): a primary carries a
        # `Replicator` (replication.py) — the flush tick's write-concern
        # barrier — while followers carry None and learn their role
        # from the group driver. The crdtlint `ack-before-replicate`
        # rule holds the flush tick to "barrier before any ack".
        self.replicator = None
        self.role: Optional[str] = None
        self.group_name: Optional[str] = None
        self._lease: Optional[Tuple[str, float, int]] = None
        self.killed = False
        self.host = host
        self.port: Optional[int] = None
        self._want_port = port
        self.max_sessions = max_sessions
        self.flush_interval = flush_interval
        self._auto_flush_rows = auto_flush_rows
        self.cold_lane_depth = cold_lane_depth
        self.idle_timeout = idle_timeout
        self._io_timeout = io_timeout
        self._kenc, self._venc = key_encoder, value_encoder
        self._kdec, self._vdec = key_decoder, value_decoder
        self._node = str(crdt.node_id)
        self._n_slots = int(getattr(crdt, "n_slots", 0) or 0)

        from .obs.registry import default_registry
        reg = default_registry()
        self.tally = WireTally()
        reg.attach("wire", self.tally, replace=True, role="serve",
                   node=self._node)
        self._m_sessions = reg.gauge(
            "crdt_tpu_serve_sessions",
            "live multiplexed client sessions")
        self._m_depth = reg.gauge(
            "crdt_tpu_serve_queue_depth",
            "writes queued for the next combiner tick")
        self._m_shed = reg.counter(
            "crdt_tpu_serve_shed_total",
            "requests shed for backpressure (admission watermark or "
            "cold-join lane bound)")
        self._m_ops = reg.counter(
            "crdt_tpu_serve_ops_total",
            "serve-tier ops by kind (client ops carry lane=json|bin)")
        self._m_loops = reg.gauge(
            "crdt_tpu_serve_loops",
            "accept loops sharing this tier's port (SO_REUSEPORT "
            "multi-loop serving; 1 = single loop, incl. the "
            "no-SO_REUSEPORT fallback)")
        self._m_flush = reg.histogram(
            "crdt_tpu_serve_flush_seconds",
            "combiner flush wall time under the serving tier, by "
            "trigger")
        self._m_ack = reg.histogram(
            "crdt_tpu_serve_ack_seconds",
            "write enqueue-to-ack latency (queue wait + tick commit)")
        # Sketch twins of the ack/phase histograms: same observations,
        # γ-indexed buckets, ~1% relative-error quantiles instead of
        # bucket ceilings. The fleet poller merges these into the
        # fleet-true p99 evaluate_slo and the autoscaler gate on —
        # the 14.6 ms envelope is not a power of two
        # (docs/OBSERVABILITY.md).
        self._m_ack_sketch = reg.sketch(
            "crdt_tpu_serve_ack_seconds_sketch",
            "write enqueue-to-ack latency, relative-error quantile "
            "sketch")
        # Per-lane twin of the ack sketch: the json|bin split lives on
        # its own instrument so the unlabeled series above keeps its
        # exact label key — evaluate_slo and the bench quantile reads
        # match label sets exactly, and a new label would orphan them.
        self._m_ack_lane_sketch = reg.sketch(
            "crdt_tpu_serve_ack_lane_seconds_sketch",
            "write ack latency by client lane (json per-op vs bin "
            "batched frame), relative-error quantile sketch")
        self._m_ack_phase = reg.histogram(
            "crdt_tpu_serve_ack_phase_seconds",
            "write-ack latency decomposed by phase: queue_wait (enqueue "
            "to tick pickup), stamp (HLC send_batch), scatter (device "
            "commit dispatch), ack_write (residual tick work + ack "
            "fan-out)")
        self._m_ack_phase_sketch = reg.sketch(
            "crdt_tpu_serve_ack_phase_seconds_sketch",
            "write-ack phase latency, relative-error quantile sketch")
        self._m_moved = reg.counter(
            "crdt_tpu_serve_moved_total",
            "keyspace ops redirected with the moved reply (federated "
            "routing)")
        self._m_proxied = reg.counter(
            "crdt_tpu_serve_proxied_total",
            "keyspace ops forwarded to the owning tier for "
            "pre-federation sessions")
        self._m_watchers = reg.gauge(
            "crdt_tpu_serve_watchers",
            "live watch subscriptions on the serve loop")
        self._m_fanout = reg.counter(
            "crdt_tpu_serve_watch_fanout_total",
            "watch event frames fanned out at flush ticks")

        # The pending write queue: multi-producer (every accept loop
        # pushes), single-consumer (loop 0's flusher drains). Session
        # state, watch indexes and proxy pools live per-loop in
        # `_LoopCtx`; the counters below are plain ints bumped from
        # any loop — load signals with GIL-granular (not transactional)
        # accuracy, exact whenever one loop serves (every test).
        self._q = MpscQueue()
        self.shed_count = 0
        self.dropped_sessions = 0
        self.idle_closed_sessions = 0
        self.watch_shed_sessions = 0
        self._cold_inflight = 0

        # One replica executor serializes every warm-path replica
        # touch; the cold lane gets its own single worker so a digest
        # walk never queues AHEAD of the flusher tick.
        self._replica_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serve-replica")
        self._cold_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serve-cold")

        self._ctxs: List[_LoopCtx] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop_event: Optional[asyncio.Event] = None
        self._ingest_cm = None
        self._wc = None

    # --- lifecycle ---

    @property
    def _thread(self) -> Optional[threading.Thread]:
        # Liveness probe kept under the pre-multi-loop name (the
        # rehome/stop guards read it): the committer loop's thread.
        return self._ctxs[0].thread if self._ctxs else None

    def _effective_loops(self) -> int:
        """Feature-detect SO_REUSEPORT at bind time: the constant must
        exist AND the kernel must accept it (WSL/macOS quirks), else
        the tier serves on one loop — counted on the loop gauge, never
        a silent downscale."""
        want = self.loops
        if want <= 1:
            return 1
        if not hasattr(socket, "SO_REUSEPORT"):
            return 1
        probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        probe.settimeout(1.0)   # never does I/O; bound for hygiene
        try:
            probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        except OSError:
            return 1
        finally:
            probe.close()
        return want

    def start(self) -> "ServeTier":
        eff = self._effective_loops()
        self._ctxs = [_LoopCtx(i) for i in range(eff)]
        self.loops_effective = eff
        self._m_loops.set(eff, node=self._node)
        ctx0 = self._ctxs[0]
        ctx0.thread = threading.Thread(
            target=self._run, args=(ctx0,), daemon=True,
            name="serve-tier-loop")
        ctx0.thread.start()
        ctx0.started.wait(timeout=60)
        if ctx0.error is not None:
            err, ctx0.error = ctx0.error, None
            ctx0.thread.join(timeout=5)
            self._ctxs = []
            raise err
        if self.port is None:
            self._ctxs = []
            raise RuntimeError("serving tier failed to start in time")
        # Secondary accept loops bind the CONCRETE port loop 0 got
        # (which may have been ephemeral), so they start second.
        for ctx in self._ctxs[1:]:
            ctx.thread = threading.Thread(
                target=self._run, args=(ctx,), daemon=True,
                name=f"serve-tier-loop-{ctx.index}")
            ctx.thread.start()
        failed: Optional[BaseException] = None
        for ctx in self._ctxs[1:]:
            ctx.started.wait(timeout=60)
            if ctx.error is not None and failed is None:
                failed = ctx.error
        if failed is not None:
            self.stop()
            raise failed
        return self

    def _signal_stop(self) -> None:
        for ctx in self._ctxs:
            loop, ev = ctx.loop, ctx.stop_event
            if loop is not None and ev is not None:
                try:
                    loop.call_soon_threadsafe(ev.set)
                except RuntimeError:
                    pass

    def stop(self) -> None:
        if not self._ctxs or self._ctxs[0].thread is None:
            return
        # Every loop tears down concurrently: the committer's final
        # flush tick resolves cross-loop acks while the other loops
        # are still draining their sessions, so no ack is stranded.
        self._signal_stop()
        for ctx in reversed(self._ctxs):
            thread, ctx.thread = ctx.thread, None
            if thread is None:
                continue
            thread.join(timeout=60)
            if thread.is_alive():
                raise RuntimeError(
                    "serving tier loop failed to stop; the replica "
                    "may still be accessed — do not reuse it")
        self._replica_pool.shutdown(wait=True)
        self._cold_pool.shutdown(wait=True)

    def kill(self) -> None:
        """SIGKILL-equivalent teardown for fault injection: no final
        flush tick, no ack resolution, transports aborted (RST, close
        without FIN) — clients observe exactly what a crashed process
        shows them. Queued-but-unacked writes die with the tier; acked
        writes survive only to the extent the write-concern barrier
        already replicated them, which is the property the failover
        tests measure. The replica object is left as the crash image —
        a restart must build a FRESH store and catch up via the merkle
        walk, never reuse this one."""
        if not self._ctxs or self._ctxs[0].thread is None:
            return
        self.killed = True
        self._signal_stop()
        for ctx in self._ctxs:
            thread, ctx.thread = ctx.thread, None
            if thread is not None:
                thread.join(timeout=60)
        self._replica_pool.shutdown(wait=True)
        self._cold_pool.shutdown(wait=True)

    def __enter__(self) -> "ServeTier":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self, ctx: _LoopCtx) -> None:
        try:
            asyncio.run(self._main(ctx))
        except BaseException as e:   # pragma: no cover - belt+braces
            if not ctx.started.is_set():
                ctx.error = e
                ctx.started.set()

    def _reuseport_socket(self, port: int) -> socket.socket:
        # Sync helper on purpose: bind/setsockopt never block, and
        # keeping them out of the coroutine keeps the async-blocking
        # lint focused on calls that actually can.
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            # Non-blocking from birth (settimeout(0) IS non-blocking
            # mode): asyncio owns this socket the moment start_server
            # adopts it.
            sock.settimeout(0.0)
            sock.bind((self.host, port))
        except BaseException:
            sock.close()
            raise
        return sock

    async def _listen(self, ctx: _LoopCtx) -> asyncio.AbstractServer:
        def handler(r, w):
            return self._session(ctx, r, w)
        if self.loops_effective == 1:
            return await asyncio.start_server(
                handler, self.host, self._want_port, backlog=2048)
        # Multi-loop: every loop binds its OWN SO_REUSEPORT socket so
        # the kernel load-balances accepts across them. Loop 0 binds
        # the requested port; the others bind whatever concrete port
        # loop 0 was actually given.
        port = self._want_port if ctx.index == 0 else self.port
        sock = self._reuseport_socket(port)
        try:
            return await asyncio.start_server(
                handler, sock=sock, backlog=2048)
        except BaseException:
            sock.close()
            raise

    async def _main(self, ctx: _LoopCtx) -> None:
        ctx.loop = asyncio.get_running_loop()
        ctx.stop_event = asyncio.Event()
        committer = ctx.index == 0
        if committer:
            # Committer aliases: the flusher, rehome_watchers and the
            # stop path address loop 0 through the pre-multi-loop
            # names.
            self._loop = ctx.loop
            self._stop_event = ctx.stop_event
            try:
                self._open_ingest()
            except BaseException as e:
                ctx.error = e
                ctx.started.set()
                return
        try:
            server = await self._listen(ctx)
        except BaseException as e:
            ctx.error = e
            if committer:
                self._close_ingest()
            ctx.started.set()
            return
        flusher = None
        if committer:
            self.port = server.sockets[0].getsockname()[1]
            flusher = asyncio.ensure_future(self._flusher())
        ctx.started.set()
        try:
            await ctx.stop_event.wait()
        finally:
            server.close()
            await server.wait_closed()
            if flusher is not None:
                flusher.cancel()
                try:
                    await flusher
                except asyncio.CancelledError:
                    pass
            if self.killed:
                # Crash fidelity (`kill()`): drop the queue unacked,
                # RST every transport, leave the ingest window where
                # the crash left it. Pending sessions are cancelled
                # when asyncio.run tears the loop down.
                for w in list(ctx.writers):
                    transport = w.transport
                    if transport is not None:
                        try:
                            transport.abort()
                        except Exception:
                            pass
            else:
                # Resolve every queued ack (the committer's final tick
                # also resolves writes the OTHER loops enqueued — they
                # are still draining their sessions because stop()
                # joins the committer last), give the sessions one loop
                # breath to write their replies, then cut the
                # transports.
                if committer:
                    await self._flush_tick()
                await asyncio.sleep(0)
                for proxy in ctx.proxies.values():
                    await proxy.close()
                ctx.proxies.clear()
                for w in list(ctx.writers):
                    try:
                        w.close()
                    except Exception:
                        pass
                deadline = ctx.loop.time() + 5.0
                while ctx.sessions and ctx.loop.time() < deadline:
                    await asyncio.sleep(0.01)
                if committer:
                    self._close_ingest()

    def _open_ingest(self) -> None:
        with self.lock:
            self._ingest_cm = self.crdt.ingest(
                auto_flush_rows=self._auto_flush_rows)
            self._wc = self._ingest_cm.__enter__()
            self._wc.on_flush = self._note_flush

    def _close_ingest(self) -> None:
        with self.lock:
            wc, self._wc = self._wc, None
            if wc is not None:
                wc.on_flush = None
            cm, self._ingest_cm = self._ingest_cm, None
            if cm is not None:
                cm.__exit__(None, None, None)

    def _note_flush(self, trigger: str, rows: int,
                    seconds: float) -> None:
        # WriteCombiner flush listener — fires on EVERY trigger (tick,
        # auto, any barrier a pack/merge drains through), so the
        # histogram sees the tier's true flush distribution.
        self._m_flush.observe(seconds, node=self._node,
                              trigger=trigger)

    # --- write path: queue -> one combiner tick ---

    async def _flusher(self) -> None:
        while not self._stop_event.is_set():
            await asyncio.sleep(self.flush_interval)
            try:
                await self._flush_tick()
            except Exception:
                # The flusher is the tier's heartbeat: if it ever died,
                # every queued ack would hang forever. _flush_tick
                # already converts batch failures into per-write
                # rejections, so anything reaching here is unexpected —
                # drop the tick and keep ticking.
                continue

    async def _flush_tick(self) -> None:
        entries = self._q.drain()
        self._m_depth.set(0, node=self._node)
        if not entries:
            # Quiet ticks still fan out: merges (push_packed from a
            # migration, gossip) advance the store without touching
            # this tier's write queue, and watchers must see them.
            await self._fanout_all()
            return
        # Two entry shapes share the queue — JSON per-op writes
        # ("j", slot, value, tomb, fut, t0, loop) and binop batch
        # frames ("b", slots, vals, tombs, fut, t0, decode_s, loop).
        # Both carry fut at [4], t0 at [5] and the OWNING loop last.
        jq = [e for e in entries if e[0] == "j"]
        bq = [e for e in entries if e[0] == "b"]
        nj = len(jq)
        writes = nj + sum(len(e[1]) for e in bq)
        tick_t = time.perf_counter()
        phases: dict = {}
        # Write concern (docs/REPLICATION.md): a primary may resolve
        # this tick's acks only after its `Replicator` confirms the
        # delta on `ack_replicas` followers, and only while it still
        # holds a fresh lease — an expired lease means the group
        # monitor may already have promoted someone else, so acking
        # here could lose the write to the client's view. Both
        # failures map to the retryable `busy` code (the local commit
        # stands — it is an idempotent lattice join that will
        # converge via gossip — but the CLIENT is told to retry, so
        # its ack, when it finally lands, is backed by the group).
        rep = self.replicator
        try:
            slots = np.fromiter((e[1] for e in jq), np.int64, count=nj)
            vals = np.fromiter((e[2] for e in jq), np.int64, count=nj)
            tombs = np.fromiter((e[3] for e in jq), bool, count=nj)
            batches = [(e[1], e[2], e[3]) for e in bq]
            phases = await self._loop.run_in_executor(
                self._replica_pool, self._commit, slots, vals, tombs,
                batches)
            if self._lease_expired():
                outcome: Any = ("busy", "primary lease expired "
                                        "(fenced; retry)")
                # Flight recorder (obs/recorder.py): a tripped lease
                # fence is an incident edge — a write arrived after
                # this primary's authority lapsed. Capture while the
                # trace ring still holds the window; the recorder
                # throttles the tight-retry storm itself.
                try:
                    from .obs.recorder import default_recorder
                    default_recorder().trigger(
                        "lease_fence",
                        {"node": self._node, "writes_fenced": writes})
                except Exception:
                    pass
            elif rep is not None:
                replicated, detail = await self._loop.run_in_executor(
                    self._replica_pool, rep.barrier)
                outcome = True if replicated else ("busy", detail)
            else:
                outcome = True
        except Exception as e:
            # The whole tick failed (e.g. a value-width guard): every
            # writer in it gets the rejection. Resolved via
            # set_result, not set_exception, so a session torn down
            # mid-ack never leaves an unretrieved exception behind.
            outcome = f"{type(e).__name__}: {e}"
        now = time.perf_counter()
        # Ack attribution (SERVE_r01 follow-up): every write in the
        # tick shares the combiner's stamp/scatter legs; queue_wait is
        # per write; ack_write is the residual tick time the phase
        # timers don't cover (queue drain, executor hop, ack fan-out).
        # Per-write observation keeps sum(phase sums) comparable to
        # the crdt_tpu_serve_ack_seconds sum. Failed ticks committed
        # nothing, so nothing is attributed. A binop batch is ONE
        # client-visible ack (one reply frame), so it is one
        # observation — with its decode+admission cost attributed to
        # the binary-lane-only `decode` phase.
        stamp = float(phases.get("stamp", 0.0)) if phases else 0.0
        scatter = float(phases.get("scatter", 0.0)) if phases else 0.0
        ack_write = max(0.0, (now - tick_t) - stamp - scatter)
        this_loop = self._loop
        for e in entries:
            fut, t0, floop = e[4], e[5], e[-1]
            lane = "json" if e[0] == "j" else "bin"
            dec = e[6] if e[0] == "b" else 0.0
            if floop is this_loop:
                if not fut.done():
                    fut.set_result(outcome)
            else:
                # The write was enqueued by another accept loop: its
                # future must resolve THERE (futures are not
                # thread-safe). A loop mid-teardown just drops the ack
                # — its sessions are gone anyway.
                try:
                    floop.call_soon_threadsafe(_resolve_ack, fut,
                                               outcome)
                except RuntimeError:
                    pass
            self._m_ack.observe(now - t0, node=self._node)
            self._m_ack_sketch.observe(now - t0, node=self._node)
            self._m_ack_lane_sketch.observe(now - t0, lane=lane,
                                            node=self._node)
            if outcome is True:
                if dec > 0.0:
                    self._m_ack_phase.observe(
                        dec, phase="decode", node=self._node)
                    self._m_ack_phase_sketch.observe(
                        dec, phase="decode", node=self._node)
                queue_wait = max(0.0, tick_t - t0 - dec)
                self._m_ack_phase.observe(
                    queue_wait, phase="queue_wait", node=self._node)
                self._m_ack_phase.observe(stamp, phase="stamp",
                                          node=self._node)
                self._m_ack_phase.observe(scatter, phase="scatter",
                                          node=self._node)
                self._m_ack_phase.observe(ack_write, phase="ack_write",
                                          node=self._node)
                self._m_ack_phase_sketch.observe(
                    queue_wait, phase="queue_wait", node=self._node)
                self._m_ack_phase_sketch.observe(
                    stamp, phase="stamp", node=self._node)
                self._m_ack_phase_sketch.observe(
                    scatter, phase="scatter", node=self._node)
                self._m_ack_phase_sketch.observe(
                    ack_write, phase="ack_write", node=self._node)
        await self._fanout_all()

    def _commit(self, slots: np.ndarray, vals: np.ndarray,
                tombs: np.ndarray, batches: list) -> dict:
        with self.lock:
            wc = self._wc
            if len(slots):
                self.crdt.put_batch(slots, vals, tombs)
            # Each binop frame stages as its own stamp group — its
            # wire views land straight in the combiner's columnar
            # staging — but the tick still ends in ONE send_batch and
            # ONE ingest_scatter (the dispatch-ledger invariant).
            for bs, bv, bt in batches:
                self.crdt.put_batch(bs, bv, bt)
            if wc is not None:
                wc.flush("tick")
                return dict(wc.last_phase_seconds)
        return {}

    # --- watch fan-out: one pack per flush tick, pushed to every
    # watcher of a touched slot (docs/FEDERATION.md) ---

    async def _fanout_all(self) -> None:
        """Fan out the tick to every loop's watchers. The committer's
        own watchers are pushed inline (awaited — the single-loop path
        keeps its exact pre-multi-loop ordering); other loops get a
        `call_soon_threadsafe` nudge that packs-and-pushes on THEIR
        thread, because watch writers are loop-confined. A loop whose
        previous fan-out is still in flight is skipped this tick —
        watch delivery is at-least-once off a watermark, so the next
        tick covers the gap."""
        await self._fanout_tick(self._ctxs[0])
        for ctx in self._ctxs[1:]:
            if ctx.watch.empty or ctx.fanout_busy:
                continue
            if ctx.loop is None or ctx.stop_event is None \
                    or ctx.stop_event.is_set():
                continue
            try:
                ctx.loop.call_soon_threadsafe(self._spawn_fanout, ctx)
            except RuntimeError:
                pass

    def _spawn_fanout(self, ctx: _LoopCtx) -> None:
        # Runs ON ctx's loop. The busy flag is flipped here (not at
        # the committer) so it is only ever touched from ctx's thread
        # once set, and from the committer only as a skip hint.
        if ctx.fanout_busy:
            return
        ctx.fanout_busy = True
        task = asyncio.ensure_future(self._fanout_tick(ctx))

        def _done(t: "asyncio.Future") -> None:
            ctx.fanout_busy = False
            t.exception()   # a pack failure must never go unretrieved

        task.add_done_callback(_done)

    async def _fanout_tick(self, ctx: _LoopCtx) -> None:
        if ctx.watch.empty:
            return
        try:
            out = await ctx.loop.run_in_executor(
                self._replica_pool, self._watch_pack, ctx)
        except Exception:
            return   # a pack failure must never kill the flusher
        if out is None:
            return
        meta_msg, bufs, touched = out
        targets = ctx.watch.touched(touched)
        if not targets:
            return
        # Frame pieces are built ONCE per codec flavor (raw vs zlib)
        # and the SAME memoryviews are vectored to every watcher —
        # the zero-copy fan-out: 10k watchers cost 10k writelines,
        # not 10k serializations.
        flavors: dict = {}
        meta_raw = [json.dumps(meta_msg).encode()]
        for w in list(targets):
            codec = ctx.watch_codec.get(w)
            key = codec is not None and codec.compress
            cached = flavors.get(key)
            if cached is None:
                head = frame_pieces(meta_raw, codec)
                body = frame_pieces(bufs, codec)
                nbytes = sum(getattr(p, "nbytes", len(p))
                             for p in head + body)
                cached = flavors[key] = (head, body, nbytes)
            head, body, nbytes = cached
            transport = w.transport
            if (transport is None or transport.is_closing()
                    or transport.get_write_buffer_size()
                    > _WATCH_BUFFER_CAP):
                # Backpressure: a watcher that cannot keep up is shed
                # (its session close deregisters it) rather than
                # letting its transport buffer grow without bound.
                self.watch_shed_sessions += 1
                self._m_shed.inc(lane="watch", node=self._node)
                self._drop_watcher(ctx, w)
                try:
                    w.close()
                except Exception:
                    pass
                continue
            try:
                w.writelines(head)
                w.writelines(body)
            except (ConnectionError, OSError):
                self._drop_watcher(ctx, w)
                continue
            self.tally.sent += nbytes
            self._m_fanout.inc(node=self._node)

    def _drop_watcher(self, ctx: _LoopCtx, writer) -> None:
        ctx.watch.remove(writer)
        ctx.watch_codec.pop(writer, None)
        self._m_watchers.set(sum(len(c.watch) for c in self._ctxs),
                             node=self._node)

    def rearm_watch(self, mark) -> None:
        """Rewind the watch pack watermark to ``mark`` (keeping the
        MORE inclusive of the two — None means "from store birth" and
        is never overwritten). The merge engine calls this on the
        RECIPIENT at the routing flip: rows streamed over from the
        donor keep their ORIGIN HLC stamps, which an already-advanced
        recipient watermark would silently skip, so the first fan-out
        tick after re-homed watchers subscribe must pack from the flip
        watermark. Rewinding re-delivers rows between the two marks to
        existing watchers — watch delivery is at-least-once and the
        rows are idempotent lattice states, so re-applying is safe."""
        with self.lock:
            for ctx in self._ctxs:
                cur = ctx.watch_mark
                if cur is not None and (mark is None or mark < cur):
                    ctx.watch_mark = mark

    def rehome_watchers(self, owner: str, epoch: int,
                        since: Optional[str] = None,
                        timeout: float = 5.0) -> int:
        """Push a typed ``moved`` frame to every live watch session
        and deregister it — the watch half of a partition retire. Runs
        on the serve loop (watch state is loop-confined) and blocks
        the calling control thread until the frames are flushed, so
        the tier stop that follows cannot RST them off the wire.
        Returns the number of sessions re-homed."""
        if self._loop is None or self._thread is None or self.killed:
            return 0
        msg = {"op": "moved", "ok": False, "code": "moved",
               "owner": owner, "epoch": int(epoch),
               "error": (f"partition merged into {owner} at "
                         f"routing epoch {epoch}")}
        if since is not None:
            # Resume mark: the merge's flip watermark. The client
            # resubscribes with it so the recipient re-packs from
            # there regardless of interleaved fan-out ticks.
            msg["since"] = str(since)
        raw = [json.dumps(msg).encode()]

        async def _push(ctx: _LoopCtx) -> int:
            moved = 0
            for w in list(ctx.watch.watchers()):
                codec = ctx.watch_codec.get(w)
                try:
                    w.writelines(frame_pieces(raw, codec))
                    await w.drain()
                except (ConnectionError, OSError):
                    pass
                self._drop_watcher(ctx, w)
                moved += 1
            return moved

        total = 0
        for ctx in self._ctxs:
            if ctx.loop is None or ctx.thread is None:
                continue
            fut = asyncio.run_coroutine_threadsafe(_push(ctx),
                                                   ctx.loop)
            try:
                total += fut.result(timeout)
            except (TimeoutError, RuntimeError, OSError):
                fut.cancel()
        return total

    def partition_info(self) -> Optional[dict]:
        """Per-partition load/ownership roll-up for the fleet poller
        (obs/fleet.py `format_partitions`): address, routing epoch,
        owned-slot count, cumulative committed rows, instantaneous
        queue depth, shed count, and the last scale action this
        partition took part in. None when the tier is not a federated
        partition (no bound router)."""
        router = self.router
        if router is None or router.addr is None:
            return None
        table = router.table
        wc = self._wc
        info = {
            "addr": router.addr,
            "epoch": None if table is None else table.epoch,
            "slots": (None if table is None
                      else table.slots_of(router.addr)),
            "rows_committed": (0 if wc is None
                               else int(wc.rows_committed)),
            # len() on the loop-confined queue is a torn-free read
            # under the GIL — a load signal, not an invariant.
            "queue_depth": len(self._q),
            "shed": int(self.shed_count),
        }
        if self.last_scale is not None:
            info["last_scale"] = dict(self.last_scale)
        return info

    def _watch_arm(self, ctx: _LoopCtx,
                   since: Optional[str] = None) -> str:
        """Register-time replica touch: the head stamp the reply
        reports, also seeding the pack watermark so event streams
        start at subscription time, not store birth. A ``since``
        stamp (the resume mark a merge's ``moved`` frame hands a
        re-homed subscription) rewinds the watermark at REGISTRATION
        time, so rows committed between the routing flip and this
        resubscribe are re-packed at the next tick no matter how many
        fan-out ticks other watchers drove in between."""
        from .hlc import Hlc
        mark = None
        if since is not None:
            try:
                mark = Hlc.parse(str(since))
            except (ValueError, TypeError, IndexError):
                mark = None   # malformed resume mark: plain subscribe
        with self.lock:
            head = self.crdt.canonical_time
            # A None watermark on a store with no watcher ever armed
            # carries no from-birth promise to anyone, so a resume
            # mark may seed it directly — a re-homed subscription
            # must start at the flip watermark, not at head, or the
            # commits it is resuming across are silently skipped.
            if mark is not None and (ctx.watch_mark is None
                                     or mark < ctx.watch_mark):
                ctx.watch_mark = mark
            if ctx.watch_mark is None:
                ctx.watch_mark = head
        return str(head)

    def _watch_pack(self, ctx: _LoopCtx):
        """One tick's event pack (executor thread, lock held): every
        row modified at-or-after the watermark, tags included. The
        inclusive bound means a row exactly AT the watermark can ship
        twice across ticks — watch delivery is at-least-once, and the
        rows are idempotent lattice states, so re-applying is safe."""
        from .ops.packing import pack_rows
        with self.lock:
            head = self.crdt.canonical_time
            if ctx.watch_mark is not None \
                    and head == ctx.watch_mark:
                return None
            packed, ids = _pack_for_peer(self.crdt, ctx.watch_mark,
                                         True)
            ctx.watch_mark = head
        if not packed.k:
            return None
        meta, bufs = pack_rows(packed)
        touched = [int(s) for s in packed.slots]
        return ({"op": "event", "meta": meta,
                 "node_ids": list(ids), "k": packed.k},
                bufs, touched)

    # --- replica helpers (executor threads, lock held) ---

    def _caps(self) -> set:
        caps = {"zlib"}
        with self.lock:
            packed = (hasattr(self.crdt, "pack_since")
                      and hasattr(self.crdt, "merge_packed"))
            semantics = packed and hasattr(self.crdt, "set_semantics")
            merkle = packed and callable(
                getattr(self.crdt, "digest_tree", None))
        if packed:
            caps.add("packed")
        if semantics:
            caps.add("semantics")
        if merkle:
            caps.add("merkle")
        # Trace-context piggybacking is pure frame metadata — no
        # replica surface needed, so it is advertised unconditionally
        # (same as SyncServer).
        caps.add("trace")
        # Quantile-sketch metrics payloads: sessions that agree get a
        # "sketches" section on the metrics op; everyone else gets
        # the pre-sketch reply byte-identically (same as SyncServer).
        caps.add("sketch")
        # Binary client op lane (docs/WIRE.md): serve-tier-only — the
        # peer wire (`SyncServer`) keeps its packed-lane dialect and
        # its hello bytes unchanged. Advertised unconditionally: the
        # lane rides the write combiner the tier always owns. A client
        # that never offers it gets today's JSON dialect byte-for-byte.
        caps.add("binop")
        if self.router is not None:
            # Advertised only by routed tiers: a client that agrees
            # gets `moved` redirects; one that never asks is a
            # pre-federation session and gets the proxy fallback.
            caps.add("federation")
        if packed:
            # heartbeat/lease/replicate — the group-membership wire
            # surface (docs/REPLICATION.md); replicate needs the
            # packed merge path.
            caps.add("replication")
        return caps

    def _read_slot(self, slot: int):
        with self.lock:
            return self.crdt.get(slot)

    def _merge_json(self, payload: str, tctx=None) -> None:
        with _recv_span("push", tctx):
            with self.lock:
                self.crdt.merge_json(payload, key_decoder=self._kdec,
                                     value_decoder=self._vdec)

    def _export_json(self, since: Optional[str]) -> str:
        with self.lock:
            return self.crdt.to_json(
                modified_since=None if since is None
                else Hlc.parse(since),
                key_encoder=self._kenc, value_encoder=self._venc)

    def _merge_dense(self, meta, blob: bytes, ids, tctx=None) -> None:
        scs = _unpack_split(meta, blob)
        if not isinstance(ids, list) or not ids:
            raise ValueError("push_dense without node_ids")
        with _recv_span("push_dense", tctx):
            with self.lock:
                self.crdt.merge_split(scs, ids)

    def _export_dense(self, since: Optional[str]):
        with self.lock:
            scs, ids = self.crdt.export_split_delta(
                None if since is None else Hlc.parse(since))
        meta, bufs = _pack_split(scs)
        return {"meta": meta, "node_ids": list(ids)}, bufs

    def _merge_packed(self, meta, blob: bytes, ids, tctx=None) -> None:
        from .ops.packing import unpack_rows
        packed = unpack_rows(meta, blob)
        if not isinstance(ids, list):
            raise ValueError("push_packed without node_ids")
        if packed.k:
            with _recv_span("push_packed", tctx):
                with self.lock:
                    self.crdt.merge_packed(packed, ids)

    def _export_packed(self, since: Optional[str], ranges,
                       sem_ok: bool):
        from .ops.packing import pack_rows
        if ranges is not None:
            ranges = tuple((int(lo), int(hi)) for lo, hi in ranges)
        with self.lock:
            packed, ids = _pack_for_peer(
                self.crdt, None if since is None else Hlc.parse(since),
                sem_ok, ranges=ranges)
        meta, bufs = pack_rows(packed)
        return ({"meta": meta, "node_ids": list(ids),
                 "k": packed.k}, bufs)

    def _digest_values(self, groups):
        with self.lock:
            tree = self.crdt.digest_tree()
            per_group = [tree.values(lvl, ix) for lvl, ix in groups]
        flat = [v for vals in per_group for v in vals]
        buf = np.asarray(flat, np.uint64).astype(">u8").tobytes()
        return ({"op": "digest_resp", "ok": True, "k": len(flat),
                 "ks": [len(v) for v in per_group],
                 "n_slots": tree.n_slots,
                 "leaf_width": tree.leaf_width,
                 "depth": tree.depth}, buf)

    def _metrics_snapshot(self) -> dict:
        from .obs import metrics_snapshot
        snap = metrics_snapshot()
        if "node" not in snap:
            with self.lock:
                snap["node"] = {
                    "node_id": str(self.crdt.node_id),
                    "hlc_head": str(self.crdt.canonical_time)}
        if self.role is not None:
            # Group membership state for the fleet poller: role +
            # lease freshness is what `evaluate_slo`'s primary-
            # liveness check reads (obs/fleet.py).
            with self.lock:
                head = str(self.crdt.canonical_time)
            rep = self.replicator
            info = {"group": self.group_name, "role": self.role,
                    "hlc_head": head, "lease_ms": self._lease_ms()}
            if rep is not None:
                info["followers"] = rep.status()
            snap["replication"] = info
        part = self.partition_info()
        if part is not None:
            snap["partition"] = part
        return snap

    # --- tombstone GC (docs/STORAGE.md) ---

    def stability_hlc(self) -> "Optional[Hlc]":
        """Partition stability watermark: the min over every
        follower's durable HLC (the heartbeat/replicate-ack field) and
        this tier's own head — what this partition has PROVEN
        replicated. An unreplicated tier is its partition's sole
        owner, so its own head is the watermark. Any follower without
        a measured durable head pins the watermark to ``None``
        (unmeasured ≠ safe-to-purge — the autoscaler's degraded-freeze
        discipline), as does follower role: a follower cannot prove
        group-wide delivery, its primary drives GC. Raw watermark —
        `DenseCrdt.gc_purge` applies the drift slack."""
        if self.role == "follower":
            return None
        rep = self.replicator
        with self.lock:
            head = self.crdt.canonical_time
        if rep is None:
            return head
        marks = [head]
        for st in rep.status().values():
            d = st.get("durable")
            if d is None:
                return None
            try:
                marks.append(Hlc.parse(str(d)))
            except (ValueError, TypeError):
                return None
        return min(marks)

    def gc_pass(self, drift_slack_ms: Optional[int] = None) -> int:
        """One epoch-GC pass under the tier lock: purge tombstones the
        partition stability watermark has passed. Returns slots purged
        (0 when the watermark is pinned, the replica has no `gc_purge`
        surface, or the watermark hasn't advanced — the latter without
        a dispatch)."""
        from .obs.registry import default_registry
        stability = self.stability_hlc()
        if stability is None:
            default_registry().counter(
                "crdt_tpu_gc_pinned_total",
                "GC passes skipped on a pinned stability watermark"
            ).inc(surface="serve")
            return 0
        with self.lock:
            if not hasattr(self.crdt, "gc_purge"):
                return 0
            return self.crdt.gc_purge(stability,
                                      drift_slack_ms=drift_slack_ms)

    # --- replication surface (docs/REPLICATION.md) ---

    def _lease_ms(self) -> Optional[float]:
        lease = self._lease
        if lease is None:
            return None
        return max(0.0, (lease[1] - time.monotonic()) * 1000.0)

    def _lease_expired(self) -> bool:
        lease = self._lease
        return lease is not None and time.monotonic() > lease[1]

    def _grant_lease(self, lease: dict) -> Optional[str]:
        """Adopt a lease grant iff it is not older than the one held —
        a stale monitor (e.g. orphaned by a group restart) must not
        extend a fence a newer monitor already owns. Returns the
        refusal reason, or None when adopted."""
        try:
            holder = str(lease["holder"])
            ttl_ms = float(lease["ttl_ms"])
            epoch = int(lease.get("epoch", 0))
        except (KeyError, TypeError, ValueError):
            return "malformed lease grant"
        cur = self._lease
        if cur is not None and epoch < cur[2]:
            return f"stale lease epoch {epoch} < {cur[2]}"
        self._lease = (holder, time.monotonic() + ttl_ms / 1000.0,
                       epoch)
        return None

    def _hb_state(self, want_root: bool) -> dict:
        """Heartbeat replica touch (executor thread, lock held): the
        durable HLC head every beat, plus the digest root only when
        asked — elections need the tie-break, per-beat probes must not
        pay a tree build."""
        with self.lock:
            out = {"hlc": str(self.crdt.canonical_time)}
            if want_root and callable(
                    getattr(self.crdt, "digest_tree", None)):
                out["root"] = int(self.crdt.digest_tree().root)
        return out

    def _replicate_in(self, meta, blob: bytes, ids,
                      tctx=None) -> str:
        """Follower half of the write-concern barrier: merge the
        primary's tick pack, then report the durable head from the
        SAME lock hold — the watermark the primary's ack rests on."""
        with self.lock:
            self._merge_packed(meta, blob, ids, tctx)
            return str(self.crdt.canonical_time)

    # --- the session coroutine ---

    async def _session(self, ctx: _LoopCtx,
                       reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        seated = sum(c.sessions for c in self._ctxs)
        if seated >= self.max_sessions or ctx.stop_event.is_set():
            # Admission watermark: refuse with the same pre-hello
            # untagged busy frame SyncServer's accept path uses, so
            # every client generation reads it and backs off
            # (retryable, never the legacy-downgrade signal). The
            # seated count sums per-loop tallies — GIL-granular, so a
            # racing burst across loops can overshoot by at most one
            # accept per loop, which the watermark tolerates.
            self.shed_count += 1
            self._m_shed.inc(lane="admission", node=self._node)
            try:
                await write_json_async(
                    writer,
                    {"ok": False, "code": "busy",
                     "error": "serving tier at capacity "
                              f"(max_sessions={self.max_sessions})"},
                    None, self.tally)
            except (ConnectionError, OSError):
                pass
            await self._hangup(writer)
            return
        ctx.sessions += 1
        self._m_sessions.set(seated + 1, node=self._node)
        ctx.writers.add(writer)
        try:
            await self._session_loop(ctx, reader, writer)
        except (ConnectionError, OSError, ValueError,
                json.JSONDecodeError, asyncio.TimeoutError,
                asyncio.IncompleteReadError):
            # An ADMITTED session torn down by error (vs a clean
            # bye/EOF) counts as dropped — the bench's "zero dropped
            # below the watermark" criterion reads this. Idle expiry
            # is absorbed as a clean close in _read_op_raw, so the
            # only TimeoutError reaching here is a mid-op io_timeout
            # (a genuinely stalled client).
            self.dropped_sessions += 1
        finally:
            self._drop_watcher(ctx, writer)
            ctx.writers.discard(writer)
            ctx.sessions -= 1
            self._m_sessions.set(sum(c.sessions for c in self._ctxs),
                                 node=self._node)
            await self._hangup(writer)

    @staticmethod
    async def _hangup(writer: asyncio.StreamWriter) -> None:
        try:
            writer.close()
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass

    async def _read_op_raw(self, reader: asyncio.StreamReader,
                           codec: Optional[FrameCodec],
                           idle_exempt: bool = False):
        # RAW body bytes, not parsed JSON: the session loop dispatches
        # on the first byte (0xB1 binop vs '{' JSON) before paying for
        # a parse.
        if self.idle_timeout is None or idle_exempt:
            return await read_bytes_frame_async(reader, codec,
                                                self.tally)
        try:
            return await asyncio.wait_for(
                read_bytes_frame_async(reader, codec, self.tally),
                timeout=self.idle_timeout)
        except asyncio.TimeoutError:
            # Idle expiry is ROUTINE housekeeping, not a failure: close
            # like an EOF so the session never lands in
            # dropped_sessions (the bench's zero-dropped criterion).
            self.idle_closed_sessions += 1
            return None

    async def _read_blob(self, reader: asyncio.StreamReader,
                         codec: Optional[FrameCodec]):
        # Binary continuation frames are bounded by io_timeout, like
        # SyncServer: an announced-but-never-sent frame must not hold
        # the session forever.
        return await asyncio.wait_for(
            read_bytes_frame_async(reader, codec, self.tally),
            timeout=self._io_timeout)

    async def _route_verdict(self, ctx: _LoopCtx, msg: dict,
                             slot: int, fed_ok: bool):
        """Admission through the router for one keyspace op: None to
        enqueue locally, else the reply dict to send instead. The
        `moved`/proxy taxonomy lives in routing.PartitionRouter.check;
        this adds the forwarded-op guard (a proxied op landing on a
        non-owner means the table flipped mid-flight — shed retryably
        rather than bounce between tiers) and the proxy hop itself."""
        router = self.router
        if router is None:
            return None
        from .routing import PROXY
        verdict = router.check(slot, msg.get("epoch"), fed_ok)
        if verdict is None:
            return None
        if msg.get("fwd"):
            return {"ok": False, "code": "busy",
                    "error": "routing flux: forwarded op landed on a "
                             "non-owner (retry after table refresh)"}
        if verdict is PROXY:
            owner = router.table.owner_of(slot)
            proxy = ctx.proxies.get(owner)
            if proxy is None:
                proxy = ctx.proxies[owner] = _OwnerProxy(
                    owner, self._io_timeout)
            fwd = dict(msg)
            fwd["fwd"] = int(fwd.get("fwd", 0) or 0) + 1
            fwd.pop("trace", None)
            try:
                reply = await proxy.request(fwd)
            except (ConnectionError, OSError, ValueError,
                    asyncio.TimeoutError):
                return {"ok": False, "code": "busy",
                        "error": f"owner {owner} unreachable (proxy)"}
            self._m_proxied.inc(op=str(msg.get("op")),
                                node=self._node)
            return reply if isinstance(reply, dict) else {
                "ok": False, "code": "busy",
                "error": "owner returned garbage (proxy)"}
        self._m_moved.inc(op=str(msg.get("op")), node=self._node)
        return verdict

    def _read_slots(self, slots: np.ndarray) -> list:
        # Batched point reads for a binop frame: one lock hold, one
        # executor hop for every `get` in the frame.
        with self.lock:
            return [self.crdt.get(int(s)) for s in slots]

    async def _binop_frame(self, ctx: _LoopCtx, body: bytes,
                           writer: asyncio.StreamWriter,
                           codec: Optional[FrameCodec],
                           fed_ok: bool) -> bool:
        """One binary op batch -> one status reply frame. Per-op error
        isolation: a rejected slot, a refused route or a proxied miss
        fails ITS status byte; its batchmates commit normally. Writes
        stage before gets execute, so read-your-writes extends into
        the frame — a get observes every write earlier in (or
        anywhere in) its own batch. Returns False when the reply could
        not be written (transport gone) so the session ends."""
        t0 = time.perf_counter()
        opcodes, slots, values, epoch = decode_binop_request(body)
        n = len(opcodes)
        status = np.zeros(n, np.uint8)
        details: list = []
        values_out: Optional[np.ndarray] = None
        for code, name in _BINOP_OP_NAMES.items():
            count = int((opcodes == code).sum())
            if count:
                self._m_ops.inc(count, op=name, lane="bin",
                                node=self._node)
        ok = slots < self._n_slots
        if not ok.all():
            for i in np.nonzero(~ok)[0]:
                status[i] = BINOP_ST_REJECTED
                details.append({"i": int(i), "code": "write_rejected",
                                "error": "bad slot"})
        router = self.router
        if router is not None:
            admit = router.check_batch(slots, epoch, fed_ok)
            if admit is not None:
                # Refused ops re-enter the JSON verdict path one by
                # one: `moved` redirects, proxy hops and the fwd-flux
                # guard keep ONE taxonomy (and one set of counters)
                # across both dialects. Refusals are the cold path —
                # a current-epoch client on the owner never lands
                # here.
                for i in np.nonzero(~admit & ok)[0]:
                    i = int(i)
                    code = int(opcodes[i])
                    msg = {"op": _BINOP_OP_NAMES[code],
                           "slot": int(slots[i])}
                    if code != BINOP_GET:
                        msg["value"] = int(values[i])
                    if epoch is not None:
                        msg["epoch"] = epoch
                    reply = await self._route_verdict(
                        ctx, msg, int(slots[i]), fed_ok)
                    st, detail = _binop_status_of(reply)
                    status[i] = st
                    if detail is not None:
                        detail["i"] = i
                        details.append(detail)
                    if st == BINOP_ST_OK and code == BINOP_GET:
                        if values_out is None:
                            values_out = np.zeros(n, np.int64)
                        value = reply.get("value")
                        if value is None:
                            status[i] = BINOP_ST_OK_NULL
                        else:
                            values_out[i] = int(value)
                ok &= admit
        wmask = ok & (opcodes != BINOP_GET)
        nw = int(wmask.sum())
        if nw:
            # decode+admission cost rides the entry so the flush tick
            # can attribute it as the binary lane's `decode` phase.
            decode_s = time.perf_counter() - t0
            if nw == n:
                # The hot shape (a pure write batch): the wire views
                # go STRAIGHT into combiner staging — zero copies in
                # this module, proven by the pack-copy counters.
                wslots, wvals = slots, values
                wtombs = opcodes == BINOP_DELETE
            else:
                wslots = slots[wmask]
                wvals = values[wmask]
                wtombs = opcodes[wmask] == BINOP_DELETE
            fut = ctx.loop.create_future()
            self._q.push(("b", wslots, wvals, wtombs, fut, t0,
                          decode_s, ctx.loop))
            self._m_depth.set(len(self._q), node=self._node)
            outcome = await fut
            if outcome is not True:
                widx = np.nonzero(wmask)[0]
                if isinstance(outcome, tuple):
                    status[widx] = BINOP_ST_BUSY
                    details.append({"code": outcome[0],
                                    "error": outcome[1]})
                else:
                    status[widx] = BINOP_ST_REJECTED
                    details.append({"code": "write_rejected",
                                    "error": str(outcome)})
        gmask = ok & (opcodes == BINOP_GET)
        if gmask.any():
            gidx = np.nonzero(gmask)[0]
            read = await ctx.loop.run_in_executor(
                self._replica_pool, self._read_slots, slots[gidx])
            if values_out is None:
                values_out = np.zeros(n, np.int64)
            for i, value in zip(gidx, read):
                if value is None:
                    status[i] = BINOP_ST_OK_NULL
                else:
                    values_out[i] = int(value)
        try:
            writer.writelines(frame_pieces(
                encode_binop_reply(status, values_out, details),
                codec, self.tally))
            await writer.drain()
        except (ConnectionError, OSError):
            return False
        return True

    async def _session_loop(self, ctx: _LoopCtx,
                            reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        loop = ctx.loop
        codec: Optional[FrameCodec] = None
        binop_ok = False
        sem_ok = False
        trace_ok = False
        fed_ok = False
        sketch_ok = False
        watching = False
        while not ctx.stop_event.is_set():
            raw = await self._read_op_raw(reader, codec,
                                          idle_exempt=watching)
            if raw is None:
                return
            if binop_ok and raw[:1] == _BINOP_REQ_TAG:
                # Binary op lane: a whole batch of keyspace ops in one
                # frame, one reply frame back. A malformed binop frame
                # raises ValueError out of this loop — protocol
                # violation, hang up — while a bad op INSIDE a
                # well-formed frame only fails that op's status slot.
                if not await self._binop_frame(ctx, raw, writer,
                                               codec, fed_ok):
                    return
                continue
            msg = json.loads(raw)
            if not isinstance(msg, dict) or msg.get("op") == "bye":
                return
            op = msg.get("op")
            if op in ("put", "delete", "get"):
                # Client keyspace ops carry the lane label (the binop
                # path counts the same ops with lane="bin"); control
                # ops stay label-compatible with every dashboard that
                # predates the lane split.
                self._m_ops.inc(op=str(op), lane="json",
                                node=self._node)
            else:
                self._m_ops.inc(op=str(op), node=self._node)
            tctx = msg.get("trace") if trace_ok else None
            if not isinstance(tctx, dict):
                tctx = None

            if op in ("put", "delete"):
                slot = msg.get("slot")
                value = msg.get("value", 0)
                # bools are JSON true/false, not slot/value ints; the
                # int64 bound keeps an oversized Python int from ever
                # reaching the flush tick's np.int64 conversion (which
                # would reject the WHOLE batch, not just this write).
                if not _slot_ok(slot, self._n_slots) \
                        or not _value_ok(value):
                    await write_json_async(
                        writer, {"ok": False, "code": "write_rejected",
                                 "error": "bad slot/value"},
                        codec, self.tally)
                    continue
                routed = await self._route_verdict(ctx, msg, slot,
                                                   fed_ok)
                if routed is not None:
                    await write_json_async(writer, routed, codec,
                                           self.tally)
                    continue
                fut = loop.create_future()
                self._q.push(("j", slot, value, op == "delete", fut,
                              time.perf_counter(), loop))
                self._m_depth.set(len(self._q), node=self._node)
                outcome = await fut
                if outcome is True:
                    await write_json_async(writer, {"ok": True},
                                           codec, self.tally)
                elif isinstance(outcome, tuple):
                    # Retryable tick outcome (write-concern barrier
                    # miss, lease fence): the client backs off and
                    # retries, same contract as admission `busy`.
                    await write_json_async(
                        writer, {"ok": False, "code": outcome[0],
                                 "error": outcome[1]},
                        codec, self.tally)
                else:
                    await write_json_async(
                        writer, {"ok": False, "code": "write_rejected",
                                 "error": str(outcome)},
                        codec, self.tally)

            elif op == "get":
                slot = msg.get("slot")
                if not _slot_ok(slot, self._n_slots):
                    await write_json_async(
                        writer, {"ok": False, "code": "write_rejected",
                                 "error": "bad slot"},
                        codec, self.tally)
                    continue
                routed = await self._route_verdict(ctx, msg, slot,
                                                   fed_ok)
                if routed is not None:
                    await write_json_async(writer, routed, codec,
                                           self.tally)
                    continue
                value = await loop.run_in_executor(
                    self._replica_pool, self._read_slot, slot)
                await write_json_async(writer,
                                       {"ok": True, "value": value},
                                       codec, self.tally)

            elif op == "hello":
                want = msg.get("caps")
                want = set(want) if isinstance(want, list) else set()
                agreed = sorted(want & self._caps())
                reply = {"ok": True, "proto": 1, "caps": agreed}
                router = self.router
                if router is not None and router.epoch is not None:
                    # The epoch rides hello so long-lived sessions
                    # notice a flip on reconnect without a route op.
                    reply["routing_epoch"] = router.epoch
                await write_json_async(writer, reply, codec,
                                       self.tally)
                codec = FrameCodec(compress="zlib" in agreed)
                binop_ok = "binop" in agreed
                sem_ok = "semantics" in agreed
                trace_ok = "trace" in agreed
                fed_ok = "federation" in agreed
                sketch_ok = "sketch" in agreed

            elif op == "route":
                router = self.router
                if router is None or router.table is None:
                    await write_json_async(
                        writer, {"ok": False, "code": "unrouted",
                                 "error": "no routing table installed"},
                        codec, self.tally)
                else:
                    await write_json_async(
                        writer, {"ok": True,
                                 "routing": router.table.to_json()},
                        codec, self.tally)

            elif op == "watch":
                slots = msg.get("slots")
                if slots is not None and (
                        not isinstance(slots, list) or not slots
                        or not all(_slot_ok(s, self._n_slots)
                                   for s in slots)):
                    await write_json_async(
                        writer, {"ok": False, "code": "write_rejected",
                                 "error": "bad watch slots"},
                        codec, self.tally)
                    continue
                head = await loop.run_in_executor(
                    self._replica_pool, self._watch_arm, ctx,
                    msg.get("since"))
                ctx.watch.add(writer, slots)
                ctx.watch_codec[writer] = codec
                self._m_watchers.set(
                    sum(len(c.watch) for c in self._ctxs),
                    node=self._node)
                # A subscribed session is exempt from idle expiry —
                # a silent watcher is the normal state, and the
                # fan-out path owns its liveness (buffer-cap shed).
                watching = True
                await write_json_async(
                    writer, {"ok": True, "mode": "watch",
                             "since": head},
                    codec, self.tally)

            elif op == "push":
                try:
                    await loop.run_in_executor(
                        self._replica_pool, self._merge_json,
                        msg["payload"], tctx)
                except Exception as e:
                    await write_json_async(
                        writer, {"ok": False, "code": "merge_rejected",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                await write_json_async(writer, {"ok": True}, codec,
                                       self.tally)

            elif op == "delta":
                try:
                    payload = await loop.run_in_executor(
                        self._replica_pool, self._export_json,
                        msg.get("since"))
                except Exception as e:
                    await write_json_async(
                        writer, {"code": "delta_failed",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                await write_json_async(writer, {"payload": payload},
                                       codec, self.tally)

            elif op == "push_dense":
                blob = await self._read_blob(reader, codec)
                if blob is None:
                    return
                try:
                    await loop.run_in_executor(
                        self._replica_pool, self._merge_dense,
                        msg.get("meta"), blob, msg.get("node_ids"),
                        tctx)
                except Exception as e:
                    await write_json_async(
                        writer, {"ok": False, "code": "dense_rejected",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                await write_json_async(writer, {"ok": True}, codec,
                                       self.tally)

            elif op == "delta_dense":
                try:
                    meta_msg, bufs = await loop.run_in_executor(
                        self._replica_pool, self._export_dense,
                        msg.get("since"))
                except Exception as e:
                    await write_json_async(
                        writer, {"code": "dense_rejected",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                await write_json_async(writer, meta_msg, codec,
                                       self.tally)
                await write_frame_async(writer, bufs, codec,
                                        self.tally)

            elif op == "push_packed":
                blob = await self._read_blob(reader, codec)
                if blob is None:
                    return
                try:
                    await loop.run_in_executor(
                        self._replica_pool, self._merge_packed,
                        msg.get("meta"), blob, msg.get("node_ids"),
                        tctx)
                except Exception as e:
                    await write_json_async(
                        writer, {"ok": False,
                                 "code": "packed_rejected",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                await write_json_async(writer, {"ok": True}, codec,
                                       self.tally)

            elif op == "delta_packed":
                try:
                    meta_msg, bufs = await loop.run_in_executor(
                        self._replica_pool, self._export_packed,
                        msg.get("since"), msg.get("ranges"), sem_ok)
                except Exception as e:
                    await write_json_async(
                        writer, {"code": "packed_rejected",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                await write_json_async(writer, meta_msg, codec,
                                       self.tally)
                # The packed read path's last hop: arena memoryviews,
                # vectored out with zero copies in this module.
                await write_frame_async(writer, bufs, codec,
                                        self.tally)

            elif op == "digest":
                # Cold-join slow lane: bounded, sheddable, and on its
                # OWN executor so a tree build never runs ahead of a
                # warm flush tick in the replica queue.
                if self._cold_inflight >= self.cold_lane_depth:
                    self.shed_count += 1
                    self._m_shed.inc(lane="cold", node=self._node)
                    await write_json_async(
                        writer,
                        {"ok": False, "code": "busy",
                         "error": "cold-join lane full "
                                  f"(depth={self.cold_lane_depth})"},
                        codec, self.tally)
                    continue
                try:
                    groups = _parse_digest_groups(msg)
                except (ValueError, TypeError) as e:
                    await write_json_async(
                        writer, {"code": "merkle_rejected",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                self._cold_inflight += 1
                try:
                    reply, buf = await loop.run_in_executor(
                        self._cold_pool, self._digest_values, groups)
                except Exception as e:
                    await write_json_async(
                        writer, {"code": "merkle_rejected",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                finally:
                    self._cold_inflight -= 1
                await write_json_async(writer, reply, codec,
                                       self.tally)
                await write_frame_async(writer, [buf], codec,
                                        self.tally)

            elif op == "heartbeat":
                # Group liveness probe (replication.py monitor). Rides
                # the replica executor ON PURPOSE: a tier whose replica
                # lane is wedged reads as dead — the monitor measures
                # end-to-end serviceability, not TCP accept.
                lease = msg.get("lease")
                lease_err = (self._grant_lease(lease)
                             if isinstance(lease, dict) else None)
                try:
                    state = await loop.run_in_executor(
                        self._replica_pool, self._hb_state,
                        bool(msg.get("want_root")))
                except Exception as e:
                    await write_json_async(
                        writer, {"ok": False, "code": "hb_failed",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                reply = {"ok": True, "op": "heartbeat",
                         "role": self.role, "group": self.group_name,
                         "node": self._node,
                         "lease_ms": self._lease_ms(), **state}
                if lease_err is not None:
                    reply["lease_rejected"] = lease_err
                await write_json_async(writer, reply, codec,
                                       self.tally)

            elif op == "lease":
                # Standalone grant (heartbeat can piggyback one too):
                # the fence a partitioned ex-primary honors by
                # answering `busy` once its TTL runs out.
                lease_err = self._grant_lease(msg)
                if lease_err is not None:
                    await write_json_async(
                        writer, {"ok": False, "code": "lease_stale",
                                 "error": lease_err},
                        codec, self.tally)
                else:
                    await write_json_async(
                        writer, {"ok": True, "role": self.role,
                                 "lease_ms": self._lease_ms()},
                        codec, self.tally)

            elif op == "replicate":
                blob = await self._read_blob(reader, codec)
                if blob is None:
                    return
                try:
                    head = await loop.run_in_executor(
                        self._replica_pool, self._replicate_in,
                        msg.get("meta"), blob, msg.get("node_ids"),
                        tctx)
                except Exception as e:
                    await write_json_async(
                        writer, {"ok": False,
                                 "code": "packed_rejected",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                await write_json_async(
                    writer, {"ok": True, "hlc": head,
                             "role": self.role},
                    codec, self.tally)

            elif op == "metrics":
                try:
                    snap = await loop.run_in_executor(
                        self._replica_pool, self._metrics_snapshot)
                except Exception as e:
                    await write_json_async(
                        writer, {"code": "metrics_failed",
                                 "error": type(e).__name__,
                                 "detail": str(e)},
                        codec, self.tally)
                    return
                if not sketch_ok:
                    # Pre-sketch sessions (no hello, or one that did
                    # not agree "sketch") get the reply a pre-sketch
                    # server produced, byte for byte: stripping the
                    # section restores the old key order exactly.
                    snap.pop("sketches", None)
                await write_json_async(writer, {"metrics": snap},
                                       codec, self.tally)

            elif op == "debug_dump":
                # Flight-recorder bundles (obs/recorder.py): the
                # post-incident forensics surface. New op — legacy
                # pollers never send it, so no cap is needed.
                from .obs.recorder import default_recorder
                bundles = default_recorder().bundles()
                if not sketch_ok:
                    bundles = [
                        {k: v for k, v in b.items()
                         if k != "sketches"} for b in bundles]
                await write_json_async(
                    writer, {"ok": True, "bundles": bundles},
                    codec, self.tally)

            else:
                await write_json_async(
                    writer, {"code": "unknown_op",
                             "error": f"unknown op {op!r}"},
                    codec, self.tally)
                return


def _binop_status_of(reply) -> Tuple[int, Optional[dict]]:
    """Map a JSON routing/proxy verdict onto a binop status byte plus
    an optional detail dict (the human-readable half of the reply:
    owner address, epoch, error text)."""
    if not isinstance(reply, dict):
        return BINOP_ST_BUSY, {"code": "busy",
                               "error": "owner returned garbage "
                                        "(proxy)"}
    if reply.get("ok"):
        return BINOP_ST_OK, None
    code = str(reply.get("code", "write_rejected"))
    status = {"busy": BINOP_ST_BUSY,
              "moved": BINOP_ST_MOVED}.get(code, BINOP_ST_REJECTED)
    detail = {k: v for k, v in reply.items() if k != "ok"}
    return status, detail


def _slot_ok(slot: Any, n_slots: int) -> bool:
    return (isinstance(slot, int) and not isinstance(slot, bool)
            and 0 <= slot < n_slots)


def _value_ok(value: Any) -> bool:
    # The int64 bound matches the store's value lane; anything wider
    # must be rejected per-write, never per-batch.
    return (isinstance(value, int) and not isinstance(value, bool)
            and -(1 << 63) <= value < (1 << 63))


def _parse_digest_groups(msg: dict) -> list:
    """Validate a digest op into [(level, idx-list), ...] — the same
    checks SyncServer applies, shared shape with the prefetch 'more'
    extension."""
    level = msg.get("level")
    idxs = msg.get("idx")
    if not isinstance(level, int) or not isinstance(idxs, list):
        raise ValueError("digest needs int 'level' + list 'idx'")
    groups = [(level, idxs)]
    more = msg.get("more")
    if more is not None:
        if not isinstance(more, list):
            raise ValueError(
                "digest 'more' must be a list of [level, idx] pairs")
        for pair in more:
            if not isinstance(pair, (list, tuple)) or len(pair) != 2:
                raise ValueError(
                    "digest 'more' entries need int level + list idx")
            lvl2, idx2 = pair
            if not isinstance(lvl2, int) or not isinstance(idx2, list):
                raise ValueError(
                    "digest 'more' entries need int level + list idx")
            groups.append((lvl2, idx2))
    return groups

"""CRDT core / merge engine (L2).

Abstract base holding the canonical clock and implementing the full CRDT
algebra on top of seven abstract storage primitives, matching the
reference `lib/src/crdt.dart:1-170` stage-for-stage:

- ``put`` advances the clock via ``Hlc.send`` and writes
  ``Record(t, v, t)`` (crdt.dart:39-43).
- ``put_all`` stamps a whole batch with ONE timestamp (crdt.dart:46-54).
- ``delete`` = ``put(key, None)`` (crdt.dart:58); ``clear`` tombstones
  all, or purges (crdt.dart:67-73).
- ``merge`` — the lattice join (crdt.dart:77-94): per remote record, (1)
  canonical = ``Hlc.recv(canonical, remote.hlc)`` for winners AND losers;
  (2) LWW filter — local wins on ``local.hlc >= remote.hlc``; (3) winners
  keep the remote ``hlc`` but ``modified`` = final canonical time; (4)
  bulk store; (5) final ``Hlc.send`` bump.
- ``refresh_canonical_time`` seeds the clock from the max stored
  logical_time (crdt.dart:114-121).

Wall-clock reads are injectable (``wall_clock`` ctor arg) so N-replica
tests are deterministic without real sleeps — the same injection pattern
the reference's own clock tests use (hlc_test.dart:185).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

from . import crdt_json
from .analysis import sanitizer as _sanitizer
from .hlc import Hlc, wall_clock_millis
from .record import (KeyDecoder, KeyEncoder, Record, ValueDecoder,
                     ValueEncoder)
from .utils.stats import merge_annotation
from .watch import ChangeStream

K = TypeVar("K")
V = TypeVar("V")


class Crdt(ABC, Generic[K, V]):
    """Abstract LWW-map CRDT (crdt.dart:7-170)."""

    # Backends that account merges set a MergeStats here (and usually
    # register it with the obs metrics registry); the base merge then
    # counts seen/adopted records without per-backend plumbing.
    stats = None

    def __init__(self, wall_clock: Optional[Callable[[], int]] = None):
        self._wall_clock = wall_clock or wall_clock_millis
        self._canonical_time: Hlc = None  # set by refresh_canonical_time
        self.refresh_canonical_time()

    # --- clock ---

    @property
    def canonical_time(self) -> Hlc:
        return self._canonical_time

    @property
    @abstractmethod
    def node_id(self) -> Any:
        ...

    def refresh_canonical_time(self) -> None:
        """Seed the canonical clock from the max stored logical time
        (crdt.dart:114-121). Backends with columnar storage override this
        with a vectorized max-reduce."""
        records = self.record_map()
        max_lt = max(
            (r.hlc.logical_time for r in records.values()), default=0)
        self._canonical_time = Hlc.from_logical_time(max_lt, self.node_id)

    # --- views (tombstones excluded: crdt.dart:16-29) ---

    @property
    def map(self) -> Dict[K, V]:
        return {k: r.value for k, r in self.record_map().items()
                if not r.is_deleted}

    @property
    def is_empty(self) -> bool:
        return len(self.map) == 0

    def __len__(self) -> int:
        return len(self.map)

    @property
    def length(self) -> int:
        return len(self.map)

    @property
    def keys(self) -> List[K]:
        return list(self.map.keys())

    @property
    def values(self) -> List[V]:
        return list(self.map.values())

    # --- local ops (crdt.dart:36-73) ---

    def get(self, key: K) -> Optional[V]:
        record = self.get_record(key)
        return None if record is None else record.value

    def put(self, key: K, value: Optional[V]) -> None:
        self._canonical_time = Hlc.send(self._canonical_time,
                                        millis=self._wall_clock())
        record: Record[V] = Record(self._canonical_time, value,
                                   self._canonical_time)
        self.put_record(key, record)

    def put_all(self, values: Dict[K, Optional[V]]) -> None:
        # Avoid touching the canonical time if no data is inserted
        if not values:
            return
        self._canonical_time = Hlc.send(self._canonical_time,
                                        millis=self._wall_clock())
        t = self._canonical_time
        self.put_records({k: Record(t, v, t) for k, v in values.items()})

    def delete(self, key: K) -> None:
        self.put(key, None)

    def is_deleted(self, key: K) -> Optional[bool]:
        record = self.get_record(key)
        return None if record is None else record.is_deleted

    def clear(self, purge: bool = False) -> None:
        if purge:
            self.purge()
        else:
            self.put_all({k: None for k in self.map})

    # --- merge: the lattice join (crdt.dart:77-94) ---

    def merge(self, remote_records: Dict[K, Record[V]]) -> None:
        with merge_annotation("crdt_tpu.host_merge",
                              hlc=lambda: self._canonical_time):
            self._merge_impl(remote_records)

    def _merge_impl(self, remote_records: Dict[K, Record[V]]) -> None:
        local_records = self._local_records_for(remote_records)

        wall = self._wall_clock()
        updated: Dict[K, Record[V]] = {}
        winners: List[K] = []
        for key, record in remote_records.items():
            # Clock absorption runs for winners AND losers (crdt.dart:82).
            self._canonical_time = Hlc.recv(self._canonical_time, record.hlc,
                                            millis=wall)
            local = local_records.get(key)
            if local is not None and local.hlc >= record.hlc:
                continue  # LWW: local wins on tie (crdt.dart:84)
            winners.append(key)

        # Winners keep the remote hlc; modified = FINAL canonical time —
        # the Dart removeWhere pass completes before re-stamping
        # (crdt.dart:86-87).
        for key in winners:
            record = remote_records[key]
            updated[key] = Record(record.hlc, record.value,
                                  self._canonical_time)

        self.put_records(updated)

        if self.stats is not None:
            self.stats.merges += 1
            self.stats.add_seen_lazy(len(remote_records))
            self.stats.add_adopted_lazy(len(winners))

        self._canonical_time = Hlc.send(self._canonical_time,
                                        millis=self._wall_clock())

        if _sanitizer.enabled():
            _sanitizer.check_scalar_join(self, remote_records)

    def _decode_wall_millis(self) -> int:
        """The ONE wall-clock read ``merge_json`` consumes for the
        decode-time ``modified`` stamp (crdt_json.dart:23-24).

        Tick-accounting contract: any override of ``merge_json`` that
        skips the generic decode (e.g. a columnar ingest) must consume
        its decode-time tick through THIS method — then both paths
        draw the same number of reads from an injected wall clock and
        FakeClock differentials stay aligned by construction (the
        conformance kit pins this with a counting clock). If the
        generic path ever grows another read, it must go through here
        too."""
        return self._wall_clock()

    def merge_json(self, json_str: str,
                   key_decoder: Optional[KeyDecoder] = None,
                   value_decoder: Optional[ValueDecoder] = None) -> None:
        records = crdt_json.decode(
            json_str,
            self._canonical_time,
            key_decoder=key_decoder,
            value_decoder=value_decoder,
            now_millis=self._decode_wall_millis(),
        )
        self.merge(records)

    # --- wire export (crdt.dart:124-135) ---

    def to_json(self, modified_since: Optional[Hlc] = None,
                key_encoder: Optional[KeyEncoder] = None,
                value_encoder: Optional[ValueEncoder] = None) -> str:
        return crdt_json.encode(
            self.record_map(modified_since=modified_since),
            key_encoder=key_encoder,
            value_encoder=value_encoder,
        )

    def count_modified_since(self, modified_since: Optional[Hlc] = None
                             ) -> int:
        """How many records (tombstones included) have
        ``modified.logical_time >= modified_since`` — the backlog a
        delta sync from that watermark would ship. ``None`` counts the
        whole store. Backends with columnar or SQL storage override
        this so lag monitoring never materializes a record map."""
        return len(self.record_map(modified_since=modified_since))

    def __repr__(self) -> str:
        return repr(self.record_map())

    def _local_records_for(self, keys) -> Dict[K, Record[V]]:
        """Local records consulted by ``merge`` for the given keys.

        Defaults to the full snapshot (the reference shape,
        crdt.dart:79); backends whose store may exceed memory (e.g.
        `SqliteCrdt`) override this with a keyed lookup so a delta
        merge is O(delta), not O(table)."""
        return self.record_map()

    # --- abstract storage primitives (crdt.dart:140-169) ---

    @abstractmethod
    def contains_key(self, key: K) -> bool:
        ...

    @abstractmethod
    def get_record(self, key: K) -> Optional[Record[V]]:
        ...

    @abstractmethod
    def put_record(self, key: K, record: Record[V]) -> None:
        """Store a record without updating the HLC. Meant for subclassing;
        clients should use put()."""

    @abstractmethod
    def put_records(self, record_map: Dict[K, Record[V]]) -> None:
        ...

    @abstractmethod
    def record_map(self, modified_since: Optional[Hlc] = None
                   ) -> Dict[K, Record[V]]:
        """Full record map including tombstones; ``modified_since`` keeps
        records with ``modified.logical_time >= t`` (inclusive —
        map_crdt.dart:44-45)."""

    @abstractmethod
    def watch(self, key: Optional[K] = None) -> ChangeStream:
        """Change stream; ``key`` filters to a single key
        (crdt.dart:162-164)."""

    @abstractmethod
    def purge(self) -> None:
        ...

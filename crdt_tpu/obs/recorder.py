"""SLO flight recorder: bounded debug bundles captured at incident
edges, dumped over the wire after the fact.

Post-incident forensics today depend on having had a poller attached
*while* the incident happened — the trace ring rotates, sketches keep
merging new samples over the bad window, and by the time a human asks
"what did p99 look like when the autoscaler split?", the evidence is
gone. The flight recorder inverts that: the *process that noticed*
captures a bundle at the moment of the edge, and keeps the last few
in memory for the ``debug_dump`` wire op (net.py / serve.py) and the
``python -m crdt_tpu.obs dump`` CLI to fetch later.

Trigger sites (all in-tree, all fire-and-forget):

- ``evaluate_slo`` flips to failing — the autoscaler's observe loop
  edge-detects the verdict (autoscale.py);
- the primary lease fence trips — a write arrived after the lease
  expired (serve.py);
- the runtime deadlock sanitizer counts a lock-order violation
  (analysis/concurrency.py ``OrderedLock._report``).

Each bundle carries the recent TraceRing span tail, the registry's
sketch snapshots (quantiles over the bad window, not bucket
ceilings), and whatever context sources are attached — a gossip node
attaches its ``metrics_extra`` provider, so bundles include the lag
matrix, routing-table epoch and per-partition load sections the
``metrics`` op would have shown a poller.

Capture is deliberately defensive: every section is independently
try/except-ed (a recorder must never turn an incident into a crash),
per-kind throttling stops a storming trigger (a fenced lease retried
in a tight loop) from churning the ring, and the recorder's own lock
is a leaf — capture gathers all obs state *before* taking it.
"""

from __future__ import annotations

import time
import weakref
from typing import Any, Callable, Dict, List, Optional

from ..analysis.concurrency import make_lock

# Bundles kept (oldest evicted) and trace-ring tail length per bundle.
DEFAULT_CAPACITY = 8
DEFAULT_SPAN_TAIL = 128
# Same-kind triggers inside this window are dropped (storm guard).
DEFAULT_THROTTLE_S = 5.0


class FlightRecorder:
    """Bounded in-memory ring of incident debug bundles."""

    # Leaf lock: capture gathers registry/trace state before taking
    # it, and nothing is acquired while holding it.
    _CRDTLINT_GUARDED = {"_lock": ("_bundles", "_seq", "_last_t",
                                   "_sources")}
    _CRDTLINT_LOCK_ORDER = ("_lock",)

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 span_tail: int = DEFAULT_SPAN_TAIL,
                 throttle_s: float = DEFAULT_THROTTLE_S):
        self.capacity = int(capacity)
        self.span_tail = int(span_tail)
        self.throttle_s = float(throttle_s)
        self._lock = make_lock("FlightRecorder._lock", 95)
        self._bundles: List[dict] = []
        self._seq = 0
        self._last_t: Dict[str, float] = {}
        # Weakly-held context providers (gossip nodes come and go in
        # tests; the recorder is process-global and must not pin them).
        self._sources: List[weakref.ref] = []

    # --- context sources ---

    def attach_source(self, fn: Callable[[], dict]) -> None:
        """Register a zero-arg provider whose dict is folded into
        every future bundle's ``sources`` list (weakly held; a bound
        method keeps only its instance alive-or-not)."""
        ref = (weakref.WeakMethod(fn) if hasattr(fn, "__self__")
               else weakref.ref(fn))
        with self._lock:
            self._sources = [r for r in self._sources
                             if r() is not None]
            self._sources.append(ref)

    # --- capture ---

    def trigger(self, kind: str,
                context: Optional[dict] = None) -> Optional[dict]:
        """Capture a bundle for incident ``kind``; returns it, or
        ``None`` when throttled. Never raises."""
        now = time.monotonic()
        with self._lock:
            last = self._last_t.get(kind)
            if last is not None and now - last < self.throttle_s:
                return None
            self._last_t[kind] = now
            sources = [r() for r in self._sources]
        bundle = self._capture(kind, context,
                               [s for s in sources if s is not None])
        with self._lock:
            self._seq += 1
            bundle["seq"] = self._seq
            self._bundles.append(bundle)
            del self._bundles[:-self.capacity]
        return bundle

    def _capture(self, kind: str, context: Optional[dict],
                 sources: List[Callable[[], dict]]) -> dict:
        from ..hlc import wall_clock_millis
        bundle: Dict[str, Any] = {"kind": kind,
                                  "t_wall_ms": float(wall_clock_millis()),
                                  "context": context or {}}
        try:
            from .trace import tracer
            ring = tracer()
            if ring.enabled:
                bundle["trace"] = ring.events()[-self.span_tail:]
        except Exception:
            pass
        try:
            from .registry import default_registry
            snap = default_registry().snapshot()
            bundle["sketches"] = snap.get("sketches", {})
            bundle["counters"] = snap.get("counters", {})
        except Exception:
            pass
        outs = []
        for fn in sources:
            try:
                out = fn()
                if isinstance(out, dict):
                    outs.append(out)
            except Exception:
                continue
        if outs:
            bundle["sources"] = outs
        return bundle

    # --- read side ---

    def bundles(self) -> List[dict]:
        """The retained bundles, oldest first (shallow list copy —
        bundles are write-once after capture)."""
        with self._lock:
            return list(self._bundles)

    def clear(self) -> None:
        with self._lock:
            self._bundles = []
            self._last_t = {}


_DEFAULT = FlightRecorder()


def default_recorder() -> FlightRecorder:
    """The process-wide recorder every in-tree trigger site uses."""
    return _DEFAULT

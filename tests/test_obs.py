"""Unified telemetry (crdt_tpu.obs): metrics registry, HLC-stamped
trace ring, convergence-lag monitor, the ``metrics`` wire op, and the
``python -m crdt_tpu.obs`` CLI — plus the crdtlint gate over the obs
package itself.

The registry under test is usually a FRESH ``MetricsRegistry`` (unit
scope); end-to-end tests go through the process-wide default registry
and therefore filter snapshots by label instead of asserting global
counts (other tests' backends live in the same process).
"""

import io
import json
import random
import threading

import pytest

from crdt_tpu import (DenseCrdt, GossipNode, Hlc, MapCrdt, Record,
                      RetryPolicy, SqliteCrdt, fetch_metrics)
from crdt_tpu.obs import (default_registry, metrics_snapshot, span,
                          tracer)
from crdt_tpu.obs.lag import health_status, lag_entry, lag_millis
from crdt_tpu.obs.registry import (Counter, Gauge, Histogram,
                                   MetricsRegistry)
from crdt_tpu.obs.render import (format_phase_table, render_prometheus,
                                 render_summary, summarize_trace)
from crdt_tpu.obs.trace import TraceRing
from crdt_tpu.testing import FakeClock, FaultProxy, FaultSchedule
from crdt_tpu.utils.stats import MergeStats

pytestmark = pytest.mark.obs

NO_SLEEP = lambda _s: None


# ---------------------------------------------------------------- registry


def test_counter_inc_value_and_labels():
    c = Counter("reqs_total", "requests")
    c.inc()
    c.inc(2, route="a")
    c.inc(route="a")
    assert c.value() == 1
    assert c.value(route="a") == 3
    assert c.value(route="never") == 0
    by_labels = {tuple(sorted(s["labels"].items())): s["value"]
                 for s in c.samples()}
    assert by_labels == {(): 1, (("route", "a"),): 3}


def test_counter_rejects_negative_increment():
    c = Counter("n", "")
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_and_add():
    g = Gauge("depth", "")
    g.set(5, q="x")
    g.add(-2, q="x")
    assert g.value(q="x") == 3


def test_histogram_log2_buckets_and_overflow():
    h = Histogram("lat", "", low_exp=-2, high_exp=2)
    assert h.bounds == (0.25, 0.5, 1.0, 2.0, 4.0)
    h.observe(0.2)     # <= 0.25 -> first bucket
    h.observe(0.25)    # boundary lands in its own bucket (le=0.25)
    h.observe(3.0)     # <= 4.0 -> last finite bucket
    h.observe(100.0)   # overflow (+Inf)
    (s,) = h.samples()
    assert s["count"] == 4
    assert s["sum"] == pytest.approx(103.45)
    assert s["overflow"] == 1
    counts = dict(s["buckets"])
    assert counts[0.25] == 2
    assert counts[4.0] == 1


def test_registry_get_or_create_and_kind_clash():
    reg = MetricsRegistry()
    c1 = reg.counter("a_total", "help")
    c2 = reg.counter("a_total")
    assert c1 is c2
    with pytest.raises(ValueError):
        reg.gauge("a_total")


def test_registry_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("c_total").inc(7)
    reg.gauge("g").set(1.5)
    reg.histogram("h", low_exp=0, high_exp=1).observe(1.0)
    snap = reg.snapshot()
    assert snap["counters"]["c_total"][0]["value"] == 7
    assert snap["gauges"]["g"][0]["value"] == 1.5
    assert snap["histograms"]["h"][0]["count"] == 1


def test_stats_collectors_absorbed_and_weakly_held():
    import gc
    reg = MetricsRegistry()
    ms = MergeStats()
    ms.merges = 3
    reg.attach("merge", ms, backend="X", node="n1")
    entries = reg.snapshot()["stats"]["merge"]
    assert entries == [{"labels": {"backend": "X", "node": "n1"},
                        "values": ms.as_dict()}]
    del ms
    gc.collect()
    assert reg.snapshot()["stats"].get("merge", []) == []


def test_backends_register_with_default_registry():
    crdt = SqliteCrdt("obs-reg-node")
    crdt.merge({"k": Record(Hlc(1_700_000_000_000, 0, "peer"), 1,
                            Hlc(1_700_000_000_000, 0, "peer"))})
    merge_rows = metrics_snapshot()["stats"]["merge"]
    (row,) = [e for e in merge_rows
              if e["labels"].get("node") == "obs-reg-node"]
    assert row["labels"]["backend"] == "SqliteCrdt"
    assert row["values"]["merges"] == 1
    assert row["values"]["records_seen"] == 1
    assert row["values"]["records_adopted"] == 1


# ---------------------------------------------------------------- trace ring


def test_ring_disabled_is_noop_and_lazy_hlc_not_evaluated():
    ring = TraceRing()
    calls = []
    ring.emit("merge", hlc=lambda: calls.append(1))
    assert ring.events() == [] and calls == []


def test_ring_bounded_and_ordered():
    ring = TraceRing(capacity=3)
    ring.enabled = True
    for i in range(5):
        ring.emit("k", i=i)
    assert [e["i"] for e in ring.events()] == [2, 3, 4]
    assert [e["seq"] for e in ring.events()] == [3, 4, 5]


def test_ring_jsonl_sink_and_hlc_stamp(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    ring = TraceRing()
    ring.enable(jsonl_path=path)
    ring.emit("merge", hlc=lambda: Hlc(1_700_000_000_000, 2, "a"),
              n=1)
    ring.disable()
    (line,) = open(path).read().splitlines()
    event = json.loads(line)
    assert event["kind"] == "merge" and event["n"] == 1
    assert event["hlc"] == str(Hlc(1_700_000_000_000, 2, "a"))


def test_span_emits_duration_and_histogram_sample():
    ring = tracer()
    ring.enable()
    ring.clear()
    try:
        with span("obs.test.phase", kind="bench_phase"):
            pass
        (event,) = ring.events("bench_phase")
        assert event["span"] == "obs.test.phase"
        assert event["dur_s"] >= 0
        hist = default_registry().histogram("crdt_tpu_span_seconds")
        assert any(s["labels"] == {"span": "obs.test.phase"}
                   for s in hist.samples())
    finally:
        ring.disable()
        ring.clear()


# ---------------------------------------------------------------- lag math


def test_lag_millis_and_entry():
    head = Hlc(1_700_000_060_000, 0, "a")
    mark = Hlc(1_700_000_000_000, 3, "a")
    assert lag_millis(head, mark) == 60_000
    assert lag_millis(head, None) is None
    assert lag_millis(mark, head) == 0    # clamped, never negative
    entry = lag_entry(head, mark, pending=4, breaker="closed",
                      dense=True)
    assert entry["synced"] and entry["lag_ms"] == 60_000
    assert entry["pending_records"] == 4 and entry["dense"]
    never = lag_entry(head, None)
    assert not never["synced"] and never["lag_ms"] is None


def test_health_status_rules():
    head = Hlc(1_700_000_060_000, 0, "a")
    ok = {"b": lag_entry(head, Hlc(1_700_000_059_000, 0, "a"),
                         breaker="closed")}
    assert health_status(ok) == "ok"
    assert health_status(ok, stale_after_ms=500) == "degraded"
    assert health_status(
        {"b": lag_entry(head, None)}) == "degraded"
    open_breaker = {"b": lag_entry(head, head, breaker="open")}
    assert health_status(open_breaker) == "degraded"
    assert health_status({}) == "ok"


# ------------------------------------------------- count_modified_since


def _mk_since(crdt):
    crdt.put("k1", 1)
    since = crdt.canonical_time
    crdt.put("k2", 2)
    crdt.put("k3", 3)
    return since


def test_count_modified_since_map():
    crdt = MapCrdt("a", wall_clock=FakeClock())
    since = _mk_since(crdt)
    # Inclusive bound (map_crdt.dart:44-45): the record at the watermark
    # itself still counts, so k1 is in the backlog along with k2/k3.
    assert crdt.count_modified_since(since) == 3
    assert crdt.count_modified_since(None) == 3
    assert crdt.count_modified_since(since) == \
        len(crdt.record_map(modified_since=since))


def test_count_modified_since_sqlite():
    crdt = SqliteCrdt("a", wall_clock=FakeClock())
    since = _mk_since(crdt)
    assert crdt.count_modified_since(since) == 3
    assert crdt.count_modified_since(None) == 3
    # matches the record_map view it summarizes
    assert crdt.count_modified_since(since) == \
        len(crdt.record_map(modified_since=since))


def test_count_modified_since_dense():
    crdt = DenseCrdt("a", 16, wall_clock=FakeClock())
    crdt.put_batch([1], [10])
    since = crdt.canonical_time
    crdt.put_batch([2], [20])
    crdt.delete_batch([1])   # tombstones count: they still need shipping
    assert crdt.count_modified_since(since) == 2
    assert crdt.count_modified_since(None) == 2


# -------------------------------------------------- metrics wire op / e2e


def _node(crdt, **kw):
    kw.setdefault("rng", random.Random(7))
    kw.setdefault("sleep", NO_SLEEP)
    return GossipNode(crdt, **kw)


def test_metrics_wire_op_end_to_end():
    clk = FakeClock()
    a = _node(MapCrdt("obs-a", wall_clock=clk))
    b = _node(MapCrdt("obs-b", wall_clock=clk))
    with a, b:
        a.add_peer("b", b.host, b.port)
        with a.lock:
            a.crdt.put("x", 1)
            a.crdt.put("y", 2)
        assert a.run_round() == {"b": "ok"}
        snap = fetch_metrics(a.host, a.port)

    assert snap["node"]["node_id"] == "obs-a"
    assert "hlc_head" in snap["node"]
    # per-peer HLC lag, from the node that owns the peers
    entry = snap["lag"]["b"]
    assert entry["synced"] is True
    assert entry["lag_ms"] is not None and entry["lag_ms"] >= 0
    assert entry["pending_records"] is not None
    assert entry["breaker"] == "closed"
    # per-peer gossip counters
    (peer_row,) = [e for e in snap["stats"]["peer_sync"]
                   if e["labels"].get("node") == "obs-a"]
    assert peer_row["labels"]["peer"] == "b"
    assert peer_row["values"]["rounds_ok"] == 1
    assert peer_row["values"]["bytes_sent"] > 0
    # merge counters from the remote replica's ingest
    merge_rows = [e for e in snap["stats"]["merge"]
                  if e["labels"].get("node") == "obs-b"]
    assert merge_rows and merge_rows[0]["values"]["records_seen"] >= 2
    # wire bytes, both roles
    roles = {e["labels"]["role"] for e in snap["stats"]["wire"]}
    assert {"server", "client"} <= roles
    client_rows = [e for e in snap["stats"]["wire"]
                   if e["labels"] == {"role": "client",
                                      "node": "obs-a"}]
    assert client_rows[0]["values"]["sent"] > 0

    # the snapshot renders in both formats without loss
    prom = render_prometheus(snap)
    assert 'crdt_tpu_peer_synced{node="obs-a",peer="b"} 1' in prom
    assert "crdt_tpu_merge_merges_total" in prom
    assert "crdt_tpu_wire_sent_bytes_total" in prom
    human = render_summary(snap)
    assert "obs-a" in human and "b" in human


def test_metrics_op_on_bare_sync_server():
    """A SyncServer without a GossipNode still answers: registry
    snapshot plus its own node identity, no lag section."""
    from crdt_tpu.net import SyncServer
    crdt = MapCrdt("obs-bare", wall_clock=FakeClock())
    server = SyncServer(crdt)
    server.start()
    try:
        snap = fetch_metrics(server.host, server.port)
    finally:
        server.stop()
    assert snap["node"]["node_id"] == "obs-bare"
    assert "lag" not in snap
    assert "stats" in snap


def test_unknown_op_still_rejected():
    """The metrics op must not have loosened the op whitelist."""
    from crdt_tpu.net import (SyncProtocolError, SyncServer,
                              recv_frame, send_frame)
    import socket
    import time
    crdt = MapCrdt("obs-unknown", wall_clock=FakeClock())
    server = SyncServer(crdt)
    server.start()
    try:
        with socket.create_connection((server.host, server.port),
                                      timeout=5) as sock:
            send_frame(sock, {"op": "metricz"})
            reply = recv_frame(sock, deadline=time.monotonic() + 5)
        assert reply["code"] == "unknown_op"
    finally:
        server.stop()


# --------------------------------- satellite: partitioned-peer lag growth


def test_three_node_lag_grows_under_partition_and_heals():
    """Hub node `a` gossips with a healthy peer `b` and a peer `c`
    behind an all-drop fault proxy. After one clean sync everywhere,
    the partition begins: c's lag (local head minus its watermark)
    grows with every local write while b's stays near zero, health
    degrades once c is staler than the threshold — then the proxy
    heals, one round collapses c's lag, and health returns to ok."""
    clk = FakeClock()
    a = _node(MapCrdt("a", wall_clock=clk),
              retry=RetryPolicy(max_attempts=1, base_delay=0.001))
    b = _node(MapCrdt("b", wall_clock=clk))
    c = _node(MapCrdt("c", wall_clock=clk))
    with a, b, c:
        drop_all = FaultSchedule(rate=1.0, kinds={"drop": 1})
        with FaultProxy(c.host, c.port, drop_all) as proxy:
            proxy.passthrough = True          # healthy to begin with
            a.add_peer("b", b.host, b.port)
            a.add_peer("c", proxy.host, proxy.port)
            with a.lock:
                a.crdt.put("k0", 0)
            assert a.run_round() == {"b": "ok", "c": "ok"}
            lag0 = a.lag_snapshot()
            assert lag0["c"]["synced"] and lag0["b"]["synced"]

            proxy.passthrough = False         # partition begins
            samples = []
            for i in range(3):
                clk.advance(10_000)
                with a.lock:
                    a.crdt.put(f"p{i}", i)
                outcome = a.run_round()
                assert outcome["b"] == "ok"
                assert outcome["c"] == "failed"
                snap = a.lag_snapshot()
                samples.append(snap["c"]["lag_ms"])
                # healthy peer keeps re-syncing: watermark tracks head
                assert snap["b"]["lag_ms"] < snap["c"]["lag_ms"]
            # monotone growth while partitioned
            assert samples == sorted(samples)
            assert samples[-1] > samples[0] >= 10_000
            assert snap["c"]["pending_records"] >= 3
            health = a.health(stale_after_ms=15_000)
            assert health["status"] == "degraded"

            proxy.passthrough = True          # heal
            assert a.sync_peer("c") == "ok"
            healed = a.lag_snapshot()["c"]
            assert healed["lag_ms"] < samples[0]
            assert a.health(stale_after_ms=15_000)["status"] == "ok"
    assert a.crdt.map == c.crdt.map


# ---------------------------------------------------------------- CLI


def test_cli_once_summary_json_and_prom():
    from crdt_tpu.obs.cli import main as obs_main
    clk = FakeClock()
    a = _node(MapCrdt("obs-cli", wall_clock=clk))
    b = _node(MapCrdt("obs-cli-b", wall_clock=clk))
    with a, b:
        a.add_peer("b", b.host, b.port)
        with a.lock:
            a.crdt.put("x", 1)
        assert a.run_round() == {"b": "ok"}
        target = f"{a.host}:{a.port}"

        out = io.StringIO()
        assert obs_main([target, "--once"], out=out) == 0
        assert "obs-cli" in out.getvalue()

        out = io.StringIO()
        assert obs_main([target, "--once", "--json"], out=out) == 0
        snap = json.loads(out.getvalue())
        assert snap["node"]["node_id"] == "obs-cli"
        assert snap["lag"]["b"]["synced"] is True

        out = io.StringIO()
        assert obs_main([target, "--once", "--prom"], out=out) == 0
        assert "crdt_tpu_peer_synced" in out.getvalue()


def test_cli_poll_failure_returns_nonzero():
    import socket
    from crdt_tpu.obs.cli import main as obs_main
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    assert obs_main([f"127.0.0.1:{port}", "--once"],
                    out=io.StringIO()) == 1


def test_cli_trace_summary_table(tmp_path):
    from crdt_tpu.obs.cli import main as obs_main
    path = str(tmp_path / "trace.jsonl")
    with open(path, "w") as f:
        for dur in (0.010, 0.020, 0.030):
            f.write(json.dumps({"kind": "merge", "span": "merge",
                                "dur_s": dur}) + "\n")
        f.write(json.dumps({"kind": "gossip_round",
                            "dur_s": 0.5}) + "\n")
        f.write(json.dumps({"kind": "breaker"}) + "\n")  # no dur_s
        f.write("{corrupt json\n")                       # tail line
    out = io.StringIO()
    assert obs_main(["--trace", path], out=out) == 0
    table = out.getvalue()
    assert "merge" in table and "gossip_round" in table
    assert "breaker" not in table


def test_summarize_trace_percentiles():
    events = [{"kind": "merge", "span": "m", "dur_s": d / 100}
              for d in range(1, 101)]
    summary = summarize_trace(events)
    stats = summary["m"]
    assert stats["count"] == 100
    assert stats["p50_s"] == pytest.approx(0.50)
    assert stats["p95_s"] == pytest.approx(0.95)
    assert stats["max_s"] == pytest.approx(1.00)
    table = format_phase_table(summary)
    assert "m" in table
    assert format_phase_table({}) == "no span events\n"


# ----------------------------------------------- breaker trace events


def test_breaker_transitions_emit_trace_events():
    from crdt_tpu import BreakerPolicy, CircuitBreaker
    clock = [100.0]
    br = CircuitBreaker(BreakerPolicy(failure_threshold=1,
                                      reset_timeout=5.0),
                        clock=lambda: clock[0], name="peer-x")
    ring = tracer()
    ring.enable()
    ring.clear()
    try:
        br.record_failure()                   # -> open
        clock[0] += 6.0
        assert br.allow()                     # -> half_open
        br.record_success()                   # -> closed
        states = [e["state"] for e in ring.events("breaker")
                  if e["peer"] == "peer-x"]
        assert states == ["open", "half_open", "closed"]
    finally:
        ring.disable()
        ring.clear()


# ------------------------------------------------ satellite: lint gate


@pytest.mark.analysis
def test_crdtlint_clean_on_obs_package():
    import os
    from crdt_tpu.analysis.cli import main as lint_main
    obs_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "crdt_tpu", "obs")
    assert lint_main(["--lint", obs_dir, "--json"]) == 0

"""Deliberately UNFENCED epoch-GC stub — crdtlint self-test fixture
for ``purge-watermark-unfenced``. Never imported by production code:

    python -m crdt_tpu.analysis --lint tests/fixtures/unfenced_purge.py

Expected findings: exactly ONE purge-watermark-unfenced, on the
`reckless_sweep` call below. `fenced_sweep` and `fenced_passthrough`
consult a stability watermark lexically first and must NOT be
flagged (docs/STORAGE.md).
"""


class RecklessJanitor:
    """Purges against the local clock — the exact corruption the rule
    exists to catch: tombstones other replicas still need get
    physically deleted, and their deletes later resurrect."""

    def __init__(self, crdt):
        self.crdt = crdt

    def reckless_sweep(self):
        # UNFENCED: no stability watermark anywhere in this function;
        # the local head says nothing about what peers have seen.
        return self.crdt.gc_purge(self.crdt.canonical_time)


class FencedJanitor:
    """The disciplined shape: fold the fleet watermark, pin on
    unmeasured peers, purge only what stability proves stable."""

    def __init__(self, crdt, node):
        self.crdt = crdt
        self.node = node

    def fenced_sweep(self):
        stability = self.node.stability_hlc()
        if stability is None:
            return 0                       # pinned: purge nothing
        return self.crdt.gc_purge(stability)

    def fenced_passthrough(self, stability):
        # Evidence on the call line itself (the adapter shape:
        # KeyedDenseCrdt.gc_purge forwards its argument).
        return self.crdt.gc_purge(stability)

"""Federated serving suite (docs/FEDERATION.md): routed client ops
across partitions, the `moved` wire protocol (shape, session
survival, never-legacy classification), server-side proxying for
pre-federation sessions, the stale-epoch refusal that fences live
splits, watch fan-out end-to-end, and a kill-and-restart split under
a write storm proving zero acked writes are lost.

Metrics recorded here stay here: the conftest registry-isolation
fixture snapshots and restores the process-global registry around
each module, so this suite's ack latency samples cannot leak into
another module's fleet-poller SLO verdict (modules may run in any
order)."""

import socket
import threading
import time

import pytest

from crdt_tpu import (FederatedClient, FederatedTier, PeerConnection,
                      SyncProtocolError, SyncRedirectError,
                      SyncTransportError)
from crdt_tpu.net import (FrameCodec, _check_reply, recv_frame,
                          send_frame)
from crdt_tpu.testing import FaultProxy, ScriptedSchedule

pytestmark = pytest.mark.serve

N_SLOTS = 256


def _req(sock, obj, codec=None):
    send_frame(sock, obj, None, codec)
    return recv_frame(sock, deadline=time.monotonic() + 10.0,
                      codec=codec)


def _fed_session(tier):
    """Raw federated session: hello with the federation cap, then the
    post-hello codec (no zlib requested, so uncompressed tagged
    frames)."""
    sock = socket.create_connection((tier.host, tier.port),
                                    timeout=10.0)
    sock.settimeout(10.0)
    reply = _req(sock, {"op": "hello", "proto": 1,
                        "caps": ["federation"]})
    assert reply["ok"] and "federation" in reply["caps"]
    return sock, FrameCodec(compress=False)


def _foreign_slot(fed, tier):
    """A slot the given tier does NOT own."""
    for slot in range(fed.table.n_slots):
        if fed.table.owner_of(slot) != tier.router.addr:
            return slot
    raise AssertionError("single-owner table")


def _owned_slot(fed, tier):
    for slot in range(fed.table.n_slots):
        if fed.table.owner_of(slot) == tier.router.addr:
            return slot
    raise AssertionError(f"{tier.router.addr} owns nothing")


# --- routed client across partitions ---

def test_client_put_get_across_partitions():
    with FederatedTier(N_SLOTS, partitions=3,
                       flush_interval=0.002) as fed:
        assert len(set(fed.table.owners())) == 3
        cli = FederatedClient(fed.addrs())
        try:
            # One write per partition plus range edges: every op must
            # land regardless of which tier owns the slot.
            slots = sorted({_owned_slot(fed, t) for t in fed.tiers}
                           | {0, N_SLOTS // 2, N_SLOTS - 1})
            for slot in slots:
                cli.put(slot, 1000 + slot)
            for slot in slots:
                assert cli.get(slot) == 1000 + slot
            cli.delete(slots[0])
            assert cli.get(slots[0]) is None
            # A well-routed client never needed a redirect.
            assert cli.moved_redirects == 0
        finally:
            cli.close()


# --- the moved wire protocol ---

def test_moved_reply_shape_and_session_survives():
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        tier = fed.tiers[0]
        sock, codec = _fed_session(tier)
        with sock:
            foreign = _foreign_slot(fed, tier)
            reply = _req(sock, {"op": "put", "slot": foreign,
                                "value": 1, "epoch": fed.table.epoch},
                         codec)
            assert reply["ok"] is False
            assert reply["code"] == "moved"
            assert reply["owner"] == fed.table.owner_of(foreign)
            assert reply["epoch"] == fed.table.epoch
            # The redirect carries everything a single-slot client
            # needs — and the session is NOT torn down by it.
            owned = _owned_slot(fed, tier)
            assert _req(sock, {"op": "put", "slot": owned,
                               "value": 7,
                               "epoch": fed.table.epoch},
                        codec) == {"ok": True}
            assert _req(sock, {"op": "get", "slot": owned,
                               "epoch": fed.table.epoch},
                        codec)["value"] == 7
            send_frame(sock, {"op": "bye"}, None, codec)


def test_pre_federation_session_is_proxied():
    """A session that never negotiated the federation cap cannot
    parse `moved`; the server must forward the op to the owner and
    relay the ack — pre-federation clients keep working unchanged."""
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        tier = fed.tiers[0]
        foreign = _foreign_slot(fed, tier)
        with socket.create_connection((tier.host, tier.port),
                                      timeout=10.0) as sock:
            sock.settimeout(10.0)
            # No hello at all: the oldest client generation.
            assert _req(sock, {"op": "put", "slot": foreign,
                               "value": 9}) == {"ok": True}
            assert _req(sock, {"op": "get",
                               "slot": foreign})["value"] == 9
            send_frame(sock, {"op": "bye"})
        # The write really lives on the owning tier, not the proxy.
        owner = fed.tier_at(fed.table.owner_of(foreign))
        with socket.create_connection((owner.host, owner.port),
                                      timeout=10.0) as sock:
            sock.settimeout(10.0)
            assert _req(sock, {"op": "get",
                               "slot": foreign})["value"] == 9
            send_frame(sock, {"op": "bye"})


def test_stale_epoch_refused_even_on_owned_slot():
    """After a split bumps the epoch, an op stamped with the old
    epoch answers `moved` even when the slot's owner did not change —
    the refusal that forces a table refetch before a write can race a
    migrating range."""
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        tier = fed.tiers[0]
        sock, codec = _fed_session(tier)
        with sock:
            assert _req(sock, {"op": "put", "slot": 3, "value": 1,
                               "epoch": 0}, codec) == {"ok": True}
            split = fed.split_hot(src=0)
            assert split["epoch"] == 1
            # Slot 3 sits in the donor's KEPT half: same owner, new
            # epoch. The stale stamp must still be refused.
            assert fed.table.owner_of(3) == tier.router.addr
            reply = _req(sock, {"op": "put", "slot": 3, "value": 2,
                                "epoch": 0}, codec)
            assert reply["code"] == "moved"
            assert reply["owner"] == tier.router.addr
            assert reply["epoch"] == 1
            # Re-stamped with the new epoch, the same op lands.
            assert _req(sock, {"op": "put", "slot": 3, "value": 2,
                               "epoch": 1}, codec) == {"ok": True}
            send_frame(sock, {"op": "bye"}, None, codec)


# --- client-side classification: moved is typed, never legacy ---

def test_check_reply_moved_raises_typed_redirect():
    reply = {"ok": False, "code": "moved", "owner": "10.0.0.2:7002",
             "epoch": 5, "error": "slot 9 owned elsewhere"}
    with pytest.raises(SyncRedirectError) as exc:
        _check_reply("put", reply, "ok")
    assert exc.value.owner == "10.0.0.2:7002"
    assert exc.value.epoch == 5
    # Retryable-by-construction: transport class, not a protocol
    # rejection (a protocol error would poison the peer forever).
    assert isinstance(exc.value, SyncTransportError)
    assert not isinstance(exc.value, SyncProtocolError)


def test_hello_moved_does_not_demote_to_legacy():
    """A `moved` at hello must raise the typed redirect and leave the
    connection un-demoted: the pre-hello fallback is for servers that
    don't SPEAK hello, and a federated tier emphatically does."""
    lsock = socket.create_server(("127.0.0.1", 0))
    lsock.settimeout(10.0)
    host, port = lsock.getsockname()[:2]

    def serve_one():
        conn, _ = lsock.accept()
        with conn:
            conn.settimeout(10.0)
            recv_frame(conn, deadline=time.monotonic() + 10.0)
            send_frame(conn, {"ok": False, "code": "moved",
                              "owner": "10.0.0.9:7009", "epoch": 4})

    t = threading.Thread(target=serve_one, daemon=True)
    t.start()
    conn = PeerConnection(host, port, timeout=5.0)
    try:
        with pytest.raises(SyncRedirectError) as exc:
            conn.ensure()
        assert exc.value.owner == "10.0.0.9:7009"
        assert exc.value.epoch == 4
        assert conn.legacy is False
        assert conn.connected is False
    finally:
        conn.close()
        t.join(timeout=10)
        lsock.close()


# --- watch fan-out ---

def test_watch_fan_out_delivers_committed_writes():
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        cli = FederatedClient(fed.addrs())
        slot = _owned_slot(fed, fed.tiers[1])
        owner = fed.table.owner_of(slot)
        watch = cli.watch(owner, slots=[slot])
        try:
            cli.put(slot, 42)
            deadline = time.monotonic() + 10.0
            events = []
            # Shared-tick packs are filtered client-side, so a pack
            # carrying only other slots legally arrives empty.
            while not events and time.monotonic() < deadline:
                events = watch.next_event(timeout=10.0)
            assert events == [(slot, 42)]
            cli.delete(slot)
            events = []
            while not events and time.monotonic() < deadline:
                events = watch.next_event(timeout=10.0)
            assert events == [(slot, None)]
        finally:
            watch.close()
            cli.close()


# --- kill-and-restart split under a write storm ---

class _ProxiedFed(FederatedTier):
    """Arms a FaultProxy at the newly spawned recipient before the
    split engine can dial it: `_spawn_tier` runs inside
    `_split_locked` strictly before the `_Upstream(stream_addr)`
    connect, so retargeting here cannot race the stream."""

    def __init__(self, *args, proxy=None, **kw):
        super().__init__(*args, **kw)
        self._proxy = proxy

    def _spawn_tier(self, index):
        tier = super()._spawn_tier(index)
        if self._proxy is not None and index >= self._n_initial:
            self._proxy.target_port = tier.port
        return tier


def test_split_survives_mid_handoff_cut_with_zero_lost_writes():
    """The acceptance drill: cut the migration stream mid-frame while
    a write storm targets the migrating range. The split must retry
    on a fresh connection (idempotent replay), complete, and every
    acked write must read back — zero lost."""
    sched = ScriptedSchedule([
        # Connection 1 (the split engine's initial upstream): let the
        # ~70-byte hello through, then cut the round-1 push mid-frame.
        {"kind": "truncate", "after": 150},
        # Connection 2+ (the retry): behave.
        None,
    ])
    proxy = FaultProxy("127.0.0.1", 1, sched)   # retargeted at spawn
    with proxy:
        with _ProxiedFed(N_SLOTS, partitions=2,
                         flush_interval=0.002, proxy=proxy) as fed:
            cli = FederatedClient(fed.addrs())
            # Seed the migrating half [64, 128) so round 1's pack is
            # fat enough to trip the truncate.
            for slot in range(64, 128):
                cli.put(slot, slot)

            storm_slots = (70, 90, 110, 127)
            acked = {s: None for s in storm_slots}
            stop = threading.Event()
            failures = []

            def storm():
                scli = FederatedClient(fed.addrs())
                v = 1000
                try:
                    while not stop.is_set():
                        for s in storm_slots:
                            v += 1
                            scli.put(s, v)
                            acked[s] = v
                except Exception as e:     # pragma: no cover
                    failures.append(e)
                finally:
                    scli.close()

            t = threading.Thread(target=storm, daemon=True)
            t.start()
            try:
                split = fed.split_hot(src=0, settle_rows=8,
                                      dst_addr_override=(
                                          f"{proxy.host}:{proxy.port}"))
            finally:
                stop.set()
                t.join(timeout=30)

            assert not failures, f"storm writes failed: {failures!r}"
            assert proxy.counters.get("truncate", 0) >= 1, \
                f"cut never fired: {proxy.counters}"
            assert proxy.counters["connections"] >= 2   # reconnected
            assert split["epoch"] == 1
            assert split["migrated_rows"] >= 64
            assert len(fed.tiers) == 3
            assert fed.table.owner_of(64) == fed.tiers[2].router.addr

            # Zero lost writes: per-slot values are monotone, so the
            # last ACK is exactly what a read must return — from the
            # NEW owner, post-migration.
            cli.refresh()
            for slot in range(64, 128):
                want = acked.get(slot)
                if want is None:
                    want = slot            # seed value, never stormed
                assert cli.get(slot) == want, f"slot {slot}"
            cli.close()


# --- the live merge state machine (inverse of split_hot) ---

def _wait(pred, timeout=10.0, interval=0.005, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


# Tight but CI-safe replica-group timings (mirrors
# tests/test_replication.py): detection in ~3 beats, promote in
# milliseconds, client retry budget comfortably above both.
FAST_FED = dict(flush_interval=0.002, heartbeat_interval=0.02,
                heartbeat_timeout=0.15, lease_misses=3)


def test_merge_cold_retires_donor_with_zero_lost_rows():
    with FederatedTier(N_SLOTS, partitions=3,
                       flush_interval=0.002) as fed:
        cli = FederatedClient(fed.addrs())
        try:
            for slot in range(0, N_SLOTS, 3):
                cli.put(slot, slot + 7)
        finally:
            cli.close()
        e0 = fed.table.epoch
        stats = fed.merge_cold()
        assert stats is fed.last_merge
        assert stats["epoch"] == e0 + 1 == fed.table.epoch
        assert len(fed.tiers) == 2 and len(fed.groups) == 2
        assert stats["src_addr"] not in fed.table.owners()
        assert stats["dst_addr"] in fed.table.owners()
        assert stats["migrated_rows"] >= 1
        # The recipient wears the absorb stamp the fleet table shows.
        absorber = fed.tier_at(stats["dst_addr"])
        assert absorber.last_scale == {"action": "merge-absorb",
                                       "epoch": stats["epoch"],
                                       "peer": stats["src_addr"]}
        # Every row the donor owned reads back from the survivors.
        cli = FederatedClient(fed.addrs())
        try:
            for slot in range(0, N_SLOTS, 3):
                assert cli.get(slot) == slot + 7
        finally:
            cli.close()


def test_merge_refuses_the_last_partition():
    with FederatedTier(N_SLOTS, partitions=1,
                       flush_interval=0.002) as fed:
        with pytest.raises(ValueError):
            fed.merge_cold()
        assert len(fed.tiers) == 1 and fed.table.epoch == 0


def test_elastic_cycles_never_reuse_partition_identity():
    """split -> write through the recipient -> merge -> split again.

    The first cycle's recipient commits rows stamped with its own
    node id; the merge carries them into the survivor. If the second
    cycle's recipient reused the retired name (list position instead
    of the monotone spawn sequence), re-migrating those rows would be
    rejected as a duplicate node mid-stream — a deterministic
    `ConnectionError` after retries exhaust. Regression for exactly
    that."""
    acked = {}
    with FederatedTier(N_SLOTS, partitions=1,
                       flush_interval=0.002) as fed:
        for cycle in range(2):
            fed.split_hot()
            # Commit rows THROUGH the fresh recipient so its node id
            # outlives it inside whichever partition absorbs it.
            cli = FederatedClient(fed.addrs())
            try:
                for slot in range(1, N_SLOTS, 7):
                    cli.put(slot, cycle * N_SLOTS + slot)
                    acked[slot] = cycle * N_SLOTS + slot
            finally:
                cli.close()
            fed.merge_cold()
            assert len(fed.tiers) == 1
        assert fed.table.epoch == 4
        rcli = FederatedClient(fed.addrs())
        try:
            for slot, want in acked.items():
                assert rcli.get(slot) == want, f"slot {slot}"
        finally:
            rcli.close()


def test_merge_survives_mid_handoff_cut_with_zero_lost_writes():
    """Cut the merge's migration stream mid-frame: the stream must
    retry on a fresh connection (idempotent replay), complete, and
    every row the donor owned must read back from the recipient."""
    sched = ScriptedSchedule([
        # Connection 1: let the ~70-byte hello through, then cut the
        # round-1 push mid-frame.
        {"kind": "truncate", "after": 150},
        # Connection 2+ (the retry): behave.
        None,
    ])
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        donor_addr = fed.tiers[0].router.addr
        dst_addr = fed._merge_neighbor(donor_addr)
        recipient = fed.tier_at(dst_addr)
        seeded = [s for lo, hi in fed.table.ranges_of(donor_addr)
                  for s in range(lo, hi)]
        cli = FederatedClient(fed.addrs())
        try:
            for slot in seeded:
                cli.put(slot, slot + 3)
        finally:
            cli.close()
        with FaultProxy(recipient.host, recipient.port,
                        sched) as proxy:
            stats = fed.merge_cold(
                src=0,
                dst_addr_override=f"{proxy.host}:{proxy.port}")
            assert proxy.counters.get("truncate", 0) >= 1, \
                f"cut never fired: {proxy.counters}"
            assert proxy.counters["connections"] >= 2  # reconnected
        assert stats["epoch"] == 1
        assert stats["migrated_rows"] >= len(seeded)
        assert len(fed.tiers) == 1
        cli = FederatedClient(fed.addrs())
        try:
            for slot in seeded:
                assert cli.get(slot) == slot + 3, f"slot {slot}"
        finally:
            cli.close()


def test_watch_rehomes_across_merge_and_keeps_delivering():
    """A watch session subscribed on the retiring donor receives a
    typed ``moved`` push, transparently resubscribes at the absorbing
    owner with the flip-watermark resume mark, and keeps receiving
    commit events — none dropped across the move."""
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        cli = FederatedClient(fed.addrs())
        donor = fed.tiers[0]
        slot = _owned_slot(fed, donor)
        watch = cli.watch(donor.router.addr, slots=[slot])
        try:
            cli.put(slot, 1)
            deadline = time.monotonic() + 10.0
            events = []
            while not events and time.monotonic() < deadline:
                events = watch.next_event(timeout=10.0)
            assert events == [(slot, 1)]

            stats = fed.merge_cold(src=0)
            assert stats["rehomed_watchers"] == 1

            # A write committed at the NEW owner still reaches the
            # session. (The recipient's rewound watermark may re-ship
            # the pre-merge row first — at-least-once delivery — so
            # poll until the new value lands.)
            cli.put(slot, 2)
            got = None
            while got != 2 and time.monotonic() < deadline:
                for s, v in watch.next_event(timeout=10.0):
                    if s == slot:
                        got = v
            assert got == 2
            assert watch.moved_rehomes == 1
            assert watch.addr == stats["dst_addr"]
        finally:
            watch.close()
            cli.close()


# --- client redirect budget across topology churn ---

def test_redirect_budget_resets_only_when_the_epoch_advances():
    """Deterministic budget accounting: five consecutive ``moved``
    replies would blow a 3-attempt budget, but each refresh that
    ADVANCES the epoch resets it — while a refresh that learns
    nothing must still burn an attempt (or a permanently stale table
    would spin forever)."""
    from crdt_tpu.routing import RoutingTable

    def _client(max_redirects):
        cli = FederatedClient.__new__(FederatedClient)
        cli._seeds = ["h:1"]
        cli._timeout = 1.0
        cli._max_redirects = max_redirects
        cli._sessions = {}
        cli.moved_redirects = 0
        cli.busy_retries = 0
        cli.redirect_resets = 0
        cli.table = RoutingTable(16, 0, [(0, 16, "h:1")])
        cli._backoff = lambda attempt: None
        return cli

    moved = {"ok": False, "code": "moved", "owner": "h:1", "epoch": 0}

    class _Scripted:
        def __init__(self, replies):
            self.replies = list(replies)

        def request(self, msg):
            return self.replies.pop(0)

    # Churny fleet: every refresh advances the epoch, so the budget
    # keeps resetting and the op outlives 5 redirects on a budget
    # of 3.
    cli = _client(max_redirects=3)
    sess = _Scripted([moved] * 5 + [{"ok": True}])
    cli._session = lambda addr: sess
    cli._try_refresh = lambda: setattr(
        cli, "table",
        RoutingTable(16, cli.table.epoch + 1, [(0, 16, "h:1")]))
    assert cli._keyspace({"op": "put", "slot": 1, "value": 1}, 1) \
        == {"ok": True}
    assert cli.redirect_resets == 5
    assert cli.moved_redirects == 5

    # Stale fleet: refresh learns nothing, so the budget bounds the
    # spin at exactly max_redirects attempts.
    cli = _client(max_redirects=3)
    sess = _Scripted([moved] * 10)
    cli._session = lambda addr: sess
    cli._try_refresh = lambda: None
    with pytest.raises(ConnectionError):
        cli._keyspace({"op": "put", "slot": 1, "value": 1}, 1)
    assert cli.redirect_resets == 0
    assert len(sess.replies) == 10 - 3


def test_client_survives_more_churn_than_its_redirect_budget():
    """Forced churn: four topology changes while a 3-attempt client
    keeps writing. Epoch-advancing refreshes reset the budget, so
    every write lands and nothing acked is lost."""
    with FederatedTier(N_SLOTS, partitions=2,
                       flush_interval=0.002) as fed:
        cli = FederatedClient(fed.addrs(), max_redirects=3)
        slots = (1, 90, 170, 250)
        acked = {}
        failures = []

        def churn():
            try:
                for _ in range(2):
                    fed.split_hot()
                    fed.merge_cold()
            except Exception as e:   # pragma: no cover
                failures.append(e)

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        try:
            v = 0
            while t.is_alive():
                for s in slots:
                    v += 1
                    cli.put(s, v)
                    acked[s] = v
        finally:
            t.join(timeout=60)
        assert not failures, f"churn failed: {failures!r}"
        assert fed.table.epoch >= 4          # four changes landed
        for s, want in acked.items():
            assert cli.get(s) == want, f"slot {s}"
        cli.close()


# --- merge crash-safety: donor-primary kills on both sides of the flip ---

def test_merge_pre_flip_donor_kill_aborts_cleanly_then_retries():
    """Donor primary abruptly killed before the routing flip: the
    merge must abort with the topology untouched (the table still
    names the donor group, whose failover keeps serving the arc), and
    a retry after promotion must complete with zero acked loss."""
    from crdt_tpu.testing_faults import abrupt_kill

    with FederatedTier(N_SLOTS, partitions=2, replicas=2,
                       ack_replicas=1, **FAST_FED) as fed:
        donor_addr = fed.tiers[0].router.addr
        seeded = [s for lo, hi in fed.table.ranges_of(donor_addr)
                  for s in range(lo, hi, 4)]
        cli = FederatedClient(fed.addrs())
        try:
            for slot in seeded:
                cli.put(slot, slot + 11)
        finally:
            cli.close()

        abrupt_kill(fed.tiers[0])
        with pytest.raises(ConnectionError):
            fed.merge_cold(src=0)
        # Clean abort: nothing flipped, nothing retired.
        assert fed.last_merge is None
        assert len(fed.tiers) == 2 and len(fed.groups) == 2

        # The group fails over on its own; the arc is served
        # throughout (by the survivor, under the reassigned table).
        _wait(lambda: not fed.tiers[0].killed, timeout=15.0,
              what="donor-group failover")
        cli = FederatedClient(fed.addrs())
        try:
            assert cli.get(seeded[0]) == seeded[0] + 11
        finally:
            cli.close()

        # The retry streams from the new primary and completes.
        stats = fed.merge_cold(src=0)
        assert stats["migrated_rows"] >= len(seeded)
        assert len(fed.tiers) == 1
        cli = FederatedClient(fed.addrs())
        try:
            for slot in seeded:
                assert cli.get(slot) == slot + 11, f"slot {slot}"
        finally:
            cli.close()


def test_merge_post_flip_donor_kill_hands_off_to_failover():
    """Donor primary abruptly killed in the post-flip drain window:
    the table already dropped the donor, so aborting would strand its
    arcs — the merge must instead wait out the group's promotion and
    re-ship the full arc from the new primary (write concern put
    every acked row there), then retire the group."""
    from crdt_tpu.testing_faults import abrupt_kill

    with FederatedTier(N_SLOTS, partitions=2, replicas=2,
                       ack_replicas=1, flush_interval=0.05,
                       heartbeat_interval=0.02,
                       heartbeat_timeout=0.15,
                       lease_misses=3) as fed:
        donor_addr = fed.tiers[0].router.addr
        seeded = [s for lo, hi in fed.table.ranges_of(donor_addr)
                  for s in range(lo, hi, 4)]
        cli = FederatedClient(fed.addrs())
        try:
            for slot in seeded:
                cli.put(slot, slot + 13)
        finally:
            cli.close()

        e0 = fed.table.epoch
        donor_group = fed.groups[0]
        result, errors = [], []

        def run():
            try:
                result.append(fed.merge_cold(src=0))
            except BaseException as e:   # pragma: no cover
                errors.append(e)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        # The epoch bump IS the flip; the drain window behind it is
        # flush_interval * 4 = 200 ms — kill the donor primary inside
        # it, before the final catch-up round.
        _wait(lambda: fed.table.epoch > e0, timeout=30.0,
              interval=0.0005, what="routing flip")
        abrupt_kill(donor_group)
        th.join(timeout=60.0)

        assert not errors, f"merge failed: {errors!r}"
        stats = result[0]
        # The failover counter increments after the monitor's
        # _on_promote callback returns, which can trail the merge's
        # own completion by a beat.
        _wait(lambda: donor_group.failovers >= 1, timeout=5.0,
              what="handoff failover")
        assert len(fed.tiers) == 1 and len(fed.groups) == 1
        assert stats["src_addr"] == donor_addr
        cli = FederatedClient(fed.addrs())
        try:
            for slot in seeded:
                assert cli.get(slot) == slot + 13, f"slot {slot}"
        finally:
            cli.close()


# --- the elastic chaos soak: split/merge cycles on an all-proxied fleet ---

@pytest.mark.slow
@pytest.mark.soak
def test_chaos_soak_elastic_cycles_through_fault_proxies():
    """>= 2 full split+merge cycles with EVERY wire the federation
    uses (client ops, heartbeats, replication ships, migration
    streams) routed through misbehaving `FaultProxy`s, under a
    client write storm: zero acked loss, every replica group
    convergent, and the partition count back at baseline."""
    from crdt_tpu.testing_faults import FaultSchedule, ProxyFarm

    # `rate` is per-CONNECTION (the chance a connection faults at
    # all), and this fleet holds few, long-lived sessions — a timid
    # rate fires nothing in a short soak. Keep it high and the fault
    # mix delay-heavy so chaos is guaranteed without stalling the
    # storm behind client-timeout-length drop recoveries.
    farm = ProxyFarm(lambda i: FaultSchedule(
        seed=i, rate=0.45,
        kinds={"drop": 1, "delay": 4, "duplicate": 1},
        max_delay=0.01))
    with farm:
        with FederatedTier(N_SLOTS, partitions=2, replicas=2,
                           ack_replicas=1, addr_via=farm.via,
                           **FAST_FED) as fed:
            baseline = len(fed.tiers)
            acked = {}
            lock = threading.Lock()
            stop = threading.Event()
            failures = []

            def storm():
                scli = FederatedClient(fed.addrs(), timeout=5.0)
                slots = (3, 77, 130, 200)
                v = 0
                try:
                    while not stop.is_set():
                        for s in slots:
                            v += 1
                            scli.put(s, v)
                            with lock:
                                acked[s] = v
                        time.sleep(0.002)
                except Exception as e:   # pragma: no cover
                    failures.append(e)
                finally:
                    scli.close()

            th = threading.Thread(target=storm, daemon=True)
            th.start()
            try:
                for cycle in range(2):
                    fed.split_hot()
                    assert len(fed.tiers) == baseline + 1
                    fed.merge_cold()
                    assert len(fed.tiers) == baseline
            finally:
                stop.set()
                th.join(timeout=60.0)
            assert not failures, f"storm writes failed: {failures!r}"
            assert fed.table.epoch >= 4

            # Zero acked loss, read back through the faulty wires.
            rcli = FederatedClient(fed.addrs(), timeout=5.0)
            try:
                with lock:
                    frozen = dict(acked)
                for s, want in frozen.items():
                    assert rcli.get(s) == want, f"slot {s}"

                # Every surviving group converges: nudge each
                # partition's arc to re-arm its flush tick, then
                # compare member digest roots.
                def _converged():
                    for i, g in enumerate(fed.groups):
                        lo, hi = fed.table.ranges_of(
                            fed.tiers[i].router.addr)[0]
                        rcli.put(lo, cycle + 1000)
                        time.sleep(0.05)
                        roots = set()
                        for m in g.members:
                            t = m.tier
                            if m.role == "down" or t is None \
                                    or t.killed:
                                continue
                            with t.lock:
                                roots.add(
                                    int(t.crdt.digest_tree().root))
                        if len(roots) != 1:
                            return False
                    return True

                _wait(_converged, timeout=30.0, interval=0.1,
                      what="replica convergence")
            finally:
                rcli.close()

        # The chaos was real: faults actually flowed through the
        # farm. (Read before farm.stop() clears the proxy registry.)
        counters = farm.counters()
        assert counters.get("connections", 0) > 0
        assert sum(counters.get(k, 0)
                   for k in ("drop", "delay", "duplicate")) > 0

"""Cross-backend network soak (VERDICT r3 item 8): every backend
family in one randomized gossip mesh, with a real TCP boundary in the
loop, driven to global convergence.

Extends the in-process merge soaks (tests/test_properties.py,
benchmarks/validate_on_chip.py) to the transport layer: replicas
gossip through `SyncServer`/`sync_over_tcp` frames — nothing but wire
JSON crosses — interleaved with random local writes, deletes, clears,
and direct record-map merges. At the end, one full all-pairs round
settles the mesh and every replica must hold the same records with
byte-identical wire exports (same insertion history ⇒ same bytes is
NOT required across replicas; record equality is the contract, and
export equality is checked key-sorted)."""

import json
import random
import time

import pytest

from conformance import FakeClock
from crdt_tpu import (DenseCrdt, KeyedDenseCrdt, MapCrdt, SqliteCrdt,
                      SyncServer, TpuMapCrdt, sync_over_tcp)

KEYS = [f"k{i}" for i in range(40)]


def _mk_replicas(clk):
    return [
        MapCrdt("oracle", wall_clock=clk),
        TpuMapCrdt("tpu", wall_clock=clk),
        SqliteCrdt("lite", wall_clock=clk, check_same_thread=False),
        KeyedDenseCrdt(DenseCrdt("dense", 64, wall_clock=clk)),
    ]


def _sorted_state(crdt):
    # key-sorted wire view: replicas with different insertion
    # histories legitimately order keys differently
    return dict(sorted(json.loads(crdt.to_json()).items()))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_four_backend_tcp_gossip_converges(seed):
    rng = random.Random(seed)
    clk = FakeClock(step=3)
    replicas = _mk_replicas(clk)
    servers = [SyncServer(c) for c in replicas]
    for s in servers:
        s.start()
    marks = {}
    try:
        for step in range(120):
            r = rng.randrange(len(replicas))
            c = replicas[r]
            op = rng.random()
            with servers[r].lock:
                if op < 0.40:
                    c.put(rng.choice(KEYS), rng.randrange(1000))
                elif op < 0.55:
                    c.delete(rng.choice(KEYS))
                elif op < 0.62:
                    c.put_all({rng.choice(KEYS): rng.randrange(1000)
                               for _ in range(rng.randrange(1, 6))})
                elif op < 0.66:
                    c.clear()
            if op >= 0.66 or step % 7 == 0:
                # gossip: one anti-entropy round against a random peer
                # over real TCP, with the self-served replica's lock
                o = rng.randrange(len(replicas))
                if o != r:
                    marks[(r, o)] = sync_over_tcp(
                        c, servers[o].host, servers[o].port,
                        since=marks.get((r, o)), lock=servers[r].lock)
        # settle: two deterministic all-pairs rounds (full pulls)
        for _ in range(2):
            for i, c in enumerate(replicas):
                for j, s in enumerate(servers):
                    if i != j:
                        sync_over_tcp(c, s.host, s.port,
                                      lock=servers[i].lock)
    finally:
        for s in servers:
            s.stop()

    states = [_sorted_state(c) for c in replicas]
    for i, st in enumerate(states[1:], 1):
        assert st == states[0], (
            f"replica {i} diverged at seed {seed}: "
            f"{set(st) ^ set(states[0])}")
    # live views agree too (tombstones hidden consistently)
    maps = [c.map for c in replicas]
    assert all(m == maps[0] for m in maps[1:])
    replicas[2].close()


@pytest.mark.parametrize("seed", [0, 1])
def test_dense_binary_gossip_mesh_converges(seed):
    """Binary split-lane sync in a randomized dense mesh: three dense
    replicas gossiping via `sync_dense_over_tcp` (raw lane frames)
    interleaved with local writes/deletes; a JSON `sync_over_tcp`
    round is mixed in so both wire forms interoperate mid-soak."""
    import numpy as np
    from crdt_tpu import sync_dense_over_tcp
    rng = random.Random(seed * 7 + 3)
    clk = FakeClock(step=3)
    n = 64
    replicas = [DenseCrdt(f"d{i}", n, wall_clock=clk) for i in range(3)]
    servers = [SyncServer(c) for c in replicas]
    for s in servers:
        s.start()
    try:
        for step in range(60):
            r = rng.randrange(len(replicas))
            c = replicas[r]
            op = rng.random()
            with servers[r].lock:
                if op < 0.45:
                    slots = sorted(rng.sample(range(n),
                                              rng.randrange(1, 6)))
                    c.put_batch(slots,
                                [rng.randrange(1000) for _ in slots])
                elif op < 0.6:
                    c.delete_batch([rng.randrange(n)])
            if op >= 0.6:
                peer = rng.randrange(len(replicas))
                if peer == r:
                    continue
                if op < 0.9:
                    sync_dense_over_tcp(c, servers[peer].host,
                                        servers[peer].port,
                                        lock=servers[r].lock)
                else:
                    # JSON round against the same mesh: both wire
                    # forms must interoperate mid-soak
                    sync_over_tcp(c, servers[peer].host,
                                  servers[peer].port, key_decoder=int,
                                  lock=servers[r].lock)
        # settle: all-pairs binary rounds
        for i, c in enumerate(replicas):
            for j, s in enumerate(servers):
                if i != j:
                    sync_dense_over_tcp(c, s.host, s.port,
                                        lock=servers[i].lock)
        for i, c in enumerate(replicas):
            for j, s in enumerate(servers):
                if i != j:
                    sync_dense_over_tcp(c, s.host, s.port,
                                        lock=servers[i].lock)
    finally:
        for s in servers:
            s.stop()
    base = replicas[0]
    occ = np.asarray(base.store.occupied)
    live = occ & ~np.asarray(base.store.tomb)
    for other in replicas[1:]:
        np.testing.assert_array_equal(occ,
                                      np.asarray(other.store.occupied))
        for lane, mask in (("lt", occ), ("tomb", occ), ("val", live)):
            # val is compared at LIVE slots only: the payload under a
            # tombstone is unobservable (every read masks it) and
            # legitimately differs by ingest path — JSON nulls it to
            # 0, dense changesets carry the store's stale payload.
            np.testing.assert_array_equal(
                np.asarray(getattr(base.store, lane))[mask],
                np.asarray(getattr(other.store, lane))[mask],
                err_msg=lane)


@pytest.mark.slow
@pytest.mark.soak
@pytest.mark.parametrize("seed", [0, 1])
def test_fault_matrix_gossip_soak(seed, tmp_path):
    """The robustness claim end-to-end: a 4-backend GossipNode mesh
    where EVERY link runs through a fault proxy on a seeded schedule
    (drops, delays, trickles, truncations, corruptions, duplicated
    frames), interleaved with random local writes and a mid-soak
    kill-and-restart of the durable node — which must resume with a
    DELTA pull from its persisted watermark, not a full re-pull. After
    a passthrough settle phase all replicas hold byte-identical
    key-sorted wire exports, and the retry/fault counters prove the
    faults actually fired."""
    from crdt_tpu import BreakerPolicy, GossipNode, RetryPolicy
    from crdt_tpu.testing import FaultProxy, FaultSchedule

    rng = random.Random(1000 + seed)
    clk = FakeClock(step=3)
    db = str(tmp_path / "lite.db")
    state = str(tmp_path / "lite.gossip.json")
    retry = RetryPolicy(max_attempts=5, base_delay=0.001,
                        max_delay=0.02)
    breaker = BreakerPolicy(failure_threshold=3, reset_timeout=0.05)

    def mk_node(crdt):
        return GossipNode(crdt, retry=retry, breaker=breaker,
                          rng=random.Random(seed))

    nodes = {
        "oracle": mk_node(MapCrdt("oracle", wall_clock=clk)),
        "tpu": mk_node(TpuMapCrdt("tpu", wall_clock=clk)),
        "lite": GossipNode(SqliteCrdt("lite", db, wall_clock=clk,
                                      check_same_thread=False),
                           retry=retry, breaker=breaker,
                           rng=random.Random(seed), state_path=state),
        "dense": mk_node(KeyedDenseCrdt(
            DenseCrdt("dense", 64, wall_clock=clk))),
    }
    names = sorted(nodes)
    proxies = {}
    try:
        for name, node in nodes.items():
            node.start()
            proxies[name] = FaultProxy(
                node.host, node.port,
                FaultSchedule(seed=seed * 31 + len(proxies),
                              rate=0.5, max_delay=0.01)).start()
        for name, node in nodes.items():
            for other in names:
                if other != name:
                    node.add_peer(other, proxies[other].host,
                                  proxies[other].port)

        def soak_steps(count):
            for _ in range(count):
                name = rng.choice(sorted(nodes))   # live nodes only
                node = nodes[name]
                op = rng.random()
                with node.lock:
                    if op < 0.45:
                        node.crdt.put(rng.choice(KEYS),
                                      rng.randrange(1000))
                    elif op < 0.60:
                        node.crdt.delete(rng.choice(KEYS))
                    elif op < 0.68:
                        node.crdt.put_all(
                            {rng.choice(KEYS): rng.randrange(1000)
                             for _ in range(rng.randrange(1, 5))})
                if op >= 0.68:
                    peer = rng.choice([n for n in names if n != name])
                    node.sync_peer(peer)

        def settled_round(node):
            # passthrough leaves only breaker cool-downs between us
            # and an all-ok sweep
            for _ in range(50):
                if all(v == "ok" for v in node.run_round().values()):
                    return
                time.sleep(0.05)
            raise AssertionError(
                f"mesh did not settle: {node.stats_snapshot()}")

        soak_steps(60)

        # make sure the durable node holds a watermark for every peer
        # before it "crashes"
        for _ in range(50):
            if all(v == "ok"
                   for v in nodes["lite"].run_round().values()):
                break
            time.sleep(0.02)
        assert all(p.watermark is not None
                   for p in nodes["lite"].peers.values())

        # kill the durable node; the world keeps writing and gossiping
        nodes["lite"].stop()
        nodes["lite"].crdt.close()
        lite_port = nodes["lite"].port
        del nodes["lite"]
        # trip a breaker against the dead peer, deterministically
        for _ in range(breaker.failure_threshold):
            nodes["oracle"].sync_peer("lite")
        assert nodes["oracle"].peers["lite"].stats.breaker_opened >= 1
        soak_steps(25)

        # restart: same replica file, same watermark file, same port
        # (the proxies keep targeting it)
        lite = GossipNode(SqliteCrdt("lite", db, wall_clock=clk,
                                     check_same_thread=False),
                          port=lite_port, retry=retry, breaker=breaker,
                          rng=random.Random(seed), state_path=state)
        nodes["lite"] = lite
        lite.start()
        for other in names:
            if other != "lite":
                lite.add_peer(other, proxies[other].host,
                              proxies[other].port)
        # the persisted watermarks survived the crash...
        assert all(p.watermark is not None
                   for p in lite.peers.values())
        for _ in range(50):
            if all(v == "ok" for v in lite.run_round().values()):
                break
            time.sleep(0.02)
        # ...and the resumed rounds were DELTA pulls, not full re-pulls
        for peer in lite.peers.values():
            assert peer.stats.full_pulls == 0
            assert peer.stats.delta_pulls >= 1
        soak_steps(30)

        # settle: faults off, every node completes an all-ok sweep,
        # twice (round 1 spreads everything anyone holds; round 2
        # spreads what round 1 taught the early sweepers)
        for proxy in proxies.values():
            proxy.passthrough = True
        for _ in range(2):
            for name in names:
                settled_round(nodes[name])

        fault_counts = {}
        for proxy in proxies.values():
            for kind, n in proxy.counters.items():
                if kind != "connections":
                    fault_counts[kind] = fault_counts.get(kind, 0) + n
        retries = sum(p.stats.retries for node in nodes.values()
                      for p in node.peers.values())
        assert sum(fault_counts.values()) > 0, "no faults fired"
        assert retries > 0, f"faults fired but nothing retried: " \
            f"{fault_counts}"
    finally:
        for proxy in proxies.values():
            proxy.stop()
        for node in nodes.values():
            node.stop()

    states = {name: _sorted_state(nodes[name].crdt) for name in names}
    base = states[names[0]]
    for name, st in states.items():
        assert st == base, (
            f"{name} diverged at seed {seed}: {set(st) ^ set(base)}")
    maps = [nodes[n].crdt.map for n in names]
    assert all(m == maps[0] for m in maps[1:])
    nodes["lite"].crdt.close()

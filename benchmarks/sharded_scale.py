"""Sharded fan-in at scale on the virtual 8-device mesh.

Correctness at scale (round 2) plus a WEAK-SCALING characterization
(round 4): 1/2/4/8 devices with FIXED per-device key shards, timing
the sharded fan-in and `put_batch` at each width, against the
single-device executor at the same total size. The round-3 verdict's
gap — "no 1/2/4/8 curve separating collective overhead from the
virtual-CPU artifact" — is this curve; write scatters now land
pre-sharded (`with_sharding_constraint` inside the jit), closing the
3.4× sharded `put_batch` overhead.

CAVEAT the artifact also records: these are 8 VIRTUAL CPU devices on
one host — absolute times mean nothing and "collectives" are memcpy;
the curve's SHAPE (does per-device work stay flat as devices grow?)
and the sharded/single write ratio are the meaningful outputs. Real
ICI scaling needs real chips.

Run:
    python benchmarks/sharded_scale.py [--keys 262144] [--rows 64]
(The script pins jax to the virtual CPU mesh itself — no env needed.)
"""

from __future__ import annotations

import argparse
import json
import time

import jax

# Must run before any backend init: this environment pins an 'axon' TPU
# plugin via sitecustomize, so the env var alone cannot switch to the
# virtual CPU mesh (tests/conftest.py does the same).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from crdt_tpu.hlc import SHIFT  # noqa: E402
from crdt_tpu.models.dense_crdt import DenseCrdt, ShardedDenseCrdt  # noqa: E402
from crdt_tpu.ops.dense import DenseChangeset  # noqa: E402
from crdt_tpu.parallel import make_fanin_mesh  # noqa: E402
from crdt_tpu.testing import FakeClock, assert_dense_stores_equal  # noqa: E402

BASE = 1_700_000_000_000


def random_changesets(rows: int, n: int, seed: int, n_groups: int):
    """``n_groups`` peer changesets of rows//n_groups replica rows each,
    all-distinct random records, as (DenseChangeset, node_ids) pairs."""
    rng = np.random.default_rng(seed)
    per = rows // n_groups
    out = []
    for g in range(n_groups):
        lt = ((BASE + rng.integers(0, 1000, (per, n))) << SHIFT) \
            + rng.integers(0, 4, (per, n))
        cs = DenseChangeset(
            lt=jnp.asarray(lt, jnp.int64),
            node=jnp.asarray(rng.integers(0, 4, (per, n)), jnp.int32),
            val=jnp.asarray(lt, jnp.int64),
            tomb=jnp.asarray(rng.random((per, n)) < 0.3),
            valid=jnp.asarray(rng.random((per, n)) < 0.8),
        )
        out.append((cs, [f"peer{g}-{i}" for i in range(4)]))
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=1 << 18)
    ap.add_argument("--rows", type=int, default=64)
    ap.add_argument("--out", default="MULTICHIP_SCALE_r04.json")
    args = ap.parse_args()
    n, rows = args.keys, args.rows

    result = {"ok": False, "n_devices": jax.device_count(),
              "n_keys": n, "replica_rows": rows,
              "mesh": "(replica=2, key=4)"}
    mesh = make_fanin_mesh(2, 4)
    changesets = random_changesets(rows, n, seed=7, n_groups=8)
    merges = int(sum(int(jnp.sum(cs.valid)) for cs, _ in changesets))

    # --- sharded fan-in: 64 replica rows into 256k+ sharded slots ---
    sharded = ShardedDenseCrdt("local", n, mesh,
                               wall_clock=FakeClock(start=BASE + 2000))
    t0 = time.perf_counter()
    sharded.merge_many(changesets)
    jax.block_until_ready(sharded.store.lt)
    warm_compile = time.perf_counter() - t0

    sharded2 = ShardedDenseCrdt("local", n, mesh,
                                wall_clock=FakeClock(start=BASE + 2000))
    t0 = time.perf_counter()
    sharded2.merge_many(changesets)
    jax.block_until_ready(sharded2.store.lt)
    sharded_s = time.perf_counter() - t0

    # --- single-device cross-check (lane-exact) ---
    single = DenseCrdt("local", n, executor="xla",
                       wall_clock=FakeClock(start=BASE + 2000))
    t0 = time.perf_counter()
    single.merge_many(changesets)
    jax.block_until_ready(single.store.lt)
    single_s = time.perf_counter() - t0

    assert_dense_stores_equal(single.store, sharded2.store,
                              "single vs sharded @ scale")
    assert single.canonical_time == sharded2.canonical_time
    result["lane_exact_vs_single_device"] = True
    result["merges"] = merges
    result["timings_s"] = {
        "sharded_fanin_first_call_incl_compile": round(warm_compile, 3),
        "sharded_fanin_warm": round(sharded_s, 3),
        "single_device_fanin_warm": round(single_s, 3),
    }
    result["sharded_merges_per_sec_warm"] = round(merges / sharded_s, 1)

    # --- put_batch cost on the sharded store (the round-2 concern:
    # a full-store re-shard per local write batch?) ---
    k = 1024
    slots = np.arange(0, k * 16, 16)
    vals = np.arange(k, dtype=np.int64)
    sharded2.put_batch(slots, vals)  # compile
    single.put_batch(slots, vals)
    jax.block_until_ready(sharded2.store.lt)
    jax.block_until_ready(single.store.lt)
    # Interleaved best-of reps: host-contention noise on the virtual
    # mesh hits both sides alike, so the RATIO stays meaningful.
    reps = 12
    put_sharded = put_single = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        sharded2.put_batch(slots, vals)
        jax.block_until_ready(sharded2.store.lt)
        put_sharded = min(put_sharded, time.perf_counter() - t0)
        t0 = time.perf_counter()
        single.put_batch(slots, vals)
        jax.block_until_ready(single.store.lt)
        put_single = min(put_single, time.perf_counter() - t0)

    # Dispatch floor: one trivial elementwise program over the same
    # store — what merely RUNNING an 8-partition program on this ONE
    # host costs, independent of any scatter work. The sharded write's
    # "overhead" over single-device is ~this floor (plus each
    # partition scanning the replicated index list serially on one
    # host); on real chips partitions dispatch in parallel and the
    # floor collapses. No re-shard exists: see
    # sharded_put_collective_free below.
    @jax.jit
    def _touch(store):
        return type(store)(*(
            (lane if lane.dtype == bool else lane + 0)
            for lane in store))

    floors = {}
    for label, cc in (("sharded", sharded2), ("single_device", single)):
        st = cc.store
        jax.block_until_ready(_touch(st))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(_touch(st))
            best = min(best, time.perf_counter() - t0)
        floors[label] = round(best * 1e3, 3)
    result["dispatch_floor_ms"] = floors

    shardings = {str(getattr(sharded2.store, f).sharding)
                 for f in sharded2.store._fields}
    result["put_batch_1024_slots_ms"] = {
        "sharded": round(put_sharded * 1e3, 2),
        "single_device": round(put_single * 1e3, 2),
    }
    result["store_sharding_consistent"] = len(shardings) == 1
    result["store_sharding"] = shardings.pop()

    # --- weak scaling: fixed per-device keys, 1/2/4/8 devices ---
    # replica axis fixed at 2 (1-device mesh has 1); key shards grow
    # with the device count, so per-device key work is constant.
    per_dev_keys = n // 4               # matches the 8-dev (2,4) mesh
    curve = []
    for n_dev, (r_sh, k_sh) in [(1, (1, 1)), (2, (2, 1)),
                                (4, (2, 2)), (8, (2, 4))]:
        keys_d = per_dev_keys * k_sh
        # slot array scaled to THIS width's capacity (a --keys below
        # 64k must not index past the 1-device store)
        stride = max(keys_d // k, 1)
        slots_d = np.arange(0, k * stride, stride)[:keys_d]
        vals_d = np.arange(len(slots_d), dtype=np.int64)
        mesh_d = make_fanin_mesh(r_sh, k_sh,
                                 devices=jax.devices()[:n_dev])
        batches = random_changesets(rows, keys_d, seed=11, n_groups=4)
        m_count = int(sum(int(jnp.sum(cs.valid)) for cs, _ in batches))
        c = ShardedDenseCrdt("local", keys_d, mesh_d,
                             wall_clock=FakeClock(start=BASE + 2000))
        c.merge_many(batches)                      # compile
        jax.block_until_ready(c.store.lt)
        # Best-of protocol throughout (same rationale as the
        # head-to-head put comparison: on this one-host virtual mesh
        # only minima are noise-robust, and the curve SHAPE is the
        # deliverable).
        fanin_s = float("inf")
        for _ in range(3):
            c2 = ShardedDenseCrdt(
                "local", keys_d, mesh_d,
                wall_clock=FakeClock(start=BASE + 2000))
            t0 = time.perf_counter()
            c2.merge_many(batches)
            jax.block_until_ready(c2.store.lt)
            fanin_s = min(fanin_s, time.perf_counter() - t0)

        c2.put_batch(slots_d, vals_d)              # compile
        jax.block_until_ready(c2.store.lt)
        put_s = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            c2.put_batch(slots_d, vals_d)
            jax.block_until_ready(c2.store.lt)
            put_s = min(put_s, time.perf_counter() - t0)
        curve.append({
            "devices": n_dev, "mesh": f"(replica={r_sh}, key={k_sh})",
            "n_keys": keys_d, "replica_rows": rows,
            "fanin_warm_s": round(fanin_s, 4),
            "fanin_merges_per_sec": round(m_count / fanin_s, 1),
            "fanin_merges_per_sec_per_device":
                round(m_count / fanin_s / n_dev, 1),
            "put_batch_1024_slots_ms": round(put_s * 1e3, 3),
        })
    result["weak_scaling_note"] = (
        "fixed per-device keys; virtual CPU devices — curve SHAPE and "
        "write ratios are meaningful, absolute times are not")
    result["weak_scaling"] = curve
    result["sharded_put_vs_single_ratio"] = round(
        put_sharded / put_single, 2)

    # --- structural check: the sharded write must compile with ZERO
    # collectives (each shard scatters its own rows; no re-shard, no
    # gather). Robust where virtual-CPU timings wobble. ---
    import re
    from collections import Counter

    from crdt_tpu.ops.dense import _put_scatter
    from crdt_tpu.parallel import store_sharding
    fn = _put_scatter(False, store_sharding(mesh))
    hlo = fn.lower(
        sharded2.store, jnp.asarray(slots, jnp.int32),
        jnp.asarray(vals), jnp.zeros(len(slots), bool),
        jnp.int64(1), jnp.int32(0)).compile().as_text()
    colls = Counter(re.findall(
        r"(all-gather|all-reduce|collective-permute|all-to-all)", hlo))
    result["sharded_put_collectives"] = dict(colls)
    result["sharded_put_collective_free"] = not colls
    result["ok"] = True

    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()

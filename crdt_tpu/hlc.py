"""Hybrid Logical Clock — the causality primitive (L0).

Scalar/host implementation of the HLC per Kulkarni et al.
(https://cse.buffalo.edu/tech-reports/2014-04.pdf), semantically matching
the reference `lib/src/hlc.dart:1-189`:

- ``Hlc`` immutable value type ``(millis, counter, node_id)`` with
  ``logical_time = (millis << 16) | counter`` (hlc.dart:16).
- ``Hlc.send`` / ``Hlc.recv`` clock-update algorithms (hlc.dart:51-97).
- Total order: logical_time, then node_id (hlc.dart:158-161).
- String codecs: ISO8601 human codec (hlc.dart:39-46,102-104), fixed-width
  sortable base36 ``pack``/``unpack`` (hlc.dart:110-127), secure
  ``random_node_id`` (hlc.dart:129-141).
- Three exception types (hlc.dart:164-189).

The TPU path never manipulates this object per-record: clocks are packed
into (int64 logical_time, int32 node ordinal) lanes — see
``crdt_tpu.ops.packing``. This module is the semantic oracle and the
host-side boundary (wall-clock reads and exception raising live here,
outside jit).
"""

from __future__ import annotations

import re
import secrets
import time
from datetime import datetime, timezone
from typing import (Any, Callable, Generic, List, Optional, Tuple,
                    TypeVar)

T = TypeVar("T")

SHIFT = 16
MAX_COUNTER = 0xFFFF
MAX_DRIFT = 60_000  # 1 minute in ms (hlc.dart:5)

# millis >= this threshold are auto-detected as microseconds (hlc.dart:23)
MICROS_THRESHOLD = 0x0001_0000_0000_0000

_BASE36_DIGITS = "0123456789abcdefghijklmnopqrstuvwxyz"


def wall_clock_millis() -> int:
    """Host wall clock in milliseconds (the DateTime.now() boundary)."""
    return time.time_ns() // 1_000_000


def to_base36(n: int) -> str:
    """Integer to lowercase base36, matching Dart's toRadixString(36)."""
    if n == 0:
        return "0"
    neg = n < 0
    n = abs(n)
    out = []
    while n:
        n, r = divmod(n, 36)
        out.append(_BASE36_DIGITS[r])
    return ("-" if neg else "") + "".join(reversed(out))


def _civil_from_days(z: int):
    """Epoch day -> (year, month, day), proleptic Gregorian (Howard
    Hinnant's civil_from_days — branchless integer math, no datetime)."""
    z += 719468
    era = (z if z >= 0 else z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + (3 if mp < 10 else -9)
    return y + (m <= 2), m, d


_CANONICAL_ISO = re.compile(
    r"\A(\d{4})-(\d{2})-(\d{2})T(\d{2}):(\d{2}):(\d{2})\.(\d{3})Z\Z",
    re.ASCII)

_DAYS_IN_MONTH = (31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31)


def _days_in_month(y: int, m: int) -> int:
    if m == 2 and (y % 4 == 0 and (y % 100 != 0 or y % 400 == 0)):
        return 29
    return _DAYS_IN_MONTH[m - 1]


def _days_from_civil(y: int, m: int, d: int) -> int:
    """(year, month, day) -> epoch day (inverse of `_civil_from_days`)."""
    y -= m <= 2
    era = (y if y >= 0 else y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _iso8601(millis: int) -> str:
    """UTC ISO-8601 with exactly 3 fractional digits and 'Z' suffix,
    matching Dart's DateTime.toIso8601String() for millisecond-precision
    UTC times (hlc.dart:102). Years outside 1-9999 raise (the datetime
    range every parser in the system accepts) — emitting them would
    poison the wire for all peers."""
    secs, ms = divmod(millis, 1000)
    days, sod = divmod(secs, 86400)
    y, mo, d = _civil_from_days(days)
    if not 1 <= y <= 9999:
        raise ValueError(f"year {y} out of range for the wire codec")
    h, rem = divmod(sod, 3600)
    mi, s = divmod(rem, 60)
    return (f"{y:04d}-{mo:02d}-{d:02d}T{h:02d}:{mi:02d}:{s:02d}"
            f".{ms:03d}Z")


_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)


def _parse_iso_millis(s: str) -> int:
    """Parse an ISO-8601 timestamp to epoch millis, accepting the formats
    Dart's DateTime.parse accepts in practice for this codec ('T' or space
    separator, optional fractional seconds, 'Z' or a UTC offset).

    The canonical 24-char wire shape `YYYY-MM-DDTHH:MM:SS.mmmZ` (exactly
    what `_iso8601` emits) takes a no-datetime fast path — it dominates
    every wire ingest. The fast path validates shape AND calendar
    ranges (ASCII digits only, real month/day, 24h clock); anything
    else falls through to the strict general parser."""
    m = _CANONICAL_ISO.match(s)
    if m is not None:
        y, mo, d, h, mi, sec, ms = map(int, m.groups())
        if (1 <= mo <= 12 and 1 <= d <= _days_in_month(y, mo)
                and h < 24 and mi < 60 and sec < 60):
            days = _days_from_civil(y, mo, d)
            return ((days * 86400 + h * 3600 + mi * 60 + sec) * 1000
                    + ms)
    iso = s.strip().replace(" ", "T")
    if iso.endswith(("Z", "z")):   # fromisoformat grew 'Z' in py3.11
        iso = iso[:-1] + "+00:00"
    dt = datetime.fromisoformat(iso)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    delta = dt - _EPOCH
    micros = (delta.days * 86_400_000_000 + delta.seconds * 1_000_000
              + delta.microseconds)
    return micros // 1000


class ClockDriftException(Exception):
    """Clock drift beyond MAX_DRIFT (hlc.dart:164-171)."""

    def __init__(self, millis_ts: int, millis_wall: int):
        self.drift = millis_ts - millis_wall
        super().__init__(
            f"Clock drift of {self.drift} ms exceeds maximum ({MAX_DRIFT})")


class OverflowException(Exception):
    """HLC counter overflow past 16 bits (hlc.dart:173-180)."""

    def __init__(self, counter: int):
        self.counter = counter
        super().__init__(f"Timestamp counter overflow: {counter}")


class DuplicateNodeException(Exception):
    """Two replicas share a node id (hlc.dart:182-189)."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        super().__init__(f"Duplicate node: {node_id}")


class Hlc(Generic[T]):
    """Immutable hybrid logical timestamp (hlc.dart:11-161).

    Total order is ``(logical_time, node_id)``; node ids must be mutually
    comparable (strings in the common case).
    """

    __slots__ = ("millis", "counter", "node_id")

    def __init__(self, millis: int, counter: int, node_id: T):
        assert counter <= MAX_COUNTER
        assert node_id is not None
        # Detect microseconds and convert to millis (hlc.dart:23)
        object.__setattr__(
            self, "millis",
            millis if millis < MICROS_THRESHOLD else millis // 1000)
        object.__setattr__(self, "counter", counter)
        object.__setattr__(self, "node_id", node_id)

    def __setattr__(self, name: str, value: Any) -> None:
        raise AttributeError("Hlc is immutable")

    def __copy__(self) -> "Hlc[T]":
        return self

    def __deepcopy__(self, memo: Any) -> "Hlc[T]":
        return self

    def __reduce__(self):
        return (Hlc, (self.millis, self.counter, self.node_id))

    # --- constructors (hlc.dart:25-46) ---

    @classmethod
    def zero(cls, node_id: T) -> "Hlc[T]":
        return cls(0, 0, node_id)

    @classmethod
    def from_date(cls, dt: datetime, node_id: T) -> "Hlc[T]":
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        delta = dt - _EPOCH
        micros = (delta.days * 86_400_000_000 + delta.seconds * 1_000_000
                  + delta.microseconds)
        return cls(micros // 1000, 0, node_id)

    @classmethod
    def now(cls, node_id: T, millis: Optional[int] = None) -> "Hlc[T]":
        return cls(wall_clock_millis() if millis is None else millis, 0,
                   node_id)

    @classmethod
    def from_logical_time(cls, logical_time: int, node_id: T) -> "Hlc[T]":
        return cls(logical_time >> SHIFT, logical_time & MAX_COUNTER, node_id)

    @classmethod
    def _raw(cls, millis: int, counter: int, node_id: T) -> "Hlc[T]":
        """Unchecked fast construction for batch decode loops (values
        already validated lane-side: counter fits 16 bits, millis is
        genuine millis). ~3x cheaper than ``__init__`` at the 1M-record
        export scales where Hlc construction dominates."""
        h = cls.__new__(cls)
        s = object.__setattr__
        s(h, "millis", millis)
        s(h, "counter", counter)
        s(h, "node_id", node_id)
        return h

    @classmethod
    def parse(cls, timestamp: str,
              id_decoder: Optional[Callable[[str], T]] = None) -> "Hlc[T]":
        """Parse '<iso8601>-<4-hex-counter>-<nodeId>' (hlc.dart:39-46).

        Mirrors the reference scan: first dash after the last ':' ends the
        ISO time; the next dash ends the counter; the rest is the node id
        (which may itself contain dashes).
        """
        counter_dash = timestamp.index("-", timestamp.rfind(":"))
        node_id_dash = timestamp.index("-", counter_dash + 1)
        millis = _parse_iso_millis(timestamp[:counter_dash])
        counter = int(timestamp[counter_dash + 1:node_id_dash], 16)
        node_id = timestamp[node_id_dash + 1:]
        return cls(millis, counter,
                   id_decoder(node_id) if id_decoder is not None else node_id)

    # --- derived views ---

    @property
    def logical_time(self) -> int:
        return (self.millis << SHIFT) + self.counter

    def copy_with(self, millis: Optional[int] = None,
                  counter: Optional[int] = None,
                  node_id: Optional[T] = None) -> "Hlc[T]":
        return Hlc(self.millis if millis is None else millis,
                   self.counter if counter is None else counter,
                   self.node_id if node_id is None else node_id)

    apply = copy_with

    # --- clock algorithms (hlc.dart:51-97) ---

    @classmethod
    def send(cls, canonical: "Hlc[T]",
             millis: Optional[int] = None) -> "Hlc[T]":
        """Monotonic local-event stamping (hlc.dart:51-74)."""
        if millis is None:
            millis = wall_clock_millis()

        millis_old = canonical.millis
        counter_old = canonical.counter

        millis_new = max(millis_old, millis)
        counter_new = counter_old + 1 if millis_old == millis_new else 0

        if millis_new - millis > MAX_DRIFT:
            raise ClockDriftException(millis_new, millis)
        if counter_new > MAX_COUNTER:
            raise OverflowException(counter_new)

        return cls(millis_new, counter_new, canonical.node_id)

    @classmethod
    def send_batch(cls, canonical: "Hlc[T]", count: int,
                   millis: Optional[int] = None
                   ) -> Tuple["Hlc[T]", List[int]]:
        """``count`` successive ``send`` stamps from ONE wall read —
        the write-combiner flush stamp (docs/INGEST.md).

        Equivalent to ``count`` sequential :meth:`send` calls under a
        frozen wall clock: every stamp shares
        ``max(canonical.millis, millis)`` and the counters run
        consecutively, so the stamps are strictly monotonic in batch
        order and each later stamp dominates every earlier one.
        Raises the same exceptions ``send`` would — drift before the
        first stamp, overflow when the counter run would pass 16 bits
        (nothing is stamped on either raise).

        Returns ``(new_canonical, logical_times)`` with
        ``new_canonical == from_logical_time(logical_times[-1], ...)``.
        """
        if count <= 0:
            raise ValueError(f"send_batch needs count >= 1; got {count}")
        if millis is None:
            millis = wall_clock_millis()
        millis_new = max(canonical.millis, millis)
        start = canonical.counter + 1 if canonical.millis == millis_new \
            else 0
        if millis_new - millis > MAX_DRIFT:
            raise ClockDriftException(millis_new, millis)
        if start + count - 1 > MAX_COUNTER:
            raise OverflowException(start + count - 1)
        base = millis_new << SHIFT
        lts = [base + c for c in range(start, start + count)]
        return (cls(millis_new, start + count - 1, canonical.node_id),
                lts)

    @classmethod
    def recv(cls, canonical: "Hlc[T]", remote: "Hlc",
             millis: Optional[int] = None) -> "Hlc[T]":
        """Remote-timestamp ingestion (hlc.dart:80-97).

        Fast path (canonical >= remote) returns canonical unchanged and
        SKIPS the duplicate-node check — reference parity (hlc.dart:85).
        """
        if millis is None:
            millis = wall_clock_millis()

        if canonical.logical_time >= remote.logical_time:
            return canonical

        if canonical.node_id == remote.node_id:
            raise DuplicateNodeException(str(canonical.node_id))
        if remote.millis - millis > MAX_DRIFT:
            raise ClockDriftException(remote.millis, millis)

        return cls.from_logical_time(remote.logical_time, canonical.node_id)

    # --- codecs (hlc.dart:99-141) ---

    def to_json(self) -> str:
        return str(self)

    def __str__(self) -> str:
        return (f"{_iso8601(self.millis)}"
                f"-{self.counter:04X}"
                f"-{self.node_id}")

    def __repr__(self) -> str:
        return f"Hlc({self})"

    def pack(self) -> str:
        """Fixed-width sortable codec: 10-char base36 millis + 4-char
        base36 counter + nodeId (hlc.dart:110-121)."""
        return (to_base36(self.millis).rjust(10, "0")[:10] +
                to_base36(self.counter).rjust(4, "0")[:4] +
                str(self.node_id))

    @staticmethod
    def unpack(packed: str) -> "Hlc[str]":
        return Hlc(int(packed[0:10], 36), int(packed[10:14], 36), packed[14:])

    @staticmethod
    def random_node_id() -> str:
        """10-char base36 secure random node id (hlc.dart:129-141)."""
        seed_a = to_base36(secrets.randbelow(4294967296))
        seed_b = to_base36(secrets.randbelow(4294967296))
        return (seed_a + seed_b).rjust(10, "0")[:10]

    # --- total order (hlc.dart:143-161) ---

    def compare_to(self, other: "Hlc") -> int:
        lt, ot = self.logical_time, other.logical_time
        if lt != ot:
            return -1 if lt < ot else 1
        a, b = self.node_id, other.node_id
        if a == b:
            return 0
        return -1 if a < b else 1

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Hlc) and self.compare_to(other) == 0

    def __lt__(self, other: "Hlc") -> bool:
        return isinstance(other, Hlc) and self.compare_to(other) < 0

    def __le__(self, other: "Hlc") -> bool:
        return self < other or self == other

    def __gt__(self, other: "Hlc") -> bool:
        return isinstance(other, Hlc) and self.compare_to(other) > 0

    def __ge__(self, other: "Hlc") -> bool:
        return self > other or self == other

    def __hash__(self) -> int:
        # Field tuple, not hash(str(self)): equality is (logical_time,
        # node_id) order, which the fields determine exactly, and the
        # ISO-8601 render is ~6x the cost of a tuple hash — it shows
        # up on any path that caches by stamp (trace emit, dedupe).
        return hash((self.millis, self.counter, self.node_id))

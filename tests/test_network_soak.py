"""Cross-backend network soak (VERDICT r3 item 8): every backend
family in one randomized gossip mesh, with a real TCP boundary in the
loop, driven to global convergence.

Extends the in-process merge soaks (tests/test_properties.py,
benchmarks/validate_on_chip.py) to the transport layer: replicas
gossip through `SyncServer`/`sync_over_tcp` frames — nothing but wire
JSON crosses — interleaved with random local writes, deletes, clears,
and direct record-map merges. At the end, one full all-pairs round
settles the mesh and every replica must hold the same records with
byte-identical wire exports (same insertion history ⇒ same bytes is
NOT required across replicas; record equality is the contract, and
export equality is checked key-sorted)."""

import json
import random

import pytest

from conformance import FakeClock
from crdt_tpu import (DenseCrdt, KeyedDenseCrdt, MapCrdt, SqliteCrdt,
                      SyncServer, TpuMapCrdt, sync_over_tcp)

KEYS = [f"k{i}" for i in range(40)]


def _mk_replicas(clk):
    return [
        MapCrdt("oracle", wall_clock=clk),
        TpuMapCrdt("tpu", wall_clock=clk),
        SqliteCrdt("lite", wall_clock=clk, check_same_thread=False),
        KeyedDenseCrdt(DenseCrdt("dense", 64, wall_clock=clk)),
    ]


def _sorted_state(crdt):
    # key-sorted wire view: replicas with different insertion
    # histories legitimately order keys differently
    return dict(sorted(json.loads(crdt.to_json()).items()))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_four_backend_tcp_gossip_converges(seed):
    rng = random.Random(seed)
    clk = FakeClock(step=3)
    replicas = _mk_replicas(clk)
    servers = [SyncServer(c) for c in replicas]
    for s in servers:
        s.start()
    marks = {}
    try:
        for step in range(120):
            r = rng.randrange(len(replicas))
            c = replicas[r]
            op = rng.random()
            with servers[r].lock:
                if op < 0.40:
                    c.put(rng.choice(KEYS), rng.randrange(1000))
                elif op < 0.55:
                    c.delete(rng.choice(KEYS))
                elif op < 0.62:
                    c.put_all({rng.choice(KEYS): rng.randrange(1000)
                               for _ in range(rng.randrange(1, 6))})
                elif op < 0.66:
                    c.clear()
            if op >= 0.66 or step % 7 == 0:
                # gossip: one anti-entropy round against a random peer
                # over real TCP, with the self-served replica's lock
                o = rng.randrange(len(replicas))
                if o != r:
                    marks[(r, o)] = sync_over_tcp(
                        c, servers[o].host, servers[o].port,
                        since=marks.get((r, o)), lock=servers[r].lock)
        # settle: two deterministic all-pairs rounds (full pulls)
        for _ in range(2):
            for i, c in enumerate(replicas):
                for j, s in enumerate(servers):
                    if i != j:
                        sync_over_tcp(c, s.host, s.port,
                                      lock=servers[i].lock)
    finally:
        for s in servers:
            s.stop()

    states = [_sorted_state(c) for c in replicas]
    for i, st in enumerate(states[1:], 1):
        assert st == states[0], (
            f"replica {i} diverged at seed {seed}: "
            f"{set(st) ^ set(states[0])}")
    # live views agree too (tombstones hidden consistently)
    maps = [c.map for c in replicas]
    assert all(m == maps[0] for m in maps[1:])
    replicas[2].close()


@pytest.mark.parametrize("seed", [0, 1])
def test_dense_binary_gossip_mesh_converges(seed):
    """Binary split-lane sync in a randomized dense mesh: three dense
    replicas gossiping via `sync_dense_over_tcp` (raw lane frames)
    interleaved with local writes/deletes; a JSON `sync_over_tcp`
    round is mixed in so both wire forms interoperate mid-soak."""
    import numpy as np
    from crdt_tpu import sync_dense_over_tcp
    rng = random.Random(seed * 7 + 3)
    clk = FakeClock(step=3)
    n = 64
    replicas = [DenseCrdt(f"d{i}", n, wall_clock=clk) for i in range(3)]
    servers = [SyncServer(c) for c in replicas]
    for s in servers:
        s.start()
    try:
        for step in range(60):
            r = rng.randrange(len(replicas))
            c = replicas[r]
            op = rng.random()
            with servers[r].lock:
                if op < 0.45:
                    slots = sorted(rng.sample(range(n),
                                              rng.randrange(1, 6)))
                    c.put_batch(slots,
                                [rng.randrange(1000) for _ in slots])
                elif op < 0.6:
                    c.delete_batch([rng.randrange(n)])
            if op >= 0.6:
                peer = rng.randrange(len(replicas))
                if peer == r:
                    continue
                if op < 0.9:
                    sync_dense_over_tcp(c, servers[peer].host,
                                        servers[peer].port,
                                        lock=servers[r].lock)
                else:
                    # JSON round against the same mesh: both wire
                    # forms must interoperate mid-soak
                    sync_over_tcp(c, servers[peer].host,
                                  servers[peer].port, key_decoder=int,
                                  lock=servers[r].lock)
        # settle: all-pairs binary rounds
        for i, c in enumerate(replicas):
            for j, s in enumerate(servers):
                if i != j:
                    sync_dense_over_tcp(c, s.host, s.port,
                                        lock=servers[i].lock)
        for i, c in enumerate(replicas):
            for j, s in enumerate(servers):
                if i != j:
                    sync_dense_over_tcp(c, s.host, s.port,
                                        lock=servers[i].lock)
    finally:
        for s in servers:
            s.stop()
    base = replicas[0]
    occ = np.asarray(base.store.occupied)
    live = occ & ~np.asarray(base.store.tomb)
    for other in replicas[1:]:
        np.testing.assert_array_equal(occ,
                                      np.asarray(other.store.occupied))
        for lane, mask in (("lt", occ), ("tomb", occ), ("val", live)):
            # val is compared at LIVE slots only: the payload under a
            # tombstone is unobservable (every read masks it) and
            # legitimately differs by ingest path — JSON nulls it to
            # 0, dense changesets carry the store's stale payload.
            np.testing.assert_array_equal(
                np.asarray(getattr(base.store, lane))[mask],
                np.asarray(getattr(other.store, lane))[mask],
                err_msg=lane)

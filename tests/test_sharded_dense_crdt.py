"""ShardedDenseCrdt on the virtual 8-device mesh: behaviorally
identical to the single-device DenseCrdt."""

import numpy as np
import pytest

import jax

from crdt_tpu import DuplicateNodeException
from crdt_tpu.models.dense_crdt import (DenseCrdt, ShardedDenseCrdt,
                                        sync_dense)
from crdt_tpu.parallel import make_fanin_mesh
from crdt_tpu.testing import FakeClock

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

N = 64
BASE = 1_700_000_000_000


def make_pair(mesh_shape=(2, 4)):
    mesh = make_fanin_mesh(*mesh_shape)
    sharded = ShardedDenseCrdt("ns", N, mesh,
                               wall_clock=FakeClock(start=BASE))
    plain = DenseCrdt("ns", N, wall_clock=FakeClock(start=BASE))
    return sharded, plain


def test_local_ops_match_plain():
    sharded, plain = make_pair()
    for c in (sharded, plain):
        c.put_batch([1, 5, 9], [10, 50, 90])
        c.delete_batch([5])
    assert sharded.get(1) == plain.get(1) == 10
    assert sharded.get(5) is plain.get(5) is None
    np.testing.assert_array_equal(np.asarray(sharded.store.val),
                                  np.asarray(plain.store.val))


@pytest.mark.parametrize("mesh_shape", [(2, 4), (1, 8), (4, 2)])
def test_sync_with_plain_replica(mesh_shape):
    mesh = make_fanin_mesh(*mesh_shape)
    a = ShardedDenseCrdt("na", N, mesh, wall_clock=FakeClock(start=BASE))
    b = DenseCrdt("nb", N, wall_clock=FakeClock(start=BASE + 7))
    a.put_batch([0, 1], [10, 11])
    b.put_batch([2], [22])
    sync_dense(a, b)
    for c in (a, b):
        assert c.get(0) == 10 and c.get(1) == 11 and c.get(2) == 22
    assert_occupied_lanes_equal(a, b)


def assert_occupied_lanes_equal(a, b):
    """Observable state only: unoccupied slots may hold divergent
    garbage (node-ordinal remaps rewrite them differently depending on
    each replica's interning history) and are filtered from every view
    (record_map semantics)."""
    occ = np.asarray(a.store.occupied)
    np.testing.assert_array_equal(occ, np.asarray(b.store.occupied))
    # node ordinals compare via the ids they name, not raw ints
    ids_a = [a._table.id_of(int(o)) for o in np.asarray(a.store.node)[occ]]
    ids_b = [b._table.id_of(int(o)) for o in np.asarray(b.store.node)[occ]]
    assert ids_a == ids_b
    for lane in ("lt", "val", "tomb"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.store, lane))[occ],
            np.asarray(getattr(b.store, lane))[occ], err_msg=lane)


def test_merge_many_fanin():
    mesh = make_fanin_mesh(2, 4)
    hub = ShardedDenseCrdt("hub", N, mesh, wall_clock=FakeClock(start=BASE))
    spokes = [DenseCrdt(f"n{i}", N,
                        wall_clock=FakeClock(start=BASE + 1 + i))
              for i in range(5)]
    for i, s in enumerate(spokes):
        s.put_batch([i, 10 + i], [100 + i, 200 + i])
    hub.merge_many([s.export_delta() for s in spokes])
    for i in range(5):
        assert hub.get(i) == 100 + i
        assert hub.get(10 + i) == 200 + i
    assert hub.stats.records_adopted == 10


def test_conflict_resolution_matches_plain():
    mesh = make_fanin_mesh(2, 4)
    writers = [DenseCrdt(f"w{i}", N, wall_clock=FakeClock(start=BASE + i))
               for i in range(4)]
    for i, w in enumerate(writers):
        w.put_batch([0, 1, 2], [i * 10, i * 10 + 1, i * 10 + 2])
    deltas = [w.export_delta() for w in writers]

    sharded = ShardedDenseCrdt("hub", N, mesh,
                               wall_clock=FakeClock(start=BASE + 99))
    plain = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 99))
    sharded.merge_many(list(deltas))
    plain.merge_many(list(deltas))
    assert_occupied_lanes_equal(sharded, plain)
    assert (sharded.canonical_time.logical_time
            == plain.canonical_time.logical_time)


def test_duplicate_node_guard():
    mesh = make_fanin_mesh(2, 4)
    a = ShardedDenseCrdt("na", N, mesh, wall_clock=FakeClock(start=BASE))
    other = DenseCrdt("na", N, wall_clock=FakeClock(start=BASE + 50))
    other.put_batch([0], [1])
    with pytest.raises(DuplicateNodeException):
        a.merge(*other.export_delta())


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (4, 2), (8, 1)])
@pytest.mark.parametrize("seed", range(3))
def test_fuzz_sharded_vs_plain(mesh_shape, seed):
    """Randomized differential: ShardedDenseCrdt must match DenseCrdt
    exactly across every mesh factorization, under adversarial node-id
    orderings (later peers interning ids that re-sort the NodeTable —
    the round-1 stale-ordinal regression, under sharding) and multiple
    merge rounds with colliding wall clocks."""
    import random
    rng = random.Random(seed * 31 + hash(mesh_shape) % 1000)
    mesh = make_fanin_mesh(*mesh_shape)
    sharded = ShardedDenseCrdt("mm", N, mesh,
                               wall_clock=FakeClock(start=BASE + 500))
    plain = DenseCrdt("mm", N, wall_clock=FakeClock(start=BASE + 500))

    pool = ["aa", "az", "ba", "ca", "na", "pa", "za", "zz"]
    rng.shuffle(pool)   # adversarial intern order incl. before-hub ids
    writers = []
    for nid in pool[:5]:
        w = DenseCrdt(nid, N,
                      wall_clock=FakeClock(start=BASE + rng.randrange(40)))
        for _ in range(rng.randrange(1, 3)):
            slots = sorted(rng.sample(range(N), rng.randrange(1, 10)))
            if rng.random() < 0.3:
                w.delete_batch(slots)
            else:
                w.put_batch(slots, [rng.randrange(100) for _ in slots])
        writers.append(w)

    half = rng.randrange(1, len(writers))
    for group in (writers[:half], writers[half:]):
        deltas = [w.export_delta() for w in group]
        sharded.merge_many(list(deltas))
        plain.merge_many(list(deltas))

    assert (sharded.canonical_time.logical_time
            == plain.canonical_time.logical_time)
    assert sharded.stats.records_adopted == plain.stats.records_adopted
    assert_occupied_lanes_equal(sharded, plain)


@pytest.mark.parametrize("mesh_shape", [(2, 2, 2), (2, 1, 4)])
def test_multislice_model_merge(mesh_shape):
    # Regression (round-2 review): merge on a multislice mesh must pad
    # the replica dim to the PRODUCT of the replica axes, not just the
    # inner "replica" axis — a 1-peer merge exercises the worst case.
    from crdt_tpu.parallel import make_multislice_fanin_mesh
    mesh = make_multislice_fanin_mesh(*mesh_shape)
    sharded = ShardedDenseCrdt("ns", N, mesh,
                               wall_clock=FakeClock(start=BASE))
    plain = DenseCrdt("ns", N, wall_clock=FakeClock(start=BASE))
    peer = DenseCrdt("peer", N, wall_clock=FakeClock(start=BASE + 3))
    peer.put_batch([0, 3, 9], [5, 6, 7])
    peer.delete_batch([3])
    delta = peer.export_delta()
    sharded.merge_many([delta])
    plain.merge_many([delta])
    assert_occupied_lanes_equal(sharded, plain)
    assert sharded.canonical_time == plain.canonical_time
    b = DenseCrdt("nb", N, wall_clock=FakeClock(start=BASE + 9))
    b.put_batch([4], [44])
    sync_dense(sharded, b)
    assert sharded.get(4) == 44 and b.get(0) == 5


def test_clear_and_purge_stay_sharded():
    mesh = make_fanin_mesh(2, 4)
    c = ShardedDenseCrdt("nc", N, mesh, wall_clock=FakeClock(start=BASE))
    c.put_batch([0, 5], [1, 2])
    c.clear()
    assert len(c) == 0 and c.is_deleted(0) and c.is_deleted(5)
    c.purge()
    assert not c.contains_slot(0)
    # the store must still carry the key-sharded layout after purge
    w = DenseCrdt("w", N, wall_clock=FakeClock(start=BASE + 9))
    w.put_batch([3], [30])
    c.merge(*w.export_delta())       # sharded step requires sharded store
    assert c.get(3) == 30


def test_watch_on_sharded_merge():
    # The win mask comes back key-sharded from the collectives; events
    # must still surface per slot, identically to the plain model.
    mesh = make_fanin_mesh(2, 4)
    hub = ShardedDenseCrdt("hub", N, mesh, wall_clock=FakeClock(start=BASE))
    w = DenseCrdt("w", N, wall_clock=FakeClock(start=BASE + 3))
    w.put_batch([1, 9, 33], [11, 99, 333])
    w.delete_batch([9])
    s = hub.watch().record()
    hub.merge(*w.export_delta())
    assert s.events == [(1, 11), (9, None), (33, 333)]


class TestGuardParity:
    """Tripped guards on the sharded path must raise the same exception
    payload as the single-device path and roll the canonical clock to
    the same pre-failure value; per-device false positives (records the
    sequential r-major order shields) must not reject the merge."""

    def _pair(self, node="hub", start=BASE + 99):
        mesh = make_fanin_mesh(2, 4)
        return (ShardedDenseCrdt(node, N, mesh,
                                 wall_clock=FakeClock(start=start)),
                DenseCrdt(node, N, wall_clock=FakeClock(start=start)))

    def test_duplicate_payload_matches_plain(self):
        sharded, plain = self._pair("na")
        other = DenseCrdt("na", N, wall_clock=FakeClock(start=BASE + 50))
        other.put_batch([3], [1])
        delta = other.export_delta()
        errs = []
        for hub in (sharded, plain):
            with pytest.raises(DuplicateNodeException) as ei:
                hub.merge(*delta)
            errs.append(ei.value)
        assert str(errs[0]) == str(errs[1])
        assert errs[0].args == errs[1].args
        assert (sharded.canonical_time.logical_time
                == plain.canonical_time.logical_time)

    def test_drift_payload_matches_plain(self):
        from crdt_tpu import ClockDriftException
        sharded, plain = self._pair()
        far = DenseCrdt("far", N, wall_clock=FakeClock(start=BASE + 200_000))
        far.put_batch([2], [9])
        delta = far.export_delta()
        errs = []
        for hub in (sharded, plain):
            with pytest.raises(ClockDriftException) as ei:
                hub.merge(*delta)
            errs.append(ei.value)
        assert str(errs[0]) == str(errs[1])
        assert errs[0].args == errs[1].args
        assert (sharded.canonical_time.logical_time
                == plain.canonical_time.logical_time)

    def test_per_device_false_positive_cleared(self):
        # Row 0 carries a large-lt shield; row 1 carries a record under
        # the hub's own node id at a smaller lt. In r-major order the
        # shield precedes it (fast path, no dup — hlc.dart:85); on a
        # 2-way replica-sharded mesh the rows land on different devices
        # and the per-device guard flags it. The merge must still go
        # through, identically to the single-device executor.
        import jax.numpy as jnp
        from crdt_tpu.ops.dense import DenseChangeset
        sharded, plain = self._pair("m")
        lanes = {f: np.zeros((2, N), d) for f, d in
                 (("lt", np.int64), ("node", np.int32), ("val", np.int64),
                  ("tomb", bool), ("valid", bool))}
        lanes["lt"][0, 0] = (BASE + 50) << 16   # shield (node 'zz')
        lanes["node"][0, 0] = 0
        lanes["val"][0, 0] = 1
        lanes["valid"][0, 0] = True
        lanes["lt"][1, 0] = (BASE + 10) << 16   # hub's own id, shielded
        lanes["node"][1, 0] = 1
        lanes["val"][1, 0] = 2
        lanes["valid"][1, 0] = True
        for hub in (sharded, plain):
            cs = DenseChangeset(**{f: jnp.asarray(v)
                                   for f, v in lanes.items()})
            hub.merge(cs, ["zz", "m"])
            assert hub.get(0) == 1     # shield wins the slot
        assert (sharded.canonical_time.logical_time
                == plain.canonical_time.logical_time)
        assert_occupied_lanes_equal(sharded, plain)


class TestShardedPallas:
    """ShardedDenseCrdt(executor="pallas-interpret") — the Mosaic
    kernel running PER SHARD inside the shard_map collective step
    (parallel.fanin._pallas_fanin_block). Must be lane-exact against
    both the XLA sharded step and the single-device model."""

    BASE = BASE + 500

    def _n(self, k_shards):
        from crdt_tpu.ops.pallas_merge import TILE
        return TILE * k_shards

    def _writers(self, n, seed):
        import random
        rng = random.Random(seed)
        pool = ["aa", "az", "ba", "ca", "na", "pa", "za", "zz"]
        rng.shuffle(pool)
        writers = []
        for nid in pool[:5]:
            w = DenseCrdt(nid, n,
                          wall_clock=FakeClock(start=BASE + rng.randrange(40)))
            for _ in range(rng.randrange(1, 3)):
                slots = sorted(rng.sample(range(n), rng.randrange(1, 40)))
                if rng.random() < 0.3:
                    w.delete_batch(slots)
                else:
                    w.put_batch(slots, [rng.randrange(100) for _ in slots])
            writers.append(w)
        return writers, rng

    @pytest.mark.parametrize("mesh_shape", [(2, 4), (4, 2), (8, 1)])
    @pytest.mark.parametrize("seed", range(2))
    def test_fuzz_pallas_vs_plain(self, mesh_shape, seed):
        mesh = make_fanin_mesh(*mesh_shape)
        n = self._n(mesh_shape[1])
        sharded = ShardedDenseCrdt("mm", n, mesh,
                                   wall_clock=FakeClock(start=self.BASE),
                                   executor="pallas-interpret")
        plain = DenseCrdt("mm", n, wall_clock=FakeClock(start=self.BASE))
        writers, rng = self._writers(n, seed * 31 + hash(mesh_shape) % 997)
        half = rng.randrange(1, len(writers))
        for group in (writers[:half], writers[half:]):
            deltas = [w.export_delta() for w in group]
            sharded.merge_many(list(deltas))
            plain.merge_many(list(deltas))
        assert (sharded.canonical_time.logical_time
                == plain.canonical_time.logical_time)
        assert sharded.stats.records_adopted == plain.stats.records_adopted
        assert_occupied_lanes_equal(sharded, plain)
        # modified lanes too: the pallas block re-stamps winners with
        # the GLOBAL canonical outside the kernel — must match exactly
        occ = np.asarray(sharded.store.occupied)
        np.testing.assert_array_equal(
            np.asarray(sharded.store.mod_lt)[occ],
            np.asarray(plain.store.mod_lt)[occ])

    def test_matches_xla_sharded_executor(self):
        mesh = make_fanin_mesh(2, 4)
        n = self._n(4)
        ours = ShardedDenseCrdt("hub", n, mesh,
                                wall_clock=FakeClock(start=self.BASE),
                                executor="pallas-interpret")
        xla = ShardedDenseCrdt("hub", n, mesh,
                               wall_clock=FakeClock(start=self.BASE),
                               executor="xla")
        writers, _ = self._writers(n, 17)
        deltas = [w.export_delta() for w in writers]
        ours.merge_many(list(deltas))
        xla.merge_many(list(deltas))
        assert_occupied_lanes_equal(ours, xla)
        assert ours.canonical_time == xla.canonical_time

    def test_multislice_pallas(self):
        from crdt_tpu.parallel import make_multislice_fanin_mesh
        mesh = make_multislice_fanin_mesh(2, 2, 2)
        n = self._n(2)
        sharded = ShardedDenseCrdt("ns", n, mesh,
                                   wall_clock=FakeClock(start=BASE),
                                   executor="pallas-interpret")
        plain = DenseCrdt("ns", n, wall_clock=FakeClock(start=BASE))
        peer = DenseCrdt("peer", n, wall_clock=FakeClock(start=BASE + 3))
        peer.put_batch([0, 3, n - 1], [5, 6, 7])
        peer.delete_batch([3])
        delta = peer.export_delta()
        sharded.merge_many([delta])
        plain.merge_many([delta])
        assert_occupied_lanes_equal(sharded, plain)
        assert sharded.canonical_time == plain.canonical_time

    def test_guard_payload_parity(self):
        # The pallas block's flags are the closed-form optimistic
        # superset; a real trip must still raise with the sequential
        # first-offender payload (exact host recompute).
        mesh = make_fanin_mesh(2, 4)
        n = self._n(4)
        sharded = ShardedDenseCrdt("na", n, mesh,
                                   wall_clock=FakeClock(start=BASE),
                                   executor="pallas-interpret")
        plain = DenseCrdt("na", n, wall_clock=FakeClock(start=BASE))
        other = DenseCrdt("na", n, wall_clock=FakeClock(start=BASE + 50))
        other.put_batch([3], [1])
        delta = other.export_delta()
        errs = []
        for hub in (sharded, plain):
            with pytest.raises(DuplicateNodeException) as ei:
                hub.merge(*delta)
            errs.append(ei.value)
        assert errs[0].args == errs[1].args
        assert (sharded.canonical_time.logical_time
                == plain.canonical_time.logical_time)

    def test_false_positive_cleared(self):
        # A local-node record shielded by an earlier larger-lt record
        # flags in the closed-form bound (it ignores shielding) but
        # must be cleared by the exact recompute — merge proceeds.
        import jax.numpy as jnp
        from crdt_tpu.ops.dense import DenseChangeset
        mesh = make_fanin_mesh(2, 4)
        n = self._n(4)
        sharded = ShardedDenseCrdt("m", n, mesh,
                                   wall_clock=FakeClock(start=BASE + 99),
                                   executor="pallas-interpret")
        plain = DenseCrdt("m", n, wall_clock=FakeClock(start=BASE + 99))
        lanes = {f: np.zeros((2, n), d) for f, d in
                 (("lt", np.int64), ("node", np.int32), ("val", np.int64),
                  ("tomb", bool), ("valid", bool))}
        lanes["lt"][0, 0] = (BASE + 50) << 16
        lanes["node"][0, 0] = 0
        lanes["val"][0, 0] = 1
        lanes["valid"][0, 0] = True
        lanes["lt"][1, 0] = (BASE + 10) << 16
        lanes["node"][1, 0] = 1
        lanes["val"][1, 0] = 2
        lanes["valid"][1, 0] = True
        for hub in (sharded, plain):
            cs = DenseChangeset(**{f: jnp.asarray(v)
                                   for f, v in lanes.items()})
            hub.merge(cs, ["zz", "m"])
            assert hub.get(0) == 1
        assert_occupied_lanes_equal(sharded, plain)

    def test_watch_events_on_pallas_sharded_merge(self):
        mesh = make_fanin_mesh(2, 4)
        n = self._n(4)
        hub = ShardedDenseCrdt("hub", n, mesh,
                               wall_clock=FakeClock(start=BASE),
                               executor="pallas-interpret")
        w = DenseCrdt("w", n, wall_clock=FakeClock(start=BASE + 3))
        w.put_batch([1, 9, n - 2], [11, 99, 333])
        w.delete_batch([9])
        s = hub.watch().record()
        hub.merge(*w.export_delta())
        assert s.events == [(1, 11), (9, None), (n - 2, 333)]

    def test_misaligned_forced_pallas_rejected(self):
        mesh = make_fanin_mesh(2, 4)
        with pytest.raises(ValueError, match="key shards"):
            ShardedDenseCrdt("x", self._n(4) + 4, mesh,
                             executor="pallas-interpret")

    def test_auto_stays_xla_off_tpu(self):
        # "auto" on the CPU virtual mesh must keep the XLA fold even
        # at aligned capacity (Mosaic lowers on TPU only; interpret is
        # opt-in via executor=); forced modes route to the kernel.
        mesh = make_fanin_mesh(2, 4)
        n = self._n(4)
        auto = ShardedDenseCrdt("a", n, mesh,
                                wall_clock=FakeClock(start=BASE))
        assert not auto._use_pallas_sharded()
        forced = ShardedDenseCrdt("a", n, mesh,
                                  wall_clock=FakeClock(start=BASE),
                                  executor="pallas-interpret")
        assert forced._use_pallas_sharded()

    def test_value_width_32_masks_overflow(self):
        # value_width=32 on the sharded-pallas route: merge_many's
        # generic branch masks out-of-range records BEFORE dispatch,
        # so the kernel never adopts them and the model raises.
        mesh = make_fanin_mesh(2, 4)
        n = self._n(4)
        hub = ShardedDenseCrdt("hub", n, mesh,
                               wall_clock=FakeClock(start=BASE),
                               executor="pallas-interpret",
                               value_width=32)
        w = DenseCrdt("w", n, wall_clock=FakeClock(start=BASE + 3))
        w.put_batch([0, 1], [5, 2 ** 40])
        with pytest.raises(ValueError, match="int32"):
            hub.merge(*w.export_delta())
        assert hub.get(0) is None and hub.get(1) is None

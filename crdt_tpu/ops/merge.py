"""The batched lattice-join merge — the framework's hot op.

This collapses the reference's sequential three-pass merge loop
(crdt.dart:77-94; call stack SURVEY.md §3.3) into data-parallel stages
with no sequential carry:

1. **Clock absorption** (crdt.dart:82): the per-record ``Hlc.recv`` fold
   reduces to ``new_canonical = max(canonical, max(remote_lt))``. The
   recv guard checks (duplicate node, drift — hlc.dart:85-94) are
   computed as vectorized masks against the *running* canonical value
   (an exclusive cumulative max), because recv's fast path skips the
   checks whenever the canonical clock is already ahead; exceptions are
   raised on the host from the reduced masks (SURVEY.md §7 hard part 5).
2. **LWW filter** (crdt.dart:83-84): gather local lanes at the remote
   slots, win iff local absent or ``(l_lt, l_node) < (r_lt, r_node)`` —
   strict compare keeps local on exact tie.
3. **Winner re-stamp + scatter** (crdt.dart:86-90): winners keep the
   remote event hlc, ``modified`` lanes get the final canonical time;
   losers' scatter indices are redirected out of bounds and dropped.

All shapes are static (changesets are padded with ``valid=False``
entries) so the whole step is one fused XLA program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..hlc import MAX_DRIFT, SHIFT

_NEG = -(2 ** 62)


class Store(NamedTuple):
    """Columnar record store (structure-of-arrays in HBM).

    One slot per key; key <-> slot assignment lives host-side (or is the
    identity for dense integer key spaces). Values live in a payload
    table indexed by slot — they never enter the reduction, only winning
    indices do (SURVEY.md §7 hard part 4).
    """
    lt: jax.Array        # int64[C] record hlc logicalTime
    node: jax.Array      # int32[C] record hlc node ordinal
    mod_lt: jax.Array    # int64[C] modified logicalTime (local-only lane)
    mod_node: jax.Array  # int32[C] modified node ordinal
    occupied: jax.Array  # bool[C]
    tomb: jax.Array      # bool[C]  value is None (record.dart:17)

    @property
    def capacity(self) -> int:
        return self.lt.shape[0]


class Changeset(NamedTuple):
    """A padded batch of remote records addressed to store slots."""
    slot: jax.Array  # int32[M] target slot; ignored when ~valid
    lt: jax.Array    # int64[M]
    node: jax.Array  # int32[M]
    tomb: jax.Array  # bool[M]
    valid: jax.Array  # bool[M]


class MergeResult(NamedTuple):
    win: jax.Array            # bool[M] remote record was adopted
    new_canonical: jax.Array  # int64 scalar (pre final-send-bump)
    any_bad: jax.Array        # bool — some recv guard tripped
    first_bad: jax.Array      # int32 index of first offending record
    first_is_dup: jax.Array   # bool — duplicate-node (vs drift) at first_bad
    canonical_at_fail: jax.Array  # int64 canonical BEFORE failing record


def empty_store(capacity: int) -> Store:
    return Store(
        lt=jnp.zeros((capacity,), jnp.int64),
        node=jnp.zeros((capacity,), jnp.int32),
        mod_lt=jnp.zeros((capacity,), jnp.int64),
        mod_node=jnp.zeros((capacity,), jnp.int32),
        occupied=jnp.zeros((capacity,), bool),
        tomb=jnp.zeros((capacity,), bool),
    )


def grow_store(store: Store, capacity: int) -> Store:
    pad = capacity - store.capacity
    assert pad >= 0
    if pad == 0:
        return store
    return Store(*(jnp.concatenate([lane, jnp.zeros((pad,), lane.dtype)])
                   for lane in store))


def recv_guards(lt: jax.Array, node: jax.Array, valid: jax.Array,
                canonical_lt: jax.Array, local_node: jax.Array,
                wall_millis: jax.Array
                ) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Vectorized ``Hlc.recv`` guard masks over a record batch (any shape;
    visited flattened in row-major order).

    A record reaches the slow path iff its lt exceeds the *running*
    canonical clock — the exclusive cumulative max over earlier records,
    because recv's fast path skips all checks whenever the canonical
    clock is already ahead (hlc.dart:85). There it trips duplicate-node
    if it carries the local ordinal (hlc.dart:88-90), else drift if >60s
    ahead of the wall (hlc.dart:92-94). Returns ``(any_bad, first_bad,
    first_is_dup, canonical_at_fail)`` with flat row-major indices."""
    flat_lt = jnp.where(valid, lt, _NEG).reshape(-1)
    incl = jax.lax.cummax(flat_lt)
    excl = jnp.concatenate([jnp.full((1,), _NEG, jnp.int64), incl[:-1]])
    running = jnp.maximum(canonical_lt, excl)

    slow = valid.reshape(-1) & (flat_lt > running)
    dup = slow & (node.reshape(-1) == local_node)
    drift = slow & ~dup & ((flat_lt >> SHIFT) - wall_millis > MAX_DRIFT)
    bad = dup | drift
    first_bad = jnp.argmax(bad).astype(jnp.int32)
    return jnp.any(bad), first_bad, dup[first_bad], running[first_bad]


@jax.jit
def merge_step(store: Store, cs: Changeset, canonical_lt: jax.Array,
               local_node: jax.Array, wall_millis: jax.Array
               ) -> tuple[Store, MergeResult]:
    """One fused lattice-join step. See module docstring for the staging."""
    masked_lt = jnp.where(cs.valid, cs.lt, _NEG)

    # --- stage 1: clock absorption + recv guard masks ---
    any_bad, first_bad, first_is_dup, canonical_at_fail = recv_guards(
        cs.lt, cs.node, cs.valid, canonical_lt, local_node, wall_millis)

    new_canonical = jnp.maximum(canonical_lt, jnp.max(masked_lt))

    # --- stage 2: vectorized LWW compare (strict: local wins ties) ---
    l_lt = store.lt.at[cs.slot].get(mode="fill", fill_value=0)
    l_node = store.node.at[cs.slot].get(mode="fill", fill_value=0)
    l_occ = store.occupied.at[cs.slot].get(mode="fill", fill_value=False)

    remote_newer = (cs.lt > l_lt) | ((cs.lt == l_lt) & (cs.node > l_node))
    win = cs.valid & (~l_occ | remote_newer)

    # --- stage 3: re-stamp winners, scatter (losers dropped OOB) ---
    target = jnp.where(win, cs.slot, store.capacity).astype(jnp.int32)
    m = cs.slot.shape[0]
    new_store = Store(
        lt=store.lt.at[target].set(cs.lt, mode="drop"),
        node=store.node.at[target].set(cs.node, mode="drop"),
        mod_lt=store.mod_lt.at[target].set(
            jnp.full((m,), 0, jnp.int64) + new_canonical, mode="drop"),
        mod_node=store.mod_node.at[target].set(
            jnp.full((m,), 0, jnp.int32) + local_node, mode="drop"),
        occupied=store.occupied.at[target].set(True, mode="drop"),
        tomb=store.tomb.at[target].set(cs.tomb, mode="drop"),
    )

    return new_store, MergeResult(
        win=win,
        new_canonical=new_canonical,
        any_bad=any_bad,
        first_bad=first_bad,
        first_is_dup=first_is_dup,
        canonical_at_fail=canonical_at_fail,
    )


@jax.jit
def scatter_put(store: Store, cs: Changeset, mod_lt: jax.Array,
                mod_node: jax.Array) -> Store:
    """Raw storage-slot write (putRecords semantics, crdt.dart:150-155):
    store records without clock logic, with explicit modified lanes."""
    target = jnp.where(cs.valid, cs.slot, store.capacity).astype(jnp.int32)
    return Store(
        lt=store.lt.at[target].set(cs.lt, mode="drop"),
        node=store.node.at[target].set(cs.node, mode="drop"),
        mod_lt=store.mod_lt.at[target].set(mod_lt, mode="drop"),
        mod_node=store.mod_node.at[target].set(mod_node, mode="drop"),
        occupied=store.occupied.at[target].set(True, mode="drop"),
        tomb=store.tomb.at[target].set(cs.tomb, mode="drop"),
    )


@jax.jit
def max_logical_time(store: Store) -> jax.Array:
    """refreshCanonicalTime's reduction (crdt.dart:114-121): max stored
    record logicalTime, 0 for an empty store — one jnp.max over the lane."""
    return jnp.max(jnp.where(store.occupied, store.lt, 0))


@jax.jit
def delta_mask(store: Store, since_lt: jax.Array) -> jax.Array:
    """modifiedSince filter: INCLUSIVE bound on the modified lane
    (map_crdt.dart:44-45)."""
    return store.occupied & (store.mod_lt >= since_lt)


@jax.jit
def send_step(lt: jax.Array, wall_millis: jax.Array):
    """``Hlc.send`` on a packed int64 logicalTime, on device
    (hlc.dart:51-74 on the lane encoding): millis = max(stored, wall),
    counter increments iff millis unchanged else resets — which on the
    packed form is ``lt + 1`` vs ``wall << 16``. Returns
    ``(new_lt, overflow, drift)`` guard FLAGS instead of raising (a
    device op can't throw; the pipelined model layer accumulates the
    flags and raises host-side at the synchronization point)."""
    from ..hlc import MAX_COUNTER, MAX_DRIFT, SHIFT
    ms = lt >> SHIFT
    stay = ms >= wall_millis
    overflow = stay & ((lt & MAX_COUNTER) == MAX_COUNTER)
    # Clamp on overflow: lt + 1 would carry into the millis field
    # (millis+1, counter 0) and thread a wrapped canonical through the
    # rest of a pipelined window — the host path raises WITHOUT
    # mutating, so the flushed clock must match what it leaves behind.
    new_lt = jnp.where(overflow, lt,
                       jnp.where(stay, lt + 1, wall_millis << SHIFT))
    drift = ms - wall_millis > MAX_DRIFT
    return new_lt, overflow, drift

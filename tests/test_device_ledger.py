"""Device dispatch ledger (docs/OBSERVABILITY.md, device plane): the
fast-path invariants asserted through the RUNTIME metrics surface, not
test instrumentation — a pack-cache or digest-cache hit moves no
per-kernel dispatch counter, a fused merge+repack moves exactly one,
a combiner flush tick moves exactly one commit scatter. Plus the
compile census (first call per pow2 bucket), donation-violation
detection, the store-bytes census and the disable switch the bench
overhead probe leans on."""

import numpy as np
import pytest

from crdt_tpu import DenseCrdt
from crdt_tpu.obs import device as obs_device
from crdt_tpu.obs.device import DispatchLedger, default_ledger, \
    pow2_bucket
from crdt_tpu.obs.registry import MetricsRegistry, default_registry
from crdt_tpu.testing import FakeClock

pytestmark = pytest.mark.ledger

BASE = 1_700_000_000_000


def _make(node="n", n_slots=64, **kw):
    return DenseCrdt(node, n_slots=n_slots,
                     wall_clock=FakeClock(start=BASE), **kw)


def _delta(before, after):
    """Per-kernel dispatch-count movement between two snapshots."""
    return {k: after.get(k, 0) - before.get(k, 0)
            for k in set(before) | set(after)
            if after.get(k, 0) != before.get(k, 0)}


def _cache_hits(name, node):
    return default_registry().counter(name).value(outcome="hit",
                                                  node=node)


# --- bucketing -------------------------------------------------------

def test_pow2_bucket():
    assert pow2_bucket(None) == "scalar"
    assert pow2_bucket(0) == "1"
    assert pow2_bucket(1) == "1"
    assert pow2_bucket(2) == "2"
    assert pow2_bucket(3) == "4"
    assert pow2_bucket(1024) == "1024"
    assert pow2_bucket(1025) == "2048"


# --- zero-dispatch invariants (default ledger = runtime metrics) ----

def test_pack_cache_hit_dispatches_nothing():
    led = default_ledger()
    a = _make("a")
    a.put_batch([1, 2], [10, 20])
    wm = a.canonical_time
    a.put_batch([3], [30])

    before = led.as_dict()
    first = a.pack_since(wm)
    moved = _delta(before, led.as_dict())
    # The miss computed the watermark delta mask on device.
    assert moved.get("dense.delta_mask") == 1

    hits = _cache_hits("crdt_tpu_pack_cache_total", "a")
    before = led.as_dict()
    again = a.pack_since(wm)
    assert _delta(before, led.as_dict()) == {}
    assert again is first
    assert _cache_hits("crdt_tpu_pack_cache_total", "a") == hits + 1


def test_digest_cache_hit_dispatches_nothing():
    led = default_ledger()
    c = _make("dig")
    c.put_batch([4, 9], [1, 2])

    before = led.as_dict()
    tree = c.digest_tree()
    assert _delta(before, led.as_dict()) == {
        "digest.digest_tree_device": 1}

    hits = _cache_hits("crdt_tpu_digest_cache_total", "dig")
    before = led.as_dict()
    assert c.digest_tree() is tree
    assert _delta(before, led.as_dict()) == {}
    assert _cache_hits("crdt_tpu_digest_cache_total", "dig") == hits + 1


def test_fused_merge_repack_is_one_dispatch_and_seeds_the_cache():
    led = default_ledger()
    a, b = _make("a"), _make("b")
    a.put_batch([5, 7], [50, 70])
    packed, ids = a.pack_since(None)

    before = led.as_dict()
    out = b.merge_and_repack(packed, ids)
    assert _delta(before, led.as_dict()) == {
        "dense.merge_repack_step": 1}

    # The fused kernel seeded b's pack cache under the post-merge key:
    # the watermark-aligned repack is a hit, zero dispatches, same
    # cached object.
    hits = _cache_hits("crdt_tpu_pack_cache_total", "b")
    before = led.as_dict()
    assert b.pack_since(None) is out
    assert _delta(before, led.as_dict()) == {}
    assert _cache_hits("crdt_tpu_pack_cache_total", "b") == hits + 1


def test_combiner_flush_tick_is_one_commit_scatter():
    led = default_ledger()
    c = _make("ing", n_slots=128)
    with c.ingest():
        c.put_batch([1, 2, 3, 4], [10, 20, 30, 40])
        c.put_batch([5, 6], [50, 60])
        staged = led.as_dict()
    c.drain_ingest()
    moved = _delta(staged, led.as_dict())
    # Staging dispatched nothing; the flush tick is exactly one
    # commit scatter regardless of how many puts it coalesced.
    assert moved == {"dense.ingest_scatter": 1}


# --- compile census --------------------------------------------------

def test_compile_census_counts_first_call_per_bucket_only():
    reg = default_registry()
    comp = reg.counter("crdt_tpu_device_compiles_total")
    disp = reg.counter("crdt_tpu_device_dispatches_total")

    def compiles(kernel):
        return sum(s["value"] for s in comp.samples()
                   if s["labels"].get("kernel") == kernel)

    c = _make("census", n_slots=128)
    c.put_batch([1], [1])
    c0 = compiles("dense.put_scatter")
    d0 = disp.value(kernel="dense.put_scatter")
    # Same batch shape -> same pow2 bucket -> jit cache hit: the
    # dispatch counter moves, the compile census does not.
    c.put_batch([2], [2])
    assert compiles("dense.put_scatter") == c0
    assert disp.value(kernel="dense.put_scatter") == d0 + 1


def test_compile_census_new_bucket_is_a_new_first_call():
    reg = MetricsRegistry()
    led = DispatchLedger(reg)
    with led.record("k", dim=4):
        pass
    with led.record("k", dim=4):
        pass
    with led.record("k", dim=9):   # pow2 ceiling 16: fresh bucket
        pass
    comp = reg.counter("crdt_tpu_device_compiles_total")
    assert led.dispatches("k") == 3
    assert comp.value(kernel="k", bucket="4") == 1
    assert comp.value(kernel="k", bucket="16") == 1


# --- donation checks -------------------------------------------------

class _LiveBuffer:
    def is_deleted(self):
        return False


class _DeletedBuffer:
    def is_deleted(self):
        return True


def test_donation_violation_counted_on_donating_backends(monkeypatch):
    monkeypatch.setattr(obs_device, "_BACKEND", "tpu")
    reg = MetricsRegistry()
    led = DispatchLedger(reg)
    with led.record("k", dim=2, donated=_LiveBuffer()):
        pass
    with led.record("k", dim=2, donated=_DeletedBuffer()):
        pass
    viol = reg.counter("crdt_tpu_device_donation_violations_total")
    assert viol.value(kernel="k") == 1


def test_donation_not_checked_on_cpu(monkeypatch):
    monkeypatch.setattr(obs_device, "_BACKEND", "cpu")
    reg = MetricsRegistry()
    led = DispatchLedger(reg)
    with led.record("k", dim=2, donated=_LiveBuffer()):
        pass
    viol = reg.counter("crdt_tpu_device_donation_violations_total")
    assert viol.value(kernel="k") == 0


# --- census + disable switch ----------------------------------------

def test_store_bytes_census(monkeypatch):
    monkeypatch.setattr(obs_device, "_BACKEND", "cpu")
    reg = MetricsRegistry()
    led = DispatchLedger(reg)
    store = (np.zeros(16, np.int64), np.zeros(16, np.int32),
             np.zeros(16, np.uint8))
    n = led.census(store)
    assert n == 16 * 8 + 16 * 4 + 16
    gauge = reg.gauge("crdt_tpu_store_bytes")
    assert gauge.value(backend="cpu") == float(n)


def test_disabled_ledger_records_nothing():
    led = DispatchLedger(MetricsRegistry())
    led.enabled = False
    with led.record("k", dim=8):
        pass
    assert led.dispatches() == 0
    # census still returns the byte total, it just skips the gauge
    assert led.census((np.zeros(4, np.int64),)) == 32


def test_failed_dispatch_is_not_counted():
    led = DispatchLedger(MetricsRegistry())
    with pytest.raises(RuntimeError):
        with led.record("k", dim=8):
            raise RuntimeError("backend rejected the program")
    assert led.dispatches("k") == 0


def test_register_is_import_time_not_dispatch_time():
    led = DispatchLedger(MetricsRegistry())
    led.register("mod.kernel_a", "mod.kernel_b")
    assert {"mod.kernel_a",
            "mod.kernel_b"} <= set(led.registered_kernels())
    assert led.dispatches() == 0

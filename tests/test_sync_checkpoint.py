"""Library sync rounds (C10) and checkpoint/resume (SURVEY.md §5)."""

import numpy as np
import jax.numpy as jnp
import pytest

from crdt_tpu import MapCrdt, TpuMapCrdt
from crdt_tpu.checkpoint import (load_dense, load_json, save_dense,
                                 save_json)
from crdt_tpu.ops.dense import DenseStore, empty_dense_store, fanin_step
from crdt_tpu.sync import sync, sync_json
from crdt_tpu.testing import FakeClock

from test_dense import LOCAL, MILLIS, lt_of, make_changeset


def make_replicas(n, cls=MapCrdt):
    return [cls(f"n{i}", wall_clock=FakeClock(start=1_700_000_000_000 + i))
            for i in range(n)]


class TestSync:
    @pytest.mark.parametrize("cls", [MapCrdt, TpuMapCrdt])
    def test_two_replica_convergence(self, cls):
        a, b = make_replicas(2, cls)
        a.put("x", 1)
        b.put("y", 2)
        sync(a, b)
        assert a.map == b.map == {"x": 1, "y": 2}

    def test_three_replica_relay(self):
        # Convergence through an intermediary (map_crdt_test.dart:237-270):
        # works because merged records are re-stamped with the relay's
        # modified time (crdt.dart:87).
        a, b, c = make_replicas(3)
        a.put("ka", 1)
        c.put("kc", 3)
        sync(a, b)
        sync(b, c)
        sync(a, b)
        assert a.map == b.map == c.map == {"ka": 1, "kc": 3}

    @pytest.mark.parametrize("cls", [MapCrdt, TpuMapCrdt])
    def test_sync_json_wire(self, cls):
        a, b = make_replicas(2, cls)
        a.put("x", 1)
        a.delete("x")
        b.put("y", 2)
        sync_json(a, b)
        assert a.map == b.map == {"y": 2}
        assert a.is_deleted("x") and b.is_deleted("x")

    def test_mixed_backends_converge(self):
        a = MapCrdt("na", wall_clock=FakeClock())
        b = TpuMapCrdt("nb", wall_clock=FakeClock(start=1_700_000_000_005))
        a.put("x", 1)
        b.put("y", 2)
        sync(a, b)
        assert a.map == b.map == {"x": 1, "y": 2}
        # Same records and HLCs; key insertion order naturally differs
        # between replicas (true of the reference's map-backed JSON too).
        import json
        ja, jb = json.loads(a.to_json()), json.loads(b.to_json())
        assert ja == jb


class TestCheckpoint:
    @pytest.mark.parametrize("cls", [MapCrdt, TpuMapCrdt])
    def test_json_roundtrip(self, cls, tmp_path):
        crdt = cls("abc", wall_clock=FakeClock())
        crdt.put("x", 1)
        crdt.put("y", 2)
        crdt.delete("y")
        p = str(tmp_path / "snap.json")
        save_json(crdt, p)
        back = load_json(cls, "abc", p, wall_clock=FakeClock())
        assert back.map == crdt.map
        assert back.is_deleted("y")
        # Resume path: the canonical clock absorbed the snapshot's max
        # HLC (crdt.dart:100-109), so new writes sort after old ones.
        assert back.canonical_time >= crdt.get_record("x").hlc

    def test_dense_roundtrip(self, tmp_path):
        store = empty_dense_store(8)
        cs = make_changeset(2, 8, [
            (0, 1, lt_of(MILLIS), 1, 5, False),
            (1, 6, lt_of(MILLIS + 3), 2, 0, True),
        ])
        store, _ = fanin_step(store, cs, jnp.int64(0), jnp.int32(LOCAL),
                              jnp.int64(MILLIS + 10_000))
        p = str(tmp_path / "snap.npz")
        save_dense(store, p)
        back = load_dense(p)
        for lane in DenseStore._fields:
            np.testing.assert_array_equal(np.asarray(getattr(store, lane)),
                                          np.asarray(getattr(back, lane)))

    def test_dense_magic_check(self, tmp_path):
        p = str(tmp_path / "bogus.npz")
        np.savez(p, magic=np.array("nope"))
        with pytest.raises(ValueError):
            load_dense(p)

"""Jaxpr lattice auditor: order-sensitivity hazards in merge kernels.

Round-5 ADVICE found the motivating bug class shipped: an XLA scatter
with duplicate indices has UNSPECIFIED winner order, so a merge built
on one is backend-dependent — the exact property a CRDT join must not
have. This auditor traces every registered merge/join op to its jaxpr
(recursively, through pjit/scan/while/cond/shard_map/pallas_call) and
flags the hazard classes:

- ``scatter-duplicate-order`` — scatter family primitive with
  ``unique_indices=False``: duplicate indices pick an unspecified
  winner. Targets whose CALL CONTRACT guarantees unique slots (a
  dict-keyed delta cannot repeat a slot) declare ``unique_slots=True``
  and the hazard is downgraded to a recorded *assumption* — it stays
  in the golden report so a contract change is a visible diff, not a
  silent regression.
- ``nonassoc-float-reduce`` — reduction/contraction primitive over a
  floating dtype on the merge path: float addition is not associative,
  so the result depends on reduction order. All CRDT lanes are
  int64/int32/bool; any float reduce appearing here is a bug.
- ``prng-in-merge`` — PRNG primitive inside a merge: a join that draws
  randomness cannot be a function of its inputs, let alone a lattice
  join.
- ``donated-invar`` — donated input buffers recorded per target
  (donation aliases the input; safe only if the caller never touches
  the donated buffer again — the host linter's donated-buffer-reuse
  rule enforces that side).

Everything here is TRACE-ONLY: ``jax.make_jaxpr`` builds the IR
without executing a kernel, so the Pallas targets audit fine on CPU
(interpret mode) and the sharded targets on 8 virtual devices.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Sequence, Tuple

from .findings import Finding

_SCATTER_PRIMS_PREFIX = "scatter"
_REDUCE_PRIMS = {
    "reduce_sum", "reduce_prod", "cumsum", "cumprod", "cumlogsumexp",
    "dot_general", "add_any", "psum", "psum2",
}
_PRNG_PRIMS = {
    "threefry2x32", "rng_bit_generator", "rng_uniform", "random_bits",
    "random_seed", "random_wrap", "random_fold_in", "random_gamma",
}


@dataclass
class AuditTarget:
    """One kernel under audit. ``build()`` returns the ClosedJaxpr —
    it must hand concrete arrays to ``jax.make_jaxpr(fn)(*args)``
    itself (closing over arrays in a zero-arg thunk would execute
    eagerly instead of tracing)."""

    name: str
    build: Callable[[], object]
    unique_slots: bool = False
    notes: str = ""


@dataclass
class AuditReport:
    target: str
    hazards: List[dict] = field(default_factory=list)
    assumptions: List[str] = field(default_factory=list)
    prim_counts: Dict[str, int] = field(default_factory=dict)

    def golden(self) -> dict:
        """The stable subset pinned as a golden: hazards and relied-on
        contracts only — prim counts churn with jax versions."""
        return {"target": self.target, "hazards": self.hazards,
                "assumptions": sorted(self.assumptions)}


def _iter_jaxprs(params: dict):
    """Yield every jaxpr-valued param (pjit/scan 'jaxpr', cond
    'branches', while 'cond_jaxpr'/'body_jaxpr', pallas_call 'jaxpr',
    scatter 'update_jaxpr', ...) — generic, so new higher-order prims
    are walked without a registry."""
    import jax.extend.core as jex_core

    def as_jaxpr(v):
        if isinstance(v, jex_core.ClosedJaxpr):
            return v.jaxpr
        if isinstance(v, jex_core.Jaxpr):
            return v
        return None

    for v in params.values():
        j = as_jaxpr(v)
        if j is not None:
            yield j
        elif isinstance(v, (tuple, list)):
            for item in v:
                j = as_jaxpr(item)
                if j is not None:
                    yield j


def _walk(jaxpr, report: AuditReport, unique_slots: bool) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        report.prim_counts[name] = report.prim_counts.get(name, 0) + 1

        if name.startswith(_SCATTER_PRIMS_PREFIX):
            unique = bool(eqn.params.get("unique_indices", False))
            if not unique:
                if unique_slots:
                    note = (f"{name} with unique_indices=False is "
                            "order-safe only under the unique-slots "
                            "call contract")
                    if note not in report.assumptions:
                        report.assumptions.append(note)
                else:
                    report.hazards.append({
                        "rule": "scatter-duplicate-order",
                        "prim": name,
                        "detail": "unique_indices=False: duplicate "
                                  "indices pick an unspecified "
                                  "(backend-dependent) winner",
                    })

        if name in _REDUCE_PRIMS:
            floaty = any(
                hasattr(v, "aval") and hasattr(v.aval, "dtype")
                and str(v.aval.dtype).startswith(("float", "bfloat"))
                for v in eqn.invars)
            if floaty:
                report.hazards.append({
                    "rule": "nonassoc-float-reduce",
                    "prim": name,
                    "detail": "floating-point reduction on the merge "
                              "path: float addition is not "
                              "associative, result depends on "
                              "reduction order",
                })

        if name in _PRNG_PRIMS or "rng" in name:
            report.hazards.append({
                "rule": "prng-in-merge",
                "prim": name,
                "detail": "PRNG primitive inside a merge kernel: the "
                          "join is not a function of its inputs",
            })

        if name == "pjit":
            donated = eqn.params.get("donated_invars", ())
            if any(donated):
                note = (f"pjit with {sum(map(bool, donated))} donated "
                        "invar(s): input aliased, caller must not "
                        "reuse the donated buffer")
                if note not in report.assumptions:
                    report.assumptions.append(note)

        for sub in _iter_jaxprs(eqn.params):
            _walk(sub, report, unique_slots)


def audit_target(target: AuditTarget) -> AuditReport:
    closed = target.build()
    report = AuditReport(target=target.name)
    _walk(closed.jaxpr, report, target.unique_slots)
    return report


def audit_all(targets: Sequence[AuditTarget]
              ) -> Tuple[List[AuditReport], List[Finding]]:
    reports: List[AuditReport] = []
    findings: List[Finding] = []
    for target in targets:
        try:
            report = audit_target(target)
        except Exception as e:  # a target that fails to trace at all
            findings.append(Finding(
                rule="jaxpr-trace-error", path=f"<jaxpr:{target.name}>",
                line=0,
                message=f"target failed to trace: {type(e).__name__}",
                detail=str(e)))
            continue
        reports.append(report)
        for hz in report.hazards:
            findings.append(Finding(
                rule=hz["rule"], path=f"<jaxpr:{target.name}>", line=0,
                message=f"{hz['prim']}: {hz['detail']}"))
    return reports, findings


# --- builtin targets over the registered kernels ---

_N = 64      # store width for scalar/dense targets
_M = 8       # changeset rows


def builtin_targets(include_sharded: bool = True) -> List[AuditTarget]:
    """Audit targets over every registered merge/join op. Jax imports
    are local so the host linter can run without jax. Sharded targets
    require 8 devices (tests/conftest.py and the CLI __main__ force 8
    virtual CPU devices) and are skipped — with a report note — when
    fewer are available."""
    import jax
    import numpy as np
    from ..ops import dense as dense_ops
    from ..ops import merge as merge_ops

    i64 = lambda *s: np.zeros(s, np.int64)
    i32 = lambda *s: np.zeros(s, np.int32)
    b8 = lambda *s: np.zeros(s, bool)

    targets: List[AuditTarget] = []

    def scalar_store():
        return merge_ops.Store(lt=i64(_N), node=i32(_N), mod_lt=i64(_N),
                               mod_node=i32(_N), occupied=b8(_N),
                               tomb=b8(_N))

    def scalar_cs():
        return merge_ops.Changeset(slot=i32(_M), lt=i64(_M),
                                   node=i32(_M), tomb=b8(_M),
                                   valid=b8(_M))

    targets.append(AuditTarget(
        name="merge.merge_step", unique_slots=True,
        notes="host key->slot dict cannot repeat a slot",
        build=lambda: jax.make_jaxpr(merge_ops.merge_step)(
            scalar_store(), scalar_cs(), np.int64(0), np.int32(0),
            np.int64(0))))

    targets.append(AuditTarget(
        name="merge.scatter_put", unique_slots=True,
        notes="host key->slot dict cannot repeat a slot",
        build=lambda: jax.make_jaxpr(merge_ops.scatter_put)(
            scalar_store(), scalar_cs(), i64(_M), i32(_M))))

    def dense_store():
        return dense_ops.DenseStore(lt=i64(_N), node=i32(_N),
                                    val=i64(_N), mod_lt=i64(_N),
                                    mod_node=i32(_N), occupied=b8(_N),
                                    tomb=b8(_N))

    def dense_cs(rows=_M):
        return dense_ops.DenseChangeset(lt=i64(rows, _N),
                                        node=i32(rows, _N),
                                        val=i64(rows, _N),
                                        tomb=b8(rows, _N),
                                        valid=b8(rows, _N))

    targets.append(AuditTarget(
        name="dense.fanin_step",
        notes="elementwise fold; no scatter at all",
        build=lambda: jax.make_jaxpr(dense_ops.fanin_step)(
            dense_store(), dense_cs(), np.int64(0), np.int32(0),
            np.int64(0))))

    targets.append(AuditTarget(
        name="dense.fanin_stream",
        notes="lax.scan over chunked changesets; walked into the body",
        build=lambda: jax.make_jaxpr(dense_ops.fanin_stream)(
            dense_store(),
            dense_ops.DenseChangeset(lt=i64(2, _M, _N),
                                     node=i32(2, _M, _N),
                                     val=i64(2, _M, _N),
                                     tomb=b8(2, _M, _N),
                                     valid=b8(2, _M, _N)),
            np.int64(0), np.int32(0), np.int64(0))))

    targets.append(AuditTarget(
        name="dense.sparse_fanin_step", unique_slots=True,
        notes="dict-keyed delta cannot repeat a slot",
        build=lambda: jax.make_jaxpr(dense_ops.sparse_fanin_step)(
            dense_store(), i64(_M), i64(_M), i32(_M), i64(_M), b8(_M),
            b8(_M), np.int64(0), np.int32(0))))

    targets.append(AuditTarget(
        name="dense.wire_join_step",
        notes="elementwise slot-aligned join; no gather, no scatter",
        build=lambda: jax.make_jaxpr(dense_ops.wire_join_step)(
            dense_store(), i64(_N), i32(_N), i64(_N), b8(_N), b8(_N),
            np.int64(0), np.int32(0))))

    targets.append(AuditTarget(
        name="dense.put_scatter", unique_slots=True,
        notes="dict-keyed batch cannot repeat a slot; donate=False "
              "variant audited (donation is a host-linter concern)",
        build=lambda: jax.make_jaxpr(dense_ops._put_scatter(False))(
            dense_store(), i64(_M), i64(_M), b8(_M), np.int64(0),
            np.int32(0))))

    targets.append(AuditTarget(
        name="dense.record_scatter", unique_slots=True,
        notes="dict-keyed batch cannot repeat a slot",
        build=lambda: jax.make_jaxpr(dense_ops._record_scatter(False))(
            dense_store(), i64(_M), i64(_M), i32(_M), i64(_M), i64(_M),
            i32(_M), b8(_M))))

    targets.append(AuditTarget(
        name="dense.delete_scatter", unique_slots=True,
        notes="dict-keyed batch cannot repeat a slot",
        build=lambda: jax.make_jaxpr(dense_ops._delete_scatter(False))(
            dense_store(), i64(_M), np.int64(0), np.int32(0))))

    targets.append(AuditTarget(
        name="dense.merge_repack_step", unique_slots=True,
        notes="fused gossip-relay join + next-pack delta mask in one "
              "program; dict-keyed delta cannot repeat a slot",
        build=lambda: jax.make_jaxpr(dense_ops._merge_repack_jit(False))(
            dense_store(), i64(_M), i64(_M), i32(_M), i64(_M), b8(_M),
            b8(_M), np.int64(0), np.int32(0), np.int64(0))))

    # Merkle anti-entropy kernels (ops/digest.py, docs/ANTIENTROPY.md):
    # read-only reductions/masks over the store — no scatter, no lane
    # mutation — but registered so the CLI completeness gate proves
    # the hot anti-entropy path stays on device.
    from ..ops import digest as digest_ops

    targets.append(AuditTarget(
        name="digest.digest_tree_levels",
        notes="on-device segment-tree digest: per-slot mix + leaf "
              "fold + every interior combine in one program; "
              "read-only over the lanes",
        build=lambda: jax.make_jaxpr(digest_ops._digest_tree_jit(
            8, False))(i64(_N), i64(_N), b8(_N), b8(_N))))

    targets.append(AuditTarget(
        name="dense.range_delta_mask",
        notes="slot-span-restricted delta mask feeding "
              "pack_since(ranges=...); elementwise, no scatter",
        build=lambda: jax.make_jaxpr(dense_ops._range_mask_jit())(
            dense_store(), np.int64(0), i64(2), i64(2))))

    # Storage-plane kernels (docs/STORAGE.md): physically destructive
    # by design, so audit coverage is gated by the CLI
    # (_GC_REQUIRED) — an order hazard here destroys state no merge
    # can repair.
    targets.append(AuditTarget(
        name="dense.gc_purge",
        notes="epoch tombstone purge: elementwise lane masking under "
              "one stability-floor predicate — no gather, no "
              "scatter, order-insensitive by shape",
        build=lambda: jax.make_jaxpr(dense_ops._gc_purge_jit(False))(
            dense_store(), np.int64(0))))

    targets.append(AuditTarget(
        name="dense.compact_remap", unique_slots=True,
        notes="slot remap to span-dense prefixes: scatter targets are "
              "a masked per-span survivor-rank cumsum, bijective over "
              "occupied rows by construction (spans are host-validated "
              "non-overlapping, so each slot lands in at most one)",
        build=lambda: jax.make_jaxpr(
            dense_ops._compact_remap_jit(False, 8, False))(
            dense_store(), i64(2), i64(2))))

    # Typed lane kernels (crdt_tpu/semantics): the shared sparse
    # scatter and fan-in shapes here, plus one per-tag elementwise
    # wire-join target per registered semantics from the registry
    # itself — a new type gets audit coverage by registering.
    from ..semantics import audit_targets as _semantics_audit_targets
    from ..semantics import kernels as _sem_kernels

    i8 = lambda *s: np.zeros(s, np.int8)

    targets.append(AuditTarget(
        name="semantics.typed_sparse_join_step", unique_slots=True,
        notes="dict-keyed delta cannot repeat a slot; gather "
              "mode=fill + scatter mode=drop over typed rows",
        build=lambda: jax.make_jaxpr(
            _sem_kernels.typed_sparse_join_step)(
            dense_store(), i8(_M), i32(_M), i64(_M), i32(_M),
            i64(_M), b8(_M), b8(_M), np.int64(0), np.int32(0))))

    targets.append(AuditTarget(
        name="semantics.typed_fanin_step",
        notes="elementwise typed fold; no scatter at all",
        build=lambda: jax.make_jaxpr(_sem_kernels.typed_fanin_step)(
            dense_store(), i8(_N), dense_cs(), np.int64(0),
            np.int32(0), np.int64(0))))

    targets.extend(_semantics_audit_targets())

    targets.append(AuditTarget(
        name="pallas.pallas_fanin_step[interpret]",
        notes="Mosaic fan-in kernel at N=TILE, traced in interpret "
              "mode; walked into the pallas_call jaxpr",
        build=_build_pallas_step))

    targets.append(AuditTarget(
        name="pallas.ingest_scatter_tiles[interpret]",
        unique_slots=True,
        notes="touched-tile ingest commit kernel (ops/pallas_scatter); "
              "combiner dedups slots before host prep; interpret "
              "mode, trace-only",
        build=_build_pallas_ingest_scatter))

    # The per-shard body of parallel/fanin.py's _pallas_fanin_block
    # (split -> pallas_fanin_batch -> join) audited at the per-device
    # shard shape. This is the golden-pinned target: it traces on any
    # jax, whereas the full shard_map step below needs `jax.P`.
    targets.append(AuditTarget(
        name="parallel.pallas_fanin_block[per-shard]",
        notes="parallel/fanin.py _pallas_fanin_block per-device body: "
              "split_store -> pallas_fanin_batch(chunk_rows=8) -> "
              "join_store at one key shard (N=TILE, R=16), interpret "
              "mode, trace-only",
        build=_build_pallas_block_per_shard))

    if include_sharded and len(jax.devices()) >= 8:
        try:
            from ..parallel import fanin as _pf  # noqa: F401
            have_parallel = True
        except ImportError:
            # parallel/ targets a newer jax (`jax.P`, top-level
            # shard_map); on older versions the per-shard body above
            # still covers the kernel path.
            have_parallel = False
        if have_parallel:
            targets.append(AuditTarget(
                name="parallel.sharded_fanin[mesh2x4]",
                notes="shard_map + psum/pmax collective fan-in block",
                build=_build_sharded_fanin))
            targets.append(AuditTarget(
                name="parallel.sharded_pallas_fanin[mesh2x4]",
                notes="per-shard Mosaic batch kernel inside the "
                      "collective step (parallel/fanin.py "
                      "_pallas_fanin_block); trace-only",
                build=_build_sharded_pallas_fanin))

    # The pod-local collective join only needs a 2-member mesh, so it
    # audits on any multi-device (or virtual-device) host even where
    # the 8-device fan-in meshes cannot build.
    if include_sharded and len(jax.devices()) >= 2:
        try:
            from ..parallel import collective as _pc  # noqa: F401
            have_collective = True
        except ImportError:
            have_collective = False
        if have_collective:
            targets.append(AuditTarget(
                name="parallel.collective_join[member2]",
                notes="pod-local group anti-entropy: shard_map lex-max "
                      "clock join + typed-semantics collectives "
                      "(gcounter/pncounter/orset pmax, mvreg "
                      "all_gather union) + in-program digest leaves "
                      "(parallel/collective.py, docs/COLLECTIVE.md)",
                build=_build_collective_join))

    return targets


def _build_pallas_step():
    import jax
    import numpy as np
    from ..ops import pallas_merge as pm
    from ..ops.dense import empty_dense_store, DenseChangeset

    n = pm.TILE
    store = pm.split_store(empty_dense_store(n))
    cs = pm.split_changeset(DenseChangeset(
        lt=np.zeros((2, n), np.int64), node=np.zeros((2, n), np.int32),
        val=np.zeros((2, n), np.int64), tomb=np.zeros((2, n), bool),
        valid=np.zeros((2, n), bool)))

    def step(store, cs, canon, local_node, wall):
        return pm.pallas_fanin_step(store, cs, canon, local_node, wall,
                                    interpret=True)

    return jax.make_jaxpr(step)(store, cs, np.int64(0), np.int32(0),
                                np.int64(0))


def _build_pallas_ingest_scatter():
    import jax
    import numpy as np
    from ..ops import pallas_scatter as ps
    from ..ops.dense import empty_dense_store

    n = ps.TILE  # one touched tile
    store = empty_dense_store(n)
    tile_ids = np.zeros((1,), np.int32)
    valid = np.zeros((ps._SB, ps._LANE), np.int32)
    lt_d = np.zeros((ps._SB, ps._LANE), np.int64)
    val_d = np.zeros((ps._SB, ps._LANE), np.int64)
    tomb_d = np.zeros((ps._SB, ps._LANE), np.int32)
    me = np.zeros((1,), np.int32)
    return jax.make_jaxpr(ps._scatter_jit(False, True))(
        store, tile_ids, valid, lt_d, val_d, tomb_d, me)


def _build_pallas_block_per_shard():
    # Mirrors parallel/fanin.py _pallas_fanin_block's per-device body
    # (the compute between the collectives): split -> batch kernel ->
    # join, at one key shard. Collectives (pmax/pmin/psum) only trace
    # inside shard_map, so they are exercised by the sharded targets
    # when `crdt_tpu.parallel` imports; the lattice-hazard surface
    # (scatters, reductions, RNG) lives entirely in this body.
    import jax
    import numpy as np
    from ..ops import pallas_merge as pm
    from ..ops.dense import empty_dense_store, DenseChangeset

    n = pm.TILE
    r = 16

    def _unwrap(fn):
        return getattr(fn, "__wrapped__", fn)

    store = _unwrap(pm.split_store)(empty_dense_store(n))
    cs = _unwrap(pm.split_changeset)(DenseChangeset(
        lt=np.zeros((r, n), np.int64), node=np.zeros((r, n), np.int32),
        val=np.zeros((r, n), np.int64), tomb=np.zeros((r, n), bool),
        valid=np.zeros((r, n), bool)))

    def block(store, cs, canon, local_node, wall):
        out, res = _unwrap(pm.pallas_fanin_batch)(
            store, cs, canon, local_node, wall,
            chunk_rows=8, interpret=True)
        return _unwrap(pm.join_store)(out), res

    return jax.make_jaxpr(block)(store, cs, np.int64(0), np.int32(0),
                                 np.int64(0))


def _sharded_args(n_per_shard: int):
    import numpy as np
    from ..parallel import fanin as pf
    from ..ops.dense import DenseStore, DenseChangeset

    mesh = pf.make_fanin_mesh(2, 4)
    r = pf.replica_extent(mesh) * 8
    n = 4 * n_per_shard
    store = DenseStore(lt=np.zeros(n, np.int64),
                       node=np.zeros(n, np.int32),
                       val=np.zeros(n, np.int64),
                       mod_lt=np.zeros(n, np.int64),
                       mod_node=np.zeros(n, np.int32),
                       occupied=np.zeros(n, bool),
                       tomb=np.zeros(n, bool))
    cs = DenseChangeset(lt=np.zeros((r, n), np.int64),
                        node=np.zeros((r, n), np.int32),
                        val=np.zeros((r, n), np.int64),
                        tomb=np.zeros((r, n), bool),
                        valid=np.zeros((r, n), bool))
    return mesh, store, cs


def _build_collective_join():
    import jax
    import numpy as np
    from ..ops.dense import DenseStore
    from ..parallel import collective as pc

    mesh = pc.make_collective_mesh(2)

    def member_store():
        return DenseStore(lt=np.zeros(_N, np.int64),
                          node=np.zeros(_N, np.int32),
                          val=np.zeros(_N, np.int64),
                          mod_lt=np.zeros(_N, np.int64),
                          mod_node=np.zeros(_N, np.int32),
                          occupied=np.zeros(_N, bool),
                          tomb=np.zeros(_N, bool))

    # has_sem=True so the audit walks every typed join branch (the
    # untyped program is a strict subset). Trace the jitted program
    # itself — the host wrapper only adds ledger accounting.
    step = pc.make_collective_join(mesh, True, 8, donate=False)
    return jax.make_jaxpr(step.jitted)(
        (member_store(), member_store()), np.zeros(_N, np.int8),
        np.zeros(2, np.int64), np.zeros(2, np.int32), np.int64(0))


def _build_sharded_fanin():
    import jax
    import numpy as np
    from ..parallel import fanin as pf

    mesh, store, cs = _sharded_args(2)
    step = pf.make_sharded_fanin(mesh)
    return jax.make_jaxpr(step)(store, cs, np.int64(0), np.int32(0),
                                np.int64(0))


def _build_sharded_pallas_fanin():
    import jax
    import numpy as np
    from ..ops.pallas_merge import TILE
    from ..parallel import fanin as pf

    mesh, store, cs = _sharded_args(TILE)
    step = pf.make_sharded_pallas_fanin(mesh, chunk_rows=8,
                                        interpret=True)
    return jax.make_jaxpr(step)(store, cs, np.int64(0), np.int32(0),
                                np.int64(0))

"""Native HLC wire codec: differential vs the pure-Python path.

The C batch codec (`crdt_tpu/native/hlccodec.c`) must be bit-identical
to the Python codec on canonical-shape strings and must defer (None)
on everything else. The build environment ships a C compiler, so the
module is REQUIRED to load here — a silent fallback hiding a build
break would otherwise go unnoticed.
"""

import random

import pytest

import crdt_tpu.crdt_json as crdt_json
from crdt_tpu import Hlc, MapCrdt
from crdt_tpu.native import load
from crdt_tpu.testing import FakeClock


@pytest.fixture(scope="module")
def codec():
    mod = load()
    assert mod is not None, "native codec failed to build/load"
    return mod


def random_hlcs(n, seed=0):
    rng = random.Random(seed)
    nodes = ["abc", "node-x", "a-b-c", "x" * 10, "n0", "ünïcode"]
    return [Hlc(rng.randrange(0, 1 << 44), rng.randrange(0, 1 << 16),
                rng.choice(nodes)) for _ in range(n)]


def test_parse_batch_matches_python(codec):
    hlcs = random_hlcs(500)
    strings = [str(h) for h in hlcs]
    millis_l, counter_l, node_l = codec.parse_hlc_batch(strings)
    for h, s, ms, c, node in zip(hlcs, strings, millis_l, counter_l,
                                 node_l):
        assert ms is not None, s
        assert Hlc(ms, c, node) == h
        assert Hlc.parse(s) == Hlc(ms, c, node)


def test_format_batch_matches_python(codec):
    hlcs = random_hlcs(500, seed=1)
    out = codec.format_hlc_batch([h.millis for h in hlcs],
                                 [h.counter for h in hlcs],
                                 [str(h.node_id) for h in hlcs])
    for h, s in zip(hlcs, out):
        assert s == str(h)


def test_non_canonical_defers(codec):
    bad = ["", "garbage", "2026-07-29 12:00:00.000Z-0000-n",  # space sep
           "2026-07-29T12:00:00Z-0000-n",                     # no millis
           "2026-07-29T12:00:00.000+00:00-0000-n",            # offset
           "2026-07-29T12:00:00.000Z-00-n"]                   # short hex
    millis_l, _, _ = codec.parse_hlc_batch(bad)
    assert millis_l == [None] * len(bad)


def test_format_out_of_range_defers(codec):
    out = codec.format_hlc_batch([-1, 400_000_000_000_000],
                                 [0, 0], ["n", "n"])
    # Negative millis -> year < 1970 but >= 0: formatted fine; the
    # far-future value exceeds year 9999 -> deferred.
    assert out[0] == str(Hlc(-1, 0, "n"))
    assert out[1] is None


def test_invalid_calendar_dates_rejected(codec):
    # Shape-valid but calendar-invalid strings must NOT silently
    # normalize — the C path defers, the Python path raises.
    bad = ["2026-02-30T00:00:00.000Z-0000-n",   # Feb 30
           "2026-13-01T00:00:00.000Z-0000-n",   # month 13
           "2026-01-01T25:00:00.000Z-0000-n",   # hour 25
           "2026-01-01T00:61:00.000Z-0000-n"]   # minute 61
    millis_l, _, _ = codec.parse_hlc_batch(bad)
    assert millis_l == [None] * len(bad)
    for s in bad:
        with pytest.raises(ValueError):
            Hlc.parse(s)
    # Leap day valid in leap years only.
    assert codec.parse_hlc_batch(
        ["2024-02-29T00:00:00.000Z-0000-n"])[0][0] is not None
    assert codec.parse_hlc_batch(
        ["2023-02-29T00:00:00.000Z-0000-n"])[0][0] is None


def test_out_of_range_year_fails_fast():
    # Encoding a year beyond 9999 must raise, not emit unparseable wire.
    from crdt_tpu.hlc import _iso8601
    with pytest.raises(ValueError):
        _iso8601(400_000_000_000_000)
    with pytest.raises(ValueError):
        _iso8601(-63_000_000_000_000)  # before year 1


def test_wire_roundtrip_native_vs_python(monkeypatch):
    src = MapCrdt("remote", wall_clock=FakeClock())
    src.put_all({f"k{i}": {"v": i, "s": "x" * (i % 23)}
                 for i in range(200)})
    src.delete("k3")
    native_json = src.to_json()

    monkeypatch.setattr(crdt_json.native, "load", lambda: None)
    python_json = src.to_json()
    assert native_json == python_json

    dst_py = MapCrdt("local", wall_clock=FakeClock())
    dst_py.merge_json(python_json)
    monkeypatch.undo()
    dst_nat = MapCrdt("local", wall_clock=FakeClock())
    dst_nat.merge_json(native_json)
    assert dst_py.record_map() == dst_nat.record_map()
    assert dst_py.to_json() == dst_nat.to_json()

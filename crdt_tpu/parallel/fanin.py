"""Sharded replica fan-in over a device mesh — C9/C10 on ICI/DCN.

The reference's replication machinery is N replicas converging by
pairwise JSON exchange over an application transport
(crdt.dart:77-135, crdt_json.dart:8-37). The TPU-native equivalent
maps both onto a 2-D ``jax.sharding.Mesh``:

- **key axis** — the record store's key slots are sharded across
  devices (the scale axis of this framework is keys × replicas,
  SURVEY.md §5: the "context" being the record store). Each key shard
  is replicated across the replica axis.
- **replica axis** — incoming replica changesets are sharded across
  devices; the per-key lattice join fans in over this axis with XLA
  collectives riding ICI (cross-slice replica groups ride DCN when the
  mesh spans slices — same code, the mesh shape decides).

The cross-device reduction is a **lexicographic (lt, node) max**, which
ICI reductions don't provide natively (SURVEY.md §5); it is composed
from primitive collectives:

1. ``pmax`` of the per-device best ``lt``;
2. ``pmax`` of ``node`` masked to devices holding that ``lt`` —
   node-ordinal tie-break (hlc.dart:158-161);
3. ``pmin`` of the replica-axis rank masked to devices holding the
   winning ``(lt, node)`` — stable lowest-rank tie on identical HLCs
   (sequential-merge parity, see ops/dense.py);
4. one-hot ``psum`` to broadcast the winner's payload/tombstone lanes.

Guard semantics (documented difference from the single-device path):
``Hlc.recv``'s fast-path shielding (hlc.dart:85) is evaluated per
device block — the running canonical clock cummaxes over the records
*this device* visits, seeded with the pre-merge canonical time. Records
on one device do not shield records on another, so the sharded guards
are strictly more sensitive than the r-major sequential visit (they can
only flag a superset). Store lanes and the canonical clock are
bit-identical to the single-device ``fanin_step`` either way; detailed
first-offender diagnostics come from the single-device path.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import functools

import jax
import jax.numpy as jnp

from ..obs import device as _obs_device

_obs_device.register(
    "parallel.sharded_fanin", "parallel.sharded_pallas_fanin",
    "parallel.sharded_ingest", "parallel.sharded_digest",
    "parallel.sharded_delta_mask", "parallel.sharded_max_logical_time",
    "parallel.sharded_compact")
try:                                     # jax >= 0.5 re-exports P
    from jax import P
except ImportError:                      # pragma: no cover
    from jax.sharding import PartitionSpec as P
from jax.sharding import Mesh, NamedSharding

try:                                     # jax >= 0.6: top-level export
    _shard_map = jax.shard_map
except AttributeError:                   # pragma: no cover
    # Older jax ships it under experimental with the replication
    # check named check_rep (same semantics as check_vma).
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_rep=check_vma)

from ..hlc import MAX_COUNTER, MAX_DRIFT, SHIFT
from ..ops.dense import (DenseChangeset, DenseStore, reduce_replicas,
                         _NEG, _I32_NEG)
from ..ops.merge import recv_guards

REPLICA_AXIS = "replica"
KEY_AXIS = "key"
# Optional outer replica axis for multi-slice meshes: collectives over
# ("slice", "replica") decompose into an ICI reduction within each
# slice and a DCN exchange across slices — XLA picks the decomposition
# from the mesh's device layout; the fan-in code is identical.
SLICE_AXIS = "slice"

# Plain int (not a jnp scalar): a module-level concrete array would
# initialize the jax backend at import time, foreclosing the platform
# selection entry points need to do first.
_BIG_RANK = 2 ** 30


class ShardedFaninResult(NamedTuple):
    new_canonical: jax.Array  # int64 scalar (pre final-send-bump)
    win_count: jax.Array      # int32 adopted records across all shards
    win: jax.Array            # bool[N] per-slot adopted mask (key-sharded)
    any_bad: jax.Array        # bool — some recv guard tripped
    any_dup: jax.Array        # bool — a duplicate-node guard tripped
    any_drift: jax.Array      # bool — a drift guard tripped


def _make_mesh(shape: tuple, axis_names: tuple, devices) -> Mesh:
    import numpy as np
    devices = np.asarray(devices if devices is not None else jax.devices())
    want = int(np.prod(shape))
    assert devices.size == want, (
        f"{devices.size} devices != "
        + "×".join(str(s) for s in shape))
    return Mesh(devices.reshape(shape), axis_names)


def make_fanin_mesh(n_replica_shards: int, n_key_shards: int,
                    devices=None) -> Mesh:
    """A (replica, key) mesh over the given/default devices."""
    return _make_mesh((n_replica_shards, n_key_shards),
                      (REPLICA_AXIS, KEY_AXIS), devices)


def make_multislice_fanin_mesh(n_slices: int, n_replica_shards: int,
                               n_key_shards: int, devices=None) -> Mesh:
    """A (slice, replica, key) mesh for multi-slice deployments.

    The replica fan-in runs over ``(slice, replica)`` jointly: the
    inner axis reduces over ICI within each slice, the outer over DCN
    across slices (scaling-book recipe — the mesh's device layout
    decides which hops each collective takes). Pass the device array
    slice-major so the outer axis really is the DCN boundary.
    """
    return _make_mesh((n_slices, n_replica_shards, n_key_shards),
                      (SLICE_AXIS, REPLICA_AXIS, KEY_AXIS), devices)


def _replica_axes(mesh: Mesh) -> tuple:
    """Every mesh axis except the key axis fans replicas in, in mesh
    order — ('replica',) on a 2-D mesh, ('slice', 'replica') on a
    multi-slice mesh."""
    return tuple(n for n in mesh.axis_names if n != KEY_AXIS)


def replica_extent(mesh: Mesh) -> int:
    """Total replica shards = product of every non-key axis size; the
    changeset's R dim must pad to a multiple of this before
    `shard_changeset`."""
    extent = 1
    for a in _replica_axes(mesh):
        extent *= mesh.shape[a]
    return extent


@functools.lru_cache(maxsize=None)
def store_sharding(mesh: Mesh) -> NamedSharding:
    """Store lanes: sharded over keys, replicated over the replica
    (and slice, if present) axes.

    Cached per mesh (Mesh is hashable): the write fast lane asks for
    this on EVERY commit (`DenseCrdt._write_sharding` feeds the
    scatter jit cache key), so the precomputed NamedSharding is
    handed back instead of re-built per flush."""
    return NamedSharding(mesh, P(KEY_AXIS))


@functools.lru_cache(maxsize=None)
def changeset_sharding(mesh: Mesh) -> NamedSharding:
    """Changeset lanes [R, N]: replicas × keys over the full mesh (the
    R dim spans every replica axis on a multi-slice mesh). Cached per
    mesh, like `store_sharding`."""
    return NamedSharding(mesh, P(_replica_axes(mesh), KEY_AXIS))


def shard_store(store: DenseStore, mesh: Mesh) -> DenseStore:
    s = store_sharding(mesh)
    return DenseStore(*(jax.device_put(lane, s) for lane in store))


def shard_changeset(cs: DenseChangeset, mesh: Mesh) -> DenseChangeset:
    s = changeset_sharding(mesh)
    return DenseChangeset(*(jax.device_put(lane, s) for lane in cs))


def _fanin_block(replica_axes: tuple, store: DenseStore,
                 cs: DenseChangeset, canonical_lt: jax.Array,
                 local_node: jax.Array, wall_millis: jax.Array
                 ) -> Tuple[DenseStore, ShardedFaninResult]:
    """Per-device body under shard_map: local reduce, then the
    lexicographic max fan-in over the replica axes (one axis on a flat
    mesh; (slice, replica) on a multi-slice mesh — ICI inside a slice,
    DCN across)."""
    all_axes = replica_axes + (KEY_AXIS,)
    # --- per-device guards (see module docstring for semantics) ---
    # The three flags ride ONE two-lane pmax (lane 0 dup, lane 1
    # drift); exception payloads come from the model's exact host-side
    # recompute on the failure path, not from here.
    any_bad, first_bad, first_is_dup, _ = recv_guards(
        cs.lt, cs.node, cs.valid, canonical_lt, local_node, wall_millis)
    flags = jnp.stack([(any_bad & first_is_dup).astype(jnp.int32),
                       (any_bad & ~first_is_dup).astype(jnp.int32)])
    flags = jax.lax.pmax(flags, all_axes)
    any_dup = flags[0] > 0
    any_drift = flags[1] > 0
    any_bad = any_dup | any_drift

    # --- local replica reduce on this device's [R_blk, N_blk] block ---
    best_lt, best_node, best_val, best_tomb, any_valid = reduce_replicas(cs)
    best_lt = jnp.where(any_valid, best_lt, _NEG)
    best_node = jnp.where(any_valid, best_node, _I32_NEG)

    # --- cross-device lexicographic (lt, node) max over the replica
    # axes: pmax lt → masked pmax node → stable pmin rank → one-hot psum
    # of the winner's payload lanes. ICI within a slice, DCN across. ---
    m1 = jax.lax.pmax(best_lt, replica_axes)
    node_cand = jnp.where(best_lt == m1, best_node, _I32_NEG)
    m2 = jax.lax.pmax(node_cand, replica_axes)
    has = (best_lt == m1) & (best_node == m2)
    rank = _flat_rank(replica_axes)
    winner_rank = jax.lax.pmin(jnp.where(has, rank, _BIG_RANK),
                               replica_axes)
    mine = has & (rank == winner_rank)
    g_val = jax.lax.psum(jnp.where(mine, best_val, 0), replica_axes)
    g_tomb = jax.lax.psum(jnp.where(mine, best_tomb, False
                                    ).astype(jnp.int32), replica_axes) > 0
    g_any = jax.lax.pmax(any_valid.astype(jnp.int32), replica_axes) > 0

    # --- canonical absorption: global max over every record seen ---
    new_canonical = jnp.maximum(
        canonical_lt,
        jax.lax.pmax(jnp.max(jnp.where(g_any, m1, _NEG)), all_axes))

    # --- LWW vs the local key shard (strict: local wins exact ties,
    # crdt.dart:84). Identical on every device of a key column, so the
    # replicated store stays consistent without further collectives. ---
    remote_newer = ((m1 > store.lt) |
                    ((m1 == store.lt) & (m2 > store.node)))
    win = g_any & (~store.occupied | remote_newer)

    new_store = DenseStore(
        lt=jnp.where(win, m1, store.lt),
        node=jnp.where(win, m2, store.node),
        val=jnp.where(win, g_val, store.val),
        mod_lt=jnp.where(win, new_canonical, store.mod_lt),
        mod_node=jnp.where(win, local_node, store.mod_node),
        occupied=store.occupied | win,
        tomb=jnp.where(win, g_tomb, store.tomb),
    )
    win_count = jax.lax.psum(jnp.sum(win).astype(jnp.int32), KEY_AXIS)
    return new_store, ShardedFaninResult(
        new_canonical=new_canonical, win_count=win_count, win=win,
        any_bad=any_bad, any_dup=any_dup, any_drift=any_drift)


def _flat_rank(replica_axes: tuple) -> jax.Array:
    """Flat rank across the replica axes, outer-major — the order the
    [R, N] changeset rows are laid out over the mesh, so the lowest
    flat rank is the earliest replica row (sequential-merge parity)."""
    rank = jax.lax.axis_index(replica_axes[0])
    for a in replica_axes[1:]:
        # psum(1, axis) is the portable axis size (jax.lax.axis_size
        # only exists on newer jax); it folds to a constant in-trace.
        size = (jax.lax.axis_size(a) if hasattr(jax.lax, "axis_size")
                else jax.lax.psum(1, a))
        rank = rank * size + jax.lax.axis_index(a)
    return rank


def _pallas_fanin_block(replica_axes: tuple, chunk_rows: int,
                        interpret: bool, store: DenseStore,
                        cs: DenseChangeset, canonical_lt: jax.Array,
                        local_node: jax.Array, wall_millis: jax.Array
                        ) -> Tuple[DenseStore, ShardedFaninResult]:
    """Per-device body with the Mosaic kernel doing the heavy reduce.

    Each device runs `ops.pallas_merge.pallas_fanin_batch` to merge its
    own [R_blk, N_blk] changeset rows into its (replica-replicated)
    store shard — the 24× single-chip kernel, per shard. The partial
    stores of a key column then differ only where a device adopted a
    remote record, and the final store is their lexicographic
    ``(lt, node)`` maximum: the same pmax → masked pmax → stable pmin
    rank → one-hot psum reduction the XLA block uses, applied to 2
    int64 + 1 int32 + 2 small lanes instead of the full R-row
    changeset. Winner ``modified`` lanes are re-stamped with the
    GLOBAL post-absorption canonical (the kernel's device-local stamp
    is discarded), so lanes match the single-device executor
    bit-for-bit.

    Guard flags are the kernel contract's closed-form optimistic
    superset (`pallas_fanin_batch` docstring): a local-node record
    above the pre-merge canonical, or any record past the drift
    threshold, pmaxed over the mesh. The model recomputes exactly on
    host when one trips (`DenseCrdt._exact_guards`), so spurious flags
    never reject a merge and raised exceptions keep first-offender
    parity.
    """
    from ..ops.pallas_merge import (join_store, pallas_fanin_batch,
                                    split_changeset, split_store)
    all_axes = replica_axes + (KEY_AXIS,)

    # --- closed-form guard bounds + canonical absorption: both ride
    # ONE two-lane pmax over the whole mesh ---
    masked_lt = jnp.where(cs.valid, cs.lt, _NEG)
    local_max = jnp.max(masked_lt)
    m_loc = jnp.max(jnp.where(cs.valid & (cs.node == local_node),
                              cs.lt, _NEG))
    g = jax.lax.pmax(jnp.stack([local_max, m_loc]), all_axes)
    g_max, g_loc = g[0], g[1]
    new_canonical = jnp.maximum(canonical_lt, g_max)
    any_dup = g_loc > canonical_lt
    thresh = ((wall_millis + MAX_DRIFT) << SHIFT) | MAX_COUNTER
    any_drift = g_max > thresh
    any_bad = any_dup | any_drift

    # --- per-shard Mosaic kernel merge (store shard is replicated
    # across the replica axes; each device folds only its own rows) ---
    sst = split_store.__wrapped__(store)
    scs = split_changeset.__wrapped__(cs)
    out, pres = pallas_fanin_batch.__wrapped__(
        sst, scs, canonical_lt, local_node, wall_millis,
        chunk_rows=chunk_rows, interpret=interpret)
    partial = join_store.__wrapped__(out)

    # --- cross-device lexicographic (lt, node) max over the partial
    # stores. Adoption in-kernel is strictly greater than the store
    # record, so every partial >= the store record and the max IS the
    # full join; ties pick the lowest flat rank (earliest replica
    # rows — sequential-merge parity, matching the in-kernel strict
    # compare that keeps the earliest row). ---
    p_lt = jnp.where(partial.occupied, partial.lt, _NEG)
    m1 = jax.lax.pmax(p_lt, replica_axes)
    node_cand = jnp.where(p_lt == m1, partial.node, _I32_NEG)
    m2 = jax.lax.pmax(node_cand, replica_axes)
    has = (p_lt == m1) & (partial.node == m2)
    rank = _flat_rank(replica_axes)
    winner_rank = jax.lax.pmin(jnp.where(has, rank, _BIG_RANK),
                               replica_axes)
    mine = has & (rank == winner_rank)
    g_val = jax.lax.psum(jnp.where(mine, partial.val, 0), replica_axes)
    g_tomb = jax.lax.psum(
        jnp.where(mine, partial.tomb, False).astype(jnp.int32),
        replica_axes) > 0
    # A slot was adopted iff the winning device's kernel adopted it
    # (devices that kept the store lose the rank tie or the lex max).
    win = jax.lax.psum(
        jnp.where(mine, pres.win, False).astype(jnp.int32),
        replica_axes) > 0

    new_store = DenseStore(
        lt=jnp.where(win, m1, store.lt),
        node=jnp.where(win, m2, store.node),
        val=jnp.where(win, g_val, store.val),
        mod_lt=jnp.where(win, new_canonical, store.mod_lt),
        mod_node=jnp.where(win, local_node, store.mod_node),
        occupied=store.occupied | win,
        tomb=jnp.where(win, g_tomb, store.tomb),
    )
    win_count = jax.lax.psum(jnp.sum(win).astype(jnp.int32), KEY_AXIS)
    return new_store, ShardedFaninResult(
        new_canonical=new_canonical, win_count=win_count, win=win,
        any_bad=any_bad, any_dup=any_dup, any_drift=any_drift)


def make_sharded_pallas_fanin(mesh: Mesh, *, chunk_rows: int = 8,
                              interpret: bool = False):
    """`make_sharded_fanin` with the per-device reduce running through
    the Mosaic batch kernel (`_pallas_fanin_block`) — the single-chip
    headline executor inside the multi-chip collective step.

    Requirements beyond the XLA step: each device's key shard must be
    a multiple of `ops.pallas_merge.TILE`, changeset replica rows must
    pad to ``replica_extent(mesh) * chunk_rows``, and node ordinals
    must fit the kernel's int16 wire lane (the model layer gates all
    three — `ShardedDenseCrdt._use_pallas_sharded`). ``interpret=True``
    runs the kernel in Pallas interpret mode for non-TPU meshes (the
    virtual-CPU validation path).
    """
    from functools import partial
    replica_axes = _replica_axes(mesh)
    step = _shard_map(
        partial(_pallas_fanin_block, replica_axes, chunk_rows, interpret),
        mesh=mesh,
        in_specs=(
            DenseStore(*([P(KEY_AXIS)] * len(DenseStore._fields))),
            DenseChangeset(*([P(replica_axes, KEY_AXIS)]
                             * len(DenseChangeset._fields))),
            P(), P(), P(),
        ),
        out_specs=(
            DenseStore(*([P(KEY_AXIS)] * len(DenseStore._fields))),
            ShardedFaninResult(
                new_canonical=P(), win_count=P(), win=P(KEY_AXIS),
                any_bad=P(), any_dup=P(), any_drift=P()),
        ),
        check_vma=False,
    )
    return _record_step("parallel.sharded_pallas_fanin", jax.jit(step))


def _record_step(kernel: str, jitted, *, donated_store: bool = False,
                 dim_arg: int = 0):
    """Wrap a factory-built jitted step in a ledger-recording closure.
    ``dim_arg`` picks the positional arg whose store/batch leading dim
    feeds the compile census; ``donated_store`` marks arg 0's ``lt``
    lane for post-call donation checking."""

    @functools.wraps(jitted)
    def step(*args, **kw):
        ref = args[dim_arg]
        dim = ref.lt.shape[0] if hasattr(ref, "lt") else ref.shape[0]
        donated = args[0].lt if donated_store else None
        with _obs_device.record(kernel, dim=dim, donated=donated):
            return jitted(*args, **kw)

    return step


def make_sharded_fanin(mesh: Mesh):
    """Build the jitted sharded fan-in step for a mesh.

    Returns ``step(store, cs, canonical_lt, local_node, wall_millis) ->
    (new_store, ShardedFaninResult)`` with the store sharded by
    ``store_sharding(mesh)`` and changesets by
    ``changeset_sharding(mesh)``.
    """
    from functools import partial
    replica_axes = _replica_axes(mesh)
    step = _shard_map(
        partial(_fanin_block, replica_axes),
        mesh=mesh,
        in_specs=(
            DenseStore(*([P(KEY_AXIS)] * len(DenseStore._fields))),
            DenseChangeset(*([P(replica_axes, KEY_AXIS)]
                             * len(DenseChangeset._fields))),
            P(), P(), P(),
        ),
        out_specs=(
            DenseStore(*([P(KEY_AXIS)] * len(DenseStore._fields))),
            ShardedFaninResult(
                new_canonical=P(), win_count=P(), win=P(KEY_AXIS),
                any_bad=P(), any_dup=P(), any_drift=P()),
        ),
        check_vma=False,
    )
    return _record_step("parallel.sharded_fanin", jax.jit(step))


@functools.lru_cache(maxsize=None)
def make_sharded_ingest(mesh: Mesh, donate: bool = False):
    """ONE shard_map program for the write combiner's commit scatter:
    every device translates the (replicated) global slot batch to its
    key-shard's local rows and applies the blind ingest overwrite
    (`ops.dense.ingest_scatter` semantics) — out-of-shard and sentinel
    rows drop. Replaces the unsharded scatter + per-lane re-shard
    round-trip (`shard_store`) that used to cost a dispatch per lane.

    Returns ``step(store, slot, lt, val, tomb, me) -> new_store`` with
    the store sharded by ``store_sharding(mesh)`` and the batch lanes
    replicated. ``donate=True`` consumes the store buffers in place
    (the model layer gates donation exactly as for merges)."""

    def _ingest_block(store: DenseStore, slot, lt, val, tomb, me
                      ) -> DenseStore:
        n_local = store.lt.shape[0]
        loc = slot - jax.lax.axis_index(KEY_AXIS) * n_local
        # Rows outside this shard (and the caller's n_slots pad
        # sentinel) land out of range and drop.
        loc = jnp.where((loc < 0) | (loc >= n_local), n_local,
                        loc).astype(jnp.int32)
        return DenseStore(
            lt=store.lt.at[loc].set(lt, mode="drop"),
            node=store.node.at[loc].set(me, mode="drop"),
            val=store.val.at[loc].set(val, mode="drop"),
            mod_lt=store.mod_lt.at[loc].set(lt, mode="drop"),
            mod_node=store.mod_node.at[loc].set(me, mode="drop"),
            occupied=store.occupied.at[loc].set(True, mode="drop"),
            tomb=store.tomb.at[loc].set(tomb, mode="drop"))

    step = _shard_map(
        _ingest_block, mesh=mesh,
        in_specs=(
            DenseStore(*([P(KEY_AXIS)] * len(DenseStore._fields))),
            P(), P(), P(), P(), P(),
        ),
        out_specs=DenseStore(*([P(KEY_AXIS)] * len(DenseStore._fields))),
        check_vma=False,
    )
    return _record_step(
        "parallel.sharded_ingest",
        jax.jit(step, donate_argnums=(0,) if donate else ()),
        donated_store=donate, dim_arg=1)


def sharded_delta_mask(mesh: Mesh):
    """modifiedSince filter over the sharded store — INCLUSIVE bound
    (map_crdt.dart:44-45), computed shard-local (no collectives)."""

    def _mask(store: DenseStore, since_lt: jax.Array) -> jax.Array:
        return store.occupied & (store.mod_lt >= since_lt)

    return _record_step("parallel.sharded_delta_mask", jax.jit(_shard_map(
        _mask, mesh=mesh,
        in_specs=(DenseStore(*([P(KEY_AXIS)] * len(DenseStore._fields))),
                  P()),
        out_specs=P(KEY_AXIS),
        check_vma=False,
    )))


@functools.lru_cache(maxsize=None)
def make_sharded_digest(mesh: Mesh, leaf_width: int, has_sem: bool):
    """Merkle digest-tree levels over the sharded store
    (docs/ANTIENTROPY.md): per-shard subtree leaves computed
    shard-local — slot digests mixed against GLOBAL positions via the
    key-axis offset — fan in along the key axis, then the interior
    combines fold in the SAME jitted program (GSPMD inserts the
    gather; the leaf row is tiny next to the lanes). Requires the
    shard width to be a multiple of ``leaf_width`` so leaf boundaries
    never straddle shards; `ShardedDenseCrdt._digest_levels` falls
    back to the single-program reduction otherwise. Levels are
    bit-identical to the unsharded `ops.digest.digest_tree_device`."""
    from ..ops.digest import (fold_leaves, slot_digests,
                              tree_levels_from_leaves)

    def _leaves(store: DenseStore, *sem):
        shard = store.lt.shape[0]
        if shard % leaf_width:
            raise ValueError(
                f"shard width {shard} not a multiple of leaf_width "
                f"{leaf_width}")
        off = (jax.lax.axis_index(KEY_AXIS).astype(jnp.uint64)
               * jnp.uint64(shard))
        h = slot_digests(store.lt, store.val, store.tomb,
                         store.occupied,
                         sem=sem[0] if has_sem else None,
                         idx_offset=off)
        return fold_leaves(h, leaf_width)

    store_spec = DenseStore(*([P(KEY_AXIS)] * len(DenseStore._fields)))
    in_specs = ((store_spec, P(KEY_AXIS)) if has_sem
                else (store_spec,))
    leaves = _shard_map(_leaves, mesh=mesh, in_specs=in_specs,
                        out_specs=P(KEY_AXIS), check_vma=False)

    def step(store: DenseStore, *sem):
        return tree_levels_from_leaves(leaves(store, *sem))

    return _record_step("parallel.sharded_digest", jax.jit(step))


@functools.lru_cache(maxsize=None)
def make_sharded_compact(mesh: Mesh, leaf_width: int, has_sem: bool,
                         donate: bool = False):
    """Whole-store online compaction over the sharded store, ONE
    shard_map program (docs/STORAGE.md): every device packs its key
    shard's surviving rows to the SHARD-LOCAL prefix — the global
    remap is per-shard and never crosses shard boundaries — emits its
    rows of the global translation table, and rebuilds its digest
    leaves against global positions (`idx_offset`, exactly like
    `make_sharded_digest`); the interior combines fold in the same
    jitted program. Requires each shard a multiple of ``leaf_width``;
    `ShardedDenseCrdt.compact` falls back to the base single-program
    kernel otherwise.

    Returns ``step(store[, sem]) -> (new_store[, new_sem],
    translation, levels)`` where ``translation[old] = new`` global
    slot for occupied rows, ``-1`` for empty slots, and ``levels`` are
    root-first digest-tree levels bit-identical to what
    `ops.digest.digest_tree_device` would build over the compacted
    store. ``donate=True`` consumes the store buffers in place."""
    from ..ops.digest import (fold_leaves, slot_digests,
                              tree_levels_from_leaves)

    def _local(store: DenseStore, *sem):
        shard = store.lt.shape[0]
        if shard % leaf_width:
            raise ValueError(
                f"shard width {shard} not a multiple of leaf_width "
                f"{leaf_width}")
        idx = jnp.arange(shard, dtype=jnp.int64)
        off = jax.lax.axis_index(KEY_AXIS).astype(jnp.int64) * shard
        keep = store.occupied
        rank = jnp.cumsum(keep.astype(jnp.int64)) - 1
        new_local = jnp.where(keep, rank, idx)
        translation = jnp.where(keep, new_local + off,
                                -1).astype(jnp.int32)
        # Empty rows scatter to the `shard` sentinel and drop; the
        # zeros base IS the compacted tail.
        target = jnp.where(keep, new_local, shard).astype(jnp.int32)

        def scat(lane):
            return jnp.zeros(lane.shape, lane.dtype).at[target].set(
                lane, mode="drop")

        out = DenseStore(lt=scat(store.lt), node=scat(store.node),
                         val=scat(store.val), mod_lt=scat(store.mod_lt),
                         mod_node=scat(store.mod_node),
                         occupied=scat(store.occupied),
                         tomb=scat(store.tomb))
        new_sem = (scat(sem[0]),) if has_sem else ()
        offu = (jax.lax.axis_index(KEY_AXIS).astype(jnp.uint64)
                * jnp.uint64(shard))
        h = slot_digests(out.lt, out.val, out.tomb, out.occupied,
                         sem=new_sem[0] if has_sem else None,
                         idx_offset=offu)
        return (out,) + new_sem + (translation, fold_leaves(h, leaf_width))

    store_spec = DenseStore(*([P(KEY_AXIS)] * len(DenseStore._fields)))
    in_specs = ((store_spec, P(KEY_AXIS)) if has_sem
                else (store_spec,))
    out_specs = ((store_spec,)
                 + ((P(KEY_AXIS),) if has_sem else ())
                 + (P(KEY_AXIS), P(KEY_AXIS)))
    local = _shard_map(_local, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)

    def step(store: DenseStore, *sem):
        parts = local(store, *sem)
        return parts[:-1] + (tree_levels_from_leaves(parts[-1]),)

    return _record_step(
        "parallel.sharded_compact",
        jax.jit(step, donate_argnums=(0,) if donate else ()),
        donated_store=donate)


def sharded_max_logical_time(mesh: Mesh):
    """refreshCanonicalTime's reduction over the sharded store
    (crdt.dart:114-121): shard-local max, then one pmax over the mesh."""

    def _max(store: DenseStore) -> jax.Array:
        local = jnp.max(jnp.where(store.occupied, store.lt, 0))
        return jax.lax.pmax(local, mesh.axis_names)

    return _record_step(
        "parallel.sharded_max_logical_time",
        jax.jit(_shard_map(
            _max, mesh=mesh,
            in_specs=(DenseStore(*([P(KEY_AXIS)]
                                   * len(DenseStore._fields))),),
            out_specs=P(),
            check_vma=False,
        )))

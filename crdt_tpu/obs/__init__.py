"""Unified telemetry for the CRDT runtime (SURVEY.md §5: the reference
has no tracing/metrics at all; ROADMAP's production north star needs
both).

Three cooperating layers, all dependency-free (stdlib + the jax the
package already requires):

- :mod:`~crdt_tpu.obs.registry` — a process-wide **metrics registry**
  (counters, gauges, log2-bucket histograms; thread-safe). The legacy
  counter dataclasses (`MergeStats`, `PeerSyncStats`, `WireTally`)
  attach to it as weak-referenced collectors, so every live backend,
  peer and wire endpoint shows up in one snapshot instead of three
  orphan objects.
- :mod:`~crdt_tpu.obs.trace` — **HLC-stamped structured trace events**
  (merge dispatch, gossip round, wire frame, checkpoint, breaker
  transition) in a bounded in-memory ring with an optional JSONL sink;
  `span()` threads `jax.profiler.TraceAnnotation` through the
  merge/pack/wire phases so TPU profiles show named kernels. Disabled
  by default — the hot path pays one attribute read.
- :mod:`~crdt_tpu.obs.lag` — the **convergence-lag monitor**: per-peer
  staleness (local HLC head minus peer watermark, in millis and
  pending records) derived from `GossipNode` watermarks; surfaced as
  ``node.health()`` and over the wire via the `SyncServer` ``metrics``
  op.

Fleet plane (PR 11): :mod:`~crdt_tpu.obs.probe` writes timestamped
canary beats into a reserved slot range through the ordinary write
path; :mod:`~crdt_tpu.obs.fleet` scrapes N replicas' ``metrics`` ops
into a per-(origin, observer) replication-lag matrix and a
machine-readable SLO verdict (``python -m crdt_tpu.obs fleet``); the
``trace`` hello capability (net.py) carries round ids across the wire
so initiator sync spans and responder merge spans correlate in one
JSONL sink.

Device plane (PR 12): :mod:`~crdt_tpu.obs.device` is the **dispatch
ledger** — every jit-cached device entry point (ops + parallel)
reports per-kernel dispatch counts, wall-time histograms, a
(kernel, pow2-bucket) compile census, donation-violation checks, and
a store-bytes gauge, turning the fast-path zero-dispatch invariants
into runtime-observable metrics; :mod:`~crdt_tpu.obs.trajectory`
normalizes every ``bench.py`` run into one
``benchmarks/history/trajectory.jsonl`` record and verdicts the
newest run against fastest-of-N floors (``python -m crdt_tpu.obs
bench --compare``), the CI regression gate.

Quantile plane (PR 18): :mod:`~crdt_tpu.obs.sketch` is a mergeable
DDSketch-style **relative-error quantile sketch** — the registry's
``sketch()`` instrument records latencies next to the log2 histograms
but answers quantiles within ~1% instead of bucket ceilings, merges
commutatively/associatively across replicas (``obs/fleet.py`` folds
per-replica sketches into fleet-true p99 for ``evaluate_slo`` and the
autoscaler's 14.6 ms gate), and ships on the ``metrics`` op behind
the negotiated ``sketch`` hello cap. :mod:`~crdt_tpu.obs.recorder` is
the **SLO flight recorder**: bounded debug bundles captured when the
SLO flips to failing, the lease fence trips, or the deadlock
sanitizer fires — fetched later via the ``debug_dump`` wire op /
``python -m crdt_tpu.obs dump``.

Exposition: :func:`~crdt_tpu.obs.render.render_prometheus` renders a
snapshot as Prometheus text; ``python -m crdt_tpu.obs`` polls a live
node's ``metrics`` op or summarizes a trace JSONL into a per-phase
latency table (see docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       Sketch, default_registry)
from .sketch import QuantileSketch, merge_sketches, sketch_quantile
from .recorder import FlightRecorder, default_recorder
from .trace import TraceRing, round_id, span, tracer
from .lag import health_status, lag_entry, lag_millis
from .probe import CanaryProbe, canary_observed
from .fleet import (evaluate_slo, format_matrix, lag_matrix,
                    poll_fleet, render_federation)
from .device import DispatchLedger, default_ledger, pow2_bucket
from .trajectory import (append_record, compare, load_trajectory,
                         normalize_record)
from .render import (format_phase_table, render_prometheus,
                     render_summary, summarize_trace)


def metrics_snapshot() -> dict:
    """One self-describing snapshot of the process-wide registry — the
    payload the `SyncServer` ``metrics`` wire op returns."""
    return default_registry().snapshot()


__all__ = [
    "Counter", "Gauge", "Histogram", "Sketch", "MetricsRegistry",
    "default_registry", "metrics_snapshot",
    "QuantileSketch", "merge_sketches", "sketch_quantile",
    "FlightRecorder", "default_recorder",
    "TraceRing", "tracer", "span", "round_id",
    "lag_millis", "lag_entry", "health_status",
    "CanaryProbe", "canary_observed",
    "poll_fleet", "lag_matrix", "evaluate_slo", "render_federation",
    "format_matrix",
    "DispatchLedger", "default_ledger", "pow2_bucket",
    "normalize_record", "append_record", "load_trajectory", "compare",
    "render_prometheus", "render_summary", "summarize_trace",
    "format_phase_table",
]

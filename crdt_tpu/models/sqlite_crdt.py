"""SQLite-backed persistent CRDT — the out-of-tree plugin pattern,
in-tree.

The reference documents persistent backends as `Crdt` subclasses built
outside the package (README.md:39 points at hive_crdt; the abstract
storage slots at crdt.dart:140-169 are the plugin contract, and the
`modified` field exists precisely so such backends can answer delta
queries, CHANGELOG.md:14-15). This module is that pattern realized on
Python's stdlib `sqlite3`: a durable replica that speaks the same wire
format, runs the same conformance suite, and can sync with any other
backend (`MapCrdt`, `TpuMapCrdt`, `DenseCrdt`) or an external JSON
peer.

Storage model — one table, one row per record:

- ``hlc``/``modified`` persist through the reference string codec
  (hlc.dart:102-104), so a row is meaningful to any replica.
- ``lt``/``modified_lt`` are the packed 64-bit logicalTimes
  (hlc.dart:16) as INTEGER columns: ``refresh_canonical_time`` is
  ``MAX(lt)`` (the efficient override the reference invites,
  crdt.dart:113) and the inclusive delta bound (map_crdt.dart:44-45)
  is an indexed ``modified_lt >= ?`` scan.
- ``value`` is JSON text; SQL ``NULL`` is the tombstone
  (record.dart:17). Custom value types plug in via
  ``value_encoder``/``value_decoder`` (record.dart:3-9 typedefs).

Resume-from-disk is the constructor: opening an existing database file
seeds the canonical clock from the stored max (crdt.dart:31-33).
"""

from __future__ import annotations

import json
import sqlite3
from typing import Any, Callable, Dict, Generic, Optional, TypeVar

from ..crdt import Crdt
from ..hlc import Hlc
from ..record import Record
from ..utils.stats import MergeStats
from ..watch import ChangeHub, ChangeStream

K = TypeVar("K")
V = TypeVar("V")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS records (
    key TEXT PRIMARY KEY,
    hlc TEXT NOT NULL,
    lt INTEGER NOT NULL,
    value TEXT,
    modified TEXT NOT NULL,
    modified_lt INTEGER NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_records_modified_lt
    ON records (modified_lt);
"""


class SqliteCrdt(Crdt[K, V], Generic[K, V]):
    """A durable LWW-map CRDT on a SQLite database.

    ``path=":memory:"`` (the default) is an ephemeral store useful for
    tests; a filesystem path makes the replica survive restarts —
    reconstructing is just ``SqliteCrdt(node_id, path)`` again.

    Like the reference's single-isolate model, a replica instance is
    single-threaded (sqlite3's default ``check_same_thread`` guard is
    left on); cross-thread consumption happens through the watch
    streams (`watch().aiter()` marshals onto the consumer's loop).
    """

    def __init__(self, node_id: Any, path: str = ":memory:", *,
                 wall_clock: Optional[Callable[[], int]] = None,
                 key_encoder: Optional[Callable[[K], str]] = None,
                 key_decoder: Optional[Callable[[str], K]] = None,
                 value_encoder: Optional[Callable[[V], Any]] = None,
                 value_decoder: Optional[Callable[[Any], V]] = None,
                 node_decoder: Optional[Callable[[str], Any]] = None,
                 check_same_thread: bool = True):
        self._node_id = node_id
        # check_same_thread=False is required to serve this replica
        # from another thread (e.g. `crdt_tpu.net.SyncServer`); the
        # single-threaded-replica contract still applies — ALL access
        # must be externally serialized (the server's lock does this).
        self._conn = sqlite3.connect(
            path, check_same_thread=check_same_thread)
        # WAL + NORMAL: writers don't block readers and a commit costs
        # one WAL append instead of a full journal round trip. Power
        # loss can drop the tail of the WAL but never corrupts — a
        # replica restarting after a crash just re-syncs the lost tail
        # (merge is idempotent; that recovery story is the whole point
        # of the CRDT). No-op on :memory: databases.
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.executescript(_SCHEMA)
        self._key_enc = key_encoder or str
        self._key_dec = key_decoder or (lambda s: s)
        self._val_enc = value_encoder or (lambda v: v)
        self._val_dec = value_decoder or (lambda v: v)
        # HLC node ids persist as text; without a decoder a non-str
        # node_id would parse back as str and break tie-break compares
        # and duplicate-node detection against the typed canonical
        # clock. Default to the node_id's own type (int("7") etc.);
        # exotic types must pass node_decoder explicitly.
        if node_decoder is None and not isinstance(node_id, str):
            node_decoder = type(node_id)
        self._node_dec = node_decoder
        self._hub = ChangeHub()
        self.stats = MergeStats().register(backend="SqliteCrdt",
                                           node=str(node_id))
        super().__init__(wall_clock=wall_clock)

    @property
    def node_id(self) -> Any:
        return self._node_id

    def close(self) -> None:
        self._conn.close()

    def __enter__(self) -> "SqliteCrdt[K, V]":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- codecs ---

    def _encode_row(self, key: K, record: Record[V]):
        value = (None if record.value is None
                 else json.dumps(self._val_enc(record.value)))
        return (self._key_enc(key), str(record.hlc),
                record.hlc.logical_time, value, str(record.modified),
                record.modified.logical_time)

    def _decode_row(self, row) -> Record[V]:
        _, hlc, _, value, modified, _ = row
        return Record(
            Hlc.parse(hlc, id_decoder=self._node_dec),
            None if value is None else self._val_dec(json.loads(value)),
            Hlc.parse(modified, id_decoder=self._node_dec))

    # --- efficient clock rebuild (crdt.dart:113: "should be overridden
    # if the implementation can do it more efficiently") ---

    def refresh_canonical_time(self) -> None:
        (max_lt,) = self._conn.execute(
            "SELECT COALESCE(MAX(lt), 0) FROM records").fetchone()
        self._canonical_time = Hlc.from_logical_time(max_lt, self._node_id)

    # --- storage primitives (crdt.dart:140-169) ---

    def contains_key(self, key: K) -> bool:
        return self._conn.execute(
            "SELECT 1 FROM records WHERE key = ?",
            (self._key_enc(key),)).fetchone() is not None

    def get_record(self, key: K) -> Optional[Record[V]]:
        row = self._conn.execute(
            "SELECT * FROM records WHERE key = ?",
            (self._key_enc(key),)).fetchone()
        return None if row is None else self._decode_row(row)

    # Upsert (NOT "INSERT OR REPLACE", which deletes + reinserts and so
    # reassigns the rowid): existing keys keep their rowid, making
    # `ORDER BY rowid` the dict-like first-insertion iteration order —
    # wire bytes match the in-memory backends op-for-op.
    _UPSERT = (
        "INSERT INTO records VALUES (?, ?, ?, ?, ?, ?) "
        "ON CONFLICT(key) DO UPDATE SET hlc=excluded.hlc, "
        "lt=excluded.lt, value=excluded.value, "
        "modified=excluded.modified, modified_lt=excluded.modified_lt")

    def put_record(self, key: K, record: Record[V]) -> None:
        with self._conn:
            self._conn.execute(self._UPSERT, self._encode_row(key, record))
        self._hub.add(key, record.value)

    def put_records(self, record_map: Dict[K, Record[V]]) -> None:
        with self._conn:
            self._conn.executemany(
                self._UPSERT,
                [self._encode_row(k, r) for k, r in record_map.items()])
        for key, record in record_map.items():
            self._hub.add(key, record.value)

    def merge_json(self, json_str: str,
                   key_decoder=None, value_decoder=None) -> None:
        """Columnar wire ingest: C batch HLC parse → vectorized recv
        guards + LWW against a keyed O(delta) lookup → ONE
        executemany upsert in ONE transaction. No `Record`/`Hlc`
        objects on the hot path (crdt.dart:100-109 surface at
        numpy+SQL speed). Rows are record-equivalent to the generic
        path: identical canonical hlc/modified strings and lt columns;
        the value column's JSON text is compact/raw-UTF-8 here vs
        json.dumps defaults there — both parse to the same value
        (pinned by the path differential test).

        Falls back to the generic object path when the native codec is
        unavailable — semantics are identical either way, and the
        wall-clock tick count matches the generic path in both
        branches (the `_decode_wall_millis` accounting contract)."""
        from .. import crdt_json, native
        codec = native.load()
        if codec is None:
            return super().merge_json(json_str,
                                      key_decoder=key_decoder,
                                      value_decoder=value_decoder)
        self._decode_wall_millis()
        keys, lt, nodes, values, hlc_strs = crdt_json.decode_columns(
            json_str, key_decoder=key_decoder,
            value_decoder=value_decoder,
            node_id_decoder=self._node_dec,
            with_hlc_strs=True)
        if not keys:
            self.merge({})
            return
        self._merge_columns(keys, lt, nodes, values, hlc_strs,
                            self._wall_clock())

    def _merge_columns(self, keys, lt, nodes, values, hlc_strs,
                       wall: int) -> None:
        from ..utils.stats import merge_annotation
        with merge_annotation("crdt_tpu.sqlite_merge",
                              hlc=lambda: self._canonical_time):
            self._merge_columns_impl(keys, lt, nodes, values,
                                     hlc_strs, wall)

    def _merge_columns_impl(self, keys, lt, nodes, values, hlc_strs,
                            wall: int) -> None:
        import numpy as np

        from ..hlc import (MAX_COUNTER, SHIFT, ClockDriftException,
                           DuplicateNodeException)
        from ..utils.host_guards import recv_fold_columns

        # --- stage 1: recv fold + guards in payload visit order
        # (the shared host fold, utils/host_guards.py).
        local_mask = np.fromiter((n == self._node_id for n in nodes),
                                 bool, count=len(nodes))
        fold = recv_fold_columns(lt, local_mask,
                                 self._canonical_time.logical_time, wall)
        if fold.bad_index is not None:
            self._canonical_time = Hlc.from_logical_time(
                fold.canonical_at_fail, self._node_id)
            if fold.bad_is_dup:
                raise DuplicateNodeException(str(self._node_id))
            raise ClockDriftException(
                int(lt[fold.bad_index]) >> SHIFT, wall)
        new_canonical = fold.new_canonical

        # --- stage 2: LWW vs the local rows, O(delta) keyed lookup.
        kenc = self._key_enc
        # Wire keys are already str; the default encoder (str) is then
        # an identity pass worth skipping at 1M-key scale.
        enc_keys = (keys if kenc is str
                    and all(type(k) is str for k in keys)
                    else [kenc(k) for k in keys])
        local: Dict[str, tuple] = {}
        # Cold sync into an empty replica (first contact) skips the
        # keyed probes entirely — one EXISTS beats N/500 IN-queries.
        if self._conn.execute(
                "SELECT EXISTS(SELECT 1 FROM records)").fetchone()[0]:
            for row in self._rows_for_keys(enc_keys, "key, lt, hlc"):
                local[row[0]] = (row[1], row[2])
        win = np.ones(len(keys), bool)
        if local:   # all-new-key merges skip the compare entirely
            get = local.get
            for i, ek in enumerate(enc_keys):
                loc = get(ek)
                if loc is None:
                    continue
                l_lt = loc[0]
                r_lt = int(lt[i])
                if r_lt < l_lt:
                    win[i] = False
                elif r_lt == l_lt:
                    # logicalTime tie: node id breaks it, typed compare
                    # (hlc.dart:158-161); local wins the exact tie.
                    l_node = self._parse_node(loc[1])
                    if self._node_dec is not None:
                        l_node = self._node_dec(l_node)
                    win[i] = nodes[i] > l_node

        # --- stage 3: one-transaction columnar upsert of the winners.
        widx = np.nonzero(win)[0]
        self.stats.merges += 1
        self.stats.add_seen_lazy(len(keys))
        self.stats.add_adopted_lazy(int(widx.size))
        if widx.size:
            import itertools

            from .. import native
            codec = native.load()
            all_win = widx.size == len(keys)
            win_list = widx.tolist()
            w_lt = lt if all_win else lt[widx]
            w_keys = enc_keys if all_win else [enc_keys[i]
                                              for i in win_list]
            w_nodes = (nodes if all_win
                       else [nodes[i] for i in win_list])
            w_vals = (values if all_win
                      else [values[i] for i in win_list])
            w_hlcs = (hlc_strs if all_win
                      else [hlc_strs[i] for i in win_list])
            if None in w_hlcs:
                # Items without a certified raw wire string (escaped /
                # non-canonical / out-of-window shapes): re-derive via
                # the batch formatter, then the slow formatter for
                # whatever IT defers.
                w_ms = (w_lt >> SHIFT).tolist()
                w_ctr = (w_lt & MAX_COUNTER).tolist()
                fmt = codec.format_hlc_batch(
                    w_ms, w_ctr,
                    [n if type(n) is str else str(n) for n in w_nodes])
                w_hlcs = [h if h is not None
                          else (f if f is not None
                                else str(Hlc._raw(m, c, n)))
                          for h, f, m, c, n in zip(w_hlcs, fmt, w_ms,
                                                   w_ctr, w_nodes)]
            mod = Hlc.from_logical_time(new_canonical, self._node_id)
            mod_str, mod_lt = str(mod), mod.logical_time
            # C batch value JSON (compact text; the generic path's
            # default-separator dumps parses identically) — per-value
            # json.dumps was the single largest ingest cost.
            enc = self._val_enc
            texts = codec.dump_values(
                [None if v is None else enc(v) for v in w_vals],
                json.dumps)
            rows = zip(w_keys, w_hlcs, w_lt.tolist(),
                       (None if v is None else t
                        for v, t in zip(w_vals, texts)),
                       itertools.repeat(mod_str),
                       itertools.repeat(mod_lt))
            with self._conn:
                self._conn.executemany(self._UPSERT, rows)
            if self._hub.active:
                # Batch emission (hub.add_batch contract): keyed
                # streams answered from a lazily-built winner dict,
                # never a per-record hub.add loop.
                win_map = None

                def get(k):
                    nonlocal win_map
                    if win_map is None:
                        win_map = {keys[i]: values[i] for i in win_list}
                    if k in win_map:
                        return True, win_map[k]
                    return False, None

                if all_win:
                    # crdtlint: disable=add-batch-unique-keys -- merge payloads are dict-keyed record maps: keys cannot repeat
                    self._hub.add_batch(lambda: (keys, values), get)
                else:
                    # crdtlint: disable=add-batch-unique-keys -- merge payloads are dict-keyed record maps: keys cannot repeat
                    self._hub.add_batch(
                        lambda: ([keys[i] for i in win_list],
                                 [values[i] for i in win_list]), get)

        self._canonical_time = Hlc.send(
            Hlc.from_logical_time(new_canonical, self._node_id),
            millis=self._wall_clock())

    @staticmethod
    def _parse_node(hlc_str: str):
        """Node id from a stored hlc string — the reference scan
        (first dash after the last colon ends the ISO time, the next
        ends the counter, hlc.dart:40-44); typed via Hlc.parse's
        decoder contract is not needed here because tie-breaks compare
        against the already-typed wire node."""
        counter_dash = hlc_str.index("-", hlc_str.rfind(":"))
        return hlc_str[hlc_str.index("-", counter_dash + 1) + 1:]

    def _rows_for_keys(self, encoded_keys, columns: str = "*"):
        """Yield the stored rows for the given ENCODED keys, batched
        under SQLite's host-parameter cap — the one keyed O(delta)
        lookup shared by the merge paths."""
        for i in range(0, len(encoded_keys), 500):
            batch = encoded_keys[i:i + 500]
            yield from self._conn.execute(
                f"SELECT {columns} FROM records WHERE key IN "
                f"({','.join('?' * len(batch))})", batch)

    def _local_records_for(self, keys) -> Dict[K, Record[V]]:
        # Keyed lookup so delta merges are O(delta) rows, not a full
        # table scan+parse (the whole point of a beyond-memory store).
        return {self._key_dec(row[0]): self._decode_row(row)
                for row in self._rows_for_keys(
                    [self._key_enc(k) for k in keys])}

    def record_map(self, modified_since: Optional[Hlc] = None
                   ) -> Dict[K, Record[V]]:
        if modified_since is None:
            # No WHERE clause: a `>= 0` default would silently drop rows
            # whose modified HLC has pre-epoch (negative) millis —
            # reachable via the public put_record primitive, where the
            # reference recordMap() returns all records.
            rows = self._conn.execute(
                "SELECT * FROM records ORDER BY rowid")
        else:
            rows = self._conn.execute(
                "SELECT * FROM records WHERE modified_lt >= ? "
                "ORDER BY rowid", (modified_since.logical_time,))
        return {self._key_dec(row[0]): self._decode_row(row)
                for row in rows}

    def count_modified_since(self, modified_since: Optional[Hlc] = None
                             ) -> int:
        """Delta-backlog size straight off the ``modified_lt`` index —
        lag monitoring never parses a row."""
        if modified_since is None:
            # Same no-WHERE rationale as record_map: pre-epoch rows
            # must count.
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM records").fetchone()
        else:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM records WHERE modified_lt >= ?",
                (modified_since.logical_time,)).fetchone()
        return n

    def watch(self, key: Optional[K] = None) -> ChangeStream:
        return self._hub.stream(key)

    def purge(self) -> None:
        with self._conn:
            self._conn.execute("DELETE FROM records")

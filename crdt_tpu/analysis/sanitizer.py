"""Opt-in runtime lattice sanitizer (``CRDT_TPU_SANITIZE=1``).

The static auditors prove properties of the KERNELS; the sanitizer
checks the deployed COMPOSITION: after every merge the store must
dominate the merged payload in the (logical_time, node) lattice order
— stored >= remote for every record the merge saw. That is the
post-state every correct join leaves regardless of who won (local
winners already dominated; adopted remotes dominate by construction),
so it holds across backends, executors, and overflow masking — and a
merge that drops, reorders, or double-applies records breaks it.

Checks are O(merged batch) numpy sweeps on data the merge already
materialized, so soak tests double as dynamic checkers at tolerable
cost — but the mode stays opt-in (env var read LIVE, so a test can
flip it per-case).

Scope notes:

- The DenseCrdt PIPELINED path is exempt by contract: it keeps
  everything on device with zero host syncs per merge, which is the
  entire point of the pipeline — a host-side assertion there would
  serialize it. Sanitize soaks run unpipelined.
- ``modified`` lanes are NOT checked: stamping is order-dependent
  bookkeeping by design (see analysis.lattice_laws).
"""

from __future__ import annotations

import os
from typing import Dict

_ENV = "CRDT_TPU_SANITIZE"


class LatticeViolation(AssertionError):
    """A merge left the store NOT dominating its input — the lattice
    join invariant is broken (lost update, reordered winner, or
    double-apply)."""


def enabled() -> bool:
    """Read ``CRDT_TPU_SANITIZE`` live — per-test toggling works
    without reimporting anything."""
    return os.environ.get(_ENV, "") not in ("", "0")


def check_scalar_join(crdt, remote_records: Dict) -> None:
    """Post-``Crdt.merge`` invariant: every remote record is dominated
    by what the store now holds for its key, and by the canonical
    clock."""
    canonical = crdt.canonical_time
    for key, remote in remote_records.items():
        stored = crdt.get_record(key)
        if stored is None:
            raise LatticeViolation(
                f"sanitizer: merge saw key {key!r} but the store "
                f"holds no record for it afterwards")
        if stored.hlc < remote.hlc:
            raise LatticeViolation(
                f"sanitizer: store does not dominate merged input at "
                f"key {key!r}: stored hlc {stored.hlc} < remote hlc "
                f"{remote.hlc}")
        if canonical < remote.hlc:
            raise LatticeViolation(
                f"sanitizer: canonical clock {canonical} was not "
                f"absorbed past remote hlc {remote.hlc} at key "
                f"{key!r}")


def check_dense_sparse_join(store, slots, lt, node, valid=None) -> None:
    """Post-merge invariant for a payload-order sparse delta against a
    DenseStore: stored (lt, node) at each valid slot is lex >= the
    delta's. Duplicate slots must already be collapsed (the same
    contract the merge itself requires)."""
    import numpy as np
    s_lt = np.asarray(store.lt)[np.asarray(slots)]
    s_node = np.asarray(store.node)[np.asarray(slots)]
    r_lt = np.asarray(lt)
    r_node = np.asarray(node)
    dominated = (s_lt > r_lt) | ((s_lt == r_lt) & (s_node >= r_node))
    if valid is not None:
        dominated = dominated | ~np.asarray(valid)
    if not bool(np.all(dominated)):
        i = int(np.argmin(dominated))
        raise LatticeViolation(
            f"sanitizer: store does not dominate merged delta at slot "
            f"{int(np.asarray(slots)[i])}: stored (lt={int(s_lt[i])}, "
            f"node={int(s_node[i])}) < remote (lt={int(r_lt[i])}, "
            f"node={int(r_node[i])})")


def check_dense_no_resurrection(store, purged_slots, floor_lt) -> None:
    """Post-merge invariant after an epoch GC pass (docs/STORAGE.md):
    no purged slot may re-occupy with a record stamped BELOW the purge
    floor — that would be a resurrection of purged state (a replayed
    pre-purge delta got past the merge fence). Re-occupation at or
    above the floor is a legitimate fresh write and passes. Armed by
    `DenseCrdt.gc_purge` under ``CRDT_TPU_SANITIZE=1``; retired on
    `compact` (the remap invalidates the recorded slot indices)."""
    import numpy as np
    purged = np.asarray(purged_slots)
    if not purged.size:
        return
    occ = np.asarray(store.occupied)[purged]
    lt = np.asarray(store.lt)[purged]
    revived = occ & (lt <= int(floor_lt))
    if bool(np.any(revived)):
        i = int(np.argmax(revived))
        raise LatticeViolation(
            f"sanitizer: purged slot {int(purged[i])} re-occupied "
            f"at or below the GC floor (lt={int(lt[i])} <= floor "
            f"{int(floor_lt)}) — a pre-purge delta resurrected "
            f"purged state past the merge fence")


def check_dense_join(store, cs) -> None:
    """Post-merge invariant for a wide [R, N] DenseChangeset: per
    slot, the store dominates the lex max over the valid replica
    rows."""
    import numpy as np
    lt = np.asarray(cs.lt)
    node = np.asarray(cs.node)
    valid = np.asarray(cs.valid).astype(bool)
    if not valid.any():
        return
    neg = np.int64(-(2 ** 62))
    m_lt = np.where(valid, lt, neg)
    # lex max over rows: max lt, then max node among rows at that lt
    best_lt = m_lt.max(axis=0)
    at_best = valid & (m_lt == best_lt)
    best_node = np.where(at_best, node, np.iinfo(np.int32).min
                         ).max(axis=0)
    any_valid = valid.any(axis=0)
    s_lt = np.asarray(store.lt)
    s_node = np.asarray(store.node)
    dominated = (~any_valid | (s_lt > best_lt)
                 | ((s_lt == best_lt) & (s_node >= best_node)))
    if not bool(np.all(dominated)):
        i = int(np.argmin(dominated))
        raise LatticeViolation(
            f"sanitizer: store does not dominate merged changeset at "
            f"slot {i}: stored (lt={int(s_lt[i])}, "
            f"node={int(s_node[i])}) < changeset best "
            f"(lt={int(best_lt[i])}, node={int(best_node[i])})")

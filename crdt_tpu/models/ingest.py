"""Write-path fast lane: the host-side `WriteCombiner`.

The reference stamps and applies one record per ``put``
(crdt.dart:77-94) and the dense port inherited that shape: every
local write pays one `Hlc.send` plus one full scatter dispatch, so
the write path is dispatch-bound — ~4.8 ms for 1024 slots on a
sharded store against a ~2.1 ms dispatch floor
(MULTICHIP_SCALE_r05.json). The combiner coalesces local writes the
LSM way (log-structured staging, PAPERS.md) and commits them as ONE
fused, sharding-aware device program:

- ``put_batch``/``delete_batch`` issued inside a
  ``DenseCrdt.ingest()`` window append to growable columnar host
  buffers (slots/values/tombs lanes) — no device work per call.
- At flush the whole backlog is stamped by ONE vectorized
  `Hlc.send_batch` (one wall read, one counter run; each staged call
  keeps its own strictly-later stamp, so per-record monotonic order
  and putAll batch-stamp semantics both survive).
- The commit is a single `ops.dense.ingest_scatter` dispatch —
  donated, jit-cached, with the owner's precomputed ``NamedSharding``
  pinned on the output so sharded commits place rows shard-locally.
- The commit is double-buffered and non-blocking: the padded commit
  lanes are fresh buffers handed to the dispatch and never touched
  again, so the stage-side buffers accept flush N+1's writes while
  flush N executes on device — no fence anywhere in the fast lane.

Read-your-writes: ``get``/``count_modified_since``/``contains_slot``/
``is_deleted`` consult the staging overlay before the device store.
Every other read/merge/pack/serialization path is a BARRIER that
drains the combiner first (`DenseCrdt.drain_ingest`), so nothing
outside the window can observe a store missing staged writes. The
storage-plane passes are barriers too: `DenseCrdt.gc_purge` drains
before purging (a staged delete must land before its stamp is judged
against the floor) and `DenseCrdt.compact` drains before remapping
(staged rows address PRE-remap slots; committing them after the
translation would scatter into the wrong rows — docs/STORAGE.md).
See docs/INGEST.md for the lifecycle and visibility rules.
"""

from __future__ import annotations

import time
from typing import Optional, TYPE_CHECKING

import numpy as np

import jax.numpy as jnp

from ..hlc import Hlc

if TYPE_CHECKING:                       # pragma: no cover
    from .dense_crdt import DenseCrdt

_INITIAL_ROWS = 1024

# Flush-path instruments, resolved once per process (the default
# registry is a fixed singleton): the flush is the latency-sensitive
# leg of the fast lane, so it should not pay four registry lookups
# per commit.
_METRICS = None


def _metrics():
    global _METRICS
    if _METRICS is None:
        from ..obs.registry import default_registry
        reg = default_registry()
        _METRICS = (
            reg.counter("crdt_tpu_ingest_flush_total",
                        "write-combiner flushes by trigger"),
            reg.counter("crdt_tpu_ingest_flush_rows_total",
                        "rows committed by write-combiner flushes "
                        "(post-dedup)"),
            reg.counter("crdt_tpu_ingest_flush_groups_total",
                        "staged put/delete calls committed by flushes"),
            reg.histogram("crdt_tpu_ingest_flush_seconds",
                          "write-combiner flush wall time (stamp + "
                          "dispatch, no fence)"),
        )
    return _METRICS


class WriteCombiner:
    """Columnar staging buffers for one `DenseCrdt.ingest()` window.

    Not thread-safe on its own — like every other local-write surface,
    callers serialize through the replica lock (`GossipNode.lock`)
    when other threads gossip concurrently.
    """

    __slots__ = ("_owner", "_auto", "_slots", "_vals", "_tombs",
                 "_group", "_k", "_groups", "_pending", "flushes",
                 "rows_committed", "on_flush", "last_phase_seconds")

    def __init__(self, owner: "DenseCrdt",
                 auto_flush_rows: int = 1 << 16):
        if auto_flush_rows < 1:
            raise ValueError(
                f"auto_flush_rows must be >= 1; got {auto_flush_rows}")
        self._owner = owner
        self._auto = auto_flush_rows
        cap = _INITIAL_ROWS
        self._slots = np.empty(cap, np.int64)
        self._vals = np.empty(cap, np.int64)
        self._tombs = np.empty(cap, bool)
        self._group = np.empty(cap, np.int64)
        self._k = 0          # staged rows
        self._groups = 0     # staged API calls (one HLC stamp each)
        # slot -> value-or-None (tombstone), LAST staged occurrence:
        # the read-your-writes overlay answers point reads in O(1).
        self._pending: dict = {}
        self.flushes = 0
        self.rows_committed = 0
        # Optional flush listener ``(trigger, rows, seconds)``, fired
        # after EVERY successful commit whatever its trigger (tick,
        # auto, barrier) — how the serving tier observes its true
        # flush-latency distribution without wrapping every drain
        # site. Listener errors are swallowed: observability must
        # never fail a commit.
        self.on_flush = None
        # Per-phase wall time of the LAST flush: {"stamp": s,
        # "scatter": s} — the HLC stamp leg (wall read + counter run)
        # vs the dedup + device-scatter dispatch leg. The serving tier
        # reads this after each tick commit to attribute write-ack
        # latency (crdt_tpu_serve_ack_phase_seconds).
        self.last_phase_seconds: dict = {}

    # --- staging ---

    @property
    def pending_rows(self) -> int:
        """Rows staged and not yet committed."""
        return self._k

    @property
    def pending_groups(self) -> int:
        """Staged API calls awaiting their flush stamp."""
        return self._groups

    def _grow_to(self, need: int) -> None:
        cap = len(self._slots)
        if need <= cap:
            return
        while cap < need:
            cap *= 2
        for name in ("_slots", "_vals", "_tombs", "_group"):
            old = getattr(self, name)
            grown = np.empty(cap, old.dtype)
            grown[:self._k] = old[:self._k]
            setattr(self, name, grown)

    def stage(self, slots: np.ndarray, values: np.ndarray,
              tombs: Optional[np.ndarray]) -> None:
        """Append one put/delete batch as a single stamp group. The
        owner has already validated slots/value-width — staging fails
        loudly at the call site, exactly like the unbatched path."""
        n = len(slots)
        k = self._k
        if n:
            self._grow_to(k + n)
            self._slots[k:k + n] = slots
            self._vals[k:k + n] = values
            self._tombs[k:k + n] = False if tombs is None else tombs
            self._group[k:k + n] = self._groups
            self._k = k + n
            # Overlay refresh is one bulk dict.update off vectorized
            # lanes — the per-row Python loop this replaces was the
            # staging hot path's last O(n)-interpreter cost, visible
            # once binop frames land whole client batches here.
            # zip(list, list) keeps insertion order, so the LAST
            # staged occurrence of a repeated slot wins, same as the
            # loop it replaces.
            pend = self._pending
            if tombs is None:
                pend.update(zip(slots.tolist(), values.tolist()))
            else:
                vals_obj = values.astype(object)
                vals_obj[np.asarray(tombs, bool)] = None
                pend.update(zip(slots.tolist(), vals_obj.tolist()))
        # An EMPTY batch still counts as a group: the unbatched path
        # spends one send per call regardless, so the flush stamps it
        # too — stats.puts and per-call stamp spacing stay uniform.
        self._groups += 1
        if self._k >= self._auto:
            self.flush("auto")

    # --- read-your-writes overlay ---

    def pending_value(self, slot: int):
        """``(staged, value)`` for the overlay: ``value`` is None for
        a staged tombstone (the same answer `get` gives for a
        committed one)."""
        if slot in self._pending:
            return True, self._pending[slot]
        return False, None

    def pending_slot_array(self) -> np.ndarray:
        """Distinct staged slots (for the count_modified_since
        overlay — staged rows commit at-or-after the canonical head,
        so they count as modified under any watermark bound)."""
        return np.fromiter(self._pending.keys(), np.int64,
                           count=len(self._pending))

    # --- commit ---

    def flush(self, trigger: str = "explicit") -> bool:
        """Stamp and commit every staged row as ONE device dispatch.

        Returns True when a commit was dispatched (False on an empty
        backlog). On a clock exception (drift/overflow from
        `Hlc.send_batch`) nothing is stamped or dispatched and the
        backlog stays staged — no write is silently dropped."""
        if self._groups == 0:
            return False
        k = self._k
        owner = self._owner
        from ..obs.trace import span
        node = str(owner.node_id)
        t0 = time.perf_counter()
        with span("ingest_flush", kind="ingest",
                  hlc=lambda: owner.canonical_time,
                  node=node, rows=k, trigger=trigger):
            # ONE wall read + one counter run for the whole backlog;
            # group g's stamp == the g'th sequential send under a
            # frozen clock, so batch (putAll) stamp-sharing and
            # strict cross-group monotonicity both hold.
            new_canonical, group_lts = Hlc.send_batch(
                owner.canonical_time, self._groups,
                millis=owner._wall_clock())
            t_stamp = time.perf_counter()
            d = 0
            if k:
                slots = self._slots[:k]
                lt = np.asarray(group_lts, np.int64)[self._group[:k]]
                vals = self._vals[:k]
                tombs = self._tombs[:k]
                # Duplicate staged slots collapse last-wins BEFORE the
                # scatter (XLA duplicate-index winner order is
                # backend-dependent); the last occurrence also carries
                # the dominating stamp, so this IS the LWW outcome.
                keep = owner._last_wins_keep(slots)
                if keep is not None:
                    slots, lt, vals, tombs = (slots[keep], lt[keep],
                                              vals[keep], tombs[keep])
                d = len(slots)
                # ONE dispatch, routed per backend: the owner picks
                # the touched-tile Mosaic kernel, the lax scatter, or
                # (sharded) a single shard_map program. Padding and
                # sentinel rows live with the route that needs them.
                owner._store = owner._commit_scatter(slots, lt, vals,
                                                     tombs)
                owner._store_escaped = False
            t_scatter = time.perf_counter()
            owner._canonical_time = new_canonical
            owner.stats.puts += self._groups
            owner.stats.records_put += k
            groups = self._groups
            self._k = 0
            self._groups = 0
            self._pending = {}
            self.flushes += 1
            self.rows_committed += d
            if d:
                self._emit_commit(slots, vals, tombs)
        dt = time.perf_counter() - t0
        self.last_phase_seconds = {"stamp": t_stamp - t0,
                                   "scatter": t_scatter - t_stamp}
        # Store-bytes census: lane nbytes is array metadata, no device
        # work — the commit is where the store's footprint last moved.
        from ..obs import device as _obs_device
        _obs_device.census(owner._store)
        flushes_c, rows_c, groups_c, seconds_h = _metrics()
        flushes_c.inc(trigger=trigger, node=node)
        rows_c.inc(d, node=node)
        groups_c.inc(groups, node=node)
        seconds_h.observe(dt, node=node)
        cb = self.on_flush
        if cb is not None:
            try:
                cb(trigger, d, dt)
            except Exception:
                pass
        return True

    def _emit_commit(self, slots: np.ndarray, vals: np.ndarray,
                     tombs: np.ndarray) -> None:
        """Change events fire AT COMMIT, with the winning post-dedup
        value per slot — a slot staged twice in the window emits once,
        with the value the store actually holds (docs/INGEST.md)."""
        owner = self._owner
        hub = owner._hub
        if not hub.active:
            return
        sl = [int(s) for s in slots.tolist()]
        if owner._has_typed:
            # Typed lanes (counter/orset/mvreg) carry packed encodings;
            # subscribers must see the decoded committed value, same as
            # the unbatched emit paths (docs/TYPES.md).
            svals = [None if t else owner._watch_decode(s, int(v))
                     for s, v, t in zip(sl, vals.tolist(),
                                        tombs.tolist())]
        else:
            svals = [None if t else int(v)
                     for v, t in zip(vals.tolist(), tombs.tolist())]
        pos = {s: i for i, s in enumerate(sl)}
        # crdtlint: disable=add-batch-unique-keys -- slots are deduplicated last-wins by flush() before reaching here, so the batch repeats no key
        hub.add_batch(lambda: (sl, svals),
                      lambda q: ((True, svals[pos[q]])
                                 if isinstance(q, (int, np.integer))
                                 and q in pos else (False, None)))

"""Sharded fan-in at scale on the virtual 8-device mesh.

Round-2 verdict: the sharded path's evidence was dryrun-scale only
(64 records), and `ShardedDenseCrdt.put_batch` re-shards the whole
store after every local write batch with unmeasured cost. This harness
runs the 8-device (2 replica-shards × 4 key-shards) mesh at
≥256k keys × 64 replica rows with a lane-exact cross-check against the
single-device executor, times the put_batch path, and writes a
MULTICHIP-style JSON artifact.

Run:
    python benchmarks/sharded_scale.py [--keys 262144] [--rows 64]
(The script pins jax to the virtual CPU mesh itself — no env needed.)
"""

from __future__ import annotations

import argparse
import json
import time

import jax

# Must run before any backend init: this environment pins an 'axon' TPU
# plugin via sitecustomize, so the env var alone cannot switch to the
# virtual CPU mesh (tests/conftest.py does the same).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from crdt_tpu.hlc import SHIFT  # noqa: E402
from crdt_tpu.models.dense_crdt import DenseCrdt, ShardedDenseCrdt  # noqa: E402
from crdt_tpu.ops.dense import DenseChangeset  # noqa: E402
from crdt_tpu.parallel import make_fanin_mesh  # noqa: E402
from crdt_tpu.testing import FakeClock, assert_dense_stores_equal  # noqa: E402

BASE = 1_700_000_000_000


def random_changesets(rows: int, n: int, seed: int, n_groups: int):
    """``n_groups`` peer changesets of rows//n_groups replica rows each,
    all-distinct random records, as (DenseChangeset, node_ids) pairs."""
    rng = np.random.default_rng(seed)
    per = rows // n_groups
    out = []
    for g in range(n_groups):
        lt = ((BASE + rng.integers(0, 1000, (per, n))) << SHIFT) \
            + rng.integers(0, 4, (per, n))
        cs = DenseChangeset(
            lt=jnp.asarray(lt, jnp.int64),
            node=jnp.asarray(rng.integers(0, 4, (per, n)), jnp.int32),
            val=jnp.asarray(lt, jnp.int64),
            tomb=jnp.asarray(rng.random((per, n)) < 0.3),
            valid=jnp.asarray(rng.random((per, n)) < 0.8),
        )
        out.append((cs, [f"peer{g}-{i}" for i in range(4)]))
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=1 << 18)
    ap.add_argument("--rows", type=int, default=64)
    ap.add_argument("--out", default="MULTICHIP_SCALE_r03.json")
    args = ap.parse_args()
    n, rows = args.keys, args.rows

    result = {"ok": False, "n_devices": jax.device_count(),
              "n_keys": n, "replica_rows": rows,
              "mesh": "(replica=2, key=4)"}
    mesh = make_fanin_mesh(2, 4)
    changesets = random_changesets(rows, n, seed=7, n_groups=8)
    merges = int(sum(int(jnp.sum(cs.valid)) for cs, _ in changesets))

    # --- sharded fan-in: 64 replica rows into 256k+ sharded slots ---
    sharded = ShardedDenseCrdt("local", n, mesh,
                               wall_clock=FakeClock(start=BASE + 2000))
    t0 = time.perf_counter()
    sharded.merge_many(changesets)
    jax.block_until_ready(sharded.store.lt)
    warm_compile = time.perf_counter() - t0

    sharded2 = ShardedDenseCrdt("local", n, mesh,
                                wall_clock=FakeClock(start=BASE + 2000))
    t0 = time.perf_counter()
    sharded2.merge_many(changesets)
    jax.block_until_ready(sharded2.store.lt)
    sharded_s = time.perf_counter() - t0

    # --- single-device cross-check (lane-exact) ---
    single = DenseCrdt("local", n, executor="xla",
                       wall_clock=FakeClock(start=BASE + 2000))
    t0 = time.perf_counter()
    single.merge_many(changesets)
    jax.block_until_ready(single.store.lt)
    single_s = time.perf_counter() - t0

    assert_dense_stores_equal(single.store, sharded2.store,
                              "single vs sharded @ scale")
    assert single.canonical_time == sharded2.canonical_time
    result["lane_exact_vs_single_device"] = True
    result["merges"] = merges
    result["timings_s"] = {
        "sharded_fanin_first_call_incl_compile": round(warm_compile, 3),
        "sharded_fanin_warm": round(sharded_s, 3),
        "single_device_fanin_warm": round(single_s, 3),
    }
    result["sharded_merges_per_sec_warm"] = round(merges / sharded_s, 1)

    # --- put_batch cost on the sharded store (the round-2 concern:
    # a full-store re-shard per local write batch?) ---
    k = 1024
    slots = np.arange(0, k * 16, 16)
    vals = np.arange(k, dtype=np.int64)
    sharded2.put_batch(slots, vals)  # compile
    jax.block_until_ready(sharded2.store.lt)
    t0 = time.perf_counter()
    reps = 10
    for _ in range(reps):
        sharded2.put_batch(slots, vals)
    jax.block_until_ready(sharded2.store.lt)
    put_sharded = (time.perf_counter() - t0) / reps

    single.put_batch(slots, vals)
    jax.block_until_ready(single.store.lt)
    t0 = time.perf_counter()
    for _ in range(reps):
        single.put_batch(slots, vals)
    jax.block_until_ready(single.store.lt)
    put_single = (time.perf_counter() - t0) / reps

    shardings = {str(getattr(sharded2.store, f).sharding)
                 for f in sharded2.store._fields}
    result["put_batch_1024_slots_ms"] = {
        "sharded": round(put_sharded * 1e3, 2),
        "single_device": round(put_single * 1e3, 2),
    }
    result["store_sharding_consistent"] = len(shardings) == 1
    result["store_sharding"] = shardings.pop()
    result["ok"] = True

    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()

"""TpuMapCrdt: full conformance suite + differential tests vs the oracle.

The TPU path must be behaviorally indistinguishable from MapCrdt —
including byte-identical `to_json` output (the north-star parity
requirement) — under arbitrary op sequences.
"""

import json
import random

import pytest

from crdt_tpu import (ClockDriftException, DuplicateNodeException, Hlc,
                      MapCrdt, Record, TpuMapCrdt)

from conformance import CrdtConformance, FakeClock

MILLIS = 1000000000000


class TestTpuConformance(CrdtConformance):
    def make_crdt(self):
        return TpuMapCrdt("abc", wall_clock=FakeClock())


class TestTpuSpecifics:
    def setup_method(self):
        self.clock = FakeClock()
        self.crdt = TpuMapCrdt("abc", wall_clock=self.clock)

    def test_seed(self):
        hlc = Hlc(MILLIS, 0, "abc")
        crdt = TpuMapCrdt("abc", seed={"x": Record(hlc, 1, hlc)})
        assert crdt.get("x") == 1
        assert crdt.canonical_time.logical_time == hlc.logical_time

    def test_capacity_growth(self):
        for i in range(100):
            self.crdt.put(f"k{i}", i)
        assert self.crdt.length == 100
        assert self.crdt.get("k42") == 42

    def test_merge_duplicate_node_raises(self):
        remote = Hlc(self.clock.millis + 1000, 0, "abc")
        with pytest.raises(DuplicateNodeException):
            self.crdt.merge({"x": Record(remote, 1, remote)})

    def test_merge_drift_raises(self):
        remote = Hlc(self.clock.millis + 120_000, 0, "xyz")
        with pytest.raises(ClockDriftException):
            self.crdt.merge({"x": Record(remote, 1, remote)})

    def test_merge_drift_fast_path_skips_check(self):
        # recv's fast path skips guard checks when canonical >= remote
        # (hlc.dart:85) — an old record from "our own" node id must NOT
        # raise DuplicateNodeException.
        self.crdt.put("x", 1)
        old = Hlc(0, 1, "abc")
        self.crdt.merge({"y": Record(old, 2, old)})  # no raise
        assert self.crdt.get("x") == 1

    def test_node_table_remap(self):
        # A node id sorting BEFORE existing ones shifts ordinals; stored
        # lanes must be re-encoded so tie-breaks stay correct.
        self.crdt.merge({"x": Record(Hlc(MILLIS, 0, "zzz"), 1,
                                     Hlc(MILLIS, 0, "zzz"))})
        self.crdt.merge({"x": Record(Hlc(MILLIS, 0, "aaa"), 2,
                                     Hlc(MILLIS, 0, "aaa"))})
        # zzz > aaa at equal logical time: local (zzz) wins
        assert self.crdt.get("x") == 1
        # but a later write from aaa wins
        self.crdt.merge({"x": Record(Hlc(MILLIS + 1, 0, "aaa"), 3,
                                     Hlc(MILLIS, 0, "aaa"))})
        assert self.crdt.get("x") == 3

    def test_tombstone_roundtrip(self):
        self.crdt.put("x", 1)
        self.crdt.delete("x")
        assert self.crdt.is_deleted("x") is True
        rm = self.crdt.record_map()
        assert rm["x"].is_deleted


def _apply_ops(crdt, ops):
    for op, args in ops:
        getattr(crdt, op)(*args)


def _random_ops(rng: random.Random, peers, n_ops=60):
    """A reproducible op script exercising puts, deletes, batches,
    tombstones, merges and tie-break-heavy timestamps."""
    keys = [f"k{i}" for i in range(12)]
    ops = []
    for _ in range(n_ops):
        kind = rng.randrange(5)
        if kind == 0:
            ops.append(("put", (rng.choice(keys), rng.randrange(100))))
        elif kind == 1:
            ops.append(("put_all", ({k: rng.randrange(100)
                                     for k in rng.sample(keys, 3)},)))
        elif kind == 2:
            ops.append(("delete", (rng.choice(keys),)))
        elif kind == 3:
            # crafted remote changeset with tie-break-prone timestamps
            base = 1_700_000_000_000 + rng.randrange(3)
            node = rng.choice(peers)
            hlc = Hlc(base, rng.randrange(3), node)
            cs = {rng.choice(keys): Record(hlc, rng.randrange(100)
                                           if rng.random() > 0.3 else None,
                                           hlc)}
            ops.append(("merge", (cs,)))
        else:
            ops.append(("clear", ()))
    return ops


@pytest.mark.parametrize("seed", range(5))
def test_differential_oracle_vs_tpu(seed):
    rng = random.Random(seed)
    ops = _random_ops(rng, peers=["n1", "n2", "zz"])
    oracle = MapCrdt("abc", wall_clock=FakeClock())
    tpu = TpuMapCrdt("abc", wall_clock=FakeClock())
    for op, args in ops:
        import copy
        getattr(oracle, op)(*copy.deepcopy(list(args)))
        getattr(tpu, op)(*copy.deepcopy(list(args)))
    # Byte-identical wire output — the parity contract.
    assert oracle.to_json() == tpu.to_json()
    assert oracle.canonical_time == tpu.canonical_time
    assert oracle.map == tpu.map


@pytest.mark.parametrize("seed", range(3))
def test_differential_oracle_vs_sqlite(seed):
    # Same random-op differential as the TPU backend, against the
    # durable backend (values are ints here so the JSON column
    # round-trips exactly).
    from crdt_tpu import SqliteCrdt
    rng = random.Random(seed + 70)
    ops = _random_ops(rng, peers=["n1", "n2", "zz"])
    oracle = MapCrdt("abc", wall_clock=FakeClock())
    lite = SqliteCrdt("abc", wall_clock=FakeClock())
    for op, args in ops:
        import copy
        getattr(oracle, op)(*copy.deepcopy(list(args)))
        getattr(lite, op)(*copy.deepcopy(list(args)))
    assert oracle.to_json() == lite.to_json()
    assert oracle.canonical_time == lite.canonical_time
    assert oracle.map == lite.map


@pytest.mark.parametrize("seed", range(3))
def test_differential_replica_convergence(seed):
    """3 mixed-backend replicas converge through the wire format."""
    rng = random.Random(100 + seed)
    clock = FakeClock()
    replicas = [MapCrdt("a", wall_clock=clock),
                TpuMapCrdt("b", wall_clock=clock),
                TpuMapCrdt("c", wall_clock=clock)]
    keys = [f"k{i}" for i in range(8)]
    for _ in range(40):
        r = rng.choice(replicas)
        if rng.random() < 0.7:
            r.put(rng.choice(keys), rng.randrange(1000))
        else:
            r.delete(rng.choice(keys))
    # anti-entropy rounds until fixpoint (pairwise full-state sync)
    for _ in range(3):
        for i in range(len(replicas)):
            for j in range(len(replicas)):
                if i != j:
                    replicas[j].merge(replicas[i].record_map())
    maps = [r.map for r in replicas]
    assert maps[0] == maps[1] == maps[2]
    jsons = [json.loads(r.to_json()) for r in replicas]
    # record-level state (hlc+value) identical everywhere
    assert jsons[0] == jsons[1] == jsons[2]


class TestLaneDirectExport:
    """TpuMapCrdt.to_json streams from the shadow lanes; it must stay
    byte-identical to the generic record_map()+encode path."""

    def _mixed(self):
        from crdt_tpu.testing import FakeClock
        from datetime import datetime, timezone
        c = TpuMapCrdt("nodeA", wall_clock=FakeClock())
        c.put_all({f"k{i}": {"x": i, "s": "é" * (i % 5)}
                   for i in range(50)})
        c.put(3, "int-key")
        c.put(datetime(2026, 1, 2, 3, 4, 5, 600000,
                       tzinfo=timezone.utc), "dt-key")
        c.put("tomb", 1)
        c.delete("tomb")
        return c

    def test_byte_identity_full(self):
        c = self._mixed()
        assert c.to_json() == super(TpuMapCrdt, c).to_json()

    def test_byte_identity_delta_and_coders(self):
        c = self._mixed()
        since = c.canonical_time
        c.put("late", {"v": [1, None, "x"]})
        for kw in (dict(modified_since=since),
                   dict(key_encoder=lambda k: f"K|{k}"),
                   dict(value_encoder=lambda k, v: {"w": v}),
                   dict(modified_since=since,
                        key_encoder=lambda k: f"K|{k}",
                        value_encoder=lambda k, v: [v])):
            assert c.to_json(**kw) == super(TpuMapCrdt, c).to_json(**kw), kw

    def test_empty_and_no_match_delta(self):
        from crdt_tpu.hlc import Hlc
        from crdt_tpu.testing import FakeClock
        c = TpuMapCrdt("nodeA", wall_clock=FakeClock())
        assert c.to_json() == "{}"
        c.put("a", 1)
        far = Hlc(c.canonical_time.millis + 10_000, 0, "nodeA")
        assert c.to_json(modified_since=far) == "{}"

    def test_wire_roundtrip_through_oracle(self):
        from crdt_tpu import MapCrdt
        from crdt_tpu.testing import FakeClock
        src = self._mixed()
        dst = MapCrdt("nodeB", wall_clock=FakeClock())
        dst.merge_json(src.to_json())
        back = TpuMapCrdt("nodeC", wall_clock=FakeClock())
        back.merge_json(dst.to_json())
        from crdt_tpu.crdt_json import dart_str
        assert {dart_str(k): r.value
                for k, r in back.record_map().items()} \
            == {dart_str(k): r.value
                for k, r in src.record_map().items()}


class TestNativeHostHelpers:
    """The C batch bookkeeping helpers (ensure_slots / none_mask /
    scatter_payload) must be behaviorally identical to the Python
    loops they replace."""

    def _payload(self, n=300):
        import random
        rng = random.Random(3)
        return {f"k{i}": Record(
            Hlc(1_700_000_000_000 + rng.randrange(50), rng.randrange(4),
                f"n{rng.randrange(3)}"),
            None if i % 5 == 0 else f"v{i}",
            Hlc(1_700_000_000_000, 0, "n0")) for i in range(n)}

    def test_merge_matches_pure_python_path(self, monkeypatch):
        from crdt_tpu import native as native_pkg
        recs = self._payload()
        fast = TpuMapCrdt("local", wall_clock=FakeClock(
            start=1_700_000_000_100))
        fast.merge(dict(recs))
        monkeypatch.setattr(native_pkg, "_mod", None)
        monkeypatch.setattr(native_pkg, "_tried", True)
        slow = TpuMapCrdt("local", wall_clock=FakeClock(
            start=1_700_000_000_100))
        slow.merge(dict(recs))
        monkeypatch.undo()
        assert fast.record_map() == slow.record_map()
        assert fast.canonical_time == slow.canonical_time
        assert fast._slot_keys == slow._slot_keys
        assert fast._payload == slow._payload

    def test_watch_subscriber_sees_same_winners(self):
        # with a subscriber the python emit loop runs instead of the C
        # scatter; store state must be identical either way
        recs = self._payload(100)
        a = TpuMapCrdt("local", wall_clock=FakeClock(
            start=1_700_000_000_100))
        events = []
        stream = a.watch()
        stream.listen(lambda e: events.append((e.key, e.value)))
        a.merge(dict(recs))
        b = TpuMapCrdt("local", wall_clock=FakeClock(
            start=1_700_000_000_100))
        b.merge(dict(recs))
        assert a.record_map() == b.record_map()
        assert len(events) == 100   # all fresh keys win
        assert dict(events) == {k: r.value for k, r in recs.items()}

    def test_ensure_slots_rolls_back_on_mid_batch_failure(self):
        """An unhashable key mid-batch must leave the key->slot dict
        and the slot tables consistent (C path parity with the
        per-key Python loop)."""
        c = TpuMapCrdt("local", wall_clock=FakeClock(
            start=1_700_000_000_100))
        c.put("pre", 0)
        before = dict(c._key_to_slot)
        bad_keys = ["a", "b", ["unhashable"], "c"]
        with pytest.raises(TypeError):
            c._ensure_slots(bad_keys)
        assert c._key_to_slot == before
        assert len(c._slot_keys) == len(c._key_to_slot)
        # and the store still works
        c.put_all({"a": 1, "b": 2})
        assert c.get("a") == 1 and c.get("pre") == 0

    def test_put_all_lane_direct_matches_oracle(self, monkeypatch):
        """put_all writes lanes directly (one shared HLC, no Record
        objects); state and wire output must match the oracle and the
        pure-Python fallback, including tombstones via None values
        and overwrites of existing keys."""
        from crdt_tpu import native as native_pkg
        batch = {f"k{i}": (None if i % 4 == 0 else i) for i in range(60)}
        o = MapCrdt("n", wall_clock=FakeClock())
        fast = TpuMapCrdt("n", wall_clock=FakeClock())
        for c in (o, fast):
            c.put("k3", "pre")       # overwrite target
            c.put_all(batch)
            c.put_all({})            # no clock touch
        assert fast.to_json() == o.to_json()
        assert fast.canonical_time == o.canonical_time
        # all records in the batch share ONE hlc (crdt.dart:50-52)
        hlcs = {str(r.hlc) for k, r in fast.record_map().items()
                if k in batch}
        assert len(hlcs) == 1
        # pure-python fallback identical
        monkeypatch.setattr(native_pkg, "_mod", None)
        monkeypatch.setattr(native_pkg, "_tried", True)
        slow = TpuMapCrdt("n", wall_clock=FakeClock())
        slow.put("k3", "pre")
        slow.put_all(batch)
        slow.put_all({})
        monkeypatch.undo()
        assert slow.record_map() == fast.record_map()

    def test_put_all_watch_events(self):
        a = TpuMapCrdt("n", wall_clock=FakeClock())
        seen = []
        a.watch().listen(lambda e: seen.append((e.key, e.value)))
        a.put_all({"x": 1, "y": None})
        assert sorted(seen) == [("x", 1), ("y", None)]

    def test_put_records_matches_pure_python_path(self, monkeypatch):
        from crdt_tpu import native as native_pkg
        recs = self._payload(200)
        fast = TpuMapCrdt("local", wall_clock=FakeClock(
            start=1_700_000_000_100))
        fast.put_records(dict(recs))
        monkeypatch.setattr(native_pkg, "_mod", None)
        monkeypatch.setattr(native_pkg, "_tried", True)
        slow = TpuMapCrdt("local", wall_clock=FakeClock(
            start=1_700_000_000_100))
        slow.put_records(dict(recs))
        monkeypatch.undo()
        assert fast.record_map() == slow.record_map()
        assert fast.to_json() == slow.to_json()
        # modified lanes preserved exactly (put_records stores the
        # records' own stamps, unlike merge's re-stamping)
        assert fast.record_map() == recs


class TestTickParity:
    """merge_json wall-read parity is structural (one shared
    accounting helper) and pinned here: if the generic path's read
    count ever changes without the columnar override following, these
    counts diverge and fail loudly."""

    def _pair(self):
        from crdt_tpu.testing import CountingClock
        co, ct = CountingClock(), CountingClock()
        return (MapCrdt("abc", wall_clock=co), co,
                TpuMapCrdt("abc", wall_clock=ct), ct)

    @pytest.mark.parametrize("no_native", [False, True])
    def test_merge_json_consumes_identical_ticks(self, no_native,
                                                 monkeypatch):
        if no_native:
            import crdt_tpu.crdt_json as cj
            monkeypatch.setattr(cj.native, "load", lambda: None)
        src = MapCrdt("peer", wall_clock=FakeClock(step=7))
        src.put_all({"a": 1, "b": None, "c": "x"})
        src.put("d", 4)
        payloads = [src.to_json(), "{}",
                    '{"a":{"hlc":"2001-01-01T00:00:00.000Z-0000-z",'
                    '"value":9}}']
        oracle, co, tpu, ct = self._pair()
        for p in payloads:
            oracle.merge_json(p)
            tpu.merge_json(p)
            assert co.reads == ct.reads, (
                f"wall-read drift on payload {p[:40]!r}: "
                f"oracle {co.reads} vs tpu {ct.reads}")
        assert oracle.to_json() == tpu.to_json()

    def test_record_merge_consumes_identical_ticks(self):
        src = MapCrdt("peer", wall_clock=FakeClock(step=3))
        src.put_all({"x": 1, "y": 2})
        recs = src.record_map()
        oracle, co, tpu, ct = self._pair()
        for cs in (recs, {}):
            oracle.merge(dict(cs))
            tpu.merge(dict(cs))
            assert co.reads == ct.reads
        assert oracle.to_json() == tpu.to_json()


def test_keyed_watch_no_spurious_event_when_clock_static():
    """A merge that does NOT advance the canonical clock (every remote
    record older/losing) must emit nothing — the keyed winner check
    may not confuse pre-merge records stamped at the current canonical
    with this merge's winners."""
    clk = FakeClock()
    c = TpuMapCrdt("abc", wall_clock=clk)
    c.put("a", 1)
    stream = c.watch(key="a").record()
    whole = c.watch().record()
    old = Hlc(1_600_000_000_000, 0, "peer")
    c.merge({"b": Record(old, 99, old)})   # b wins (new key), a untouched
    assert [(e.key, e.value) for e in stream.events] == []
    assert [(e.key, e.value) for e in whole.events] == [("b", 99)]
    # and a merge where the watched key LOSES an exact tie stays silent
    rec_a = c.get_record("a")
    c.merge({"a": Record(rec_a.hlc, 77, rec_a.hlc)})  # exact tie: local wins
    assert [(e.key, e.value) for e in stream.events] == []

/* Native batch codec for the HLC wire string
 * "YYYY-MM-DDTHH:MM:SS.mmmZ-XXXX-<node>" (hlc.dart:102-104).
 *
 * The host-side wire boundary (crdt_json.dart:8-37) is a per-record
 * string codec; at 10k+ records per sync round the Python datetime
 * round trip dominates ingest. This module batch-converts the
 * CANONICAL shape only — exactly what `Hlc.__str__` emits — and
 * returns None for anything else so the Python parser keeps full
 * reference semantics (space separators, UTC offsets, odd precision).
 *
 * Pure CPython C API, no deps; built on first use by
 * crdt_tpu/native/__init__.py with the system C compiler and loaded
 * with a silent fallback to the Python path.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

/* Howard Hinnant's civil-date algorithms (public domain), int64. */
static long long days_from_civil(long long y, int m, int d) {
    y -= m <= 2;
    long long era = (y >= 0 ? y : y - 399) / 400;
    long long yoe = y - era * 400;
    long long doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
    long long doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    return era * 146097 + doe - 719468;
}

static void civil_from_days(long long z, long long *y, int *m, int *d) {
    z += 719468;
    long long era = (z >= 0 ? z : z - 146096) / 146097;
    long long doe = z - era * 146097;
    long long yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
    long long yy = yoe + era * 400;
    long long doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
    long long mp = (5 * doy + 2) / 153;
    *d = (int)(doy - (153 * mp + 2) / 5 + 1);
    *m = (int)(mp + (mp < 10 ? 3 : -9));
    *y = yy + (*m <= 2);
}

static int digits(const char *s, int n, long long *out) {
    long long v = 0;
    for (int i = 0; i < n; i++) {
        if (s[i] < '0' || s[i] > '9') return 0;
        v = v * 10 + (s[i] - '0');
    }
    *out = v;
    return 1;
}

static int hex4(const char *s, long long *out) {
    long long v = 0;
    for (int i = 0; i < 4; i++) {
        char c = s[i];
        int d;
        if (c >= '0' && c <= '9') d = c - '0';
        else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
        else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
        else return 0;
        v = v * 16 + d;
    }
    *out = v;
    return 1;
}

static int days_in_month(long long y, int m) {
    static const int dim[] = {31, 28, 31, 30, 31, 30, 31, 31, 30, 31,
                              30, 31};
    if (m == 2 && (y % 4 == 0 && (y % 100 != 0 || y % 400 == 0)))
        return 29;
    return dim[m - 1];
}

/* "YYYY-MM-DDTHH:MM:SS.mmmZ" (24 chars) -> epoch millis. 1 on success.
 * Validates calendar ranges, not just shape — an invalid date must fall
 * through to the Python parser's ValueError, never silently normalize. */
static int parse_canonical_iso(const char *s, long long *out) {
    long long y, mo, d, h, mi, sec, ms;
    if (s[4] != '-' || s[7] != '-' || s[10] != 'T' || s[13] != ':' ||
        s[16] != ':' || s[19] != '.' || s[23] != 'Z')
        return 0;
    if (!digits(s, 4, &y) || !digits(s + 5, 2, &mo) ||
        !digits(s + 8, 2, &d) || !digits(s + 11, 2, &h) ||
        !digits(s + 14, 2, &mi) || !digits(s + 17, 2, &sec) ||
        !digits(s + 20, 3, &ms))
        return 0;
    if (mo < 1 || mo > 12 || d < 1 || d > days_in_month(y, (int)mo) ||
        h > 23 || mi > 59 || sec > 59)
        return 0;
    *out = (days_from_civil(y, (int)mo, (int)d) * 86400
            + h * 3600 + mi * 60 + sec) * 1000 + ms;
    return 1;
}

/* parse_hlc_batch(list[str]) -> (list, list, list):
 * per item (millis:int, counter:int, node:str), or (None, None, None)
 * when the item is not the canonical shape (caller falls back). */
static PyObject *parse_hlc_batch(PyObject *self, PyObject *arg) {
    if (!PyList_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "expected a list of str");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(arg);
    PyObject *millis_l = PyList_New(n);
    PyObject *counter_l = PyList_New(n);
    PyObject *node_l = PyList_New(n);
    if (!millis_l || !counter_l || !node_l) goto fail;

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(arg, i);
        Py_ssize_t len;
        const char *s = PyUnicode_Check(item)
            ? PyUnicode_AsUTF8AndSize(item, &len) : NULL;
        long long ms, counter;
        /* 24 iso + '-' + 4 hex + '-' + at least 1 node char */
        if (!s || len < 31 || s[24] != '-' || s[29] != '-' ||
            !parse_canonical_iso(s, &ms) || !hex4(s + 25, &counter)) {
            if (s == NULL) PyErr_Clear();
            Py_INCREF(Py_None); PyList_SET_ITEM(millis_l, i, Py_None);
            Py_INCREF(Py_None); PyList_SET_ITEM(counter_l, i, Py_None);
            Py_INCREF(Py_None); PyList_SET_ITEM(node_l, i, Py_None);
            continue;
        }
        PyObject *node = PyUnicode_FromStringAndSize(s + 30, len - 30);
        PyObject *ms_o = PyLong_FromLongLong(ms);
        PyObject *c_o = PyLong_FromLongLong(counter);
        if (!node || !ms_o || !c_o) {
            Py_XDECREF(node); Py_XDECREF(ms_o); Py_XDECREF(c_o);
            goto fail;
        }
        PyList_SET_ITEM(millis_l, i, ms_o);
        PyList_SET_ITEM(counter_l, i, c_o);
        PyList_SET_ITEM(node_l, i, node);
    }
    {
        PyObject *out = PyTuple_Pack(3, millis_l, counter_l, node_l);
        Py_DECREF(millis_l); Py_DECREF(counter_l); Py_DECREF(node_l);
        return out;
    }
fail:
    Py_XDECREF(millis_l); Py_XDECREF(counter_l); Py_XDECREF(node_l);
    return NULL;
}

/* format_hlc_batch(list[int] millis, list[int] counter, list[str] node)
 * -> list[str] "<iso>-<HEX4>-<node>"; None entries where millis is out
 * of the 4-digit-year window (caller falls back). */
static PyObject *format_hlc_batch(PyObject *self, PyObject *args) {
    PyObject *millis_l, *counter_l, *node_l;
    if (!PyArg_ParseTuple(args, "O!O!O!", &PyList_Type, &millis_l,
                          &PyList_Type, &counter_l, &PyList_Type, &node_l))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(millis_l);
    if (PyList_GET_SIZE(counter_l) != n || PyList_GET_SIZE(node_l) != n) {
        PyErr_SetString(PyExc_ValueError, "length mismatch");
        return NULL;
    }
    PyObject *out = PyList_New(n);
    if (!out) return NULL;

    for (Py_ssize_t i = 0; i < n; i++) {
        long long ms = PyLong_AsLongLong(PyList_GET_ITEM(millis_l, i));
        long long counter = PyLong_AsLongLong(PyList_GET_ITEM(counter_l, i));
        if (PyErr_Occurred()) { Py_DECREF(out); return NULL; }
        PyObject *node_o = PyList_GET_ITEM(node_l, i);
        Py_ssize_t nlen;
        const char *node = PyUnicode_AsUTF8AndSize(node_o, &nlen);
        if (!node) { Py_DECREF(out); return NULL; }

        long long secs = ms >= 0 ? ms / 1000 : (ms - 999) / 1000;
        int frac = (int)(ms - secs * 1000);
        long long days = secs >= 0 ? secs / 86400 : (secs - 86399) / 86400;
        int sod = (int)(secs - days * 86400);
        long long y; int mo, d;
        civil_from_days(days, &y, &mo, &d);
        /* y < 1 (not < 0): the pure-Python _iso8601 raises for year 0,
         * so the native formatter must defer it to that fallback — the
         * two codecs stay behaviorally identical at the boundary. */
        if (y < 1 || y > 9999 || counter < 0 || counter > 0xFFFF) {
            Py_INCREF(Py_None);
            PyList_SET_ITEM(out, i, Py_None);
            continue;
        }
        char buf[64];
        int w = snprintf(buf, sizeof buf,
                         "%04lld-%02d-%02dT%02d:%02d:%02d.%03dZ-%04llX-",
                         y, mo, d, sod / 3600, (sod / 60) % 60, sod % 60,
                         frac, counter);
        PyObject *s;
        if (PyUnicode_IS_ASCII(node_o)) {
            /* ASCII node: one allocation, two memcpys (bytes == chars) */
            s = PyUnicode_New(w + nlen, 127);
            if (s) {
                memcpy(PyUnicode_DATA(s), buf, w);
                memcpy((char *)PyUnicode_DATA(s) + w, node, nlen);
            }
        } else {
            PyObject *prefix = PyUnicode_FromStringAndSize(buf, w);
            s = prefix ? PyUnicode_Concat(prefix, node_o) : NULL;
            Py_XDECREF(prefix);
        }
        if (!s) { Py_DECREF(out); return NULL; }
        PyList_SET_ITEM(out, i, s);
    }
    return out;
}

static PyMethodDef methods[] = {
    {"parse_hlc_batch", parse_hlc_batch, METH_O,
     "Batch-parse canonical HLC wire strings."},
    {"format_hlc_batch", format_hlc_batch, METH_VARARGS,
     "Batch-format HLC components to wire strings."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_hlccodec",
    "Native batch codec for HLC wire strings.", -1, methods};

PyMODINIT_FUNC PyInit__hlccodec(void) { return PyModule_Create(&module); }

"""DenseCrdt: the device-resident integer-keyed model."""

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu import ClockDriftException, DuplicateNodeException, Hlc
from crdt_tpu.checkpoint import load_dense, save_dense
from crdt_tpu.models.dense_crdt import (DenseCrdt, PipelinedGuardError,
                                         sync_dense)
from crdt_tpu.testing import FakeClock

N = 64
BASE = 1_700_000_000_000


def make(node="na", start=BASE):
    return DenseCrdt(node, N, wall_clock=FakeClock(start=start))


class TestLocalOps:
    def test_put_get(self):
        c = make()
        c.put_batch([1, 5], [10, 50])
        assert c.get(1) == 10
        assert c.get(5) == 50
        assert c.get(2) is None
        assert len(c) == 2

    def test_batch_shares_one_hlc(self):
        # putAll semantics: one send per batch (crdt.dart:50-52).
        c = make()
        c.put_batch([1, 5], [10, 50])
        assert int(c.store.lt[1]) == int(c.store.lt[5])

    def test_delete_tombstones(self):
        c = make()
        c.put_batch([3], [30])
        c.delete_batch([3])
        assert c.get(3) is None
        assert bool(c.store.occupied[3])   # never physically removed
        assert len(c) == 0

    def test_overwrite_advances_clock(self):
        c = make()
        c.put_batch([0], [1])
        t1 = int(c.store.lt[0])
        c.put_batch([0], [2])
        assert int(c.store.lt[0]) > t1
        assert c.get(0) == 2


class TestReplication:
    def test_two_replica_sync(self):
        a, b = make("na"), make("nb", BASE + 5)
        a.put_batch([0, 1], [10, 11])
        b.put_batch([2], [22])
        sync_dense(a, b)
        for c in (a, b):
            assert c.get(0) == 10 and c.get(1) == 11 and c.get(2) == 22
        np.testing.assert_array_equal(np.asarray(a.store.val),
                                      np.asarray(b.store.val))

    def test_lww_conflict_newest_wins(self):
        a, b = make("na"), make("nb", BASE + 100)
        a.put_batch([0], [1])
        b.put_batch([0], [2])   # later wall clock
        sync_dense(a, b)
        assert a.get(0) == 2 and b.get(0) == 2

    def test_node_id_breaks_exact_tie(self):
        # Same wall millis on both replicas: larger node id wins
        # (hlc.dart:158-161).
        a, b = make("aa", BASE), make("zz", BASE)
        a.put_batch([0], [1])
        b.put_batch([0], [2])
        sync_dense(a, b)
        assert a.get(0) == 2 and b.get(0) == 2

    def test_tombstone_propagates(self):
        a, b = make("na"), make("nb", BASE + 5)
        a.put_batch([0], [1])
        sync_dense(a, b)
        b.delete_batch([0])
        sync_dense(a, b)
        assert a.get(0) is None and b.get(0) is None

    def test_delta_export_inclusive(self):
        a = make()
        a.put_batch([0], [1])
        t = a.canonical_time
        cs, _ = a.export_delta(since=t)
        assert bool(cs.valid[0, 0])        # == bound kept (inclusive)
        a.put_batch([1], [2])
        cs, _ = a.export_delta(since=a.canonical_time)
        assert not bool(cs.valid[0, 0])
        assert bool(cs.valid[0, 1])

    def test_three_replica_relay(self):
        a, b, c = make("na"), make("nb", BASE + 3), make("nc", BASE + 7)
        a.put_batch([0], [10])
        c.put_batch([9], [90])
        sync_dense(a, b)
        sync_dense(b, c)
        sync_dense(a, b)
        for r in (a, b, c):
            assert r.get(0) == 10 and r.get(9) == 90

    def test_duplicate_node_raises(self):
        a, b = make("na"), make("na", BASE + 50)
        a.put_batch([0], [1])
        cs, ids = a.export_delta()
        with pytest.raises(DuplicateNodeException):
            b.merge(cs, ids)

    def test_drift_raises(self):
        a = make("na", BASE + 200_000)   # far-future writer
        a.put_batch([0], [1])
        b = make("nb", BASE)
        cs, ids = a.export_delta()
        with pytest.raises(ClockDriftException):
            b.merge(cs, ids)

    def test_node_remap_preserves_tiebreak(self):
        # A peer id sorting before existing ids shifts ordinals; stored
        # lanes must re-encode or tie-breaks invert.
        z = make("zz", BASE)
        z.put_batch([0], [1])
        a = make("aa", BASE)
        a.put_batch([0], [2])
        sync_dense(a, z)
        # equal logical times: zz > aa wins on both replicas
        assert a.get(0) == 1 and z.get(0) == 1


class TestCrdtSurfaceParity:
    """The remaining reference surface on the dense model:
    containsKey/isDeleted/clear/purge (crdt.dart:61-73,141,168) and
    Crdt-duck-typed sync rounds."""

    def test_contains_and_is_deleted(self):
        c = make()
        c.put_batch([1], [10])
        c.delete_batch([2])
        assert c.contains_slot(1) and c.contains_slot(2)
        assert not c.contains_slot(3)
        assert c.is_deleted(1) is False
        assert c.is_deleted(2) is True
        assert c.is_deleted(3) is None
        # OOB reads must raise, not clamp to the edge slot.
        for bad in (N, -1, N + 100):
            for probe in (c.get, c.contains_slot, c.is_deleted):
                with pytest.raises(IndexError):
                    probe(bad)

    def test_merge_changeset_requires_node_ids(self):
        a, b = make("na"), make("nb", BASE + 5)
        a.put_batch([0], [1])
        cs, _ = a.export_delta()
        with pytest.raises(ValueError):
            b.merge(cs)

    def test_clear_tombstones_live_slots(self):
        a, b = make("na"), make("nb", BASE + 5)
        a.put_batch([0, 1], [1, 2])
        a.clear()
        assert len(a) == 0
        assert a.contains_slot(0) and a.is_deleted(0)
        # one batch HLC for the whole clear (putAll semantics)
        assert int(a.store.lt[0]) == int(a.store.lt[1])
        sync_dense(a, b)              # deletes propagate
        assert b.is_deleted(0) and b.is_deleted(1)
        # clearing an already-clear store never touches the clock
        t = a.canonical_time.logical_time
        a.clear()
        assert a.canonical_time.logical_time == t

    def test_purge_drops_records_keeps_clock(self):
        c = make()
        c.put_batch([0], [1])
        t = c.canonical_time.logical_time
        c.clear(purge=True)
        assert not c.contains_slot(0) and len(c) == 0
        assert c.canonical_time.logical_time == t

    def test_sync_rounds_with_record_backends(self):
        from crdt_tpu import MapCrdt
        from crdt_tpu.sync import sync, sync_json
        d = make("dd")
        m = MapCrdt("mm", wall_clock=FakeClock(start=BASE + 5))
        d.put_batch([0], [10])
        m.put(1, 11)
        sync(d, m)                    # record-map transport, duck-typed
        assert m.map == {0: 10, 1: 11}
        assert d.get(0) == 10 and d.get(1) == 11

        d2 = make("d2")
        m2 = MapCrdt("m2", wall_clock=FakeClock(start=BASE + 5))
        d2.put_batch([2], [22])
        m2.put(3, 33)
        sync_json(d2, m2, key_decoder=int)
        assert m2.map == {2: 22, 3: 33}
        assert d2.get(2) == 22 and d2.get(3) == 33


class TestMergeManyOrdinals:
    """Round-1 regression: merge_many interleaved peer interning with
    changeset encoding, so a later peer whose ids re-sorted the
    NodeTable left earlier-encoded changesets holding stale ordinals
    (spurious DuplicateNodeException, or silent writer mis-attribution
    and inverted tie-breaks). Ids must be interned as a union first."""

    def test_interleaved_interning_attribution(self):
        hub = DenseCrdt("m", N, wall_clock=FakeClock(start=BASE + 99))
        z = DenseCrdt("z", N, wall_clock=FakeClock(start=BASE))
        a = DenseCrdt("a", N, wall_clock=FakeClock(start=BASE + 3))
        z.put_batch([0], [10])
        a.put_batch([1], [20])
        # 'z' encodes first; interning 'a' then shifts 'z''s ordinal —
        # with the bug 'z''s rows carried hub's own ordinal ('m') and
        # raised DuplicateNodeException.
        hub.merge_many([z.export_delta(), a.export_delta()])
        assert hub.get(0) == 10 and hub.get(1) == 20
        assert hub._table.id_of(int(hub.store.node[0])) == "z"
        assert hub._table.id_of(int(hub.store.node[1])) == "a"

    def test_tiebreak_under_adversarial_intern_order(self):
        # Identical logical times on one slot: 'z' > 'a' must win the
        # node tie-break (hlc.dart:158-161) regardless of which peer's
        # changeset is encoded first.
        for order in (0, 1):
            hub = DenseCrdt("m", N, wall_clock=FakeClock(start=BASE + 99))
            z = DenseCrdt("z", N, wall_clock=FakeClock(start=BASE))
            a = DenseCrdt("a", N, wall_clock=FakeClock(start=BASE))
            z.put_batch([0], [10])
            a.put_batch([0], [20])
            deltas = [z.export_delta(), a.export_delta()]
            hub.merge_many(deltas if order == 0 else deltas[::-1])
            assert hub.get(0) == 10
            assert hub._table.id_of(int(hub.store.node[0])) == "z"

    def test_empty_merge_many_is_send_bump(self):
        # crdt.dart:93's final send bump runs even for an empty merge.
        c = make()
        t0 = c.canonical_time.logical_time
        c.merge_many([])
        assert c.canonical_time.logical_time > t0
        assert c.stats.merges == 1

    def test_slot_bounds_validated(self):
        c = make()
        with pytest.raises(IndexError):
            c.put_batch([N], [1])
        with pytest.raises(IndexError):
            c.delete_batch([-1])
        assert len(c) == 0


class TestStreamedFanin:
    """merge_many above STREAM_THRESHOLD_ROWS runs as a lax.scan stream;
    results must be bit-identical to the one-shot fused join."""

    def _writers(self, n_writers, seed=0):
        import random
        rng = random.Random(seed)
        ws = []
        for i in range(n_writers):
            w = DenseCrdt(f"w{i:02d}", N,
                          wall_clock=FakeClock(start=BASE + rng.randrange(30)))
            slots = sorted(rng.sample(range(N), rng.randrange(1, 8)))
            if rng.random() < 0.3:
                w.delete_batch(slots)
            else:
                w.put_batch(slots, [rng.randrange(100) for _ in slots])
            ws.append(w)
        return ws

    @pytest.mark.parametrize("n_writers", [17, 24, 40])
    def test_stream_matches_one_shot(self, n_writers):
        deltas = [w.export_delta() for w in self._writers(n_writers)]
        streamed = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 99))
        assert n_writers > streamed.STREAM_THRESHOLD_ROWS
        one_shot = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 99))
        one_shot.STREAM_THRESHOLD_ROWS = 10 ** 9   # force unrolled fold
        streamed.merge_many(list(deltas))
        one_shot.merge_many(list(deltas))
        for lane in DenseCrdt("x", N).store._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(streamed.store, lane)),
                np.asarray(getattr(one_shot.store, lane)), err_msg=lane)
        assert (streamed.canonical_time.logical_time
                == one_shot.canonical_time.logical_time)
        assert streamed.stats.records_adopted == one_shot.stats.records_adopted

    def test_stream_guard_diagnostics_match(self):
        # A duplicate-id record deep in the stream (row > threshold)
        # must raise the same payload as the unrolled path.
        ws = self._writers(20, seed=3)
        dup = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 500))
        dup.put_batch([7], [1])
        deltas = [w.export_delta() for w in ws] + [dup.export_delta()]
        errs = []
        for thresh in (16, 10 ** 9):
            hub = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 99))
            hub.STREAM_THRESHOLD_ROWS = thresh
            with pytest.raises(DuplicateNodeException) as ei:
                hub.merge_many(list(deltas))
            errs.append((str(ei.value), hub.canonical_time.logical_time))
        assert errs[0] == errs[1]


class TestMergeAlgebra:
    """The CRDT laws on the dense fan-in (SURVEY.md §5: the moral
    equivalent of race detection — convergence is algebraic)."""

    def _delta(self, node, slots, vals, start):
        w = DenseCrdt(node, N, wall_clock=FakeClock(start=start))
        w.put_batch(slots, vals)
        return w.export_delta()

    def test_idempotent(self):
        d = self._delta("w1", [0, 3], [1, 2], BASE)
        hub = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 9))
        hub.merge(*d)
        snap = hub.to_json()
        hub.merge(*d)          # merging the same delta again: no-op
        assert hub.to_json() == snap

    def test_commutative(self):
        d1 = self._delta("w1", [0, 3], [1, 2], BASE)
        d2 = self._delta("w2", [0, 5], [7, 8], BASE)   # conflicting slot 0
        a = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 9))
        b = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 9))
        a.merge(*d1), a.merge(*d2)
        b.merge(*d2), b.merge(*d1)
        assert a.to_json() == b.to_json()

    def test_associative_grouping(self):
        ds = [self._delta(f"w{i}", [i, 9], [i, 10 + i], BASE + i)
              for i in range(3)]
        a = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 9))
        b = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 9))
        a.merge_many([ds[0], ds[1]]), a.merge_many([ds[2]])
        b.merge_many([ds[0]]), b.merge_many([ds[1], ds[2]])
        assert a.to_json() == b.to_json()


class TestDifferentialVsOracle:
    """DenseCrdt vs MapCrdt under equivalent random op schedules: the
    observable record state (event HLC + value + tombstone per key)
    must match exactly."""

    @pytest.mark.parametrize("seed", range(3))
    def test_fanin_matches_sequential_oracle(self, seed):
        import random
        from crdt_tpu import MapCrdt, Record

        rng = random.Random(seed)
        n_writers = 5
        dense_writers = []
        oracle_writers = []
        for i in range(n_writers):
            clock_d = FakeClock(start=BASE + i * 3)
            clock_o = FakeClock(start=BASE + i * 3)
            d = DenseCrdt(f"w{i}", N, wall_clock=clock_d)
            o = MapCrdt(f"w{i}", wall_clock=clock_o)
            for _ in range(rng.randrange(1, 4)):
                slots = sorted(rng.sample(range(N), rng.randrange(1, 9)))
                if rng.random() < 0.25:
                    d.delete_batch(slots)
                    o.put_all({s: None for s in slots})
                else:
                    vals = [rng.randrange(1000) for _ in slots]
                    d.put_batch(slots, vals)
                    o.put_all(dict(zip(slots, vals)))
            dense_writers.append(d)
            oracle_writers.append(o)

        hub = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 99))
        hub.merge_many([w.export_delta() for w in dense_writers])

        oracle = MapCrdt("hub", wall_clock=FakeClock(start=BASE + 99))
        for o in oracle_writers:
            oracle.merge(o.record_map())

        recs = oracle.record_map()
        for slot in range(N):
            if slot not in recs:
                assert not bool(hub.store.occupied[slot])
                continue
            r = recs[slot]
            assert bool(hub.store.occupied[slot])
            assert int(hub.store.lt[slot]) == r.hlc.logical_time
            assert (hub._table.id_of(int(hub.store.node[slot]))
                    == r.hlc.node_id)
            assert bool(hub.store.tomb[slot]) == r.is_deleted
            if not r.is_deleted:
                assert int(hub.store.val[slot]) == r.value


class TestWireInterop:
    """Every replica speaks the JSON wire format (crdt_json.dart:8-37);
    a dense replica must round-trip the reference-golden int-key JSON
    and converge with record-dict backends."""

    GOLDEN_MILLIS = 1000000000000
    GOLDEN_ISO = "2001-09-09T01:46:40.000Z"

    def test_golden_json_export(self):
        # Reference int-key golden (map_crdt_test.dart:135-139 shape):
        # {"1":{"hlc":"<iso>-0000-<node>","value":1}}
        c = DenseCrdt("abc", N,
                      wall_clock=FakeClock(start=self.GOLDEN_MILLIS - 1))
        c.put_batch([1], [1])   # stamped at exactly GOLDEN_MILLIS
        assert c.to_json() == (
            '{"1":{"hlc":"%s-0000-abc","value":1}}' % self.GOLDEN_ISO)

    def test_golden_json_ingest(self):
        c = DenseCrdt("xyz", N,
                      wall_clock=FakeClock(start=self.GOLDEN_MILLIS + 5))
        c.merge_json(
            '{"1":{"hlc":"%s-0000-abc","value":1}}' % self.GOLDEN_ISO)
        assert c.get(1) == 1
        assert c._table.id_of(int(c.store.node[1])) == "abc"
        assert int(c.store.lt[1]) == self.GOLDEN_MILLIS << 16

    def test_json_roundtrip_with_tombstone(self):
        a = make("na")
        a.put_batch([0, 3], [7, 8])
        a.delete_batch([3])
        b = make("nb", BASE + 50)
        b.merge_json(a.to_json())
        assert b.get(0) == 7 and b.get(3) is None
        assert bool(b.store.tomb[3]) and bool(b.store.occupied[3])

    def test_mixed_backend_convergence(self):
        # DenseCrdt ↔ MapCrdt ↔ TpuMapCrdt over the JSON wire: all three
        # converge to the same live map (int keys).
        from crdt_tpu import MapCrdt, TpuMapCrdt
        d = DenseCrdt("dd", N, wall_clock=FakeClock(start=BASE))
        m = MapCrdt("mm", wall_clock=FakeClock(start=BASE + 3))
        t = TpuMapCrdt("tt", wall_clock=FakeClock(start=BASE + 7))
        d.put_batch([0, 1], [10, 11])
        m.put(2, 22)
        t.put(3, 33)
        t.delete(3)

        m.merge_json(d.to_json(), key_decoder=int)
        t.merge_json(m.to_json(), key_decoder=int)
        d.merge_json(t.to_json())
        m.merge_json(d.to_json(), key_decoder=int)
        t.merge_json(d.to_json(), key_decoder=int)

        expect = {0: 10, 1: 11, 2: 22}
        assert {k: v for k, v in d.record_map().items()
                if not v.is_deleted} == {
            k: r for k, r in m.record_map().items() if not r.is_deleted}
        assert m.map == expect and t.map == expect
        assert {s: d.get(s) for s in expect} == expect
        assert d.get(3) is None and bool(d.store.tomb[3])

    def test_record_map_matches_oracle_after_merge(self):
        # Full record-level parity (hlc + value + modified semantics)
        # between the dense record_map export and a MapCrdt peer that
        # merged the same wire payload.
        from crdt_tpu import MapCrdt
        src = DenseCrdt("src", N, wall_clock=FakeClock(start=BASE))
        src.put_batch([4, 9], [44, 99])
        wire = src.to_json()
        m = MapCrdt("mm", wall_clock=FakeClock(start=BASE + 9))
        m.merge_json(wire, key_decoder=int)
        d = DenseCrdt("mm", N, wall_clock=FakeClock(start=BASE + 9))
        d.merge_json(wire)
        dm, mm = d.record_map(), m.record_map()
        assert set(dm) == set(mm)
        for k in dm:
            assert dm[k].hlc == mm[k].hlc
            assert dm[k].value == mm[k].value

    def test_non_int_value_rejected_loudly(self):
        # Truncating would silently diverge under the peer's hlc.
        from crdt_tpu import MapCrdt
        m = MapCrdt("mm", wall_clock=FakeClock(start=BASE))
        m.put(1, "not-an-int")
        d = make("dd")
        with pytest.raises(TypeError):
            d.merge_json(m.to_json())
        assert len(d) == 0

    def test_wire_guard_order_matches_oracle(self):
        # Guards must follow the PAYLOAD's iteration order (the
        # reference visit order, crdt.dart:80-85), not slot order: a
        # high-lt foreign record earlier in the payload shields a
        # later own-id record via the recv fast path (hlc.dart:85).
        from crdt_tpu import Hlc, MapCrdt, Record
        h_high = Hlc(BASE + 20, 0, "xx")
        h_own = Hlc(BASE + 10, 0, "dd")
        recs_ok = {5: Record(h_high, 50, h_high),
                   0: Record(h_own, 9, h_own)}
        d = DenseCrdt("dd", N, wall_clock=FakeClock(start=BASE + 30))
        d.merge_records(dict(recs_ok))          # shielded: no raise
        m = MapCrdt("dd", wall_clock=FakeClock(start=BASE + 30))
        m.merge(dict(recs_ok))
        assert d.get(5) == m.get(5) == 50 and d.get(0) == m.get(0) == 9

        # Reversed payload order: the own-id record is visited first,
        # unshielded — BOTH backends raise the same duplicate payload.
        recs_bad = {0: Record(h_own, 9, h_own),
                    5: Record(h_high, 50, h_high)}
        d2 = DenseCrdt("dd", N, wall_clock=FakeClock(start=BASE + 30))
        m2 = MapCrdt("dd", wall_clock=FakeClock(start=BASE + 30))
        errs = []
        for merge in (lambda: d2.merge_records(dict(recs_bad)),
                      lambda: m2.merge(dict(recs_bad))):
            with pytest.raises(DuplicateNodeException) as ei:
                merge()
            errs.append(ei.value)
        assert str(errs[0]) == str(errs[1])
        assert (d2.canonical_time.logical_time
                == m2.canonical_time.logical_time)
        assert len(d2) == 0                      # store untouched

    def test_delta_export_since_over_json(self):
        a = make("na")
        a.put_batch([0], [1])
        t = a.canonical_time
        a.put_batch([1], [2])
        payload = a.to_json(modified_since=a.canonical_time)
        assert '"1"' in payload and '"0"' not in payload
        full = a.to_json(modified_since=t)   # inclusive bound
        assert '"0"' in full and '"1"' in full


class TestWatch:
    """C13 on the dense model: per-slot/whole-store change streams,
    emitted host-side from the fan-in win mask (crdt.dart:162-164)."""

    def test_put_delete_events(self):
        c = make()
        stream = c.watch().record()
        c.put_batch([1, 2], [10, 20])
        c.delete_batch([1])
        assert stream.events == [(1, 10), (2, 20), (1, None)]

    def test_per_slot_filter(self):
        c = make()
        s = c.watch(slot=2).record()
        c.put_batch([1, 2], [10, 20])
        c.put_batch([2], [21])
        assert s.events == [(2, 20), (2, 21)]

    def test_merge_emits_winners_only(self):
        a, b = make("na"), make("nb", BASE + 5)
        a.put_batch([0], [1])
        b.put_batch([0], [2])          # later wall clock: wins on a
        b.put_batch([1], [3])
        s = a.watch().record()
        a.merge(*b.export_delta())
        assert s.events == [(0, 2), (1, 3)]
        # Merging already-known state back emits nothing (no winners).
        s2 = b.watch().record()
        b.merge(*a.export_delta())
        assert s2.events == []

    def test_merge_tombstone_event_is_none(self):
        a, b = make("na"), make("nb", BASE + 5)
        a.put_batch([4], [9])
        sync_dense(a, b)
        b.delete_batch([4])
        s = a.watch(slot=4).record()
        a.merge(*b.export_delta())
        assert s.events == [(4, None)]

    def test_unsubscribe(self):
        c = make()
        seen = []
        stream = c.watch()
        off = stream.listen(seen.append)
        c.put_batch([0], [1])
        off()
        c.put_batch([1], [2])
        assert seen == [(0, 1)]
        # With every subscriber gone the hub reads inactive again, so
        # bulk paths skip host emission entirely.
        assert not c._hub.active

    def test_watch_cycles_do_not_accumulate_streams(self):
        c = make()
        for _ in range(5):
            off = c.watch().listen(lambda e: None)
            off()
        assert c._hub._streams == []
        # re-listening on a detached stream re-attaches it
        s = c.watch()
        s.listen(lambda e: None)()
        got = []
        s.listen(got.append)
        c.put_batch([0], [1])
        assert got == [(0, 1)]


class TestResume:
    def test_checkpoint_roundtrip(self, tmp_path):
        a = make()
        a.put_batch([0, 7], [5, 6])
        a.delete_batch([7])
        p = str(tmp_path / "dense.npz")
        save_dense(a.store, p)
        back = DenseCrdt("na", N, wall_clock=FakeClock(start=BASE + 999),
                         store=load_dense(p))
        assert back.get(0) == 5 and back.get(7) is None
        # Resume rebuilt the clock from the lanes (crdt.dart:114-121).
        assert (back.canonical_time.logical_time
                == a.canonical_time.logical_time)

    def test_snapshot_preserves_foreign_attribution(self, tmp_path):
        # Ordinal lanes index the node table; a snapshot without it
        # cannot attribute foreign records after resume.
        a, b = make("na"), make("nb", BASE + 5)
        b.put_batch([2], [22])
        a.merge(*b.export_delta())
        p = str(tmp_path / "a.npz")
        a.save(p)
        back = DenseCrdt.load("na", p,
                              wall_clock=FakeClock(start=BASE + 999))
        assert back.record_map()[2].hlc.node_id == "nb"
        assert back.to_json() == a.to_json()
        # Resume rebuilds the clock from the stored lanes — the volatile
        # post-merge send bump is deliberately NOT persisted
        # (refreshCanonicalTime, crdt.dart:114-121).
        from crdt_tpu.ops.dense import dense_max_logical_time
        assert (back.canonical_time.logical_time
                == int(dense_max_logical_time(a.store)))

    def test_resume_under_mid_sorting_new_id(self, tmp_path):
        # Resuming under a node id that sorts INTO the stored table
        # must re-encode the ordinal lanes, not shift attribution.
        z = make("nz")
        b = make("nb", BASE + 3)
        b.put_batch([0], [1])
        z.put_batch([1], [2])
        z.merge(*b.export_delta())
        p = str(tmp_path / "z.npz")
        z.save(p)
        taken = DenseCrdt.load("nc", p,   # 'nc' sorts between nb and nz
                               wall_clock=FakeClock(start=BASE + 999))
        assert taken.record_map()[0].hlc.node_id == "nb"
        assert taken.record_map()[1].hlc.node_id == "nz"

    def test_lane_only_snapshot_still_loads(self, tmp_path):
        from crdt_tpu.checkpoint import load_dense_node_ids
        a = make()
        a.put_batch([0], [5])
        p = str(tmp_path / "lanes.npz")
        save_dense(a.store, p)   # store-level: no table
        assert load_dense_node_ids(p) is None
        back = DenseCrdt("na", N, store=load_dense(p),
                         wall_clock=FakeClock(start=BASE + 999))
        assert back.get(0) == 5
        # ...but the model-level loader refuses it: without the table
        # the ordinal lanes would be silently re-attributed.
        with pytest.raises(ValueError):
            DenseCrdt.load("na", p)

    def test_stats(self):
        a, b = make("na"), make("nb", BASE + 5)
        a.put_batch([0, 1], [1, 2])
        sync_dense(a, b)
        assert b.stats.merges == 1
        assert b.stats.records_adopted == 2


class TestPallasExecutor:
    """DenseCrdt(executor="pallas-interpret") — the Mosaic merge path
    through the MODEL API, differential against the XLA executor.
    Interpret mode stands in for the chip (tile-aligned slot count)."""

    NP = 8192  # TILE-aligned

    def make_pair(self):
        k = dict(wall_clock=FakeClock(start=BASE))
        return (DenseCrdt("ns", self.NP, executor="pallas-interpret", **k),
                DenseCrdt("ns", self.NP, executor="xla",
                          wall_clock=FakeClock(start=BASE)))

    def assert_equal(self, a, b):
        for lane in ("lt", "node", "val", "mod_lt", "mod_node",
                     "occupied", "tomb"):
            occ = np.asarray(b.store.occupied)
            np.testing.assert_array_equal(
                np.asarray(getattr(a.store, lane))[occ],
                np.asarray(getattr(b.store, lane))[occ], err_msg=lane)
        np.testing.assert_array_equal(np.asarray(a.store.occupied),
                                      np.asarray(b.store.occupied))
        assert a.canonical_time == b.canonical_time

    def test_multi_peer_merge_matches_xla(self):
        pal, xla = self.make_pair()
        peers = []
        for i, name in enumerate(["p1", "p2", "p3"]):
            p = DenseCrdt(name, self.NP,
                          wall_clock=FakeClock(start=BASE + i))
            p.put_batch(jnp.arange(i * 5, i * 5 + 20),
                        jnp.arange(20, dtype=jnp.int64) + 100 * i)
            if i == 1:
                p.delete_batch(jnp.arange(7, 9))
            peers.append(p.export_delta())
        pal.merge_many(peers)
        xla.merge_many(peers)
        self.assert_equal(pal, xla)
        assert pal.stats.records_adopted == xla.stats.records_adopted

    def test_dup_guard_same_exception_payload(self):
        pal, xla = self.make_pair()
        bad = DenseCrdt("ns", self.NP,
                        wall_clock=FakeClock(start=BASE + 50))
        bad.put_batch([3], [1])  # same node id, clock ahead
        delta = bad.export_delta()
        errs = []
        for c in (pal, xla):
            with pytest.raises(DuplicateNodeException) as ei:
                c.merge_many([delta])
            errs.append((str(ei.value),
                         c.canonical_time.logical_time))
        assert errs[0] == errs[1]

    def test_shielded_false_positive_cleared(self):
        # A local-ordinal record shielded by an earlier larger record
        # trips the optimistic flags but must NOT raise: the exact
        # host recompute clears it and the merge proceeds.
        pal, xla = self.make_pair()
        peer = DenseCrdt("peer", self.NP,
                         wall_clock=FakeClock(start=BASE + 80))
        peer.put_batch([0], [11])
        shield_cs, ids = peer.export_delta()
        # Forge a changeset where row 0 (earlier) carries the larger
        # foreign record and row 1 a smaller LOCAL-node record: the
        # exact sequential order shields row 1.
        import jax.numpy as j
        lt_hi = int(shield_cs.lt.max())
        forged = type(shield_cs)(
            lt=j.stack([shield_cs.lt[0],
                        j.full_like(shield_cs.lt[0], 0).at[5].set(
                            lt_hi - (1 << 16))]),
            node=j.stack([shield_cs.node[0],
                          j.zeros_like(shield_cs.node[0])]),
            val=j.stack([shield_cs.val[0],
                         j.zeros_like(shield_cs.val[0])]),
            tomb=j.stack([shield_cs.tomb[0],
                          j.zeros_like(shield_cs.tomb[0])]),
            valid=j.stack([shield_cs.valid[0],
                           j.zeros_like(shield_cs.valid[0]).at[5].set(
                               True)]),
        )
        ids2 = list(ids) + ["ns"]
        forged = forged._replace(
            node=forged.node.at[1, 5].set(ids2.index("ns")))
        for c in (pal, xla):
            c.merge_many([(forged, ids2)])
        self.assert_equal(pal, xla)
        assert pal.get(0) == 11


class TestGrow:
    def test_grow_preserves_records_and_clock(self):
        a = DenseCrdt("na", N, wall_clock=FakeClock(start=BASE))
        a.put_batch([0, N - 1], [10, 20])
        a.delete_batch([0])
        canon = a.canonical_time
        a.grow(4 * N)
        assert a.n_slots == 4 * N
        assert a.is_deleted(0) is True
        assert a.get(N - 1) == 20
        assert a.get(2 * N) is None and not a.contains_slot(2 * N)
        assert a.canonical_time == canon
        # New capacity is writable and mergeable.
        a.put_batch([3 * N], [33])
        b = DenseCrdt("nb", 4 * N, wall_clock=FakeClock(start=BASE + 9))
        sync_dense(a, b)
        assert b.get(3 * N) == 33 and b.get(N - 1) == 20

    def test_grow_refuses_shrink(self):
        a = DenseCrdt("na", N, wall_clock=FakeClock(start=BASE))
        with pytest.raises(ValueError):
            a.grow(N - 1)

    def test_mixed_capacity_sync(self):
        # Staggered grow rollout: the grown replica ingests narrow
        # changesets; the ungrown peer gets a clear error (not an XLA
        # shape crash) until it grows too.
        a = DenseCrdt("na", N, wall_clock=FakeClock(start=BASE))
        b = DenseCrdt("nb", N, wall_clock=FakeClock(start=BASE + 5))
        a.grow(2 * N)
        b.put_batch([3], [33])
        a.merge(*b.export_delta())         # narrow -> wide: pads
        assert a.get(3) == 33
        a.put_batch([N + 1], [44])
        with pytest.raises(ValueError, match=r"grow\(128\)"):
            b.merge(*a.export_delta())     # wide -> narrow: explicit
        b.grow(2 * N)
        sync_dense(a, b)
        assert b.get(N + 1) == 44 and b.get(3) == 33

    def test_grow_forced_pallas_requires_alignment(self):
        from crdt_tpu.ops.pallas_merge import TILE
        a = DenseCrdt("na", TILE, wall_clock=FakeClock(start=BASE),
                      executor="pallas-interpret")
        with pytest.raises(ValueError, match=str(TILE)):
            a.grow(TILE + 16)
        a.grow(2 * TILE)                   # aligned growth fine
        assert a.n_slots == 2 * TILE

    @pytest.mark.parametrize("seed", range(2))
    def test_fuzz_mixed_capacity_convergence(self, seed):
        # Random ops + staggered growth across 3 replicas; all must
        # converge once everyone reaches the final capacity.
        import random
        rng = random.Random(seed * 7 + 1)
        caps = [N, N, N]
        reps = [DenseCrdt(f"n{i}", N,
                          wall_clock=FakeClock(start=BASE + i * 3))
                for i in range(3)]
        for step in range(30):
            i = rng.randrange(3)
            r = reps[i]
            op = rng.random()
            if op < 0.5:
                s = rng.randrange(caps[i])
                r.put_batch([s], [rng.randrange(1000)])
            elif op < 0.7 and len(r):
                r.delete_batch([rng.randrange(caps[i])])
            elif op < 0.85 and caps[i] < 4 * N:
                caps[i] *= 2
                r.grow(caps[i])
            else:
                j = rng.randrange(3)
                if j != i and caps[j] == caps[i]:
                    sync_dense(reps[j], r)
                elif j != i and caps[j] > caps[i]:
                    reps[j].merge(*r.export_delta())
        for r, c in zip(reps, caps):
            if c < 4 * N:
                r.grow(4 * N)
        for _ in range(2):
            for i in range(3):
                for j in range(3):
                    if i != j:
                        reps[j].merge(*reps[i].export_delta())
        base = np.asarray(reps[0].store.occupied)
        for r in reps[1:]:
            np.testing.assert_array_equal(np.asarray(r.store.occupied),
                                          base)
            for lane in ("lt", "node", "val", "tomb"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(r.store, lane))[base],
                    np.asarray(getattr(reps[0].store, lane))[base],
                    err_msg=lane)

    def test_grow_sharded_stays_sharded(self):
        import jax
        from crdt_tpu import ShardedDenseCrdt
        from crdt_tpu.parallel import make_fanin_mesh
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = make_fanin_mesh(2, 4)
        a = ShardedDenseCrdt("na", N, mesh, wall_clock=FakeClock(start=BASE))
        a.put_batch([1], [5])
        a.grow(2 * N)
        assert a.get(1) == 5 and a.n_slots == 2 * N
        with pytest.raises(ValueError):
            a.grow(2 * N + 3)  # not divisible by key shards
        b = DenseCrdt("nb", 2 * N, wall_clock=FakeClock(start=BASE + 3))
        b.put_batch([N + 5], [7])
        sync_dense(a, b)
        assert a.get(N + 5) == 7 and b.get(1) == 5


class TestSparseWireDelta:
    """merge_records is O(k): slot-indexed sparse scatter, equivalent
    to the full-width changeset join lane-for-lane."""

    @staticmethod
    def _full_width_merge(crdt, record_map):
        """The pre-sparse formulation: absorb host-side, then
        materialize an [1, n_slots] DenseChangeset and run the fused
        fan-in — the old merge_records shape, kept as the oracle."""
        wall = crdt._wall_clock()
        for rec in record_map.values():
            crdt._canonical_time = Hlc.recv(
                crdt._canonical_time, rec.hlc, millis=wall)
        ids = sorted({r.hlc.node_id for r in record_map.values()})
        id_to_ord = {nid: i for i, nid in enumerate(ids)}
        n = crdt.n_slots
        lanes = dict(lt=np.zeros((n,), np.int64),
                     node=np.zeros((n,), np.int32),
                     val=np.zeros((n,), np.int64),
                     tomb=np.zeros((n,), bool),
                     valid=np.zeros((n,), bool))
        for slot, rec in record_map.items():
            lanes["lt"][slot] = rec.hlc.logical_time
            lanes["node"][slot] = id_to_ord[rec.hlc.node_id]
            lanes["val"][slot] = 0 if rec.value is None else int(rec.value)
            lanes["tomb"][slot] = rec.is_deleted
            lanes["valid"][slot] = True
        from crdt_tpu.ops.dense import DenseChangeset
        cs = DenseChangeset(**{k: jnp.asarray(v)[None]
                               for k, v in lanes.items()})
        crdt.merge(cs, ids)

    @pytest.mark.parametrize("seed", range(6))
    def test_matches_full_width_join(self, seed):
        from crdt_tpu.testing import assert_dense_stores_equal
        from crdt_tpu import Record
        rng = np.random.default_rng(seed)
        k = int(rng.integers(1, 40))
        slots = rng.choice(N, size=k, replace=False)
        nodes = ["nb", "nc", "nd"]
        recs = {}
        for s in slots:
            h = Hlc(BASE + int(rng.integers(0, 5)),
                    int(rng.integers(0, 3)), nodes[int(rng.integers(3))])
            v = None if rng.random() < 0.3 else int(rng.integers(100))
            recs[int(s)] = Record(h, v, h)
        a, b = make(), make()
        # Pre-seed both with identical local state so LWW ties and
        # occupied-slot compares are exercised.
        a.put_batch([0, 1, 2], [7, 8, 9])
        b.put_batch([0, 1, 2], [7, 8, 9])
        a.merge_records(dict(recs))          # sparse path
        self._full_width_merge(b, dict(recs))  # full-width oracle
        assert_dense_stores_equal(a.store, b.store, "sparse vs full")
        assert a.canonical_time == b.canonical_time

    def test_host_and_transfer_cost_is_delta_sized(self, monkeypatch):
        """A k-record delta into a large store must ship k-wide arrays
        to the device, not n_slots-wide lanes."""
        import crdt_tpu.models.dense_crdt as m
        big = DenseCrdt("na", 1 << 16, wall_clock=FakeClock(start=BASE))
        seen = {}
        real = m.sparse_fanin_step

        def spy(store, slot, lt, *args, **kw):
            seen["width"] = slot.shape[0]
            return real(store, slot, lt, *args, **kw)

        monkeypatch.setattr(m, "sparse_fanin_step", spy)
        h = Hlc(BASE + 1, 0, "nb")
        from crdt_tpu import Record
        big.merge_records({5: Record(h, 1, h), 9: Record(h, 2, h),
                           (1 << 16) - 1: Record(h, 3, h)})
        assert seen["width"] == 4  # 3 records padded to pow2, not 65536
        assert big.get(5) == 1 and big.get((1 << 16) - 1) == 3

    def test_sharded_merge_records_stays_sharded(self):
        import jax
        from crdt_tpu.models.dense_crdt import ShardedDenseCrdt
        from crdt_tpu.parallel import make_fanin_mesh
        if jax.device_count() < 8:
            pytest.skip("needs an 8-device mesh")
        mesh = make_fanin_mesh(2, 4)
        c = ShardedDenseCrdt("na", N, mesh,
                             wall_clock=FakeClock(start=BASE))
        h = Hlc(BASE + 1, 0, "nb")
        from crdt_tpu import Record
        c.merge_records({3: Record(h, 30, h)})
        assert c.get(3) == 30
        # The key axis sharding survives the sparse scatter.
        shardings = {str(c.store.lt.sharding), str(c.store.val.sharding)}
        assert len(shardings) == 1 and "key" in shardings.pop()


class TestFastJsonExport:
    """The lane-direct to_json must be byte-identical to the generic
    Record-dict encoder, falling back whenever it can't be."""

    def _populated(self, node="na"):
        a, b = make(node), make("nb", BASE + 5)
        a.put_batch([0, 3, 7], [10, 30, 70])
        b.put_batch([3, 9], [31, 90])
        b.delete_batch([9])
        sync_dense(a, b)
        return a

    def test_matches_generic_encoder(self):
        from crdt_tpu import crdt_json
        a = self._populated()
        generic = crdt_json.encode(a.record_map())
        assert a.to_json() == generic
        # Delta export too (inclusive bound).
        t = a.canonical_time
        a.put_batch([1], [11])
        assert a.to_json(modified_since=t) == crdt_json.encode(
            a.record_map(modified_since=t))

    def test_empty_store(self):
        assert make().to_json() == "{}"

    def test_escape_needing_node_id_falls_back(self):
        import json
        a = self._populated(node='quo"te\\n')
        out = a.to_json()
        parsed = json.loads(out)          # still valid JSON
        assert any('quo"te' in v["hlc"] for v in parsed.values())
        from crdt_tpu import crdt_json
        assert out == crdt_json.encode(a.record_map())

    def test_round_trips_through_merge_json(self):
        a = self._populated()
        c = make("nc", BASE + 50)
        c.merge_json(a.to_json())
        assert c.record_map() == a.record_map()


class TestWriteDonationSafety:
    """Write scatters may donate store buffers only while the current
    snapshot never escaped via the public `store` property (a held
    snapshot must stay readable). Donation itself is backend-gated
    (off on CPU); the ownership tracking is what's tested here."""

    def test_escape_tracking(self):
        c = DenseCrdt("n", 256, wall_clock=FakeClock())
        assert c._store_escaped is False
        _ = c.store
        assert c._store_escaped is True
        assert c._donate_writes() is False   # escaped -> never donate
        c.put_batch([1], [10])
        assert c._store_escaped is False     # fresh post-write snapshot
        c.delete_batch([1])
        assert c._store_escaped is False

    def test_caller_supplied_store_counts_as_escaped(self):
        a = DenseCrdt("n", 256, wall_clock=FakeClock())
        a.put_batch([0, 1], [5, 6])
        held = a.store
        b = DenseCrdt("n", 256, wall_clock=FakeClock(), store=held,
                      node_ids=["n"])
        assert b._store_escaped is True
        assert b._donate_writes() is False
        b.put_batch([2], [7])
        # the caller's snapshot must still be readable afterwards
        assert int(held.val[0]) == 5

    def test_held_snapshot_survives_writes(self):
        c = DenseCrdt("n", 256, wall_clock=FakeClock())
        c.put_batch([0], [1])
        snap = c.store
        for i in range(3):
            c.put_batch([i + 1], [i])
        assert int(snap.val[0]) == 1         # old snapshot intact
        assert int(c.store.val[3]) == 2

    def test_values_and_export_escape_tracking(self):
        c = DenseCrdt("n", 256, wall_clock=FakeClock())
        c.put_batch([0], [1])
        _ = c.values           # raw lane handed out
        assert c._store_escaped is True
        c.put_batch([1], [2])  # resets after the write
        assert c._store_escaped is False
        c.export_delta()
        assert c._store_escaped is True


class TestPipelined:
    """`DenseCrdt.pipelined()` — zero-host-sync merge windows."""

    def _batches(self, n=64, count=4, base=BASE):
        out = []
        for p in range(count):
            peer = DenseCrdt(f"p{p}", n,
                             wall_clock=FakeClock(start=base + p * 7))
            peer.put_batch(list(range(0, n, p + 2)),
                           [p * 100 + i for i in range(0, n, p + 2)])
            peer.delete_batch([1, 3])
            out.append(peer.export_delta())
        return out

    def test_bit_identical_to_unpipelined(self):
        batches = self._batches()
        a = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE + 500))
        b = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE + 500))
        for cs, ids in batches:
            a.merge(cs, ids)
        with b.pipelined():
            for cs, ids in batches:
                b.merge(cs, ids)
        from crdt_tpu.testing import assert_dense_stores_equal
        assert_dense_stores_equal(a.store, b.store)
        assert a.canonical_time == b.canonical_time
        assert a.record_map() == b.record_map()

    def test_guard_trip_raises_at_flush(self):
        from crdt_tpu import PipelinedGuardError
        a = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE))
        peer = DenseCrdt("na", 64,           # duplicate node id!
                         wall_clock=FakeClock(start=BASE + 999))
        peer.put_batch([0], [1])
        cs, ids = peer.export_delta()
        with pytest.raises(PipelinedGuardError, match="recv-guard"):
            with a.pipelined():
                a.merge(cs, ids)     # no raise here (deferred)...
        # ...and the clock still materialized at flush
        assert a.canonical_time.millis >= BASE

    def test_local_writes_refused_inside_window(self):
        a = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE))
        with pytest.raises(RuntimeError, match="pipelined"):
            with a.pipelined():
                a.put_batch([0], [1])

    def test_windows_do_not_nest(self):
        a = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE))
        with pytest.raises(RuntimeError, match="nest"):
            with a.pipelined():
                with a.pipelined():
                    pass

    def test_empty_merge_in_window_bumps_clock(self):
        a = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE))
        before = a.canonical_time
        with a.pipelined():
            a.merge_many([])
        assert a.canonical_time > before

    def test_sharded_pipelined_matches(self):
        from crdt_tpu import ShardedDenseCrdt
        from crdt_tpu.parallel import make_fanin_mesh
        from crdt_tpu.testing import assert_dense_stores_equal
        batches = self._batches()
        mesh = make_fanin_mesh(2, 4)
        a = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE + 500))
        b = ShardedDenseCrdt("na", 64, mesh,
                             wall_clock=FakeClock(start=BASE + 500))
        for cs, ids in batches:
            a.merge(cs, ids)
        with b.pipelined():
            for cs, ids in batches:
                b.merge(cs, ids)
        assert_dense_stores_equal(a.store, b.store)
        assert a.canonical_time == b.canonical_time

    def test_flush_never_shadows_inflight_exception(self):
        # A guard flag set earlier in the window must not replace the
        # exception that actually interrupted the body.
        a = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE))
        peer = DenseCrdt("na", 64,           # duplicate node id
                         wall_clock=FakeClock(start=BASE + 999))
        peer.put_batch([0], [1])
        cs, ids = peer.export_delta()
        with pytest.raises(KeyError, match="boom"):
            with a.pipelined():
                a.merge(cs, ids)             # sets the guard flag
                raise KeyError("boom")       # the REAL error

    def test_send_overflow_flag_raises_at_flush(self):
        from crdt_tpu import PipelinedGuardError
        # Drive the device send bump into counter overflow: a frozen
        # wall clock at the canonical's millis makes every bump an
        # increment; start the counter at MAX via a merged record.
        from crdt_tpu.hlc import MAX_COUNTER
        frozen = lambda: BASE
        a = DenseCrdt("na", 64, wall_clock=frozen)
        peer = DenseCrdt("np", 64, wall_clock=FakeClock(start=BASE - 10))
        peer.put_batch([0], [1])
        cs, ids = peer.export_delta()
        # craft a changeset whose max lt sits at (BASE, MAX_COUNTER):
        # after absorption the device send bump must overflow.
        import jax.numpy as jnp
        cs = cs._replace(lt=jnp.where(cs.valid,
                                      (BASE << 16) | MAX_COUNTER,
                                      cs.lt))
        with pytest.raises(PipelinedGuardError, match="overflow"):
            with a.pipelined():
                a.merge(cs, ids)

    def test_send_drift_flag_raises_at_flush(self):
        from crdt_tpu import PipelinedGuardError
        from crdt_tpu.hlc import MAX_DRIFT
        # Wall clock far BEHIND the canonical: seed the high canonical
        # through the raw putRecords primitive (no clock involvement,
        # so no recv guard fires on the way in), then the device send
        # bump sees millis - wall > MAX_DRIFT.
        from crdt_tpu import Hlc, Record
        a = DenseCrdt("na", 64,
                      wall_clock=FakeClock(start=BASE - MAX_DRIFT - 10_000))
        h = Hlc(BASE, 0, "np")
        a.put_slot_records({0: Record(h, 1, h)})
        a.refresh_canonical_time()
        with pytest.raises(PipelinedGuardError, match="send drift"):
            with a.pipelined():
                a.merge_many([])   # empty merge still bumps the clock


    def test_flush_names_first_flagged_merge(self):
        from crdt_tpu import PipelinedGuardError
        a = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE))
        good = DenseCrdt("ng", 64, wall_clock=FakeClock(start=BASE + 3))
        good.put_batch([5], [1])
        bad = DenseCrdt("na", 64,            # duplicate node id
                        wall_clock=FakeClock(start=BASE + 999))
        bad.put_batch([0], [1])
        gcs, gids = good.export_delta()
        bcs, bids = bad.export_delta()
        with pytest.raises(PipelinedGuardError, match="#2 of 4"):
            with a.pipelined():
                a.merge(gcs, gids)        # 0: clean
                a.merge_many([])          # 1: empty, still a slot
                a.merge(bcs, bids)        # 2: trips
                a.merge(gcs, gids)        # 3: clean

    @pytest.mark.parametrize("seed", range(3))
    def test_randomized_window_differential(self, seed):
        # Random mixes of peer merges, empty merges, and value shapes
        # through one pipelined window vs the same sequence unpipelined
        # — lanes, clock, and stats must match exactly.
        import random
        rng = random.Random(seed * 31 + 7)
        n = 256
        batches = []
        for i in range(6):
            p = DenseCrdt(f"p{i}", n,
                          wall_clock=FakeClock(start=BASE + rng.randrange(50)))
            slots = rng.sample(range(n), rng.randrange(1, 64))
            p.put_batch(slots, [rng.randrange(-2 ** 40, 2 ** 40)
                                for _ in slots])
            if rng.random() < 0.5:
                p.delete_batch(slots[:3])
            batches.append(p.export_delta())
        seq = [rng.choice([None, *range(len(batches))])
               for _ in range(10)]
        a = DenseCrdt("na", n, wall_clock=FakeClock(start=BASE + 500))
        b = DenseCrdt("na", n, wall_clock=FakeClock(start=BASE + 500))
        for s in seq:
            if s is None:
                a.merge_many([])
            else:
                a.merge(*batches[s])
        with b.pipelined():
            for s in seq:
                if s is None:
                    b.merge_many([])
                else:
                    b.merge(*batches[s])
        from crdt_tpu.testing import assert_dense_stores_equal
        assert_dense_stores_equal(a.store, b.store, f"seed={seed}")
        assert a.canonical_time == b.canonical_time
        assert a.stats.records_seen == b.stats.records_seen
        assert a.stats.records_adopted == b.stats.records_adopted


class TestValueWidth32:
    """The value-ref mode (`value_width=32`): int32 payloads/table
    indices in a single narrow kernel lane, identical semantics."""

    def _peer_batches(self, n=8192, lo=-(2 ** 31), hi=2 ** 31):
        import numpy as np
        rng = np.random.default_rng(3)
        peers = []
        for i in range(3):
            p = DenseCrdt(f"p{i}", n,
                          wall_clock=FakeClock(start=BASE + i * 5))
            slots = rng.choice(n, 500, replace=False)
            p.put_batch(slots, rng.integers(lo, hi, 500))
            peers.append(p.export_delta())
        return peers

    def test_matches_wide_replica(self):
        from crdt_tpu.ops.pallas_merge import TILE
        batches = self._peer_batches(n=TILE)
        wide = DenseCrdt("na", TILE, wall_clock=FakeClock(start=BASE),
                         executor="pallas-interpret")
        narrow = DenseCrdt("na", TILE, wall_clock=FakeClock(start=BASE),
                           executor="pallas-interpret", value_width=32)
        for cs, ids in batches:
            wide.merge(cs, ids)
            narrow.merge(cs, ids)
        from crdt_tpu.testing import assert_dense_stores_equal
        assert_dense_stores_equal(wide.store, narrow.store)
        assert wide.canonical_time == narrow.canonical_time

    def test_host_write_rejects_wide_values(self):
        c = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE),
                      value_width=32)
        with pytest.raises(ValueError, match="int32"):
            c.put_batch([0], [2 ** 40])
        c.put_batch([0], [-(2 ** 31)])      # boundary fits
        assert c.get(0) == -(2 ** 31)

    def test_merge_rejects_wide_values_whole(self):
        from crdt_tpu.ops.pallas_merge import TILE
        peer = DenseCrdt("np", TILE, wall_clock=FakeClock(start=BASE))
        peer.put_batch([1, 2], [5, 2 ** 40])
        cs, ids = peer.export_delta()
        c = DenseCrdt("na", TILE, wall_clock=FakeClock(start=BASE + 9),
                      executor="pallas-interpret", value_width=32)
        before = c.canonical_time
        with pytest.raises(ValueError, match="int32"):
            c.merge(cs, ids)
        assert len(c.record_map()) == 0     # store untouched
        assert c.canonical_time == before

    def test_pipelined_flags_value_overflow_at_flush(self):
        from crdt_tpu import PipelinedGuardError
        from crdt_tpu.ops.pallas_merge import TILE
        peer = DenseCrdt("np", TILE, wall_clock=FakeClock(start=BASE))
        peer.put_batch([1], [2 ** 40])
        cs, ids = peer.export_delta()
        c = DenseCrdt("na", TILE, wall_clock=FakeClock(start=BASE + 9),
                      executor="pallas-interpret", value_width=32)
        with pytest.raises(PipelinedGuardError, match="value-ref"):
            with c.pipelined():
                c.merge(cs, ids)

    def test_xla_executor_enforces_width_too(self):
        # The rejection contract must not depend on the executor.
        peer = DenseCrdt("np", 64, wall_clock=FakeClock(start=BASE))
        peer.put_batch([1], [2 ** 40])
        cs, ids = peer.export_delta()
        c = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE + 9),
                      executor="xla", value_width=32)
        with pytest.raises(ValueError, match="int32"):
            c.merge(cs, ids)
        assert len(c.record_map()) == 0

    def test_put_slot_records_enforces_width(self):
        from crdt_tpu import Hlc, Record
        c = DenseCrdt("na", 64, wall_clock=FakeClock(start=BASE),
                      value_width=32)
        h = Hlc(BASE, 0, "x")
        with pytest.raises(ValueError, match="int32"):
            c.put_slot_records({0: Record(h, 2 ** 40, h)})

    def test_pipelined_overflow_skips_record_never_truncates(self):
        # The flagged record must NOT land truncated; in-range records
        # in the same changeset still merge.
        from crdt_tpu import PipelinedGuardError
        from crdt_tpu.ops.pallas_merge import TILE
        peer = DenseCrdt("np", TILE, wall_clock=FakeClock(start=BASE))
        peer.put_batch([1, 2], [2 ** 40, 7])
        cs, ids = peer.export_delta()
        c = DenseCrdt("na", TILE, wall_clock=FakeClock(start=BASE + 9),
                      executor="pallas-interpret", value_width=32)
        with pytest.raises(PipelinedGuardError, match="SKIPPED"):
            with c.pipelined():
                c.merge(cs, ids)
        assert c.get(2) == 7            # in-range record merged
        assert c.get(1) is None         # overflow record skipped,
        assert not c.contains_slot(1)   # never truncated into place


class TestColumnarWireIngest:
    """The columnar merge core (`DenseCrdt._merge_columns`): merge_json
    and merge_records route through decode_columns / records_to_columns
    + the shared `recv_fold_columns` — no per-record Hlc.recv loop.
    These pin the contracts the rewrite must preserve."""

    def test_tick_parity_with_oracle_merge_json(self):
        # Same wire payload, same CountingClock: the columnar ingest
        # must draw exactly as many wall reads as the generic path, or
        # injected-clock differentials silently diverge.
        from crdt_tpu import MapCrdt
        from crdt_tpu.testing import CountingClock
        src = DenseCrdt("src", N, wall_clock=FakeClock(start=BASE))
        src.put_batch([1, 5], [10, 50])
        src.delete_batch([5])
        wire = src.to_json()
        cd, cm = CountingClock(start=BASE + 9), CountingClock(start=BASE + 9)
        d = DenseCrdt("mm", N, wall_clock=cd)
        m = MapCrdt("mm", wall_clock=cm)
        d.merge_json(wire)
        m.merge_json(wire, key_decoder=int)
        assert cd.reads == cm.reads
        assert (d.canonical_time.logical_time
                == m.canonical_time.logical_time)
        # Empty payloads too (routes through merge_many([])).
        d.merge_json("{}")
        m.merge_json("{}")
        assert cd.reads == cm.reads
        assert (d.canonical_time.logical_time
                == m.canonical_time.logical_time)

    def test_reject_leaves_clock_untouched(self):
        # ADVICE r4: a value_width=32 replica rejecting an out-of-range
        # record must NOT have advanced its canonical clock first.
        from crdt_tpu import Hlc, Record
        d = DenseCrdt("dd", N, wall_clock=FakeClock(start=BASE),
                      value_width=32)
        before = d.canonical_time
        h = Hlc(BASE + 10, 0, "peer")
        with pytest.raises(ValueError, match="int32"):
            d.merge_records({3: Record(h, 2 ** 40, h)})
        assert d.canonical_time == before and len(d) == 0
        # Same for a non-int payload on the wire path.
        from crdt_tpu import MapCrdt
        m = MapCrdt("mm", wall_clock=FakeClock(start=BASE))
        m.put(1, "text")
        d64 = DenseCrdt("dd", N, wall_clock=FakeClock(start=BASE))
        before = d64.canonical_time
        with pytest.raises(TypeError):
            d64.merge_json(m.to_json())
        assert d64.canonical_time == before and len(d64) == 0
        # And out-of-range slots.
        src = DenseCrdt("src", N + 64, wall_clock=FakeClock(start=BASE))
        src.put_batch([N + 3], [1])
        before = d64.canonical_time
        with pytest.raises(IndexError):
            d64.merge_json(src.to_json())
        assert d64.canonical_time == before and len(d64) == 0

    def test_watch_events_on_merge_json(self):
        src = DenseCrdt("src", N, wall_clock=FakeClock(start=BASE + 5))
        src.put_batch([2, 7], [20, 70])
        src.delete_batch([7])
        d = make("dd")
        whole = d.watch().record()
        keyed = d.watch(slot=7).record()
        d.merge_json(src.to_json())
        assert sorted(whole.events) == [(2, 20), (7, None)]
        assert keyed.events == [(7, None)]

    @pytest.mark.parametrize("seed", range(3))
    def test_fuzz_merge_json_matches_oracle(self, seed):
        # Random multi-writer wire payloads (colliding millis, counter
        # ties, tombstones) ingested via the columnar path must leave
        # record-level state AND canonical identical to MapCrdt.
        import random
        from crdt_tpu import MapCrdt
        rng = random.Random(seed)
        writers = []
        for nid in ("aa", "zz", "ba"):
            w = DenseCrdt(nid, N,
                          wall_clock=FakeClock(start=BASE + rng.randrange(5)))
            for _ in range(rng.randrange(1, 4)):
                slots = sorted(rng.sample(range(N), rng.randrange(1, 12)))
                if rng.random() < 0.3:
                    w.delete_batch(slots)
                else:
                    w.put_batch(slots,
                                [rng.randrange(1000) for _ in slots])
            writers.append(w)
        d = DenseCrdt("hub", N, wall_clock=FakeClock(start=BASE + 40))
        m = MapCrdt("hub", wall_clock=FakeClock(start=BASE + 40))
        for w in writers:
            wire = w.to_json()
            d.merge_json(wire)
            m.merge_json(wire, key_decoder=int)
        assert (d.canonical_time.logical_time
                == m.canonical_time.logical_time)
        dm, mm = d.record_map(), m.record_map()
        assert set(dm) == set(mm)
        for k in dm:
            assert dm[k].hlc == mm[k].hlc and dm[k].value == mm[k].value


def test_bool_values_rejected_on_merge():
    # bool is an int subclass; storing it as 0/1 under the peer's hlc
    # would diverge forever (re-export says 1 where the peer says
    # true). Both ingest paths must reject it.
    from crdt_tpu import Hlc, MapCrdt, Record
    h = Hlc(BASE + 5, 0, "peer")
    d = make("dd")
    with pytest.raises(TypeError, match="bool"):
        d.merge_records({3: Record(h, True, h)})
    assert len(d) == 0
    m = MapCrdt("mm", wall_clock=FakeClock(start=BASE))
    m.put(1, True)
    d2 = make("dd")
    with pytest.raises(TypeError, match="bool"):
        d2.merge_json(m.to_json())
    assert len(d2) == 0


class TestSplitInterchange:
    """Pre-split changesets as a first-class interchange
    (`export_split_delta` / `merge_split`): zero-conversion gossip in
    the kernel wire form, semantics identical to the wide path."""

    NP = None  # set in setup: TILE-aligned capacity

    @classmethod
    def setup_class(cls):
        from crdt_tpu.ops.pallas_merge import TILE
        cls.NP = TILE

    def _pair(self, value_width=64):
        a = DenseCrdt("na", self.NP, executor="pallas-interpret",
                      wall_clock=FakeClock(start=BASE),
                      value_width=value_width)
        b = DenseCrdt("na", self.NP, executor="pallas-interpret",
                      wall_clock=FakeClock(start=BASE),
                      value_width=value_width)
        return a, b

    @pytest.mark.parametrize("value_width", [64, 32])
    @pytest.mark.parametrize("tiled", [True, False])
    def test_matches_wide_path(self, value_width, tiled):
        via_split, via_wide = self._pair(value_width)
        w = DenseCrdt("w", self.NP, value_width=value_width,
                      wall_clock=FakeClock(start=BASE + 5))
        w.put_batch([0, 7, self.NP - 1], [10, -70, 99])
        w.delete_batch([7])
        scs, ids = w.export_split_delta(tiled=tiled)
        via_split.merge_split(scs, ids)
        via_wide.merge(*w.export_delta())
        np.testing.assert_array_equal(
            np.asarray(via_split.store.occupied),
            np.asarray(via_wide.store.occupied))
        occ = np.asarray(via_wide.store.occupied)
        for lane in ("lt", "node", "val", "tomb", "mod_lt"):
            np.testing.assert_array_equal(
                np.asarray(getattr(via_split.store, lane))[occ],
                np.asarray(getattr(via_wide.store, lane))[occ],
                err_msg=lane)
        assert via_split.canonical_time == via_wide.canonical_time
        assert (via_split.stats.records_adopted
                == via_wide.stats.records_adopted == 3)
        assert via_split.get(7) is None and via_split.get(0) == 10

    def test_ordinal_remap(self):
        # Receiver with a DIFFERENT interning history: peer ordinals
        # must remap through the node table like the wide path.
        rcv = DenseCrdt("zz", self.NP, executor="pallas-interpret",
                        wall_clock=FakeClock(start=BASE),
                        node_ids=["m1", "m2", "zz"])
        w = DenseCrdt("aa", self.NP, wall_clock=FakeClock(start=BASE + 5))
        w.put_batch([3], [30])
        scs, ids = w.export_split_delta()
        rcv.merge_split(scs, ids)
        assert rcv.get(3) == 30
        assert rcv._table.id_of(int(rcv.store.node[3])) == "aa"

    def test_guard_parity_with_wide_path(self):
        via_split, via_wide = self._pair()
        w = DenseCrdt("na", self.NP,
                      wall_clock=FakeClock(start=BASE + 50))
        w.put_batch([1], [1])
        scs, ids = w.export_split_delta()
        errs = []
        for rcv, do in ((via_split,
                         lambda: via_split.merge_split(scs, ids)),
                        (via_wide,
                         lambda: via_wide.merge(*w.export_delta()))):
            with pytest.raises(DuplicateNodeException) as ei:
                do()
            errs.append(ei.value)
        assert errs[0].args == errs[1].args
        assert (via_split.canonical_time.logical_time
                == via_wide.canonical_time.logical_time)
        assert len(via_split) == 0

    def test_value_width_32_rejects_wide_overflow(self):
        # A value-ref replica receiving WIDE split lanes with an
        # out-of-range payload: rejected whole, replica untouched.
        from crdt_tpu.ops.pallas_merge import split_changeset
        rcv = DenseCrdt("na", self.NP, executor="pallas-interpret",
                        wall_clock=FakeClock(start=BASE),
                        value_width=32)
        w = DenseCrdt("w", self.NP, wall_clock=FakeClock(start=BASE + 5))
        w.put_batch([0, 1], [5, 2 ** 40])
        cs, ids = w.export_delta()
        with pytest.raises(ValueError, match="int32"):
            rcv.merge_split(split_changeset(cs), ids)
        assert len(rcv) == 0

    def test_capacity_mismatch_refused(self):
        rcv = DenseCrdt("na", self.NP, executor="pallas-interpret",
                        wall_clock=FakeClock(start=BASE))
        w = DenseCrdt("w", self.NP * 2,
                      wall_clock=FakeClock(start=BASE + 5))
        w.put_batch([1], [1])
        scs, ids = w.export_split_delta(tiled=False)
        with pytest.raises(ValueError, match="merge"):
            rcv.merge_split(scs, ids)

    def test_xla_fallback_joins_to_wide(self):
        # Non-kernel executors merge via the wide path — correct,
        # just without the conversion saving.
        rcv = DenseCrdt("na", 64, executor="xla",
                        wall_clock=FakeClock(start=BASE))
        w = DenseCrdt("w", 64, wall_clock=FakeClock(start=BASE + 5))
        w.put_batch([2], [22])
        w.delete_batch([2])
        from crdt_tpu.ops.pallas_merge import split_changeset
        cs, ids = w.export_delta()
        rcv.merge_split(split_changeset(cs), ids)
        assert rcv.get(2) is None and rcv.contains_slot(2)
        assert rcv.is_deleted(2)

    def test_pipelined_window(self):
        via_split, via_wide = self._pair()
        writers = []
        for i, nid in enumerate(("w1", "w2", "w3")):
            w = DenseCrdt(nid, self.NP,
                          wall_clock=FakeClock(start=BASE + 3 + i))
            w.put_batch([i, 10 + i], [i * 10, i * 100])
            writers.append(w)
        with via_split.pipelined():
            for w in writers:
                via_split.merge_split(*w.export_split_delta())
        with via_wide.pipelined():
            for w in writers:
                via_wide.merge(*w.export_delta())
        occ = np.asarray(via_wide.store.occupied)
        np.testing.assert_array_equal(
            np.asarray(via_split.store.occupied), occ)
        for lane in ("lt", "val", "mod_lt"):
            np.testing.assert_array_equal(
                np.asarray(getattr(via_split.store, lane))[occ],
                np.asarray(getattr(via_wide.store, lane))[occ],
                err_msg=lane)
        assert via_split.canonical_time == via_wide.canonical_time

    @pytest.mark.parametrize("rows", [10, 17])
    def test_multirow_split_pads_and_matches(self, rows):
        # r > STREAM_CHUNK_ROWS exercises pad_split_rows (sentinel
        # fills per lane, 2-D and tiled forms) through the kernel —
        # single-writer exports never reach it.
        import jax.numpy as jnp
        from crdt_tpu.ops.pallas_merge import (split_changeset,
                                               tile_changeset)
        from crdt_tpu.ops.dense import DenseChangeset
        rng = np.random.default_rng(rows)
        n = self.NP
        lt = ((BASE + rng.integers(0, 50, (rows, n))) << 16) \
            + rng.integers(0, 4, (rows, n))
        cs = DenseChangeset(
            lt=jnp.asarray(lt, jnp.int64),
            node=jnp.asarray(rng.integers(0, 3, (rows, n)), jnp.int32),
            val=jnp.asarray(rng.integers(0, 1000, (rows, n)), jnp.int64),
            tomb=jnp.asarray(rng.random((rows, n)) < 0.3),
            valid=jnp.asarray(rng.random((rows, n)) < 0.7),
        )
        ids = ["pa", "pb", "pc"]
        via_split, via_wide = self._pair()
        scs = split_changeset(cs)
        if rows % 2:   # alternate forms across the parametrization
            scs = tile_changeset(scs)
        via_split.merge_split(scs, ids)
        via_wide.merge(cs, ids)
        occ = np.asarray(via_wide.store.occupied)
        np.testing.assert_array_equal(
            np.asarray(via_split.store.occupied), occ)
        for lane in ("lt", "node", "val", "tomb", "mod_lt"):
            np.testing.assert_array_equal(
                np.asarray(getattr(via_split.store, lane))[occ],
                np.asarray(getattr(via_wide.store, lane))[occ],
                err_msg=lane)
        assert via_split.canonical_time == via_wide.canonical_time
        assert (via_split.stats.records_adopted
                == via_wide.stats.records_adopted)


class TestPipelinedExactGuards:
    """`pipelined(exact_guards=True)`: one recv_guards pass per merge,
    seeded with the threaded canonical — flag-identical to the
    unpipelined path; the flush raises the reference's typed
    exceptions with unpipelined payloads, never spuriously."""

    def _np(self):
        from crdt_tpu.ops.pallas_merge import TILE
        return TILE

    def test_real_dup_raises_typed_with_parity(self):
        n = self._np()
        other = DenseCrdt("na", n, wall_clock=FakeClock(start=BASE + 50))
        other.put_batch([3], [1])
        delta = other.export_delta()
        plain = DenseCrdt("na", n, executor="pallas-interpret",
                          wall_clock=FakeClock(start=BASE))
        with pytest.raises(DuplicateNodeException) as plain_err:
            plain.merge(*delta)
        piped = DenseCrdt("na", n, executor="pallas-interpret",
                          wall_clock=FakeClock(start=BASE))
        with pytest.raises(DuplicateNodeException) as piped_err:
            with piped.pipelined(exact_guards=True):
                piped.merge(*delta)
        assert piped_err.value.args == plain_err.value.args
        # Window contract: the merge has LANDED when the flush raises.
        assert piped.get(3) == 1

    def test_drift_payload_parity(self):
        from crdt_tpu import ClockDriftException
        n = self._np()
        far = DenseCrdt("far", n,
                        wall_clock=FakeClock(start=BASE + 200_000))
        far.put_batch([2], [9])
        delta = far.export_delta()
        plain = DenseCrdt("hub", n, executor="pallas-interpret",
                          wall_clock=FakeClock(start=BASE + 99))
        with pytest.raises(ClockDriftException) as plain_err:
            plain.merge(*delta)
        piped = DenseCrdt("hub", n, executor="pallas-interpret",
                          wall_clock=FakeClock(start=BASE + 99))
        with pytest.raises(ClockDriftException) as piped_err:
            with piped.pipelined(exact_guards=True):
                piped.merge(*delta)
        assert piped_err.value.args == plain_err.value.args

    def test_shielded_record_not_spurious(self):
        # A local-node record shielded by an earlier larger-lt record:
        # the fast kernels flag it (superset) — a COARSE window raises
        # PipelinedGuardError, the EXACT window completes clean, like
        # the unpipelined path.
        import jax.numpy as jnp
        from crdt_tpu.ops.dense import DenseChangeset
        n = self._np()

        def changeset():
            lanes = {f: np.zeros((2, n), d) for f, d in
                     (("lt", np.int64), ("node", np.int32),
                      ("val", np.int64), ("tomb", bool),
                      ("valid", bool))}
            lanes["lt"][0, 0] = (BASE + 50) << 16
            lanes["node"][0, 0] = 0
            lanes["val"][0, 0] = 1
            lanes["valid"][0, 0] = True
            lanes["lt"][1, 0] = (BASE + 10) << 16
            lanes["node"][1, 0] = 1
            lanes["val"][1, 0] = 2
            lanes["valid"][1, 0] = True
            return DenseChangeset(**{f: jnp.asarray(v)
                                     for f, v in lanes.items()})

        coarse = DenseCrdt("m", n, executor="pallas-interpret",
                           wall_clock=FakeClock(start=BASE + 99))
        with pytest.raises(PipelinedGuardError):
            with coarse.pipelined():
                coarse.merge(changeset(), ["zz", "m"])
        exact = DenseCrdt("m", n, executor="pallas-interpret",
                          wall_clock=FakeClock(start=BASE + 99))
        with exact.pipelined(exact_guards=True):
            exact.merge(changeset(), ["zz", "m"])     # no raise
        assert exact.get(0) == 1

    def test_clean_window_matches_coarse(self):
        # Same clean merges through both modes: bit-identical lanes
        # and canonical (the exact pass is diagnostics-only).
        n = self._np()
        writers = []
        for i, nid in enumerate(("w1", "w2")):
            w = DenseCrdt(nid, n,
                          wall_clock=FakeClock(start=BASE + 3 + i))
            w.put_batch([i, 20 + i], [i * 7, i * 11])
            writers.append(w)
        outs = []
        for kwargs in ({}, {"exact_guards": True}):
            c = DenseCrdt("hub", n, executor="pallas-interpret",
                          wall_clock=FakeClock(start=BASE))
            with c.pipelined(**kwargs):
                for w in writers:
                    c.merge(*w.export_delta())
            outs.append(c)
        a, b = outs
        assert a.canonical_time == b.canonical_time
        np.testing.assert_array_equal(np.asarray(a.store.lt),
                                      np.asarray(b.store.lt))
        np.testing.assert_array_equal(np.asarray(a.store.mod_lt),
                                      np.asarray(b.store.mod_lt))

    def test_merge_split_in_exact_window(self):
        n = self._np()
        other = DenseCrdt("na", n, wall_clock=FakeClock(start=BASE + 50))
        other.put_batch([5], [55])
        scs, ids = other.export_split_delta()
        piped = DenseCrdt("na", n, executor="pallas-interpret",
                          wall_clock=FakeClock(start=BASE))
        with pytest.raises(DuplicateNodeException):
            with piped.pipelined(exact_guards=True):
                piped.merge_split(scs, ids)
        clean = DenseCrdt("rcv", n, executor="pallas-interpret",
                          wall_clock=FakeClock(start=BASE))
        with clean.pipelined(exact_guards=True):
            clean.merge_split(scs, ids)
        assert clean.get(5) == 55

    def test_value_overflow_report_not_eaten_by_typed_raise(self):
        # Review repro: merge #0 trips value-ref overflow, merge #1 a
        # real drift — the "records were SKIPPED" report must surface
        # (the typed raise would silently eat the data-loss signal).
        n = self._np()
        big = DenseCrdt("big", n, wall_clock=FakeClock(start=BASE + 5))
        big.put_batch([0], [2 ** 40])
        far = DenseCrdt("far", n,
                        wall_clock=FakeClock(start=BASE + 200_000))
        far.put_batch([2], [9])
        hub = DenseCrdt("hub", n, executor="pallas-interpret",
                        wall_clock=FakeClock(start=BASE + 99),
                        value_width=32)
        with pytest.raises(PipelinedGuardError, match="SKIPPED"):
            with hub.pipelined(exact_guards=True):
                hub.merge(*big.export_delta())
                hub.merge(*far.export_delta())

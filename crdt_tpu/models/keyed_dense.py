"""Keys-over-slots adapter: the full `Crdt` surface on a dense model.

`DenseCrdt`/`ShardedDenseCrdt` deliberately expose an array surface
(slots, int64 lanes) rather than subclass `Crdt` — but their behavior
is the same LWW lattice, so they must pass the SAME backend-agnostic
conformance suite every other backend runs (the reference ships its
kit precisely so every storage backend proves the one contract,
test/crdt_test.dart:7-11). This adapter closes that gap: a thin
`Crdt` subclass that interns arbitrary keys onto dense slots and
delegates every operation — including the merge engine and the watch
stream — to the wrapped dense model. Nothing here re-implements CRDT
semantics; the canonical clock lives in (and only in) the dense model.

Values must be ints (or None tombstones) — the dense payload lane is
int64 (models/dense_crdt.py module docstring).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, TypeVar

from ..crdt import Crdt
from ..hlc import Hlc
from ..record import Record
from ..watch import ChangeHub, ChangeStream
from .dense_crdt import DenseCrdt

K = TypeVar("K")


class KeyedDenseCrdt(Crdt[K, int]):
    """`Crdt` facade over a dense model (slots-as-keys adapter).

    >>> kc = KeyedDenseCrdt(DenseCrdt("a", 64))
    >>> kc.put("x", 1); kc.map
    {'x': 1}

    Key→slot interning is first-come sequential; interning past the
    wrapped model's ``n_slots`` auto-grows it by doubling (the
    reference map's unbounded growth, map_crdt.dart:10). The
    adapter emits the wrapped model's change events re-keyed, so
    `watch` filters by KEY, not slot.

    Caveat (mixing surfaces): slots written through the raw ``.dense``
    surface that this adapter never interned surface in
    `record_map`/`map`/watch events keyed by their int slot index.
    An adapter whose USER keys are ints can therefore collide with
    those raw-slot keys (user key ``5`` vs raw slot ``5`` are
    indistinguishable in a dict). Use int user keys or raw ``.dense``
    writes — not both on one adapter.
    """

    def __init__(self, dense: DenseCrdt):
        self._dense = dense
        self._key_to_slot: Dict[K, int] = {}
        self._slot_keys: List[K] = []
        self._hub = ChangeHub()
        self._forwarding = None
        # Deliberately NOT calling Crdt.__init__: the canonical clock
        # is owned by the dense model (already refreshed in its ctor);
        # a second clock here could only drift from it.
        self._wall_clock = dense._wall_clock

    # --- clock: the dense model's, never a copy ---

    @property
    def node_id(self) -> Any:
        return self._dense.node_id

    @property
    def dense(self) -> DenseCrdt:
        """The wrapped dense model (for array-surface access)."""
        return self._dense

    @property
    def _canonical_time(self) -> Hlc:
        # Crdt.merge_json reads this attribute for the decode stamp.
        return self._dense.canonical_time

    @property
    def canonical_time(self) -> Hlc:
        return self._dense.canonical_time

    def refresh_canonical_time(self) -> None:
        self._dense.refresh_canonical_time()

    # --- ingest fast lane: keyed surface over the dense combiner ---

    def ingest(self, auto_flush_rows: int = 1 << 16):
        """The wrapped model's write-combining window
        (`DenseCrdt.ingest`): ``put``/``put_all``/``delete`` issued
        inside it stage host-side and commit as one fused dispatch.
        Keyed reads stay read-your-writes through the dense overlay
        (``get``/``contains_key`` route to slot point reads)."""
        return self._dense.ingest(auto_flush_rows=auto_flush_rows)

    def drain_ingest(self) -> bool:
        """Barrier passthrough (`DenseCrdt.drain_ingest`)."""
        return self._dense.drain_ingest()

    def digest_tree(self):
        """Merkle anti-entropy digest passthrough
        (`DenseCrdt.digest_tree`, docs/ANTIENTROPY.md) — keyed
        replicas walk and range-pack over the underlying slot space,
        so two keyed peers must share the same key→slot interning
        order (the same contract every packed sync already relies
        on)."""
        return self._dense.digest_tree()

    # --- key interning ---

    def _intern(self, key: K) -> int:
        slot = self._key_to_slot.get(key)
        if slot is None:
            slot = len(self._slot_keys)
            if slot >= self._dense.n_slots:
                # The reference map grows without bound
                # (map_crdt.dart:10); mirror it by doubling the dense
                # capacity. Doubling preserves tile alignment and mesh
                # key-shard divisibility, and the dense `grow()`
                # revalidates both for forced executors — a failure
                # there surfaces as its descriptive ValueError rather
                # than a hard capacity wall here.
                self._dense.grow(max(self._dense.n_slots * 2, 1))
            self._key_to_slot[key] = slot
            self._slot_keys.append(key)
        return slot

    # --- local ops: single-stamp batches via the dense scatters ---

    def put(self, key: K, value: Optional[int]) -> None:
        slot = self._intern(key)
        if value is None:
            self._dense.delete_batch([slot])
        else:
            self._dense.put_batch([slot], [value])

    def put_all(self, values: Dict[K, Optional[int]]) -> None:
        if not values:
            return  # no clock touch on an empty batch (crdt.dart:47-48)
        slots = [self._intern(k) for k in values]
        tombs = [v is None for v in values.values()]
        self._dense.put_batch(
            slots, [0 if v is None else v for v in values.values()],
            tombs=tombs if any(tombs) else None)

    def delete(self, key: K) -> None:
        self.put(key, None)

    # --- merge: the dense fan-in engine, not the generic host loop ---

    def merge(self, remote_records: Dict[K, Record]) -> None:
        self._dense.merge_records(
            {self._intern(k): r for k, r in remote_records.items()})

    # --- typed lanes: keyed surface over crdt_tpu.semantics ---

    def set_semantics(self, keys, semantics) -> None:
        """Assign a registered semantics (`docs/TYPES.md`) to the
        slots behind ``keys``, interning unseen keys. Same rules as
        `DenseCrdt.set_semantics` (empty lanes only, no pipeline)."""
        self._dense.set_semantics(
            [self._intern(k) for k in keys], semantics)

    def semantics_of(self, key: K):
        slot = self._key_to_slot.get(key)
        if slot is None:
            from ..semantics import LWW
            return LWW
        return self._dense.semantics_of(slot)

    def counter_add(self, key: K, delta: int = 1) -> int:
        return self._dense.counter_add(self._intern(key), delta)

    def counter_value(self, key: K) -> int:
        return self._dense.counter_value(self._intern(key))

    def orset_add(self, key: K, element: int) -> frozenset:
        return self._dense.orset_add(self._intern(key), element)

    def orset_remove(self, key: K, element: int) -> frozenset:
        return self._dense.orset_remove(self._intern(key), element)

    def orset_members(self, key: K) -> frozenset:
        return self._dense.orset_members(self._intern(key))

    def mvreg_put(self, key: K, value: int) -> None:
        self._dense.mvreg_put(self._intern(key), value)

    def mvreg_get(self, key: K):
        return self._dense.mvreg_get(self._intern(key))

    # --- storage primitives (crdt.dart:140-169) ---

    def contains_key(self, key: K) -> bool:
        slot = self._key_to_slot.get(key)
        return slot is not None and self._dense.contains_slot(slot)

    def get(self, key: K) -> Optional[int]:
        # Route to the dense POINT read, not Crdt.get's get_record
        # path: one batched scalar fetch instead of a 7-lane record
        # decode, and inside an ingest() window the staging overlay
        # answers without forcing a flush (get_slot_record drains —
        # records need the stamps only the flush assigns).
        slot = self._key_to_slot.get(key)
        return None if slot is None else self._dense.get(slot)

    def is_deleted(self, key: K) -> Optional[bool]:
        slot = self._key_to_slot.get(key)
        return None if slot is None else self._dense.is_deleted(slot)

    def get_record(self, key: K) -> Optional[Record]:
        slot = self._key_to_slot.get(key)
        if slot is None:
            return None
        return self._dense.get_slot_record(slot)

    def put_record(self, key: K, record: Record) -> None:
        self.put_records({key: record})

    def put_records(self, record_map: Dict[K, Record]) -> None:
        self._dense.put_slot_records(
            {self._intern(k): r for k, r in record_map.items()})

    def record_map(self, modified_since: Optional[Hlc] = None
                   ) -> Dict[K, Record]:
        # Slots written through the raw `.dense` surface that this
        # adapter never interned surface keyed by slot index — same
        # convention as the watch forwarder.
        keys = self._slot_keys
        n = len(keys)
        return {(keys[slot] if slot < n else slot): rec
                for slot, rec in self._dense.record_map(
                    modified_since).items()}

    def watch(self, key: Optional[K] = None) -> ChangeStream:
        if self._forwarding is None:
            # One persistent subscription re-keys the dense model's
            # (slot, value) events; keeping it subscribed makes the
            # dense hub 'active' so bulk paths emit. Writes made
            # through the raw `.dense` surface can touch slots this
            # adapter never interned — those events pass through keyed
            # by their slot index (never an exception from inside the
            # hub's emission loop).
            def forward(event):
                keys = self._slot_keys
                key = (keys[event.key] if 0 <= event.key < len(keys)
                       else event.key)
                self._hub.add(key, event.value)
            self._forwarding = self._dense.watch().listen(forward)
        return self._hub.stream(key)

    def purge(self) -> None:
        self._dense.purge()

    # --- tombstone GC + compaction (docs/STORAGE.md) ---

    def gc_purge(self, stability: Hlc, *,
                 drift_slack_ms: Optional[int] = None) -> int:
        """Epoch tombstone GC passthrough (`DenseCrdt.gc_purge`) —
        ``stability`` must be a fleet stability watermark. Purged
        keys keep their interned slots until the next `compact`, so
        re-putting a purged key reuses its slot."""
        return self._dense.gc_purge(stability,
                                    drift_slack_ms=drift_slack_ms)

    def compact(self, ranges=None) -> int:
        """Compact the wrapped store (`DenseCrdt.compact`) and rewrite
        the key→slot intern maps from the returned translation. Keys
        whose slots were reclaimed (purged or never committed) drop
        from the maps and re-intern on next use — the intern cursor
        falls back to the live count, so churned capacity is actually
        REUSED: a steady live-set workload stays at constant capacity
        instead of doubling through `grow` (docs/STORAGE.md). This
        adapter owns the whole slot space, so the full remap is safe
        (the raw-slot caveat in the class docstring applies: un-
        interned raw-slot rows move like any others). A semantics tag
        assigned to a key that was never written rides out with its
        empty slot — re-assert `set_semantics` after compacting such
        keys. Returns the number of live keys retained."""
        translation = self._dense.compact(ranges)
        pairs = sorted(
            (int(translation[slot]), key)
            for slot, key in enumerate(self._slot_keys)
            if translation[slot] >= 0)
        slot_keys: List[Any] = []
        key_to_slot: Dict[K, int] = {}
        for new_slot, key in pairs:
            while len(slot_keys) < new_slot:
                # A surviving raw-slot row (written through `.dense`,
                # never interned) landed between interned keys; hold
                # its position with the slot index — the same key
                # convention record_map/watch use for raw rows — so
                # the intern cursor can never hand out an occupied
                # slot.
                slot_keys.append(len(slot_keys))
            slot_keys.append(key)
            key_to_slot[key] = new_slot
        self._slot_keys = slot_keys
        self._key_to_slot = key_to_slot
        return len(pairs)

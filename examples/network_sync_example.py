"""Two-PROCESS replication over a real TCP socket.

The reference keeps transport out of scope — its example mocks the
remote with a function returning a JSON string
(example/crdt_example.dart:21-25). This example puts an actual
process + network boundary where that mock sits: a server process
hosting a `TpuMapCrdt` and a client process hosting a `MapCrdt`
converge through nothing but the wire format (crdt_json.dart:8-37)
and the reference's anti-entropy round (full push + inclusive delta
pull, test/map_crdt_test.dart:273-279).

Protocol (length-prefixed JSON frames over one TCP connection):

    client -> server   {"op": "push", "payload": <wire json>}
    server -> client   {"op": "delta", "since": <hlc str>} response:
                       the server's recordMap(modifiedSince=since)
                       as wire JSON

Nothing here is framework magic — the transport is ~40 lines of
stdlib socket code, which is the point: any channel that can carry a
string can carry replication. (`crdt_tpu.net` packages this same
protocol as `SyncServer`/`sync_over_tcp` for applications that just
want the endpoints; this example keeps the raw frames visible.)

Run: python examples/network_sync_example.py
"""

import json
import multiprocessing
import socket
import struct


def send_frame(sock: socket.socket, obj) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def recv_frame(sock: socket.socket):
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (n,) = struct.unpack(">I", head)
    body = _recv_exact(sock, n)
    return None if body is None else json.loads(body)


def _recv_exact(sock: socket.socket, n: int):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def server(port_queue, done_queue) -> None:
    """Hub process: a TpuMapCrdt behind a TCP listener."""
    from crdt_tpu import TpuMapCrdt
    from crdt_tpu.hlc import Hlc

    hub = TpuMapCrdt("hub-node")
    hub.put_all({"motd": "welcome", "hub-counter": 0})

    lsock = socket.create_server(("127.0.0.1", 0))
    port_queue.put(lsock.getsockname()[1])
    conn, _ = lsock.accept()
    with conn:
        while True:
            msg = recv_frame(conn)
            if msg is None or msg.get("op") == "bye":
                break
            if msg["op"] == "push":
                hub.merge_json(msg["payload"])
                send_frame(conn, {"ok": True})
            elif msg["op"] == "delta":
                since = Hlc.parse(msg["since"])
                send_frame(conn, {
                    "payload": hub.to_json(modified_since=since)})
    lsock.close()
    done_queue.put(sorted(hub.map.items()))


def client(port: int):
    """Edge process: a MapCrdt syncing against the hub."""
    from crdt_tpu import MapCrdt

    edge = MapCrdt("edge-node")
    edge.put_all({"edge-note": "hello from the edge", "hub-counter": 7})
    edge.delete("edge-note")
    edge.put("edge-note", "revised")

    from crdt_tpu import Hlc

    with socket.create_connection(("127.0.0.1", port)) as sock:
        # Round 1 — COLD START: the delta bound is keyed on the
        # PULLER's knowledge, and a brand-new replica knows nothing,
        # so the first pull must use the zero clock (full pull). The
        # inclusive `modified >= since` delta (map_crdt.dart:44-45)
        # only skips what this replica has provably already seen.
        def sync_round(since: str) -> str:
            nxt = str(edge.canonical_time)   # capture BEFORE pushing
            send_frame(sock, {"op": "push", "payload": edge.to_json()})
            assert recv_frame(sock)["ok"]
            send_frame(sock, {"op": "delta", "since": since})
            edge.merge_json(recv_frame(sock)["payload"])
            return nxt

        watermark = sync_round(str(Hlc.zero("edge-node")))
        # Round 2 — INCREMENTAL: later rounds pull only records the
        # hub stamped at/after our previous capture.
        edge.put("second-round", True)
        sync_round(watermark)
        send_frame(sock, {"op": "bye"})
    return sorted(edge.map.items())


def main() -> None:
    ctx = multiprocessing.get_context("spawn")
    port_queue, done_queue = ctx.Queue(), ctx.Queue()
    proc = ctx.Process(target=server, args=(port_queue, done_queue))
    proc.start()
    port = port_queue.get(timeout=60)

    edge_state = client(port)
    hub_state = done_queue.get(timeout=60)
    proc.join(timeout=60)

    print("edge:", edge_state)
    print("hub: ", hub_state)
    assert edge_state == hub_state, "replicas diverged"
    print("converged across two processes over TCP ✓")


if __name__ == "__main__":
    main()

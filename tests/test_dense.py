"""Dense N-replica fan-in: differential tests against the scalar oracle.

`fanin_step` is specified as ONE `Crdt.merge` of the conflict-resolved
union of the R changesets (ties on identical HLC to the lowest replica
index) — see crdt_tpu/ops/dense.py docstring. These tests build that
union in plain Python, run it through the `MapCrdt` oracle, and assert
lane-for-lane identical results, plus the tie-break/guard/delta
semantics pinned by SURVEY.md §2's parity checklist.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu import Hlc, MapCrdt, Record
from crdt_tpu.hlc import MAX_DRIFT, SHIFT
from crdt_tpu.ops.dense import (DenseChangeset, DenseStore,
                                dense_delta_mask, dense_max_logical_time,
                                empty_dense_store, fanin_step, fanin_stream,
                                store_to_changeset)

from conformance import FakeClock

MILLIS = 1_000_000_000_000
# Node ordinals: the local store is ordinal 0 in these tests unless noted.
LOCAL = 0


def lt_of(millis, counter=0):
    return (millis << SHIFT) + counter


def make_changeset(r, n, entries):
    """entries: list of (r, k, lt, node, val, tomb)."""
    lt = np.zeros((r, n), np.int64)
    node = np.zeros((r, n), np.int32)
    val = np.zeros((r, n), np.int64)
    tomb = np.zeros((r, n), bool)
    valid = np.zeros((r, n), bool)
    for (ri, k, l, nd, v, tb) in entries:
        lt[ri, k], node[ri, k], val[ri, k] = l, nd, v
        tomb[ri, k], valid[ri, k] = tb, True
    return DenseChangeset(*(jnp.asarray(a) for a in (lt, node, val, tomb,
                                                     valid)))


def run_step(store, cs, canonical_lt=0, local_node=LOCAL,
             wall=MILLIS + 10_000):
    return fanin_step(store, cs, jnp.int64(canonical_lt),
                      jnp.int32(local_node), jnp.int64(wall))


class TestReplicaReduceAndLww:
    def test_new_keys_adopted(self):
        store = empty_dense_store(4)
        cs = make_changeset(2, 4, [
            (0, 0, lt_of(MILLIS), 3, 7, False),
            (1, 2, lt_of(MILLIS + 1), 4, 9, True),
        ])
        store, res = run_step(store, cs)
        occ = np.asarray(store.occupied)
        assert list(occ) == [True, False, True, False]
        assert int(store.val[0]) == 7
        assert bool(store.tomb[2])
        assert int(res.win_count) == 2
        assert int(res.new_canonical) == lt_of(MILLIS + 1)

    def test_higher_lt_wins_across_replicas(self):
        store = empty_dense_store(1)
        cs = make_changeset(3, 1, [
            (0, 0, lt_of(MILLIS), 1, 10, False),
            (1, 0, lt_of(MILLIS + 5), 2, 20, False),
            (2, 0, lt_of(MILLIS + 2), 3, 30, False),
        ])
        store, _ = run_step(store, cs)
        assert int(store.val[0]) == 20
        assert int(store.node[0]) == 2

    def test_node_ordinal_breaks_lt_tie(self):
        # Disambiguate using node id (map_crdt_test.dart:59-63).
        store = empty_dense_store(1)
        cs = make_changeset(2, 1, [
            (0, 0, lt_of(MILLIS), 1, 10, False),
            (1, 0, lt_of(MILLIS), 2, 20, False),
        ])
        store, _ = run_step(store, cs)
        assert int(store.val[0]) == 20

    def test_identical_hlc_first_replica_wins(self):
        # Sequential-merge parity: first to merge wins; later identical
        # records lose the local-wins-on-tie compare (crdt.dart:84).
        store = empty_dense_store(1)
        cs = make_changeset(3, 1, [
            (0, 0, lt_of(MILLIS), 2, 111, False),
            (1, 0, lt_of(MILLIS), 2, 222, False),
            (2, 0, lt_of(MILLIS), 2, 333, False),
        ])
        store, _ = run_step(store, cs)
        assert int(store.val[0]) == 111

    def test_local_wins_exact_tie(self):
        # Merge same (map_crdt_test.dart:65-70).
        store = empty_dense_store(1)
        cs0 = make_changeset(1, 1, [(0, 0, lt_of(MILLIS), 1, 10, False)])
        store, _ = run_step(store, cs0)
        cs1 = make_changeset(1, 1, [(0, 0, lt_of(MILLIS), 1, 99, False)])
        store, res = run_step(store, cs1, canonical_lt=lt_of(MILLIS))
        assert int(store.val[0]) == 10
        assert int(res.win_count) == 0

    def test_local_loses_to_newer(self):
        store = empty_dense_store(1)
        cs0 = make_changeset(1, 1, [(0, 0, lt_of(MILLIS), 1, 10, False)])
        store, _ = run_step(store, cs0)
        cs1 = make_changeset(1, 1, [(0, 0, lt_of(MILLIS, 1), 1, 99, False)])
        store, _ = run_step(store, cs1, canonical_lt=lt_of(MILLIS))
        assert int(store.val[0]) == 99

    def test_tombstone_propagates(self):
        # Merge deleted item (map_crdt_test.dart:91-96).
        store = empty_dense_store(1)
        cs0 = make_changeset(1, 1, [(0, 0, lt_of(MILLIS), 1, 10, False)])
        store, _ = run_step(store, cs0)
        cs1 = make_changeset(1, 1, [(0, 0, lt_of(MILLIS, 1), 1, 0, True)])
        store, _ = run_step(store, cs1, canonical_lt=lt_of(MILLIS))
        assert bool(store.tomb[0])
        assert bool(store.occupied[0])  # never physically removed

    def test_modified_stamped_with_final_canonical(self):
        # Winner re-stamping (crdt.dart:86-87): event hlc kept, modified
        # lane carries the post-absorption canonical + local ordinal.
        store = empty_dense_store(2)
        cs = make_changeset(1, 2, [
            (0, 0, lt_of(MILLIS), 1, 10, False),
            (0, 1, lt_of(MILLIS + 7), 2, 20, False),
        ])
        store, res = run_step(store, cs)
        assert int(store.lt[0]) == lt_of(MILLIS)           # event hlc kept
        assert int(store.mod_lt[0]) == int(res.new_canonical)
        assert int(store.mod_lt[1]) == int(res.new_canonical)
        assert int(store.mod_node[0]) == LOCAL


class TestRecvGuards:
    def test_duplicate_node_detected(self):
        # A remote record ahead of the canonical clock carrying the
        # local ordinal → DuplicateNode (hlc.dart:88-90).
        store = empty_dense_store(1)
        cs = make_changeset(1, 1, [(0, 0, lt_of(MILLIS), LOCAL, 1, False)])
        _, res = run_step(store, cs, canonical_lt=0)
        assert bool(res.any_bad) and bool(res.first_is_dup)

    def test_duplicate_node_skipped_when_canonical_ahead(self):
        # recv fast path SKIPS the duplicate check (hlc.dart:85).
        store = empty_dense_store(1)
        cs = make_changeset(1, 1, [(0, 0, lt_of(MILLIS), LOCAL, 1, False)])
        _, res = run_step(store, cs, canonical_lt=lt_of(MILLIS))
        assert not bool(res.any_bad)

    def test_drift_detected(self):
        store = empty_dense_store(1)
        wall = MILLIS
        cs = make_changeset(1, 1, [
            (0, 0, lt_of(wall + MAX_DRIFT + 1), 1, 1, False)])
        _, res = run_step(store, cs, wall=wall)
        assert bool(res.any_bad) and not bool(res.first_is_dup)

    def test_drift_at_limit_ok(self):
        store = empty_dense_store(1)
        wall = MILLIS
        cs = make_changeset(1, 1, [
            (0, 0, lt_of(wall + MAX_DRIFT), 1, 1, False)])
        _, res = run_step(store, cs, wall=wall)
        assert not bool(res.any_bad)

    def test_running_canonical_shields_later_duplicates(self):
        # Record #0 (other node) raises the running canonical above
        # record #1 (local ordinal) → #1 takes the fast path, no dup.
        store = empty_dense_store(2)
        cs = make_changeset(1, 2, [
            (0, 0, lt_of(MILLIS + 5), 1, 1, False),
            (0, 1, lt_of(MILLIS), LOCAL, 2, False),
        ])
        _, res = run_step(store, cs)
        assert not bool(res.any_bad)

    def test_guards_fire_on_within_union_losers(self):
        # Guards visit EVERY record (recv runs for winners and losers,
        # crdt.dart:82): a duplicate-node record still trips even when a
        # newer record from another replica wins its key slot.
        store = empty_dense_store(1)
        cs = make_changeset(2, 1, [
            (0, 0, lt_of(MILLIS), LOCAL, 1, False),
            (1, 0, lt_of(MILLIS + 5), 1, 2, False),
        ])
        _, res = run_step(store, cs)
        assert bool(res.any_bad) and bool(res.first_is_dup)

    def test_first_bad_reports_r_major_order(self):
        store = empty_dense_store(2)
        cs = make_changeset(2, 2, [
            (0, 1, lt_of(MILLIS), LOCAL, 1, False),      # flat index 1
            (1, 0, lt_of(MILLIS + 99), LOCAL, 1, False),  # flat index 2
        ])
        _, res = run_step(store, cs)
        assert bool(res.any_bad)
        assert int(res.first_bad) == 1


class TestStreamAndDelta:
    def test_stream_equals_sequential_steps(self):
        rng = random.Random(7)
        n, rc, c = 16, 4, 5
        entries_by_chunk = [
            [(ri, k, lt_of(MILLIS + rng.randrange(50), rng.randrange(3)),
              rng.randrange(1, 6), rng.randrange(100), rng.random() < 0.3)
             for ri in range(rc) for k in range(n) if rng.random() < 0.6]
            for _ in range(c)]
        chunk_list = [make_changeset(rc, n, e) for e in entries_by_chunk]

        seq = empty_dense_store(n)
        canon = jnp.int64(0)
        for cs in chunk_list:
            seq, res = fanin_step(seq, cs, canon, jnp.int32(LOCAL),
                                  jnp.int64(MILLIS + 10_000))
            canon = res.new_canonical

        stacked = DenseChangeset(*(jnp.stack([getattr(cs, f) for cs in
                                              chunk_list])
                                   for f in DenseChangeset._fields))
        streamed, sres = fanin_stream(empty_dense_store(n), stacked,
                                      jnp.int64(0), jnp.int32(LOCAL),
                                      jnp.int64(MILLIS + 10_000))
        for lane in DenseStore._fields:
            np.testing.assert_array_equal(np.asarray(getattr(seq, lane)),
                                          np.asarray(getattr(streamed, lane)))
        assert int(sres.new_canonical) == int(canon)

    def test_stream_first_bad_is_global_index(self):
        # first_bad carries the chunk offset: offender in chunk 1 at
        # within-chunk flat index 1 → global index 1*Rc*N + 1.
        rc, n = 1, 2
        clean = make_changeset(rc, n, [(0, 0, lt_of(MILLIS), 1, 1, False)])
        bad = make_changeset(rc, n, [
            (0, 1, lt_of(MILLIS + 99), LOCAL, 1, False)])
        stacked = DenseChangeset(*(jnp.stack([getattr(clean, f),
                                              getattr(bad, f)])
                                   for f in DenseChangeset._fields))
        _, res = fanin_stream(empty_dense_store(n), stacked, jnp.int64(0),
                              jnp.int32(LOCAL), jnp.int64(MILLIS + 10_000))
        assert bool(res.any_bad)
        assert int(res.first_bad) == rc * n + 1

    def test_delta_mask_inclusive(self):
        # Inclusive modifiedSince bound (map_crdt.dart:44-45).
        store = empty_dense_store(2)
        cs = make_changeset(1, 2, [
            (0, 0, lt_of(MILLIS), 1, 1, False),
            (0, 1, lt_of(MILLIS + 1), 1, 2, False),
        ])
        store, res = run_step(store, cs)
        at = dense_delta_mask(store, res.new_canonical)
        assert list(np.asarray(at)) == [True, True]  # == bound kept
        above = dense_delta_mask(store, res.new_canonical + 1)
        assert list(np.asarray(above)) == [False, False]

    def test_max_logical_time(self):
        store = empty_dense_store(3)
        assert int(dense_max_logical_time(store)) == 0
        cs = make_changeset(1, 3, [(0, 1, lt_of(MILLIS, 3), 1, 1, False)])
        store, _ = run_step(store, cs)
        assert int(dense_max_logical_time(store)) == lt_of(MILLIS, 3)

    def test_store_to_changeset_roundtrip(self):
        a = empty_dense_store(4)
        cs = make_changeset(2, 4, [
            (0, 0, lt_of(MILLIS), 1, 5, False),
            (1, 3, lt_of(MILLIS + 2), 2, 6, True),
        ])
        a, res = run_step(a, cs)
        b = empty_dense_store(4)
        b, _ = run_step(b, store_to_changeset(a))
        for lane in ("lt", "node", "val", "occupied", "tomb"):
            np.testing.assert_array_equal(np.asarray(getattr(a, lane)),
                                          np.asarray(getattr(b, lane)))

    def test_store_to_changeset_delta_only(self):
        a = empty_dense_store(2)
        a, r1 = run_step(a, make_changeset(
            1, 2, [(0, 0, lt_of(MILLIS), 1, 5, False)]))
        a, r2 = run_step(a, make_changeset(
            1, 2, [(0, 1, lt_of(MILLIS + 9), 2, 6, False)]),
            canonical_lt=int(r1.new_canonical))
        delta = store_to_changeset(a, since_lt=r2.new_canonical)
        valid = np.asarray(delta.valid[0])
        assert list(valid) == [False, True]


class TestDifferentialVsOracle:
    """fanin_step vs MapCrdt oracle on the conflict-resolved union."""

    @pytest.mark.parametrize("seed", range(5))
    def test_random_fanin_matches_oracle(self, seed):
        rng = random.Random(seed)
        n_keys, n_replicas = 24, 6
        node_ids = [f"n{chr(ord('a') + i)}" for i in range(n_replicas)]
        # Ordinals must be order-preserving; 'local' sorts before all.
        local_id = "aaa-local"
        table = sorted([local_id] + node_ids)
        ordinal = {nid: i for i, nid in enumerate(table)}

        entries = []
        per_replica = [dict() for _ in range(n_replicas)]
        for ri, nid in enumerate(node_ids):
            for k in range(n_keys):
                if rng.random() < 0.55:
                    continue
                millis = MILLIS + rng.randrange(20)
                counter = rng.randrange(4)
                tomb = rng.random() < 0.25
                v = rng.randrange(1000)
                entries.append((ri, k, lt_of(millis, counter), ordinal[nid],
                                0 if tomb else v, tomb))
                per_replica[ri][k] = Record(
                    Hlc(millis, counter, nid), None if tomb else v,
                    Hlc(millis, counter, nid))

        cs = make_changeset(n_replicas, n_keys, entries)
        store, res = run_step(empty_dense_store(n_keys), cs,
                              local_node=ordinal[local_id])

        # Oracle: ONE merge of the union, identical-HLC ties to lowest r.
        union = {}
        for ri in range(n_replicas):
            for k, rec in per_replica[ri].items():
                cur = union.get(k)
                if cur is None or rec.hlc > cur.hlc:
                    union[k] = rec
        clock = FakeClock(start=MILLIS + 10_000)
        oracle = MapCrdt(local_id, wall_clock=clock)
        oracle.merge(union)

        recs = oracle.record_map()
        for k in range(n_keys):
            if k not in recs:
                assert not bool(store.occupied[k])
                continue
            rec = recs[k]
            assert bool(store.occupied[k])
            assert int(store.lt[k]) == rec.hlc.logical_time
            assert int(store.node[k]) == ordinal[rec.hlc.node_id]
            assert bool(store.tomb[k]) == rec.is_deleted
            if not rec.is_deleted:
                assert int(store.val[k]) == rec.value
            assert int(store.mod_lt[k]) == rec.modified.logical_time
        # Canonical parity: the oracle's final clock is new_canonical put
        # through the trailing send bump (crdt.dart:93); clock.millis is
        # the wall value that bump consumed.
        expected = Hlc.send(
            Hlc.from_logical_time(int(res.new_canonical), local_id),
            millis=clock.millis)
        assert oracle.canonical_time.logical_time == expected.logical_time

"""Record cell and pluggable codec types (L1).

Matches the reference `lib/src/record.dart:1-39`:

- ``Record`` = ``(hlc, value, modified)``; ``value is None`` encodes a
  tombstone (record.dart:17).
- JSON codec serializes only ``hlc`` + ``value``; ``modified`` is
  local-only and re-stamped on decode (record.dart:28-31).
- Equality ignores ``modified`` (record.dart:34-35).
- Codec callables for non-string keys / custom value classes
  (record.dart:3-9): ``key_encoder(key) -> str``,
  ``value_encoder(key, value) -> jsonable``, ``key_decoder(str) -> key``,
  ``value_decoder(key, jsonable) -> value``,
  ``node_id_decoder(str) -> node_id``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Generic, Optional, TypeVar

from .hlc import Hlc

K = TypeVar("K")
V = TypeVar("V")

KeyEncoder = Callable[[Any], str]
ValueEncoder = Callable[[Any, Any], Any]
KeyDecoder = Callable[[str], Any]
ValueDecoder = Callable[[str, Any], Any]
NodeIdDecoder = Callable[[str], Any]


class Record(Generic[V]):
    """Stores a value associated with a given HLC (record.dart:12-39)."""

    __slots__ = ("hlc", "value", "modified")

    def __init__(self, hlc: Hlc, value: Optional[V], modified: Hlc):
        self.hlc = hlc
        self.value = value
        self.modified = modified

    @property
    def is_deleted(self) -> bool:
        return self.value is None

    @classmethod
    def from_json(cls, key: Any, obj: Dict[str, Any], modified: Hlc,
                  value_decoder: Optional[ValueDecoder] = None,
                  node_id_decoder: Optional[NodeIdDecoder] = None
                  ) -> "Record[V]":
        hlc = Hlc.parse(obj["hlc"], node_id_decoder)
        raw = obj.get("value")
        value = (raw if value_decoder is None or raw is None
                 else value_decoder(key, raw))
        return cls(hlc, value, modified)

    def to_json(self, key: Any = "",
                value_encoder: Optional[ValueEncoder] = None
                ) -> Dict[str, Any]:
        return {
            "hlc": self.hlc.to_json(),
            "value": (self.value if value_encoder is None
                      else value_encoder(key, self.value)),
        }

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, Record) and self.hlc == other.hlc
                and self.value == other.value)

    def __hash__(self) -> int:
        # Equal records share an hlc (equality requires hlc ==), so the
        # hlc alone is a consistent hash even for unhashable values.
        return hash(self.hlc)

    def __repr__(self) -> str:
        return str(self.to_json(""))

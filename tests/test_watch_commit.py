"""Watch staleness regressions (docs/FEDERATION.md "Waking watch"):
change events must carry the COMMITTED, post-dedup, semantics-decoded
value — never a raw int64 lane and never a staged write the combiner
later collapsed. These pin the host-side fix that lets the serving
tier's fan-out path reuse the same hub without re-deriving values."""

from crdt_tpu import DenseCrdt
from crdt_tpu.testing import FakeClock

BASE = 1_600_000_000_000


def _dense(name="a", start=BASE):
    return DenseCrdt(name, n_slots=64,
                     wall_clock=FakeClock(start=start))


def test_ingest_lww_events_are_post_dedup():
    # Two staged writes to one slot collapse last-wins in the
    # combiner; the watcher must see ONE committed event with the
    # winner, not an event per staged put (the pre-fix behavior
    # leaked the intermediate value).
    c = _dense()
    s = c.watch().record()
    with c.ingest():
        c.put_batch([3], [1])
        c.put_batch([3], [2])
        c.put_batch([4], [7])
    assert s.events == [(3, 2), (4, 7)]


def test_counter_events_decode_not_raw_lanes():
    # A pncounter lane packs (pos << 32) | neg; an event carrying the
    # raw lane would hand a watcher a ~2**33 integer for a counter
    # sitting at 2. Events must decode through the slot's semantics.
    c = _dense()
    c.set_semantics([5], "pncounter")
    s = c.watch().record()
    c.counter_add(5, 3)
    c.counter_add(5, -1)
    assert s.events == [(5, 3), (5, 2)]


def test_ingest_counter_event_decodes_committed_value():
    c = _dense()
    c.set_semantics([5], "pncounter")
    s = c.watch().record()
    with c.ingest():
        c.counter_add(5, 4)
    assert s.events == [(5, 4)]


def test_merge_counter_event_decodes():
    # Merge-path winners go through the same decode: a replica's
    # counter arriving over anti-entropy must surface its value, not
    # its encoding.
    a = _dense("ma")
    b = _dense("mb", start=BASE + 5)
    for c in (a, b):
        c.set_semantics([6], "pncounter")
    b.counter_add(6, 9)
    s = a.watch().record()
    a.merge(*b.export_delta())
    assert s.events == [(6, 9)]


def test_merge_tombstone_still_none_on_typed_slot():
    a = _dense("ta")
    b = _dense("tb", start=BASE + 5)
    for c in (a, b):
        c.set_semantics([7], "pncounter")
    a.counter_add(7, 1)
    b.counter_add(7, 2)
    b.delete_batch([7])
    s = a.watch(slot=7).record()
    a.merge(*b.export_delta())
    assert s.events[-1] == (7, None)
